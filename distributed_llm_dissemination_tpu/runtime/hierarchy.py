"""Hierarchical control: sub-leaders own a group's fan-out and fold its
control traffic upward (docs/hierarchy.md).

The flat control plane makes the leader touch every (dest, layer) pair:
it plans them all in one flow graph, receives every announce, every ack,
every heartbeat, and every metrics report.  At fleet scale both ends of
that are the ceiling — the solve grows with node count, and the leader's
message loop handles O(nodes) control traffic per layer.

This module is the scale-out: the fleet partitions into GROUPS, each
owned by a sub-leader (itself an ordinary receiver seat).  The root
plans delivery to group INGRESS nodes only (``sched/flow.py`` over
groups and the inter-group links); the sub-leader owns its members'
plan dispatch, ack/NACK aggregation, liveness, and telemetry fold,
reporting only aggregate coverage upward (``GroupStatusMsg``) — the
root handles O(groups) messages where the flat plane handled O(nodes).

Pieces:

- :func:`partition_groups` — deterministic auto-partition (explicit
  group declarations come from the config's ``Groups`` section).
- :class:`SubLeaderController` — attach to a receiver to make its seat
  a sub-leader: registers the member-facing handlers (announce / ack /
  heartbeat / metrics) on the receiver's already-running loop, fans
  each completed layer out to the members wanting it, and folds
  everything upward.
- The root half is :class:`~.leader.HierarchicalFlowLeaderNode`
  (runtime/leader.py), which also owns the failover semantics: a dead
  sub-leader DISSOLVES its group back to flat delivery
  (``GroupPlanMsg(dissolve=True)`` to each member), and the group
  table rides the epoch-fenced ``ControlDeltaMsg`` replication so a
  promoted standby keeps the hierarchy.
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Dict, List, Optional

from ..core.types import (
    LayerID,
    NodeID,
    codec_accepts,
    delivered,
    satisfies,
    shard_covers,
    shard_range,
)
from ..sched.flow import chain_forward_roles
from ..transport.messages import (
    AckMsg,
    AnnounceMsg,
    BootReadyMsg,
    GroupPlanMsg,
    GroupStatusMsg,
    HeartbeatMsg,
    MetricsReportMsg,
    SwapCommitMsg,
)
from ..utils import telemetry, threads, trace
from ..utils.logging import log
from .failure import FailureDetector
from .send import send_layer

# How often a sub-leader re-drives unacked member sends (the safety net
# under event-driven fan-out: a send eaten by a partition window or a
# member restart is re-sent instead of waiting on root-level recovery).
GROUP_RESEND_S = float(os.environ.get("DLD_GROUP_RESEND_S", "2.0"))
# Debounce for folding member announces into one upward aggregate: a
# fleet announcing at start collapses into ~one message per group.
ANNOUNCE_FOLD_S = float(os.environ.get("DLD_GROUP_ANNOUNCE_FOLD_S", "0.1"))
# Chain fan-out (docs/hierarchy.md): first dispatch of a layer wanted by
# ≥2 members rides a K-striped member-to-member chain, so the
# sub-leader's egress is O(model_bytes) instead of O(model_bytes × R).
# Off degrades to the pre-chain star.  The REDRIVE pass always sends
# direct — that is the convergence guarantee for legacy members (which
# ignore forward roles) and the repair path around dead hops.
GROUP_CHAIN = (os.environ.get("DLD_GROUP_CHAIN", "1").lower()
               not in ("0", "false", "off"))
GROUP_STRIPES = max(1, int(os.environ.get("DLD_GROUP_STRIPES", "4")))


def partition_groups(node_ids: List[NodeID],
                     group_size: int = 0) -> Dict[int, dict]:
    """Deterministic auto-partition of ``node_ids`` into groups:
    ``{gid: {"leader": sub_leader_id, "members": [...]}}`` (the
    sub-leader is the group's first member).  ``group_size`` 0 sizes
    groups at ~sqrt(N), so both the root's group count and each
    sub-leader's member count grow as sqrt(N) — the balanced two-level
    split (root-handled traffic grows sub-linearly in N)."""
    ids = sorted(int(n) for n in node_ids)
    if not ids:
        return {}
    size = int(group_size) or max(2, math.isqrt(len(ids)))
    out: Dict[int, dict] = {}
    for gid, start in enumerate(range(0, len(ids), size)):
        chunk = ids[start:start + size]
        out[gid] = {"leader": chunk[0], "members": chunk}
    return out


def groups_from_config(spec, node_ids: List[NodeID],
                       leader_id: NodeID) -> Dict[int, dict]:
    """The config's ``Groups`` section → the group table.  Either an
    auto-partition request (``{"Size": K}``; 0 = sqrt sizing) over every
    non-root seat, or an explicit list of ``{"Leader": id, "Members":
    [...]}`` declarations.  The root is never grouped."""
    ids = [int(n) for n in node_ids if int(n) != int(leader_id)]
    if isinstance(spec, dict):
        return partition_groups(ids, int(spec.get("Size", 0) or 0))
    out: Dict[int, dict] = {}
    seen: set = set()
    known = set(ids)
    for gid, rec in enumerate(spec or []):
        sub = int(rec["Leader"])
        members = sorted({int(m) for m in rec.get("Members") or []} | {sub})
        if int(leader_id) in members:
            raise ValueError("the root leader cannot be a group member")
        unknown = set(members) - known
        if unknown:
            # Fail at CONFIG time like every other topology error — a
            # hierarchy around a seat that doesn't exist would hang the
            # run (its members' ingress demand targets a dead address).
            raise ValueError(
                f"Groups names unknown node ids {sorted(unknown)}")
        overlap = seen & set(members)
        if overlap:
            raise ValueError(f"nodes {sorted(overlap)} appear in more "
                             "than one group")
        seen |= set(members)
        out[gid] = {"leader": sub, "members": members}
    return out


class SubLeaderController:
    """Make a receiver seat the sub-leader of one group.

    Attach AFTER the receiver's loop is running: the member-facing
    handlers (announce / ack / heartbeat / metrics report — message
    types a plain receiver never registers) go onto the same loop, and
    the receiver's ``on_layer_complete`` hook triggers fan-out the
    moment one of this seat's own layers completes.  Everything the
    members produce folds into cumulative ``GroupStatusMsg`` aggregates
    to whatever seat is currently the root (``node.leader_id`` — a
    takeover re-points it via the normal lease path, and the pending
    queue + the reply-to-every-``GroupPlanMsg`` rule reconcile the new
    root's view)."""

    def __init__(self, receiver, group_id: int, members: List[NodeID],
                 member_timeout: float = 0.0):
        self.receiver = receiver
        self.node = receiver.node
        self.group_id = int(group_id)
        self.members = [int(m) for m in members
                        if int(m) != self.node.my_id]
        self._lock = threading.Lock()
        self._active = True
        self._targets: Dict[NodeID, dict] = {}   # member -> {lid: meta}
        self._covered: Dict[LayerID, set] = {}   # lid -> members done
        # QUALIFIED coverage (shard/codec/version targets) is tracked
        # separately and NEVER pushed upward as ``covered`` — the root
        # synthesizes plain INMEM acks from that section, which would
        # erase the tags; qualified members ack the root verbatim (the
        # forwarded-ack path) and this set only stops re-sends.
        self._covered_q: Dict[LayerID, set] = {}
        self._announced: Dict[NodeID, dict] = {}  # member -> holdings
        self._member_digests: Dict[NodeID, dict] = {}  # member -> stamps
        self._member_codecs: Dict[NodeID, list] = {}  # member -> caps
        self._plan_epoch = -1
        self._announce_dirty: set = set()
        self._announce_timer: Optional[threading.Timer] = None
        self._dead: set = set()
        self._sent: Dict[tuple, float] = {}      # (member, lid) -> t
        self._member_metrics: Dict[NodeID, dict] = {}
        self._metrics_dirty = False
        self._metrics_since_push: set = set()
        self._stop = threading.Event()
        # Member liveness is the sub-leader's job now: a silent member
        # is reported upward as Dead (the root drops its pairs loudly),
        # never individually monitored by the root.
        self.detector = FailureDetector(member_timeout, self._member_dead)
        for m in self.members:
            self.detector.touch(m)
        loop = receiver.loop
        loop.register(GroupPlanMsg, self.handle_group_plan)
        loop.register(AnnounceMsg, self.handle_member_announce)
        loop.register(AckMsg, self.handle_member_ack)
        loop.register(HeartbeatMsg,
                      lambda msg: self.detector.touch(msg.src_id))
        loop.register(MetricsReportMsg, self.handle_member_metrics)
        # Root-bound member traffic the aggregate vocabulary doesn't
        # carry is FORWARDED verbatim: boot reports gate the root's
        # boot wait, and a member's swap confirm/query/error must reach
        # the rollout driver (the sub-leader handles leader-originated
        # swap roles itself — it can be a swap dest too).
        loop.register(BootReadyMsg, self._forward_to_root)
        loop.register(SwapCommitMsg, self._route_swap)
        receiver.on_layer_complete = self._on_own_layer
        self.detector.start()
        threading.Thread(target=self._redrive_loop, daemon=True,
                         name=f"subleader-redrive-{self.node.my_id}"
                         ).start()

    def close(self) -> None:
        self._stop.set()
        self.detector.stop()
        with self._lock:
            if self._announce_timer is not None:
                self._announce_timer.cancel()

    def drain(self, timeout: float = 2.0) -> None:
        """Bounded wait for every live member's final telemetry flush
        (receivers flush at startup, right before exiting a one-shot
        run) to arrive and fold upward — a sub-leader exiting the
        moment ITS startup lands would otherwise race its members'
        flushes and the root's run report would miss them.  Anything
        still dirty at the deadline is pushed as-is.  With the
        telemetry plane disabled members never report, so there is
        nothing to wait for."""
        from ..utils import telemetry

        if not telemetry.enabled():
            return
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                live = {m for m in self.members if m not in self._dead}
                settled = (not self._metrics_dirty
                           and set(self._member_metrics) >= live)
            if settled:
                return
            time.sleep(0.05)
        self._push_metrics_if_dirty()

    # ------------------------------------------------------ root-facing

    def _push(self, **sections) -> None:
        """One aggregate upward.  Rides the receiver's leader-routed
        send, so a root lost to a failover window queues the report and
        the takeover lease flushes it."""
        msg = GroupStatusMsg(self.node.my_id, self.group_id, **sections)
        self.receiver._send_to_leader(msg)

    def _covered_snapshot_locked(self) -> Dict[LayerID, list]:
        return {lid: sorted(members)
                for lid, members in self._covered.items() if members}

    def _covered_spans(self, covered: Dict[LayerID, list]) -> dict:
        """The advisory span map riding a coverage push (docs/
        observability.md): each covered (member, layer)'s fan-out child
        span id — deterministic, so the root's synthesized acks file
        ``acked`` events on the members' own spans."""
        return {lid: {m: telemetry.span_id(m, lid) for m in members}
                for lid, members in covered.items()}

    def handle_group_plan(self, msg: GroupPlanMsg) -> None:
        if self.receiver._fence_stale(msg):
            return
        if msg.dissolve:
            # A root that declared THIS seat dead dissolved the group
            # (we are a zombie to it): stand down as sub-leader — stop
            # fan-out AND member liveness monitoring (members now
            # heartbeat the root; keeping the detector would dead-
            # report every one of them forever) — and follow the
            # member path: re-announce to the root.
            log.warn("sub-leader received dissolve; standing down",
                     group=self.group_id)
            with self._lock:
                self._active = False
                self._targets.clear()
            self.detector.stop()
            self.receiver.handle_group_plan(msg)
            return
        with self._lock:
            rearmed = not self._active
            self._active = True
            self._plan_epoch = msg.epoch
            self._targets = {int(m): dict(row)
                             for m, row in msg.targets.items()
                             if int(m) != self.node.my_id}
            # Elastic membership (docs/membership.md): the plan is the
            # root's authoritative member view — absorb seats it added
            # (joiners placed into this group) so liveness monitoring
            # and the announce/metrics flush gates cover them.
            for m in self._targets:
                if m not in self.members:
                    self.members.append(m)
                    self._dead.discard(m)
                    self.detector.touch(m)
            covered = self._covered_snapshot_locked()
        if rearmed:
            # A stood-down sub-leader whose group RE-FORMED (its seat
            # was re-admitted): member liveness re-arms with fan-out.
            self.detector.start()
        trace.count("hier.group_plans")
        log.info("group plan received", group=self.group_id,
                 members=sorted(self._targets),
                 layers=sorted({lid for row in msg.targets.values()
                                for lid in row}))
        # Receipt always answers with full cumulative coverage: this is
        # the reconcile channel a promoted root's first re-plan uses.
        self._push(covered=covered, spans=self._covered_spans(covered))
        self._fan_out_ready()

    # ---------------------------------------------------- member-facing

    def handle_member_announce(self, msg: AnnounceMsg) -> None:
        self.detector.touch(msg.src_id)
        if self.detector.is_dead(msg.src_id):
            self.detector.revive(msg.src_id)
        with self._lock:
            # A joiner the root placed here may announce before the
            # updated group plan lands: absorb it (docs/membership.md).
            if msg.src_id not in self.members:
                self.members.append(msg.src_id)
            self._dead.discard(msg.src_id)
            self._announced[msg.src_id] = dict(msg.layer_ids)
            # Digest fold (docs/membership.md): the member's announced
            # stamps ride the same debounce — they are what lets the
            # root verify a GROUPED joiner and promote it to a source.
            self._member_digests[msg.src_id] = dict(msg.digests or {})
            # Codec capability fold (docs/codec.md): an empty announce
            # is an authoritative revocation, exactly like the flat
            # path — the root must stop choosing quantized transfers
            # for a member that lost the capability with its config.
            self._member_codecs[msg.src_id] = [
                str(c) for c in (msg.codecs or [])]
            self._announce_dirty.add(msg.src_id)
            # A re-announce is a restart: its RAM holdings are whatever
            # the announce says now, so sends re-arm.
            for key in [k for k in self._sent if k[0] == msg.src_id]:
                del self._sent[key]
            for members in self._covered.values():
                members.discard(msg.src_id)
            for members in self._covered_q.values():
                members.discard(msg.src_id)
            for lid, meta in msg.layer_ids.items():
                want = self._targets.get(msg.src_id, {}).get(lid)
                held_ok = (satisfies(meta, want) if want is not None
                           else delivered(meta))
                if not held_ok:
                    continue
                if want is not None and (want.shard or want.codec
                                         or want.version):
                    self._covered_q.setdefault(lid, set()).add(msg.src_id)
                else:
                    self._covered.setdefault(lid, set()).add(msg.src_id)
            pending = set(self._announce_dirty)
        # This seat never member-announces to itself (its announce goes
        # to the root directly), so it must not count as a pending
        # announcer — with it in the set the immediate flush could
        # never fire and every fold would eat the full debounce.
        if pending >= set(m for m in self.members
                          if m not in self._dead
                          and m != self.node.my_id):
            self._flush_announces()
        else:
            with self._lock:
                if self._announce_timer is None:
                    self._announce_timer = threading.Timer(
                        ANNOUNCE_FOLD_S, self._flush_announces)
                    self._announce_timer.daemon = True
                    self._announce_timer.start()
        self._fan_out_ready()

    def _flush_announces(self) -> None:
        with self._lock:
            if self._announce_timer is not None:
                self._announce_timer.cancel()
                self._announce_timer = None
            dirty = {m: dict(self._announced.get(m) or {})
                     for m in self._announce_dirty}
            digests = {m: dict(self._member_digests.get(m) or {})
                       for m in self._announce_dirty
                       if self._member_digests.get(m)}
            codecs = {m: list(self._member_codecs.get(m) or [])
                      for m in self._announce_dirty
                      if m in self._member_codecs}
            self._announce_dirty.clear()
            covered = self._covered_snapshot_locked()
        if dirty:
            trace.count("hier.announce_folds")
            self._push(announced=dirty, covered=covered, digests=digests,
                       codecs=codecs)

    def handle_member_ack(self, msg: AckMsg) -> None:
        self.detector.touch(msg.src_id)
        if msg.shard or msg.version or msg.codec:
            # Qualified acks (sharded / versioned / codec holdings)
            # carry tags the aggregate vocabulary doesn't: forward the
            # ack VERBATIM so the root's swap fences and codec
            # bookkeeping keep full fidelity.  Locally it still settles
            # the member's chain/fan-out send when the tags match its
            # target — qualified coverage stops re-sends without ever
            # riding the plain ``covered`` section upward.
            with self._lock:
                want = self._targets.get(msg.src_id, {}).get(msg.layer_id)
                if (want is not None
                        and (msg.shard or "") == (want.shard or "")
                        and codec_accepts(msg.codec, want.codec)
                        and (not want.version
                             or msg.version == want.version)):
                    self._covered_q.setdefault(
                        msg.layer_id, set()).add(msg.src_id)
                    self._sent.pop((msg.src_id, msg.layer_id), None)
            trace.count("hier.acks_forwarded")
            self.receiver._send_to_leader(msg)
            return
        push = None
        with self._lock:
            done = self._covered.setdefault(msg.layer_id, set())
            if msg.src_id not in done:
                done.add(msg.src_id)
                self._sent.pop((msg.src_id, msg.layer_id), None)
                if self._layer_complete_locked(msg.layer_id):
                    push = self._covered_snapshot_locked()
        if push is not None:
            trace.count("hier.layer_folds")
            log.info("group layer fully covered; folding upward",
                     group=self.group_id, layerID=msg.layer_id)
            self._push(covered=push, spans=self._covered_spans(push))

    def handle_member_metrics(self, msg: MetricsReportMsg) -> None:
        self.detector.touch(msg.src_id)
        with self._lock:
            self._member_metrics[msg.src_id] = {
                "Counters": dict(msg.counters),
                "Gauges": dict(msg.gauges),
                "Links": dict(msg.links),
                # Hists and span events batch upward too (docs/
                # observability.md): the root's serve-p99 health view
                # and critical-path walk need the members' OWN data —
                # a grouped replica must not go silently blind to the
                # SLO guard or the span timeline.
                "Hists": {k: dict(h) for k, h in msg.hists.items()},
                "Spans": [dict(ev) for ev in msg.spans],
                "T": msg.t_wall_ms, "Proc": msg.proc}
            self._metrics_dirty = True
            self._metrics_since_push.add(msg.src_id)
            live = {m for m in self.members if m not in self._dead}
            flush_now = self._metrics_since_push >= live
        if flush_now:
            # Every live member has reported since the last batch: push
            # NOW instead of waiting out the redrive tick — a short run
            # (receivers exit right after startup, having flushed their
            # final snapshots) would otherwise end before the batch
            # ever left, and the root's report would miss the members.
            self._push_metrics_if_dirty()

    def _forward_to_root(self, msg) -> None:
        """Pass a member's root-bound message upward verbatim (boot
        reports; the forwarded-ack path uses this too)."""
        self.detector.touch(msg.src_id)
        trace.count("hier.msgs_forwarded")
        self.receiver._send_to_leader(msg)

    def _route_swap(self, msg: SwapCommitMsg) -> None:
        """Leader-bound swap roles (confirm/query/error) from a member
        forward to the root; leader-ORIGINATED roles (prepare / commit
        / abort) are this seat's own business — the sub-leader can be
        a swap dest like any receiver."""
        if msg.applied or msg.query or msg.error:
            self._forward_to_root(msg)
            return
        self.receiver.handle_swap_commit(msg)

    def _member_dead(self, member: NodeID) -> None:
        with self._lock:
            self._dead.add(member)
            # Chain repair (docs/hierarchy.md): un-claim every uncovered
            # send of the layers the dead member targeted, so the next
            # event pass re-chains over the SURVIVORS — fresh forward
            # roles splice around the hole, and the re-seeded stripes
            # re-drive the dead seat's tail.  Downstream holes from
            # bytes it never forwarded heal via the members' gap-NACK
            # watchdogs against their upstream hop.
            lids = set(self._targets.get(member) or {})
            for key in [k for k in self._sent
                        if k[0] == member
                        or (k[1] in lids and not self._covered_done_locked(
                            k[1], k[0]))]:
                del self._sent[key]
            covered = self._covered_snapshot_locked()
        trace.count("hier.member_dead_reports")
        log.error("group member silent past timeout; reporting upward",
                  group=self.group_id, member=member)
        self._push(dead=[int(member)], covered=covered)
        self._fan_out_ready()

    # ----------------------------------------------------------- fan-out

    def _covered_done_locked(self, lid: LayerID, member: NodeID) -> bool:
        return (member in self._covered.get(lid, ())
                or member in self._covered_q.get(lid, ()))

    def _layer_complete_locked(self, lid: LayerID) -> bool:
        wanting = [m for m, row in self._targets.items()
                   if lid in row and m not in self._dead]
        return bool(wanting) and all(
            self._covered_done_locked(lid, m) for m in wanting)

    def _on_own_layer(self, lid: LayerID) -> None:
        self._fan_out_ready()

    def _fan_out_ready(self, resend_after: Optional[float] = None) -> None:
        """Deliver every held layer to every member still missing it.

        FIRST dispatch of a layer wanted by ≥2 members rides a
        K-striped member-to-member CHAIN (docs/hierarchy.md): forward
        roles install on the members, each stripe seeds at its head,
        and the rest of the bytes relay peer-to-peer — this seat's
        egress is the wire size once, not once per member.  Single
        wanters, chain-disabled runs, and every REDRIVE go direct — the
        redrive star is the convergence guarantee (legacy members that
        ignore roles, dead mid-chain hops, eaten sends).

        Pairs are claimed under ONE lock pass: two concurrent triggers
        (own-layer hook + plan receipt) must not both dispatch."""
        now = time.monotonic()
        due = []        # (member, lid, meta): direct sends
        fresh: Dict[LayerID, list] = {}  # lid -> [(member, meta)] chains
        with self._lock:
            if not self._active:
                return
            for member, row in self._targets.items():
                if member in self._dead:
                    continue
                for lid, meta in row.items():
                    if self._covered_done_locked(lid, member):
                        continue
                    t_sent = self._sent.get((member, lid))
                    if t_sent is not None and (
                            resend_after is None
                            or now - t_sent < resend_after):
                        continue
                    self._sent[(member, lid)] = now
                    if GROUP_CHAIN and t_sent is None:
                        fresh.setdefault(lid, []).append((member, meta))
                    else:
                        due.append((member, lid, meta))
        for lid in sorted(fresh):
            pairs = fresh[lid]
            if len(pairs) < 2:
                due.extend((m, lid, meta) for m, meta in pairs)
                continue
            if not self._dispatch_chain(lid, pairs):
                # Not servable yet (layer in flight / wrong form /
                # members want mixed forms): un-claim so the next
                # trigger re-collects; mixed forms degrade to star.
                with self._lock:
                    for m, _ in pairs:
                        self._sent.pop((m, lid), None)
                self._fan_out_star(due=[], retry=pairs, lid=lid)
        self._fan_out_star(due)

    def _fan_out_star(self, due, retry=None, lid=None) -> None:
        """The direct-send leg: dispatch each (member, lid, meta) whose
        target this seat's holding can serve, un-claiming the rest.
        ``retry``: mixed-form chain rejects re-dispatched per member —
        each pair re-claims individually so forms that DO serve
        star-send now instead of waiting out a redrive tick."""
        if retry:
            now = time.monotonic()
            with self._lock:
                for m, meta in retry:
                    if (m, lid) not in self._sent:
                        self._sent[(m, lid)] = now
                        due = due + [(m, lid, meta)]
        for member, lid, meta in due:
            with self.receiver._lock:
                layer = self.receiver.layers.get(lid)
            if layer is None or not self._holding_serves(layer, meta):
                # Not landed here yet (the root's plan is in flight), or
                # a holding in the WRONG form for this target (e.g. a
                # version-stamped rollout copy against a plain target):
                # un-claim so the next trigger re-collects it once a
                # servable copy exists.
                with self._lock:
                    self._sent.pop((member, lid), None)
                continue
            trace.count("hier.fanout_sends")
            trace.count("hier.subleader_egress_bytes", layer.data_size)
            log.info("fanning layer out to group member", layerID=lid,
                     member=member, group=self.group_id)
            threads.tx_pool().submit(self._send_one, member, lid, layer,
                                     meta)

    def _holding_serves(self, layer, meta) -> bool:
        """Whether this seat's holding can produce the exact bytes the
        member's target meta names (docs/hierarchy.md): the same
        encoded form (or raw + an encode-capable plane), a shard range
        the holding covers, and no version mismatch — a version-stamped
        copy serves only that version's targets (a plain target's
        digest gate would reject its bytes)."""
        held = layer.meta
        want_codec = meta.codec or ""
        if held.codec and held.codec != want_codec:
            return False
        if (want_codec and not held.codec
                and getattr(self.receiver, "codec_plane", None) is None):
            return False
        if not shard_covers(held.shard or "", meta.shard or ""):
            return False
        if (held.version or "") != (meta.version or ""):
            return False
        return True

    def _dispatch_chain(self, lid: LayerID, pairs) -> bool:
        """Plan + dispatch one layer's chain: forward roles to the
        members, stripe seeds to the heads.  False when the holding
        can't serve, or the members disagree on the target form (a
        chain ships ONE byte space; mixed forms fall back to star)."""
        forms = {(meta.shard or "", meta.codec or "", meta.version or "")
                 for _, meta in pairs}
        if len(forms) != 1:
            return False
        meta = pairs[0][1]
        with self.receiver._lock:
            layer = self.receiver.layers.get(lid)
        if layer is None or not self._holding_serves(layer, meta):
            return False
        want_codec = meta.codec or ""
        if want_codec and not layer.meta.codec:
            plane = getattr(self.receiver, "codec_plane", None)
            # Data-dependent forms (entropy, delta) size by their one
            # cached encode — the same blob the stripe sends then serve
            # ranges of (docs/codec.md).
            wire_total = (plane.ensure_sized(lid, layer, want_codec)
                          if plane else None)
            if wire_total is None:
                return False
        else:
            wire_total = layer.data_size
        lo, size = shard_range(meta.shard or "", wire_total)
        if size <= 0:
            return False
        members = sorted(m for m, _ in pairs)
        stripes = min(GROUP_STRIPES, len(members))
        heads, roles = chain_forward_roles(members, lo, size, stripes)
        epoch = self._plan_epoch
        trace.count("hier.chain_plans")
        trace.count("hier.subleader_egress_bytes", size)
        log.info("group chain planned", layerID=lid, group=self.group_id,
                 members=len(members), stripes=len(heads),
                 wire_bytes=size)
        for m, hops in sorted(roles.items()):
            if not hops:
                continue
            try:
                self.node.add_node(m)
                self.node.transport.send(m, GroupPlanMsg(
                    self.node.my_id, self.group_id, epoch=epoch,
                    forward={lid: [[a, b, nxt] for a, b, nxt in hops]}))
            except (OSError, KeyError, ConnectionError) as e:
                log.warn("chain role install failed (redrive will "
                         "star-send)", member=m, layerID=lid,
                         err=repr(e))
        for head, (a, b) in heads:
            threads.tx_pool().submit(self._send_range, head, lid, layer,
                                     meta, (a, b - a))
        return True

    def _send_range(self, member: NodeID, lid: LayerID, layer, meta,
                    rng) -> None:
        """One stripe seed: ship only the stripe's wire range to its
        head member; the chain relays the rest of the layer to it."""
        try:
            self.node.add_node(member)
            send_layer(self.node, member, lid, layer,
                       shard=meta.shard, codec=meta.codec,
                       codecs=getattr(self.receiver, "codec_plane", None),
                       span_parent=telemetry.span_id(self.node.my_id, lid),
                       wire_range=rng)
        except (OSError, KeyError, ConnectionError) as e:
            log.warn("chain stripe send failed (redrive will retry)",
                     layerID=lid, member=member, err=repr(e))

    def _send_one(self, member: NodeID, lid: LayerID, layer,
                  meta=None) -> None:
        try:
            self.node.add_node(member)
            # Span correlation (docs/observability.md): the fan-out is
            # a CHILD span chained under this seat's own (root-planned)
            # group-ingress pair — the parent tag rides the frames.
            # Qualified targets ship in their stamped byte space: the
            # shard/codec tags come from the member's target meta, and
            # the plane encode-serves a raw holding (docs/codec.md).
            send_layer(self.node, member, lid, layer,
                       shard=(meta.shard if meta is not None else ""),
                       codec=(meta.codec if meta is not None else ""),
                       codecs=getattr(self.receiver, "codec_plane", None),
                       span_parent=telemetry.span_id(self.node.my_id, lid))
        except (OSError, KeyError, ConnectionError) as e:
            log.warn("group fan-out send failed (redrive will retry)",
                     layerID=lid, member=member, err=repr(e))

    # ----------------------------------------------------------- redrive

    def _redrive_loop(self) -> None:
        interval = max(GROUP_RESEND_S / 2, 0.05)
        while not self._stop.wait(interval):
            try:
                self._fan_out_ready(resend_after=GROUP_RESEND_S)
                self._push_metrics_if_dirty()
            except Exception as e:  # noqa: BLE001 — keep the net up
                log.error("sub-leader redrive failed", err=repr(e))

    def _push_metrics_if_dirty(self) -> None:
        with self._lock:
            if not self._metrics_dirty:
                return
            self._metrics_dirty = False
            self._metrics_since_push.clear()
            batch = {m: dict(s) for m, s in self._member_metrics.items()}
        if batch:
            self._push(metrics=batch)
