"""External clients: the weight source, and the inference requester.

``Client`` is a re-design of ``/root/reference/distributor/client.go``: a
separate process holding layers (stand-in for S3/GCS/blob store) attached
to one node.  On a ``ClientReqMsg`` it streams the requested layer to its
node at the configured rate; the node's registered pipe relays it onward
cut-through.

``GenRequester`` is the client role's natural next step, beyond the
reference: once dissemination booted the engine, the same transport
serves inference — send prompt token ids to a booted node, get the
decoded ids back (``runtime/receiver.handle_generate_req``).
"""

from __future__ import annotations

import itertools
import queue
import threading

from ..core.types import CLIENT_ID, LayersSrc, NodeID  # noqa: F401  (CLIENT_ID re-exported)
from ..transport.base import Transport
from ..transport.messages import (
    ClientReqMsg,
    GenerateReqMsg,
    GenerateRespMsg,
    LayerMsg,
)
from ..utils.logging import log
from .node import MessageLoop


class Client:
    """Serves layers to its attached node on request (client.go:12-63)."""

    def __init__(self, node_id: NodeID, transport: Transport, layers: LayersSrc,
                 start_loop: bool = True):
        self.node_id = node_id  # the node this client is attached to
        self.transport = transport
        self.layers = layers
        self.loop = MessageLoop(transport)
        self.loop.register(ClientReqMsg, self.handle_client_req)
        if start_loop:
            self.loop.start()

    def handle_client_req(self, msg: ClientReqMsg) -> None:
        layer = self.layers.get(msg.layer_id)
        if layer is None:
            log.error("client has no such layer", layerID=msg.layer_id)
            return
        log.debug("sending layer", layerID=msg.layer_id)
        try:
            self.transport.send(
                self.node_id,
                LayerMsg(CLIENT_ID, msg.layer_id, layer, layer.data_size),
            )
        except (OSError, KeyError) as e:
            log.error("failed to send layer", dest=self.node_id, err=repr(e))

    def close(self) -> None:
        self.loop.stop()


class GenRequester:
    """Request inference from a booted node over the dissemination
    transport and block for the answer.

    ``my_id``: the id replies are addressed to — it must resolve on the
    serving node's transport (a topology node id, or the client role's
    id; defaults to ``int(transport.addr)`` when the addr is numeric,
    the in-process test convention).  Thread-safe: concurrent requests
    multiplex on ``req_id``."""

    def __init__(self, transport: Transport, my_id: NodeID = None,
                 start_loop: bool = True):
        if my_id is None:
            addr = getattr(transport, "addr", "")
            if not str(addr).isdigit():
                raise ValueError(
                    "my_id is required when the transport address is not "
                    "a bare node id")
            my_id = int(addr)
        self.my_id = my_id
        self.transport = transport
        self.loop = MessageLoop(transport)
        self.loop.register(GenerateRespMsg, self._handle_resp)
        self._lock = threading.Lock()
        self._pending: dict = {}  # req_id -> Queue[GenerateRespMsg]
        self._req_ids = itertools.count(1)
        if start_loop:
            self.loop.start()

    def _handle_resp(self, msg: GenerateRespMsg) -> None:
        with self._lock:
            q = self._pending.get(msg.req_id)
        if q is None:
            log.warn("response for unknown/expired request",
                     req=msg.req_id, server=msg.src_id)
            return
        q.put(msg)

    def request(self, dest: NodeID, prompt, max_new: int,
                timeout: float = 120.0, temperature: float = 0.0,
                seed: int = 0) -> list:
        """Decode ``max_new`` tokens after ``prompt`` on node ``dest``.
        ``temperature`` 0 = greedy; > 0 samples with ``seed`` (same seed,
        same tokens).  Returns the new token ids; raises RuntimeError on
        a served error and TimeoutError when no answer arrives (lost
        message / dead node)."""
        req_id = next(self._req_ids)
        q: "queue.Queue" = queue.Queue()
        with self._lock:
            self._pending[req_id] = q
        try:
            self.transport.send(
                dest,
                GenerateReqMsg(self.my_id, req_id, list(prompt),
                               int(max_new), float(temperature),
                               int(seed)),
            )
            try:
                resp = q.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(
                    f"no generation response from node {dest} within "
                    f"{timeout:g}s") from None
            if resp.error:
                raise RuntimeError(
                    f"node {dest} refused generation: {resp.error}")
            return list(resp.tokens)
        finally:
            with self._lock:
                self._pending.pop(req_id, None)

    def close(self) -> None:
        self.loop.stop()
