"""External weight source ("client").

Re-design of ``/root/reference/distributor/client.go``: a separate process
holding layers (stand-in for S3/GCS/blob store) attached to one node.  On a
``ClientReqMsg`` it streams the requested layer to its node at the
configured rate; the node's registered pipe relays it onward cut-through.
"""

from __future__ import annotations

from ..core.types import CLIENT_ID, LayersSrc, NodeID  # noqa: F401  (CLIENT_ID re-exported)
from ..transport.base import Transport
from ..transport.messages import ClientReqMsg, LayerMsg
from ..utils.logging import log
from .node import MessageLoop


class Client:
    """Serves layers to its attached node on request (client.go:12-63)."""

    def __init__(self, node_id: NodeID, transport: Transport, layers: LayersSrc,
                 start_loop: bool = True):
        self.node_id = node_id  # the node this client is attached to
        self.transport = transport
        self.layers = layers
        self.loop = MessageLoop(transport)
        self.loop.register(ClientReqMsg, self.handle_client_req)
        if start_loop:
            self.loop.start()

    def handle_client_req(self, msg: ClientReqMsg) -> None:
        layer = self.layers.get(msg.layer_id)
        if layer is None:
            log.error("client has no such layer", layerID=msg.layer_id)
            return
        log.debug("sending layer", layerID=msg.layer_id)
        try:
            self.transport.send(
                self.node_id,
                LayerMsg(CLIENT_ID, msg.layer_id, layer, layer.data_size),
            )
        except (OSError, KeyError) as e:
            log.error("failed to send layer", dest=self.node_id, err=repr(e))

    def close(self) -> None:
        self.loop.stop()
