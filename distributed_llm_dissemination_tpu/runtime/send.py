"""Shared layer-send paths used by leaders and receivers.

Re-design of the reference's send helpers: ``sendLayer``
(``/root/reference/distributor/node.go:354-373``), ``fetchFromClient``
(node.go:1345-1351), and the flow-job executor ``handleFlowRetransmit``
(node.go:1592-1643).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, Optional, Tuple

from ..core.types import (
    CLIENT_ID,
    LayerID,
    LayerLocation,
    LayerMeta,
    LayerSrc,
    LayersSrc,
    NodeID,
    shard_range,
)
from ..transport.messages import ClientReqMsg, FlowRetransmitMsg, LayerMsg
from ..utils import telemetry, threads, trace
from ..utils.logging import log
from ..utils.rate import TokenBucket
from .node import Node

# Flow jobs are sent as sub-fragments of at most this many bytes (the
# reference streams a job as one blob, node.go:1592-1607).  Bounded
# fragments give receivers incremental progress: each one advances the
# interval accounting and the durable checkpoint journal, so a transfer
# killed mid-job loses at most one fragment, not the whole job.
FLOW_FRAGMENT_BYTES = int(os.environ.get("DLD_FLOW_FRAGMENT_BYTES",
                                         str(16 << 20)))


def _fragment_bytes(rate: int) -> int:
    """Fragment size for one flow job.  Jobs whose commanded rate the
    transport will STRIPE (unlimited, or a budget-scale allotment —
    tcp.STRIPE_PACED_MIN_RATE) use STRIPE_COUNT-times larger fragments:
    each stripe is delivered/journaled/device-ingested as its own
    fragment, so the progress granularity receivers see stays
    ~FLOW_FRAGMENT_BYTES while the larger fragment amortizes the
    per-fragment barrier (all of a fragment's stripes land before the
    next fragment starts).  Slow modeled sources never stripe, so they
    keep the exact 16 MiB loss/progress granularity."""
    from ..transport.tcp import STRIPE_COUNT, STRIPE_PACED_MIN_RATE

    if rate == 0 or rate >= STRIPE_PACED_MIN_RATE:
        return FLOW_FRAGMENT_BYTES * max(1, STRIPE_COUNT)
    return FLOW_FRAGMENT_BYTES


def _codec_view(layer: LayerSrc, layer_id: LayerID, codec: str,
                codecs) -> Optional[LayerSrc]:
    """The LayerSrc a transfer at wire-codec ``codec`` reads its bytes
    — and byte SPACE — from (docs/codec.md): the holding itself when it
    already is that encoded form (encoded bytes forward verbatim, no
    decode/re-encode round trip), the cached encoded form of a
    canonical holding otherwise (``codecs`` is the node's
    ``WireCodecPlane``).  None = this holder cannot produce those exact
    bytes (wrong encoded form, or no encode capability) — the caller
    must refuse loudly rather than ship bytes the dest will account in
    a different byte space."""
    if not codec:
        return layer
    held = getattr(layer.meta, "codec", "")
    if held == codec:
        return layer
    if held or codecs is None:
        return None
    return codecs.encoded_src(layer_id, layer, codec)


def send_layer(node: Node, dest: NodeID, layer_id: LayerID, layer: LayerSrc,
               job_id: str = "", shard: str = "", codec: str = "",
               codecs=None, span_parent: str = "",
               wire_range: Optional[tuple] = None) -> None:
    """Send one full layer to ``dest``; client-held layers are fetched via
    the pipe mechanism instead (node.go:354-365).  ``job_id`` tags the
    frames with the admitted dissemination job they serve ("" = the base
    run) so link telemetry splits per job (docs/service.md).

    ``shard`` (docs/sharding.md): send only that shard spec's byte
    range of the layer, as a byte-range fragment (``total_size`` stays
    the full layer size, so the dest's interval accounting speaks
    absolute layer coordinates) — the whole-layer path for modes 0-2
    honoring a sharded target.  Client-held layers can't range-serve
    and fall back to the full-layer pipe fetch (over-delivery is safe).

    ``codec`` (docs/codec.md): ship the layer's ENCODED form — the
    wire total (and any shard range) then lives in encoded byte space,
    and the frames carry the codec tag.  Client-held layers can't
    encode-serve; they fall back to the raw pipe fetch (the dest's
    digest gate treats the raw bytes as a raw delivery — raw satisfies
    every target).

    ``wire_range`` (docs/hierarchy.md): send only ``(offset, size)`` of
    the wire byte space — the chain stripe seed path, where the
    sub-leader ships each stripe to its head member and the rest of the
    range arrives via member relays.  Offsets index the view the
    shard/codec tags describe, and the frames still carry those tags so
    downstream accounting stays in the stamped byte space."""
    if layer.meta.location == LayerLocation.CLIENT:
        log.debug("loading layer from client", layer=layer_id)
        fetch_from_client(node, layer_id, dest)
        return
    view = _codec_view(layer, layer_id, codec, codecs)
    if view is None:
        log.error("cannot serve layer at commanded wire codec",
                  layerID=layer_id, codec=codec,
                  held=getattr(layer.meta, "codec", ""))
        return
    if codec:
        trace.count("codec.wire_sends")
    # Pair-lifecycle span (docs/observability.md): the send begins NOW
    # — the frames carry the advisory id (+ the parent tag for
    # sub-leader fan-out children) for cross-node correlation.
    span = telemetry.span_id(dest, layer_id)
    telemetry.span_event(span, "dispatched", node=node.my_id,
                         src=node.my_id, dest=dest, layer=layer_id,
                         job=job_id, codec=codec, shard=shard,
                         parent=span_parent)
    if wire_range is not None:
        off, size = int(wire_range[0]), int(wire_range[1])
        size = min(size, max(0, view.data_size - off))
        if size <= 0:
            log.error("wire range outside the layer's byte space; dropped",
                      layerID=layer_id, offset=wire_range[0],
                      size=wire_range[1], layer_size=view.data_size)
            return
        sub = _sub_layer_src(view, _sendable_location(view), off, size,
                             layer.meta.limit_rate)
        node.transport.send(
            dest, LayerMsg(node.my_id, layer_id, sub, view.data_size,
                           job_id=job_id, shard=shard, codec=codec,
                           span_id=span, span_parent=span_parent)
        )
        return
    if shard:
        off, size = shard_range(shard, view.data_size)
        sub = _sub_layer_src(view, _sendable_location(view), off, size,
                             layer.meta.limit_rate)
        trace.count("shard.range_sends")
        node.transport.send(
            dest, LayerMsg(node.my_id, layer_id, sub, view.data_size,
                           job_id=job_id, shard=shard, codec=codec,
                           span_id=span, span_parent=span_parent)
        )
        return
    node.transport.send(
        dest, LayerMsg(node.my_id, layer_id, view, view.data_size,
                       job_id=job_id, codec=codec,
                       span_id=span, span_parent=span_parent)
    )


def fetch_from_client(node: Node, layer_id: LayerID, dest: NodeID) -> None:
    """Register a cut-through pipe (layer → dest) and ask the external
    client to stream the layer (node.go:367-373)."""
    log.debug("ask the client to send the layer", layerID=layer_id)
    node.transport.register_pipe(layer_id, dest)
    node.transport.send(CLIENT_ID, ClientReqMsg(node.my_id, layer_id, False))


def _sendable_location(layer: LayerSrc) -> LayerLocation:
    """The location a range-send should read from.  An HBM-staged layer
    serves like INMEM: from its retained host buffer, or — for
    fabric-delivered layers that never had one — from a host copy
    materialized off the device array (one cached fetch)."""
    loc = layer.meta.location
    if loc == LayerLocation.HBM and layer.ensure_host_bytes():
        loc = LayerLocation.INMEM
    return loc


def _sub_layer_src(layer: LayerSrc, send_loc: LayerLocation, offset: int,
                   size: int, rate: int) -> LayerSrc:
    """A byte-range view of a held layer for (re)transmission — the ONE
    construction shared by flow sends and NACK retransmits, so the two
    paths can't drift.  ``LayerSrc.offset`` doubles as the read position
    in the backing store AND the wire fragment offset; held layers are
    always constructed with ``offset == 0`` (core/config.py), which
    keeps the two roles coincident."""
    return LayerSrc(
        inmem_data=layer.inmem_data, fp=layer.fp, data_size=size,
        offset=layer.offset + offset,
        meta=LayerMeta(location=send_loc, limit_rate=rate,
                       source_type=layer.meta.source_type),
    )


class RevokeRegistry:
    """Sender-side preemption revoke (docs/service.md): the leader's
    ``JobRevokeMsg`` names a demoted job's (dest, layer) pairs whose
    queued sends should not burn the reclaimed link budget.  Entries
    are CONSUMED on first match (the re-plan that triggered the revoke
    re-dispatches the same pair at the demoted rate — the fresh command
    must not be eaten too) and TTL-bounded (a revocation whose send
    already finished must not linger to eat a future command).

    Generation keying closes the wrong-eat race the TTL alone left
    open: a revoke carries the plan generation it fenced, a dispatched
    command carries the generation of the solve that produced it, and
    an entry eats ONLY commands stamped at or below its generation — a
    revoke applied late at a slow sender can no longer eat the
    re-plan's fresh command for the same (job, dest, layer).  ``gen=0``
    on both sides preserves the legacy (TTL-only) behavior."""

    TTL_S = 30.0

    def __init__(self):
        self._lock = threading.Lock()
        # (job, dest, layer) -> (wall time, revoked plan generation)
        self._revoked: Dict[tuple, Tuple[float, int]] = {}

    def add(self, job_id: str, pairs, gen: int = 0) -> int:
        import time

        now = time.time()
        with self._lock:
            for dest, lid in pairs:
                key = (str(job_id), int(dest), int(lid))
                old = self._revoked.get(key)
                # A newer revoke's generation wins; never let a stale
                # re-delivery LOWER the fence.
                g = max(int(gen), old[1] if old else 0)
                self._revoked[key] = (now, g)
            return len(self._revoked)

    def consume(self, job_id: str, dest: NodeID, lid: LayerID,
                gen: int = 0) -> bool:
        """True when (job, dest, layer) is revoked for this command's
        plan generation; a match spends the entry.  A command from a
        NEWER generation than the revoke survives — and leaves the
        entry ARMED, because the stale command it fences may still be
        queued (or mid-fragments) behind this one; popping here would
        disarm the revoke before its target ever checked (TTL bounds
        the entry if that command never arrives)."""
        import time

        if not job_id:
            return False  # base-run sends are never revoked
        key = (str(job_id), int(dest), int(lid))
        now = time.time()
        with self._lock:
            rec = self._revoked.get(key)
            if rec is None:
                return False
            t, revoked_gen = rec
            if now - t > self.TTL_S:
                del self._revoked[key]
                return False  # expired: treat as never revoked
            if int(gen) > revoked_gen:
                # The command postdates the revoke's plan: it is the
                # re-dispatch the revoke made room for — let it run.
                return False
            del self._revoked[key]
            return True


class NackRetransmitter:
    """Bounded-retry byte-range retransmit service for ``LayerNackMsg``
    (docs/integrity.md) — the sender half of the integrity plane, shared
    by every node that serves layers (leaders of all four modes and
    retransmit-capable receivers).

    A receiver whose transport dropped a corrupt fragment NACKs the
    range; this re-sends exactly ``[offset, offset+size)`` of the named
    layer as ONE logical send (the transport re-stripes large ranges
    itself, so a regrouping plain receiver still sees one whole
    message).  Retries are bounded per (dest, layer, offset): a
    persistently corrupt path — bad RAM on the source, a broken NIC —
    must surface as a loud failure for the crash/re-plan machinery, not
    a silent retransmit livelock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[tuple, int] = {}
        # Read at construction like the other integrity knobs
        # (DLD_GAP_NACK_S, DLD_WIRE_CRC, ...), not at import time.
        self.LIMIT = int(os.environ.get("DLD_NACK_RETRY_LIMIT", "6"))

    def admit(self, dest: NodeID, layer_id: LayerID, offset: int,
              size: int = 0) -> int:
        """Count one retransmit attempt for (dest, layer, offset) and
        return the attempt number, or 0 when the bounded budget is
        exhausted.  ONE budget shared by every serving path on this
        node (completed-holding retransmits and in-flight partial-range
        relay serves), so a range can't double its retries by being
        servable two ways."""
        key = (dest, layer_id, offset)
        with self._lock:
            n = self._counts.get(key, 0) + 1
            self._counts[key] = n
        if n > self.LIMIT:
            log.error("NACK retry budget exhausted; giving up on range "
                      "(crash detection / re-announce must recover it)",
                      dest=dest, layerID=layer_id,
                      offset=offset, size=size, tries=n)
            trace.count("integrity.nack_suppressed")
            return 0
        return n

    def handle(self, node: Node, layers: LayersSrc, lock: threading.Lock,
               msg, codecs=None) -> bool:
        """Serve one NACK; True when the range was re-sent.  A NACK
        carrying a wire codec (docs/codec.md) names a range of the
        ENCODED blob: it is served from the same-codec holding (or the
        cached encoded form of a canonical one, ``codecs``) so the
        retransmitted bytes are byte-identical to the originals —
        NACK/retransmit recovery runs entirely in encoded space."""
        n = self.admit(msg.src_id, msg.layer_id, msg.offset, msg.size)
        if not n:
            return False
        with lock:
            layer = layers.get(msg.layer_id)
        if layer is None:
            log.error("NACK for a layer this node doesn't hold",
                      layerID=msg.layer_id, dest=msg.src_id)
            return False
        if layer.meta.location == LayerLocation.CLIENT:
            log.error("NACK for a client-held layer; cannot range-serve "
                      "it from here", layerID=msg.layer_id)
            return False
        codec = getattr(msg, "codec", "")
        view = _codec_view(layer, msg.layer_id, codec, codecs)
        if view is None:
            log.error("NACK names a wire codec this holder cannot serve",
                      layerID=msg.layer_id, codec=codec,
                      held=getattr(layer.meta, "codec", ""))
            return False
        send_loc = _sendable_location(view)
        size = min(msg.size, max(0, view.data_size - msg.offset))
        if size <= 0:
            log.error("NACK names an out-of-range span", layerID=msg.layer_id,
                      offset=msg.offset, size=msg.size,
                      layer_size=view.data_size)
            return False
        if layer.meta.shard:
            # A SHARD holder's buffer is only real inside its shard's
            # range — serving bytes outside it would retransmit garbage
            # as verified-looking frames (docs/sharding.md).  For a
            # codec shard-holding the range lives in encoded space, the
            # same space the holding's buffer is real in.
            s0, sz = shard_range(layer.meta.shard, view.data_size)
            if msg.offset < s0 or msg.offset + size > s0 + sz:
                log.error("NACK names bytes outside this holder's shard; "
                          "cannot range-serve them from here",
                          layerID=msg.layer_id, offset=msg.offset,
                          size=size, shard=layer.meta.shard)
                return False
        node.add_node(msg.src_id)
        # Retransmits honor the holder's modeled source rate — a NACK
        # must not let a rate-limited seeder exceed what its source
        # could physically serve.
        sub = _sub_layer_src(view, send_loc, msg.offset, size,
                             layer.meta.limit_rate)
        log.warn("NACK retransmit", layerID=msg.layer_id, dest=msg.src_id,
                 offset=msg.offset, bytes=size, reason=msg.reason,
                 attempt=n, codec=codec or None)
        trace.count("integrity.retransmit_frags")
        trace.count("integrity.retransmit_bytes", size)
        telemetry.link_add(node.my_id, msg.src_id,
                           retransmit_frames=1, retransmit_bytes=size)
        node.transport.send(
            msg.src_id,
            LayerMsg(node.my_id, msg.layer_id, sub, view.data_size,
                     codec=codec,
                     # Tag only: a retransmit serves the pair's EXISTING
                     # span — re-recording "dispatched" here would
                     # falsely shift the span's wire window.
                     span_id=telemetry.span_id(msg.src_id, msg.layer_id)),
        )
        return True


class _FabricUploadCache:
    """Budgeted LRU over seeder-side full-layer device copies.

    A seeder serving many layers to many destinations must not pin one
    whole-layer HBM copy per layer forever — at 70B scale that exceeds a
    chip.  Entries count against ``budget_bytes`` (default 4 GiB,
    ``FABRIC_UPLOAD_CACHE_BYTES`` env overrides); eviction clears the
    record's ``device_array`` (safe: only records this cache populated —
    never receiver-staged HBM layers, whose location is HBM).  A failed
    upload is memoized so k plans don't re-read a multi-GiB layer into
    host RAM k times just to fail the same device_put again."""

    def __init__(self):
        import os

        self.budget = int(os.environ.get("FABRIC_UPLOAD_CACHE_BYTES",
                                         4 << 30))
        self._lock = threading.Lock()
        self._order: Dict[int, object] = {}  # id(record) -> record (LRU)
        self._bytes = 0
        # Latched by clear() at startup: while closed, new uploads serve
        # their plan transiently and are never retained — the decision is
        # made at INSERT time under the cache lock, so no caller-side
        # flag-read can race the release (the HBM belongs to the booted
        # model until reopen()).
        self._closed = False

    def get_or_put(self, layer, layer_id, device):
        import jax
        import numpy as np

        key = id(layer)
        with layer._host_lock:  # once-guard, shared with ensure_host_bytes
            dev = getattr(layer, "device_array", None)
            if dev is not None:
                with self._lock:  # LRU touch: reuse = recency
                    if key in self._order:
                        self._order[key] = self._order.pop(key)
                return dev if (getattr(dev, "ndim", 0) == 1
                               and dev.dtype == np.uint8) else None
            if layer.upload_failed or layer.data_size > self.budget:
                return None
            try:
                whole = np.frombuffer(
                    layer.read_span(0, layer.data_size), np.uint8
                )
                dev = jax.device_put(whole, device)
            except Exception as e:  # noqa: BLE001 — fall back to ranges
                log.warn("full-layer upload cache failed; using range "
                         "uploads for this layer from now on",
                         layerID=layer_id, err=repr(e))
                # Memoized on the RECORD (an id()-keyed set would outlive
                # the object and poison whatever reuses its address).
                layer.upload_failed = True
                return None
            layer.device_array = dev
        # Victims are collected under the cache lock but cleared outside
        # it: clearing takes the victim's _host_lock, and another thread
        # in get_or_put holds its own _host_lock while briefly taking the
        # cache lock — nesting them here in the opposite order could
        # deadlock.
        victims = []
        retained = True
        with self._lock:
            if self._closed:
                # Released (startup fired, the model owns the HBM): serve
                # THIS plan from the transient handle, retain nothing.
                retained = False
            else:
                self._order[key] = layer
                self._bytes += layer.data_size
                while self._bytes > self.budget and len(self._order) > 1:
                    old_key, old = next(iter(self._order.items()))
                    if old_key == key:
                        break  # never evict the entry just inserted
                    del self._order[old_key]
                    self._bytes -= old.data_size
                    victims.append(old)
        if not retained:
            with layer._host_lock:
                if (layer.device_array is dev
                        and layer.meta.location != LayerLocation.HBM):
                    layer.device_array = None
        for old in victims:
            with old._host_lock:
                if old.meta.location != LayerLocation.HBM:
                    old.device_array = None  # frees the HBM copy
        return dev

    def reopen(self) -> None:
        """Re-arm retention for a new distribution cycle (a node
        announcing, or a leader dispatching plans for an unfinished
        goal)."""
        with self._lock:
            self._closed = False

    def clear(self) -> int:
        """Release every cached upload (dissemination is over — the HBM
        belongs to the booting model now).  Returns entries freed."""
        with self._lock:
            victims = list(self._order.values())
            self._order.clear()
            self._bytes = 0
            self._closed = True
        for old in victims:
            with old._host_lock:
                if old.meta.location != LayerLocation.HBM:
                    old.device_array = None
        return len(victims)


_upload_cache = _FabricUploadCache()


def release_upload_cache() -> None:
    """Drop the fabric upload cache's device copies and close retention;
    nodes call this on startup (assignment satisfied — the HBM belongs
    to whatever boots next).  ``reopen_upload_cache`` re-arms it."""
    freed = _upload_cache.clear()
    if freed:
        log.info("released fabric upload cache", entries=freed)


def reopen_upload_cache() -> None:
    """Re-arm upload retention for a new distribution cycle."""
    _upload_cache.reopen()


def contribute_device_plan(
    node: Node, layers: LayersSrc, lock: threading.Lock, fabric, placement,
    msg,
) -> None:
    """Publish this node's byte ranges of a device plan onto its OWN stage
    devices (the pod-fabric sender half, ``parallel/fabric.py``).

    The host→HBM upload happens here, locally — the same hop a TCP send
    would have paid to read the layer — and the destination's ingest then
    moves the fragment device-to-device (ICI).  A seeder whose copy is
    already HBM-staged contributes an on-device slice: no host traffic at
    all.  Multiple ranges from one node fan out round-robin across its
    stage devices so their uploads overlap."""
    mine = [(off, size) for s, off, size in msg.layout if s == node.my_id]
    if not mine:
        return
    with lock:
        layer = layers.get(msg.layer_id)
    if layer is None:
        log.error("no layer for device plan", layerID=msg.layer_id,
                  plan=msg.plan_id)
        return
    import jax
    import numpy as np

    devices = placement.devices_for_node(node.my_id)
    dev_src = getattr(layer, "device_array", None)
    if dev_src is not None and not (
        getattr(dev_src, "ndim", 0) == 1 and dev_src.dtype == np.uint8
    ):
        dev_src = None  # only raw uint8 blobs slice meaningfully by byte

    if dev_src is None and sum(size for _, size in mine) * 2 >= layer.data_size:
        # Contributing most of the layer: upload it whole ONCE and cache
        # the device copy on the record — a mode-0/1 seeder serving k
        # destinations (k plans, each a full-layer layout) then pays one
        # host→HBM upload instead of k, and every later plan or re-plan
        # slices device-side.  Small byte-range jobs (mode-3 splits) keep
        # the range-only upload below.
        dev_src = _upload_cache.get_or_put(layer, msg.layer_id, devices[0])

    for k, (off, size) in enumerate(mine):
        dev = devices[k % len(devices)]
        if dev_src is not None:
            piece = jax.device_put(dev_src[off : off + size], dev)
        else:
            # read_span: only the contributed range touches host RAM (a
            # disk seeder of a multi-GiB layer serves small ranges).
            piece = jax.device_put(
                np.frombuffer(layer.read_span(off, size), np.uint8), dev
            )
        fabric.publish(msg.plan_id, off, piece)
        log.debug("published fabric contribution", layerID=msg.layer_id,
                  plan=msg.plan_id, offset=off, size=size)


def handle_flow_retransmit(
    node: Node,
    layers: LayersSrc,
    lock: threading.Lock,
    fetch_fn: Callable[[LayerID, NodeID], None],
    msg: FlowRetransmitMsg,
    revokes: "Optional[RevokeRegistry]" = None,
    codecs=None,
) -> None:
    """Execute one flow job: send ``[offset, offset+data_size)`` of a layer
    to the dest at the commanded rate (node.go:1592-1643).

    ``revokes``: the sender's preemption-revoke registry.  A queued job
    whose (job, dest, layer) the leader revoked before it started is
    dropped whole (counted on ``jobs.revoked_pairs``); a revocation
    landing mid-job stops the remaining fragments — either way the
    re-plan that issued the revoke re-dispatches the pair at the
    demoted tier's budget.

    ``codecs`` (docs/codec.md): the sender's wire-codec plane.  A job
    carrying a codec indexes the ENCODED blob — the commanded byte
    range, every emitted fragment, and the wire total all live in
    encoded space, read from the cached encoded form (or a same-codec
    holding verbatim).  A holder that can't produce those bytes refuses
    loudly (the leader's arc filter should never have picked it).

    The ClientLayer branch simulates a rate-limited fetch from the node's
    own external client, then loops the partial layer back into the node's
    own delivery queue — the reference does the same (node.go:1610-1635)
    but would nil-panic there because client-layer records carry no data
    (cmd/config.go:187-198); here missing bytes are zero-filled."""
    with lock:
        layer = layers.get(msg.layer_id)
    if layer is None:
        log.error("no layer for flow job", layerID=msg.layer_id)
        return
    if (revokes is not None
            and revokes.consume(msg.job_id, msg.dest_id, msg.layer_id,
                                gen=getattr(msg, "gen", 0))):
        trace.count("jobs.revoked_pairs")
        log.warn("queued flow send revoked by preemption; dropped",
                 layerID=msg.layer_id, dest=msg.dest_id, job=msg.job_id)
        return
    node.add_node(msg.dest_id)

    codec = getattr(msg, "codec", "")
    view = layer
    if codec and layer.meta.location != LayerLocation.CLIENT:
        view = _codec_view(layer, msg.layer_id, codec, codecs)
        if view is None:
            log.error("flow job commands a wire codec this holder "
                      "cannot serve", layerID=msg.layer_id, codec=codec,
                      held=getattr(layer.meta, "codec", ""))
            return
        trace.count("codec.wire_sends")

    send_loc = _sendable_location(view)
    if send_loc in (LayerLocation.INMEM, LayerLocation.DISK):
        # Pair-lifecycle span (docs/observability.md): the command left
        # the sender's queue NOW — planned→dispatched is the queueing
        # attribution the critical-path walk charges to this sender.
        span = telemetry.span_id(msg.dest_id, msg.layer_id)
        telemetry.span_event(span, "dispatched", node=node.my_id,
                             src=node.my_id, dest=msg.dest_id,
                             layer=msg.layer_id, job=msg.job_id,
                             codec=codec, bytes=msg.data_size)
        frag_bytes = _fragment_bytes(msg.rate)
        sent = 0
        while sent < msg.data_size:
            if (sent > 0 and revokes is not None
                    and revokes.consume(msg.job_id, msg.dest_id,
                                        msg.layer_id,
                                        gen=getattr(msg, "gen", 0))):
                trace.count("jobs.revoked_pairs")
                log.warn("in-flight flow send revoked mid-job; stopping",
                         layerID=msg.layer_id, dest=msg.dest_id,
                         job=msg.job_id, sent=sent)
                return
            n = min(frag_bytes, msg.data_size - sent)
            partial = _sub_layer_src(view, send_loc, msg.offset + sent, n,
                                     msg.rate)
            node.transport.send(
                msg.dest_id,
                LayerMsg(node.my_id, msg.layer_id, partial, view.data_size,
                         job_id=msg.job_id, codec=codec, span_id=span),
            )
            sent += n
    elif layer.meta.location == LayerLocation.CLIENT:
        def _simulate_client_fetch() -> None:
            if layer.inmem_data is not None:
                data = bytearray(
                    memoryview(layer.inmem_data)[msg.offset : msg.offset + msg.data_size]
                )
            else:
                data = bytearray(msg.data_size)
            TokenBucket(msg.rate).wait_n(len(data))
            partial = LayerSrc(
                inmem_data=data,
                data_size=msg.data_size,
                offset=msg.offset,
                meta=LayerMeta(location=LayerLocation.INMEM),
            )
            node.transport.deliver().put(
                LayerMsg(node.my_id, msg.layer_id, partial, layer.data_size,
                         job_id=msg.job_id)
            )

        # A per-transfer data-plane task: rides the bounded tx pool
        # (utils/threads.py) — simulated client fetches must not imply
        # a thread each any more than real sends do.
        threads.tx_pool().submit(_simulate_client_fetch)
    else:
        log.error("unknown location", layerID=msg.layer_id)
