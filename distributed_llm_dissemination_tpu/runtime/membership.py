"""Elastic membership: the cluster roster as a replicated, epoch-fenced
state machine (docs/membership.md).

Until this module, the topology was a config constant: every node the
run would ever speak to was named before the first announce, and a node
that appeared or disappeared mid-run was either invisible or a crash.
A fleet autoscales.  :class:`MembershipTable` is the leader's
authoritative roster — who is in the cluster, in what state, admitted
under which epoch, reachable at what address — with exactly the
lifecycle the three membership verbs need:

- **join**: an unconfigured node announces itself (``JoinMsg``) and is
  admitted as ``JOINING`` — a delivery DEST immediately, but quarantined
  as a SOURCE until its announced holdings digest-verify against the
  leader's stamps (``verified``); verification flips it ``ACTIVE``.
- **drain**: a planned departure moves ``ACTIVE → DRAINING`` while the
  leader re-homes the drainer's unique holdings onto survivors, then
  ``DRAINING → LEFT`` atomically with its removal from the failure
  detector, lease recipients, and announce gating — a clean leave never
  fires the crash path.
- **cold-boot** is join plus content: the joiner's announce carries its
  local shard set (checkpointed partials + digests), so the planner
  ships only the complement — mostly from current peer holders.

Epoch fencing vs zombie rejoiners: every record remembers the leader
epoch it was admitted under and a per-seat ``generation`` counter.  A
node that LEFT stays left — its late announces, acks, and heartbeats
are fenced (the leader consults :meth:`is_left`) until it re-joins,
which mints a FRESH generation at the CURRENT epoch.  The whole table
replicates to standbys (``ControlDeltaMsg`` kind ``"membership"`` +
the snapshot's ``Membership`` section), so a promoted leader resumes
admission and in-flight drains instead of rediscovering the fleet.

The table never calls back into leader code (same contract as
``sched.jobs.JobManager``): it is bookkeeping the leader mutates under
its own locking discipline, safe to snapshot from any thread.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set

from ..core.types import NodeID

# Member lifecycle states.  JOINING is a dest-only probation (announced
# holdings are not yet trusted as transfer sources); ACTIVE is full
# citizenship; DRAINING is a departure in progress (still a SOURCE for
# its own re-home transfers, never new demand); LEFT is terminal for
# the generation — only a fresh join resurrects the seat.
JOINING = "joining"
ACTIVE = "active"
DRAINING = "draining"
LEFT = "left"


class MemberRecord:
    """One seat's membership row."""

    __slots__ = ("node_id", "state", "addr", "epoch", "generation",
                 "verified")

    def __init__(self, node_id: NodeID, state: str = ACTIVE,
                 addr: str = "", epoch: int = -1, generation: int = 0,
                 verified: bool = True):
        self.node_id = int(node_id)
        self.state = str(state)
        self.addr = str(addr)
        self.epoch = int(epoch)
        self.generation = int(generation)
        self.verified = bool(verified)

    def to_json(self) -> dict:
        out: dict = {"State": self.state}
        if self.addr:
            out["Addr"] = self.addr
        if self.epoch >= 0:
            out["Epoch"] = self.epoch
        if self.generation:
            out["Gen"] = self.generation
        if not self.verified:
            out["Unverified"] = True
        return out

    @classmethod
    def from_json(cls, node_id: NodeID, d: dict) -> "MemberRecord":
        return cls(node_id, str(d.get("State", ACTIVE)),
                   str(d.get("Addr", "")), int(d.get("Epoch", -1)),
                   int(d.get("Gen", 0)),
                   not bool(d.get("Unverified", False)))


class MembershipTable:
    """The leader's replicated cluster roster.  Thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._members: Dict[NodeID, MemberRecord] = {}

    # ------------------------------------------------------------- seeding

    def seed(self, node_ids, epoch: int = -1) -> None:
        """Configured seats are ACTIVE and source-verified from the
        start: the config is the operator's trust statement, exactly
        the trust the pre-membership planner already placed in it."""
        with self._lock:
            for n in node_ids:
                self._members.setdefault(
                    int(n), MemberRecord(int(n), ACTIVE, epoch=epoch))

    # --------------------------------------------------------------- verbs

    def admit(self, node: NodeID, addr: str = "",
              epoch: int = -1) -> MemberRecord:
        """Admit a joiner (idempotent for a live seat; a LEFT seat —
        the zombie-rejoiner case — re-admits as a FRESH generation at
        the caller's current epoch, so nothing its dead generation did
        is trusted)."""
        node = int(node)
        with self._lock:
            rec = self._members.get(node)
            if rec is not None and rec.state != LEFT:
                if addr:
                    rec.addr = str(addr)
                return rec
            gen = rec.generation + 1 if rec is not None else 0
            rec = MemberRecord(node, JOINING, addr=addr, epoch=epoch,
                               generation=gen, verified=False)
            self._members[node] = rec
            return rec

    def verify_source(self, node: NodeID) -> bool:
        """The joiner's announced holdings digest-verified: it may now
        be planned as a SOURCE.  Returns whether anything changed."""
        with self._lock:
            rec = self._members.get(int(node))
            if rec is None or rec.state == LEFT:
                return False
            changed = not rec.verified or rec.state == JOINING
            rec.verified = True
            if rec.state == JOINING:
                rec.state = ACTIVE
            return changed

    def start_drain(self, node: NodeID) -> bool:
        """ACTIVE/JOINING → DRAINING.  False when the seat is unknown
        or already left (the caller answers the requester either way)."""
        with self._lock:
            rec = self._members.get(int(node))
            if rec is None or rec.state == LEFT:
                return False
            if rec.state == DRAINING:
                return False
            rec.state = DRAINING
            return True

    def complete_drain(self, node: NodeID) -> bool:
        """DRAINING → LEFT, exactly once."""
        with self._lock:
            rec = self._members.get(int(node))
            if rec is None or rec.state != DRAINING:
                return False
            rec.state = LEFT
            return True

    def mark_left(self, node: NodeID) -> None:
        """Record a terminal departure without the drain protocol (a
        crash the caller wants fenced like a leave)."""
        with self._lock:
            rec = self._members.get(int(node))
            if rec is not None:
                rec.state = LEFT

    def forget(self, node: NodeID) -> None:
        with self._lock:
            self._members.pop(int(node), None)

    # ------------------------------------------------------------- queries

    def state_of(self, node: NodeID) -> Optional[str]:
        with self._lock:
            rec = self._members.get(int(node))
            return rec.state if rec is not None else None

    def is_left(self, node: NodeID) -> bool:
        return self.state_of(node) == LEFT

    def is_draining(self, node: NodeID) -> bool:
        return self.state_of(node) == DRAINING

    def generation_of(self, node: NodeID) -> int:
        with self._lock:
            rec = self._members.get(int(node))
            return rec.generation if rec is not None else 0

    def addr_of(self, node: NodeID) -> str:
        with self._lock:
            rec = self._members.get(int(node))
            return rec.addr if rec is not None else ""

    def unverified_sources(self) -> Set[NodeID]:
        """Seats whose announced holdings must NOT be planned as
        transfer sources (joining probation, or a failed verify)."""
        with self._lock:
            return {n for n, rec in self._members.items()
                    if rec.state != LEFT and not rec.verified}

    def live(self) -> Set[NodeID]:
        """Every seat that has not LEFT (draining counts: it still
        sources its own re-home transfers)."""
        with self._lock:
            return {n for n, rec in self._members.items()
                    if rec.state != LEFT}

    def placeable(self) -> Set[NodeID]:
        """Seats eligible to RECEIVE new demand (re-homed holdings,
        joiner refills): live, not on their way out."""
        with self._lock:
            return {n for n, rec in self._members.items()
                    if rec.state in (ACTIVE, JOINING)}

    def spares(self, busy) -> List[NodeID]:
        """Placeable seats NOT in ``busy`` — the candidate pool the
        autonomy engine's grow rule places a replica refill onto
        (docs/autonomy.md).  Sorted for deterministic policy choice;
        ACTIVE (verified) seats order before still-JOINING ones so a
        grow lands on settled capacity when any exists."""
        busy = set(int(b) for b in busy)
        with self._lock:
            pool = [(0 if rec.state == ACTIVE else 1, n)
                    for n, rec in self._members.items()
                    if rec.state in (ACTIVE, JOINING) and n not in busy]
        return [n for _, n in sorted(pool)]

    def draining(self) -> List[NodeID]:
        with self._lock:
            return sorted(n for n, rec in self._members.items()
                          if rec.state == DRAINING)

    def joining(self) -> List[NodeID]:
        with self._lock:
            return sorted(n for n, rec in self._members.items()
                          if rec.state == JOINING)

    def addrs(self) -> Dict[NodeID, str]:
        """Every known (node, addr) — a promoted leader installs them
        into its transport registry so adopted joiners stay dialable."""
        with self._lock:
            return {n: rec.addr for n, rec in self._members.items()
                    if rec.addr and rec.state != LEFT}

    # --------------------------------------------------------- replication

    def to_json(self) -> Dict[str, dict]:
        with self._lock:
            return {str(n): rec.to_json()
                    for n, rec in sorted(self._members.items())}

    def load(self, records: Dict[str, dict]) -> None:
        """Restore from a replicated snapshot/delta (REPLACE — the
        delta always carries the leader's full current table, so a
        revoked membership is exactly an absent row)."""
        with self._lock:
            self._members = {
                int(n): MemberRecord.from_json(int(n), dict(rec or {}))
                for n, rec in (records or {}).items()}

    def size(self) -> int:
        with self._lock:
            return len(self._members)
