"""On-device partial-layer reassembly.

The device-plane fix for the reference's biggest shortcut: its mode-3
receiver never reassembles partial layers (the copy is commented out,
``/root/reference/distributor/node.go:1545-1547``).  Host-side reassembly
lives in ``runtime/receiver.py``; here fragments are written into a
preallocated HBM buffer with ``lax.dynamic_update_slice`` under donation,
so shards arriving from different seeders land at their element offsets
without host round-trips.

Import-light on purpose: the split helpers (``split_offsets``,
``stripe_offsets``) are pure integer arithmetic shared with the HOST data
plane — ``transport/tcp.py`` tiles striped sends with ``stripe_offsets``
— so jax is imported lazily, only when a device write actually happens.
A host-only node (a pure seeder, a control-plane process) can import
this module without paying for (or even having) a jax backend.

TPU index-width constraint: XLA's TPU backend rejects dynamic-update-slice
on shapes whose indices exceed 32 bits ("While rewriting computation to not
contain X64 element types..."), and on a buffer longer than 2^31-1 elements
even an in-range int32 start is *silently misplaced* because the clamp
bound ``size - update_size`` overflows S32.  Layers past that size
(llama3-405b: ~3.19B elements) therefore use a **segmented 2-D layout**:
the buffer is ``(rows, seg)`` with ``seg <= 2^30``, a fragment write is
split into row-aligned pieces, and every dynamic index stays far below
2^31.  The final 1-D view is a free reshape when ``seg`` divides the
element count (true for all real transformer layer sizes, which carry
large power-of-two factors).
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import numpy as np

_INT32_MAX = np.iinfo(np.int32).max
_MAX_SEG = 1 << 30  # elements per row of the segmented layout


@functools.lru_cache(maxsize=1)
def _writers():
    """The jitted fragment writers, built on first device write (lazy so
    importing this module never initializes a jax backend).

    Donation lets XLA write fragments into the existing HBM buffer
    instead of allocating a copy per fragment — essential at multi-GiB
    layer sizes.  The segmented variant takes (row, col) int32 indices on
    a 2-D buffer; the update is a (1, n) row slice, so both clamp bounds
    (rows-1, seg-n) fit int32."""
    import jax
    from jax import lax

    write_1d = jax.jit(
        lambda buf, frag, off: lax.dynamic_update_slice(buf, frag, (off,)),
        donate_argnums=(0,),
    )
    write_2d = jax.jit(
        lambda buf, frag, row, col: lax.dynamic_update_slice(
            buf, frag[None, :], (row, col)
        ),
        donate_argnums=(0,),
    )
    return write_1d, write_2d


def _pick_seg(n_elements: int) -> int:
    """Largest power-of-two divisor of ``n_elements``.  Real layer element
    counts are multiples of the model dims' big 2-power factors, so this is
    >= 2^20 in practice."""
    return n_elements & -n_elements  # lowest set bit = largest 2^k divisor


class LayerBuffer:
    """A preallocated HBM reassembly target of any size.

    Small layers (< 2^31 elements) are a flat 1-D array; larger ones use
    the segmented ``(rows, seg)`` layout.  ``write`` places a fragment at
    its absolute element offset; ``array()`` returns the contiguous 1-D
    layer (a free reshape — no copy, no re-layout)."""

    def __init__(self, n_elements: int, dtype=None, sharding=None,
                 max_flat: int = _INT32_MAX, seg_cap: int = _MAX_SEG):
        """``max_flat``/``seg_cap`` exist so tests can force the segmented
        layout at small sizes; production callers use the defaults."""
        import jax.numpy as jnp

        self.n_elements = n_elements
        self.dtype = jnp.bfloat16 if dtype is None else dtype
        if n_elements <= max_flat:
            self.seg = 0  # flat mode
            shape: Tuple[int, ...] = (n_elements,)
        else:
            self.seg = min(_pick_seg(n_elements), seg_cap)
            rows = n_elements // self.seg
            if rows * self.seg != n_elements:
                # Reachable only via a non-power-of-two seg_cap: a short
                # buffer would let dynamic_update_slice clamp the row index
                # and silently overwrite the previous row.
                raise ValueError(
                    f"seg {self.seg} does not divide {n_elements} elements; "
                    f"seg_cap must be a power of two"
                )
            if rows > _INT32_MAX:
                raise ValueError(
                    f"layer of {n_elements} elements factors into "
                    f"{rows} rows x {self.seg} (> 2^31-1 rows): row indices "
                    f"would overflow int32; pad the layer to a count with a "
                    f"larger power-of-two factor"
                )
            shape = (rows, self.seg)
        if sharding is not None:
            self.buf = jnp.zeros(shape, dtype=self.dtype, device=sharding)
        else:
            self.buf = jnp.zeros(shape, dtype=self.dtype)

    def write(self, offset: int, frag) -> None:
        """Write ``frag`` at absolute element ``offset`` (donating the
        previous buffer).  Fragments may span row boundaries; each
        row-aligned piece is one 32-bit-indexed update."""
        import jax.numpy as jnp
        from jax import lax

        if offset < 0 or offset + frag.size > self.n_elements:
            raise ValueError(
                f"fragment [{offset}, {offset + frag.size}) outside layer "
                f"of {self.n_elements} elements"
            )
        write_1d, write_2d = _writers()
        if self.seg == 0:
            self.buf = write_1d(self.buf, frag, jnp.asarray(offset, jnp.int32))
            return
        pos = 0
        while pos < frag.size:
            row, col = divmod(offset + pos, self.seg)
            n = min(frag.size - pos, self.seg - col)
            self.buf = write_2d(
                self.buf,
                lax.dynamic_slice(frag, (pos,), (n,)) if (pos or n != frag.size) else frag,
                jnp.asarray(row, jnp.int32),
                jnp.asarray(col, jnp.int32),
            )
            pos += n

    def array(self):
        """The assembled contiguous layer (free reshape in segmented mode)."""
        return self.buf if self.seg == 0 else self.buf.reshape(self.n_elements)


def alloc_layer_buffer(n_elements: int, dtype=None, sharding=None) -> LayerBuffer:
    """Preallocate the reassembly target in HBM."""
    return LayerBuffer(n_elements, dtype, sharding)


def write_fragment(buf, frag, offset: int):
    """Write one fragment into ``buf``, donating the previous storage.

    ``buf`` may be a ``LayerBuffer`` (any size — the ``alloc_layer_buffer``
    return type) or a flat jax.Array of < 2^31 elements; a flat giant
    buffer cannot be dynamically indexed on TPU at all (module docstring).
    Returns the updated buffer, same type as given."""
    import jax.numpy as jnp

    if isinstance(buf, LayerBuffer):
        buf.write(offset, frag)
        return buf
    if buf.size > _INT32_MAX:
        raise ValueError(
            f"buffer of {buf.size} elements exceeds the TPU 32-bit dynamic "
            f"index range; use LayerBuffer for segmented reassembly"
        )
    if offset < 0 or offset + frag.size > buf.size:
        # dynamic_update_slice would silently clamp the start and misplace
        # the fragment — the exact failure mode LayerBuffer.write rejects.
        raise ValueError(
            f"fragment [{offset}, {offset + frag.size}) outside buffer "
            f"of {buf.size} elements"
        )
    write_1d, _ = _writers()
    return write_1d(buf, frag, jnp.asarray(offset, jnp.int32))


def assemble_fragments(
    n_elements: int,
    fragments: Sequence[Tuple[int, object]],
    dtype=None,
    sharding=None,
):
    """Build a full layer in HBM from (element_offset, fragment) pairs —
    the device-side equivalent of the receiver's byte-range reassembly."""
    buf = LayerBuffer(n_elements, dtype, sharding)
    for offset, frag in fragments:
        buf.write(offset, frag)
    return buf.array()


def split_offsets(total: int, parts: int) -> Sequence[Tuple[int, int]]:
    """Contiguous (offset, size) tiling of ``total`` elements into
    ``parts`` chunks — the shape of a flow schedule's per-sender jobs
    (flow.go:193-211)."""
    base, rem = divmod(total, parts)
    spans = []
    off = 0
    for i in range(parts):
        size = base + (1 if i < rem else 0)
        spans.append((off, size))
        off += size
    return spans


def stripe_offsets(total: int, parts: int,
                   min_size: int = 1) -> List[Tuple[int, int]]:
    """``split_offsets`` with a floor: the even tiling of ``total`` into
    at most ``parts`` spans, each at least ``min_size`` (the whole thing
    as one span when ``total < 2 * min_size``).  The stripe split of the
    TCP data plane — a payload too small to give every stripe a
    meaningful run of bytes just uses fewer stripes, so striping can
    never fragment a transfer into slow-start-dominated slivers."""
    if total <= 0:
        return []
    if min_size > 0:
        parts = min(parts, total // min_size)
    parts = max(1, parts)
    return [s for s in split_offsets(total, parts) if s[1] > 0]
