"""On-device partial-layer reassembly.

The device-plane fix for the reference's biggest shortcut: its mode-3
receiver never reassembles partial layers (the copy is commented out,
``/root/reference/distributor/node.go:1545-1547``).  Host-side reassembly
lives in ``runtime/receiver.py``; here fragments are written into a
preallocated HBM buffer with ``lax.dynamic_update_slice`` under donation,
so shards arriving from different seeders land at their byte offsets
without host round-trips.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


# Donation lets XLA write fragments into the existing HBM buffer instead of
# allocating a copy per fragment — essential at multi-GiB layer sizes.
_write_fragment_donated = jax.jit(
    lambda buf, frag, offset: lax.dynamic_update_slice(buf, frag, (offset,)),
    donate_argnums=(0,),
)


def alloc_layer_buffer(n_elements: int, dtype=jnp.bfloat16, sharding=None) -> jax.Array:
    """Preallocate the reassembly target in HBM."""
    if sharding is not None:
        return jnp.zeros((n_elements,), dtype=dtype, device=sharding)
    return jnp.zeros((n_elements,), dtype=dtype)


def write_fragment(buf: jax.Array, frag: jax.Array, offset: int) -> jax.Array:
    """Write one fragment at its element offset, donating the buffer."""
    return _write_fragment_donated(buf, frag, jnp.asarray(offset, jnp.int32))


def assemble_fragments(
    n_elements: int,
    fragments: Sequence[Tuple[int, jax.Array]],
    dtype=jnp.bfloat16,
    sharding=None,
) -> jax.Array:
    """Build a full layer in HBM from (element_offset, fragment) pairs —
    the device-side equivalent of the receiver's byte-range reassembly."""
    buf = alloc_layer_buffer(n_elements, dtype, sharding)
    for offset, frag in fragments:
        buf = write_fragment(buf, frag, offset)
    return buf


def split_offsets(total: int, parts: int) -> Sequence[Tuple[int, int]]:
    """Contiguous (offset, size) tiling of ``total`` elements into
    ``parts`` chunks — the shape of a flow schedule's per-sender jobs
    (flow.go:193-211)."""
    base, rem = divmod(total, parts)
    spans = []
    off = 0
    for i in range(parts):
        size = base + (1 if i < rem else 0)
        spans.append((off, size))
        off += size
    return spans
