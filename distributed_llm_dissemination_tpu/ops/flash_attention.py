"""Blockwise causal GQA attention — the pallas hot-op behind ring attention.

``block_attention`` computes one (Q block x KV block) partial attention
with LOCAL online-softmax statistics: it returns ``(pv, m, l)`` where
``m``/``l`` are the block's own running max / normalizer and ``pv`` the
unnormalized value sum.  The ring loop (``parallel/ring_attention.py``)
merges successive blocks' partials with the standard rescale
``exp(m - m_new)`` — so K/V rotation over ICI composes with on-chip
blockwise attention, the two halves of the ring-attention recipe.

Two interchangeable implementations:

- ``_block_attention_ref``: pure lax (einsum + where).  Runs anywhere,
  differentiates, and is the numerical oracle.  It materializes the
  [sq, t] logits in HBM — fine for short blocks, the memory hot spot for
  long ones.
- ``_block_attention_pallas``: a pallas TPU kernel.  Grid is
  (batch*kv_head*group, q_tiles, kv_tiles) with the kv tile dimension
  innermost, so for each Q tile the output block stays resident in VMEM
  while KV tiles stream through: logits live only as a
  [tile_q, tile_k] VMEM tile, never in HBM.  Entirely-masked KV tiles
  (future positions under the causal mask — half the work in a causal
  ring) are skipped with ``pl.when``.  Tile edges are the largest
  128-multiples up to 512 dividing the block (measured on v5e: 128-edge
  tiles are grid-overhead-bound and LOSE to the lax oracle past ~2k
  blocks, 512-edge tiles beat it ~1.3x; whole-block tiles blow VMEM).
  Batch and Q-tile grid axes are declared parallel for Mosaic; the kv
  axis is arbitrary (it carries the online-softmax accumulation).

The public ``block_attention`` picks pallas when the backend is TPU and
the shapes meet the MXU tiling constraints (hd and block lengths
multiples of 128), else falls back to lax.  It is forward-only:
differentiation happens one level up, in ``ring_attention``'s custom
vjp, which recomputes each block from the saved log-sum-exp while
re-rotating K/V around the ring — flash attention's recompute-the-
logits trade, composed with the ring's communication schedule.

The reference has no compute at all (SURVEY §2.3); this op exists for
the framework's long-context model path (ring attention over the ``sp``
mesh axis), which the reference's Assignment-as-pipeline-placement
implies but never executes.
"""

from __future__ import annotations


import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

_NEG_INF = -1e30  # finite: -inf would make (m - m_new) NaN on empty rows
TILE = 128  # MXU tiling granule: block edges must be multiples of this
MAX_TILE = 512  # largest tile edge (VMEM-safe, empirically fastest on v5e)


def _tile_edge(n: int) -> int:
    """Largest multiple of TILE up to MAX_TILE that divides ``n``."""
    start = min(n, MAX_TILE) // TILE * TILE  # candidates: 128-multiples only
    for cand in range(start, TILE - 1, -TILE):
        if n % cand == 0:
            return cand
    # eligible() gates the public path; a direct caller with a non-128-
    # multiple block must fail loudly, not get a non-MXU-tileable spec.
    raise ValueError(f"block edge {n} is not a multiple of {TILE}")

# Test hook: force the pallas path (interpret mode) off-TPU.
FORCE_PALLAS = False


def eligible(sq: int, t: int, hd: int) -> bool:
    """Shapes the pallas kernel accepts: MXU-tileable blocks."""
    return sq % TILE == 0 and t % TILE == 0 and hd % 128 == 0


def _use_pallas(sq: int, t: int, hd: int) -> bool:
    import os

    if os.environ.get("DLD_DISABLE_PALLAS_ATTN", "").lower() not in (
        "", "0", "false", "no",
    ):
        # Field escape hatch: flip to the lax oracle without a code
        # change (e.g. a Mosaic regression on a new TPU generation).
        return False
    if not eligible(sq, t, hd):
        return False
    return FORCE_PALLAS or jax.default_backend() == "tpu"


# ------------------------------------------------------------- lax oracle


def _block_attention_ref(qg, k, v, q_off, k_off):
    """qg: [b, kvh, g, sq, hd]; k, v: [b, kvh, t, hd]; offsets are the
    global positions of row/col 0 (f32 scalars holding integer values).
    Returns (pv f32, m f32, l f32) with shapes
    ([b, kvh, g, sq, hd], [b, kvh, g, sq], [b, kvh, g, sq])."""
    hd = qg.shape[-1]
    sq, t = qg.shape[3], k.shape[2]
    logits = jnp.einsum(
        "bkgsh,bkth->bkgst", qg, k, preferred_element_type=jnp.float32
    ) / np.sqrt(hd)
    q_ids = q_off.astype(jnp.int32) + jnp.arange(sq)
    k_ids = k_off.astype(jnp.int32) + jnp.arange(t)
    causal = q_ids[:, None] >= k_ids[None, :]
    logits = jnp.where(causal, logits, _NEG_INF)
    m = logits.max(axis=-1)
    p = jnp.exp(logits - m[..., None])
    # A fully-masked row (this whole KV block is in the row's future) has
    # m == _NEG_INF and p == 1 everywhere; zero it so (pv, l) are exact
    # partials and the caller's exp(m - m_new) rescale gets 0 * 0, not
    # garbage * 0.
    p = jnp.where((m > _NEG_INF / 2)[..., None], p, 0.0)
    l = p.sum(axis=-1)
    pv = jnp.einsum(
        "bkgst,bkth->bkgsh", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return pv, m, l


# ----------------------------------------------------------- pallas kernel


def _attn_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref,
                 o_ref, m_ref, l_ref, *, tile_q: int, tile_k: int):
    j = pl.program_id(1)  # q tile
    kk = pl.program_id(2)  # kv tile (innermost: o/m/l stay resident)

    @pl.when(kk == 0)
    def _():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        o_ref[...] = jnp.zeros_like(o_ref)

    q_lo = qoff_ref[0, 0] + j * tile_q
    k_lo = koff_ref[0, 0] + kk * tile_k

    # The tile contributes iff its last query row can see its first key.
    @pl.when(q_lo + tile_q - 1 >= k_lo)
    def _():
        q = q_ref[0, 0, 0]  # [tile_q, hd]
        k = k_ref[0, 0]  # [tile_k, hd]
        v = v_ref[0, 0]
        hd = q.shape[-1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) / np.sqrt(hd)  # [tile_q, tile_k]
        q_ids = q_lo + lax.broadcasted_iota(jnp.int32, (tile_q, tile_k), 0)
        k_ids = k_lo + lax.broadcasted_iota(jnp.int32, (tile_q, tile_k), 1)
        s = jnp.where(q_ids >= k_ids, s, _NEG_INF)

        # Row stats are [tile_q, 1] column vectors: sublane-aligned with
        # the logits' query rows, so every broadcast below is rank-2.
        m_prev = m_ref[0, 0, 0]  # [tile_q, 1]
        l_prev = l_ref[0, 0, 0]
        o_prev = o_ref[0, 0, 0]  # [tile_q, hd]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        # Rows whose visible keys start beyond this tile: see the oracle.
        p = jnp.where(m_new > _NEG_INF / 2, p, 0.0)
        l_ref[0, 0, 0] = l_prev * alpha + p.sum(axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[0, 0, 0] = o_prev * alpha + pv
        m_ref[0, 0, 0] = m_new


def _block_attention_pallas(qg, k, v, q_off, k_off, interpret):
    b, kvh, g, sq, hd = qg.shape
    t = k.shape[2]
    bh = b * kvh * g
    tile_q, tile_k = _tile_edge(sq), _tile_edge(t)
    grid = (bh, sq // tile_q, t // tile_k)

    def q_idx(i, j, kk):
        return (i // (kvh * g), (i // g) % kvh, i % g, j, 0)

    def kv_idx(i, j, kk):
        return (i // (kvh * g), (i // g) % kvh, kk, 0)

    stat_idx = q_idx  # same coordinates; stats blocks just have width 1

    # Scalar offsets ride SMEM on TPU; interpret mode accepts the same
    # spec (memory spaces are advisory there).
    from jax.experimental.pallas import tpu as pltpu  # noqa: PLC0415

    smem = pl.BlockSpec((1, 1), lambda i, j, kk: (0, 0),
                        memory_space=pltpu.SMEM)

    # Stats carry a trailing singleton dim so kernel-side row vectors
    # are [TILE, 1] (sublane-aligned); squeezed off on return.
    # Inside shard_map the outputs vary over every mesh axis the inputs
    # do (vma): required by pallas_call when the mesh checks vma.
    typeof = getattr(jax, "typeof", None)
    vma = frozenset()
    if typeof is not None:
        for x in (qg, k, v):
            vma |= getattr(typeof(x), "vma", frozenset()) or frozenset()

    def _struct(shape):
        try:
            return jax.ShapeDtypeStruct(shape, jnp.float32, vma=vma)
        except TypeError:  # older jax: no vma kwarg
            return jax.ShapeDtypeStruct(shape, jnp.float32)

    out_shape = [
        _struct((b, kvh, g, sq, hd)),
        _struct((b, kvh, g, sq, 1)),
        _struct((b, kvh, g, sq, 1)),
    ]
    # Batch and q-tile axes are embarrassingly parallel; the kv axis is
    # "arbitrary" — it must run in order (online-softmax accumulation
    # into o/m/l).  Interpret mode (CPU tests) ignores compiler params.
    kwargs = {}
    if not interpret:
        params_cls = getattr(pltpu, "CompilerParams",
                             getattr(pltpu, "TPUCompilerParams", None))
        if params_cls is not None:
            kwargs["compiler_params"] = params_cls(
                dimension_semantics=("parallel", "parallel", "arbitrary"))
    pv, m, l = pl.pallas_call(
        functools.partial(_attn_kernel, tile_q=tile_q, tile_k=tile_k),
        grid=grid,
        in_specs=[
            smem,
            smem,
            pl.BlockSpec((1, 1, 1, tile_q, hd), q_idx),
            pl.BlockSpec((1, 1, tile_k, hd), kv_idx),
            pl.BlockSpec((1, 1, tile_k, hd), kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, 1, tile_q, hd), q_idx),
            pl.BlockSpec((1, 1, 1, tile_q, 1), stat_idx),
            pl.BlockSpec((1, 1, 1, tile_q, 1), stat_idx),
        ],
        out_shape=out_shape,
        interpret=interpret,
        **kwargs,
    )(
        q_off.astype(jnp.int32).reshape(1, 1),
        k_off.astype(jnp.int32).reshape(1, 1),
        qg, k, v,
    )
    return pv, m.squeeze(-1), l.squeeze(-1)


# ------------------------------------------------------------- public op


def block_attention(qg, k, v, q_off, k_off):
    """One KV block's partial attention (see module docstring).

    qg: [b, kvh, g, sq, hd]; k, v: [b, kvh, t, hd]; ``q_off``/``k_off``
    are f32 scalars holding the blocks' global start positions (f32 for
    a uniform traced-scalar convention; exact for any realistic
    sequence length).  Returns f32 (pv, m, l).

    This op is forward-only: its consumer, ``ring_attention``, defines
    its own custom vjp (the backward ring in
    ``parallel/ring_attention.py``), which never differentiates through
    this call."""
    sq, hd = qg.shape[3], qg.shape[4]
    t = k.shape[2]
    if _use_pallas(sq, t, hd):
        return _block_attention_pallas(
            qg, k, v, q_off, k_off,
            interpret=jax.default_backend() != "tpu",
        )
    return _block_attention_ref(qg, k, v, q_off, k_off)


def merge_partials(carry, part):
    """Online-softmax merge of a block's (pv, m, l) into the running
    (o, m, l) accumulator — all f32."""
    o, m, l = carry
    pv, m_blk, l_blk = part
    m_new = jnp.maximum(m, m_blk)
    alpha = jnp.exp(m - m_new)
    beta = jnp.exp(m_blk - m_new)
    l_new = l * alpha + l_blk * beta
    o_new = o * alpha[..., None] + pv * beta[..., None]
    return o_new, m_new, l_new
