from .reassembly import (  # noqa: F401
    alloc_layer_buffer,
    assemble_fragments,
    split_offsets,
    stripe_offsets,
    write_fragment,
)
