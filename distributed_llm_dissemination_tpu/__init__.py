"""TPU-native model-weight dissemination framework.

A ground-up re-design of ``ynishimi/distributed-llm-dissemination`` for TPU
pods: given a declarative ``Assignment`` of model layers to nodes, it
disseminates LLM weight layers under pluggable schedules — naive leader
broadcast (mode 0), peer retransmission (mode 1), pull/work-stealing
(mode 2), and a max-flow-optimal plan (mode 3) — then signals readiness and
reports time-to-deliver.  The host control plane mirrors the reference's
announce/ack/retransmit/startup protocol; the data plane is JAX/XLA
collectives over ICI/DCN landing weights directly in TPU HBM, with the
Assignment mapping to pipeline-parallel device groups on a
``jax.sharding.Mesh``.
"""

__version__ = "0.1.0"
