"""Byte-range interval accounting.

The reference's mode-3 receiver counts received *sizes* and acks when the
sum reaches the layer total (``/root/reference/distributor/node.go:
1542-1566``) — duplicated or overlapping fragments would ack a layer full
of holes.  Tracking the union of covered ``[start, end)`` intervals makes
reassembly idempotent, which is what allows the failure detector to
re-plan in-flight layers (duplicates are harmless) and resumable
transfers to report precise missing ranges.
"""

from __future__ import annotations

from typing import List, Tuple

Interval = Tuple[int, int]  # [start, end)


def insert(intervals: List[Interval], start: int, end: int) -> List[Interval]:
    """Union ``[start, end)`` into a sorted list of disjoint intervals."""
    if start >= end:
        return intervals
    out: List[Interval] = []
    i, n = 0, len(intervals)
    while i < n and intervals[i][1] < start:
        out.append(intervals[i])
        i += 1
    while i < n and intervals[i][0] <= end:
        start = min(start, intervals[i][0])
        end = max(end, intervals[i][1])
        i += 1
    out.append((start, end))
    out.extend(intervals[i:])
    return out


def covered(intervals: List[Interval]) -> int:
    """Total bytes covered by a disjoint interval list."""
    return sum(e - s for s, e in intervals)


def uncovered(
    intervals: List[Interval], start: int, end: int
) -> List[Interval]:
    """Subranges of ``[start, end)`` NOT covered by the (sorted, disjoint)
    interval list — what a duplicate-tolerant writer still has to land."""
    out: List[Interval] = []
    pos = start
    for s, e in intervals:
        if e <= pos:
            continue
        if s >= end:
            break
        if s > pos:
            out.append((pos, min(s, end)))
        pos = max(pos, min(e, end))
        if pos >= end:
            break
    if pos < end:
        out.append((pos, end))
    return out


def remove(intervals: List[Interval], start: int, end: int) -> List[Interval]:
    """Subtract ``[start, end)`` from a sorted disjoint interval list —
    the rollback of a failed write claim."""
    if start >= end:
        return intervals
    out: List[Interval] = []
    for s, e in intervals:
        if e <= start or s >= end:
            out.append((s, e))
            continue
        if s < start:
            out.append((s, start))
        if e > end:
            out.append((end, e))
    return out


def complement(intervals: List[Interval], total: int) -> List[Interval]:
    """The gaps: ranges of ``[0, total)`` NOT covered — the byte ranges a
    resumed transfer still needs."""
    gaps: List[Interval] = []
    pos = 0
    for s, e in intervals:
        if s > pos:
            gaps.append((pos, s))
        pos = max(pos, e)
    if pos < total:
        gaps.append((pos, total))
    return gaps
