"""Byte-range interval accounting.

The reference's mode-3 receiver counts received *sizes* and acks when the
sum reaches the layer total (``/root/reference/distributor/node.go:
1542-1566``) — duplicated or overlapping fragments would ack a layer full
of holes.  Tracking the union of covered ``[start, end)`` intervals makes
reassembly idempotent, which is what allows the failure detector to
re-plan in-flight layers (duplicates are harmless) and resumable
transfers to report precise missing ranges.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

Interval = Tuple[int, int]  # [start, end)


def insert(intervals: List[Interval], start: int, end: int) -> List[Interval]:
    """Union ``[start, end)`` into a sorted list of disjoint intervals."""
    if start >= end:
        return intervals
    out: List[Interval] = []
    i, n = 0, len(intervals)
    while i < n and intervals[i][1] < start:
        out.append(intervals[i])
        i += 1
    while i < n and intervals[i][0] <= end:
        start = min(start, intervals[i][0])
        end = max(end, intervals[i][1])
        i += 1
    out.append((start, end))
    out.extend(intervals[i:])
    return out


def covered(intervals: List[Interval]) -> int:
    """Total bytes covered by a disjoint interval list."""
    return sum(e - s for s, e in intervals)


def uncovered(
    intervals: List[Interval], start: int, end: int
) -> List[Interval]:
    """Subranges of ``[start, end)`` NOT covered by the (sorted, disjoint)
    interval list — what a duplicate-tolerant writer still has to land."""
    out: List[Interval] = []
    pos = start
    for s, e in intervals:
        if e <= pos:
            continue
        if s >= end:
            break
        if s > pos:
            out.append((pos, min(s, end)))
        pos = max(pos, min(e, end))
        if pos >= end:
            break
    if pos < end:
        out.append((pos, end))
    return out


def remove(intervals: List[Interval], start: int, end: int) -> List[Interval]:
    """Subtract ``[start, end)`` from a sorted disjoint interval list —
    the rollback of a failed write claim."""
    if start >= end:
        return intervals
    out: List[Interval] = []
    for s, e in intervals:
        if e <= start or s >= end:
            out.append((s, e))
            continue
        if s < start:
            out.append((s, start))
        if e > end:
            out.append((end, e))
    return out


def intersect(a: List[Interval], b: List[Interval]) -> List[Interval]:
    """Ranges covered by BOTH sorted disjoint interval lists — what a
    resume may trust when the journal's coverage and the disk bytes'
    verified ranges disagree (checkpoint CRC hardening)."""
    out: List[Interval] = []
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if lo < hi:
            out.append((lo, hi))
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return out


def complement(intervals: List[Interval], total: int) -> List[Interval]:
    """The gaps: ranges of ``[0, total)`` NOT covered — the byte ranges a
    resumed transfer still needs."""
    gaps: List[Interval] = []
    pos = 0
    for s, e in intervals:
        if s > pos:
            gaps.append((pos, s))
        pos = max(pos, e)
    if pos < total:
        gaps.append((pos, total))
    return gaps


class ClaimedCoverage:
    """Claim/commit coverage accounting for out-of-lock byte movement.

    THE shared discipline of the incremental device ingest
    (``parallel/ingest.ShardedLayerIngest``) and the mode-3 receiver's
    fragment assembly (``runtime/receiver``): a writer CLAIMS its
    still-uncovered subranges (reserving them so concurrent duplicates
    never copy twice), moves the bytes outside the caller's lock, then
    COMMITS — or ABORTS, rolling the reservation back so failed copies
    are never reported as landed bytes.  ``committed()`` is the honest
    view (covered minus in-flight claims); ``complete()`` is the
    promotion/finalize gate (full coverage, nothing in flight).

    NOT itself thread-safe: callers mutate it under their own lock — the
    point is precisely that the byte movement happens OUTSIDE that lock,
    bracketed by claim/commit.

    Tokens are PROCESS-unique (one shared counter), not per-instance:
    claim tokens travel outside their coverage object (a transport
    sink's placed fragments carry them through the delivery queue), and
    a receiver replaced on a live transport (declared-dead revival) can
    drain a predecessor's queued tokens — per-instance counters would
    let such a foreign token collide with a live claim and commit bytes
    that never landed.  A foreign token now pops nothing, ever.
    """

    __slots__ = ("_covered", "_inflight")

    _TOKENS = itertools.count()  # process-unique: see docstring

    def __init__(self, covered: Optional[List[Interval]] = None):
        self._covered: List[Interval] = list(covered or [])
        self._inflight: Dict[int, List[Interval]] = {}

    def claim(self, start: int, end: int):
        """Reserve the uncovered subranges of ``[start, end)``.  Returns
        ``(token, ranges)``; ``(None, [])`` when fully covered already (a
        duplicate — nothing to move)."""
        ranges = uncovered(self._covered, start, end)
        if not ranges:
            return None, []
        for lo, hi in ranges:
            self._covered = insert(self._covered, lo, hi)
        tok = next(ClaimedCoverage._TOKENS)
        self._inflight[tok] = ranges
        return tok, ranges

    def commit(self, tok: Optional[int]) -> None:
        if tok is not None:
            self._inflight.pop(tok, None)

    def abort(self, tok: Optional[int]) -> None:
        """Roll a failed claim's reservation back out of the coverage."""
        if tok is None:
            return
        for lo, hi in self._inflight.pop(tok, ()):
            self._covered = remove(self._covered, lo, hi)

    def covered_bytes(self) -> int:
        return covered(self._covered)

    def idle(self) -> bool:
        return not self._inflight

    def complete(self, total: int) -> bool:
        return not self._inflight and covered(self._covered) >= total

    def complete_range(self, start: int, end: int) -> bool:
        """Promotion gate for a SHARDED target (docs/sharding.md): the
        range ``[start, end)`` is fully covered and nothing is in
        flight — coverage outside the range is irrelevant."""
        return not self._inflight and not uncovered(self._covered,
                                                    start, end)

    def committed(self) -> List[Interval]:
        """Covered ranges whose bytes REALLY landed (in-flight claims
        excluded) — what salvage/announce/seed may read."""
        out = list(self._covered)
        for ranges in self._inflight.values():
            for lo, hi in ranges:
                out = remove(out, lo, hi)
        return out
