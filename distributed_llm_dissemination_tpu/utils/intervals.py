"""Byte-range interval accounting.

The reference's mode-3 receiver counts received *sizes* and acks when the
sum reaches the layer total (``/root/reference/distributor/node.go:
1542-1566``) — duplicated or overlapping fragments would ack a layer full
of holes.  Tracking the union of covered ``[start, end)`` intervals makes
reassembly idempotent, which is what allows the failure detector to
re-plan in-flight layers (duplicates are harmless) and resumable
transfers to report precise missing ranges.
"""

from __future__ import annotations

from typing import List, Tuple

Interval = Tuple[int, int]  # [start, end)


def insert(intervals: List[Interval], start: int, end: int) -> List[Interval]:
    """Union ``[start, end)`` into a sorted list of disjoint intervals."""
    if start >= end:
        return intervals
    out: List[Interval] = []
    i, n = 0, len(intervals)
    while i < n and intervals[i][1] < start:
        out.append(intervals[i])
        i += 1
    while i < n and intervals[i][0] <= end:
        start = min(start, intervals[i][0])
        end = max(end, intervals[i][1])
        i += 1
    out.append((start, end))
    out.extend(intervals[i:])
    return out


def covered(intervals: List[Interval]) -> int:
    """Total bytes covered by a disjoint interval list."""
    return sum(e - s for s, e in intervals)


def complement(intervals: List[Interval], total: int) -> List[Interval]:
    """The gaps: ranges of ``[0, total)`` NOT covered — the byte ranges a
    resumed transfer still needs."""
    gaps: List[Interval] = []
    pos = 0
    for s, e in intervals:
        if s > pos:
            gaps.append((pos, s))
        pos = max(pos, e)
    if pos < total:
        gaps.append((pos, total))
    return gaps
