"""Process-environment helpers for accelerator-independent subprocesses."""

from __future__ import annotations

import os


def cpu_pinned_env(base: dict = None) -> dict:
    """Env for a process that imports jax but must never depend on
    accelerator availability: pin the CPU backend AND drop the
    accelerator-relay pool var — with it set, jax init blocks on the
    relay even under JAX_PLATFORMS=cpu when the tunnel is unhealthy."""
    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env
