"""Process-environment helpers for accelerator-independent subprocesses."""

from __future__ import annotations

import os


def cpu_pinned_env(base: dict = None) -> dict:
    """Env for a process that imports jax but must never depend on
    accelerator availability: pin the CPU backend AND drop the
    accelerator-relay pool var — with it set, jax init blocks on the
    relay even under JAX_PLATFORMS=cpu when the tunnel is unhealthy."""
    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def boot_donate_mode() -> str:
    """The donated-staging knob (``DLD_BOOT_DONATE``): ``"off"`` (0),
    ``"force"`` (1), or ``"auto"`` (unset/anything else).  Auto donates
    only where it is both profitable and safe: non-CPU device blobs with
    a retained host fallback — the CPU backend zero-copy-ADOPTS host
    buffers as device arrays (``utils.hostmem``), and donating an adopted
    array would let XLA scribble over the very memory ``inmem_data``
    still serves retransmits from.  The consumers of this knob
    (``runtime/boot.py``, ``parallel/ingest.py``) each apply their own
    platform/aliasing checks on top of the mode."""
    v = os.environ.get("DLD_BOOT_DONATE", "")
    if v == "0":
        return "off"
    if v == "1":
        return "force"
    return "auto"


def stream_boot_enabled() -> bool:
    """Per-layer receive-to-device streaming boot staging
    (``runtime/stream_boot.py``), default ON; ``DLD_STREAM_BOOT=0``
    disables it (the boot then assembles everything after startup, the
    pre-streaming behavior)."""
    return os.environ.get("DLD_STREAM_BOOT", "1") != "0"
