from .logging import JsonLogger, configure, log  # noqa: F401
from .rate import DEFAULT_BURST, PacedWriter, TokenBucket  # noqa: F401
