"""Bounded data-plane worker pools + the process thread census.

The data plane used to spawn a bare ``threading.Thread`` per accepted
connection, per stripe, and per simulated client fetch — so connection
count implied thread count, and a 1000-node fan-out meant a thousand
stacks per seeder.  This module is the ONE place data-plane concurrency
comes from now:

- :class:`WorkerPool` — a fixed-ceiling pool of named daemon workers
  (``<name>-<k>``) fed by an unbounded task queue.  Workers spawn
  lazily up to the ceiling and then persist; excess tasks queue, so K
  concurrent transfers use ``min(K, size)`` threads, never K.
- :func:`rx_pool` / :func:`tx_pool` — the process-wide pools serving
  layer-body receives (``transport/tcp.py``'s readiness loop hands
  ready connections here) and concurrent stripe sends.  They are
  SEPARATE pools on purpose: an in-process loopback test can otherwise
  fill every slot with sends blocked on a receiver that needs a slot
  to drain them — a classic one-pool deadlock.
- :func:`census` — live thread counts bucketed by plane (data /
  control / other) from thread NAMES, surfaced as ``threads_*`` gauges
  in metric reports and the run report (docs/observability.md).  The
  static drift check (tests/test_threads.py) pins every remaining bare
  ``threading.Thread(`` site, so new spawns must either route through
  a pool here or be explicitly allowlisted with a stable name.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Callable, Dict, Optional

# One pool's worker ceiling.  Small on purpose: these threads do
# syscall-bound socket work, and the receive path's control traffic is
# handled inline by the readiness loop (transport/tcp.py) — only layer
# BODIES occupy a slot.  Env-tunable per deployment.
DEFAULT_POOL_SIZE = max(2, int(os.environ.get("DLD_DATA_THREADS", "8")))

# Thread-name prefixes per plane, the census's classification table.
# Data plane: pool workers + the transport readiness loop.  Control
# plane: every named long-lived protocol/bookkeeping thread.  Anything
# unnamed (or Python's own threads) counts as "other" — the census is
# a gauge, not an allowlist; the drift check is the allowlist.
DATA_PREFIXES = ("data-rx", "data-tx", "tcp-evloop")
CONTROL_PREFIXES = (
    "msgloop", "ctl-worker", "detector", "heartbeat-", "metrics-",
    "leader-lease", "lease-", "replicate-", "plan-watchdog",
    "plan-window", "layer-digests", "swap-", "boot-", "gap-nack",
    "subleader-", "fault-pump", "fabric-", "spmd-", "serve",
    "genreq-", "telemetry-watch", "lp-warm", "tcp-stripe-sweep",
)


class _Task:
    """A submitted unit of work; ``wait()`` blocks until it ran (the
    exception, if any, re-raises in the waiter — stripe sends need the
    first error back on the dispatching thread)."""

    __slots__ = ("fn", "args", "_done", "error")

    def __init__(self, fn: Callable, args: tuple):
        self.fn = fn
        self.args = args
        self._done = threading.Event()
        self.error: Optional[BaseException] = None

    def run(self) -> None:
        try:
            self.fn(*self.args)
        except BaseException as e:  # noqa: BLE001 — surfaced to wait()
            self.error = e
        finally:
            self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)


class WorkerPool:
    """Fixed-ceiling named worker pool.  Threads spawn lazily (a pool
    that never sees work costs nothing) up to ``size`` and then
    persist; the task queue is unbounded, so ``submit`` never blocks
    the caller — excess concurrency serializes instead of spawning."""

    def __init__(self, size: int, name: str):
        self.size = max(1, int(size))
        self.name = name
        self._q: "queue.Queue[_Task]" = queue.Queue()
        self._lock = threading.Lock()
        self._spawned = 0
        self._idle = 0
        self._pending = 0  # submitted, not yet dequeued by a worker

    def submit(self, fn: Callable, *args) -> _Task:
        task = _Task(fn, args)
        with self._lock:
            self._pending += 1
            # Spawn while queued work exceeds genuinely idle workers
            # and the ceiling has room.  An "idle" worker that is
            # already committed to an earlier task makes this
            # over-spawn by at most one — bounded by the ceiling and
            # strictly better than a racing submit serializing behind
            # a long transfer with ceiling headroom unused.
            spawn = (self._pending > self._idle
                     and self._spawned < self.size)
            if spawn:
                self._spawned += 1
                worker_id = self._spawned - 1
        self._q.put(task)
        if spawn:
            threading.Thread(
                target=self._work, daemon=True,
                name=f"{self.name}-{worker_id}",
            ).start()
        return task

    def run_all(self, calls) -> None:
        """Run ``(fn, *args)`` tuples concurrently: all but the first
        go to the pool, the first runs on the CALLING thread, and while
        waiting the caller HELPS — it steals queued tasks and runs them
        inline.  The help loop is what makes nested pool use safe: a
        pool worker whose own task fans into ``run_all`` (a striped
        send inside a pooled fan-out send) never parks a worker slot
        waiting on work that needs a free worker — every waiter IS a
        worker, so the pool can saturate but never deadlock.
        Re-raises the first failure after every call finished."""
        calls = list(calls)
        if not calls:
            return
        tasks = [self.submit(fn, *args) for fn, *args in calls[1:]]
        first = _Task(calls[0][0], tuple(calls[0][1:]))
        first.run()
        for t in tasks:
            while not t.wait(0):
                try:
                    stolen = self._q.get_nowait()
                except queue.Empty:
                    t.wait(0.02)
                    continue
                with self._lock:
                    self._pending -= 1
                stolen.run()
        for t in [first] + tasks:
            if t.error is not None:
                raise t.error

    def _work(self) -> None:
        while True:
            with self._lock:
                self._idle += 1
            try:
                task = self._q.get()
            finally:
                with self._lock:
                    self._idle -= 1
                    self._pending -= 1
            task.run()


_rx: Optional[WorkerPool] = None
_tx: Optional[WorkerPool] = None
_pools_lock = threading.Lock()


def rx_pool() -> WorkerPool:
    """The process-wide receive pool: transport readiness loops hand
    layer-body reads here."""
    global _rx
    with _pools_lock:
        if _rx is None:
            _rx = WorkerPool(DEFAULT_POOL_SIZE, "data-rx")
        return _rx


def tx_pool() -> WorkerPool:
    """The process-wide send pool: concurrent stripe sends (and other
    per-transfer send work) run here."""
    global _tx
    with _pools_lock:
        if _tx is None:
            _tx = WorkerPool(DEFAULT_POOL_SIZE, "data-tx")
        return _tx


def data_thread_ceiling() -> int:
    """The hard ceiling on data-plane threads this process can reach:
    both pools' worker budgets plus one readiness-loop thread.  The
    dual-backend ceiling test asserts live data threads never exceed
    this, whatever the connection count."""
    return 2 * DEFAULT_POOL_SIZE + 1


def census() -> Dict[str, int]:
    """Live thread counts by plane, classified by thread name."""
    out = {"data": 0, "control": 0, "other": 0}
    for t in threading.enumerate():
        name = t.name or ""
        if name.startswith(DATA_PREFIXES):
            out["data"] += 1
        elif name.startswith(CONTROL_PREFIXES):
            out["control"] += 1
        else:
            out["other"] += 1
    return out


def publish_census() -> Dict[str, int]:
    """File the census as ``threads_<plane>`` telemetry gauges (the
    metric reporters call this just before snapshotting, so the run
    report's threads-by-plane table is per node)."""
    from . import telemetry

    counts = census()
    for plane, n in counts.items():
        telemetry.gauge(f"threads_{plane}", n)
    return counts
