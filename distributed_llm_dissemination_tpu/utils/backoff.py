"""Bounded exponential backoff with deterministic jitter.

Transport send paths retry transient failures (a dialing peer that
hasn't bound its listener yet, a pooled connection whose peer restarted,
a briefly-partitioned leader) before surfacing ``OSError`` to the
protocol layer.  The retry cadence matters twice over:

- **Exponential + capped**: a dead peer must cost a bounded, cheap probe
  sequence — not a tight dial loop that burns CPU exactly when the
  cluster is already degraded.
- **Jittered**: every worker loses the leader at the SAME instant during
  a failover, so un-jittered retries stampede the successor in lockstep.
  The jitter here is *deterministic* — derived from (seed, attempt) by a
  Weyl-style integer hash, no ``random`` — so a failing chaos run
  replays its exact retry timeline from the seed (the same property
  ``transport/faults.py`` guarantees for the fault schedule itself).
"""

from __future__ import annotations

import time
from typing import Iterator

# Knuth's multiplicative hash constant (2^32 / phi), for the jitter mix.
_MIX = 2654435761


def jitter_frac(seed: int, attempt: int) -> float:
    """Deterministic jitter fraction in [0, 1) for one (seed, attempt)."""
    h = (seed * _MIX + attempt * 40503 + 0x9E3779B9) & 0xFFFFFFFF
    h ^= h >> 16
    h = (h * _MIX) & 0xFFFFFFFF
    return (h >> 8) / float(1 << 24)


class Backoff:
    """A bounded exponential backoff schedule.

    ``delays()`` yields ``retries`` sleep durations: attempt k's base is
    ``base * factor**k`` capped at ``max_delay``, scaled into
    ``[1/2, 1) * base_k`` by the deterministic jitter.  Total worst-case
    wall is therefore bounded by ``sum(min(base * factor**k, max_delay))``
    — callers with their own deadline (the TCP dial window) additionally
    clamp each sleep to the time remaining.
    """

    def __init__(self, base: float = 0.05, factor: float = 2.0,
                 max_delay: float = 2.0, retries: int = 4, seed: int = 0):
        self.base = base
        self.factor = factor
        self.max_delay = max_delay
        self.retries = retries
        self.seed = seed

    def delays(self) -> Iterator[float]:
        for attempt in range(self.retries):
            raw = min(self.base * (self.factor ** attempt), self.max_delay)
            yield raw * (0.5 + 0.5 * jitter_frac(self.seed, attempt))

    def run(self, fn, retry_on=(OSError,), deadline: float = 0.0,
            sleep=time.sleep):
        """Call ``fn`` until it returns, retrying ``retry_on`` failures
        through the delay schedule; the last failure re-raises.  A
        nonzero ``deadline`` (monotonic timestamp) stops retrying — and
        clamps each sleep — once reached."""
        last = None
        for i, delay in enumerate([0.0] + list(self.delays())):
            if delay:
                if deadline and time.monotonic() >= deadline:
                    break
                if deadline:
                    delay = min(delay, max(0.0, deadline - time.monotonic()))
                sleep(delay)
            try:
                return fn()
            except retry_on as e:  # noqa: PERF203 — retry loop
                last = e
        raise last
