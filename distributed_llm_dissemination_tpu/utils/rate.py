"""Token-bucket rate limiter for paced byte streams.

Equivalent of golang.org/x/time/rate as used by the reference's transport
(``/root/reference/distributor/transport.go:407-424``): in-memory layer
sends are chunked (256 KiB bucket) and each chunk waits for tokens so a
transfer never exceeds its source's configured bytes/sec.
"""

from __future__ import annotations

import threading
import time

# Reference uses a 256 KiB burst bucket (distributor/transport.go:409).
DEFAULT_BURST = 256 * 1024

# One bucket quantum must represent at least this much wall time of
# traffic: time.sleep's OS granularity is ~1 ms, so a fixed 256 KiB
# bucket silently caps ANY commanded rate at ~burst/1ms (~256 MB/s) —
# a 10 GB/s ICI-class budget would ship at 1/40th of it.  Scaling the
# burst UP for fast rates keeps the pacing overhead bounded while
# leaving slow rates (where 256 KiB already spans many ms) at exact
# reference-parity burst semantics.
MIN_QUANTUM_S = 0.005


def effective_burst(rate: float, burst: int = DEFAULT_BURST) -> int:
    if rate <= 0:
        return burst
    return max(int(burst), int(rate * MIN_QUANTUM_S))


class TokenBucket:
    """Thread-safe token bucket: ``wait_n(n)`` blocks until n tokens exist.

    ``rate`` is tokens (bytes) per second; ``rate <= 0`` means unlimited.
    """

    def __init__(self, rate: float, burst: int = DEFAULT_BURST):
        self.rate = float(rate)
        # burst must be positive when limited, or wait_n's chunking spins;
        # fast rates scale it so sleep granularity can't cap throughput.
        self.burst = (max(1, effective_burst(rate, burst))
                      if rate > 0 else 0)
        self._tokens = float(self.burst)
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def wait_n(self, n: int) -> None:
        if self.rate <= 0:
            return
        if n > self.burst:
            # Split oversized requests into burst-sized waits.
            remaining = n
            while remaining > 0:
                chunk = min(remaining, self.burst)
                self.wait_n(chunk)
                remaining -= chunk
            return
        while True:
            with self._lock:
                now = time.monotonic()
                self._tokens = min(
                    float(self.burst), self._tokens + (now - self._last) * self.rate
                )
                self._last = now
                if self._tokens >= n:
                    self._tokens -= n
                    return
                deficit = n - self._tokens
            time.sleep(deficit / self.rate)


class PacedWriter:
    """Wrap a write callable so bytes flow at most at ``rate`` B/s, in
    bucket-sized chunks (transport.go:407-424)."""

    def __init__(self, write, rate: float, burst: int = DEFAULT_BURST):
        self._write = write
        self._bucket = TokenBucket(rate, burst)
        self._chunk = self._bucket.burst if rate > 0 else 1 << 20

    def write(self, data: bytes) -> int:
        view = memoryview(data)
        sent = 0
        while sent < len(view):
            chunk = view[sent : sent + self._chunk]
            self._bucket.wait_n(len(chunk))
            self._write(chunk)
            sent += len(chunk)
        return sent
