"""Receive-buffer allocation for the data plane.

``bytearray(n)`` zeroes its memory inside a single C call — for a
multi-hundred-MiB layer that is hundreds of milliseconds spent holding
the GIL, which starves every other thread in the node process (the
sender half of a relay, the control-plane loop) before the first byte is
even received.  ``np.empty`` returns unfaulted pages immediately; the
bytes are written exactly once by ``recv_into``/fragment writes, so the
zero-fill was pure waste.  The array supports the full buffer protocol
(slice assignment, ``memoryview``, ``bytes()``), so downstream LayerSrc
handling is unchanged.
"""

from __future__ import annotations

from . import hostmem


def alloc_recv_buffer(n: int):
    """An n-byte write-once receive buffer (unzeroed, instant).

    Aligned (``hostmem.ALIGN``) so a completed reassembly buffer is
    directly adoptable as a CPU device array — the shared-buffer ingest
    then stages the layer with ZERO additional copies."""
    return hostmem.aligned_empty(n)
