"""Aligned host buffers and zero-copy adoption onto the CPU backend.

On an accelerator, landing bytes in device memory means a real DMA over
the host link.  On the CPU backend there is no link: "device memory" IS
host memory, and ``jax.device_put`` of a numpy array is a pure-overhead
copy (measured ~5x slower than a plain memcpy on the bench host).  XLA
will alias an external host buffer as a device array zero-copy via
DLPack — but only when the buffer is 64-byte aligned, which numpy's
allocator does not guarantee.  So: allocate ingest buffers aligned
(``aligned_empty``), assemble bytes in place, and adopt the buffer as
the device array with no copy at all (``adopt_as_device_array``).

Safety contract for adoption: the jax.Array aliases the numpy buffer,
so the caller must never write to the buffer afterwards.  The DLPack
capsule keeps the buffer alive for the array's lifetime.
"""

from __future__ import annotations

import ctypes

import numpy as np

ALIGN = 64  # XLA's zero-copy import requires 64-byte alignment

# Below this, numpy's sliced assignment is fine; above it, the memmove
# path's ~5x higher bandwidth (measured 7.3 vs 1.4 GB/s for 256 MiB on
# the bench host — numpy's buffer-protocol assignment path is NOT a
# plain memcpy) dominates the call overhead.
_MEMMOVE_MIN = 64 * 1024


def copy_into(dst, dst_off: int, src) -> None:
    """``dst[dst_off : dst_off+len(src)] = src`` at memmove speed.

    ``dst`` is a writable byte buffer (uint8 ndarray, or the bytearray a
    checkpoint restore hands back); ``src`` any byte buffer
    (bytes/bytearray/memoryview/ndarray).  The fragment-assembly hot
    path of the receiver and the CPU ingest arm — big enough copies go
    through ``ctypes.memmove`` (a real memcpy, GIL released during the
    foreign call; numpy's buffer-protocol assignment measured ~5x
    slower), small ones through plain numpy assignment."""
    sv = np.frombuffer(src, dtype=np.uint8)  # zero-copy view
    dv = (dst if isinstance(dst, np.ndarray)
          else np.frombuffer(dst, dtype=np.uint8))  # writable for bytearray
    n = sv.shape[0]
    if n >= _MEMMOVE_MIN:
        ctypes.memmove(dv.ctypes.data + dst_off, sv.ctypes.data, n)
    else:
        dv[dst_off : dst_off + n] = sv


def aligned_empty(nbytes: int, align: int = ALIGN) -> np.ndarray:
    """An uninitialized uint8 buffer whose data pointer is ``align``-byte
    aligned (numpy gives no alignment guarantee; over-allocate + offset)."""
    raw = np.empty(nbytes + align, dtype=np.uint8)
    off = (-raw.ctypes.data) % align
    return raw[off : off + nbytes]


def is_adoptable(buf: np.ndarray) -> bool:
    return (
        buf.dtype == np.uint8
        and buf.flags["C_CONTIGUOUS"]
        and buf.ctypes.data % ALIGN == 0
    )


def adopt_as_device_array(buf: np.ndarray, device) -> "jax.Array":
    """Materialize ``buf`` as a jax.Array on ``device`` without copying
    when possible (CPU backend + aligned buffer); fall back to a plain
    ``device_put``.  The caller forfeits write access to ``buf``."""
    import jax

    if device.platform == "cpu" and is_adoptable(buf):
        try:
            arr = jax.dlpack.from_dlpack(buf, device=device, copy=False)
        except Exception:  # noqa: BLE001 — alignment/backend corner: copy
            arr = None
        if arr is None:
            try:  # without the placement hint (single-device CPU)
                arr = jax.dlpack.from_dlpack(buf, copy=False)
            except Exception:  # noqa: BLE001
                arr = None
        if arr is not None:
            if device in arr.devices():
                return arr
            # A virtual multi-CPU mesh wants a specific device id; the
            # cross-device put is still host memory either way.
            return jax.device_put(arr, device)
    return jax.device_put(buf, device)
