"""Aligned host buffers and zero-copy adoption onto the CPU backend.

On an accelerator, landing bytes in device memory means a real DMA over
the host link.  On the CPU backend there is no link: "device memory" IS
host memory, and ``jax.device_put`` of a numpy array is a pure-overhead
copy (measured ~5x slower than a plain memcpy on the bench host).  XLA
will alias an external host buffer as a device array zero-copy via
DLPack — but only when the buffer is 64-byte aligned, which numpy's
allocator does not guarantee.  So: allocate ingest buffers aligned
(``aligned_empty``), assemble bytes in place, and adopt the buffer as
the device array with no copy at all (``adopt_as_device_array``).

Safety contract for adoption: the jax.Array aliases the numpy buffer,
so the caller must never write to the buffer afterwards.  The DLPack
capsule keeps the buffer alive for the array's lifetime.
"""

from __future__ import annotations

import numpy as np

ALIGN = 64  # XLA's zero-copy import requires 64-byte alignment


def aligned_empty(nbytes: int, align: int = ALIGN) -> np.ndarray:
    """An uninitialized uint8 buffer whose data pointer is ``align``-byte
    aligned (numpy gives no alignment guarantee; over-allocate + offset)."""
    raw = np.empty(nbytes + align, dtype=np.uint8)
    off = (-raw.ctypes.data) % align
    return raw[off : off + nbytes]


def is_adoptable(buf: np.ndarray) -> bool:
    return (
        buf.dtype == np.uint8
        and buf.flags["C_CONTIGUOUS"]
        and buf.ctypes.data % ALIGN == 0
    )


def adopt_as_device_array(buf: np.ndarray, device) -> "jax.Array":
    """Materialize ``buf`` as a jax.Array on ``device`` without copying
    when possible (CPU backend + aligned buffer); fall back to a plain
    ``device_put``.  The caller forfeits write access to ``buf``."""
    import jax

    if device.platform == "cpu" and is_adoptable(buf):
        try:
            arr = jax.dlpack.from_dlpack(buf, device=device, copy=False)
        except Exception:  # noqa: BLE001 — alignment/backend corner: copy
            arr = None
        if arr is None:
            try:  # without the placement hint (single-device CPU)
                arr = jax.dlpack.from_dlpack(buf, copy=False)
            except Exception:  # noqa: BLE001
                arr = None
        if arr is not None:
            if device in arr.devices():
                return arr
            # A virtual multi-CPU mesh wants a specific device id; the
            # cross-device put is still host memory either way.
            return jax.device_put(arr, device)
    return jax.device_put(buf, device)
