"""Structured JSON logging, zerolog-style.

The reference emits zerolog JSON to stderr with unix-ms timestamps and a
per-process ``node`` field (``/root/reference/cmd/main.go:35-44``); the log
stream doubles as the metrics system (phase markers like ``"timer start"``,
per-transfer throughputs), merged offline by ``conf/collect_logs.sh``.
This module reproduces that: one JSON object per line with ``level``,
``time`` (unix ms), ``node``, ``message``, plus arbitrary fields.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from typing import IO, Optional

_lock = threading.Lock()


class JsonLogger:
    """zerolog-equivalent: ``log.info("msg", layer=3, mibps=812.5)``."""

    LEVELS = {"debug": 0, "info": 1, "warn": 2, "error": 3}

    def __init__(
        self,
        node: Optional[str] = None,
        stream: Optional[IO[str]] = None,
        level: str = "info",
    ):
        self.node = node
        self.stream = stream if stream is not None else sys.stderr
        self.level = level

    def with_node(self, node: str) -> "JsonLogger":
        return JsonLogger(node=node, stream=self.stream, level=self.level)

    def _emit(self, level: str, message: str, **fields) -> None:
        if self.LEVELS[level] < self.LEVELS[self.level]:
            return
        rec = {"level": level, "time": int(time.time() * 1000)}
        if self.node is not None:
            rec["node"] = self.node
        rec.update(fields)
        rec["message"] = message
        line = json.dumps(rec, default=str)
        with _lock:
            self.stream.write(line + "\n")
            self.stream.flush()

    def debug(self, message: str = "", **fields) -> None:
        self._emit("debug", message, **fields)

    def info(self, message: str = "", **fields) -> None:
        self._emit("info", message, **fields)

    def warn(self, message: str = "", **fields) -> None:
        self._emit("warn", message, **fields)

    def error(self, message: str = "", **fields) -> None:
        self._emit("error", message, **fields)


# Module-level default logger; configure() mutates it in place so modules
# that imported `log` by value (``from ...utils import log``) see the update.
log = JsonLogger()


def configure(node: Optional[str] = None, verbose: bool = False,
              stream: Optional[IO[str]] = None) -> JsonLogger:
    """Set up the global logger like cmd/main.go:35-44 (-v => debug)."""
    log.node = node
    log.stream = stream if stream is not None else sys.stderr
    log.level = "debug" if verbose else "info"
    return log
