"""Run-scoped telemetry: the cluster's flight recorder (docs/observability.md).

Every perf and robustness bar so far was judged by reading process-local
sums at run end and guessing at cross-node attribution.  This module is
the one registry behind all of that accounting, with three properties the
old ``utils/trace.py`` globals lacked:

- **Run-scoped**: ``reset_run()`` clears everything (phases, counters,
  gauges, histograms, links), so back-to-back runs in one process —
  tests, a promoted standby, the future multi-job service — never
  inherit each other's totals.  ``snapshot()`` is a cheap consistent
  copy; a report is "the run so far", and deltas are snapshots diffed by
  the consumer.
- **Per-link flight recorder**: every (src, dest) node pair accumulates
  bytes, frames, stripe occupancy, CRC drops, NACKs, retransmit bytes,
  and stall attribution (wire-wait vs verify vs placement vs
  decode/stage seconds).  Writers are the transports (wire-level frames)
  and the receiver runtime (committed delivered bytes — the byte-exact
  number a run report reconciles against the goal state).
- **Always-on and cheap**: a dict update under one lock per frame-scale
  event (frames are MiB-scale, so the accounting is noise — measured in
  TTD_MATRIX.md's telemetry-overhead row).  ``DLD_TELEMETRY=0`` disables
  the LINK recorder and histograms (the overhead A/B knob); phase
  buckets and event counters stay on — pre-existing harness tables
  depend on them.

The registry feeds three consumers: ``MetricsReportMsg`` (periodic
node → leader shipping, ``runtime/receiver.MetricsReporter``), the
leader's cluster table (``runtime/leader.py``), and the one-command run
report (``cli/report.py``).
"""

from __future__ import annotations

import os
import secrets
import threading
from typing import Dict, Optional, Tuple

# Process identity for snapshot folding: every snapshot carries this
# token, and ``fold_counters`` counts ONE snapshot per distinct token.
# Nodes sharing a process (podrun, the in-process harnesses, tests)
# share ONE registry, so their per-node reports are cumulative views of
# the SAME counters — summing them would multiply every cluster total
# by the co-resident node count.  One-process-per-node deployments get
# distinct tokens and the plain sum.
PROC_TOKEN = f"{os.getpid():x}-{secrets.token_hex(4)}"

# Fixed histogram bucket upper bounds, in milliseconds (the last bucket
# is unbounded).  Power-of-4 spacing spans one frame's syscall (~1 ms)
# to a wedged multi-minute stall in 9 buckets — coarse on purpose: the
# histograms attribute hangs to a phase, they don't profile kernels.
HIST_BUCKETS_MS: Tuple[float, ...] = (
    1.0, 4.0, 16.0, 64.0, 256.0, 1024.0, 4096.0, 16384.0, 65536.0)

# Per-link field ownership: each field is written by exactly ONE end of
# the link (rx-ish fields by the dest's process, tx-ish by the src's),
# so the leader's cluster fold can union two nodes' reports of the same
# link without double-counting (runtime/leader.py, cli/report.py).
LINK_RX_FIELDS = frozenset((
    "rx_bytes", "rx_frames", "rx_stripe_frames", "rx_placed_frames",
    "delivered_bytes", "crc_drops", "crc_drop_bytes", "nacks",
    "wire_s", "verify_s", "place_s", "stage_s",
))
LINK_TX_FIELDS = frozenset((
    "tx_bytes", "tx_frames", "tx_stripe_frames",
    "retransmit_frames", "retransmit_bytes",
))
LINK_FIELDS = LINK_RX_FIELDS | LINK_TX_FIELDS


def _links_enabled() -> bool:
    """The always-on link recorder's kill switch (``DLD_TELEMETRY=0``) —
    exists for the overhead A/B row in TTD_MATRIX.md, read per call so
    tests can flip it without re-importing."""
    return os.environ.get("DLD_TELEMETRY", "1") != "0"


class Telemetry:
    """One run's metric state.  All methods are thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        # name -> [sum_s, n]  (the trace.py phase buckets live here now)
        self._phases: Dict[str, list] = {}
        # name -> {"buckets": [..], "sum_ms": float, "n": int}
        self._hists: Dict[str, dict] = {}
        # (src, dest, job) -> {field: number}.  job "" is the base link
        # row (every field files there); a non-empty job ADDITIONALLY
        # files on its own row, so per-job splits are an additive view
        # of the base totals, never a replacement (docs/service.md).
        self._links: Dict[Tuple[int, int, str], Dict[str, float]] = {}

    # ------------------------------------------------------------ scalars

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def add_phase(self, name: str, seconds: float) -> None:
        with self._lock:
            rec = self._phases.get(name)
            if rec is None:
                rec = self._phases[name] = [0.0, 0]
            rec[0] += seconds
            rec[1] += 1

    def observe_ms(self, name: str, ms: float) -> None:
        """One fixed-bucket histogram sample (milliseconds)."""
        if not _links_enabled():
            return
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = {
                    "buckets": [0] * (len(HIST_BUCKETS_MS) + 1),
                    "sum_ms": 0.0, "n": 0}
            idx = 0
            for idx, bound in enumerate(HIST_BUCKETS_MS):
                if ms <= bound:
                    break
            else:
                idx = len(HIST_BUCKETS_MS)
            h["buckets"][idx] += 1
            h["sum_ms"] += ms
            h["n"] += 1

    # -------------------------------------------------------------- links

    def link_add(self, src, dest, job: str = "", **fields) -> None:
        """Accumulate numeric fields onto the (src, dest) link.  Unknown
        src/dest (a transport without a bound node id) records nothing —
        an unattributable byte is better dropped than misfiled.

        ``job``: the dissemination-job tag riding the frame
        (docs/service.md).  Tagged fields file on the BASE (src, dest)
        row as always — cluster totals and the byte-exact delivered
        reconciliation are unchanged — and additionally on the
        (src, dest, job) row, serialized ``"src->dest#job"`` in
        snapshots, so overlapping jobs' bytes split instead of pooling
        into one undifferentiated counter."""
        if src is None or dest is None or not _links_enabled():
            return
        keys = [(int(src), int(dest), "")]
        if job:
            keys.append((int(src), int(dest), str(job)))
        with self._lock:
            for key in keys:
                link = self._links.get(key)
                if link is None:
                    link = self._links[key] = {}
                for name, v in fields.items():
                    if v:
                        link[name] = link.get(name, 0) + v

    # ---------------------------------------------------------- snapshots

    def snapshot(self) -> dict:
        """A consistent copy of the run so far — JSON-ready (link keys
        serialized ``"src->dest"``, seconds rounded)."""
        with self._lock:
            return {
                "proc": PROC_TOKEN,
                "counters": dict(self._counters),
                "gauges": {k: round(v, 3)
                           for k, v in self._gauges.items()},
                "phases": {name: {"ms": round(s * 1000, 1), "n": n}
                           for name, (s, n) in sorted(self._phases.items())},
                "hists": {name: {"buckets": list(h["buckets"]),
                                 "sum_ms": round(h["sum_ms"], 1),
                                 "n": h["n"]}
                          for name, h in sorted(self._hists.items())},
                "links": {
                    (f"{s}->{d}#{j}" if j else f"{s}->{d}"): {
                        k: (round(v, 4) if isinstance(v, float) else v)
                        for k, v in sorted(fields.items())}
                    for (s, d, j), fields in sorted(self._links.items())
                },
            }

    def counter_totals(self) -> dict:
        with self._lock:
            return dict(sorted(self._counters.items()))

    def phase_totals(self) -> dict:
        with self._lock:
            return {name: {"ms": round(s * 1000, 1), "n": n}
                    for name, (s, n) in sorted(self._phases.items())}

    # -------------------------------------------------------------- reset

    def reset_run(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._phases.clear()
            self._hists.clear()
            self._links.clear()

    def reset_phases(self) -> None:
        with self._lock:
            self._phases.clear()

    def reset_counters(self) -> None:
        with self._lock:
            self._counters.clear()


# The process default registry.  One per process on purpose: a process
# IS a node, and run scoping comes from reset_run() between runs (the
# tests' autouse fixture, a harness's per-trial reset) — not from
# threading registries through every call site.
_default = Telemetry()


def default() -> Telemetry:
    return _default


def count(name: str, n: int = 1) -> None:
    _default.count(name, n)


def gauge(name: str, value: float) -> None:
    _default.gauge(name, value)


def add_phase(name: str, seconds: float) -> None:
    _default.add_phase(name, seconds)


def observe_ms(name: str, ms: float) -> None:
    _default.observe_ms(name, ms)


def link_add(src, dest, **fields) -> None:
    _default.link_add(src, dest, **fields)


def snapshot() -> dict:
    return _default.snapshot()


def reset_run() -> None:
    _default.reset_run()


def enabled() -> bool:
    return _links_enabled()


# -------------------------------------------------- histogram analysis


def percentile_from_hist(hist: Optional[dict], q: float) -> Optional[float]:
    """Estimate the ``q``-quantile (0 < q <= 1) of a fixed-bucket
    histogram (``{"buckets": [...], "n": int}``) as the UPPER bound of
    the bucket where the cumulative count crosses ``q * n`` —
    deliberately conservative (never under-reports a latency), which is
    the right bias for an SLO guard (docs/rollout.md).  The last bucket
    is unbounded: a quantile landing there returns ``inf``.  Returns
    None for an empty/absent histogram (no samples = no verdict)."""
    if not hist:
        return None
    buckets = list(hist.get("buckets") or [])
    n = int(hist.get("n", 0)) or sum(int(b) for b in buckets)
    if n <= 0 or not buckets:
        return None
    want = q * n
    seen = 0
    for idx, count in enumerate(buckets):
        seen += int(count)
        if seen >= want:
            if idx < len(HIST_BUCKETS_MS):
                return float(HIST_BUCKETS_MS[idx])
            return float("inf")
    return float("inf")


def hist_delta(now: Optional[dict], base: Optional[dict]) -> dict:
    """Bucket-wise ``now - base`` of two cumulative fixed-bucket
    histograms — the soak-window view the SLO guard evaluates
    (docs/rollout.md).  A missing ``base`` means the window starts at
    zero; counts are floored at 0 so a registry reset mid-window reads
    as a fresh window, never a negative one."""
    now = now or {}
    base = base or {}
    nb = list(now.get("buckets") or [])
    bb = list(base.get("buckets") or [])
    bb += [0] * (len(nb) - len(bb))
    buckets = [max(0, int(a) - int(b)) for a, b in zip(nb, bb)]
    return {
        "buckets": buckets,
        "sum_ms": max(0.0, float(now.get("sum_ms", 0.0))
                      - float(base.get("sum_ms", 0.0))),
        "n": max(0, int(now.get("n", 0)) - int(base.get("n", 0))),
    }


# ------------------------------------------------------- cluster folding


def fold_links(reports: Dict[int, dict],
               local: Optional[dict] = None) -> Dict[str, dict]:
    """Merge per-node snapshots' link tables into one cluster view.

    Each (src, dest) link is reported by up to two nodes — the dest owns
    the rx-ish fields, the src the tx-ish fields (LINK_*_FIELDS) — so
    the fold takes each field from the endpoint that owns it; a field
    reported by a non-owner (shouldn't happen) is kept only when the
    owner never reported.  ``local``: the folding process's own
    snapshot, merged like any node's report."""
    out: Dict[str, dict] = {}

    def merge(node_id, snap) -> None:
        for key, fields in (snap.get("links") or {}).items():
            base, _, job = key.partition("#")
            try:
                src_s, dest_s = base.split("->", 1)
                src, dest = int(src_s), int(dest_s)
            except ValueError:
                continue
            row = out.setdefault(key, {"src": src, "dest": dest})
            if job:
                row["job"] = job
            for name, v in fields.items():
                owner = (dest if name in LINK_RX_FIELDS
                         else src if name in LINK_TX_FIELDS else None)
                if owner is None or owner == node_id or name not in row:
                    row[name] = v

    for node_id, snap in sorted(reports.items()):
        merge(node_id, snap)
    if local is not None:
        merge(None, local)  # owner unknown: fill gaps only
    return out


def fold_counters(reports: Dict[int, dict],
                  local: Optional[dict] = None) -> Dict[str, int]:
    """Sum event counters into cluster totals, counting ONE snapshot
    per process (``PROC_TOKEN``): co-resident nodes report cumulative
    views of the same shared registry, and summing those would multiply
    every total by the node count.  Per process the FRESHEST snapshot
    wins (max ``t_wall_ms``; a ``local`` live read beats any shipped
    report from the same process).  Legacy reports without a token
    count per node, the pre-token behavior."""
    by_proc: Dict[object, dict] = {}

    def admit(key, snap, force=False):
        prior = by_proc.get(key)
        if (force or prior is None
                or snap.get("t_wall_ms", 0) >= prior.get("t_wall_ms", 0)):
            by_proc[key] = snap

    for node_id, snap in sorted(reports.items()):
        admit(snap.get("proc") or ("node", node_id), snap)
    if local is not None:
        admit(local.get("proc") or ("local",), local, force=True)
    out: Dict[str, int] = {}
    for snap in by_proc.values():
        for name, v in (snap.get("counters") or {}).items():
            out[name] = out.get(name, 0) + int(v)
    return dict(sorted(out.items()))
