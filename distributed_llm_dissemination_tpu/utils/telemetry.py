"""Run-scoped telemetry: the cluster's flight recorder (docs/observability.md).

Every perf and robustness bar so far was judged by reading process-local
sums at run end and guessing at cross-node attribution.  This module is
the one registry behind all of that accounting, with three properties the
old ``utils/trace.py`` globals lacked:

- **Run-scoped**: ``reset_run()`` clears everything (phases, counters,
  gauges, histograms, links), so back-to-back runs in one process —
  tests, a promoted standby, the future multi-job service — never
  inherit each other's totals.  ``snapshot()`` is a cheap consistent
  copy; a report is "the run so far", and deltas are snapshots diffed by
  the consumer.
- **Per-link flight recorder**: every (src, dest) node pair accumulates
  bytes, frames, stripe occupancy, CRC drops, NACKs, retransmit bytes,
  and stall attribution (wire-wait vs verify vs placement vs
  decode/stage seconds).  Writers are the transports (wire-level frames)
  and the receiver runtime (committed delivered bytes — the byte-exact
  number a run report reconciles against the goal state).
- **Always-on and cheap**: a dict update under one lock per frame-scale
  event (frames are MiB-scale, so the accounting is noise — measured in
  TTD_MATRIX.md's telemetry-overhead row).  ``DLD_TELEMETRY=0`` disables
  the LINK recorder and histograms (the overhead A/B knob); phase
  buckets and event counters stay on — pre-existing harness tables
  depend on them.

The registry feeds three consumers: ``MetricsReportMsg`` (periodic
node → leader shipping, ``runtime/receiver.MetricsReporter``), the
leader's cluster table (``runtime/leader.py``), and the one-command run
report (``cli/report.py``).
"""

from __future__ import annotations

import collections
import os
import secrets
import threading
import time as _time
from typing import Dict, List, Optional, Tuple

# Process identity for snapshot folding: every snapshot carries this
# token, and ``fold_counters`` counts ONE snapshot per distinct token.
# Nodes sharing a process (podrun, the in-process harnesses, tests)
# share ONE registry, so their per-node reports are cumulative views of
# the SAME counters — summing them would multiply every cluster total
# by the co-resident node count.  One-process-per-node deployments get
# distinct tokens and the plain sum.
PROC_TOKEN = f"{os.getpid():x}-{secrets.token_hex(4)}"

# Fixed histogram bucket upper bounds, in milliseconds (the last bucket
# is unbounded).  Power-of-4 spacing spans one frame's syscall (~1 ms)
# to a wedged multi-minute stall in 9 buckets — coarse on purpose: the
# histograms attribute hangs to a phase, they don't profile kernels.
HIST_BUCKETS_MS: Tuple[float, ...] = (
    1.0, 4.0, 16.0, 64.0, 256.0, 1024.0, 4096.0, 16384.0, 65536.0)

# Per-link field ownership: each field is written by exactly ONE end of
# the link (rx-ish fields by the dest's process, tx-ish by the src's),
# so the leader's cluster fold can union two nodes' reports of the same
# link without double-counting (runtime/leader.py, cli/report.py).
LINK_RX_FIELDS = frozenset((
    "rx_bytes", "rx_frames", "rx_stripe_frames", "rx_placed_frames",
    "delivered_bytes", "crc_drops", "crc_drop_bytes", "nacks",
    "wire_s", "verify_s", "place_s", "stage_s",
))
LINK_TX_FIELDS = frozenset((
    "tx_bytes", "tx_frames", "tx_stripe_frames",
    "retransmit_frames", "retransmit_bytes",
))
LINK_FIELDS = LINK_RX_FIELDS | LINK_TX_FIELDS


def _links_enabled() -> bool:
    """The always-on link recorder's kill switch (``DLD_TELEMETRY=0``) —
    exists for the overhead A/B row in TTD_MATRIX.md, read per call so
    tests can flip it without re-importing."""
    return os.environ.get("DLD_TELEMETRY", "1") != "0"


# ------------------------------------------------- pair lifecycle spans

# The causal span vocabulary (docs/observability.md): every delivery
# pair's lifecycle is a chain of these phases, recorded where each
# transition actually happens — ``planned``/``acked`` at the leader,
# ``dispatched`` at the sender, ``first_byte``/``wire_complete``/
# ``verified``/``staged`` at the dest, ``flipped`` at a swap/rollout
# replica.  ``utils/critical_path.py`` walks the chain; the tier-1
# static drift check pins each name to a live ``span_event`` call site,
# so a renamed phase can't silently vanish from the critical-path walk.
SPAN_PHASES: Tuple[str, ...] = (
    "planned", "dispatched", "first_byte", "wire_complete",
    "verified", "staged", "acked", "flipped")


def spans_enabled() -> bool:
    """Span recording's own kill switch (``DLD_SPANS=0`` — the overhead
    A/B knob) on top of the telemetry master switch: spans are part of
    the flight recorder, so ``DLD_TELEMETRY=0`` silences them too."""
    return (os.environ.get("DLD_SPANS", "1") != "0") and _links_enabled()


def span_ring_size() -> int:
    """Bounded span ring capacity per registry (``DLD_SPAN_RING``).
    Oldest events drop first — the honest limit docs/observability.md
    records; ``telemetry.spans_dropped`` counts every drop."""
    try:
        return max(64, int(os.environ.get("DLD_SPAN_RING", "4096")))
    except ValueError:
        return 4096


def span_id(dest, layer) -> str:
    """The deterministic span id of one delivery pair, ``"dest.layer"``.
    Every participant — the planning leader, the commanded sender, the
    receiving dest — can mint it from what it already knows, so span
    correlation works even when the advisory wire tag (``SpanId`` on
    LayerHeader/AckMsg) was dropped by a legacy peer.  Qualified pairs
    (shard/codec/version) share the pair's span and carry the
    qualifiers as event fields — one (dest, layer) is one delivery
    story."""
    return f"{int(dest)}.{int(layer)}"


class Telemetry:
    """One run's metric state.  All methods are thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        # name -> [sum_s, n]  (the trace.py phase buckets live here now)
        self._phases: Dict[str, list] = {}
        # name -> {"buckets": [..], "sum_ms": float, "n": int}
        self._hists: Dict[str, dict] = {}
        # (src, dest, job) -> {field: number}.  job "" is the base link
        # row (every field files there); a non-empty job ADDITIONALLY
        # files on its own row, so per-job splits are an additive view
        # of the base totals, never a replacement (docs/service.md).
        self._links: Dict[Tuple[int, int, str], Dict[str, float]] = {}
        # Pair-lifecycle span events (docs/observability.md): a bounded
        # ring of {"span", "phase", "t_ms", "node", ...} dicts.  Sized
        # lazily at first event so tests can flip DLD_SPAN_RING.
        self._spans: Optional[collections.deque] = None

    # ------------------------------------------------------------ scalars

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def add_phase(self, name: str, seconds: float) -> None:
        with self._lock:
            rec = self._phases.get(name)
            if rec is None:
                rec = self._phases[name] = [0.0, 0]
            rec[0] += seconds
            rec[1] += 1

    def observe_ms(self, name: str, ms: float) -> None:
        """One fixed-bucket histogram sample (milliseconds)."""
        if not _links_enabled():
            return
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = {
                    "buckets": [0] * (len(HIST_BUCKETS_MS) + 1),
                    "sum_ms": 0.0, "n": 0}
            idx = 0
            for idx, bound in enumerate(HIST_BUCKETS_MS):
                if ms <= bound:
                    break
            else:
                idx = len(HIST_BUCKETS_MS)
            h["buckets"][idx] += 1
            h["sum_ms"] += ms
            h["n"] += 1

    # -------------------------------------------------------------- spans

    def span_event(self, span: str, phase: str, node=None,
                   **fields) -> None:
        """Record one pair-lifecycle span transition (docs/
        observability.md).  ``span`` is the pair's span id
        (``span_id(dest, layer)`` — or a sub-leader fan-out child's);
        ``phase`` one of ``SPAN_PHASES``; ``node`` the seat where the
        transition happened; extra fields (src, dest, layer, job,
        bytes, codec, shard, version, parent) are attached verbatim.
        Bounded: the ring drops oldest (``telemetry.spans_dropped``
        counts), so a long service run degrades to a recent window
        instead of growing without bound."""
        if not spans_enabled():
            return
        ev = {"span": str(span), "phase": str(phase),
              "t_ms": round(_time.time() * 1000.0, 3)}
        if node is not None:
            ev["node"] = int(node)
        for k, v in fields.items():
            if v or v == 0 and k in ("src", "dest", "layer"):
                ev[k] = v
        with self._lock:
            ring = self._spans
            if ring is None:
                ring = self._spans = collections.deque(
                    maxlen=span_ring_size())
            if len(ring) == ring.maxlen:
                self._counters["telemetry.spans_dropped"] = (
                    self._counters.get("telemetry.spans_dropped", 0) + 1)
            ring.append(ev)

    def span_events(self) -> List[dict]:
        with self._lock:
            return [dict(ev) for ev in (self._spans or ())]

    # -------------------------------------------------------------- links

    def link_add(self, src, dest, job: str = "", **fields) -> None:
        """Accumulate numeric fields onto the (src, dest) link.  Unknown
        src/dest (a transport without a bound node id) records nothing —
        an unattributable byte is better dropped than misfiled.

        ``job``: the dissemination-job tag riding the frame
        (docs/service.md).  Tagged fields file on the BASE (src, dest)
        row as always — cluster totals and the byte-exact delivered
        reconciliation are unchanged — and additionally on the
        (src, dest, job) row, serialized ``"src->dest#job"`` in
        snapshots, so overlapping jobs' bytes split instead of pooling
        into one undifferentiated counter."""
        if src is None or dest is None or not _links_enabled():
            return
        keys = [(int(src), int(dest), "")]
        if job:
            keys.append((int(src), int(dest), str(job)))
        with self._lock:
            for key in keys:
                link = self._links.get(key)
                if link is None:
                    link = self._links[key] = {}
                for name, v in fields.items():
                    if v:
                        link[name] = link.get(name, 0) + v

    # ---------------------------------------------------------- snapshots

    def snapshot(self) -> dict:
        """A consistent copy of the run so far — JSON-ready (link keys
        serialized ``"src->dest"``, seconds rounded)."""
        with self._lock:
            return {
                "proc": PROC_TOKEN,
                "counters": dict(self._counters),
                "gauges": {k: round(v, 3)
                           for k, v in self._gauges.items()},
                "phases": {name: {"ms": round(s * 1000, 1), "n": n}
                           for name, (s, n) in sorted(self._phases.items())},
                "hists": {name: {"buckets": list(h["buckets"]),
                                 "sum_ms": round(h["sum_ms"], 1),
                                 "n": h["n"]}
                          for name, h in sorted(self._hists.items())},
                "links": {
                    (f"{s}->{d}#{j}" if j else f"{s}->{d}"): {
                        k: (round(v, 4) if isinstance(v, float) else v)
                        for k, v in sorted(fields.items())}
                    for (s, d, j), fields in sorted(self._links.items())
                },
                "spans": [dict(ev) for ev in (self._spans or ())],
            }

    def counter_totals(self) -> dict:
        with self._lock:
            return dict(sorted(self._counters.items()))

    def phase_totals(self) -> dict:
        with self._lock:
            return {name: {"ms": round(s * 1000, 1), "n": n}
                    for name, (s, n) in sorted(self._phases.items())}

    # -------------------------------------------------------------- reset

    def reset_run(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._phases.clear()
            self._hists.clear()
            self._links.clear()
            self._spans = None

    def reset_phases(self) -> None:
        with self._lock:
            self._phases.clear()

    def reset_counters(self) -> None:
        with self._lock:
            self._counters.clear()


# The process default registry.  One per process on purpose: a process
# IS a node, and run scoping comes from reset_run() between runs (the
# tests' autouse fixture, a harness's per-trial reset) — not from
# threading registries through every call site.
_default = Telemetry()


def default() -> Telemetry:
    return _default


def count(name: str, n: int = 1) -> None:
    _default.count(name, n)


def gauge(name: str, value: float) -> None:
    _default.gauge(name, value)


def add_phase(name: str, seconds: float) -> None:
    _default.add_phase(name, seconds)


def observe_ms(name: str, ms: float) -> None:
    _default.observe_ms(name, ms)


def link_add(src, dest, **fields) -> None:
    _default.link_add(src, dest, **fields)


def span_event(span: str, phase: str, node=None, **fields) -> None:
    _default.span_event(span, phase, node=node, **fields)


def span_events() -> List[dict]:
    return _default.span_events()


def snapshot() -> dict:
    return _default.snapshot()


def reset_run() -> None:
    _default.reset_run()


def enabled() -> bool:
    return _links_enabled()


# -------------------------------------------------- histogram analysis


def percentile_from_hist(hist: Optional[dict], q: float) -> Optional[float]:
    """Estimate the ``q``-quantile (0 < q <= 1) of a fixed-bucket
    histogram (``{"buckets": [...], "n": int}``) as the UPPER bound of
    the bucket where the cumulative count crosses ``q * n`` —
    deliberately conservative (never under-reports a latency), which is
    the right bias for an SLO guard (docs/rollout.md).  The last bucket
    is unbounded: a quantile landing there returns ``inf``.  Returns
    None for an empty/absent histogram (no samples = no verdict)."""
    if not hist:
        return None
    buckets = list(hist.get("buckets") or [])
    n = int(hist.get("n", 0)) or sum(int(b) for b in buckets)
    if n <= 0 or not buckets:
        return None
    want = q * n
    seen = 0
    for idx, count in enumerate(buckets):
        seen += int(count)
        if seen >= want:
            if idx < len(HIST_BUCKETS_MS):
                return float(HIST_BUCKETS_MS[idx])
            return float("inf")
    return float("inf")


def hist_delta(now: Optional[dict], base: Optional[dict]) -> dict:
    """Bucket-wise ``now - base`` of two cumulative fixed-bucket
    histograms — the soak-window view the SLO guard evaluates
    (docs/rollout.md).  A missing ``base`` means the window starts at
    zero; counts are floored at 0 so a registry reset mid-window reads
    as a fresh window, never a negative one."""
    now = now or {}
    base = base or {}
    nb = list(now.get("buckets") or [])
    bb = list(base.get("buckets") or [])
    bb += [0] * (len(nb) - len(bb))
    buckets = [max(0, int(a) - int(b)) for a, b in zip(nb, bb)]
    return {
        "buckets": buckets,
        "sum_ms": max(0.0, float(now.get("sum_ms", 0.0))
                      - float(base.get("sum_ms", 0.0))),
        "n": max(0, int(now.get("n", 0)) - int(base.get("n", 0))),
    }


# ------------------------------------------------------- cluster folding


def fold_links(reports: Dict[int, dict],
               local: Optional[dict] = None) -> Dict[str, dict]:
    """Merge per-node snapshots' link tables into one cluster view.

    Each (src, dest) link is reported by up to two nodes — the dest owns
    the rx-ish fields, the src the tx-ish fields (LINK_*_FIELDS) — so
    the fold takes each field from the endpoint that owns it; a field
    reported by a non-owner (shouldn't happen) is kept only when the
    owner never reported.  ``local``: the folding process's own
    snapshot, merged like any node's report."""
    out: Dict[str, dict] = {}

    def merge(node_id, snap) -> None:
        for key, fields in (snap.get("links") or {}).items():
            base, _, job = key.partition("#")
            try:
                src_s, dest_s = base.split("->", 1)
                src, dest = int(src_s), int(dest_s)
            except ValueError:
                continue
            row = out.setdefault(key, {"src": src, "dest": dest})
            if job:
                row["job"] = job
            for name, v in fields.items():
                owner = (dest if name in LINK_RX_FIELDS
                         else src if name in LINK_TX_FIELDS else None)
                if owner is None or owner == node_id or name not in row:
                    row[name] = v

    for node_id, snap in sorted(reports.items()):
        merge(node_id, snap)
    if local is not None:
        merge(None, local)  # owner unknown: fill gaps only
    return out


def _freshest_per_proc(reports: Dict[int, dict],
                       local: Optional[dict]) -> List[dict]:
    """The ONE snapshot per process token (``PROC_TOKEN``) every
    cluster fold dedups by: co-resident nodes report cumulative views
    of the same shared registry, so per process the FRESHEST snapshot
    wins (max ``t_wall_ms``; a ``local`` live read beats any shipped
    report from the same process).  Legacy reports without a token
    count per node, the pre-token behavior."""
    by_proc: Dict[object, dict] = {}

    def admit(key, snap, force=False):
        prior = by_proc.get(key)
        if (force or prior is None
                or snap.get("t_wall_ms", 0) >= prior.get("t_wall_ms", 0)):
            by_proc[key] = snap

    for node_id, snap in sorted(reports.items()):
        admit(snap.get("proc") or ("node", node_id), snap)
    if local is not None:
        admit(local.get("proc") or ("local",), local, force=True)
    return list(by_proc.values())


def fold_counters(reports: Dict[int, dict],
                  local: Optional[dict] = None) -> Dict[str, int]:
    """Sum event counters into cluster totals over one snapshot per
    process (``_freshest_per_proc`` — summing co-resident views would
    multiply every total by the node count)."""
    out: Dict[str, int] = {}
    for snap in _freshest_per_proc(reports, local):
        for name, v in (snap.get("counters") or {}).items():
            out[name] = out.get(name, 0) + int(v)
    return dict(sorted(out.items()))


def fold_spans(reports: Dict[int, dict],
               local: Optional[dict] = None) -> List[dict]:
    """Merge per-node snapshots' span-event rings into one cluster
    timeline over one snapshot per process (``_freshest_per_proc`` —
    co-resident nodes report the same shared ring, so concatenating
    them would duplicate every event).  Events sort by wall time;
    correlation across nodes is the span id itself
    (docs/observability.md)."""
    out: List[dict] = []
    for snap in _freshest_per_proc(reports, local):
        out.extend(dict(ev) for ev in (snap.get("spans") or ()))
    out.sort(key=lambda ev: ev.get("t_ms", 0.0))
    return out


# ---------------------------------------------- live fleet health timeline


def metrics_interval() -> float:
    """The MetricsReportMsg period (``DLD_METRICS_INTERVAL_S``, default
    2 s; 0 disables shipping) — the ONE parse the reporter thread and
    the health plane's in-flight age gate both read."""
    try:
        return float(os.environ.get("DLD_METRICS_INTERVAL_S", "2.0"))
    except ValueError:
        return 2.0


def straggler_threshold() -> float:
    """Achieved/modeled link-rate fraction below which a transferring
    link counts as straggling (``DLD_STRAGGLER_FRAC``)."""
    try:
        return float(os.environ.get("DLD_STRAGGLER_FRAC", "0.5"))
    except ValueError:
        return 0.5


def straggler_sustain() -> int:
    """Consecutive breaching metrics intervals before a straggler event
    fires (``DLD_STRAGGLER_N``; default 1 — onset within one
    interval)."""
    try:
        return max(1, int(os.environ.get("DLD_STRAGGLER_N", "1")))
    except ValueError:
        return 1


def health_ring_size() -> int:
    """Bounded interval-series / event ring capacity
    (``DLD_HEALTH_RING``); oldest drop first."""
    try:
        return max(16, int(os.environ.get("DLD_HEALTH_RING", "512")))
    except ValueError:
        return 512


class HealthTimeline:
    """The leader-side live fleet health derivation (docs/
    observability.md): per-interval DELTAS of each node's cumulative
    ``MetricsReportMsg`` snapshots, folded into a bounded ring of
    time-series — per-link throughput, stall split, NACK/CRC-drop rate,
    per-node serve p99 (the PR-13 hists) — plus first-class STRAGGLER
    events: a link whose achieved rate sustains below
    ``straggler_threshold()`` × its modeled rate while a transfer is
    actually in flight is flagged with an onset timestamp, un-flagged
    when it recovers.  All methods thread-safe; state is plain dicts so
    it replicates through ``ControlDeltaMsg`` and a promoted standby
    keeps the picture."""

    def __init__(self):
        self._lock = threading.Lock()
        self._prev: Dict[int, dict] = {}       # node -> last snapshot
        self._series = collections.deque(maxlen=health_ring_size())
        self._events = collections.deque(maxlen=health_ring_size())
        self._breach: Dict[str, int] = {}      # link key -> consecutive
        self._flagged: Dict[str, float] = {}   # link key -> onset t_ms
        self._seen: set = set()                # ingest dedup keys

    # ------------------------------------------------------------ intake

    def observe(self, node_id: int, snap: dict,
                modeled_rate_fn=None, expected_srcs=()) -> List[dict]:
        """Fold one node's cumulative snapshot; returns NEW events.

        Links are scored from the DEST's report only (the rx-owner of
        ``delivered_bytes`` — co-resident registries would otherwise
        double-count) and only against base rows (per-job rows are an
        additive split).  ``modeled_rate_fn(src, dest)`` returns the
        modeled link rate in bytes/s, or 0 to skip scoring — the mode-3
        leader returns 0 for links with no in-flight pair, so a
        completed burst is never mis-read as a straggler.

        ``expected_srcs``: sources the caller KNOWS have in-flight
        pairs to this dest — a link so stalled its FIRST byte never
        landed has no snapshot row at all, and would otherwise be
        invisible to scoring (found hand-driving a whole-layer frame
        through a throttled link: the frame completes or nothing does).
        Absent rows for expected sources score as zero-rate
        intervals."""
        t_now = float(snap.get("t_wall_ms") or 0.0)
        new_events: List[dict] = []
        with self._lock:
            prev = self._prev.get(int(node_id))
            self._prev[int(node_id)] = snap
            if prev is None:
                return []
            dt = (t_now - float(prev.get("t_wall_ms") or 0.0)) / 1000.0
            if dt <= 0:
                return []
            links: Dict[str, dict] = {}

            def score(key, src, dest, rec, d_bytes):
                modeled = 0
                if modeled_rate_fn is not None:
                    try:
                        modeled = int(modeled_rate_fn(src, dest) or 0)
                    except Exception:  # noqa: BLE001 — advisory
                        modeled = 0
                if modeled <= 0:
                    # Unscored (no model, or nothing in flight any
                    # more): the breach streak AND the flag end here —
                    # a later transfer's breaches must not inherit this
                    # one's count, a flag held past its transfer would
                    # suppress the next transfer's straggler event, and
                    # a much-later recovery would carry a stale onset.
                    # The straggler event itself stays in the ring —
                    # that is the history; the flag is only "currently
                    # judged slow".
                    self._breach.pop(key, None)
                    self._flagged.pop(key, None)
                    return
                # Scored whenever a judged transfer is in flight —
                # INCLUDING a zero-delta interval: 0 B/s on a link the
                # model says should be moving is the worst straggler,
                # not an exempt one.
                frac = (d_bytes / dt) / modeled
                rec["modeled_bps"] = modeled
                rec["frac"] = round(frac, 4)
                if frac < straggler_threshold():
                    n = self._breach.get(key, 0) + 1
                    self._breach[key] = n
                    if (n >= straggler_sustain()
                            and key not in self._flagged):
                        ev = {"t_ms": round(t_now, 1),
                              "kind": "straggler_link",
                              "link": key, "src": src, "dest": dest,
                              "achieved_bps": rec["bps"],
                              "modeled_bps": modeled,
                              "frac": rec["frac"],
                              "intervals": n}
                        self._flagged[key] = ev["t_ms"]
                        self._events.append(ev)
                        new_events.append(dict(ev))
                else:
                    # Carry the recovered-from streak length and the
                    # measured ratio on the recovery event too, so
                    # policies (and RUN_REPORT readers) threshold on
                    # data, not just the event name (docs/autonomy.md).
                    streak = self._breach.pop(key, None) or 0
                    if key in self._flagged:
                        ev = {"t_ms": round(t_now, 1),
                              "kind": "link_recovered", "link": key,
                              "src": src, "dest": dest,
                              "achieved_bps": rec["bps"],
                              "modeled_bps": modeled,
                              "frac": rec["frac"],
                              "intervals": int(streak),
                              "onset_t_ms": self._flagged.pop(key)}
                        self._events.append(ev)
                        new_events.append(dict(ev))

            for key, row in (snap.get("links") or {}).items():
                base, _, job = key.partition("#")
                if job:
                    continue
                try:
                    src_s, dest_s = base.split("->", 1)
                    src, dest = int(src_s), int(dest_s)
                except ValueError:
                    continue
                if dest != int(node_id):
                    continue  # rx fields are owned by the dest's report
                prow = (prev.get("links") or {}).get(key) or {}

                def delta(name):
                    return max(0.0, float(row.get(name) or 0)
                               - float(prow.get(name) or 0))

                d_bytes = delta("delivered_bytes")
                rec = {"bps": round(d_bytes / dt, 1),
                       "delivered": int(d_bytes),
                       "nacks": int(delta("nacks")),
                       "crc_drops": int(delta("crc_drops")),
                       "wire_s": round(delta("wire_s"), 4),
                       "verify_s": round(delta("verify_s"), 4),
                       "place_s": round(delta("place_s"), 4)}
                links[key] = rec
                score(key, src, dest, rec, d_bytes)
            # Links the caller expects in flight but whose FIRST byte
            # never landed (no snapshot row): score them as zero-rate
            # intervals — the fully-dark link must be the first flag,
            # not the one blind spot.
            for src in expected_srcs or ():
                key = f"{int(src)}->{int(node_id)}"
                if key in links:
                    continue
                rec = {"bps": 0.0, "delivered": 0, "absent": True}
                links[key] = rec
                score(key, int(src), int(node_id), rec, 0.0)
            # Per-node serve p99 off the cumulative hists' window delta
            # (the PR-13 SLO plumbing, reused — docs/rollout.md).
            serve_p99 = None
            for name, h in (snap.get("hists") or {}).items():
                if not name.startswith("serve.latency_ms"):
                    continue
                d = hist_delta(h, (prev.get("hists") or {}).get(name))
                p99 = percentile_from_hist(d, 0.99)
                if p99 is not None:
                    serve_p99 = (p99 if serve_p99 is None
                                 else max(serve_p99, p99))
            interval = {"t_ms": round(t_now, 1), "node": int(node_id),
                        "dt_s": round(dt, 3), "links": links}
            if serve_p99 is not None:
                interval["serve_p99_ms"] = serve_p99
            self._series.append(interval)
        return new_events

    def ingest(self, events) -> List[dict]:
        """Adopt foreign events verbatim (a replicated shadow's ring at
        takeover, or an advisory ``MetricsReportMsg.health`` section),
        deduplicated by (t_ms, kind, link)."""
        fresh: List[dict] = []
        with self._lock:
            if len(self._seen) > 8 * health_ring_size():
                # Bound the dedup memory like every other health
                # structure; a cleared set only risks re-appending an
                # event already rotated out of the bounded ring.
                self._seen.clear()
            for ev in events or ():
                key = (ev.get("t_ms"), ev.get("kind"), ev.get("link"))
                if key in self._seen:
                    continue
                self._seen.add(key)
                self._events.append(dict(ev))
                link = str(ev.get("link") or "")
                if ev.get("kind") == "straggler_link" and link:
                    self._flagged.setdefault(link,
                                             float(ev.get("t_ms") or 0))
                elif ev.get("kind") == "link_recovered" and link:
                    # Replay the recovery too: an adopted ring whose
                    # link already healed must not stay marked flagged
                    # (a later healthy interval would emit a spurious
                    # duplicate recovery with the stale onset).
                    self._flagged.pop(link, None)
                fresh.append(dict(ev))
        return fresh

    # ----------------------------------------------------------- export

    def events(self) -> List[dict]:
        with self._lock:
            return [dict(ev) for ev in self._events]

    def snapshot(self, series_tail: int = 32) -> dict:
        """JSON-ready view: the full event ring + the series tail (the
        live ``-watch`` window; RUN_REPORT embeds the same shape)."""
        with self._lock:
            series = list(self._series)[-max(0, int(series_tail)):]
            return {"events": [dict(ev) for ev in self._events],
                    "intervals": [dict(iv) for iv in series],
                    "flagged": dict(self._flagged)}
