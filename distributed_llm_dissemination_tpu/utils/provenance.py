"""Harness provenance: tie recorded artifacts to the code that ran.

Round-4 lesson (VERDICT): a committed ``TPU_SMOKE.json`` recorded
several commits before the kernels it vouched for had changed — nothing
stopped a stale artifact from masquerading as current evidence.  The
same content-hash discipline ``native/__init__.py`` uses for the C++
solver (rebuild when the source changed) applies to measurement
artifacts: every harness embeds ``harness_hash()`` in its report, and a
CI-style test (``tests/test_provenance.py``) fails when a committed
artifact's hash doesn't match the working tree — unless the artifact
carries an explicit, documented ``stale`` marker (e.g. recorded during
a tunnel outage and honestly labeled as superseded evidence).
"""

from __future__ import annotations

import hashlib
import os

_PKG = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO = os.path.dirname(_PKG)


def harness_hash() -> str:
    """Content hash of every source file that can change a measurement:
    the package's .py and .cc files plus the repo-root ``bench.py`` /
    ``__graft_entry__.py`` drivers.  Deterministic (sorted relative
    paths mixed into the digest); 16 hex chars is plenty for a
    did-the-code-change check."""
    h = hashlib.sha256()
    files = []
    for root, dirs, names in os.walk(_PKG):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for name in sorted(names):
            if name.endswith((".py", ".cc")):
                files.append(os.path.join(root, name))
    for extra in ("bench.py", "__graft_entry__.py"):
        path = os.path.join(_REPO, extra)
        if os.path.exists(path):
            files.append(path)
    for path in sorted(files):
        h.update(os.path.relpath(path, _REPO).encode())
        h.update(b"\0")
        with open(path, "rb") as f:
            h.update(f.read())
        h.update(b"\0")
    return h.hexdigest()[:16]


def artifact_is_current(report: dict) -> tuple:
    """(ok, why) for a recorded artifact against the working tree:
    current hash, or an explicit ``stale`` marker string documenting
    why superseded evidence is still committed."""
    marker = report.get("stale")
    if isinstance(marker, str) and marker.strip():
        return True, f"documented-stale: {marker}"
    got = report.get("harness_hash")
    want = harness_hash()
    if got == want:
        return True, "hash-current"
    return False, (f"artifact hash {got!r} != working tree {want!r} "
                   "and no documented 'stale' marker")
