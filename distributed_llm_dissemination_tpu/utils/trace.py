"""In-process span instrumentation over the structured log stream.

The reference profiles exclusively through timestamped logs
(``/root/reference/distributor/node.go:1168-1186`` et al.); ``span``
standardizes that idiom: a context manager that logs completion with a
``duration_ms`` field, which ``cli/trace.py`` renders as a timeline
slice.  Zero infrastructure — the logs stay the single source of truth,
merged across hosts by ``cli/collect_logs.py`` exactly like the
reference's jq pipeline.
"""

from __future__ import annotations

import contextlib
import threading
import time

from .logging import log


@contextlib.contextmanager
def span(name: str, **fields):
    """Time a block and log it as a trace-friendly completion record::

        with span("stage layer", layerID=3):
            ...

    emits ``{"message": "stage layer", "layerID": 3, "duration_ms": ...}``.
    The record is logged even when the block raises (with ``error`` set),
    so traces show failed work instead of omitting it.
    """
    t0 = time.monotonic()
    try:
        yield
    except BaseException as e:
        log.error(name, duration_ms=round((time.monotonic() - t0) * 1000, 3),
                  error=repr(e), **fields)
        raise
    else:
        log.info(name, duration_ms=round((time.monotonic() - t0) * 1000, 3),
                 **fields)


# ----------------------------------------------------------- phase markers
#
# Cheap in-process phase accounting for the device-fabric plane: the
# per-plan pipeline (compile / upload / collective / splice) runs across
# handler threads and async device queues, so wall-clock spans alone
# can't attribute where a TTD went.  Timed sections call ``add_phase``
# (or use the ``phase`` context manager); harnesses read the summed
# totals via ``phase_totals`` — podrun folds them into its summary line,
# and ``cli/ttd_matrix.py`` renders the fabric row's phase-breakdown
# table from them.  Sums are thread-time: concurrent phases overlap, so
# totals may exceed the run's wall clock (the tables say so).

# TTFT buckets (the boot pipeline, ISSUE 3): writers in
# ``runtime/receiver.py`` and ``runtime/stream_boot.py``; the
# ``cli/ttd_matrix.py`` physical row renders them as the TTFT breakdown.
# - ``boot_precompile``          hint-time XLA compile seconds (total)
# - ``boot_precompile_in_wire``  the subset that finished BEFORE startup
#                                — compile-overlap-achieved
# - ``boot_stream_stage``        per-blob streamed decode/upload seconds
# - ``boot_stream_in_wire``      the subset that ran before startup —
#                                stage-overlap-achieved

_phase_lock = threading.Lock()
_phase_s: dict = {}
_phase_n: dict = {}


def add_phase(name: str, seconds: float) -> None:
    """Accumulate ``seconds`` into the named phase bucket."""
    with _phase_lock:
        _phase_s[name] = _phase_s.get(name, 0.0) + seconds
        _phase_n[name] = _phase_n.get(name, 0) + 1


@contextlib.contextmanager
def phase(name: str):
    """Time a block into the named phase bucket (recorded even when the
    block raises — failed work is still attributable work)."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        add_phase(name, time.monotonic() - t0)


def phase_totals() -> dict:
    """``{name: {"ms": summed_milliseconds, "n": samples}}`` so far."""
    with _phase_lock:
        return {
            name: {"ms": round(s * 1000, 1), "n": _phase_n[name]}
            for name, s in sorted(_phase_s.items())
        }


def reset_phases() -> None:
    with _phase_lock:
        _phase_s.clear()
        _phase_n.clear()


# ------------------------------------------------------------ event counters
#
# Integrity-plane accounting (docs/integrity.md): how many fragments were
# dropped for a bad CRC, how many NACKs went out, how many bytes were
# retransmitted, how many digests mismatched.  Same shape as the phase
# buckets — in-process sums the harness reads at the end of a run — but
# counting EVENTS, not seconds.  Writers: transport/tcp.py,
# transport/inmem.py, runtime/receiver.py, runtime/send.py.

_counter_lock = threading.Lock()
_counters: dict = {}


def count(name: str, n: int = 1) -> None:
    """Add ``n`` to the named event counter."""
    with _counter_lock:
        _counters[name] = _counters.get(name, 0) + n


def counter_totals() -> dict:
    """``{name: total}`` so far."""
    with _counter_lock:
        return dict(sorted(_counters.items()))


def reset_counters() -> None:
    with _counter_lock:
        _counters.clear()
