"""In-process span instrumentation over the structured log stream.

The reference profiles exclusively through timestamped logs
(``/root/reference/distributor/node.go:1168-1186`` et al.); ``span``
standardizes that idiom: a context manager that logs completion with a
``duration_ms`` field, which ``cli/trace.py`` renders as a timeline
slice.  Zero infrastructure — the logs stay the single source of truth,
merged across hosts by ``cli/collect_logs.py`` exactly like the
reference's jq pipeline.
"""

from __future__ import annotations

import contextlib
import time

from .logging import log


@contextlib.contextmanager
def span(name: str, **fields):
    """Time a block and log it as a trace-friendly completion record::

        with span("stage layer", layerID=3):
            ...

    emits ``{"message": "stage layer", "layerID": 3, "duration_ms": ...}``.
    The record is logged even when the block raises (with ``error`` set),
    so traces show failed work instead of omitting it.
    """
    t0 = time.monotonic()
    try:
        yield
    except BaseException as e:
        log.error(name, duration_ms=round((time.monotonic() - t0) * 1000, 3),
                  error=repr(e), **fields)
        raise
    else:
        log.info(name, duration_ms=round((time.monotonic() - t0) * 1000, 3),
                 **fields)


# ----------------------------------------------------------- phase markers
#
# Cheap in-process phase accounting for the device-fabric plane: the
# per-plan pipeline (compile / upload / collective / splice) runs across
# handler threads and async device queues, so wall-clock spans alone
# can't attribute where a TTD went.  Timed sections call ``add_phase``
# (or use the ``phase`` context manager); harnesses read the summed
# totals via ``phase_totals`` — podrun folds them into its summary line,
# and ``cli/ttd_matrix.py`` renders the fabric row's phase-breakdown
# table from them.  Sums are thread-time: concurrent phases overlap, so
# totals may exceed the run's wall clock (the tables say so).
#
# STORAGE lives in the run-scoped ``utils/telemetry.py`` registry now —
# these functions are the stable writer API (every instrumented call
# site keeps ``trace.add_phase``/``trace.count``), but the sums are no
# longer process-global module state: ``telemetry.reset_run()`` clears
# them between runs (the tests' autouse fixture, a promoted standby, a
# harness's per-trial reset), and ``telemetry.snapshot()`` ships them in
# MetricsReportMsg / RUN_REPORT.  ``reset_run`` is re-exported here for
# writers that already import ``trace``.

# TTFT buckets (the boot pipeline, ISSUE 3): writers in
# ``runtime/receiver.py`` and ``runtime/stream_boot.py``; the
# ``cli/ttd_matrix.py`` physical row renders them as the TTFT breakdown.
# - ``boot_precompile``          hint-time XLA compile seconds (total)
# - ``boot_precompile_in_wire``  the subset that finished BEFORE startup
#                                — compile-overlap-achieved
# - ``boot_stream_stage``        per-blob streamed decode/upload seconds
# - ``boot_stream_in_wire``      the subset that ran before startup —
#                                stage-overlap-achieved

from . import telemetry as _telemetry  # noqa: E402  (storage backend)


def add_phase(name: str, seconds: float) -> None:
    """Accumulate ``seconds`` into the named phase bucket."""
    _telemetry.add_phase(name, seconds)


@contextlib.contextmanager
def phase(name: str):
    """Time a block into the named phase bucket (recorded even when the
    block raises — failed work is still attributable work)."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        add_phase(name, time.monotonic() - t0)


def phase_totals() -> dict:
    """``{name: {"ms": summed_milliseconds, "n": samples}}`` so far."""
    return _telemetry.default().phase_totals()


def reset_phases() -> None:
    _telemetry.default().reset_phases()


# ------------------------------------------------------------ event counters
#
# Integrity-plane accounting (docs/integrity.md): how many fragments were
# dropped for a bad CRC, how many NACKs went out, how many bytes were
# retransmitted, how many digests mismatched.  Same shape as the phase
# buckets — in-process sums the harness reads at the end of a run — but
# counting EVENTS, not seconds.  Writers: transport/tcp.py,
# transport/inmem.py, runtime/receiver.py, runtime/send.py.  Stored in
# the run-scoped telemetry registry (see the phase-marker note above).


def count(name: str, n: int = 1) -> None:
    """Add ``n`` to the named event counter."""
    _telemetry.count(name, n)


def counter_totals() -> dict:
    """``{name: total}`` so far."""
    return _telemetry.default().counter_totals()


def reset_counters() -> None:
    _telemetry.default().reset_counters()


def reset_run() -> None:
    """Clear ALL run-scoped accounting (phases, counters, gauges,
    histograms, per-link flight recorder) — the between-runs reset."""
    _telemetry.reset_run()
