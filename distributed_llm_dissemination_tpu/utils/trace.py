"""In-process span instrumentation over the structured log stream.

The reference profiles exclusively through timestamped logs
(``/root/reference/distributor/node.go:1168-1186`` et al.); ``span``
standardizes that idiom: a context manager that logs completion with a
``duration_ms`` field, which ``cli/trace.py`` renders as a timeline
slice.  Zero infrastructure — the logs stay the single source of truth,
merged across hosts by ``cli/collect_logs.py`` exactly like the
reference's jq pipeline.
"""

from __future__ import annotations

import contextlib
import time

from .logging import log


@contextlib.contextmanager
def span(name: str, **fields):
    """Time a block and log it as a trace-friendly completion record::

        with span("stage layer", layerID=3):
            ...

    emits ``{"message": "stage layer", "layerID": 3, "duration_ms": ...}``.
    The record is logged even when the block raises (with ``error`` set),
    so traces show failed work instead of omitting it.
    """
    t0 = time.monotonic()
    try:
        yield
    except BaseException as e:
        log.error(name, duration_ms=round((time.monotonic() - t0) * 1000, 3),
                  error=repr(e), **fields)
        raise
    else:
        log.info(name, duration_ms=round((time.monotonic() - t0) * 1000, 3),
                 **fields)
