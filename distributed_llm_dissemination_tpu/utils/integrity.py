"""End-to-end payload integrity primitives (docs/integrity.md).

The dissemination path moves physical-size layers through sockets, stripe
regrouping, zero-copy placement, a crash-durable journal, and device
staging — and historically never checksummed a byte anywhere: one flipped
bit silently booted a corrupted model.  This module is the shared
vocabulary of the integrity plane:

- **Per-fragment checksum** (``fragment_checksum``): an advisory
  checksum stamped on every layer frame (``transport/messages.
  LayerHeader``), verified by the receiving transport BEFORE the
  fragment is delivered — a bad frame is dropped and NACKed
  (``LayerNackMsg``), never committed to interval accounting, the
  journal, or a device buffer.  The algorithm is picked by measurement
  (``hash_bench`` on the running host; TTD_MATRIX.md records it):
  xxh3-64 when the ``xxhash`` extension is importable — it is the only
  candidate that tracks the wire rate here (~6x stdlib ``zlib.crc32``)
  — falling back to crc32 otherwise.  Negotiation is per frame,
  omitted-field style: the header carries ``Xxh3`` or ``Crc``, and the
  receiver verifies whichever is present (a receiver without ``xxhash``
  treats an xxh3-stamped frame as unstamped — advisory, never a drop).
- **Per-layer digest** (``layer_digest``): a digest of the whole layer,
  announced by every holder, collected by the leader, and stamped to
  each assignee (``LayerDigestsMsg``).  The end-to-end backstop:
  receivers verify a completed layer against it before acking/staging,
  and a mismatch re-opens the covered intervals instead of acking.
  Digest strings are self-describing (``xxh3:<hex>`` / bare hex =
  blake2b-128), so both algorithms interoperate: xxh3-128 is the
  default where available — the threat model is CORRUPTION (wire, DMA,
  disk rot), against which 128 random-collision bits are equivalent and
  ~11x cheaper on this host than blake2b (``hash_bench``); set
  ``DLD_DIGEST_ALGO=blake2b`` where the model includes adversarial
  substitution and a cryptographic identity is worth the measured cost.

Both checks are wire-compatible (omitted-field style) and individually
gated: ``DLD_WIRE_CRC=0`` / ``DLD_LAYER_DIGESTS=0`` disable them.
Verification *cost* accounting uses ``time.thread_time`` (CPU seconds,
not preemption-inflated wall spans) — on a contended host a wall-clock
span around a hash mostly measures the scheduler.
"""

from __future__ import annotations

import hashlib
import os
import time
import zlib
from typing import Optional, Tuple

try:  # hot-path accelerator; every check below falls back to stdlib
    import xxhash as _xxhash
except ImportError:  # pragma: no cover - container-dependent
    _xxhash = None

# blake2b truncated to 128 bits: collision-resistant far past this
# system's layer counts, and half the hex bytes on the control plane.
DIGEST_SIZE = 16

_DIGEST_CHUNK = 8 << 20  # streaming-digest read granularity


def wire_crc_enabled() -> bool:
    """Per-fragment wire CRC (default ON; ``DLD_WIRE_CRC=0`` disables)."""
    return os.environ.get("DLD_WIRE_CRC", "1") != "0"


def digests_enabled() -> bool:
    """Per-layer blake2b digests (default ON; ``DLD_LAYER_DIGESTS=0``
    disables — the wire CRC still guards individual fragments)."""
    return os.environ.get("DLD_LAYER_DIGESTS", "1") != "0"


def fragment_crc(view) -> int:
    """crc32 of a fragment payload (bytes/bytearray/memoryview).
    zlib.crc32 runs in C with the GIL released for large buffers, so
    concurrent stripe receivers really verify in parallel."""
    return zlib.crc32(view) & 0xFFFFFFFF


def fragment_checksum(view) -> Tuple[str, int]:
    """The checksum a SENDER stamps on a frame: ``("xxh3", v)`` when the
    ``xxhash`` extension is importable, else ``("crc32", v)``.  Both C
    implementations release the GIL for large buffers, so concurrent
    stripe receivers really verify in parallel — and xxh3 sustains ~6x
    the crc32 rate on this host (``hash_bench``), which is what keeps
    the per-stripe check off the wire's critical path."""
    if _xxhash is not None:
        return "xxh3", _xxhash.xxh3_64_intdigest(view)
    return "crc32", zlib.crc32(view) & 0xFFFFFFFF


def checksum_of(view, algo: str) -> Optional[int]:
    """Compute the named fragment checksum, or None when this host
    can't (xxh3 stamp, no ``xxhash`` here — the check is advisory, so
    an unverifiable stamp reads as unstamped, never as corrupt)."""
    if algo == "crc32":
        return zlib.crc32(view) & 0xFFFFFFFF
    if algo == "xxh3" and _xxhash is not None:
        return _xxhash.xxh3_64_intdigest(view)
    return None


def verify_stamp(view, crc: Optional[int] = None,
                 xxh3: Optional[int] = None) -> Optional[bool]:
    """Verify a frame payload against its stamped checksum, preferring
    the xxh3 stamp when this host can compute it.  Returns None when the
    frame is EFFECTIVELY unstamped — no stamp at all, or an xxh3 stamp
    with no ``xxhash`` here (advisory: unverifiable never reads as
    corrupt) — else whether the payload matches."""
    if xxh3 is not None and _xxhash is not None:
        return _xxhash.xxh3_64_intdigest(view) == xxh3
    if crc is not None:
        return (zlib.crc32(view) & 0xFFFFFFFF) == crc
    return None


def file_checksum(path: str, offset: int, size: int) -> Tuple[str, int]:
    """Streaming ``fragment_checksum`` of a file range — what a DISK
    sender stamps (one warm page-cache sweep; the body itself still
    leaves via kernel ``sendfile``)."""
    if _xxhash is None:
        return "crc32", file_crc(path, offset, size)
    h = _xxhash.xxh3_64()
    with open(path, "rb") as f:
        f.seek(offset)
        left = size
        while left > 0:
            chunk = f.read(min(_DIGEST_CHUNK, left))
            if not chunk:
                raise ValueError(f"short read checksumming {path}")
            h.update(chunk)
            left -= len(chunk)
    return "xxh3", h.intdigest()


def file_crc(path: str, offset: int, size: int) -> int:
    """Chunked crc32 of a file range — the disk-body variant of
    ``fragment_crc`` (one warm page-cache sweep; senders still ship the
    bytes via kernel ``sendfile``)."""
    crc = 0
    with open(path, "rb") as f:
        f.seek(offset)
        left = size
        while left > 0:
            chunk = f.read(min(_DIGEST_CHUNK, left))
            if not chunk:
                raise ValueError(f"short read computing crc of {path}")
            crc = zlib.crc32(chunk, crc)
            left -= len(chunk)
    return crc & 0xFFFFFFFF


def digest_algo() -> str:
    """The layer-digest algorithm this process STAMPS (verification is
    driven by the stamp's own prefix, so mixed clusters interoperate).
    Default: xxh3-128 where available — against the corruption threat
    model its 128 collision bits are equivalent to blake2b's at ~11x
    less CPU on this host (``hash_bench``); ``DLD_DIGEST_ALGO=blake2b``
    buys a cryptographic identity where adversarial substitution is in
    scope (TTD_MATRIX.md records the measured cost of each)."""
    algo = os.environ.get("DLD_DIGEST_ALGO", "").strip().lower()
    if algo in ("blake2b", "xxh3"):
        if algo == "xxh3" and _xxhash is None:
            return "blake2b"
        return algo
    return "xxh3" if _xxhash is not None else "blake2b"


def _digest_hasher(algo: str):
    if algo == "xxh3":
        if _xxhash is None:
            raise ValueError("xxh3 digest stamped but xxhash is not "
                             "importable on this host")
        return _xxhash.xxh3_128()
    return hashlib.blake2b(digest_size=DIGEST_SIZE)


def layer_digest(data, algo: Optional[str] = None) -> str:
    """Self-describing hex digest of a full layer's bytes:
    ``xxh3:<hex>`` for xxh3-128, bare hex for blake2b-128 (the
    pre-negotiation format, so old stamps still verify)."""
    algo = algo or digest_algo()
    h = _digest_hasher(algo)
    h.update(data)
    hx = h.hexdigest()
    return f"xxh3:{hx}" if algo == "xxh3" else hx


def stamp_algo(stamp: str) -> str:
    """The algorithm a self-describing digest stamp was made with.
    Digests from holders with different capabilities (one has the
    ``xxhash`` extension, one doesn't) differ as STRINGS over identical
    bytes — conflict detection must only compare same-algorithm
    stamps."""
    return "xxh3" if stamp.startswith("xxh3:") else "blake2b"


def digest_check(data, expected: str) -> Tuple[Optional[bool], float, str]:
    """Verify ``data`` against a stamped digest using the STAMP's own
    algorithm (self-describing prefix — a blake2b stamp must never be
    "verified" with local xxh3).  THE one home of the stamp-format
    policy; every verifier (ack gate, boot, resume) routes through it.
    Returns ``(ok, thread_seconds, got)``: ``ok`` is None for an
    unverifiable stamp (xxh3 with no xxhash here — advisory, never
    read as corrupt), else whether the bytes match; ``thread_seconds``
    is the hash's CPU cost (``time.thread_time``) for the callers'
    trace buckets; ``got`` is the computed digest ("" when skipped)."""
    algo = "xxh3" if expected.startswith("xxh3:") else "blake2b"
    if algo == "xxh3" and _xxhash is None:
        return None, 0.0, ""
    t0 = time.thread_time()
    got = layer_digest(data, algo=algo)
    return got == expected, time.thread_time() - t0, got


def digest_matches(data, expected: str) -> bool:
    """Verify ``data`` against a stamped digest, using the STAMP's own
    algorithm (prefix); an unverifiable stamp (xxh3 with no xxhash
    here) is advisory-skipped as True, never read as corrupt."""
    ok, _, _ = digest_check(data, expected)
    return ok is not False


def report_corrupt_frame(on_corrupt, src_id, layer_id, offset: int,
                         size: int, total: int, reason: str,
                         stripe: str = "", silent: bool = False,
                         dest_id=None) -> None:
    """THE shared drop-report for both transports: one log wording (the
    ttd harness greps it), one counter scheme, one ``on_corrupt`` firing
    discipline — so inmem- and tcp-backed runs account corruption
    identically.  ``silent`` counts+logs without firing the hook (the
    regroup path reports the whole span itself).  ``dest_id``: the
    dropping transport's bound node id, so the drop also lands on the
    (src, dest) link of the telemetry flight recorder."""
    from .logging import log
    from . import telemetry, trace

    extra = {"stripe": stripe} if stripe else {}
    log.error("corrupt layer fragment dropped", layerID=layer_id,
              offset=offset, size=size, reason=reason, **extra)
    if reason == "stale":
        trace.count("integrity.stale_prune")
    else:
        trace.count("integrity.crc_drop")
        trace.count("integrity.crc_drop_bytes", size)
        telemetry.link_add(src_id, dest_id, crc_drops=1,
                           crc_drop_bytes=size)
    if silent:
        return
    fire_on_corrupt(on_corrupt, src_id, layer_id, offset, size, total,
                    reason)


def fire_on_corrupt(on_corrupt, src_id, layer_id, offset: int, size: int,
                    total: int, reason: str) -> None:
    """The one ``on_corrupt`` firing discipline: a raising hook must
    never wedge a receive path.  Used by ``report_corrupt_frame`` and by
    the stripe-regroup span report (which logs/counts per stripe but
    NACKs the whole logical span, so it fires the hook directly)."""
    if on_corrupt is None:
        return
    from .logging import log
    try:
        on_corrupt(src_id, layer_id, offset, size, total, reason)
    except Exception as e:  # noqa: BLE001 — reporting must not wedge rx
        log.error("on_corrupt hook failed", err=repr(e))


def digest_file_range(path: str, offset: int, size: int,
                      algo: Optional[str] = None) -> str:
    """Streaming layer digest over ``[offset, offset+size)`` of a file —
    disk-held layers digest without materializing the layer in RAM."""
    algo = algo or digest_algo()
    h = _digest_hasher(algo)
    with open(path, "rb") as f:
        f.seek(offset)
        left = size
        while left > 0:
            chunk = f.read(min(_DIGEST_CHUNK, left))
            if not chunk:
                raise ValueError(
                    f"short read digesting {path}: {left} bytes missing")
            h.update(chunk)
            left -= len(chunk)
    hx = h.hexdigest()
    return f"xxh3:{hx}" if algo == "xxh3" else hx


def digest_layer_src(src) -> Optional[str]:
    """Digest of a ``core.types.LayerSrc``'s full layer bytes, or None
    when the bytes aren't locally readable (CLIENT-held layers — the
    external client's bytes are outside this process).  Disk layers
    digest by streaming the file range; HBM-only layers materialize their
    one cached host copy first (``ensure_host_bytes``)."""
    from ..core.types import LayerLocation

    loc = src.meta.location
    if loc == LayerLocation.CLIENT:
        return None
    try:
        if src.inmem_data is not None:
            base = src.offset
            return layer_digest(
                memoryview(src.inmem_data)[base : base + src.data_size])
        if loc == LayerLocation.DISK and src.fp:
            return digest_file_range(src.fp, src.offset, src.data_size)
        if src.ensure_host_bytes():
            base = src.offset
            return layer_digest(
                memoryview(src.inmem_data)[base : base + src.data_size])
    except (OSError, ValueError):
        return None
    return None


def digest_layer_src_range(src, off: int, size: int) -> Optional[str]:
    """Digest of the byte range ``[off, off+size)`` of a LayerSrc — the
    per-RANGE digest the sharded-delivery plane stamps so a shard
    verifies without holding the full layer (docs/sharding.md).  Same
    readability rules as :func:`digest_layer_src`; None when the bytes
    aren't locally readable."""
    from ..core.types import LayerLocation

    loc = src.meta.location
    if loc == LayerLocation.CLIENT:
        return None
    try:
        if src.inmem_data is not None:
            base = src.offset + off
            return layer_digest(memoryview(src.inmem_data)[base:base + size])
        if loc == LayerLocation.DISK and src.fp:
            return digest_file_range(src.fp, src.offset + off, size)
        if src.ensure_host_bytes():
            base = src.offset + off
            return layer_digest(memoryview(src.inmem_data)[base:base + size])
    except (OSError, ValueError):
        return None
    return None


def hash_bench(nbytes: int = 64 << 20) -> dict:
    """Micro-bench the candidate integrity hashes on THIS host — the
    measured justification for the per-fragment and per-layer algorithm
    choices (TTD_MATRIX.md records the numbers, and ``digest_algo`` /
    ``fragment_checksum`` encode the conclusion).  Returns {name: GB/s};
    xxh3 entries are 0.0 when the extension isn't importable."""
    buf = memoryview(bytearray(os.urandom(1 << 20)) * (nbytes >> 20))

    def rate(fn) -> float:
        fn(buf[: 1 << 20])  # warm
        t0 = time.monotonic()
        fn(buf)
        dt = time.monotonic() - t0
        return round(len(buf) / max(dt, 1e-9) / 1e9, 2)

    out = {
        "bytes": len(buf),
        "crc32_gbps": rate(lambda b: zlib.crc32(b)),
        "adler32_gbps": rate(lambda b: zlib.adler32(b)),
        "blake2b_gbps": rate(
            lambda b: hashlib.blake2b(b, digest_size=DIGEST_SIZE).digest()),
        "sha256_gbps": rate(lambda b: hashlib.sha256(b).digest()),
        "xxh3_64_gbps": 0.0,
        "xxh3_128_gbps": 0.0,
    }
    if _xxhash is not None:
        out["xxh3_64_gbps"] = rate(lambda b: _xxhash.xxh3_64_intdigest(b))
        out["xxh3_128_gbps"] = rate(
            lambda b: _xxhash.xxh3_128_hexdigest(b))
    out["fragment_algo"] = fragment_checksum(buf[:16])[0]
    out["digest_algo"] = digest_algo()
    return out
