"""Critical-path analysis over pair-lifecycle spans (docs/observability.md).

The telemetry plane answers "where did every byte go"; this module
answers "why did THIS delivery take THIS long".  Input is the merged
cluster span-event list (``utils/telemetry.fold_spans`` — each event a
``{"span", "phase", "t_ms", "node", ...}`` dict recorded where the
transition actually happened); output is

- per-span **phase chains** (``build_spans``): the last event per phase,
  clock-aligned when per-node offsets are supplied, with per-segment
  durations bucketed into the attribution vocabulary — ``queue``
  (planned→dispatched: command propagation + sender queueing), ``wire``
  (dispatched→wire-complete, first-byte latency included), ``verify``
  (→verified), ``stage`` (→staged), ``ack`` (→acked ack propagation +
  leader handling), ``flip`` (→flipped, swap/rollout pairs);
- the **critical chain** (``critical_chain``): walking back from the
  last-finishing span, each predecessor is the latest span finishing at
  or before the current one's start — the chain of blocking spans whose
  windows (plus the idle gaps between them, reported separately as the
  honest "unattributed" residual) tile the achieved TTD;
- the **attribution summary** (``analyze``): chain phase totals, the
  predicted-vs-achieved gap decomposed per phase and per link, and the
  reconciliation fraction the TTD_MATRIX ``attribution`` row is judged
  on.

Phase names are the one canonical tuple ``telemetry.SPAN_PHASES``; the
tier-1 static drift check pins each to a live ``span_event`` call site.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from . import telemetry

# Re-exported so consumers (cli/trace.py flow arrows, the drift check)
# have one import for the vocabulary.
PHASES = telemetry.SPAN_PHASES

# segment = (from_phase, to_phase, attribution bucket)
SEGMENTS = (
    ("planned", "dispatched", "queue"),
    ("dispatched", "first_byte", "wire"),
    ("first_byte", "wire_complete", "wire"),
    ("wire_complete", "verified", "verify"),
    ("verified", "staged", "stage"),
    ("staged", "acked", "ack"),
    ("acked", "flipped", "flip"),
)

BUCKETS = ("queue", "wire", "verify", "stage", "ack", "flip")


def build_spans(events, offsets: Optional[dict] = None) -> Dict[str, dict]:
    """Events → ``{span: {"phases": {phase: t_ms}, ...attrs}}``.

    The LAST event per (span, phase) wins — a re-delivery (digest
    mismatch, salvage) overwrites its earlier attempt's timestamps,
    which is the honest reading: the chain then shows the attempt that
    actually completed.  ``offsets`` is the per-node clock-offset map
    (leader clock minus node clock, ms — the RUN_REPORT's
    ``clock_offsets_ms``); each event shifts by its recording node's
    offset so cross-node segments don't go negative on skewed hosts."""
    offsets = offsets or {}
    out: Dict[str, dict] = {}
    for ev in events or ():
        span = ev.get("span")
        phase = ev.get("phase")
        t = ev.get("t_ms")
        if not span or phase not in PHASES or not isinstance(
                t, (int, float)):
            continue
        t = float(t) + float(offsets.get(str(ev.get("node", "")), 0.0))
        rec = out.setdefault(str(span), {"phases": {}})
        rec["phases"][phase] = t
        for k in ("src", "dest", "layer", "job", "bytes", "codec",
                  "shard", "version", "parent"):
            if k in ev:
                rec[k] = ev[k]
    for span, rec in out.items():
        ph = rec["phases"]
        order = [p for p in PHASES if p in ph]
        if order:
            rec["start_ms"] = min(ph[p] for p in order)
            rec["end_ms"] = max(ph[p] for p in order)
        if "dest" not in rec or "layer" not in rec:
            # The deterministic id IS (dest, layer) — recover them for
            # events recorded without the fields.
            try:
                d, l = span.split(".", 1)
                rec.setdefault("dest", int(d))
                rec.setdefault("layer", int(l))
            except ValueError:
                pass
    return out


def phase_durations(rec: dict) -> Dict[str, float]:
    """One span's segment durations (seconds), bucketed.  Missing
    intermediate phases collapse: each present phase's segment runs
    from the PREVIOUS present phase, filed under the later phase's
    bucket — the chain's buckets always tile the span window exactly."""
    ph = rec.get("phases") or {}
    present = [p for p in PHASES if p in ph]
    out: Dict[str, float] = {}
    bucket_of = {to: b for _, to, b in SEGMENTS}
    for prev, cur in zip(present, present[1:]):
        dt = max(0.0, (ph[cur] - ph[prev]) / 1000.0)
        b = bucket_of.get(cur)
        if b is not None:
            out[b] = out.get(b, 0.0) + dt
    return out


def critical_chain(spans: Dict[str, dict],
                   terminal: str = "acked") -> List[str]:
    """The blocking chain, latest-first walk returned earliest-first.

    Anchor: the span whose ``terminal`` phase (falling back to its last
    present phase) is LATEST — the delivery that finished the run.
    Predecessor step: among spans ending at or before the current
    span's start, the one ending latest — the span whose completion
    unblocked (or most nearly abutted) the current one; ties break by
    span id for determinism.  Stops when no span ends earlier."""

    def end_of(rec):
        ph = rec.get("phases") or {}
        if terminal in ph:
            return ph[terminal]
        return rec.get("end_ms", float("-inf"))

    todo = {s: rec for s, rec in spans.items()
            if rec.get("phases") and rec.get("start_ms") is not None}
    if not todo:
        return []
    chain: List[str] = []
    cur = max(sorted(todo), key=lambda s: end_of(todo[s]))
    while cur is not None:
        chain.append(cur)
        start = todo[cur]["start_ms"]
        best, best_end = None, float("-inf")
        for s, rec in sorted(todo.items()):
            if s in chain:
                continue
            e = end_of(rec)
            if e <= start and e > best_end:
                best, best_end = s, e
        cur = best
    chain.reverse()
    return chain


def analyze(events, ttd_s: Optional[float] = None,
            predicted_s: Optional[float] = None,
            offsets: Optional[dict] = None,
            spans: Optional[Dict[str, dict]] = None) -> dict:
    """The full attribution: build spans, walk the chain, total the
    buckets, decompose the predicted-vs-achieved gap, split the wire
    time per link.  Returns a JSON-ready dict (the RUN_REPORT's
    ``critical_path`` section).  ``spans``: a prebuilt ``build_spans``
    table — callers that also render waterfalls pass it so the event
    list is grouped once, not twice."""
    if spans is None:
        spans = build_spans(events, offsets=offsets)
    chain_ids = critical_chain(spans)
    chain: List[dict] = []
    phase_totals: Dict[str, float] = {}
    per_link: Dict[str, float] = {}
    idle_s = 0.0
    prev_end = None
    for sid in chain_ids:
        rec = spans[sid]
        durs = phase_durations(rec)
        for b, v in durs.items():
            phase_totals[b] = phase_totals.get(b, 0.0) + v
        if "src" in rec and "dest" in rec:
            key = f"{rec['src']}->{rec['dest']}"
            per_link[key] = round(
                per_link.get(key, 0.0) + durs.get("wire", 0.0), 4)
        if prev_end is not None:
            idle_s += max(0.0, (rec["start_ms"] - prev_end) / 1000.0)
        prev_end = max(prev_end or rec["end_ms"], rec["end_ms"])
        chain.append({
            "span": sid,
            "dest": rec.get("dest"), "layer": rec.get("layer"),
            "src": rec.get("src"), "job": rec.get("job", ""),
            "start_ms": round(rec["start_ms"], 1),
            "end_ms": round(rec["end_ms"], 1),
            "phases_s": {b: round(v, 4) for b, v in sorted(durs.items())},
        })
    window_s = ((chain[-1]["end_ms"] - chain[0]["start_ms"]) / 1000.0
                if chain else 0.0)
    attributed_s = sum(phase_totals.values())
    out = {
        "spans_seen": len(spans),
        "chain": chain,
        "phase_totals_s": {b: round(phase_totals.get(b, 0.0), 4)
                           for b in BUCKETS if b in phase_totals},
        "idle_s": round(idle_s, 4),
        "window_s": round(window_s, 4),
        "attributed_s": round(attributed_s, 4),
        "per_link_wire_s": dict(sorted(per_link.items())),
    }
    if window_s > 0:
        # The honest residual: wall the chain's phases can't explain —
        # the idle gaps between chained spans (re-plan latency, solver
        # waits) — as a fraction of the chain window.
        out["unattributed_frac"] = round(
            max(0.0, window_s - attributed_s) / window_s, 4)
    if ttd_s:
        out["ttd_s"] = round(ttd_s, 4)
        out["coverage_frac"] = round(window_s / ttd_s, 4)
    if predicted_s is not None:
        out["predicted_s"] = round(predicted_s, 4)
        if ttd_s:
            out["gap_s"] = round(ttd_s - predicted_s, 4)
            # Decompose the gap: phases the model never priced, plus
            # the wire's own excess over the modeled transfer time,
            # plus inter-span idle.  Signed — a wire FASTER than
            # modeled shows as negative excess, honestly.
            gap = {b: round(phase_totals.get(b, 0.0), 4)
                   for b in BUCKETS
                   if b != "wire" and phase_totals.get(b)}
            gap["wire_excess"] = round(
                phase_totals.get("wire", 0.0) - predicted_s, 4)
            gap["idle"] = round(idle_s, 4)
            out["gap_attribution_s"] = gap
    return out


def waterfall_lines(spans: Dict[str, dict], width: int = 40,
                    limit: int = 24, job: Optional[str] = None
                    ) -> List[str]:
    """A fixed-width text waterfall (the per-job md rendering): one bar
    per span, offset/scaled to the observed window.  ``job`` filters to
    one dissemination job's spans ("" = the base run); ``limit`` keeps
    a fleet-scale run's table readable (dropped rows are announced)."""
    rows = [(sid, rec) for sid, rec in sorted(spans.items())
            if rec.get("start_ms") is not None
            and (job is None or rec.get("job", "") == job)]
    if not rows:
        return []
    t0 = min(rec["start_ms"] for _, rec in rows)
    t1 = max(rec["end_ms"] for _, rec in rows)
    span_ms = max(t1 - t0, 1e-9)
    rows.sort(key=lambda kv: (kv[1]["start_ms"], kv[0]))
    shown = rows[:max(1, int(limit))]
    lines = []
    for sid, rec in shown:
        lo = int((rec["start_ms"] - t0) / span_ms * width)
        hi = max(lo + 1, int((rec["end_ms"] - t0) / span_ms * width))
        bar = " " * lo + "#" * (hi - lo)
        dur = (rec["end_ms"] - rec["start_ms"]) / 1000.0
        lines.append(f"`{bar:<{width}}` {sid} "
                     f"({rec.get('src', '?')}→{rec.get('dest', '?')}, "
                     f"{dur:.3f}s)")
    if len(rows) > len(shown):
        lines.append(f"… {len(rows) - len(shown)} more spans not shown")
    return lines
