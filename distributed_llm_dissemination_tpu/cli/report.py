"""One-command run report: ``RUN_REPORT.{json,md}`` (docs/observability.md).

Every run already emits the raw material — the leader's folded cluster
telemetry (``runtime/leader.cluster_telemetry``), the timer records, the
integrity/failover counters — but until now each harness hand-rolled its
own tables from ad-hoc greps.  This module is the ONE renderer: a typed
report dict with a provenance hash, built either

- **live**, from a leader object at the end of a run
  (``build_from_leader`` — the ``cli.main -report`` path; a promoted
  standby's adopted leader works identically, so a failover run still
  yields a complete report), or
- **offline**, from merged per-node JSON logs
  (``build_from_records`` — the ``python -m ...cli.report logs/`` path,
  reading the leader's end-of-run "cluster telemetry" dump).

The per-(src, dest) link table's ``delivered_bytes`` are the receiver
runtime's COMMITTED bytes (claims actually landed — duplicates count
nothing), so in a clean run they reconcile byte-exactly with the
delivered layer bytes of the goal state; the dual-backend test asserts
exactly that.

Usage:
    python -m distributed_llm_dissemination_tpu.cli.report logs/ -o RUN_REPORT
    python -m ...cli.main -id 0 -f conf.json -m 3 -report RUN_REPORT
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time
from typing import Iterable, List, Optional

from ..utils.provenance import harness_hash

SCHEMA = "dld-run-report/v1"

# Link-table column order (md rendering); missing fields render "—".
_LINK_COLS = (
    "delivered_bytes", "rx_bytes", "rx_frames", "rx_stripe_frames",
    "rx_placed_frames", "tx_bytes", "tx_frames", "tx_stripe_frames",
    "wire_s", "verify_s", "place_s",
    "crc_drops", "nacks", "retransmit_bytes",
)


def report_hash(report: dict) -> str:
    """Deterministic content hash of the report (minus the hash field
    itself) — the provenance stamp TTD_MATRIX rows embed so a row's
    event counts are traceable to exactly one report artifact."""
    doc = {k: v for k, v in report.items() if k != "provenance"}
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _finish(report: dict) -> dict:
    report["provenance"] = report_hash(report)
    return report


def _split_counters(counters: dict) -> dict:
    """Group cluster counters by plane prefix (integrity./failover./
    telemetry.) — the report sections docs/integrity.md and
    docs/failover.md point their readers at."""
    out: dict = {"integrity": {}, "failover": {}, "telemetry": {},
                 "other": {}}
    for name, v in sorted((counters or {}).items()):
        plane, _, rest = name.partition(".")
        if plane in ("integrity", "failover", "telemetry") and rest:
            out[plane][rest] = v
        else:
            out["other"][name] = v
    return out


def _link_rows(links: dict) -> List[dict]:
    rows = []
    for key, fields in sorted(
            (links or {}).items(),
            key=lambda kv: (kv[1].get("src", 0), kv[1].get("dest", 0))):
        row = dict(fields)
        if "src" not in row or "dest" not in row:
            base, _, job = key.partition("#")
            try:
                s, d = base.split("->", 1)
                row["src"], row["dest"] = int(s), int(d)
            except ValueError:
                continue
            if job:
                row["job"] = job
        wire_s = row.get("wire_s") or 0.0
        delivered = row.get("delivered_bytes") or 0
        if wire_s > 0 and delivered:
            # Goodput over the link's summed wire-wait (thread-time:
            # concurrent stripes overlap, so this can exceed what one
            # socket could carry — that is the point of striping).
            row["wire_gbps"] = round(delivered / wire_s / 1e9, 3)
        rows.append(row)
    return rows


def build(cluster: dict, ttd_s: Optional[float] = None,
          ttft_s: Optional[float] = None,
          predicted_s: Optional[float] = None,
          solve_ms: Optional[float] = None,
          extra: Optional[dict] = None) -> dict:
    """Assemble the report from a folded cluster-telemetry table (the
    shape ``runtime/leader.cluster_telemetry`` returns)."""
    from ..utils import critical_path as cp

    nodes = cluster.get("nodes") or {}
    counters = cluster.get("counters") or {}
    offsets = {}
    phases: dict = {}
    threads_by_plane: dict = {}
    for node_id, snap in sorted(nodes.items(), key=lambda kv: str(kv[0])):
        gauges = snap.get("gauges") or {}
        if "clock_offset_ms" in gauges:
            offsets[str(node_id)] = gauges["clock_offset_ms"]
        for name, v in gauges.items():
            if name.startswith("phase."):
                phases.setdefault(str(node_id), {})[
                    name[len("phase."):]] = v
            elif name.startswith("threads_"):
                # Thread census (utils/threads.py): live thread counts
                # by plane per node — the audit trail that the bounded
                # data pools actually bound (docs/transport.md).
                threads_by_plane.setdefault(str(node_id), {})[
                    name[len("threads_"):]] = int(v)
    # Job plane (docs/service.md): rows tagged "src->dest#job" are the
    # per-job ADDITIVE split of the base rows — they render in their own
    # section so the base table still reconciles byte-exactly.
    all_rows = _link_rows(cluster.get("links") or {})
    base_rows = [r for r in all_rows if "job" not in r]
    job_rows: dict = {}
    for r in all_rows:
        if "job" in r:
            job_rows.setdefault(r["job"], []).append(r)
    report = {
        "schema": SCHEMA,
        "generated_unix_ms": int(time.time() * 1000),
        "harness_hash": harness_hash(),
        "ttd_s": round(ttd_s, 6) if ttd_s is not None else None,
        "ttft_s": round(ttft_s, 6) if ttft_s is not None else None,
        "predicted_s": (round(predicted_s, 6)
                        if predicted_s is not None else None),
        "solve_ms": round(solve_ms, 3) if solve_ms is not None else None,
        "links": base_rows,
        "job_links": job_rows,
        "counters": dict(sorted(counters.items())),
        "planes": _split_counters(counters),
        "phases_ms_by_node": phases,
        "threads_by_plane": threads_by_plane,
        "clock_offsets_ms": offsets,
        "nodes": {str(n): {"counters": snap.get("counters") or {},
                           "gauges": snap.get("gauges") or {}}
                  for n, snap in sorted(nodes.items(),
                                        key=lambda kv: str(kv[0]))},
    }
    # Causal observability (docs/observability.md): the merged span
    # timeline → the critical-path/attribution section + per-job
    # waterfalls; the leader-derived fleet health timeline verbatim.
    spans = cluster.get("spans") or []
    if spans:
        span_recs = cp.build_spans(spans, offsets=offsets)
        report["critical_path"] = cp.analyze(
            spans, ttd_s=ttd_s, predicted_s=predicted_s,
            offsets=offsets, spans=span_recs)
        jobs_seen = sorted({rec.get("job", "")
                            for rec in span_recs.values()})
        # Keyed by the job id VERBATIM ("" = the base run) — a job
        # literally named "base" must not collide with the base run's
        # waterfall; the renderer labels "" as "base run".
        report["span_waterfalls"] = {
            j: cp.waterfall_lines(span_recs, job=j) for j in jobs_seen}
    health = cluster.get("health") or {}
    if health.get("events") or health.get("intervals"):
        report["health"] = {
            "events": health.get("events") or [],
            "intervals": health.get("intervals") or [],
        }
    if extra:
        report.update(extra)
    return _finish(report)


def build_from_leader(leader, ttd_s: Optional[float] = None,
                      ttft_s: Optional[float] = None,
                      extra: Optional[dict] = None) -> dict:
    """The live path: fold the leader's cluster table now and stamp the
    run's headline timings.  Works on an ADOPTED leader too — the shadow
    replication carried the dead predecessor's table, and every live
    node's cumulative reports refreshed it since."""
    pred_ms = getattr(leader, "predicted_ttd_ms", 0)
    # Admitted-job table (docs/service.md): rides the report whenever
    # the leader ran as a service (empty single-run tables add nothing).
    jobs = getattr(leader, "jobs", None)
    table = jobs.table() if jobs is not None else {}
    if table:
        extra = dict(extra or {})
        extra.setdefault("jobs", table)
    # Per-dest wire-vs-decoded byte columns (docs/codec.md): the link
    # table reconciles against WIRE bytes; the decoded side is its own
    # column, never conflated.
    dest_fn = getattr(leader, "dest_bytes_table", None)
    if dest_fn is not None:
        dests = dest_fn()
        if dests:
            extra = dict(extra or {})
            extra.setdefault("dests", dests)
    return build(
        leader.cluster_telemetry(), ttd_s=ttd_s, ttft_s=ttft_s,
        predicted_s=(pred_ms / 1000.0) if pred_ms else None,
        solve_ms=getattr(leader, "solve_ms", 0.0) or None,
        extra=extra)


def build_from_records(records: Iterable[dict],
                       extra: Optional[dict] = None) -> dict:
    """The offline path: reconstruct the report from merged per-node
    JSON logs — the leader's end-of-run "cluster telemetry" dump (last
    one wins: a failover run's adopted leader re-dumps), the timer
    records, and each node's clock-offset estimate."""
    from .trace import clock_offsets

    records = list(records)
    cluster: dict = {"nodes": {}, "counters": {}, "links": {}}
    t_start = t_stop = None
    ttft_s = predicted_s = solve_ms = None
    # The one scanner of "clock offset estimated" records — shared with
    # the Perfetto aligner, so the record shape has a single consumer.
    offsets = {str(n): off for n, off in clock_offsets(records).items()}
    for rec in records:
        msg = rec.get("message")
        if msg == "cluster telemetry":
            links = rec.get("links") or {}
            counters = rec.get("counters") or {}
            gauges = rec.get("gauges") or {}
            cluster = {
                "nodes": {n: {"counters": {}, "gauges": g}
                          for n, g in gauges.items()},
                "counters": counters,
                "links": links,
                # The dump carries the merged span timeline + health
                # view (docs/observability.md) — the offline report's
                # critical-path and health sections read them back.
                "spans": rec.get("spans") or [],
                "health": rec.get("health") or {},
            }
        elif msg == "timer start":
            t_start = rec.get("time")
        elif msg == "timer stop: startup":
            t_stop = rec.get("time")
        elif msg == "timer stop: first token":
            ttft_s = rec.get("seconds")
        elif msg == "Predicted time to deliver":
            predicted_s = rec.get("seconds")
            solve_ms = rec.get("solve_ms")
    ttd_s = ((t_stop - t_start) / 1000.0
             if t_start is not None and t_stop is not None else None)
    for node, off in offsets.items():
        cluster["nodes"].setdefault(
            node, {"counters": {}, "gauges": {}})
        cluster["nodes"][node].setdefault("gauges", {})[
            "clock_offset_ms"] = off
    return build(cluster, ttd_s=ttd_s, ttft_s=ttft_s,
                 predicted_s=predicted_s, solve_ms=solve_ms, extra=extra)


# ------------------------------------------------------------- rendering


def _fmt(v) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:g}"
    return str(v)


def _fmt_unit(v, unit: str) -> str:
    return "—" if v is None else f"{_fmt(v)}{unit}"


def render_md(report: dict) -> str:
    lines = [
        "# Run report",
        "",
        f"Schema `{report['schema']}` · harness `{report['harness_hash']}`"
        f" · provenance `{report.get('provenance', '?')}`",
        "",
        "| TTD | TTFT | predicted (mode 3) | solve |",
        "|---|---|---|---|",
        f"| {_fmt_unit(report.get('ttd_s'), 's')} "
        f"| {_fmt_unit(report.get('ttft_s'), 's')} "
        f"| {_fmt_unit(report.get('predicted_s'), 's')} "
        f"| {_fmt_unit(report.get('solve_ms'), 'ms')} |",
        "",
    ]
    links = report.get("links") or []
    if links:
        lines += [
            "## Per-link flight recorder",
            "",
            "`delivered` is the dest runtime's COMMITTED bytes (the "
            "byte-exact reconciliation number); `wire/verify/place` are "
            "the link's stall seconds (thread-time — concurrent stripes "
            "overlap); `stripe occupancy` is stripe frames over total "
            "frames on the tx side.",
            "",
            "| link | delivered | wire GB/s | rx frames (striped/placed)"
            " | tx frames (striped) | wire s | verify s | place s "
            "| drops | NACKs | retx bytes |",
            "|---|---|---|---|---|---|---|---|---|---|---|",
        ]
        for row in links:
            lines.append(
                f"| {row['src']}→{row['dest']} "
                f"| {_fmt(row.get('delivered_bytes'))} "
                f"| {_fmt(row.get('wire_gbps'))} "
                f"| {_fmt(row.get('rx_frames'))} "
                f"({_fmt(row.get('rx_stripe_frames', 0))}/"
                f"{_fmt(row.get('rx_placed_frames', 0))}) "
                f"| {_fmt(row.get('tx_frames'))} "
                f"({_fmt(row.get('tx_stripe_frames', 0))}) "
                f"| {_fmt(row.get('wire_s'))} "
                f"| {_fmt(row.get('verify_s'))} "
                f"| {_fmt(row.get('place_s'))} "
                f"| {_fmt(row.get('crc_drops', 0))} "
                f"| {_fmt(row.get('nacks', 0))} "
                f"| {_fmt(row.get('retransmit_bytes', 0))} |")
        lines.append("")
    dests = report.get("dests") or {}
    if dests:
        lines += [
            "## Per-dest wire vs decoded bytes (docs/codec.md)",
            "",
            "`wire` is what crossed the network for each delivered "
            "pair (the ENCODED size for quantized transfers — the "
            "column the link table reconciles against); `decoded` is "
            "what the dest materializes.  Two columns on purpose: the "
            "two are never conflated.",
            "",
            "| dest | wire bytes | decoded bytes | layers (quantized) |",
            "|---|---|---|---|",
        ]
        for dest, row in sorted(dests.items(), key=lambda kv: kv[0]):
            lines.append(
                f"| {dest} | {_fmt(row.get('wire_bytes'))} "
                f"| {_fmt(row.get('decoded_bytes'))} "
                f"| {_fmt(row.get('layers'))} "
                f"({_fmt(row.get('codec_layers', 0))}) |")
        lines.append("")
    jobs = report.get("jobs") or {}
    job_links = report.get("job_links") or {}
    if jobs or job_links:
        lines += [
            "## Dissemination jobs (docs/service.md)",
            "",
            "Per-job link rows are an ADDITIVE split of the base table "
            "above (frames serving a job file on both).",
            "",
        ]
        for jid, row in sorted(jobs.items()):
            lines.append(
                f"- `{jid}`: {row.get('State')} "
                f"(priority {row.get('Priority')}, kind "
                f"{row.get('Kind')}, {row.get('RemainingPairs')}/"
                f"{row.get('TotalPairs')} pairs remaining, "
                f"{row.get('ResolvedAtAdmit')} resolved at admit, "
                f"{row.get('DroppedPairs')} dropped)")
        for jid, rows in sorted(job_links.items()):
            delivered = sum(r.get("delivered_bytes") or 0 for r in rows)
            per = ", ".join(
                f"{r['src']}→{r['dest']}: "
                f"{_fmt(r.get('delivered_bytes', 0))}B"
                for r in rows)
            lines.append(f"- `{jid}` links ({delivered} B delivered): "
                         f"{per}")
        lines.append("")
    cp = report.get("critical_path") or {}
    if cp.get("chain"):
        lines += [
            "## Critical path (docs/observability.md)",
            "",
            "The chain of blocking delivery spans whose windows tile "
            "the achieved TTD; per-phase totals attribute the "
            "predicted-vs-achieved gap (`idle` is the honest residual "
            "— wall between chained spans no live span explains).",
            "",
            f"Window {_fmt_unit(cp.get('window_s'), 's')} over "
            f"{len(cp['chain'])} blocking span(s) of "
            f"{cp.get('spans_seen')} seen · attributed "
            f"{_fmt_unit(cp.get('attributed_s'), 's')} · idle "
            f"{_fmt_unit(cp.get('idle_s'), 's')} · TTD coverage "
            f"{_fmt(cp.get('coverage_frac'))} · unattributed frac "
            f"{_fmt(cp.get('unattributed_frac'))}",
            "",
            "| phase | seconds |",
            "|---|---|",
        ]
        for b, v in sorted((cp.get("phase_totals_s") or {}).items()):
            lines.append(f"| {b} | {_fmt(v)} |")
        lines.append("")
        gap = cp.get("gap_attribution_s") or {}
        if gap:
            lines += [
                f"Predicted {_fmt_unit(cp.get('predicted_s'), 's')} vs "
                f"achieved {_fmt_unit(cp.get('ttd_s'), 's')} — gap "
                f"{_fmt_unit(cp.get('gap_s'), 's')} decomposed: "
                + ", ".join(f"{k}={_fmt(v)}s"
                            for k, v in sorted(gap.items())),
                "",
            ]
        per_link = cp.get("per_link_wire_s") or {}
        if per_link:
            lines += ["Per-link wire seconds on the chain: "
                      + ", ".join(f"{k}: {_fmt(v)}s"
                                  for k, v in sorted(per_link.items())),
                      ""]
        for entry in cp["chain"]:
            ph = ", ".join(f"{k}={_fmt(v)}s"
                           for k, v in (entry.get("phases_s") or {}).items())
            lines.append(
                f"- span `{entry['span']}` "
                f"({_fmt(entry.get('src'))}→{_fmt(entry.get('dest'))}, "
                f"layer {_fmt(entry.get('layer'))}"
                + (f", job `{entry['job']}`" if entry.get("job") else "")
                + f"): {ph}")
        lines.append("")
    waterfalls = report.get("span_waterfalls") or {}
    for jname, rows in sorted(waterfalls.items()):
        if not rows:
            continue
        lines += [f"### Delivery waterfall — "
                  f"{f'job `{jname}`' if jname else 'base run'}",
                  ""]
        lines += [f"- {row}" for row in rows]
        lines.append("")
    health = report.get("health") or {}
    if health.get("events"):
        lines += [
            "## Fleet health timeline (docs/observability.md)",
            "",
            "Straggler/recovery events derived from per-interval "
            "deltas of the cumulative metrics reports, with onset "
            "timestamps (`-watch` printed these live).",
            "",
        ]
        for ev in health["events"]:
            lines.append(
                f"- t={_fmt(ev.get('t_ms'))}ms `{ev.get('kind')}` "
                f"link {ev.get('link')} achieved "
                f"{_fmt(ev.get('achieved_bps'))} B/s vs modeled "
                f"{_fmt(ev.get('modeled_bps'))} B/s "
                f"(frac {_fmt(ev.get('frac'))})")
        lines.append("")
    planes = report.get("planes") or {}
    for plane, doc in (("integrity", "docs/integrity.md"),
                       ("failover", "docs/failover.md")):
        counts = planes.get(plane) or {}
        if counts:
            lines += [f"## {plane.capitalize()} events ({doc})", ""]
            lines += [f"- `{k}`: {v}" for k, v in sorted(counts.items())]
            lines.append("")
    offsets = report.get("clock_offsets_ms") or {}
    if offsets:
        lines += [
            "## Clock offsets (leader clock minus node clock)",
            "",
            "Estimated at announce time from the TimeSync round trip; "
            "`cli/trace.py` applies these so multi-host Perfetto "
            "timelines line up.",
            "",
        ]
        lines += [f"- node {n}: {_fmt(v)} ms"
                  for n, v in sorted(offsets.items())]
        lines.append("")
    phases = report.get("phases_ms_by_node") or {}
    if phases:
        lines += ["## Phase totals by node (ms, thread-time sums)", ""]
        for node, per in sorted(phases.items()):
            items = ", ".join(f"{k}={_fmt(v)}"
                              for k, v in sorted(per.items()))
            lines.append(f"- node {node}: {items}")
        lines.append("")
    threads = report.get("threads_by_plane") or {}
    if threads:
        lines += [
            "## Threads by plane (live census at last report)",
            "",
            "Data-plane threads are bounded by the worker pools "
            "(utils/threads.py; docs/transport.md) — connection count "
            "never implies thread count.",
            "",
        ]
        for node, per in sorted(threads.items()):
            items = ", ".join(f"{k}={v}"
                              for k, v in sorted(per.items()))
            lines.append(f"- node {node}: {items}")
        lines.append("")
    other = (report.get("planes") or {}).get("other") or {}
    if other:
        lines += ["## Other counters", ""]
        lines += [f"- `{k}`: {v}" for k, v in sorted(other.items())]
        lines.append("")
    return "\n".join(lines)


def write_report(report: dict, out: str) -> dict:
    """Write ``<out>.json`` and ``<out>.md`` (an ``out`` ending in
    ``.json``/``.md`` is treated as the prefix; a directory gets
    ``RUN_REPORT`` inside it).  Returns {json, md, provenance}."""
    prefix = out
    if os.path.isdir(out):
        prefix = os.path.join(out, "RUN_REPORT")
    elif prefix.endswith((".json", ".md")):
        prefix = os.path.splitext(prefix)[0]
    json_path, md_path = prefix + ".json", prefix + ".md"
    with open(json_path, "w") as f:
        json.dump(report, f, indent=1)
    with open(md_path, "w") as f:
        f.write(render_md(report))
    return {"json": json_path, "md": md_path,
            "provenance": report.get("provenance")}


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(prog="report", description=__doc__)
    p.add_argument("paths", nargs="+", help="log files or directories")
    p.add_argument("-o", "--output", default="RUN_REPORT",
                   help="output prefix (writes <prefix>.json and "
                        "<prefix>.md)")
    args = p.parse_args(argv)
    from .collect_logs import iter_records

    report = build_from_records(iter_records(args.paths))
    paths = write_report(report, args.output)
    print(f"run report -> {paths['json']} / {paths['md']} "
          f"(provenance {paths['provenance']})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
