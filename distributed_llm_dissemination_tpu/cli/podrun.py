"""Single-controller pod driver: one process, whole mesh, fabric data plane.

The deployment shape the reference cannot express: its data plane is one OS
process per node streaming TCP (``/root/reference/cmd/main.go:113-146``,
``distributor/transport.go:267-274``).  On a TPU pod under a single
controller, one Python process addresses every chip — so this driver hosts
ALL the topology's nodes in-process (control plane on the in-memory
transport), maps each node to a pipeline stage of the configured device
mesh, and lets every scheduled layer transfer ride the device fabric
(``parallel/fabric.py``): seeders upload their planned byte ranges to their
own stage's HBM, destinations ingest them over ICI.  No layer byte ever
touches a socket.

    python -m distributed_llm_dissemination_tpu.cli.podrun -f conf.json -m 3

Prints the reference's "Time to deliver" (cmd/main.go:173-181) and one
machine-readable JSON summary line.  For multi-process/multi-host
deployments use ``cli.main`` (TCP data plane) — the SPMD fabric across
processes needs ``jax.distributed`` mesh formation; see the README runbook.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict

from ..core import config as cfg
from ..runtime import (
    FlowRetransmitLeaderNode,
    FlowRetransmitReceiverNode,
    LeaderNode,
    Node,
    PullRetransmitLeaderNode,
    ReceiverNode,
    RetransmitLeaderNode,
    RetransmitReceiverNode,
)
from ..transport.inmem import InmemTransport
from ..utils import logging as ulog

_LEADERS = {
    0: LeaderNode,
    1: RetransmitLeaderNode,
    2: PullRetransmitLeaderNode,
    3: FlowRetransmitLeaderNode,
}
_RECEIVERS = {
    0: ReceiverNode,
    1: RetransmitReceiverNode,
    2: RetransmitReceiverNode,
    3: FlowRetransmitReceiverNode,
}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="podrun", description=__doc__,
                                prefix_chars="-")
    p.add_argument("-f", type=str, required=True,
                   help="filename of topology JSON file (Mesh section "
                        "required; Fabric implied)")
    p.add_argument("-m", type=int, default=3, choices=[0, 1, 2, 3],
                   help="0: naive, 1: retransmit, 2: pull, 3: max-flow")
    p.add_argument("-boot", type=str, default="",
                   help="model config name: boot the model from the "
                        "fabric-delivered blobs and report TTFT")
    p.add_argument("-gen", type=int, default=0,
                   help="after a servable pipeline boot, greedy-decode "
                        "this many tokens across the pod (KV-cached)")
    p.add_argument("-report", type=str, default="",
                   help="write RUN_REPORT.{json,md} at this path/prefix "
                        "when the run completes (cli/report.py)")
    p.add_argument("-v", action="store_true", help="output debug messages")
    return p


def fabric_bandwidths(conf: cfg.Config) -> Dict[int, int]:
    """Per-node bandwidths for the mode-3 flow solve on a fabric.

    With ``Mesh.IciBW`` set, every node plans against the stage's ICI
    capacity — the device plane carries the bytes, so the NIC is not in
    the path; per-source LimitRates still cap seeders.  Without it, the
    configured NetworkBW is used as-is."""
    ici = conf.mesh.ici_bw if conf.mesh is not None else 0
    return {nc.id: (ici if ici > 0 else nc.network_bw) for nc in conf.nodes}


def run_pod(conf: cfg.Config, mode: int = 3, boot: str = "",
            timeout: float = 600.0, gen: int = 0,
            on_delivered=None, report: str = "") -> Dict[str, float]:
    """Drive one full pod dissemination; returns the timing summary.

    Callable from tests/benchmarks; the fabric and placement span every
    configured node (seeders contribute from their own stages)."""
    if conf.mesh is None:
        raise SystemExit("podrun needs a Mesh section in the config")
    from ..parallel.multihost import honor_jax_platforms

    honor_jax_platforms()
    from ..parallel.fabric import FabricPlane
    from ..parallel.mesh import fabric_placement, mesh_from_conf

    mesh = mesh_from_conf(conf.mesh)
    node_ids = [nc.id for nc in conf.nodes]
    placement = fabric_placement(node_ids, conf.assignment, mesh,
                                 conf.mesh.pipeline_axis)
    fabric = FabricPlane()
    ulog.log.info("pod fabric up",
                  mesh={n: s for n, s in zip(conf.mesh.axis_names,
                                             conf.mesh.axis_sizes)},
                  stages={str(n): s for n, s in placement.node_to_stage.items()})

    transports = {
        nc.id: InmemTransport(str(nc.id),
                              addr_registry={i: str(i) for i in node_ids})
        for nc in conf.nodes
    }
    leader_conf = cfg.get_leader_conf(conf)
    from .main import boot_config  # same validation as the per-node CLI

    boot_cfg = boot_config(boot or conf.model)

    leader = None
    receivers = []
    try:
        for nc in conf.nodes:
            layers = cfg.create_layers(nc, save_disk=False,
                                       model=conf.model,
                                       model_seed=conf.model_seed,
                                       model_codec=conf.model_codec)
            node = Node(nc.id, leader_conf.id, transports[nc.id])
            if nc.id == leader_conf.id:
                kwargs = dict(expected_nodes=set(node_ids),
                              fabric=fabric, placement=placement)
                if mode == 3:
                    leader = _LEADERS[3](node, layers, conf.assignment,
                                         fabric_bandwidths(conf),
                                         topology=conf.mesh.topology(),
                                         **kwargs)
                else:
                    leader = _LEADERS[mode](node, layers, conf.assignment,
                                            **kwargs)
                leader.boot_enabled = boot_cfg is not None
            else:
                receivers.append(_RECEIVERS[mode](
                    node, layers, fabric=fabric, placement=placement,
                    boot_cfg=boot_cfg, boot_codec=conf.model_codec,
                ))
        for r in receivers:
            r.announce()
        leader.start_distribution().get(timeout=timeout)
        t0 = time.monotonic()
        leader.ready().get(timeout=timeout)
        ttd = time.monotonic() - t0
        ulog.log.info("Time to deliver", seconds=round(ttd, 6))
        print(f"Time to deliver: {ttd:.6f}s", flush=True)
        # Executable reuse + phase attribution for THIS dissemination,
        # sampled at ready (before any boot compiles muddy the water):
        # the ttd_matrix fabric row reads these out of the summary line.
        from ..parallel import plan_cache
        from ..utils import telemetry as utelemetry
        from ..utils import trace as utrace

        plan_cache.log_stats()
        # The whole pod lives in this ONE process, so the process
        # registry IS the cluster's flight recorder: counters +
        # histograms ride the summary line (ttd_matrix embeds them in
        # its rows), and the links feed the run report below.
        tel_snap = utelemetry.snapshot()
        summary = {"mode": mode, "ttd_s": round(ttd, 6),
                   "nodes": len(node_ids), "fabric": True,
                   "collective_cache": plan_cache.stats(),
                   "plan_phases": utrace.phase_totals(),
                   "telemetry": {"counters": tel_snap.get("counters"),
                                 "hists": tel_snap.get("hists")}}
        pred_ms = getattr(leader, "predicted_ttd_ms", 0)
        if pred_ms:
            # Mode-3 plan fidelity next to the achieved TTD.
            summary["predicted_s"] = round(pred_ms / 1000.0, 6)
            summary["solve_ms"] = round(getattr(leader, "solve_ms", 0.0), 3)
        if boot_cfg is not None:
            booted = leader.boot_ready().get(timeout=timeout)
            ttft = time.monotonic() - t0
            ulog.log.info("Time to first token", seconds=round(ttft, 6))
            print(f"Time to first token: {ttft:.6f}s", flush=True)
            summary["ttft_s"] = round(ttft, 6)
            summary["boot_nodes"] = len(booted)
            # When the stage boots partition the model, the POD serves as
            # one pipelined model from the landed weights (pp_serve).
            from ..runtime.pp_serve import assemble_pp_params, pod_forward

            results = {r.node.my_id: r.boot_result for r in receivers}
            stores = {r.node.my_id: r.layers for r in receivers}
            assembled = assemble_pp_params(boot_cfg, placement, results,
                                           stores, conf.model_codec)
            served = pod_forward(boot_cfg, placement, results, stores,
                                 codec=conf.model_codec,
                                 assembled=assembled)
            if served is not None:
                _, pod_s = served
                summary["pod_forward_s"] = round(pod_s, 6)
                print(f"Pod pipelined forward: {pod_s:.6f}s", flush=True)
            if served is not None and gen > 0:
                from ..runtime.pp_serve import pod_decode

                dec = pod_decode(boot_cfg, placement, results, stores,
                                 max_new=gen, codec=conf.model_codec,
                                 assembled=assembled)
                if dec is not None:
                    toks, dec_s = dec
                    summary["pod_decode_s"] = round(dec_s, 6)
                    summary["tokens"] = [int(t) for t in toks[0]]
                    print(f"Pod decoded {toks.shape[1]} tokens: "
                          f"{summary['tokens']}", flush=True)
        if on_delivered is not None:
            # Harvest hook (cli.train): read the DELIVERED layer stores
            # while the nodes are still alive; runs before any close.
            on_delivered(leader, receivers)
        if report:
            from . import report as report_mod

            rep = report_mod.build_from_leader(
                leader, ttd_s=ttd, ttft_s=summary.get("ttft_s"))
            paths = report_mod.write_report(rep, report)
            summary["run_report"] = paths["provenance"]
            print(f"Run report: {paths['json']} "
                  f"(provenance {paths['provenance']})", flush=True)
        print(json.dumps(summary), flush=True)
        return summary
    finally:
        if leader is not None:
            leader.close()
        for r in receivers:
            r.close()
        for t in transports.values():
            t.close()


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    ulog.configure(node="pod", verbose=args.v)
    conf = cfg.read_json(args.f)
    run_pod(conf, mode=args.m, boot=args.boot, gen=max(0, args.gen),
            report=args.report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
