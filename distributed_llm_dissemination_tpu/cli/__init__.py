from .main import build_parser, main  # noqa: F401
