"""Dissemination → training bring-up, as one driveable command.

The reference stops at "bytes delivered + startup signal"; the point of
delivering weights to a TPU pod is to USE them.  This CLI closes the
training half of that loop:

    python -m distributed_llm_dissemination_tpu.cli.train \\
        -f conf/boot_tiny_4node.json -steps 20 -ckpt /ckpt/run1

1. Disseminates the topology's model blobs over the pod fabric
   (``cli.podrun`` machinery — mode 3, single controller), so the
   weights land exactly as a deployment's would;
2. assembles the delivered blobs into params (the boot path) and shards
   them onto the 5-axis training mesh (``models.sharded``);
3. runs AdamW steps (f32 moments sharded like the params, layer
   rematerialization) on a seeded self-supervised batch stream;
4. optionally checkpoints the training state (``models.train_ckpt``) —
   and ``-resume`` continues bit-exactly from a saved state, skipping
   the dissemination entirely (the weights' bytes already live in the
   optimizer trajectory).

Summary JSON on stdout: ttd/boot seconds, per-step losses, ckpt path.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from ..core import config as cfg_mod
from ..utils import logging as ulog
from ..utils.logging import log


def _params_from_dissemination(conf, timeout: float):
    """Run one mode-3 pod dissemination and return (params, cfg,
    timings) assembled from the DELIVERED blobs on the dest."""
    from ..models import serde
    from ..models.llama import CONFIGS
    from ..models.serde import params_from_blobs

    from .podrun import run_pod  # noqa: PLC0415 — heavy import path

    if conf.model.startswith("hf:"):
        from ..models.hf import config_from_dir

        mcfg = config_from_dir(conf.model[3:])
    else:
        mcfg = CONFIGS[conf.model]
    head_id = serde.head_blob_id(mcfg)
    want = set(range(head_id + 1))
    blobs: dict = {}

    def harvest(_leader, receivers):
        # Assignees only: a seeder's own copy of a blob proves nothing
        # about delivery — the training weights must be the ones the
        # dissemination actually landed.
        dests = set(conf.assignment)
        for r in receivers:
            if r.node.my_id not in dests:
                continue
            for bid, src in r.layers.items():
                if bid in want and bid not in blobs:
                    blobs[bid] = bytes(
                        src.inmem_data if src.inmem_data is not None
                        else src.read_bytes())

    t0 = time.monotonic()
    summary = dict(run_pod(conf, mode=3, timeout=timeout,
                           on_delivered=harvest))
    missing = want - set(blobs)
    if missing:
        raise SystemExit(
            f"dissemination left blobs missing: {sorted(missing)}")
    if conf.model_codec != "raw":
        import numpy as np

        from ..models import quant

        raws = {}
        for bid, data in blobs.items():
            dec = quant.decode_blob_host(mcfg, bid, data, conf.model_codec)
            raw = bytearray()
            for _nm, arr in dec.items():
                raw += np.ascontiguousarray(arr).tobytes()
            raws[bid] = bytes(raw)
        params = params_from_blobs(mcfg, raws)
    else:
        params = params_from_blobs(mcfg, blobs)
    summary["assemble_s"] = round(
        time.monotonic() - t0 - summary.get("ttd_s", 0.0), 3)
    return params, mcfg, summary


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="train")
    p.add_argument("-f", type=str, required=True,
                   help="topology JSON with a Model section")
    p.add_argument("-steps", type=int, default=10)
    p.add_argument("-lr", type=float, default=1e-3)
    p.add_argument("-batch", type=int, default=0,
                   help="global batch (default: 2*dp)")
    p.add_argument("-seq", type=int, default=0,
                   help="sequence length (default: 8*sp)")
    p.add_argument("-ckpt", type=str, default="",
                   help="save the final (params, opt) state here")
    p.add_argument("-resume", action="store_true",
                   help="restore state from -ckpt instead of "
                        "disseminating; continues the trajectory exactly")
    p.add_argument("-t", type=float, default=600.0,
                   help="dissemination timeout seconds")
    p.add_argument("-v", action="store_true")
    args = p.parse_args(argv)
    ulog.configure(node="train", verbose=args.v)

    conf = cfg_mod.read_json(args.f)
    if not conf.model:
        raise SystemExit("training needs a Model section in the topology")
    if args.resume and not args.ckpt:
        raise SystemExit("-resume needs -ckpt")

    import jax

    from ..models.llama import CONFIGS
    from ..models.sharded import (
        build_adamw_train_step,
        example_batch,
        factor_mesh_axes,
        init_adamw_state,
        make_train_mesh,
        shard_params,
    )
    from ..models.train_ckpt import restore_train_state, save_train_state

    summary: dict = {}
    if args.resume:
        if conf.model.startswith("hf:"):
            from ..models.hf import config_from_dir

            mcfg = config_from_dir(conf.model[3:])
        else:
            mcfg = CONFIGS[conf.model]
        mesh = make_train_mesh(len(jax.devices()), mcfg)
        params, opt = restore_train_state(args.ckpt, mcfg, mesh)
        summary["resumed_step"] = int(opt["step"])
        log.info("training state restored", step=summary["resumed_step"])
    else:
        params, mcfg, summary = _params_from_dissemination(conf, args.t)
        mesh = make_train_mesh(len(jax.devices()), mcfg)
        params = shard_params(params, mesh, mcfg)
        opt = init_adamw_state(params)

    step = build_adamw_train_step(mcfg, mesh, lr=args.lr)
    inputs, targets = example_batch(mcfg, mesh, batch=args.batch,
                                    seq=args.seq)
    losses = []
    t0 = time.monotonic()
    for _ in range(args.steps):
        params, opt, loss = step(params, opt, inputs, targets)
        losses.append(round(float(loss), 4))
    train_s = time.monotonic() - t0
    log.info("training ran", steps=args.steps, losses=losses)

    if args.ckpt:
        save_train_state(args.ckpt, params, opt)
        summary["ckpt"] = args.ckpt
    summary.update({
        "mesh": factor_mesh_axes(len(jax.devices()), mcfg),
        "steps": args.steps,
        "final_step": int(opt["step"]),
        "losses": losses,
        "train_s": round(train_s, 3),
    })
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
