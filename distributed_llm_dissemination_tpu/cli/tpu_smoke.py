"""Live-hardware validation: prove the compute path on the real chip.

The test suite deliberately pins the CPU backend (tests/conftest.py) so it
is deterministic and runs anywhere; ``bench.py`` measures exactly one
thing (the terminal ingest hop).  What neither covers is evidence that
the FRAMEWORK'S KERNELS are correct and fast on physical TPU silicon —
the Mosaic-compiled pallas attention kernel, the flagship model forward,
and the device ingest path all behave subtly differently on a real MXU
(bf16 truncation inside f32 matmuls, VMEM tiling, async DMA) than on the
virtual CPU mesh.

This harness runs on whatever backend is live (recorded in the report —
a CPU run is a dry pass, not evidence) and emits ONE JSON report:

- ``pallas_block_attention``: the ring-attention hot op
  (``ops/flash_attention.py``) against the pure-lax oracle on the same
  device AND a float64 host oracle.  On TPU both device paths truncate
  matmul inputs to bf16 in the MXU (expected, models run bf16), so the
  bar is relative error vs the f64 oracle — and the pallas and lax
  errors should be the SAME ORDER (a kernel bug shows up as pallas
  diverging from lax, not as shared truncation noise).
- ``flagship_forward``: ``__graft_entry__.entry()`` — compile + execute
  the reduced-depth Llama-3-8B forward, finite-logits check, steady-state
  step time.
- ``decode``: the KV-cached greedy serving loop
  (``models/generate.py``) on the flagship config — steady-state
  tokens/s, in-vocab ids, bit-identical on re-run.
- ``ingest_link``: a scaled-down ``ShardedLayerIngest`` vs one bulk
  ``device_put`` of the same bytes, paired (the full-size honest number
  is ``bench.py``'s; this is the quick in-harness cross-check).

Usage: ``python -m distributed_llm_dissemination_tpu.cli.tpu_smoke
[-o report.json] [--size-mib 64]``.  Exit 0 iff every check passed.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Callable, Dict, List


def _median_time(fn: Callable[[], object], trials: int = 5) -> float:
    import jax

    times: List[float] = []
    for _ in range(trials):
        t0 = time.monotonic()
        jax.block_until_ready(fn())
        times.append(time.monotonic() - t0)
    return sorted(times)[len(times) // 2]


def check_pallas_block_attention() -> Dict:
    import jax
    import numpy as np

    from ..ops import flash_attention as fa

    b, kvh, g, sq, t, hd = 1, 2, 4, 512, 512, 128
    rng = np.random.default_rng(0)
    qg_n = rng.standard_normal((b, kvh, g, sq, hd))
    k_n = rng.standard_normal((b, kvh, t, hd))
    v_n = rng.standard_normal((b, kvh, t, hd))
    import jax.numpy as jnp

    qg, k, v = (jnp.asarray(x, jnp.float32) for x in (qg_n, k_n, v_n))
    zero = jnp.float32(0.0)

    on_tpu = jax.default_backend() == "tpu"
    t0 = time.monotonic()
    pv_p, m_p, l_p = jax.block_until_ready(
        fa._block_attention_pallas(qg, k, v, zero, zero,
                                   interpret=not on_tpu))
    compile_s = time.monotonic() - t0
    pv_r, m_r, l_r = jax.block_until_ready(
        fa._block_attention_ref(qg, k, v, zero, zero))

    # Float64 host oracle (the causal square block at offset 0).
    s = np.einsum("bhgqd,bhtd->bhgqt", qg_n, k_n) / np.sqrt(hd)
    mask = np.tril(np.ones((sq, t), bool))
    s = np.where(mask[None, None, None], s, -1e30)
    m64 = s.max(-1)
    p = np.exp(s - m64[..., None])
    pv64 = np.einsum("bhgqt,bhtd->bhgqd", p, v_n)
    scale = float(np.abs(pv64).max())

    rel_pallas = float(np.abs(np.asarray(pv_p) - pv64).max() / scale)
    rel_lax = float(np.abs(np.asarray(pv_r) - pv64).max() / scale)
    rel_cross = float(
        np.abs(np.asarray(pv_p) - np.asarray(pv_r)).max() / scale)

    rec = {
        "selected_pallas": bool(fa._use_pallas(sq, t, hd)),
        "interpret_mode": not on_tpu,
        "compile_s": round(compile_s, 2),
        "rel_err_pallas_vs_f64": rel_pallas,
        "rel_err_lax_vs_f64": rel_lax,
        "rel_err_pallas_vs_lax": rel_cross,
    }
    if on_tpu:
        # Per-call dispatch through the device relay is ~50 ms — far more
        # than the kernel itself — so time STEPS INSIDE ONE JIT: a scan
        # whose carry feeds each step's pv back into the next step's
        # query (a real data dependency, so XLA can't fold the loop).
        steps = 16

        def _loop(impl, k_, v_):
            def body(c, _):
                pv, m, l = impl(c, k_, v_, zero, zero)
                return c + 1e-3 * pv, m[..., 0].sum() + l[..., 0].sum()
            @jax.jit
            def run(q0):
                out, aux = jax.lax.scan(body, q0, None, length=steps)
                return out, aux
            return run

        impls = (
            ("pallas", lambda a, b_, c, d, e:
                fa._block_attention_pallas(a, b_, c, d, e, False)),
            ("lax", fa._block_attention_ref),
        )
        # Two shapes under ONE timing protocol: the short smoke block,
        # and the ring path's realistic 2048 block — where the 512-edge
        # tiling pays and the kernel must WIN, not just match.
        sq2 = t2 = 2048
        qg2 = jnp.asarray(
            rng.standard_normal((1, 8, 4, sq2, hd)), jnp.float32)
        k2 = jnp.asarray(rng.standard_normal((1, 8, t2, hd)), jnp.float32)
        v2 = jnp.asarray(rng.standard_normal((1, 8, t2, hd)), jnp.float32)
        for suffix, q_, k_, v_ in (("", qg, k, v), ("_2k", qg2, k2, v2)):
            for label, impl in impls:
                run = _loop(impl, k_, v_)
                jax.block_until_ready(run(q_))  # compile
                per_call = _median_time(lambda: run(q_), trials=5) / steps
                rec[f"{label}{suffix}_median_ms"] = round(1e3 * per_call, 3)
        rec["pallas_2k_speedup_vs_lax"] = round(
            rec["lax_2k_median_ms"] / max(rec["pallas_2k_median_ms"], 1e-9),
            3)
    # bf16 MXU truncation is ~6e-3 relative at these shapes; 2e-2 flags a
    # real kernel defect while tolerating precision-mode drift.  The
    # cross-check is tighter: pallas and lax share the truncation, so
    # they must agree with each other well below the f64 gap.
    rec["ok"] = (rel_pallas < 2e-2 and rel_cross <= max(rel_lax, 5e-3)
                 and (rec["selected_pallas"] or not on_tpu))
    if on_tpu:
        # Perf bars: production routes attention through pallas at these
        # shapes (_use_pallas), so a kernel slower than its own lax
        # fallback is a regression this harness must fail, not
        # green-light.  Short block: parity within 20% noise headroom.
        # 2k block: the kernel must actually WIN (>= 1.0x; the tuned
        # measurement is 1.23x, so parity already flags a regression).
        rec["ok"] = rec["ok"] and (
            rec["pallas_median_ms"] <= 1.2 * rec["lax_median_ms"]
            and rec["pallas_2k_speedup_vs_lax"] >= 1.0)
    return rec


def check_flagship_forward() -> Dict:
    import importlib.util
    import os

    import jax
    import jax.numpy as jnp

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__",
        os.path.join(os.path.dirname(__file__), "..", "..",
                     "__graft_entry__.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, args = mod.entry()
    jitted = jax.jit(fn)
    t0 = time.monotonic()
    out = jax.block_until_ready(jitted(*args))
    compile_s = time.monotonic() - t0
    finite = bool(jnp.isfinite(out).all())
    step_s = _median_time(lambda: jitted(*args), trials=3)
    return {
        "logits_shape": list(out.shape),
        "dtype": str(out.dtype),
        "compile_s": round(compile_s, 1),
        "step_median_s": round(step_s, 4),
        "finite": finite,
        "ok": finite,
    }


def check_decode() -> Dict:
    """KV-cached greedy decode on the flagship config: the serving loop
    (``models/generate.py``) compiled and timed on the live backend.
    Correctness bars that need no oracle: token ids in-vocab, and the
    whole decode bit-identical when re-run (greedy is deterministic)."""
    import jax
    import jax.numpy as jnp

    from ..models.generate import generate
    from ..models.llama import CONFIGS, init_params

    cfg = CONFIGS["llama3-8b-d4"]
    params = init_params(cfg, jax.random.key(0))
    prompt = jnp.ones((1, 16), jnp.int32)
    max_new = 32
    t0 = time.monotonic()
    toks = jax.block_until_ready(generate(params, prompt, cfg, max_new))
    compile_s = time.monotonic() - t0
    t0 = time.monotonic()
    again = jax.block_until_ready(generate(params, prompt, cfg, max_new))
    steady_s = time.monotonic() - t0
    in_vocab = bool(((toks >= 0) & (toks < cfg.vocab)).all())
    deterministic = bool((toks == again).all())
    return {
        "config": cfg.name,
        "tokens": max_new,
        "compile_s": round(compile_s, 1),
        "steady_tokens_per_s": round(max_new / steady_s, 1),
        "in_vocab": in_vocab,
        "deterministic": deterministic,
        "ok": in_vocab and deterministic,
    }


def check_ingest_link(size_mib: int) -> Dict:
    import jax
    import numpy as np

    from ..parallel.ingest import ShardedLayerIngest

    total = size_mib << 20
    parts = 8
    devices = jax.devices()[:1]
    rng = np.random.default_rng(0)
    blob = rng.integers(0, 256, total, dtype=np.uint8)
    bounds = [i * total // parts for i in range(parts)] + [total]
    frags = [(bounds[i], blob[bounds[i]:bounds[i + 1]].tobytes())
             for i in range(parts)]

    def ingest_once():
        ing = ShardedLayerIngest(total, devices)
        for off, data in frags:
            ing.write(off, data)
        return ing.finalize()

    def raw_once():
        return jax.device_put(blob, devices[0])

    # Warm both (compiles the splice), then pair raw/ingest so link drift
    # cancels in the ratio (same discipline as bench.py).
    jax.block_until_ready(raw_once())
    jax.block_until_ready(ingest_once())
    ratios = []
    for _ in range(3):
        t0 = time.monotonic()
        jax.block_until_ready(raw_once())
        raw_s = time.monotonic() - t0
        t0 = time.monotonic()
        jax.block_until_ready(ingest_once())
        ing_s = time.monotonic() - t0
        ratios.append(raw_s / ing_s)
    link_fraction = sorted(ratios)[len(ratios) // 2]
    return {
        "size_mib": size_mib,
        "fragments": parts,
        "link_fraction": round(link_fraction, 3),
        "link_fraction_spread": [round(min(ratios), 3),
                                 round(max(ratios), 3)],
        # In-harness cross-check at reduced size: the bar is "same order
        # as bulk DMA" (>=0.7); the full-size >=0.95 claim is bench.py's.
        "ok": link_fraction >= 0.7,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpu_smoke")
    p.add_argument("-o", type=str, default="",
                   help="also write the JSON report to this path")
    p.add_argument("--size-mib", type=int, default=64,
                   help="ingest cross-check size")
    p.add_argument("--skip-forward", action="store_true",
                   help="skip the flagship forward (the slow compile)")
    p.add_argument("--check", type=str, default="",
                   help="don't run checks: verify the artifact at this "
                        "path was recorded by the CURRENT harness (or "
                        "carries a documented 'stale' marker); exit 1 "
                        "otherwise")
    args = p.parse_args(argv)

    from ..utils.provenance import artifact_is_current, harness_hash

    if args.check:
        try:
            with open(args.check) as f:
                report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"unreadable artifact {args.check}: {e!r}",
                  file=sys.stderr)
            return 1
        ok, why = artifact_is_current(report)
        print(f"{args.check}: {why}", file=sys.stderr)
        return 0 if ok else 1

    import jax

    report = {
        "backend": jax.default_backend(),
        "devices": [str(d) for d in jax.devices()],
        "harness_hash": harness_hash(),
        "checks": {},
    }
    checks = [("pallas_block_attention", check_pallas_block_attention),
              ("ingest_link", lambda: check_ingest_link(args.size_mib))]
    if not args.skip_forward:
        checks.append(("flagship_forward", check_flagship_forward))
        checks.append(("decode", check_decode))
    for name, fn in checks:
        t0 = time.monotonic()
        try:
            rec = fn()
        except Exception as e:  # a crashed check fails the report
            rec = {"ok": False, "error": repr(e)}
        rec["wall_s"] = round(time.monotonic() - t0, 1)
        report["checks"][name] = rec
        print(f"{name}: {'ok' if rec.get('ok') else 'FAIL'} "
              f"({rec['wall_s']}s)", file=sys.stderr, flush=True)
    report["ok"] = all(c.get("ok") for c in report["checks"].values())
    out = json.dumps(report)
    print(out)
    if args.o:
        with open(args.o, "w") as f:
            f.write(out + "\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
