"""Process entry point: reference-compatible CLI.

Re-design of ``/root/reference/cmd/main.go``: same flags
(``-id -f -s -m -l -c -v``, cmd/main.go:15-21), same JSON config, same role
dispatch (leader / receiver / external client), same "Time to deliver"
measurement printed from the leader.  Run one process per node:

    python -m distributed_llm_dissemination_tpu.cli.main -id 0 -f conf.json -m 1
    python -m distributed_llm_dissemination_tpu.cli.main -id 1 -f conf.json -m 1
    python -m distributed_llm_dissemination_tpu.cli.main -id 2 -f conf.json -c

An external client shares the node ID it is attached to (``-c`` selects the
client role for that ID, cmd/main.go:69-91).
"""

from __future__ import annotations

import argparse
from typing import Optional
import os
import sys
import time

from ..core import config as cfg
from ..core.types import CLIENT_ID
from ..runtime import (
    Client,
    FlowRetransmitLeaderNode,
    FlowRetransmitReceiverNode,
    LeaderNode,
    Node,
    PullRetransmitLeaderNode,
    ReceiverNode,
    RetransmitLeaderNode,
    RetransmitReceiverNode,
)
from ..transport import TcpTransport
from ..utils import logging as ulog


def build_parser() -> argparse.ArgumentParser:
    # Single-dash long flags, matching the Go CLI (cmd/main.go:15-21).
    p = argparse.ArgumentParser(
        prog="distributor", description=__doc__, prefix_chars="-"
    )
    p.add_argument("-id", type=int, required=True, help="my ID")
    p.add_argument("-f", type=str, required=True,
                   help="filename of topology JSON file")
    p.add_argument("-s", type=str, default="",
                   help="path of storing layers (empty: keep layers in RAM)")
    p.add_argument("-m", type=int, default=0, choices=[0, 1, 2, 3],
                   help="0: naive, 1: retransmit, 2: pull, 3: max-flow")
    p.add_argument("-l", action="store_true",
                   help="create layer files and exit")
    p.add_argument("-c", action="store_true", help="if the process is client")
    p.add_argument("-v", action="store_true", help="output debug messages")
    # Extensions beyond the reference flag set (failure handling is its
    # TODO, node.go:218-220); both default off = exact reference behavior.
    p.add_argument("-ft", type=float, default=0.0,
                   help="leader: seconds of node silence before declaring "
                        "it crashed and re-planning (0: off)")
    p.add_argument("-hb", type=float, default=0.0,
                   help="receiver: heartbeat interval seconds (use ~ft/4; "
                        "0: off)")
    p.add_argument("-ckpt", type=str, default="",
                   help="receiver (mode 3): directory for durable partial-"
                        "layer checkpoints; a restarted receiver resumes "
                        "and only the missing byte ranges are re-sent")
    p.add_argument("-hbm", action="store_true",
                   help="receiver: stage each delivered layer into TPU HBM "
                        "(jax.Array) before acking")
    p.add_argument("-boot", type=str, default="",
                   help="model config name (models.llama.CONFIGS), "
                        "hf:<checkpoint-dir>, or 'none': receivers boot the "
                        "model from the delivered layer blobs on startup; "
                        "the leader waits for every assignee's boot and "
                        "prints Time to first token (give the flag to both "
                        "roles)")
    p.add_argument("-gen", type=int, default=0,
                   help="receiver: after a full boot, greedily decode this "
                        "many tokens with the KV-cached serving loop "
                        "(models/generate.py) and log them — dissemination "
                        "ends at emitted tokens")
    p.add_argument("-bw", type=float, default=3600.0,
                   help="boot-wait bound in seconds: how long the leader "
                        "waits for missing boot reports (then exits 1) and "
                        "a receiver drains its own in-flight boot before "
                        "exiting; size to the slowest expected boot")
    p.add_argument("-test-drop-plan-seqs", type=str, default="",
                   help="TEST ONLY: comma-separated SPMD plan seqs whose "
                        "first delivery this process drops (fault "
                        "injection for the gap-recovery tests).  "
                        "Implemented by wrapping the transport in the "
                        "deterministic fault-injection layer "
                        "(transport/faults.py); armed exclusively by this "
                        "flag — environment variables cannot enable it")
    p.add_argument("-test-faults", type=str, default="",
                   help="TEST ONLY: deterministic fault-injection spec "
                        "for this process's transport "
                        "(transport/faults.rules_from_spec), e.g. "
                        "'seed=7,corrupt=9,dropin=13,dup=11,times=8' — "
                        "corrupt/drop inbound layer frames below the CRC "
                        "check, dup/delay/reset outbound sends.  The "
                        "integrity plane (docs/integrity.md) must recover "
                        "byte-exactly; armed exclusively by this flag")
    p.add_argument("-serve", type=float, default=0.0,
                   help="receiver: after a successful boot, stay alive "
                        "this many seconds answering GenerateReqMsg "
                        "inference requests (cli.genreq) from the "
                        "resident params; 0 = exit after boot as before")
    p.add_argument("-report", type=str, default="",
                   help="write RUN_REPORT.{json,md} at this path/prefix "
                        "when the run completes (cli/report.py): TTD/"
                        "TTFT, the per-(src,dest) link flight-recorder "
                        "table, integrity/failover event counts, clock "
                        "offsets, provenance hash.  Leader flag; a "
                        "receiver that assumed leadership mid-run "
                        "honors it too, so a failover run still yields "
                        "a report")
    p.add_argument("-watch", type=float, default=0.0,
                   help="leader: log the folded cluster telemetry table "
                        "('cluster telemetry' records) every N seconds "
                        "mid-run — the live where-is-every-byte status "
                        "hook (0: off; one dump always fires at "
                        "delivery)")
    p.add_argument("-lease", type=float, default=1.0,
                   help="control-plane HA (docs/failover.md; only active "
                        "when the config declares Standbys): the leader's "
                        "lease beacon interval in seconds; standbys "
                        "declare it dead after ~3x this (staggered by "
                        "succession rank) and take over")
    # Dissemination service plane (docs/service.md): the leader as a
    # long-lived multi-job daemon, plus the submitter/query tools.
    p.add_argument("-daemon", type=float, default=0.0,
                   help="leader: after the initial goal completes, stay "
                        "alive this many seconds as a dissemination "
                        "service accepting job submissions (-submit) — "
                        "version pushes, repair refills, A/B variants — "
                        "scheduled as one shared-capacity flow problem "
                        "with priorities (0: exit after the run as "
                        "before)")
    p.add_argument("-submit", type=str, default="",
                   help="submit one dissemination job to the running "
                        "leader daemon and exit: a JSON file (or inline "
                        "JSON) with JobID, Assignment ({dest: [layer "
                        "ids]} or nested metas), optional Priority "
                        "(higher preempts), Kind (push|repair|ab), and "
                        "Digests ({layer: 'xxh3:<hex>'} — content keys "
                        "for delta resolution).  Run from an idle seat: "
                        "-id must not collide with a live node process")
    p.add_argument("-jobs", action="store_true",
                   help="query the running leader daemon's admitted-job "
                        "table (states, remaining pairs, priorities) as "
                        "JSON on stdout and exit; same seat rules as "
                        "-submit")
    # Elastic membership (docs/membership.md): the operator verbs.
    p.add_argument("-join", action="store_true",
                   help="receiver: this seat is NOT part of the running "
                        "cluster's goal — send a JoinMsg to the leader "
                        "first (admitted as a dest immediately, as a "
                        "source once its holdings digest-verify), then "
                        "run the normal receiver loop.  The seat still "
                        "needs a topology entry for its own address")
    p.add_argument("-drain", type=int, default=-1, metavar="NODE",
                   help="one-shot operator tool: ask the running leader "
                        "to DRAIN node NODE — its unique holdings are "
                        "re-homed onto survivors before it is released "
                        "— print the answer, exit.  Run from an idle "
                        "seat like -submit/-jobs")
    # SLO-guarded rollout pipeline (docs/rollout.md): submit a rollout
    # via -submit (Kind "rollout" + Waves/SLO/Split in the spec); these
    # are the operator control verbs.
    p.add_argument("-rollouts", action="store_true",
                   help="query the running leader's rollout-pipeline "
                        "table (wave states, SLO verdicts, traffic "
                        "split, v1/v2 pools) as JSON and exit; same "
                        "seat rules as -jobs")
    p.add_argument("-rollout-pause", type=str, default="", metavar="ID",
                   help="pause rollout ID: no further waves commit "
                        "(in-flight dissemination and soaks finish)")
    p.add_argument("-rollout-resume", type=str, default="",
                   metavar="ID",
                   help="resume paused rollout ID: a rolled-back wave "
                        "is re-disseminated as a retry")
    p.add_argument("-rollout-split", type=str, default="",
                   metavar="ID:FRACTION",
                   help="set rollout ID's traffic-split knob (the "
                        "fraction of eligible traffic routed at v2 "
                        "replicas during soak), e.g. canary-v2:0.25")
    # Closed-loop autonomy (docs/autonomy.md): the policy engine's
    # operator verbs — query is open, enable/disable ride the
    # DLD_JOB_TOKEN admission gate like every other fleet mutation.
    p.add_argument("-policies", action="store_true",
                   help="query the running leader's policy engine "
                        "(armed rules, cooldowns, quarantine mask, "
                        "in-flight actions, audit tail) as JSON and "
                        "exit; same seat rules as -jobs")
    p.add_argument("-policy-enable", action="store_true",
                   help="re-enable automatic policy actioning on the "
                        "running leader (token-gated via DLD_JOB_TOKEN)")
    p.add_argument("-policy-disable", action="store_true",
                   help="drop the running leader's policy engine to "
                        "MANUAL: rules keep sensing (streaks/cooldowns "
                        "stay warm) but no action fires (token-gated "
                        "via DLD_JOB_TOKEN)")
    return p


def validate_boot_choice(args, conf) -> None:
    """`-boot <name>` naming a model different from the config's Model is
    a config error: the disseminated bytes are sized/laid out (and codec-
    encoded, conf.model_codec) for the config's model, so booting another
    one can only fail later as a swallowed boot error.  Fail fast at
    argument validation instead (like the -gen checks).  `-boot none`
    (opt out of booting) always passes."""
    if (args.boot and args.boot != "none" and conf.model
            and args.boot != conf.model):
        raise SystemExit(
            f"-boot {args.boot!r} names a different model than the "
            f"config's Model {conf.model!r}: the layer bytes on the wire "
            f"are the config model's; drop -boot or fix the config"
        )


def _resolve_model_config(name: str):
    """THE model-name resolution (CONFIGS entry or ``hf:<dir>``) —
    shared by the boot path and the wire-codec plane so a new naming
    scheme can't silently reach one and miss the other.  Raises
    KeyError/OSError/ValueError for unresolvable names; callers own the
    error policy (boot fails fast, the codec plane degrades to None)."""
    from ..models import hf

    if hf.is_hf(name):
        # A Hugging Face Llama checkpoint directory (models/hf.py).
        return hf.config_from_name(name)
    from ..models.llama import CONFIGS

    return CONFIGS[name]


def boot_config(name: str):
    if not name or name == "none":
        # "-boot none" opts a boot-capable topology (a Model section) out
        # of booting: dissemination-only runs, e.g. wire benchmarks.
        return None
    try:
        return _resolve_model_config(name)
    except KeyError:
        from ..models.llama import CONFIGS

        raise SystemExit(
            f"unknown -boot model {name!r}; known: {sorted(CONFIGS)}, "
            "none, hf:<checkpoint-dir>"
        )
    except (OSError, ValueError) as e:
        raise SystemExit(f"bad hf checkpoint for -boot {name!r}: {e}")


def build_codec_plane(conf: cfg.Config):
    """The node's wire-codec plane (docs/codec.md): built for every
    role of a model run — leaders use it to CHOOSE quantized transfers
    (conf.wire_codec governs), receivers to advertise decode capability
    and encode-serve as senders.  None for model-less topologies (codec
    sizes derive from the blob layouts)."""
    if not conf.model:
        return None
    from ..runtime.codec import WireCodecPlane

    try:
        mcfg = _resolve_model_config(conf.model)
    except (OSError, ValueError, KeyError) as e:
        ulog.log.warn("wire-codec plane unavailable for this model",
                      model=conf.model, err=repr(e))
        return None
    return WireCodecPlane(mcfg, model_codec=conf.model_codec,
                          wire_codec=conf.wire_codec)


def _parse_job_spec(raw: str) -> dict:
    """A -submit spec: a JSON file path, or inline JSON.  Assignment
    values may be layer-id LISTS (shorthand; default metas) or nested
    ``{layer: meta}`` maps (the wire shape)."""
    import json

    from ..core.types import LayerMeta

    text = raw
    if os.path.exists(raw):
        with open(raw) as f:
            text = f.read()
    try:
        spec = json.loads(text)
    except ValueError as e:
        raise SystemExit(f"-submit spec is neither a file nor JSON: {e}")
    if not spec.get("JobID"):
        raise SystemExit("-submit spec needs a JobID")
    asg_raw = spec.get("Assignment") or {}
    if not asg_raw:
        raise SystemExit("-submit spec needs a non-empty Assignment")
    try:
        assignment = {}
        for dest, lids in asg_raw.items():
            if isinstance(lids, dict):
                assignment[int(dest)] = {
                    int(l): LayerMeta.from_json(m or {})
                    for l, m in lids.items()}
            else:
                assignment[int(dest)] = {int(l): LayerMeta()
                                         for l in lids}
        spec["Assignment"] = assignment
        spec["Digests"] = {int(l): str(d)
                           for l, d in (spec.get("Digests") or {}).items()}
        spec["Avoid"] = [int(n) for n in spec.get("Avoid") or []]
    except (TypeError, ValueError) as e:
        raise SystemExit(
            f"-submit spec has non-integer node/layer keys: {e}")
    try:
        # Rollout pipeline (docs/rollout.md): the wave plan + SLO +
        # split ride a Kind "rollout" spec through the same submit.
        spec["Waves"] = [[int(n) for n in w]
                         for w in spec.get("Waves") or []]
        spec["SLO"] = dict(spec.get("SLO") or {})
        # -1 = unset (driver default); an explicit 0.0 is honored.
        spec["Split"] = float(spec.get("Split", -1.0))
    except (TypeError, ValueError) as e:
        raise SystemExit(
            f"-submit spec has a malformed Waves/SLO/Split field: {e}")
    return spec


def _oneshot_leader_rpc(args, conf: cfg.Config, reply_cls, make_msg,
                        timeout: float, timeout_error: str):
    """The one-shot operator-tool scaffolding shared by -submit/-jobs/
    -drain: bind this idle seat's address, send one request to the
    leader (``make_msg(leader_id)``), await one ``reply_cls`` reply.
    Returns the reply, or None after ``timeout`` (the caller prints
    ``timeout_error``).  Like cli.genreq, -id must name a topology seat
    NOT also running cli.main (the reply multiplexes on the seat's
    address)."""
    import json
    import queue as _queue

    from ..runtime.node import MessageLoop

    node_conf = cfg.get_node_conf(conf, args.id)
    leader_id = cfg.get_leader_conf(conf).id
    if args.id == leader_id:
        raise SystemExit("one-shot tools must run from a non-leader "
                         "seat (the leader process owns that address)")
    transport = TcpTransport(node_conf.addr,
                             addr_registry={nc.id: nc.addr
                                            for nc in conf.nodes})
    loop = MessageLoop(transport)
    replies: "_queue.Queue" = _queue.Queue()
    loop.register(reply_cls, replies.put)
    loop.start()
    try:
        transport.send(leader_id, make_msg(leader_id))
        try:
            return replies.get(timeout=timeout)
        except _queue.Empty:
            print(json.dumps({"error": timeout_error}))
            return None
    finally:
        loop.stop()
        transport.close()


def run_jobtool(args, conf: cfg.Config) -> int:
    """The -submit / -jobs one-shot tools (docs/service.md): send the
    request to the leader daemon, print its JobStatusMsg reply as
    JSON, exit."""
    import json

    from ..transport.messages import JobStatusMsg, JobSubmitMsg

    def make_msg(leader_id):
        if args.submit:
            spec = _parse_job_spec(args.submit)
            return JobSubmitMsg(
                args.id, str(spec["JobID"]), spec["Assignment"],
                priority=int(spec.get("Priority", 0)),
                kind=str(spec.get("Kind", "push")),
                digests=spec["Digests"], avoid=spec["Avoid"],
                version=str(spec.get("Version", "")),
                swap_base=int(spec.get("SwapBase", -1)),
                # Admission control (docs/service.md): a token-armed
                # leader daemon rejects unauthenticated submits; the
                # operator exports the same secret on both sides.
                auth=os.environ.get("DLD_JOB_TOKEN", ""),
                waves=spec["Waves"], slo=spec["SLO"],
                split=spec["Split"])
        return JobStatusMsg(args.id, query=True)

    resp = _oneshot_leader_rpc(
        args, conf, JobStatusMsg, make_msg, timeout=30.0,
        timeout_error="no reply from the leader daemon (is it running "
                      "with -daemon?)")
    if resp is None:
        return 1
    out = {"leader_epoch": resp.epoch, "jobs": resp.jobs}
    if resp.error:
        out["error"] = resp.error
    print(json.dumps(out, indent=1, sort_keys=True))
    return 1 if resp.error else 0


def run_rollouttool(args, conf: cfg.Config) -> int:
    """The rollout-pipeline operator verbs (docs/rollout.md): query /
    pause / resume / set-split against the running leader, print its
    RolloutCtlMsg reply (the full rollout table) as JSON, exit."""
    import json

    from ..transport.messages import RolloutCtlMsg

    # One mutating verb per invocation: the leader's verb chain
    # executes exactly one, so combined flags would silently drop (or
    # worse, mis-target) the rest — refuse up front.
    if sum(map(bool, (args.rollout_pause, args.rollout_resume,
                      args.rollout_split))) > 1:
        raise SystemExit("pick ONE of -rollout-pause / -rollout-resume"
                         " / -rollout-split per invocation")
    rid, split = "", -1.0
    if args.rollout_split:
        rid, _, frac = args.rollout_split.rpartition(":")
        if not rid:
            raise SystemExit("-rollout-split wants ID:FRACTION")
        try:
            split = float(frac)
        except ValueError:
            raise SystemExit(f"-rollout-split fraction is not a "
                             f"number: {frac!r}")
    elif args.rollout_pause:
        rid = args.rollout_pause
    elif args.rollout_resume:
        rid = args.rollout_resume

    resp = _oneshot_leader_rpc(
        args, conf, RolloutCtlMsg,
        lambda leader_id: RolloutCtlMsg(
            args.id, rollout_id=rid, query=args.rollouts,
            pause=bool(args.rollout_pause),
            resume=bool(args.rollout_resume), split=split,
            # Mutating verbs ride the job-token admission gate
            # (docs/service.md): the operator exports the same secret.
            auth=os.environ.get("DLD_JOB_TOKEN", "")),
        timeout=30.0,
        timeout_error="no rollout answer from the leader (is it "
                      "running?)")
    if resp is None:
        return 1
    out = {"leader_epoch": resp.epoch, "rollouts": resp.table}
    if resp.error:
        out["error"] = resp.error
    print(json.dumps(out, indent=1, sort_keys=True))
    return 1 if resp.error else 0


def run_policytool(args, conf: cfg.Config) -> int:
    """The autonomy operator verbs (docs/autonomy.md): query the policy
    engine's table / enable / disable automatic actioning against the
    running leader, print its PolicyCtlMsg reply as JSON, exit."""
    import json

    from ..transport.messages import PolicyCtlMsg

    # One mutating verb per invocation, same refusal as the rollout
    # verbs — the leader executes exactly one.
    if args.policy_enable and args.policy_disable:
        raise SystemExit("pick ONE of -policy-enable / -policy-disable "
                         "per invocation")

    resp = _oneshot_leader_rpc(
        args, conf, PolicyCtlMsg,
        lambda leader_id: PolicyCtlMsg(
            args.id, query=args.policies,
            enable=bool(args.policy_enable),
            disable=bool(args.policy_disable),
            # Mutating verbs ride the job-token admission gate
            # (docs/service.md): the operator exports the same secret.
            auth=os.environ.get("DLD_JOB_TOKEN", "")),
        timeout=30.0,
        timeout_error="no policy answer from the leader (is it "
                      "running?)")
    if resp is None:
        return 1
    out = {"leader_epoch": resp.epoch, "policies": resp.table}
    if resp.error:
        out["error"] = resp.error
    print(json.dumps(out, indent=1, sort_keys=True))
    return 1 if resp.error else 0


def run_draintool(args, conf: cfg.Config) -> int:
    """The -drain NODE one-shot (docs/membership.md): ask the leader to
    drain the named node, print its DONE (or refusal) answer as JSON,
    exit."""
    import json

    from ..transport.messages import DrainMsg

    resp = _oneshot_leader_rpc(
        args, conf, DrainMsg,
        lambda leader_id: DrainMsg(args.id, node=args.drain),
        timeout=120.0,
        timeout_error="no drain answer from the leader (is it "
                      "running?)")
    if resp is None:
        return 1
    out = {"node": resp.node, "done": resp.done,
           "leader_epoch": resp.epoch}
    if resp.error:
        out["error"] = resp.error
    print(json.dumps(out, indent=1, sort_keys=True))
    return 0 if resp.done else 1


def run_client(args, conf: cfg.Config) -> int:
    """External-client role: serve layers to the node with my ID
    (cmd/main.go:69-91, 217-220)."""
    client_conf = cfg.get_client_conf(conf, args.id)
    node_conf = cfg.get_node_conf(conf, args.id)
    transport = TcpTransport(
        client_conf.addr,
        addr_registry={node_conf.id: node_conf.addr},
        is_client=True,
    )
    layers = {
        lid: cfg.create_client_layer(lid, conf.layer_size, rate)
        for lid, rate in client_conf.layers_rate_limit.items()
    }
    Client(args.id, transport, layers)
    ulog.log.info("client ready", addr=client_conf.addr)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        return 0


def resolve_groups(conf: cfg.Config, mode: Optional[int] = None):
    """The config's ``Groups`` section → the resolved group table
    (docs/hierarchy.md), or None for flat control.  One resolution
    shared by the leader (planner + dispatch), the member seats (their
    control parent is the sub-leader), and the sub-leader seats (they
    attach a SubLeaderController) — and therefore the ONE place the
    mode-3 requirement is enforced: EVERY role must refuse a
    mis-moded hierarchical config, or members re-point at a
    sub-leader that will never plan and hang instead of erroring."""
    if conf.groups is None:
        return None
    if mode is not None and mode != 3:
        raise SystemExit(
            "Groups (hierarchical control, docs/hierarchy.md) requires "
            f"mode 3; got mode {mode}")
    from ..runtime.hierarchy import groups_from_config

    leader_id = cfg.get_leader_conf(conf).id
    return groups_from_config(conf.groups, [nc.id for nc in conf.nodes],
                              leader_id) or None


def resolve_pods(conf: cfg.Config, mode: Optional[int] = None):
    """The config's ``Pods`` section → ``{pod_id: [members]}`` for the
    mode-3 leader (fabric-assisted pod delivery, docs/fabric.md), or
    None.  Config-time validation (disjoint, known ids) already ran in
    ``Config.from_json``; the leader seat is re-checked at leader
    construction."""
    if conf.pods is None:
        return None
    if mode is not None and mode != 3:
        raise SystemExit(
            "Pods (fabric-assisted pod delivery, docs/fabric.md) "
            f"requires mode 3; got mode {mode}")
    return {pid: list(members) for pid, members in enumerate(conf.pods)}


def run_leader(args, conf: cfg.Config, node: Node, layers) -> int:
    """Leader role: constructor per mode, then drive the TTD timer
    (cmd/main.go:149-181)."""
    assignment = conf.assignment
    # Wait for every configured node to announce, seeders included, so the
    # schedule sees all sources (the reference waits only for assignees and
    # races seeder announcements).  IDLE SEATS — nodes seeding nothing
    # (neither initial layers nor an attached external client), assigned
    # nothing — are excluded: they can't affect the schedule, and they may
    # not run cli.main at all (e.g. a cli.genreq requester seat that only
    # needs a dialable address in the topology).
    client_nodes = {cc.id for cc in conf.clients}
    expected = {
        nc.id for nc in conf.nodes
        if nc.is_leader
        or nc.id in assignment
        or nc.id in client_nodes
        or any((nc.initial_layers or {}).values())
    }
    ft = args.ft
    fabric, placement = build_spmd_fabric(args, conf)
    if os.environ.get("DLD_PLAN_ACK_TIMEOUT"):
        # Test knob: shrink the SPMD plan watchdog's ack timeout (and
        # check period with it) so tail-gap recovery runs in test time.
        LeaderNode.PLAN_ACK_TIMEOUT = float(
            os.environ["DLD_PLAN_ACK_TIMEOUT"])
        LeaderNode.PLAN_WATCH_PERIOD = min(
            LeaderNode.PLAN_WATCH_PERIOD,
            LeaderNode.PLAN_ACK_TIMEOUT / 2 or 1.0)
    common = dict(expected_nodes=expected, failure_timeout=ft,
                  fabric=fabric, placement=placement,
                  codecs=build_codec_plane(conf))
    if conf.standbys:
        # Control-plane HA (docs/failover.md): replicate control state
        # to the declared standbys, beacon the lease, fence by epoch.
        common.update(standbys=list(conf.standbys),
                      lease_interval=max(args.lease, 0.05), epoch=0)
    groups = resolve_groups(conf, args.m)
    pods = resolve_pods(conf, args.m)
    if args.m == 0:
        leader = LeaderNode(node, layers, assignment, **common)
    elif args.m == 1:
        leader = RetransmitLeaderNode(node, layers, assignment, **common)
    elif args.m == 2:
        leader = PullRetransmitLeaderNode(node, layers, assignment, **common)
    else:
        bw = {nc.id: nc.network_bw for nc in conf.nodes}
        topo = conf.mesh.topology() if conf.mesh is not None else None
        if groups is not None:
            from ..runtime import HierarchicalFlowLeaderNode

            leader = HierarchicalFlowLeaderNode(
                node, layers, assignment, bw, groups=groups,
                topology=topo, pods=pods, **common)
        else:
            leader = FlowRetransmitLeaderNode(node, layers, assignment, bw,
                                              topology=topo, pods=pods,
                                              **common)

    # One flag governs the run: the leader's decision rides StartupMsg,
    # so receivers can never boot (or skip) against the leader's wait.
    validate_boot_choice(args, conf)
    leader.boot_enabled = boot_config(args.boot or conf.model) is not None
    # Pod serving decodes -gen tokens (rides the ServeMsg): the leader's
    # flag governs the whole pod, like the boot decision.
    leader.serve_generate = max(0, args.gen)
    # Closed-loop autonomy (docs/autonomy.md): arm the config's
    # validated Policies block.  A bad block already failed LOUDLY at
    # config parse (core/config.py → policy.validate_policies).
    if conf.policies:
        leader.policy.arm(conf.policies)

    print(
        f"launching leader...\n[addr: {node.transport.get_address()}, "
        f"id: {args.id}, filename: {args.f}, storagePath: {args.s}, mode: {args.m}]",
        flush=True,
    )
    if args.watch > 0:
        # Mid-run status hook: the folded cluster table lands in the
        # log stream every interval (daemon — dies with the process).
        import threading as _threading

        def _watch_loop():
            while True:
                time.sleep(args.watch)
                try:
                    leader.log_cluster_metrics()
                except Exception as e:  # noqa: BLE001 — advisory hook
                    ulog.log.debug("cluster metrics watch failed",
                                   err=repr(e))

        _threading.Thread(target=_watch_loop, daemon=True,
                          name="telemetry-watch").start()

    ttft = None
    t_ready_mono = None

    def write_run_report(ttd_s):
        """RUN_REPORT.{json,md} from the leader's folded cluster
        telemetry — written on every exit path that has a TTD, so a
        failed boot still leaves the evidence behind."""
        if not args.report:
            return
        from . import report as report_mod

        # Freshness gate: receivers flush a final snapshot on startup;
        # wait (bounded) until every known node's report post-dates the
        # ready event so a fast run's report carries completion totals.
        if t_ready_mono is not None:
            leader.await_metrics(newer_than=t_ready_mono)
        # One more dump with the final fold, so OFFLINE reconstruction
        # from this process's log gets completion totals too.
        leader.log_cluster_metrics()
        try:
            rep = report_mod.build_from_leader(leader, ttd_s=ttd_s,
                                               ttft_s=ttft)
            paths = report_mod.write_report(rep, args.report)
        except OSError as e:
            ulog.log.error("run report write failed", err=repr(e))
            return
        ulog.log.info("run report written", **paths)
        print(f"Run report: {paths['json']} "
              f"(provenance {paths['provenance']})", flush=True)

    leader.start_distribution().get()
    t0 = time.monotonic()
    leader.ready().get()
    t_ready_mono = time.monotonic()
    ttd = t_ready_mono - t0
    ulog.log.info("Time to deliver", seconds=round(ttd, 6))
    print(f"Time to deliver: {ttd:.6f}s", flush=True)
    pred_ms = getattr(leader, "predicted_ttd_ms", 0)
    if pred_ms:
        # Mode 3 plan fidelity: the solver's min-time next to achieved
        # TTD (VERDICT item 2's measurement half).  Machine-parsed by
        # cli.ttd_matrix into predicted_s/solve_ms columns.
        solve_ms = getattr(leader, "solve_ms", 0.0)
        ulog.log.info("Predicted time to deliver",
                      seconds=round(pred_ms / 1000.0, 6),
                      solve_ms=round(solve_ms, 3))
        print(f"Predicted time to deliver: {pred_ms / 1000.0:.6f}s "
              f"(solve {solve_ms:.3f}ms)", flush=True)
    if leader.boot_enabled:
        # Receivers boot their model from the delivered blobs and report
        # back; TTFT = timer start → last boot report (includes TTD).
        # Bounded: failed boots now report (kind "failed") and crashes
        # shrink the wait, but a hard-killed dest with failure detection
        # off (-ft 0) still can't unblock it — exit loudly instead of
        # hanging the whole deployment.
        import queue as _queue

        try:
            booted = leader.boot_ready().get(timeout=args.bw)
        except _queue.Empty:
            ulog.log.error("boot wait timed out; missing reports",
                           booted=sorted(leader.boots_seen()))
            print(f"Boot wait timed out after {args.bw:g}s", flush=True)
            write_run_report(ttd)
            return 1
        ttft = time.monotonic() - t0
        kinds = leader.boot_kinds()
        ulog.log.info("Time to first token", seconds=round(ttft, 6),
                      nodes={str(n): round(s, 3) for n, s in booted.items()},
                      kinds={str(n): k for n, k in kinds.items()})
        print(f"Time to first token: {ttft:.6f}s", flush=True)
        failed = sorted(n for n, k in kinds.items()
                        if k in ("failed", "crashed"))
        if failed:
            print(f"Boot FAILED on nodes {failed}", flush=True)
            write_run_report(ttd)
            return 1
    if args.daemon > 0:
        # Dissemination service (docs/service.md): stay alive as a
        # long-lived daemon accepting -submit jobs; each completed job
        # cycle re-fires ready() and logs the admitted-job table.
        import json as _json
        import queue as _queue

        print(f"daemon: accepting job submissions for {args.daemon:g}s",
              flush=True)
        deadline = time.monotonic() + args.daemon
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                break
            try:
                goal = leader.ready().get(timeout=min(1.0, left))
            except _queue.Empty:
                continue
            ulog.log.info("job cycle complete", dests=sorted(goal),
                          jobs=leader.jobs.table())
            print(f"jobs: {_json.dumps(leader.jobs.table(), sort_keys=True)}",
                  flush=True)
        t_ready_mono = time.monotonic()  # freshness-gate the final report
    write_run_report(ttd)
    return 0


def build_placement(args, conf: cfg.Config):
    """The Assignment → pipeline-stage placement on the configured device
    mesh (the ``Mesh`` config section), when HBM staging is on.  Without a
    Mesh section, ``-hbm`` stages to the default device — the single-chip
    degenerate case."""
    if not args.hbm or conf.mesh is None:
        return None
    import jax as _jax

    from ..parallel.multihost import honor_jax_platforms

    honor_jax_platforms()
    from ..parallel.mesh import assignment_to_placement, mesh_from_conf
    from ..parallel.multihost import host_aligned_device_order

    # Multi-host: order the mesh's devices so each pipeline stage's block
    # lives on the host of the node mapped to that stage — otherwise a
    # node's delivered layers would target another host's chips.
    mesh = mesh_from_conf(
        conf.mesh, host_aligned_device_order(conf, conf.assignment)
    )
    placement = assignment_to_placement(
        conf.assignment, mesh, conf.mesh.pipeline_axis
    )
    # Every device this node will stage onto must be locally addressable:
    # in a multi-host deployment each process sees only its host's chips,
    # and a device_put onto a remote stage device would fail deep in the
    # receive path (or, worse, a local-only device list would silently
    # misalign with global stage indices).  Fail loudly up front instead.
    stage = placement.node_to_stage.get(args.id)
    if stage is not None:
        local = set(_jax.local_devices())
        missing = [d for d in placement.stage_devices(stage)
                   if d not in local]
        if missing:
            raise SystemExit(
                f"node {args.id} is mapped to pipeline stage {stage}, but "
                f"its devices {missing} are not in jax.local_devices(); "
                "multi-host runs need jax.distributed so the mesh spans "
                "all hosts, or a Mesh section restricted to local devices"
            )
    ulog.log.info(
        "device mesh placement",
        mesh={n: s for n, s in zip(conf.mesh.axis_names, conf.mesh.axis_sizes)},
        stages={str(n): s for n, s in placement.node_to_stage.items()},
    )
    return placement


def build_spmd_fabric(args, conf: cfg.Config):
    """(fabric, placement) for a Mesh.Fabric + Distributed topology: the
    multi-controller SPMD fabric (``parallel/spmd_fabric.py``), with a
    placement covering EVERY node (seeders upload through their own
    stages).  Returns (None, None) when the config doesn't ask for it."""
    if conf.mesh is None or not conf.mesh.fabric:
        return None, None
    from ..parallel.mesh import fabric_placement, mesh_from_conf
    from ..parallel.multihost import (
        honor_jax_platforms,
        host_aligned_device_order,
    )
    from ..parallel.spmd_fabric import SpmdFabric

    honor_jax_platforms()
    mesh = mesh_from_conf(
        conf.mesh, host_aligned_device_order(conf, conf.assignment)
    )
    placement = fabric_placement(
        [nc.id for nc in conf.nodes], conf.assignment, mesh,
        conf.mesh.pipeline_axis,
    )
    fabric = SpmdFabric(
        placement, args.id,
        gap_timeout=float(os.environ.get("DLD_SPMD_GAP_TIMEOUT", "60")),
    )
    ulog.log.info(
        "spmd fabric up",
        stages={str(n): s for n, s in placement.node_to_stage.items()},
    )
    return fabric, placement


def run_receiver(args, conf: cfg.Config, node: Node, layers) -> int:
    """Receiver role (cmd/main.go:183-215)."""
    fabric, placement = build_spmd_fabric(args, conf)
    if fabric is None:
        placement = build_placement(args, conf)
    # A config with a Model section is boot-capable: receivers boot by
    # default so the leader's boot wait can't hang on a missing flag.
    validate_boot_choice(args, conf)
    boot_cfg = boot_config(args.boot or conf.model)
    if args.gen < 0:
        raise SystemExit(f"-gen must be >= 0, got {args.gen}")
    if args.gen > 0 and boot_cfg is None:
        raise SystemExit(
            "-gen needs a bootable model: give -boot <name> or a config "
            "with a Model section"
        )
    codec = conf.model_codec
    common = dict(heartbeat_interval=args.hb, stage_hbm=args.hbm,
                  placement=placement, boot_cfg=boot_cfg, boot_codec=codec,
                  fabric=fabric, boot_generate=args.gen,
                  codecs=build_codec_plane(conf))
    if args.m == 0:
        receiver = ReceiverNode(node, layers, args.s or ".", **common)
    elif args.m in (1, 2):
        receiver = RetransmitReceiverNode(node, layers, args.s or ".",
                                          **common)
    else:
        receiver = FlowRetransmitReceiverNode(node, layers, args.s or ".",
                                              checkpoint_dir=args.ckpt,
                                              **common)
    # Announce-carried NIC rate (docs/membership.md): this seat's own
    # configured rate rides its announce, so a leader admitting it as a
    # JOINER models the real link instead of pinning the most
    # conservative configured value.
    try:
        receiver.nic_bw = int(cfg.get_node_conf(conf, args.id).network_bw
                              or 0)
    except (AttributeError, ValueError, KeyError):
        pass

    groups = resolve_groups(conf, args.m)
    sub_ctl = None
    if groups is not None:
        for gid, rec in groups.items():
            if rec["leader"] == args.id:
                # This seat owns a group (docs/hierarchy.md): attach
                # the sub-leader controller on the already-running loop
                # — member announces/acks/heartbeats/metrics fold here.
                from ..runtime import SubLeaderController

                sub_ctl = SubLeaderController(
                    receiver, gid, rec["members"],
                    member_timeout=args.ft)
                ulog.log.info("sub-leader controller armed", group=gid,
                              members=rec["members"])
                break

    standby_ctl = None
    if args.id in conf.standbys:
        # This seat is in the leader succession: shadow the control
        # state and take over (at a bumped, fenced epoch) if the
        # leader's lease expires (docs/failover.md).
        from ..runtime import StandbyController

        bw = {nc.id: nc.network_bw for nc in conf.nodes}
        standby_ctl = StandbyController(
            receiver, rank=conf.standbys.index(args.id),
            lease_timeout=max(args.lease, 0.05) * 3,
            standbys=list(conf.standbys), mode=args.m,
            node_network_bw=bw, failure_timeout=args.ft,
            lease_interval=max(args.lease, 0.05),
        )
        ulog.log.info("standby controller armed",
                      rank=conf.standbys.index(args.id),
                      succession=conf.standbys)

    print(
        f"launching receiver...\n[addr: {node.transport.get_address()}, "
        f"id: {args.id}, filename: {args.f}, storagePath: {args.s}, mode: {args.m}]",
        flush=True,
    )
    # Elastic membership (docs/membership.md): an explicit -join seat —
    # or one whose seeded churn schedule (-test-faults join=T) says it
    # appears late — JOINS the running cluster instead of announcing as
    # a configured member.
    join_wait = getattr(node.transport, "seconds_until_join",
                        lambda: None)()
    if args.join or join_wait is not None:
        if join_wait:
            ulog.log.info("churn schedule: dark until join",
                          seconds=round(join_wait, 3))
            time.sleep(join_wait)
        if not receiver.join():
            ulog.log.error("join was never admitted; exiting")
            return 1
        print("joined", flush=True)
    else:
        receiver.announce()
    leave_wait = getattr(node.transport, "seconds_until_leave",
                         lambda: None)()
    if leave_wait is not None:
        # The seeded departure: drain gracefully at the scheduled
        # moment, then release the startup wait so the process exits
        # cleanly (a drained seat never receives a StartupMsg).
        import threading as _threading

        def _scheduled_leave():
            time.sleep(leave_wait)
            ok = receiver.request_drain()
            ulog.log.info("scheduled drain finished", ok=ok)
            print(f"drained (ok={ok})", flush=True)
            receiver.release_ready()

        _threading.Thread(target=_scheduled_leave, daemon=True,
                          name="churn-leave").start()
    receiver.ready().get()
    if standby_ctl is not None and standby_ctl.promoted.is_set():
        # This process took over mid-run: it IS the leader now — report
        # the recovery like a leader would report TTD.
        leader = standby_ctl.leader
        ulog.log.info("this process assumed leadership during the run",
                      epoch=leader.epoch)
        print(f"assumed leadership (epoch {leader.epoch})", flush=True)
        if args.report:
            # The dead leader can't write its RUN_REPORT; the adopted
            # one can — its cluster table was replicated before the
            # takeover and refreshed by every node's cumulative reports
            # since (TTD is the dead leader's clock and stays unset).
            from . import report as report_mod

            try:
                rep = report_mod.build_from_leader(leader)
                paths = report_mod.write_report(rep, args.report)
                ulog.log.info("run report written by adopted leader",
                              **paths)
                print(f"Run report: {paths['json']} "
                      f"(provenance {paths['provenance']})", flush=True)
            except OSError as e:
                ulog.log.error("run report write failed", err=repr(e))
    if sub_ctl is not None:
        # A one-shot sub-leader must not exit before its members' final
        # telemetry flushes folded upward (docs/hierarchy.md).
        sub_ctl.drain()
    ulog.log.info("received startup: ready")
    if fabric is not None or args.hbm:
        # Executable-reuse evidence for this process's device plane
        # (harnesses grep the structured record).
        from ..parallel import plan_cache

        plan_cache.log_stats()
    print("ready", flush=True)
    if receiver.expect_serve:
        # Multi-controller serving: a ServeMsg follows startup; stay
        # alive to enter the pod-wide pipelined forward (pp_serve).
        # Two clocks on purpose.  The first spans EVERY member's stage
        # boot (the leader dispatches ServeMsg — or an explicit cancel —
        # only after the last BootReadyMsg), so it is generous; it is a
        # backstop against a dead leader, not the normal release path.
        # The second covers the collective itself — exiting
        # mid-collective would crash the healthy members.
        import queue as _queue

        if not receiver.serve_started.wait(timeout=1800.0):
            ulog.log.error("expected ServeMsg never arrived")
        else:
            try:
                receiver.serve_done().get(timeout=3600.0)
            except _queue.Empty:
                ulog.log.error("pod serve never completed")
    # A started boot runs on daemon threads: exiting now would kill it
    # silently and strand the leader's TTFT wait on the missing report.
    if not receiver.wait_boot_drain(timeout=args.bw):
        ulog.log.error("boot still running at exit timeout; leaving")
    if args.serve > 0 and receiver.boot_result is not None:
        # Inference window: the booted engine answers GenerateReqMsg
        # (cli.genreq) from its resident params until the window closes.
        ulog.log.info("serving generation requests",
                      window_s=args.serve)
        print(f"serving for {args.serve:g}s", flush=True)
        time.sleep(args.serve)
    if args.daemon > 0:
        # Dissemination service (docs/service.md): the leader daemon
        # keeps admitting jobs, so this seat keeps receiving (and
        # serving) layers — its message loop stays live for the window.
        ulog.log.info("daemon window: serving dissemination jobs",
                      window_s=args.daemon)
        print(f"daemon: serving jobs for {args.daemon:g}s", flush=True)
        time.sleep(args.daemon)
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    ulog.configure(node=str(args.id), verbose=args.v)
    conf = cfg.read_json(args.f)

    if args.submit or args.jobs:
        # One-shot service tools: no fabrication, no role loop — talk
        # to the running leader daemon and exit (docs/service.md).
        return run_jobtool(args, conf)

    if (args.rollouts or args.rollout_pause or args.rollout_resume
            or args.rollout_split):
        # One-shot rollout-pipeline tools (docs/rollout.md).
        return run_rollouttool(args, conf)

    if args.policies or args.policy_enable or args.policy_disable:
        # One-shot autonomy tools (docs/autonomy.md).
        return run_policytool(args, conf)

    if args.drain >= 0:
        # One-shot membership tool (docs/membership.md): ask the leader
        # to drain the named node and report its answer.
        return run_draintool(args, conf)

    if args.c:
        return run_client(args, conf)

    if (conf.mesh is not None and conf.mesh.fabric
            and conf.distributed is None):
        # One OS process per node cannot share an in-process FabricPlane;
        # refusing beats silently running the TCP data plane the config
        # opted out of.  Checked BEFORE any distributed init: joining the
        # pod runtime blocks on every rank, and a doomed run must fail
        # fast instead.  WITH a Distributed section the processes join one
        # JAX runtime and the multi-controller SPMD fabric
        # (parallel/spmd_fabric.py) carries the layer bytes instead.
        raise SystemExit(
            "config has Mesh.Fabric=true but no Distributed section: the "
            "in-process pod-fabric data plane runs all nodes under one "
            "controller — use "
            "`python -m distributed_llm_dissemination_tpu.cli.podrun "
            f"-f {args.f} -m {args.m}`, add a Distributed section for the "
            "multi-controller SPMD fabric, or drop the Fabric flag to run "
            "per-node processes over TCP"
        )

    if conf.distributed is not None:
        # Join the pod-wide JAX runtime BEFORE any device use, so a
        # configured Mesh can span hosts.  Gated on the config section so
        # pure-TCP nodes never pay the jax import; external clients never
        # join (they are auxiliary byte servers, not mesh ranks).
        from ..parallel.multihost import honor_jax_platforms, maybe_initialize

        honor_jax_platforms()
        maybe_initialize(conf, args.id)

    node_conf = cfg.get_node_conf(conf, args.id)
    if (args.m == 3 and node_conf.is_leader and conf.mesh is not None
            and conf.mesh.topology() is not None):
        # Adversarial-holdings topology solves need the exact LP; its
        # ~2 s one-time scipy/HiGHS initialization starts here — the
        # earliest possible moment — so it overlaps fabrication and the
        # announce round-trips instead of the TTD clock.  (The common
        # attribution-first path never touches scipy at all.)
        import threading as _threading

        from ..sched.flow import warm_lp

        _threading.Thread(target=warm_lp, name="lp-warm",
                          daemon=True).start()
    try:
        my_client_conf = cfg.get_client_conf(conf, args.id)
    except ValueError:
        my_client_conf = None
        ulog.log.info("external client not found in config")

    save_disk = bool(args.s)

    def fabricate():
        layers = cfg.create_layers(node_conf, save_disk, args.s or ".",
                                   model=conf.model,
                                   model_seed=conf.model_seed,
                                   model_codec=conf.model_codec)
        if my_client_conf is not None:
            cfg.add_client_layers(my_client_conf, conf.layer_size, layers)
        return layers

    if args.l:
        fabricate()
        ulog.log.info("layer set up")
        return 0

    addr_registry = {nc.id: nc.addr for nc in conf.nodes}
    if my_client_conf is not None:
        addr_registry[CLIENT_ID] = my_client_conf.addr

    # Bind the port BEFORE fabricating: seeding physical-size blobs takes
    # minutes, and a leader that only listens afterwards forces every
    # receiver (whose dial retry budget is ~10 s) to be spawned against a
    # polled port.  The transport's delivery queue simply buffers any
    # announces that arrive while fabrication runs.
    transport = TcpTransport(node_conf.addr, addr_registry=addr_registry)
    # TEST-ONLY deterministic fault injection (transport/faults.py):
    # armed exclusively by explicit flags — construction-gated, so no
    # environment variable can inject faults into a production run.
    fault_spec = args.test_faults or ""
    if args.test_drop_plan_seqs.strip():
        seqs = ";".join(s.strip()
                        for s in args.test_drop_plan_seqs.split(",")
                        if s.strip())
        fault_spec = (fault_spec + "," if fault_spec else "") + \
            f"drop-plan-seqs={seqs}"
    if fault_spec:
        from ..transport.faults import FaultyTransport, rules_from_spec

        seed, rules = rules_from_spec(fault_spec)
        transport = FaultyTransport(transport, rules, seed=seed)
        ulog.log.warn("TEST fault injection armed", spec=fault_spec)
    try:
        layers = fabricate()
        # Hierarchical control (docs/hierarchy.md): a grouped member's
        # control parent is its SUB-LEADER — announces, acks,
        # heartbeats, and metric reports all fold there; the root only
        # ever sees the group aggregate.
        parent = cfg.get_leader_conf(conf).id
        groups = resolve_groups(conf, args.m)
        if groups is not None:
            for rec in groups.values():
                if args.id in rec["members"] and args.id != rec["leader"]:
                    parent = rec["leader"]
                    break
        node = Node(args.id, parent, transport)
        if node_conf.is_leader:
            return run_leader(args, conf, node, layers)
        return run_receiver(args, conf, node, layers)
    finally:
        transport.close()
        if conf.distributed is not None:
            # Orderly pod-runtime teardown: interpreter exit destroying
            # the coordination client's still-joinable C++ threads
            # occasionally aborts (std::terminate) an otherwise-green
            # run.
            from ..parallel.multihost import maybe_shutdown

            maybe_shutdown()


if __name__ == "__main__":
    sys.exit(main())
