"""Request inference from a booted deployment.

The terminal step of the whole pipeline: after ``cli.main`` disseminated
the weights and the startup hook booted the engine, any topology node's
seat can ask it for tokens —

    python -m distributed_llm_dissemination_tpu.cli.genreq \\
        -f conf.json -id 2 -node 3 -prompt 128000,3923,374 -n 16

binds node 2's address from the topology, sends a ``GenerateReqMsg`` to
node 3, and prints the decoded ids as JSON on stdout.  ``-id`` must name
a topology node NOT also running ``cli.main`` in this process space (the
request/response plane multiplexes on the node's address; default: the
highest node id with no assignment and no initial layers, the natural
"idle seat").
"""

from __future__ import annotations

import argparse
import json
import sys

from ..core import config as cfg_mod
from ..runtime.client import GenRequester
from ..transport.tcp import TcpTransport
from ..utils import logging as ulog
from ..utils.logging import log


def _idle_seat(conf) -> int:
    """The highest node id holding nothing, assigned nothing, and with
    no attached external client — client-attached seats DO run cli.main
    (the leader awaits them), so their address is already bound by a
    live node process and binding it here would fail or hijack replies."""
    client_ids = {cc.id for cc in conf.clients}
    for nc in sorted(conf.nodes, key=lambda n: -n.id):
        holds = any(nc.initial_layers.values()) if nc.initial_layers else False
        if (not holds and nc.id not in conf.assignment
                and not nc.is_leader and nc.id not in client_ids):
            return nc.id
    raise SystemExit(
        "no idle node seat in the topology; pass -id explicitly")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="genreq")
    p.add_argument("-f", type=str, required=True, help="topology JSON")
    p.add_argument("-node", type=int, required=True,
                   help="the booted node to ask")
    p.add_argument("-prompt", type=str, default="",
                   help="comma-separated prompt token ids")
    p.add_argument("-text", type=str, default="",
                   help="prompt as text — needs an hf:<dir> Model whose "
                        "checkpoint dir has a tokenizer; the reply then "
                        "also carries decoded text")
    p.add_argument("-n", type=int, default=16, help="tokens to decode")
    p.add_argument("-temp", type=float, default=0.0,
                   help="sampling temperature (0 = greedy)")
    p.add_argument("-seed", type=int, default=0,
                   help="sampling seed (same seed, same tokens)")
    p.add_argument("-id", type=int, default=-1,
                   help="this requester's node seat (default: the "
                        "highest idle node in the topology)")
    p.add_argument("-t", type=float, default=300.0, help="reply timeout s")
    p.add_argument("-v", action="store_true")
    args = p.parse_args(argv)
    ulog.configure(node="genreq", verbose=args.v)

    conf = cfg_mod.read_json(args.f)
    my_id = args.id if args.id >= 0 else _idle_seat(conf)
    by_id = {nc.id: nc for nc in conf.nodes}
    if my_id not in by_id:
        raise SystemExit(f"-id {my_id} is not a topology node")
    if args.node not in by_id:
        raise SystemExit(f"-node {args.node} is not a topology node")
    if bool(args.prompt) == bool(args.text):
        raise SystemExit("give exactly one of -prompt (token ids) or "
                         "-text (needs an hf: Model)")

    tokenizer = None
    if args.text:
        if not conf.model.startswith("hf:"):
            raise SystemExit(
                f"-text needs an hf:<dir> Model (config has "
                f"{conf.model!r}); use -prompt with token ids")
        from transformers import AutoTokenizer  # noqa: PLC0415

        tokenizer = AutoTokenizer.from_pretrained(conf.model[3:])
        prompt = [int(t) for t in tokenizer.encode(args.text)]
    else:
        prompt = [int(t) for t in args.prompt.split(",") if t.strip()]

    transport = TcpTransport(by_id[my_id].addr)
    transport.addr_registry.update({nc.id: nc.addr for nc in conf.nodes})
    requester = GenRequester(transport, my_id=my_id)
    try:
        tokens = requester.request(args.node, prompt, args.n,
                                   timeout=args.t, temperature=args.temp,
                                   seed=args.seed)
    except (RuntimeError, TimeoutError, OSError, ConnectionError) as e:
        log.error("generation request failed", err=str(e))
        print(json.dumps({"error": str(e)}))
        return 1
    finally:
        requester.close()
        transport.close()
    rec = {"node": args.node, "prompt": prompt, "tokens": tokens}
    if tokenizer is not None:
        rec["text"] = tokenizer.decode(tokens)
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
