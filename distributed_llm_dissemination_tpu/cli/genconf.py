"""Generate the five BASELINE benchmark topologies as config files.

``BASELINE.json`` (driver-provided) names five scenarios; the first is the
reference's own shape (shipped as ``conf/local_4node.json``), the rest are
materialized here so they can be run anywhere — full size on real clusters,
or scaled down by the TTD matrix for loopback recording:

1. 4 nodes, 3 dummy layers @1 MiB, mode 0            → conf/local_4node.json
2. 8-node mode-0 broadcast, 32 layers @400 MiB       → bench_8node_llama8b.json
3. 16-node mode-1 retransmit, 80 layers @1.6 GiB     → bench_16node_llama70b.json
4. 32-node contiguous pipeline Assignment, mode 1    → bench_32node_pipeline.json
5. 64-node pod, 126 layers @3.2 GiB + disk sources   → bench_64node_llama405b.json

Shape choices (documented here because the driver's scenario lines name
sizes, not topologies): scenario 2 is a pure broadcast — the leader seeds
every layer, every other node is assigned all of them.  Scenario 3 spreads
partial seeds over the first half of the nodes (mode 1's raison d'être:
peers co-serve) with the second half cold and assigned everything.
Scenario 4 assigns each non-leader node one contiguous layer range — the
pipeline-stage placement the Assignment doubles as (SURVEY §2.3).
Scenario 5 is scenario 4 at Llama-3-405B scale with layers seeded on DISK
(SourceType 1 @200 MiB/s, the reference's NVMe rate) on the leader plus
seven replica seeders — the disk-spill path.

    python -m distributed_llm_dissemination_tpu.cli.genconf -o conf/
"""

from __future__ import annotations

import argparse
import json
import os
import sys

MIB = 1 << 20
GIB = 1 << 30
NIC_BW = 1_562_500_000  # 12.5 Gbit/s, the reference's modeled NetworkBW
DISK_RATE = 209_715_200  # 200 MiB/s, the reference's NVMe source rate


def _node(node_id: int, port: int, leader: bool = False,
          source_type: int = 2, rate: int = 0, layers=None,
          layer_size: int = 0) -> dict:
    d = {
        "Id": node_id,
        "Addr": f":{port}",
        "NetworkBW": NIC_BW,
        "Sources": {str(source_type): rate},
        "InitialLayers": {},
    }
    if leader:
        d["IsLeader"] = True
    if layers:
        d["InitialLayers"] = {
            str(source_type): {str(lid): {"LayerSize": layer_size}
                               for lid in layers}
        }
    return d


def _contiguous_assignment(dests, n_layers: int) -> dict:
    """Each dest gets one contiguous slice — pipeline-stage placement."""
    per, rem = divmod(n_layers, len(dests))
    out, pos = {}, 0
    for i, dest in enumerate(dests):
        take = per + (1 if i < rem else 0)
        out[str(dest)] = {str(lid): {} for lid in range(pos, pos + take)}
        pos += take
    return out


def scenario_8node_llama8b() -> dict:
    """#2: 8-node mode-0 broadcast, 32 layers @400 MiB (Llama-3-8B)."""
    n_layers, size = 32, 400 * MIB
    nodes = [_node(0, 9180, leader=True, layers=range(n_layers),
                   layer_size=size)]
    nodes += [_node(i, 9180 + i) for i in range(1, 8)]
    return {
        "Nodes": nodes,
        "Assignment": {str(i): {str(lid): {} for lid in range(n_layers)}
                       for i in range(1, 8)},
        "LayerSize": size,
    }


def scenario_16node_llama70b() -> dict:
    """#3: 16-node mode-1, 80 layers @1.6 GiB (Llama-3-70B); nodes 1-7
    partially seed (10 layers each) so peers co-serve, nodes 8-15 cold."""
    n_layers, size = 80, int(1.6 * GIB)
    nodes = [_node(0, 9280, leader=True, layers=range(n_layers),
                   layer_size=size)]
    for i in range(1, 8):
        seed = range((i - 1) * 10, i * 10)
        nodes.append(_node(i, 9280 + i, layers=seed, layer_size=size))
    nodes += [_node(i, 9280 + i) for i in range(8, 16)]
    return {
        "Nodes": nodes,
        "Assignment": {str(i): {str(lid): {} for lid in range(n_layers)}
                       for i in range(8, 16)},
        "LayerSize": size,
    }


def scenario_32node_pipeline() -> dict:
    """#4: 32-node contiguous pipeline Assignment (80 layers), mode 1."""
    n_layers, size = 80, int(1.6 * GIB)
    nodes = [_node(0, 9380, leader=True, layers=range(n_layers),
                   layer_size=size)]
    nodes += [_node(i, 9380 + i) for i in range(1, 32)]
    return {
        "Nodes": nodes,
        "Assignment": _contiguous_assignment(list(range(1, 32)), n_layers),
        "LayerSize": size,
        "Mesh": {"AxisNames": ["nodes"], "AxisSizes": [32],
                 "PipelineAxis": "nodes"},
    }


def scenario_64node_llama405b() -> dict:
    """#5: 64-node pod, 126 layers @3.2 GiB (Llama-3-405B), mode 1, layers
    seeded on DISK (the NVMe spill path) on the leader + 7 replicas."""
    n_layers, size = 126, int(3.2 * GIB)
    nodes = [_node(0, 9480, leader=True, source_type=1, rate=DISK_RATE,
                   layers=range(n_layers), layer_size=size)]
    for i in range(1, 8):  # disk replica seeders
        nodes.append(_node(i, 9480 + i, source_type=1, rate=DISK_RATE,
                           layers=range(n_layers), layer_size=size))
    nodes += [_node(i, 9480 + i) for i in range(8, 64)]
    return {
        "Nodes": nodes,
        "Assignment": _contiguous_assignment(list(range(8, 64)), n_layers),
        "LayerSize": size,
        "Mesh": {"AxisNames": ["nodes"], "AxisSizes": [64],
                 "PipelineAxis": "nodes"},
    }


SCENARIOS = {
    "bench_8node_llama8b.json": scenario_8node_llama8b,
    "bench_16node_llama70b.json": scenario_16node_llama70b,
    "bench_32node_pipeline.json": scenario_32node_pipeline,
    "bench_64node_llama405b.json": scenario_64node_llama405b,
}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="genconf", prefix_chars="-")
    p.add_argument("-o", type=str, default="conf",
                   help="output directory for the generated configs")
    args = p.parse_args(argv)
    os.makedirs(args.o, exist_ok=True)
    for name, builder in SCENARIOS.items():
        path = os.path.join(args.o, name)
        with open(path, "w") as f:
            json.dump(builder(), f, indent=1)
        print(path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
