"""Disk read-throughput microbenchmark.

Equivalent of the reference's ``diskspeed`` tool
(``/root/reference/diskspeed/main.go:18-68``): time a full sequential read
of a file into RAM and print MiB/s.  Used to calibrate the per-source rate
limits (``Sources``) in the topology config — on TPU-VMs, run it against
the local NVMe scratch disk that stages checkpoints before the HBM upload.

Extensions over the reference: ``--size`` fabricates a test file first (so
no pre-existing layer file is needed), ``--drop-caches`` re-reads after an
fadvise(DONTNEED) to measure cold-cache throughput instead of page-cache
bandwidth (the reference relies on an external ``drop_caches`` in
``conf/exe.sh:16``), and the result is also emitted as one JSON line so
``collect_logs`` can merge it with run logs.

Usage:
    python -m distributed_llm_dissemination_tpu.cli.diskspeed <file>
    python -m distributed_llm_dissemination_tpu.cli.diskspeed --size 1G /nvme/t
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_CHUNK = 8 << 20  # 8 MiB read chunks


def parse_size(s: str) -> int:
    """'512M', '4G', '1048576' -> bytes."""
    s = s.strip().upper()
    mult = 1
    for suffix, m in (("K", 1 << 10), ("M", 1 << 20), ("G", 1 << 30)):
        if s.endswith(suffix):
            s, mult = s[: -len(suffix)], m
            break
    return int(float(s) * mult)


def fabricate(path: str, size: int) -> None:
    """Write ``size`` pseudo-random-ish bytes (not zeros: some filesystems
    and SSD firmware short-circuit all-zero blocks)."""
    block = os.urandom(1 << 20)
    with open(path, "wb") as f:
        remaining = size
        while remaining > 0:
            n = min(remaining, len(block))
            f.write(block[:n])
            remaining -= n
        f.flush()
        os.fsync(f.fileno())


def drop_cache(path: str) -> None:
    """Evict the file from the page cache (best effort)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
        if hasattr(os, "posix_fadvise"):
            os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_DONTNEED)
    finally:
        os.close(fd)


def read_throughput(path: str) -> tuple[int, float]:
    """Full sequential read into RAM; returns (bytes, seconds) —
    the reference's Read() (diskspeed/main.go:47-68)."""
    total = 0
    t0 = time.monotonic()
    with open(path, "rb", buffering=0) as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            total += len(chunk)
    return total, time.monotonic() - t0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="diskspeed", description=__doc__)
    p.add_argument("file", help="file to read (created if --size is given)")
    p.add_argument("--size", type=parse_size, default=None,
                   help="fabricate the file at this size first (e.g. 4G)")
    p.add_argument("--drop-caches", action="store_true",
                   help="fadvise(DONTNEED) before reading (cold-cache run)")
    args = p.parse_args(argv)

    if args.size is not None:
        fabricate(args.file, args.size)
    if args.drop_caches:
        drop_cache(args.file)

    nbytes, secs = read_throughput(args.file)
    mibps = nbytes / max(secs, 1e-9) / (1 << 20)
    print(f"read {nbytes} bytes in {secs:.3f}s: {mibps:.1f} MiB/s")
    print(json.dumps({
        # unix-ms "time" keys the collect_logs merge; without it the
        # calibration record would be silently dropped from the trace
        "time": int(time.time() * 1000),
        "metric": "disk read throughput",
        "file": args.file,
        "bytes": nbytes,
        "seconds": round(secs, 6),
        "value": round(mibps, 1),
        "unit": "MiB/s",
        # the config wants bytes/sec for Sources rate limits
        "sources_rate": int(nbytes / max(secs, 1e-9)),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
