"""TTD matrix: time-to-deliver across all four modes, recorded.

The reference's primary metric is time-to-deliver, printed per run
(``/root/reference/cmd/main.go:173-181``) and never recorded anywhere.
This harness runs the REAL CLI (one OS process per node, loopback TCP —
the reference's own benchmark shape, ``distributor/node_test.go:275-326``)
for every mode over the shipped topologies and emits a checked-in matrix,
including the north-star secondary target: mode 1 (peer retransmission)
matching mode 0 (leader broadcast) completion time.

    python -m distributed_llm_dissemination_tpu.cli.ttd_matrix \
        -o TTD_MATRIX.json [-scale BYTES] [-trials N]

Scenarios:
- ``local_4node``: 4 receivers + leader, 3 dummy layers @1 MiB.
- ``reference_8node``: the reference benchmark topology (7 seeders co-send
  one cold node's full model) with LayerSize scaled from 10.18 GiB down to
  ``-scale`` bytes so the matrix runs on loopback in seconds.  Rates and
  NIC bandwidths stay at their configured (physical) values — the matrix
  compares the MODES' scheduling behavior, which scaled-down rates would
  drown in artificial pacing.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import statistics
import subprocess
import sys
import tempfile
import time

CONF_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "conf")
_TTD_RE = re.compile(r"Time to deliver: ([0-9.]+)s")
# Mode-3 plan fidelity: the leader prints its solver's min-time next to
# the achieved TTD (cli.main); recorded as predicted_s/solve_ms columns.
_PRED_RE = re.compile(
    r"Predicted time to deliver: ([0-9.]+)s \(solve ([0-9.]+)ms\)")


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def measure_loopback_gbps(streams: int = 1, per_stream: int = 192 << 20,
                          chunk: int = 1 << 20) -> float:
    """This host's RAW loopback TCP bandwidth: ``streams`` concurrent
    sender/receiver thread pairs move ``per_stream`` bytes each through
    plain sockets (sendall / recv_into, no framing, no assembly) and the
    aggregate bytes-over-wall-clock is the ceiling the physical rows are
    judged against — the same honest-denominator pattern as bench.py's
    ``raw_dma_gbps``/``link_fraction``.  Multi-stream probes measure what
    the STRIPED data plane can draw on; on small hosts the loopback is
    CPU-bound, so more streams than cores can come back SLOWER than one —
    which is exactly why the ceiling must be measured, not assumed."""
    import socket
    import threading

    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]

    def sender():
        with socket.create_connection(("127.0.0.1", port)) as s:
            buf = memoryview(bytearray(chunk))
            sent = 0
            while sent < per_stream:
                s.sendall(buf[: min(chunk, per_stream - sent)])
                sent += chunk

    # Bytes each receiver REALLY got: a sender thread dying mid-stream
    # (its exception is swallowed by the thread) must shrink the
    # numerator, not silently inflate the recorded ceiling.
    delivered = [0] * streams

    def receiver(conn, slot):
        with conn:
            buf = bytearray(4 << 20)
            while delivered[slot] < per_stream:
                r = conn.recv_into(buf)
                if r == 0:
                    return
                delivered[slot] += r

    senders = [threading.Thread(target=sender, daemon=True)
               for _ in range(streams)]
    t0 = time.monotonic()
    for t in senders:
        t.start()
    # A sender whose connect fails dies with its exception swallowed by
    # the thread; without a timeout the accept() below would then hang
    # the whole harness before any node process even spawns.  A failed
    # probe returns 0.0 and the caller skips the ceiling columns.
    srv.settimeout(30.0)
    receivers = []
    accepted = []
    try:
        for i in range(streams):
            conn = srv.accept()[0]
            accepted.append(conn)
            receivers.append(threading.Thread(
                target=receiver, args=(conn, i)))
    except OSError:
        print("loopback ceiling probe failed (accept timeout); "
              "skipping ceiling columns", file=sys.stderr)
        # Release everything or the stuck senders outlive the probe:
        # closing the accepted conns fails their peers' sendall, and
        # closing the listener fails any connect still retrying.
        for conn in accepted:
            conn.close()
        srv.close()
        for t in senders:
            t.join(timeout=5.0)
        return 0.0
    for t in receivers:
        t.start()
    for t in receivers:
        t.join()
    dt = time.monotonic() - t0
    for t in senders:
        t.join(timeout=10.0)
    srv.close()
    return round(sum(delivered) / max(dt, 1e-9) / 1e9, 3)


def _cpu_env() -> dict:
    from distributed_llm_dissemination_tpu.utils.env import cpu_pinned_env

    return cpu_pinned_env()


def _localize_config(src_path: str, out_path: str,
                     scale_to: int = 0, mutate=None) -> None:
    """Rewrite node/client addresses to free loopback ports (the shipped
    configs use fixed ports that anything else on the host may hold) and,
    when ``scale_to`` > 0, scale every LayerSize down to loopback-friendly
    bytes; rates and NIC bandwidths keep their configured (physical)
    values.  ``mutate``: optional callback applied to the loaded dict
    before the rewrite — scenario-specific edits share this one
    load/write path."""
    with open(src_path) as f:
        conf = json.load(f)
    if mutate is not None:
        mutate(conf)
    if scale_to > 0:
        if "LayerSize" in conf:
            conf["LayerSize"] = scale_to
        for n in conf["Nodes"]:
            for by_layer in (n.get("InitialLayers") or {}).values():
                for lc in by_layer.values():
                    if "LayerSize" in lc:
                        lc["LayerSize"] = scale_to
    for n in conf["Nodes"]:
        n["Addr"] = f"127.0.0.1:{_free_port()}"
    for c in conf.get("Clients") or []:
        c["Addr"] = f"127.0.0.1:{_free_port()}"
    with open(out_path, "w") as f:
        json.dump(conf, f)


def run_once(conf_path: str, mode: int, timeout: float = 120.0,
             env: dict = None, extra_args=()) -> float:
    """One full dissemination via the real CLI; returns the leader's TTD.
    ``extra_args`` go to every node process (not external clients), e.g.
    ("-boot", "none") for dissemination-only runs of boot topologies."""
    with open(conf_path) as f:
        conf = json.load(f)
    leader_id = next(n["Id"] for n in conf["Nodes"]
                     if n.get("IsLeader") or n.get("isLeader"))
    receiver_ids = [n["Id"] for n in conf["Nodes"] if n["Id"] != leader_id]
    client_ids = [c["Id"] for c in conf.get("Clients") or []]

    def spawn(node_id, extra=()):
        return subprocess.Popen(
            [sys.executable, "-m",
             "distributed_llm_dissemination_tpu.cli.main",
             "-id", str(node_id), "-f", conf_path, "-m", str(mode), *extra],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        )

    procs = []
    try:
        leader = spawn(leader_id, extra_args)
        procs.append(leader)
        time.sleep(0.3)  # listener up before the dial-retry window matters
        for rid in receiver_ids:
            procs.append(spawn(rid, extra_args))
        for cid in client_ids:
            procs.append(spawn(cid, ("-c",)))
        out, _ = leader.communicate(timeout=timeout)
        text = out.decode()
        m = _TTD_RE.search(text)
        if not m:
            raise RuntimeError(
                f"no TTD in leader output (mode {mode}): {out[-2000:]!r}"
            )
        pm = _PRED_RE.search(text)
        run_once.last_predicted = (
            (float(pm.group(1)), float(pm.group(2))) if pm else None)
        for p in procs[1:]:
            if p.args[-1] != "-c":  # clients run forever; killed below
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    # Known container flake (see run_span_overhead): a
                    # seat sporadically wedges in its post-run
                    # ack-requeue loop.  The TTD above is already
                    # measured, so kill the straggler instead of
                    # failing the whole matrix.
                    print(f"warn: post-run seat wedge (pid {p.pid}), "
                          "killing — known container flake",
                          file=sys.stderr)
                    p.kill()
        return float(m.group(1))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def _parse_summary_line(out: str):
    """podrun's machine-readable summary (the last JSON line carrying
    ``ttd_s``): collective-cache stats + phase totals, or None."""
    summary = None
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if "ttd_s" in d:
                summary = d
    return summary


def run_once_pod(conf_path: str, mode: int, timeout: float = 240.0) -> float:
    """One fabric dissemination via the single-controller pod driver
    (cli.podrun) on a virtual 8-device CPU mesh; returns the TTD.  The
    layer bytes move over the device plane — this row measures the
    fabric's scheduling + ingest path, not TCP."""
    env = _cpu_env()
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    proc = subprocess.run(
        [sys.executable, "-m",
         "distributed_llm_dissemination_tpu.cli.podrun",
         "-f", conf_path, "-m", str(mode)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        timeout=timeout, env=env,
    )
    out = proc.stdout.decode()
    m = _TTD_RE.search(out)
    if not m:
        raise RuntimeError(
            f"no TTD in podrun output (mode {mode}): {proc.stdout[-2000:]!r}"
        )
    # Stash the run's machine-readable summary (collective-cache stats,
    # phase totals) for run_matrix to fold into the scenario record.
    summary = _parse_summary_line(out)
    run_once_pod.last_summary = summary
    run_once_pod.last_predicted = (
        (summary["predicted_s"], summary.get("solve_ms", 0.0))
        if summary and "predicted_s" in summary else None)
    return float(m.group(1))


def spmd_two_proc_config(scale: int, layers: int = 3) -> dict:
    """A 2-process multi-controller SPMD fabric topology (leader seeds,
    node 1 assigned): one OS process per node, one jax.distributed
    runtime, layer bytes as lockstep collectives
    (``parallel/spmd_fabric.py``).  Free loopback ports are assigned
    here.  THE shared builder: the recorded matrix row and the 2-process
    e2e tests (tests/test_spmd_fabric.py) exercise the same topology."""
    return {
        "Nodes": [
            {"Id": 0, "Addr": f"127.0.0.1:{_free_port()}", "IsLeader": True,
             "NetworkBW": 12500000000, "Sources": {"2": 0},
             "InitialLayers": {"2": {str(i): {"LayerSize": scale}
                                     for i in range(layers)}}},
            {"Id": 1, "Addr": f"127.0.0.1:{_free_port()}",
             "NetworkBW": 12500000000, "Sources": {"2": 0},
             "InitialLayers": {}},
        ],
        "Assignment": {"1": {str(i): {} for i in range(layers)}},
        "LayerSize": scale,
        "Mesh": {"AxisNames": ["nodes"], "AxisSizes": [2],
                 "PipelineAxis": "nodes", "Fabric": True},
        "Distributed": {"Coordinator": f"127.0.0.1:{_free_port()}",
                        "CpuCollectives": "gloo"},
    }


def spmd_pod_config(scale: int, layers: int = 2) -> dict:
    """A 3-process SPMD pod-delivery topology (docs/fabric.md): leader
    0 seeds; nodes 1 and 2 form ONE pod and both want every layer —
    the NIC ships each member its 1/2 shard (host TCP), and the leader
    dispatches the pod gather as a lockstep collective that leaves the
    full tree on BOTH members.  The shared builder for the 3-process
    e2e test (tests/test_spmd_fabric.py)."""
    return {
        "Nodes": [
            {"Id": 0, "Addr": f"127.0.0.1:{_free_port()}",
             "IsLeader": True, "NetworkBW": 12500000000,
             "Sources": {"2": 0},
             "InitialLayers": {"2": {str(i): {"LayerSize": scale}
                                     for i in range(layers)}}},
            {"Id": 1, "Addr": f"127.0.0.1:{_free_port()}",
             "NetworkBW": 12500000000, "Sources": {"2": 0},
             "InitialLayers": {}},
            {"Id": 2, "Addr": f"127.0.0.1:{_free_port()}",
             "NetworkBW": 12500000000, "Sources": {"2": 0},
             "InitialLayers": {}},
        ],
        "Assignment": {"1": {str(i): {} for i in range(layers)},
                       "2": {str(i): {} for i in range(layers)}},
        "LayerSize": scale,
        "Pods": [[1, 2]],
        "Mesh": {"AxisNames": ["nodes"], "AxisSizes": [3],
                 "PipelineAxis": "nodes", "Fabric": True},
        "Distributed": {"Coordinator": f"127.0.0.1:{_free_port()}",
                        "CpuCollectives": "gloo"},
    }


def _spmd_config(out_path: str, scale: int) -> None:
    with open(out_path, "w") as f:
        json.dump(spmd_two_proc_config(scale), f)


def run_once_spmd(conf_path: str, mode: int, timeout: float = 240.0) -> float:
    """One dissemination over the multi-controller SPMD fabric: the REAL
    per-node CLI, one OS process per node, collectives over gloo."""
    env = _cpu_env()
    env.pop("XLA_FLAGS", None)  # one device per process
    return run_once(conf_path, mode, timeout, env=env)


def run_matrix(scale: int, trials: int, modes=(0, 1, 2, 3),
               timeout: float = 240.0) -> dict:
    with tempfile.TemporaryDirectory() as td:
        local4 = os.path.join(td, "local_4node.json")
        _localize_config(os.path.join(CONF_DIR, "local_4node.json"), local4)
        scaled = os.path.join(td, "reference_8node_scaled.json")
        _localize_config(os.path.join(CONF_DIR, "reference_8node.json"),
                         scaled, scale_to=scale)
        fabric = os.path.join(td, "pod_fabric_4node.json")
        _localize_config(os.path.join(CONF_DIR, "pod_fabric_4node.json"),
                         fabric, scale_to=scale)
        spmd = os.path.join(td, "spmd_2proc.json")
        _spmd_config(spmd, scale)

        def drop_fabric(conf):
            # Host-path run of the 2-slice topology: the mode-3 leader
            # still receives Mesh.Slices/DcnBW (the topology LP paces
            # cross-slice senders to the pair capacity) but no process
            # needs the 32-device fabric mesh.
            conf.get("Mesh", {}).pop("Fabric", None)

        dcn = os.path.join(td, "tpu_2slice_dcn.json")
        _localize_config(os.path.join(CONF_DIR, "tpu_2slice_dcn.json"),
                         dcn, scale_to=scale, mutate=drop_fabric)
        scenarios = {
            "local_4node": (local4, run_once),
            f"reference_8node@{scale >> 20}MiB": (scaled, run_once),
            f"dcn_2slice_8node@{scale >> 20}MiB": (dcn, run_once),
            f"pod_fabric_4node@{scale >> 20}MiB": (fabric, run_once_pod),
            f"spmd_fabric_2proc@{scale >> 20}MiB": (spmd, run_once_spmd),
        }
        results: dict = {"scenarios": {}, "scale_bytes": scale,
                         "trials": trials}
        for name, (path, runner) in scenarios.items():
            per_mode = {}
            for mode in modes:
                ts = [runner(path, mode, timeout) for _ in range(trials)]
                per_mode[str(mode)] = {
                    "ttd_s": round(statistics.median(ts), 4),
                    "all": [round(t, 4) for t in ts],
                }
                summary = getattr(runner, "last_summary", None)
                if summary and summary.get("collective_cache"):
                    per_mode[str(mode)]["collective_cache"] = (
                        summary["collective_cache"])
                if summary and summary.get("telemetry"):
                    # Each pod run's counter/histogram snapshot rides its
                    # row — event counts come from the run's own flight
                    # recorder, not hand-collected greps.
                    per_mode[str(mode)]["telemetry"] = summary["telemetry"]
                if mode == 3:
                    # Plan fidelity: the last trial's solver prediction
                    # (deterministic across trials) next to achieved TTD.
                    pred = getattr(runner, "last_predicted", None)
                    if pred is None and runner is run_once_spmd:
                        pred = getattr(run_once, "last_predicted", None)
                    if pred:
                        per_mode["3"]["predicted_s"] = round(pred[0], 4)
                        per_mode["3"]["solve_ms"] = round(pred[1], 3)
                print(f"{name} mode {mode}: TTD {per_mode[str(mode)]['ttd_s']}s",
                      file=sys.stderr, flush=True)
            if "0" in per_mode and "1" in per_mode:
                per_mode["mode1_vs_mode0"] = round(
                    per_mode["1"]["ttd_s"] / max(per_mode["0"]["ttd_s"], 1e-9), 3
                )
            results["scenarios"][name] = per_mode
    return results


def _codec_variant(src_path: str, out_path: str, codec: str,
                   rate: int) -> None:
    """boot_tiny_4node's topology, retargeted at the tiny2 model (~2 MiB
    layers, so the 256 KiB burst bucket is noise), every in-RAM source
    rate-limited to ``rate`` B/s, under the given transfer codec — the
    A/B pair where TTD is bytes over a fixed rate, so the codec's
    wire-size ratio shows up as the TTD ratio."""
    def mutate(conf):
        conf["Model"] = "tiny2"
        conf["ModelCodec"] = codec
        for n in conf["Nodes"]:
            n["Sources"] = {"2": rate}

    _localize_config(src_path, out_path, mutate=mutate)


def run_codec_ab(trials: int, rate: int = 4 << 20, mode: int = 3,
                 timeout: float = 240.0) -> dict:
    """Measured codec benefit: the same model topology disseminated
    raw vs int8 vs int4 at a fixed source rate (models/quant.py shrinks
    the blob bytes ~0.51x / ~0.27x, so mode-3 completion time should
    shrink by roughly the same ratio; the transport's reference-parity
    256 KiB burst bucket gives each job a free head start, so at tiny2's
    ~2 MiB layers the measured ratios sit a bit below the pure size
    ratios)."""
    out: dict = {"rate_bytes_per_s": rate, "mode": mode, "model": "tiny2"}
    # Blob fabrication imports jax in the receivers: CPU-pinned so the
    # row measures the rate-limited wire, not the device.  -boot none
    # skips the post-TTD model boot (compile seconds per run that the
    # TTD timer doesn't even see).
    env = _cpu_env()
    with tempfile.TemporaryDirectory() as td:
        for codec in ("raw", "int8", "int4"):
            path = os.path.join(td, f"boot_{codec}.json")
            _codec_variant(os.path.join(CONF_DIR, "boot_tiny_4node.json"),
                           path, codec, rate)
            ts = [run_once(path, mode, timeout, env=env,
                           extra_args=("-boot", "none"))
                  for _ in range(trials)]
            out[codec] = {"ttd_s": round(statistics.median(ts), 4),
                          "all": [round(t, 4) for t in ts]}
            print(f"codec {codec}: TTD {out[codec]['ttd_s']}s",
                  file=sys.stderr, flush=True)
    for codec in ("int8", "int4"):
        out[f"{codec}_vs_raw"] = round(
            out[codec]["ttd_s"] / max(out["raw"]["ttd_s"], 1e-9), 3
        )
    return out


def _codec_wire_variant(src_path: str, out_path: str, wire_codec: str,
                        rate: int) -> None:
    """boot_tiny_4node retargeted at tiny2 with RAW canonical blobs and
    every in-RAM source rate-limited — the rate-limited BASELINE the
    negotiated wire codec exists for.  ``wire_codec`` "" leaves the
    run canonical (the A side)."""
    def mutate(conf):
        conf["Model"] = "tiny2"
        if wire_codec:
            conf["WireCodec"] = wire_codec
        for n in conf["Nodes"]:
            n["Sources"] = {"2": rate}

    _localize_config(src_path, out_path, mutate=mutate)


def run_codec_wire(trials: int, rate: int = 4 << 20, mode: int = 3,
                   timeout: float = 240.0) -> dict:
    """The NEGOTIATED wire-codec row (docs/codec.md): the same
    raw-canonical tiny2 topology disseminated with and without
    ``WireCodec: int8`` at a fixed slow source rate.  Unlike
    ``run_codec_ab`` (which re-fabricates the whole run's blobs in the
    codec), here the SEEDERS HOLD RAW BYTES and the leader chooses the
    encoded form per transfer — encode-on-send, decode-at-staging,
    codec-qualified digests — so the TTD ratio measures the negotiated
    plane end to end.  The RUN_REPORT's per-dest table cross-checks the
    wire bytes against ``quant.blob_nbytes_codec`` exactly."""
    from ..models import quant
    from ..models.llama import CONFIGS

    mcfg = CONFIGS["tiny2"]
    blob_ids = list(range(5))  # boot_tiny_4node assigns blobs 0-4
    raw_bytes = sum(quant.blob_nbytes_codec(mcfg, b, "raw")
                    for b in blob_ids)
    int8_bytes = sum(quant.blob_nbytes_codec(mcfg, b, "int8")
                     for b in blob_ids)
    out: dict = {"rate_bytes_per_s": rate, "mode": mode, "model": "tiny2",
                 "raw_bytes_per_dest": raw_bytes,
                 "int8_bytes_per_dest": int8_bytes,
                 "ratio": round(raw_bytes / int8_bytes, 4)}
    env = _cpu_env()
    with tempfile.TemporaryDirectory() as td:
        for label, wire in (("raw_wire", ""), ("int8_wire", "int8")):
            path = os.path.join(td, f"wire_{label}.json")
            _codec_wire_variant(
                os.path.join(CONF_DIR, "boot_tiny_4node.json"),
                path, wire, rate)
            report = os.path.join(td, f"report_{label}")
            ts = []
            for k in range(trials):
                extra = ["-boot", "none"]
                if k == 0:
                    extra += ["-report", report]
                ts.append(run_once(path, mode, timeout, env=env,
                                   extra_args=tuple(extra)))
            row = {"ttd_s": round(statistics.median(ts), 4),
                   "all": [round(t, 4) for t in ts]}
            try:
                with open(report + ".json") as f:
                    rep = json.load(f)
                row["dests"] = rep.get("dests") or {}
                row["codec_counters"] = {
                    k: v for k, v in (rep.get("counters") or {}).items()
                    if k.startswith("codec.")}
                row["provenance"] = rep.get("provenance", "")
            except (OSError, ValueError):
                row["dests"] = {}
            ts_str = row["ttd_s"]
            print(f"codec_wire {label}: TTD {ts_str}s",
                  file=sys.stderr, flush=True)
            out[label] = row
    out["int8_vs_raw"] = round(
        out["int8_wire"]["ttd_s"] / max(out["raw_wire"]["ttd_s"], 1e-9), 3)
    # The acceptance cross-check: every dest's delivered wire bytes
    # must be EXACTLY the blob_nbytes_codec sums (int8 run), and the
    # TTD must drop ~proportionally to the compression ratio.
    dests = out["int8_wire"].get("dests") or {}
    out["wire_bytes_exact"] = bool(dests) and all(
        row.get("wire_bytes") == int8_bytes for row in dests.values())
    expect = 1.0 / out["ratio"]
    out["bound"] = {
        "expected_ttd_fraction": round(expect, 4),
        # The transport's reference-parity 256 KiB burst bucket gives
        # each job a free head start at these ~1-2 MiB layers, so allow
        # a generous margin above the pure size ratio.
        "met": out["int8_vs_raw"] <= expect * 1.35 + 0.05,
    }
    out["entropy"] = run_codec_wire_entropy(trials, rate=rate, mode=mode,
                                            timeout=timeout)
    return out


def run_codec_wire_entropy(trials: int, rate: int = 4 << 20,
                           mode: int = 3,
                           timeout: float = 240.0) -> dict:
    """The ENTROPY-CODED wire arm (docs/codec.md): the same tiny2
    topology under ``WireCodec: int8e``.  Entropy forms are
    DATA-DEPENDENT — their size is known only by encoding — so the
    leader must hold the blobs to price them: this variant seeds the
    leader with the full blob set (both arms, so the A/B stays fair)
    and the acceptance bar is EXACTNESS, not a byte win: every dest's
    delivered wire bytes must equal the solver-priced encoded sizes
    (computed independently here by DLE1-encoding the run's seeded
    blobs).  On tiny2's seeded-random weights the quantized bytes are
    near-incompressible, so int8e lands a hair ABOVE int8 — recorded
    honestly; the order-of-magnitude entropy wins live on sparse/
    low-entropy layers and on the delta rows."""
    from ..models import quant, serde
    from ..models.llama import CONFIGS

    mcfg = CONFIGS["tiny2"]
    blob_ids = list(range(5))  # boot_tiny_4node assigns blobs 0-4
    raw_bytes = sum(quant.blob_nbytes_codec(mcfg, b, "raw")
                    for b in blob_ids)
    # The independent pricing: encode the SAME seeded blobs the run
    # fabricates (ModelSeed 0) and sum the true DLE1 sizes.
    int8e_bytes = sum(
        len(quant.encode_blob(mcfg, b, serde.seeded_blob(mcfg, b, 0),
                              "int8e"))
        for b in blob_ids)
    int8_bytes = sum(quant.blob_nbytes_codec(mcfg, b, "int8")
                     for b in blob_ids)

    def variant(src_path: str, out_path: str, wire_codec: str) -> None:
        def mutate(conf):
            conf["Model"] = "tiny2"
            if wire_codec:
                conf["WireCodec"] = wire_codec
            # Seed the leader with every blob any seeder holds: the
            # data-dependent sizing encodes the leader's own copy.
            blobs: dict = {}
            for n in conf["Nodes"]:
                for by_layer in (n.get("InitialLayers") or {}).values():
                    blobs.update(by_layer)
            lead = next(n for n in conf["Nodes"] if n.get("IsLeader"))
            lead["InitialLayers"] = {"2": dict(blobs)}
            for n in conf["Nodes"]:
                n["Sources"] = {"2": rate}

        _localize_config(src_path, out_path, mutate=mutate)

    out: dict = {"rate_bytes_per_s": rate, "mode": mode,
                 "model": "tiny2",
                 "raw_bytes_per_dest": raw_bytes,
                 "int8_bytes_per_dest": int8_bytes,
                 "int8e_bytes_per_dest": int8e_bytes,
                 "ratio_vs_raw": round(raw_bytes / int8e_bytes, 4),
                 "int8e_vs_int8_bytes": round(int8e_bytes / int8_bytes,
                                              4)}
    env = _cpu_env()
    with tempfile.TemporaryDirectory() as td:
        for label, wire in (("raw_wire", ""), ("int8e_wire", "int8e")):
            path = os.path.join(td, f"wire_{label}.json")
            variant(os.path.join(CONF_DIR, "boot_tiny_4node.json"),
                    path, wire)
            report = os.path.join(td, f"report_{label}")
            ts = []
            for k in range(trials):
                extra = ["-boot", "none"]
                if k == 0:
                    extra += ["-report", report]
                ts.append(run_once(path, mode, timeout, env=env,
                                   extra_args=tuple(extra)))
            row = {"ttd_s": round(statistics.median(ts), 4),
                   "all": [round(t, 4) for t in ts]}
            try:
                with open(report + ".json") as f:
                    rep = json.load(f)
                row["dests"] = rep.get("dests") or {}
                row["codec_counters"] = {
                    k: v for k, v in (rep.get("counters") or {}).items()
                    if k.startswith("codec.")}
                row["provenance"] = rep.get("provenance", "")
            except (OSError, ValueError):
                row["dests"] = {}
            print(f"codec_wire entropy {label}: TTD {row['ttd_s']}s",
                  file=sys.stderr, flush=True)
            out[label] = row
    out["int8e_vs_raw"] = round(
        out["int8e_wire"]["ttd_s"] / max(out["raw_wire"]["ttd_s"], 1e-9),
        3)
    # The acceptance bar: wire bytes per dest EXACTLY equal the
    # solver-priced entropy sizes.
    dests = out["int8e_wire"].get("dests") or {}
    out["wire_bytes_exact"] = bool(dests) and all(
        row.get("wire_bytes") == int8e_bytes for row in dests.values())
    return out


# The driver-provided BASELINE.json scenarios (#2-#5), materialized by
# cli.genconf: (config file, the modes to record).  The 64-node row runs
# ALL FOUR modes so the mode-3 solver is exercised — and its solve time
# recorded — at the scenario's full node count (VERDICT item 6).
BASELINE_SCENARIOS = (
    ("bench_8node_llama8b.json", (0,)),
    ("bench_16node_llama70b.json", (1,)),
    ("bench_32node_pipeline.json", (1,)),
    ("bench_64node_llama405b.json", (0, 1, 2, 3)),
)


def run_baseline_scenarios(scale: int = 64 << 20,
                           timeout: float = 1200.0) -> dict:
    """Recorded TTDs for the BASELINE scenarios, at ≥64 MiB layers.

    Layer sizes scale down from physical (64-node Llama-405B at full
    size needs a real cluster) but stay big enough that the bandwidth
    term — not per-transfer overhead — dominates; node counts and
    schedules stay faithful: up to 64 OS processes over loopback, the
    reference's own benchmark shape.  Each scenario records its per-mode
    rows with the layer bytes; mode-3 rows carry the solver's
    predicted_s and solve_ms."""
    if scale <= 0:
        raise ValueError("baseline scale must be positive (bytes)")
    out = {}
    with tempfile.TemporaryDirectory() as td:
        for name, modes in BASELINE_SCENARIOS:
            local = os.path.join(td, name)
            _localize_config(os.path.join(CONF_DIR, name), local,
                             scale_to=scale)
            key = f"{os.path.splitext(name)[0]}@{scale >> 20}MiB"
            rows = []
            for mode in modes:
                ttd = run_once(local, mode, timeout)
                row = {"mode": mode, "ttd_s": round(ttd, 4),
                       "layer_bytes": scale}
                pred = getattr(run_once, "last_predicted", None)
                if mode == 3 and pred:
                    row["predicted_s"] = round(pred[0], 4)
                    row["solve_ms"] = round(pred[1], 3)
                rows.append(row)
                print(f"{key} mode {mode}: TTD {ttd:.4f}s",
                      file=sys.stderr, flush=True)
            out[key] = rows
    return out


def run_north_star(timeout_unused: float = 0.0) -> dict:
    """VERDICT item 5: argue the BASELINE north-star target (<10 s /
    ≥70% ICI utilization for Llama-70B's 80 layers on a v5e-32) by
    MODEL — run the mode-3 solver on ``conf/tpu_v5e32_llama70b.json``
    exactly as the leader would and record predicted completion time,
    aggregate rate, and the dest-side ICI-utilization fraction.  No
    hardware in the loop: the solver is the only instrument this
    environment allows, and its prediction-vs-achieved fidelity is
    regression-guarded separately (the predicted_s columns).

    Three rows, same assignment (each of 8 hosts ends up holding its 10
    pipeline-stage layers):
    - ``shipped``: the config as checked in — ONE seeder whose 80 blobs
      sit behind a 3 GB/s disk-class source;
    - ``mem_seeder``: the same seeder's blobs re-typed in-RAM (source
      uncapped, its 25 GB/s line rate is the ceiling);
    - ``mem_4seeders``: hot-spare replicas — 4 of the 8 hosts hold the
      full blob set in RAM, the paper's multi-seeder co-send shape.
    The variants isolate WHERE the target lives: the solver hits <10 s
    the moment sources stop being the bottleneck, and ≥70% dest-side
    utilization with replicated in-RAM seeders."""
    from ..core import config as cfgmod
    from ..core.types import LayerLocation, LayerMeta, SourceType
    from ..sched import make_flow_graph

    conf = cfgmod.read_json(
        os.path.join(CONF_DIR, "tpu_v5e32_llama70b.json"))
    line_bw = {nc.id: nc.network_bw for nc in conf.nodes}
    shipped_holdings = {}
    sizes = {}
    for nc in conf.nodes:
        by_node = {}
        for st, by_layer in (nc.initial_layers or {}).items():
            rate = nc.sources.get(st, 0)
            for lid, size in by_layer.items():
                size = size or conf.layer_size
                by_node[lid] = (st, rate, size)
                sizes[lid] = size
        if by_node:
            shipped_holdings[nc.id] = by_node
    topo = conf.mesh.topology() if conf.mesh is not None else None

    def solve(label: str, holdings: dict) -> dict:
        status = {nc.id: {} for nc in conf.nodes}
        layer_sizes = {}
        for node_id, by_node in holdings.items():
            for lid, (st, rate, size) in by_node.items():
                loc = (LayerLocation.DISK if st == SourceType.DISK
                       else LayerLocation.INMEM)
                status[node_id][lid] = LayerMeta(
                    location=loc, limit_rate=rate, source_type=st,
                    data_size=size)
                layer_sizes[lid] = size
        # The leader's assign_jobs discipline: pairs the dest already
        # holds are satisfied, the solver plans the rest.
        modified = {}
        for dest, lids in conf.assignment.items():
            for lid, meta in lids.items():
                if lid in status.get(dest, {}):
                    continue
                modified.setdefault(dest, {})[lid] = meta
        t0 = time.monotonic()
        graph = make_flow_graph(modified, status, layer_sizes, line_bw,
                                topology=topo)
        t_ms, jobs = graph.get_job_assignment()
        solve_ms = (time.monotonic() - t0) * 1000
        wire = sum(j.data_size for jl in jobs.values() for j in jl)
        pred_s = t_ms / 1000.0
        dests = {j.dest_id for jl in jobs.values() for j in jl}
        dest_cap = sum(line_bw[d] for d in sorted(dests))
        agg_gbps = wire / max(pred_s, 1e-9) / 1e9
        rec = {
            "label": label,
            "wire_bytes": wire,
            "predicted_s": round(pred_s, 3),
            "solve_ms": round(solve_ms, 1),
            "aggregate_gbps": round(agg_gbps, 2),
            "dest_line_gbps": round(dest_cap / 1e9, 1),
            "ici_utilization": round(agg_gbps / max(dest_cap / 1e9, 1e-9),
                                     3),
        }
        rec["meets_time"] = pred_s < 10.0
        rec["meets_utilization"] = rec["ici_utilization"] >= 0.70
        print(f"north_star {label}: predicted {pred_s:.2f}s, "
              f"{rec['ici_utilization']:.0%} dest-side utilization "
              f"(solve {solve_ms:.0f}ms)", file=sys.stderr, flush=True)
        return rec

    mem1 = {n: {lid: (SourceType.MEM, 0, size)
                for lid, (_st, _r, size) in by.items()}
            for n, by in shipped_holdings.items()}
    seeders4 = sorted(line_bw)[:4]
    mem4 = {n: {lid: (SourceType.MEM, 0, sizes[lid]) for lid in sizes}
            for n in seeders4}
    return {
        "config": "tpu_v5e32_llama70b.json",
        "layers": len(sizes),
        "layer_bytes": next(iter(sizes.values())) if sizes else 0,
        "target": {"time_s": 10.0, "utilization": 0.70},
        "rows": [
            solve("shipped (1 disk seeder @3GB/s)", shipped_holdings),
            solve("mem_seeder (1 in-RAM seeder)", mem1),
            solve("mem_4seeders (hot-spare replicas)", mem4),
        ],
    }


_TTFT_RE = re.compile(r"Time to first token: ([0-9.]+)s")


# Seeded fault schedule for the physical row's FAULTED sibling
# (transport/faults.py): corrupt every 7th and drop every 11th inbound
# layer frame below the CRC check, duplicate every 13th outbound layer
# send — each capped at 6 firings per node so recovery cost is bounded
# and the run stays deterministic.
PHYSICAL_FAULT_SPEC = "seed=3,corrupt=7,dropin=11,dup=13,times=6"


def physical_config() -> tuple:
    """PHYSICAL-size scenario: 2 seeders hold the ``llama3-8b-d4v8k``
    blobs — four ~416 MiB layers (EXACTLY the per-layer bytes ``bench.py``
    measures: the full 8B layer shape) plus a vocab-trimmed head — and
    one cold dest is assigned everything, mode 3 with ``-hbm`` staging
    and a model boot (TTFT).  Returns (conf dict, per-layer bytes, the
    dest's total assigned bytes)."""
    from ..models import quant, serde
    from ..models.llama import CONFIGS

    mcfg = CONFIGS["llama3-8b-d4v8k"]
    head_id = serde.head_blob_id(mcfg)
    nodes = []
    for i in range(3):
        nodes.append({
            "Id": i, "Addr": f"127.0.0.1:{_free_port()}",
            "NetworkBW": 10**10, "IsLeader": i == 0,
            "Sources": {"1": 0},
            "InitialLayers": (
                {"1": {str(b): {} for b in range(head_id + 1)}}
                if i < 2 else {}),
        })
    conf = {
        "Model": mcfg.name, "ModelSeed": 0,
        "Nodes": nodes,
        "Assignment": {"2": {str(b): {} for b in range(head_id + 1)}},
        "Mesh": {"AxisNames": ["nodes"], "AxisSizes": [1]},
    }
    layer_bytes = quant.blob_nbytes_codec(mcfg, 0, "raw")
    total = sum(quant.blob_nbytes_codec(mcfg, b, "raw")
                for b in range(head_id + 1))
    return conf, layer_bytes, total


def _live_backend(probe_timeout: float = 60.0) -> str:
    """'tpu'/... when the accelerator answers within the probe window,
    else '' (the caller pins CPU) — same throwaway-subprocess discipline
    as bench.py (a wedged tunnel blocks even jax.devices())."""
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print(jax.default_backend())"],
            timeout=probe_timeout, capture_output=True, text=True,
        )
        lines = probe.stdout.strip().splitlines()
        return lines[-1] if probe.returncode == 0 and lines else ""
    except subprocess.TimeoutExpired:
        return ""


def physical_fabric_config() -> tuple:
    """PHYSICAL-size pod-fabric scenario: leader + 2 seeders hold the
    ``llama3-8b-d4v8k`` blobs, one cold dest (stage 3 of a [4, 2] mesh)
    is assigned everything — the device plane carries the 416 MiB
    layers, TCP only control.  Returns (conf dict, total bytes)."""
    from ..models import quant, serde
    from ..models.llama import CONFIGS

    mcfg = CONFIGS["llama3-8b-d4v8k"]
    head_id = serde.head_blob_id(mcfg)
    blobs = {str(b): {} for b in range(head_id + 1)}
    nodes = []
    for i in range(4):
        nodes.append({
            "Id": i, "Addr": str(i), "NetworkBW": 10**10,
            "IsLeader": i == 0, "Sources": {"1": 0},
            "InitialLayers": ({"1": dict(blobs)} if i < 3 else {}),
        })
    conf = {
        "Model": mcfg.name, "ModelSeed": 0,
        "Nodes": nodes,
        "Assignment": {"3": dict(blobs)},
        "Mesh": {"AxisNames": ["nodes", "tp"], "AxisSizes": [4, 2],
                 "PipelineAxis": "nodes", "Fabric": True,
                 "IciBW": 90_000_000_000},
    }
    total = sum(quant.blob_nbytes_codec(mcfg, b, "raw")
                for b in range(head_id + 1))
    return conf, total


def run_physical_fabric(timeout: float = 2400.0) -> dict:
    """The physical row's DEVICE-PLANE sibling (VERDICT r4 ask#5): the
    same ~1.8 GiB model, but the layer bytes ride the pod fabric
    (single-controller FabricPlane over the virtual 8-device CPU mesh —
    the one real chip can't host a [4, 2] mesh, so the collective path
    runs on the CPU mesh and the real-chip evidence stays with the
    ``-hbm`` TCP row).  Records TTD + achieved GB/s + the zero-TCP
    assertion next to the host-path row."""
    conf, total = physical_fabric_config()
    env = _cpu_env()
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "physical_fabric.json")
        with open(path, "w") as f:
            json.dump(conf, f)
        proc = subprocess.run(
            [sys.executable, "-m",
             "distributed_llm_dissemination_tpu.cli.podrun",
             "-f", path, "-m", "3"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            timeout=timeout, env=env,
        )
    out = proc.stdout.decode()
    err = proc.stderr.decode()
    ttd_m = _TTD_RE.search(out)
    if proc.returncode != 0 or not ttd_m:
        raise RuntimeError(
            f"physical fabric run failed rc={proc.returncode}: "
            f"{err[-2000:]!r}")
    ttd = float(ttd_m.group(1))
    # podrun's machine-readable summary line carries the run's compiled-
    # collective cache stats and per-phase totals (compile / upload /
    # collective / splice) — the attribution the 47 s row lacked.
    summary = _parse_summary_line(out)
    rec = {
        "scenario": "physical_4node_fabric_llama8b-d4@416MiB-layers",
        "mode": 3,
        "backend": "cpu-mesh8",  # virtual 8-device CPU mesh (see doc)
        "total_bytes": total,
        "ttd_s": round(ttd, 4),
        "achieved_gbps": round(total / ttd / 1e9, 3),
        # Zero layer bytes on TCP: every delivery rode the fabric.  The
        # count matches the receiver's EXACT per-fragment log message —
        # a wording drift breaks the harness loudly (a KeyError in the
        # markdown) instead of silently reporting "none" forever.
        "fabric_deliveries": err.count("layer landed over device fabric"),
        "tcp_layer_fragments": err.count("(a fraction of) layer received"),
    }
    if summary is not None:
        if summary.get("collective_cache"):
            rec["collective_cache"] = summary["collective_cache"]
        if summary.get("plan_phases"):
            rec["plan_phases"] = summary["plan_phases"]
    ttft_m = _TTFT_RE.search(out)
    if ttft_m:
        rec["ttft_s"] = round(float(ttft_m.group(1)), 4)
    cache = rec.get("collective_cache") or {}
    print(f"physical fabric: TTD {ttd:.2f}s "
          f"({rec['achieved_gbps']} GB/s over the device plane; "
          f"gather cache {cache.get('hits', '?')} hits / "
          f"{cache.get('misses', '?')} misses)",
          file=sys.stderr, flush=True)
    return rec


def _physical_phases(dest_log: str) -> dict:
    """Decompose the dest's TTD from its JSON log: where the seconds
    went, per phase (VERDICT r4 asked exactly this of the 19.6 s run).

    - ``wire_recv_ms``: summed per-fragment socket receive durations
      (the transport's own measurement, node.go:1180-1186 parity);
      striped fragments log one entry per stripe, so concurrent stripes
      each contribute their own wall time (thread-time sum).
    - ``assembly_copy_ms`` / ``ingest_write_ms``: summed host memcpy
      and device-ingest write time (receiver phase accumulators).
    - ``recv_span_ms``: max per-layer wall span first-fragment→complete.
    - ``stage_ms``: summed HBM staging (ingest finalize / bulk put).
    - ``boot_ms``: the model boot (startup hook → engine ready).
    - ``fragments`` / ``placed_fragments``: delivered fragments (stripes
      included) and how many of them the zero-copy sink landed directly
      in the reassembly buffer — the receive-to-stage overlap evidence:
      a placed fragment's bytes are already where staging adopts them,
      so its device-ingest accounting runs DURING the wire receive.
    """
    wire = copy = ingest = stage = boot = 0.0
    span = stream_wait = precompile = stream = stream_wire = 0.0
    layers = frags = placed = streamed = streamed_wire = 0
    crc_ms = digest_ms = 0.0
    crc_dropped = nacks = 0
    nacked_bytes = 0
    boot_via = ""
    precompile_in_wire = None
    with open(dest_log) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            m = rec.get("message", "")
            if m == "corrupt layer fragment dropped":
                # TTL prunes share the message with reason="stale"; the
                # table's column is CRC-detected corruption only, to
                # match the integrity.crc_drop counter.
                if rec.get("reason") != "stale":
                    crc_dropped += 1
            elif m == "layer fragment NACKed":
                nacks += 1
                nacked_bytes += int(rec.get("bytes", 0))
            elif m == "layer digest verified":
                digest_ms += float(rec.get("digest_ms", 0.0))
            if m == "(a fraction of) layer received":
                wire += float(rec.get("duration_ms", 0.0))
                crc_ms += float(rec.get("crc_ms", 0.0))
            elif m == "layer fully received":
                copy += float(rec.get("copy_ms", 0.0))
                ingest += float(rec.get("ingest_ms", 0.0))
                span = max(span, float(rec.get("recv_span_ms", 0.0)))
                frags += int(rec.get("fragments", 0))
                placed += int(rec.get("placed_fragments", 0))
                layers += 1
            elif m == "layer staged to HBM":
                stage += float(rec.get("stage_ms", 0.0))
            elif m == "model booted from disseminated layers":
                boot += float(rec.get("ttft_ms", 0.0))
                stream_wait += float(rec.get("stream_wait_ms", 0.0))
                boot_via = rec.get("via", boot_via)
            elif m == "boot programs precompiled during dissemination":
                precompile += float(rec.get("compile_s", 0.0)) * 1000
                precompile_in_wire = bool(rec.get("in_wire", False))
            elif m == "layer boot-staged (streamed)":
                streamed += 1
                stream += float(rec.get("stage_ms", 0.0))
                if rec.get("in_wire"):
                    streamed_wire += 1
                    stream_wire += float(rec.get("stage_ms", 0.0))
    return {
        "layers": layers,
        "fragments": frags,
        "placed_fragments": placed,
        "wire_recv_ms": round(wire, 1),
        "assembly_copy_ms": round(copy, 1),
        "ingest_write_ms": round(ingest, 1),
        "max_layer_recv_span_ms": round(span, 1),
        "stage_ms": round(stage, 1),
        "boot_ms": round(boot, 1),
        "boot_via": boot_via,
        # TTFT pipeline evidence: hint-time compile (and whether it
        # finished inside the wire window), per-blob streamed staging
        # (and how much of it overlapped the wire), and the boot's wait
        # for any staging tail.
        "precompile_ms": round(precompile, 1),
        "precompile_in_wire": precompile_in_wire,
        "stream_stage_ms": round(stream, 1),
        "stream_stage_in_wire_ms": round(stream_wire, 1),
        "streamed_blobs": streamed,
        "streamed_blobs_in_wire": streamed_wire,
        "boot_stream_wait_ms": round(stream_wait, 1),
        # Integrity plane (docs/integrity.md): per-fragment CRC verify
        # (thread-time sum over all receive threads) and once-per-layer
        # digest verify on the dest, plus corruption-recovery counters.
        "crc_verify_ms": round(crc_ms, 1),
        "digest_verify_ms": round(digest_ms, 1),
        "crc_dropped_frames": crc_dropped,
        "nacks_sent": nacks,
        "nacked_bytes": nacked_bytes,
    }


def _retransmits_from_logs(logdir: str) -> dict:
    """Sum the SENDER-side NACK retransmit records across every node's
    log (the dest NACKs; seeders/leader re-send)."""
    frags = 0
    total = 0
    for name in sorted(os.listdir(logdir)):
        if not name.endswith(".jsonl"):
            continue
        with open(os.path.join(logdir, name)) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("message") == "NACK retransmit":
                    frags += 1
                    total += int(rec.get("bytes", 0))
    return {"retransmitted_fragments": frags, "retransmitted_bytes": total}


def run_physical(timeout: float = 1200.0, trace_out: str = "",
                 cache_dir: str = "", label: str = "",
                 faults: str = "", integrity_off: bool = False) -> dict:
    """One recorded run at PHYSICAL layer size (no -scale): ties the TTD
    story to the bench's measured ingest bandwidth — TTD, TTFT, and the
    achieved dest ingest rate on whatever backend is live (recorded).
    ``trace_out``: also merge the per-node JSON logs and write a
    Chrome-trace of the run there (the observability pipeline exercised
    on the recorded scenario itself).
    ``cache_dir``: persistent compilation cache directory handed to the
    node processes (DLD_COMPILE_CACHE_DIR) — the cold run writes it, the
    warm run's boot reads it; ``label`` tags the record ("cold"/"warm").
    Seeders run ``-boot none``: only the DEST's boot is the metric, and
    a seeder pointlessly booting its own full copy would contend for the
    same cores during the measured window.
    ``faults``: a ``transport/faults.py`` spec handed to every node
    (``-test-faults``) — the FAULTED sibling row: seeded corruption/
    drops below the CRC check plus duplicated sends, which the
    integrity plane must recover byte-exactly (digests verified at the
    dest); the record carries the NACK/retransmit counts and the TTD
    degradation vs the clean row."""
    backend = _live_backend()
    env = dict(os.environ) if backend else _cpu_env()
    if cache_dir:
        env["DLD_COMPILE_CACHE_DIR"] = cache_dir
    if integrity_off:
        # The integrity-OFF sibling: same scenario with CRC stamping/
        # verification and layer digests disabled — the wall-clock delta
        # to the clean (integrity-on) row is the checksum overhead the
        # ≤5%-of-TTD acceptance criterion measures.
        env["DLD_WIRE_CRC"] = "0"
        env["DLD_LAYER_DIGESTS"] = "0"
    # The host's measured loopback ceiling: one raw stream, and the
    # striped data plane's stream count — the denominator that makes the
    # achieved rate attributable (bench.py's raw_dma_gbps/link_fraction
    # pattern, applied to the wire).  Probed BEFORE the node processes
    # spawn: the run saturates small hosts end to end (and the dest's
    # boot outlives the TTD), so a probe next to live processes would
    # understate the ceiling and flatter the fraction.
    from ..transport.tcp import STRIPE_COUNT

    loop_raw = measure_loopback_gbps(1)
    loop_striped = measure_loopback_gbps(max(2, STRIPE_COUNT))
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "physical_3node.json")
        conf, layer_bytes, total = physical_config()
        with open(path, "w") as f:
            json.dump(conf, f)
        receiver_ids = [n["Id"] for n in conf["Nodes"]
                        if not n.get("IsLeader")]
        leader_addr = next(n["Addr"] for n in conf["Nodes"]
                           if n.get("IsLeader"))
        logdir = os.path.join(td, "logs")
        os.makedirs(logdir)

        errfs = []

        def spawn(node_id, extra=()):
            # Per-node JSON logs (zerolog-style, on stderr) captured to
            # files: the same artifacts a deployment's collect_logs
            # gathers, here feeding the committed trace.
            errf = open(os.path.join(logdir, f"node{node_id}.jsonl"), "wb")
            errfs.append(errf)
            fault_flags = ("-test-faults", faults) if faults else ()
            return subprocess.Popen(
                [sys.executable, "-m",
                 "distributed_llm_dissemination_tpu.cli.main",
                 "-id", str(node_id), "-f", path, "-m", "3", "-hbm",
                 *fault_flags, *extra],
                stdout=subprocess.PIPE, stderr=errf, env=env,
            )

        def wait_listening(proc, addr: str, budget: float) -> None:
            # The leader fabricates ~2 GiB of seeded blobs BEFORE it
            # listens; receivers only retry dialing for ~10 s, so spawn
            # them once the port actually answers.  A leader that DIED
            # during fabrication must fail the run now, not after the
            # whole budget.
            import socket

            host, port = addr.rsplit(":", 1)
            deadline = time.monotonic() + budget
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"leader exited rc={proc.returncode} before "
                        "listening (fabrication failure?)")
                try:
                    with socket.create_connection((host, int(port)),
                                                  timeout=2.0):
                        return
                except OSError:
                    time.sleep(1.0)
            raise RuntimeError(f"leader never listened on {addr}")

        procs = []
        try:
            leader = spawn(0)
            procs.append(leader)
            wait_listening(leader, leader_addr, budget=600.0)
            dest_ids = {int(k) for k in conf.get("Assignment", {})}
            for rid in receiver_ids:
                # Seeders opt out of booting (they report "skipped");
                # only the dest's boot is measured.
                procs.append(spawn(
                    rid, () if rid in dest_ids else ("-boot", "none")))
            out, _ = leader.communicate(timeout=timeout)
            text = out.decode()
            ttd_m = _TTD_RE.search(text)
            ttft_m = _TTFT_RE.search(text)
            pred_m = _PRED_RE.search(text)
            if not ttd_m:
                raise RuntimeError(
                    f"no TTD in physical run output: {text[-2000:]!r}")
            ttd = float(ttd_m.group(1))
            ceiling = max(loop_raw, loop_striped)
            rec = {
                "scenario": "physical_3node_llama8b-d4@416MiB-layers",
                "mode": 3, "hbm": True,
                "backend": backend or "cpu-fallback",
                "layer_bytes": layer_bytes,
                "total_bytes": total,
                "ttd_s": round(ttd, 4),
                "achieved_gbps": round(total / ttd / 1e9, 3),
                "stripes": STRIPE_COUNT,
            }
            if label:
                rec["cache"] = label
            if faults:
                rec["fault_spec"] = faults
            if pred_m:
                rec["predicted_s"] = round(float(pred_m.group(1)), 4)
                rec["solve_ms"] = round(float(pred_m.group(2)), 3)
            # 0.0 = that probe arm failed (accept timeout): record only
            # the arms that really measured, never a bogus zero ceiling.
            if loop_raw > 0:
                rec["loopback_raw_gbps"] = loop_raw
            if loop_striped > 0:
                rec["loopback_striped_gbps"] = loop_striped
            if ceiling > 0:
                rec["link_fraction"] = round(
                    total / ttd / 1e9 / ceiling, 3)
            if ttft_m:
                rec["ttft_s"] = round(float(ttft_m.group(1)), 4)
            try:
                # The run's own RUN_REPORT (cli/report.py), built from
                # the same per-node logs: the row embeds its provenance
                # hash + folded event counters, so the integrity/
                # failover numbers in this record are traceable to one
                # report artifact instead of hand-collected.
                from . import collect_logs as _cl
                from . import report as report_mod

                rep = report_mod.build_from_records(
                    _cl.iter_records([logdir]))
                rec["run_report"] = {
                    "provenance": rep.get("provenance"),
                    "counters": rep.get("counters"),
                }
            except Exception as e:  # noqa: BLE001 — report is a bonus
                print(f"run report build failed: {e!r}", file=sys.stderr)
            try:
                rec["phases"] = _physical_phases(
                    os.path.join(logdir, "node2.jsonl"))
                ph = rec["phases"]
                integ = _retransmits_from_logs(logdir)
                # The acceptance metric: dest-side checksum thread-time
                # (per-fragment CRC + once-per-layer digest) over the
                # TTD wall clock.  Thread-time over wall-time, so
                # overlapped verification (concurrent stripe receivers)
                # can honestly exceed its wall-clock share.
                integ["crc_overhead_frac"] = round(
                    (ph["crc_verify_ms"] + ph["digest_verify_ms"])
                    / max(ttd * 1000.0, 1e-9), 4)
                integ["verify_ms"] = round(
                    ph["crc_verify_ms"] + ph["digest_verify_ms"], 1)
                integ["crc_dropped_frames"] = ph["crc_dropped_frames"]
                integ["nacks_sent"] = ph["nacks_sent"]
                rec["integrity"] = integ
            except Exception as e:  # noqa: BLE001 — breakdown is a bonus
                print(f"phase breakdown failed: {e!r}", file=sys.stderr)
            if trace_out:
                # Receivers exit shortly after their boot reports; wait
                # so the trace gets their final events too.
                for p in procs[1:]:
                    try:
                        p.wait(timeout=60)
                    except subprocess.TimeoutExpired:
                        pass
                try:
                    from . import collect_logs, trace as trace_mod

                    # Same pipeline as `cli.trace logs/` (to_trace_events
                    # sorts internally; merge() would leak rel_ms into
                    # every event's args and diverge from that path).
                    events = trace_mod.to_trace_events(
                        collect_logs.iter_records([logdir]))
                    with open(trace_out, "w") as f:
                        json.dump({"traceEvents": events,
                                   "displayTimeUnit": "ms"}, f)
                    rec["trace_events"] = len(events)
                except Exception as e:  # noqa: BLE001 — trace is a bonus
                    print(f"trace export failed: {e!r}", file=sys.stderr)
            print(f"physical: TTD {ttd:.2f}s "
                  f"({rec['achieved_gbps']} GB/s into the dest, "
                  f"backend {rec['backend']})", file=sys.stderr, flush=True)
            return rec
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            for f in errfs:
                f.close()


def _cache_evidence(results: dict) -> dict:
    """Build the 'compiled-collective cache: reuse evidence' table from
    the run's own records (the pod scenarios' per-mode summaries and
    the physical fabric row), so a full re-measure regenerates it
    instead of silently dropping a hand-curated key."""
    ev = {}
    for name, per_mode in (results.get("scenarios") or {}).items():
        if "fabric" not in name:
            continue
        for mode in ("0", "1", "2", "3"):
            cc = (per_mode.get(mode) or {}).get("collective_cache")
            if cc:
                note = (" (batched)" if mode == "3" else "")
                ev[f"{name} mode {mode}{note}"] = {
                    k: cc[k] for k in ("hits", "misses", "compile_ms")
                    if k in cc}
    fab = results.get("physical_fabric") or {}
    if fab.get("collective_cache"):
        cc = fab["collective_cache"]
        ev[f"{fab.get('scenario', 'physical_fabric')} (batched)"] = {
            k: cc[k] for k in ("hits", "misses", "compile_ms") if k in cc}
    return ev


def run_failover(layer_bytes: int = 96 << 20, n_workers: int = 2,
                 lease: float = 0.25, expiry: float = 0.6,
                 kill_frac: float = 0.5, timeout: float = 180.0) -> dict:
    """Control-plane HA at physical-row sizes (docs/failover.md): one
    clean HA-armed mode-3 run over loopback TCP, then an identical run
    with the leader KILLED at ``kill_frac`` of the clean TTD.  Records
    time-to-recover (TTR: kill → delivery resumed to completion) and
    the failover overhead vs the clean sibling.  In-process (threads,
    real TCP transports): the leader kill is a surgical freeze of the
    leader's loops — exactly the mid-run death the standby must absorb
    — with the wall clock honest end to end."""
    import threading

    from ..core.types import (
        LayerMeta,
        LayerLocation,
        LayerSrc,
        SourceType,
    )
    from ..runtime import (
        FlowRetransmitLeaderNode,
        FlowRetransmitReceiverNode,
        Node,
        StandbyController,
    )
    from ..transport import TcpTransport

    ids = list(range(n_workers + 2))  # 0 leader, 1 standby, 2.. workers
    block = os.urandom(1 << 20)

    def mem_layer(lid: int) -> LayerSrc:
        reps = (layer_bytes + len(block) - 1) // len(block)
        data = bytearray((block * reps)[:layer_bytes])
        data[:8] = lid.to_bytes(8, "big")  # distinct per layer
        return LayerSrc(inmem_data=data, data_size=layer_bytes,
                        meta=LayerMeta(location=LayerLocation.INMEM,
                                       source_type=SourceType.MEM))

    def build():
        ts = {i: TcpTransport("127.0.0.1:0") for i in ids}
        reg = {i: t.get_address() for i, t in ts.items()}
        for t in ts.values():
            t.addr_registry.update(reg)
        assignment = {w: {w - 2: LayerMeta()}
                      for w in range(2, n_workers + 2)}
        seed = lambda: {i: mem_layer(i)  # noqa: E731
                        for i in range(n_workers)}
        leader = FlowRetransmitLeaderNode(
            Node(0, 0, ts[0]), seed(), assignment,
            {i: 10 ** 10 for i in ids},
            expected_nodes=set(ids[1:]), standbys=[1],
            lease_interval=lease, epoch=0)
        standby = FlowRetransmitReceiverNode(
            Node(1, 0, ts[1]), seed(), heartbeat_interval=lease)
        ctl = StandbyController(
            standby, rank=0, lease_timeout=expiry, standbys=[1], mode=3,
            node_network_bw={i: 10 ** 10 for i in ids},
            failure_timeout=0.0, lease_interval=lease)
        workers = [FlowRetransmitReceiverNode(
            Node(w, 0, ts[w]), {}, heartbeat_interval=lease)
            for w in range(2, n_workers + 2)]
        return leader, standby, ctl, workers, ts, assignment

    def teardown(leader, standby, ctl, workers, ts):
        ctl.close()
        leader.close()
        for r in [standby] + workers:
            r.close()
        for t in ts.values():
            t.close()

    def one_run(kill_at_s=None):
        # Run-scoped telemetry: both runs share this process, so each
        # starts from a clean registry (the trace.py global-bleed fix) —
        # the embedded counters below are THIS run's events only.
        from ..utils import telemetry

        telemetry.reset_run()
        leader, standby, ctl, workers, ts, assignment = build()
        try:
            standby.announce()
            for w in workers:
                w.announce()
            leader.start_distribution().get(timeout=timeout)
            t0 = time.monotonic()
            rec = {}
            if kill_at_s is not None:
                time.sleep(kill_at_s)
                t_kill = time.monotonic()
                leader.close()  # the mid-run death
                if not ctl.promoted.wait(timeout=timeout):
                    raise TimeoutError("standby never promoted")
                rec["takeover_s"] = round(
                    time.monotonic() - t_kill, 4)
                ready_q = ctl.leader.ready()
            else:
                ready_q = leader.ready()
            import queue as _q

            try:
                ready_q.get(timeout=timeout)
            except _q.Empty:
                raise TimeoutError("delivery never completed")
            now = time.monotonic()
            rec["total_s"] = round(now - t0, 4)
            if kill_at_s is not None:
                rec["kill_at_s"] = round(t_kill - t0, 4)
                rec["ttr_s"] = round(now - t_kill, 4)
            # Byte-exactness: every worker's layer matches its seed.
            for w in workers:
                for lid in assignment[w.node.my_id]:
                    got = bytes(w.layers[lid].inmem_data)
                    want = bytes(mem_layer(lid).inmem_data)
                    if got != want:
                        raise AssertionError(
                            f"layer {lid} corrupt after failover")
            rec["byte_exact"] = True
            # The row's event counts come from the run's own flight
            # recorder + RUN_REPORT (cli/report.py) — the report is
            # built from whichever leader FINISHED the run (the adopted
            # one on the killed run: the replicated cluster picture is
            # part of what this row evidences).
            from . import report as report_mod

            live = ctl.leader if kill_at_s is not None else leader
            rep = report_mod.build_from_leader(live,
                                               ttd_s=rec["total_s"])
            rec["telemetry"] = telemetry.snapshot().get("counters")
            rec["run_report"] = rep.get("provenance")
            rec["report_links"] = len(rep.get("links") or [])
            return rec
        finally:
            teardown(leader, standby, ctl, workers, ts)

    clean = one_run()
    kill_at = max(0.05, clean["total_s"] * kill_frac)
    killed = one_run(kill_at_s=kill_at)
    from ..utils.provenance import harness_hash

    return {
        "harness_hash": harness_hash(),
        "mode": 3,
        "backend": "tcp-loopback",
        "layer_bytes": layer_bytes,
        "n_workers": n_workers,
        "lease_interval_s": lease,
        "standby_expiry_s": expiry,
        "clean": clean,
        "killed": killed,
        "overhead_s": round(killed["total_s"] - clean["total_s"], 4),
    }


def _dest_wire_bytes(links: dict, node_id) -> dict:
    """Per-dest NIC accounting off the folded link table: rx and
    delivered bytes summed over the base (un-job-tagged) rows ending at
    ``node_id`` — one definition for every row that reconciles wire
    bytes per dest."""
    rx = sum(row.get("rx_bytes", 0) for key, row in links.items()
             if "#" not in key and key.endswith(f"->{node_id}"))
    delivered = sum(row.get("delivered_bytes", 0)
                    for key, row in links.items()
                    if "#" not in key and key.endswith(f"->{node_id}"))
    return {"rx_bytes": rx, "delivered_bytes": delivered}


def _service_rig(n_layers: int, layer_bytes: int, assignment,
                 bw_per_node: int, n_dests: int = 2, fabric=None,
                 pods=None, codec: bool = False):
    """Leader 0 (mode 3, holds every layer) + dests 1..n over loopback
    TCP — the in-process rig the service-plane rows run on.

    ``fabric``/``pods`` (docs/fabric.md): a shared in-process
    ``FabricPlane`` (its pod shard board is the single-controller
    stand-in for the ICI hop) + the pod grouping, for the
    fabric-assisted pod-delivery row.

    ``codec``: wire every node with a model-less ``WireCodecPlane``
    (docs/codec.md).  With no model config only the content-DELTA form
    can encode (whole-form sizes derive from blob layouts), and the
    leader's ``wire_codec`` stays "raw" — so the rows that set this
    exercise exactly the delta path: dests announce the "delta"
    capability, the leader prices encoded (v2 − base) streams, and
    reconstruction verifies against the stamped full-form digest."""
    from ..core.types import (
        LayerMeta,
        LayerLocation,
        LayerSrc,
        SourceType,
    )
    from ..runtime import (
        FlowRetransmitLeaderNode,
        FlowRetransmitReceiverNode,
        Node,
    )
    from ..runtime.codec import WireCodecPlane
    from ..transport import TcpTransport

    ids = list(range(n_dests + 1))
    block = os.urandom(1 << 20)

    def mem_layer(lid: int) -> LayerSrc:
        reps = (layer_bytes + len(block) - 1) // len(block)
        data = bytearray((block * reps)[:layer_bytes])
        data[:8] = lid.to_bytes(8, "big")
        return LayerSrc(inmem_data=data, data_size=layer_bytes,
                        meta=LayerMeta(location=LayerLocation.INMEM,
                                       source_type=SourceType.MEM))

    ts = {i: TcpTransport("127.0.0.1:0") for i in ids}
    reg = {i: t.get_address() for i, t in ts.items()}
    for t in ts.values():
        t.addr_registry.update(reg)
    # One plane PER NODE (never shared): each role wires its own
    # base_resolver (leader: goal digests; receiver: content store).
    plane = (lambda: WireCodecPlane(None)) if codec else (lambda: None)
    leader = FlowRetransmitLeaderNode(
        Node(0, 0, ts[0]), {i: mem_layer(i) for i in range(n_layers)},
        assignment, {i: bw_per_node for i in ids},
        expected_nodes=set(ids[1:]), fabric=fabric, pods=pods,
        codecs=plane())
    dests = [FlowRetransmitReceiverNode(Node(i, 0, ts[i]), {},
                                        fabric=fabric, codecs=plane())
             for i in ids[1:]]
    return leader, dests, ts, mem_layer


def _service_teardown(leader, dests, ts):
    leader.close()
    for r in dests:
        r.close()
    for t in ts.values():
        t.close()


def run_service_jobs(layer_bytes: int = 32 << 20,
                     bw: int = 200_000_000,
                     timeout: float = 300.0) -> dict:
    """Two overlapping dissemination jobs, different priorities, one
    shared source NIC (docs/service.md): the leader daemon admits both
    at once; the joint solver gives the HIGH tier the full modeled link
    and the LOW tier the preemption-floor residue, and the per-job link
    telemetry + per-job completion walls record the split actually
    achieved.  Byte-exact with digests verified (the jobs only complete
    through the ack gate)."""
    import queue as _q

    from ..core.types import LayerMeta
    from ..utils import telemetry
    from ..utils.provenance import harness_hash
    from . import report as report_mod

    telemetry.reset_run()
    assignment = {}  # service-only: the daemon starts with an empty goal
    leader, dests, ts, mem_layer = _service_rig(
        2, layer_bytes, assignment, bw, n_dests=2)
    try:
        for r in dests:
            r.announce()
        leader.start_distribution().get(timeout=timeout)
        leader.ready().get(timeout=timeout)  # empty base goal: instant
        t0 = time.monotonic()
        s_hi = leader.submit_job("push-hi", {1: {0: LayerMeta()}},
                                 priority=2)
        s_lo = leader.submit_job("push-lo", {2: {1: LayerMeta()}},
                                 priority=1)
        done_at = {}
        deadline = time.monotonic() + timeout
        while len(done_at) < 2:
            if time.monotonic() > deadline:
                raise TimeoutError("service jobs never completed")
            for jid, row in leader.jobs.table().items():
                if row["State"] == "done" and jid not in done_at:
                    done_at[jid] = round(time.monotonic() - t0, 4)
            time.sleep(0.02)
        try:
            leader.ready().get(timeout=timeout)
        except _q.Empty:
            pass
        # Byte-exact + digest-verified.
        for r, lid in ((dests[0], 0), (dests[1], 1)):
            want = bytes(mem_layer(lid).inmem_data)
            if bytes(r.layers[lid].inmem_data) != want:
                raise AssertionError(f"job layer {lid} corrupt")
            expected = r._expected_digest(lid)
            if expected is not None and lid not in r._digest_ok:
                raise AssertionError(f"layer {lid} digest unverified")
        intended = {jid: leader._tier_time.get(jid)
                    for jid in ("push-hi", "push-lo")}
        links = telemetry.snapshot()["links"]
        per_job_links = {
            key: {f: row[f] for f in ("delivered_bytes", "rx_bytes",
                                      "tx_bytes") if f in row}
            for key, row in links.items() if "#" in key}
        rep = report_mod.build_from_leader(leader)
        return {
            "harness_hash": harness_hash(),
            "backend": "tcp-loopback",
            "mode": 3,
            "layer_bytes": layer_bytes,
            "modeled_bw_bps": bw,
            "jobs": {
                "push-hi": {"priority": 2, "summary": s_hi},
                "push-lo": {"priority": 1, "summary": s_lo},
            },
            # The solver's INTENDED split: each tier's min-time budget
            # (ms) — hi gets the full modeled link, lo the 1/16
            # preemption-floor residue (sched.flow.PREEMPT_FLOOR_SHIFT).
            "intended_tier_ms": intended,
            "measured_done_s": done_at,
            "per_job_links": per_job_links,
            "byte_exact": True,
            "table": leader.jobs.table(),
            "run_report": rep.get("provenance"),
        }
    finally:
        _service_teardown(leader, dests, ts)


def _perturbed(src, stride: int = 1024, salt: int = 0) -> bytearray:
    """A small-perturbation v2 of ``src``'s bytes: every ``stride``-th
    byte flipped (deterministic) — the rollout shape the content-delta
    codec exists for: ~0.1% of positions changed, scattered through the
    whole layer, so whole-layer content dedup can't help but an encoded
    XOR delta is tiny.  ``salt`` offsets the perturbed positions so two
    perturbed layers never mutate the SAME positions — otherwise each
    would be the other's closest base (the XOR cancels) and the leader
    would pin a base the dests don't hold yet."""
    data = bytearray(src.inmem_data)
    for off in range(salt % stride, len(data), stride):
        data[off] ^= 0xA5
    return data


def run_delta_rollout(layer_bytes: int = 16 << 20, n_layers: int = 4,
                      changed: int = 1, perturb_stride: int = 1024,
                      bw: int = 200_000_000,
                      timeout: float = 300.0) -> dict:
    """v2 delta rollout against a populated content store + the
    content-delta wire codec (docs/service.md, docs/codec.md): after a
    v1 run delivers ``n_layers`` to the dest, a v2 job re-keys them
    under new layer ids — ``changed`` of them small-perturbation
    siblings of their v1 bytes, the rest byte-identical.  The
    content-addressed store must resolve the UNCHANGED layers locally
    (zero wire bytes), and the leader must ship each CHANGED layer as
    an encoded ``delta:<v1-digest>`` stream the dest reconstructs and
    verifies against the stamped full-form digest — so the shipped
    bytes land far below even the changed layers' raw size.  The row
    records both wins plus the honest encode cost (the leader's
    XOR+DLE1 wall time, ``codec_encode``)."""
    from ..core.types import LayerMeta
    from ..utils import integrity, telemetry, trace
    from ..utils.provenance import harness_hash
    from . import report as report_mod

    telemetry.reset_run()
    trace.reset_phases()
    assignment = {1: {i: LayerMeta() for i in range(n_layers)}}
    # v2 ids are 100+i; ids < 100+changed are perturbed v1 bytes, the
    # rest reuse v1 bytes verbatim (unchanged).  ``bw`` models the NIC
    # at or below the delta negotiation threshold
    # (runtime/codec.DELTA_MIN_RATE_DEFAULT) so the pairs qualify.
    leader, dests, ts, mem_layer = _service_rig(
        n_layers, layer_bytes, assignment, bw, n_dests=1, codec=True)
    try:
        dests[0].announce()
        t0 = time.monotonic()
        leader.ready().get(timeout=timeout)
        v1_s = round(time.monotonic() - t0, 4)
        base_rx = telemetry.snapshot()["links"].get(
            "0->1", {}).get("rx_bytes", 0)
        from ..core.types import LayerLocation, LayerSrc, SourceType

        with leader._lock:
            for i in range(changed):
                data = _perturbed(leader.layers[i], perturb_stride,
                                   salt=1 + 7 * i)
                leader.layers[100 + i] = LayerSrc(
                    inmem_data=data, data_size=len(data),
                    meta=LayerMeta(location=LayerLocation.INMEM,
                                   source_type=SourceType.MEM))
            for i in range(changed, n_layers):
                leader.layers[100 + i] = leader.layers[i]
        digests = {}
        for i in range(n_layers):
            src = leader.layers[100 + i]
            digests[100 + i] = integrity.layer_digest(
                bytes(src.inmem_data))
        t1 = time.monotonic()
        leader.submit_job(
            "v2-rollout", {1: {100 + i: LayerMeta()
                               for i in range(n_layers)}},
            priority=1, kind="push", digests=digests)
        leader.ready().get(timeout=timeout)
        v2_s = round(time.monotonic() - t1, 4)
        for i in range(n_layers):
            src = dests[0].layers.get(100 + i)
            want = leader.layers[100 + i]
            if src is None or bytes(src.inmem_data) != bytes(
                    want.inmem_data):
                raise AssertionError(f"v2 layer {100 + i} corrupt")
            # Digest-exact: the dest VERIFIED each v2 pair (changed
            # pairs verify twice — the delta stream, then the
            # reconstructed full form).
            if 100 + i not in dests[0]._digest_ok:
                raise AssertionError(
                    f"v2 layer {100 + i} digest unverified")
        links = telemetry.snapshot()["links"]
        v2_rx = sum(row.get("rx_bytes", 0) for key, row in links.items()
                    if key.endswith("#v2-rollout"))
        counters = trace.counter_totals()
        phases = trace.phase_totals()
        rep = report_mod.build_from_leader(leader)
        model_bytes = n_layers * layer_bytes
        changed_raw = changed * layer_bytes
        return {
            "harness_hash": harness_hash(),
            "backend": "tcp-loopback",
            "mode": 3,
            "layer_bytes": layer_bytes,
            "n_layers": n_layers,
            "changed_layers": changed,
            "perturb_stride": perturb_stride,
            "modeled_bw_bps": bw,
            "model_bytes": model_bytes,
            "changed_fraction": round(changed / n_layers, 4),
            "v1_full_push_s": v1_s,
            "v1_wire_bytes": base_rx,
            "v2_delta_push_s": v2_s,
            "v2_wire_bytes": v2_rx,
            "v2_bound_bytes": changed_raw,
            "bound_met": bool(0 < v2_rx <= changed_raw),
            # The tentpole bar: the changed layers' wire bytes are an
            # encoded (v2 − v1) stream, not whole raw layers — under
            # 25% of the changed layers' raw size (with the stride-
            # perturbation above, well under 5%).
            "delta_bound_bytes": changed_raw // 4,
            "delta_bound_met": bool(0 < v2_rx <= changed_raw // 4),
            "delta_pairs_chosen": counters.get(
                "codec.delta_pairs_chosen", 0),
            "delta_wire_bytes": counters.get("codec.delta_wire_bytes", 0),
            "delta_raw_bytes": counters.get("codec.delta_raw_bytes", 0),
            "delta_reconstructed": counters.get(
                "codec.delta_reconstructed", 0),
            # Honest encode-cost accounting: thread-time the leader
            # spent XOR+DLE1-encoding (cached once per layer; a CFS
            # container's noisy clock makes this a ceiling, not a
            # precise per-byte rate).
            "encode_ms": phases.get("codec_encode", {}).get("ms", 0.0),
            "resolved_layers": counters.get("store.resolved_layers", 0),
            "resolved_bytes": counters.get("store.resolved_bytes", 0),
            "leader_skipped": counters.get("store.leader_skipped", 0),
            "byte_exact": True,
            "digest_exact": True,
            "run_report": rep.get("provenance"),
        }
    finally:
        _service_teardown(leader, dests, ts)


def run_delta_wave(layer_bytes: int = 8 << 20, n_layers: int = 3,
                   changed: int = 2, perturb_stride: int = 1024,
                   bw: int = 200_000_000,
                   timeout: float = 300.0) -> dict:
    """Rollout WAVE over a grouped cluster, shipped as deltas
    (docs/rollout.md × docs/hierarchy.md × docs/codec.md): root 0 seeds
    ``n_layers`` v1 layers to one group of 3 (sub-leader + 2 members)
    through the group plan, then rolls a v2 that perturbs ``changed``
    layers in two version-qualified waves — wave 1 lands v2 on the
    group-ingress sub-leader, wave 2 fans it to the members.  Every v2
    pair must ship as an encoded ``delta:<v1-digest>`` stream: the
    root encodes against its own v1, and the SUB-LEADER (holding
    reconstructed v2 + verified v1) re-encodes the byte-identical
    stream for its members — striped byte ranges of one delta blob
    through the group chain, the "sharded delta wave" composition.
    Records per-wave wall + wire bytes and the root-vs-group split."""
    from ..core.types import LayerMeta
    from ..runtime import (
        HierarchicalFlowLeaderNode,
        FlowRetransmitReceiverNode,
        Node,
        SubLeaderController,
    )
    from ..runtime.codec import WireCodecPlane
    from ..transport import TcpTransport
    from ..utils import integrity, telemetry, trace
    from ..utils.provenance import harness_hash

    telemetry.reset_run()
    trace.reset_phases()
    ids = [0, 1, 2, 3]
    sub, members = 1, [1, 2, 3]
    block = os.urandom(1 << 20)

    def mem_layer(lid: int):
        from ..core.types import (
            LayerLocation,
            LayerSrc,
            SourceType,
        )

        reps = (layer_bytes + len(block) - 1) // len(block)
        data = bytearray((block * reps)[:layer_bytes])
        data[:8] = lid.to_bytes(8, "big")
        return LayerSrc(inmem_data=data, data_size=layer_bytes,
                        meta=LayerMeta(location=LayerLocation.INMEM,
                                       source_type=SourceType.MEM))

    ts = {i: TcpTransport("127.0.0.1:0") for i in ids}
    reg = {i: t.get_address() for i, t in ts.items()}
    for t in ts.values():
        t.addr_registry.update(reg)
    assignment = {i: {lid: LayerMeta() for lid in range(n_layers)}
                  for i in members}
    leader = HierarchicalFlowLeaderNode(
        Node(0, 0, ts[0]),
        {lid: mem_layer(lid) for lid in range(n_layers)},
        assignment, {i: bw for i in ids},
        groups={0: {"leader": sub, "members": members}},
        expected_nodes={sub}, codecs=WireCodecPlane(None))
    recvs = {i: FlowRetransmitReceiverNode(
        Node(i, 0 if i == sub else sub, ts[i]), {},
        codecs=WireCodecPlane(None)) for i in members}
    ctl = SubLeaderController(recvs[sub], 0, members)
    try:
        for r in recvs.values():
            r.announce()
        t0 = time.monotonic()
        leader.start_distribution().get(timeout=timeout)
        leader.ready().get(timeout=timeout)
        v1_s = round(time.monotonic() - t0, 4)

        def link_rx(frm, to):
            links = telemetry.snapshot()["links"]
            return sum(row.get("rx_bytes", 0)
                       for key, row in links.items()
                       if "#" not in key
                       and key.startswith(f"{frm}->")
                       and key.endswith(f"->{to}"))

        v1_root_tx = sum(link_rx(0, m) for m in members)
        from ..core.types import LayerLocation, LayerSrc, SourceType

        with leader._lock:
            for i in range(changed):
                data = _perturbed(leader.layers[i], perturb_stride,
                                   salt=1 + 7 * i)
                leader.layers[100 + i] = LayerSrc(
                    inmem_data=data, data_size=len(data),
                    meta=LayerMeta(location=LayerLocation.INMEM,
                                   source_type=SourceType.MEM))
        digests = {100 + i: integrity.layer_digest(
            bytes(leader.layers[100 + i].inmem_data))
            for i in range(changed)}
        waves = []
        rx_before = {m: link_rx(0, m) for m in members}
        for w, wave_dests in enumerate(([sub],
                                        [m for m in members
                                         if m != sub])):
            tw = time.monotonic()
            leader.submit_job(
                f"wave-{w + 1}",
                {d: {100 + i: LayerMeta() for i in range(changed)}
                 for d in wave_dests},
                priority=1, kind="push", version="v2", digests=digests)
            leader.ready().get(timeout=timeout)
            rx_now = {m: link_rx(0, m) for m in members}
            waves.append({
                "dests": wave_dests,
                "wall_s": round(time.monotonic() - tw, 4),
                "root_wire_bytes": sum(
                    rx_now[m] - rx_before[m] for m in members),
            })
            rx_before = rx_now
        for m in members:
            r = recvs[m]
            for i in range(changed):
                src = r.layers.get(100 + i)
                want = leader.layers[100 + i]
                if src is None or bytes(src.inmem_data) != bytes(
                        want.inmem_data):
                    raise AssertionError(
                        f"wave layer {100 + i} corrupt at {m}")
                if src.meta.version != "v2":
                    raise AssertionError(
                        f"wave layer {100 + i} at {m} lost its "
                        f"version tag: {src.meta.version!r}")
                if 100 + i not in r._digest_ok:
                    raise AssertionError(
                        f"wave layer {100 + i} at {m} unverified")
        counters = trace.counter_totals()
        changed_raw = changed * layer_bytes
        total_wire = sum(w["root_wire_bytes"] for w in waves)
        group_wire = sum(link_rx(sub, m) for m in members if m != sub)
        return {
            "harness_hash": harness_hash(),
            "backend": "tcp-loopback",
            "mode": 3,
            "layer_bytes": layer_bytes,
            "n_layers": n_layers,
            "changed_layers": changed,
            "perturb_stride": perturb_stride,
            "modeled_bw_bps": bw,
            "group": {"leader": sub, "members": members},
            "version": "v2",
            "v1_group_push_s": v1_s,
            "v1_root_wire_bytes": v1_root_tx,
            "waves": waves,
            "wave_wire_bytes": total_wire,
            "changed_raw_bytes": changed_raw,
            # Every replica materialized v2 but the root's NIC carried
            # only encoded delta streams — and wave 2 rode the group
            # chain (sub-leader re-encode), not the root.
            "delta_bound_met": bool(
                0 < total_wire <= changed_raw // 4),
            "delta_pairs_chosen": counters.get(
                "codec.delta_pairs_chosen", 0),
            "delta_reconstructed": counters.get(
                "codec.delta_reconstructed", 0),
            "delta_wire_bytes": counters.get("codec.delta_wire_bytes", 0),
            "delta_raw_bytes": counters.get("codec.delta_raw_bytes", 0),
            "group_wire_bytes": group_wire,
            "byte_exact": True,
            "digest_exact": True,
        }
    finally:
        ctl.close()
        leader.close()
        for r in recvs.values():
            r.close()
        for t in ts.values():
            t.close()


def run_sharded_delivery(layer_bytes: int = 64 << 20, n_layers: int = 2,
                         n_shards: int = 4, bw: int = 10 ** 9,
                         timeout: float = 600.0) -> dict:
    """Sharded delivery vs full-layer delivery (docs/sharding.md): the
    same multi-dest goal — ``n_shards`` dests, ``n_layers`` ×
    ``layer_bytes`` layers from one leader — run twice, once with every
    dest pulling FULL layers and once with each dest's target the
    ``1/n@k`` shard spec.  Records wire bytes per dest (must be ≈ the
    shard fraction, within 10%), TTD + predicted-vs-achieved for both
    runs, and the post-gather on-mesh layer's byte-exactness against
    the stamped full-layer digest — the acceptance bars of ROADMAP
    item 1."""
    from ..core.types import LayerMeta, shard_range, shard_specs_for
    from ..parallel.collectives import gather_byte_shards
    from ..utils import telemetry
    from ..utils.provenance import harness_hash
    from . import report as report_mod

    specs = shard_specs_for(n_shards)

    def one_run(sharded: bool) -> dict:
        telemetry.reset_run()
        assignment = {
            k + 1: {lid: LayerMeta(shard=specs[k] if sharded else "")
                    for lid in range(n_layers)}
            for k in range(n_shards)
        }
        leader, dests, ts, mem_layer = _service_rig(
            n_layers, layer_bytes, assignment, bw, n_dests=n_shards)
        try:
            t0 = time.monotonic()
            for r in dests:
                r.announce()
            leader.ready().get(timeout=timeout)
            ttd = round(time.monotonic() - t0, 4)
            links = telemetry.snapshot()["links"]
            per_dest = {r.node.my_id: _dest_wire_bytes(links,
                                                       r.node.my_id)
                        for r in dests}
            rec = {
                "ttd_s": ttd,
                "predicted_s": round(leader.predicted_ttd_ms / 1000.0, 4),
                "solve_ms": leader.solve_ms,
                "wire_bytes_per_dest": per_dest,
            }
            if sharded:
                # The acceptance gate: the dests' shards gather on-mesh
                # into layers byte-exact against the stamped digests.
                gathered_ok = 0
                for lid in range(n_layers):
                    parts = []
                    for k, r in enumerate(dests):
                        off, size = shard_range(specs[k], layer_bytes)
                        parts.append((k, bytes(
                            memoryview(r.layers[lid].inmem_data)
                            [off:off + size])))
                    out = gather_byte_shards(
                        parts, layer_bytes,
                        verify_digest=leader.layer_digests.get(lid))
                    if out != bytes(mem_layer(lid).inmem_data):
                        raise AssertionError(
                            f"gathered layer {lid} not byte-exact")
                    gathered_ok += 1
                rec["gathered_layers_byte_exact"] = gathered_ok
            else:
                # Byte-exactness of the full-layer sibling.
                for lid in range(n_layers):
                    for r in dests:
                        if bytes(r.layers[lid].inmem_data) != bytes(
                                mem_layer(lid).inmem_data):
                            raise AssertionError(
                                f"full layer {lid} corrupt at "
                                f"{r.node.my_id}")
            rep = report_mod.build_from_leader(leader)
            rec["run_report"] = rep.get("provenance")
            return rec
        finally:
            _service_teardown(leader, dests, ts)

    full = one_run(sharded=False)
    shard = one_run(sharded=True)
    frac_bytes = sum(shard_range(specs[k], layer_bytes)[1]
                     for k in range(n_shards)) // n_shards * n_layers
    bound_lo, bound_hi = frac_bytes, round(frac_bytes * 1.1)
    within = all(bound_lo <= d["rx_bytes"] <= bound_hi
                 for d in shard["wire_bytes_per_dest"].values())
    return {
        "harness_hash": harness_hash(),
        "backend": "tcp-loopback",
        "mode": 3,
        "layer_bytes": layer_bytes,
        "n_layers": n_layers,
        "n_dests": n_shards,
        "shard_fraction": f"1/{n_shards}",
        "modeled_bw_bps": bw,
        "full": full,
        "sharded": shard,
        "shard_bytes_per_dest_bound": [bound_lo, bound_hi],
        "wire_within_10pct": within,
        "ttd_ratio": round(shard["ttd_s"] / max(full["ttd_s"], 1e-9), 4),
    }


def run_fabric_delivery(layer_bytes: int = 32 << 20, n_layers: int = 2,
                        pod_size: int = 4, bw: int = 10 ** 9,
                        timeout: float = 600.0) -> dict:
    """Fabric-assisted pod delivery vs host-path fan-out
    (docs/fabric.md): the same topology — one leader, ``pod_size``
    replica dests all wanting all ``n_layers`` × ``layer_bytes`` layers
    — run twice.  HOST path: every replica pulls every full layer over
    its NIC (pod ingress = model_bytes × replicas).  FABRIC-ASSISTED:
    the leader pod-plans one 1/R shard per host over the NIC and the
    replicas materialize the full tree over the on-mesh gather (pod
    ingress ≈ model_bytes).  Records per-pod NIC wire bytes (byte-exact
    via the telemetry link table reconcile), TTD, per-replica
    tree-digest exactness against the leader's stamped full-layer
    digests, and RUN_REPORT provenance."""
    from ..core.types import LayerMeta, shard_range
    from ..parallel.fabric import FabricPlane
    from ..utils import integrity, telemetry, trace
    from ..utils.provenance import harness_hash
    from . import report as report_mod

    model_bytes = n_layers * layer_bytes

    def one_run(pod: bool) -> dict:
        telemetry.reset_run()
        assignment = {
            k + 1: {lid: LayerMeta() for lid in range(n_layers)}
            for k in range(pod_size)
        }
        members = list(range(1, pod_size + 1))
        leader, dests, ts, mem_layer = _service_rig(
            n_layers, layer_bytes, assignment, bw, n_dests=pod_size,
            fabric=FabricPlane() if pod else None,
            pods={0: members} if pod else None)
        try:
            t0 = time.monotonic()
            for r in dests:
                r.announce()
            leader.ready().get(timeout=timeout)
            ttd = round(time.monotonic() - t0, 4)
            links = telemetry.snapshot()["links"]
            per_dest = {r.node.my_id: _dest_wire_bytes(links,
                                                       r.node.my_id)
                        for r in dests}
            # The acceptance gate: every replica's FULL tree, byte-
            # and digest-exact against the leader's stamped full-layer
            # digests (for the pod run this is the post-gather state).
            exact = 0
            for r in dests:
                for lid in range(n_layers):
                    src = r.layers[lid]
                    if src.meta.shard:
                        raise AssertionError(
                            f"dest {r.node.my_id} layer {lid} is still "
                            f"a shard holding ({src.meta.shard})")
                    tree = bytes(src.inmem_data)
                    if tree != bytes(mem_layer(lid).inmem_data):
                        raise AssertionError(
                            f"dest {r.node.my_id} layer {lid} tree not "
                            "byte-exact")
                    stamped = leader.layer_digests.get(lid)
                    if stamped and not integrity.digest_matches(
                            tree, stamped):
                        raise AssertionError(
                            f"dest {r.node.my_id} layer {lid} tree "
                            "fails the stamped digest")
                    exact += 1
            pod_wire = sum(d["rx_bytes"] for d in per_dest.values())
            pod_delivered = sum(d["delivered_bytes"]
                                for d in per_dest.values())
            counters = trace.counter_totals()
            rep = report_mod.build_from_leader(leader)
            return {
                "ttd_s": ttd,
                "predicted_s": round(leader.predicted_ttd_ms / 1000.0,
                                     4),
                "solve_ms": leader.solve_ms,
                "pod_nic_wire_bytes": pod_wire,
                "pod_delivered_bytes": pod_delivered,
                "wire_bytes_per_dest": per_dest,
                "trees_digest_exact": exact,
                "gathers": counters.get("shard.gathered_layers", 0),
                "run_report": rep.get("provenance"),
            }
        finally:
            _service_teardown(leader, dests, ts)

    host = one_run(pod=False)
    fab = one_run(pod=True)
    # Per-pod ingress bars: host path ships model_bytes × R; the
    # fabric-assisted run must land within 10% of model_bytes (framing
    # overhead only — the byte-exact reconcile is on delivered bytes).
    fab_ok = (model_bytes
              <= fab["pod_nic_wire_bytes"] <= round(model_bytes * 1.1))
    return {
        "harness_hash": harness_hash(),
        "backend": "tcp-loopback",
        "mode": 3,
        "layer_bytes": layer_bytes,
        "n_layers": n_layers,
        "replicas": pod_size,
        "model_bytes": model_bytes,
        "modeled_bw_bps": bw,
        "host_path": host,
        "fabric_assisted": fab,
        "pod_wire_bound": [model_bytes, round(model_bytes * 1.1)],
        "pod_wire_within_10pct": fab_ok,
        "pod_delivered_exact": fab["pod_delivered_bytes"] == sum(
            shard_range(f"1/{pod_size}@{k}", layer_bytes)[1]
            for k in range(pod_size) for _ in range(n_layers)),
        "wire_ratio_vs_host": round(
            fab["pod_nic_wire_bytes"]
            / max(host["pod_nic_wire_bytes"], 1), 4),
        "ttd_ratio_vs_host": round(
            fab["ttd_s"] / max(host["ttd_s"], 1e-9), 4),
        "byte_exact": True,
    }


def run_fanout(sizes=(64, 256), n_layers: int = 2,
               layer_bytes: int = 256 << 10,
               timeout: float = 600.0) -> dict:
    """Scale-out acceptance row (docs/hierarchy.md; ROADMAP item 1):
    the SAME inmem BASELINE goal — every one of N dests wants every
    layer from the one seeding root — run flat (mode 3) and
    hierarchically (sqrt-sized groups under sub-leaders), at each fleet
    size in ``sizes``.  Records, per run: root flow-solve wall, the
    count of control messages the ROOT's loop handled
    (``ctrl.handled.<root>``), TTD, and RUN_REPORT provenance.  The
    bar: from N=64 to N=256 the hierarchical root's solve wall and
    handled-message count must grow SUB-LINEARLY in N while the flat
    root's grow ~linearly — and the hierarchical absolute numbers must
    beat the flat ones at 256."""
    from ..core.types import LayerMeta
    from ..runtime import (
        FlowRetransmitLeaderNode,
        FlowRetransmitReceiverNode,
        HierarchicalFlowLeaderNode,
        Node,
        SubLeaderController,
        partition_groups,
    )
    from ..transport import reset_registry
    from ..transport.inmem import InmemTransport
    from ..utils import telemetry
    from ..utils.provenance import harness_hash
    from . import report as report_mod

    pattern = bytes(range(256))

    def mem_blob(lid: int):
        from ..core.types import LayerLocation, LayerSrc, SourceType

        rot = (lid * 37) % 256
        data = bytearray((pattern[rot:] + pattern[:rot])
                         * (layer_bytes // 256))
        return LayerSrc(inmem_data=data, data_size=len(data),
                        meta=LayerMeta(location=LayerLocation.INMEM,
                                       source_type=SourceType.MEM))

    def one_run(n: int, hier: bool) -> dict:
        reset_registry()
        telemetry.reset_run()
        ids = list(range(n + 1))
        registry = {i: f"n{i}" for i in ids}
        ts = {i: InmemTransport(registry[i], addr_registry=registry)
              for i in ids}
        assignment = {i: {lid: LayerMeta() for lid in range(n_layers)}
                      for i in ids[1:]}
        layers = {lid: mem_blob(lid) for lid in range(n_layers)}
        bw = {i: 10 ** 9 for i in ids}
        recvs, ctls = {}, []
        groups = {}
        if hier:
            groups = partition_groups(ids[1:])  # ~sqrt(N)-sized groups
            subs = {rec["leader"] for rec in groups.values()}
            leader = HierarchicalFlowLeaderNode(
                Node(0, 0, ts[0]), layers, assignment, bw,
                groups=groups, expected_nodes=subs)
            for gid, rec in sorted(groups.items()):
                sub = rec["leader"]
                r = FlowRetransmitReceiverNode(Node(sub, 0, ts[sub]), {})
                ctls.append(SubLeaderController(r, gid, rec["members"]))
                recvs[sub] = r
                for m in rec["members"]:
                    if m != sub:
                        recvs[m] = FlowRetransmitReceiverNode(
                            Node(m, sub, ts[m]), {})
        else:
            leader = FlowRetransmitLeaderNode(
                Node(0, 0, ts[0]), layers, assignment, bw,
                expected_nodes=set(ids[1:]))
            for i in ids[1:]:
                recvs[i] = FlowRetransmitReceiverNode(
                    Node(i, 0, ts[i]), {})
        try:
            t0 = time.monotonic()
            for i in sorted(recvs):
                recvs[i].announce()
            leader.start_distribution().get(timeout=timeout)
            leader.ready().get(timeout=timeout)
            ttd = round(time.monotonic() - t0, 4)
            bad = 0
            for i in ids[1:]:
                for lid in range(n_layers):
                    if bytes(recvs[i].layers[lid].inmem_data) != bytes(
                            mem_blob(lid).inmem_data):
                        bad += 1
            if bad:
                raise AssertionError(
                    f"{bad} corrupt deliveries at n={n} hier={hier}")
            snap = telemetry.snapshot()
            counters = snap["counters"]
            # Byte-exact link reconcile (docs/hierarchy.md): the base
            # "src->dest" link rows claim delivered bytes exactly once
            # per dest pair, so their sum must equal N x model bytes no
            # matter how many member-to-member hops carried them.
            delivered = sum(int(row.get("delivered_bytes", 0))
                            for key, row in snap["links"].items()
                            if "#" not in key)
            egress = int(counters.get("hier.subleader_egress_bytes", 0))
            rep = report_mod.build_from_leader(leader)
            return {
                "n_nodes": n,
                "control": "hierarchical" if hier else "flat",
                "groups": len(groups),
                "ttd_s": ttd,
                "solve_ms": leader.solve_ms,
                "predicted_s": round(leader.predicted_ttd_ms / 1000.0, 4),
                "root_handled_msgs": int(counters.get("ctrl.handled.0",
                                                      0)),
                "byte_exact_deliveries": n * n_layers,
                "chain_plans": int(counters.get("hier.chain_plans", 0)),
                "relay_bytes": int(counters.get("hier.relay_bytes", 0)),
                "subleader_egress_bytes": egress,
                "egress_bytes_per_subleader": (
                    round(egress / len(groups)) if groups else 0),
                "link_reconcile_exact":
                    delivered == n * n_layers * layer_bytes,
                "run_report": rep.get("provenance"),
            }
        finally:
            for c in ctls:
                c.close()
            leader.close()
            for r in recvs.values():
                r.close()
            for t in ts.values():
                t.close()
            reset_registry()

    # An N-node in-process fleet must not lazily grow N x 16 handler
    # threads; 2 per seat is plenty for the control traffic here.
    prior_workers = os.environ.get("DLD_MSGLOOP_WORKERS")
    os.environ["DLD_MSGLOOP_WORKERS"] = "2"
    try:
        rows = []
        for n in sizes:
            for hier in (False, True):
                row = one_run(n, hier)
                rows.append(row)
                print(f"fanout n={n} {row['control']}: TTD "
                      f"{row['ttd_s']}s solve {row['solve_ms']}ms "
                      f"root-handled {row['root_handled_msgs']}",
                      file=sys.stderr, flush=True)
    finally:
        if prior_workers is None:
            os.environ.pop("DLD_MSGLOOP_WORKERS", None)
        else:
            os.environ["DLD_MSGLOOP_WORKERS"] = prior_workers

    def pick(n, control):
        return next(r for r in rows
                    if r["n_nodes"] == n and r["control"] == control)

    lo, hi = sizes[0], sizes[-1]
    node_growth = hi / lo
    flat_lo, flat_hi = pick(lo, "flat"), pick(hi, "flat")
    hier_lo, hier_hi = pick(lo, "hierarchical"), pick(hi, "hierarchical")
    msg_growth_flat = round(flat_hi["root_handled_msgs"]
                            / max(flat_lo["root_handled_msgs"], 1), 3)
    msg_growth_hier = round(hier_hi["root_handled_msgs"]
                            / max(hier_lo["root_handled_msgs"], 1), 3)
    solve_growth_flat = round(flat_hi["solve_ms"]
                              / max(flat_lo["solve_ms"], 1e-9), 3)
    solve_growth_hier = round(hier_hi["solve_ms"]
                              / max(hier_lo["solve_ms"], 1e-9), 3)
    # Chain-vs-star egress at the top size (docs/hierarchy.md): under
    # the old sub-leader star every one of the (N - n_groups) non-sub
    # members would be a full copy out of its sub's NIC; the chain
    # ships each group ~one copy and lets members relay the rest, so
    # of each group's R copies only 1/R leaves the sub — (R-1)/R of
    # the fan rides member-to-member links.
    model_bytes = n_layers * layer_bytes
    star_bytes = (hier_hi["n_nodes"] - hier_hi["groups"]) * model_bytes
    chain_bytes = hier_hi["subleader_egress_bytes"]
    return {
        "harness_hash": harness_hash(),
        "backend": "inmem",
        "mode": 3,
        "n_layers": n_layers,
        "layer_bytes": layer_bytes,
        "group_sizing": "sqrt",
        "rows": rows,
        "node_growth": node_growth,
        "root_msgs_growth": {"flat": msg_growth_flat,
                             "hierarchical": msg_growth_hier},
        "solve_growth": {"flat": solve_growth_flat,
                         "hierarchical": solve_growth_hier},
        # The acceptance bars (docs/hierarchy.md): sub-linear growth in
        # N for the hierarchical root, and absolutely cheaper than the
        # flat root at the top size.
        "msgs_sublinear": (msg_growth_hier < node_growth
                           and hier_hi["root_handled_msgs"]
                           < flat_hi["root_handled_msgs"]),
        "solve_sublinear": (solve_growth_hier < node_growth
                            and hier_hi["solve_ms"]
                            < flat_hi["solve_ms"]),
        "chain_egress": {
            "subleader_egress_bytes": chain_bytes,
            "egress_bytes_per_subleader":
                hier_hi["egress_bytes_per_subleader"],
            "relay_bytes": hier_hi["relay_bytes"],
            "star_equivalent_bytes": star_bytes,
            "egress_savings_frac": (round(1.0 - chain_bytes / star_bytes,
                                          3) if star_bytes else 0.0),
        },
        "links_reconcile_exact": all(r["link_reconcile_exact"]
                                     for r in rows),
    }


def run_elasticity(joiner_counts=(2, 6), n_base: int = 2,
                   n_layers: int = 3, layer_bytes: int = 256 << 10,
                   timeout: float = 120.0) -> dict:
    """Elastic-membership acceptance row (docs/membership.md; ROADMAP
    item 5): the base goal disseminates from ONE origin seeder (the
    leader) to ``n_base`` configured dests; then N UNCONFIGURED nodes
    JOIN the running cluster concurrently and must reach full coverage
    byte-exactly.  Per variant the row records the origin-seeder wire
    bytes into the joiners vs the bytes peer holders served, and the
    bars: the MAJORITY of refill bytes come from peer holders, and
    origin bytes grow sub-linearly in the joiner count (the join
    refill policy avoids the origin whenever peers can serve)."""
    import threading as _threading

    from ..core.types import LayerMeta
    from ..runtime import (
        FlowRetransmitLeaderNode,
        FlowRetransmitReceiverNode,
        Node,
    )
    from ..transport import reset_registry
    from ..transport.inmem import InmemTransport
    from ..utils import telemetry
    from ..utils.provenance import harness_hash
    from . import report as report_mod

    pattern = bytes(range(256))

    def mem_blob(lid: int):
        from ..core.types import LayerLocation, LayerSrc, SourceType

        rot = (lid * 53) % 256
        data = bytearray((pattern[rot:] + pattern[:rot])
                         * (layer_bytes // 256))
        return LayerSrc(inmem_data=data, data_size=len(data),
                        meta=LayerMeta(location=LayerLocation.INMEM,
                                       source_type=SourceType.MEM))

    def one_run(n_joiners: int) -> dict:
        reset_registry()
        telemetry.reset_run()
        ids = list(range(n_base + 1))
        registry = {i: f"n{i}" for i in ids}
        ts = {i: InmemTransport(registry[i], addr_registry=registry)
              for i in ids}
        assignment = {i: {lid: LayerMeta() for lid in range(n_layers)}
                      for i in ids[1:]}
        leader = FlowRetransmitLeaderNode(
            Node(0, 0, ts[0]), {lid: mem_blob(lid)
                                for lid in range(n_layers)},
            assignment, {i: 10 ** 9 for i in ids},
            expected_nodes=set(ids[1:]))
        recvs = {i: FlowRetransmitReceiverNode(Node(i, 0, ts[i]), {})
                 for i in ids[1:]}
        joiners = {}
        try:
            for r in recvs.values():
                r.announce()
            leader.start_distribution().get(timeout=timeout)
            leader.ready().get(timeout=timeout)
            # The joiners arrive CONCURRENTLY, mid-service: each join
            # admits a refill job that overlaps the others' in-flight
            # dissemination.
            t0 = time.monotonic()
            for k in range(n_joiners):
                jid = 100 + k
                tj = InmemTransport(f"n{jid}",
                                    addr_registry={0: registry[0]})
                ts[jid] = tj
                joiners[jid] = FlowRetransmitReceiverNode(
                    Node(jid, 0, tj), {})
            threads = [_threading.Thread(
                target=joiners[jid].join, kwargs={"timeout": timeout},
                daemon=True) for jid in joiners]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout)

            def covered():
                for j in joiners.values():
                    for lid in range(n_layers):
                        src = j.layers.get(lid)
                        if src is None or bytes(src.inmem_data) != bytes(
                                mem_blob(lid).inmem_data):
                            return False
                return True

            deadline = time.monotonic() + timeout
            while not covered():
                if time.monotonic() > deadline:
                    raise AssertionError(
                        f"joiners not covered at n={n_joiners}")
                time.sleep(0.02)
            cover_s = round(time.monotonic() - t0, 4)
            # BASE rows only: job-tagged fields file on the base row
            # AND the #job split row — summing both double-counts.
            origin_bytes = peer_bytes = 0
            for key, row in telemetry.snapshot()["links"].items():
                if "#" in key:
                    continue
                s, d = key.split("->")
                if int(d) >= 100:
                    b = int(row.get("tx_bytes", 0))
                    if int(s) == 0:
                        origin_bytes += b
                    else:
                        peer_bytes += b
            rep = report_mod.build_from_leader(leader)
            total = origin_bytes + peer_bytes
            return {
                "n_joiners": n_joiners,
                "coverage_s": cover_s,
                "origin_bytes": origin_bytes,
                "peer_bytes": peer_bytes,
                "peer_fraction": round(peer_bytes / total, 4)
                                 if total else 0.0,
                "byte_exact_deliveries": n_joiners * n_layers,
                "members": leader.membership.size(),
                "run_report": rep.get("provenance"),
            }
        finally:
            leader.close()
            for r in list(recvs.values()) + list(joiners.values()):
                r.close()
            for t in ts.values():
                t.close()
            reset_registry()

    rows = []
    for n in joiner_counts:
        row = one_run(n)
        rows.append(row)
        print(f"elasticity n_joiners={n}: origin "
              f"{row['origin_bytes']} B, peers {row['peer_bytes']} B "
              f"(peer fraction {row['peer_fraction']}), covered in "
              f"{row['coverage_s']}s", file=sys.stderr, flush=True)
    lo, hi = rows[0], rows[-1]
    joiner_growth = hi["n_joiners"] / max(lo["n_joiners"], 1)
    origin_growth = (hi["origin_bytes"] / lo["origin_bytes"]
                     if lo["origin_bytes"] else
                     (0.0 if not hi["origin_bytes"] else float("inf")))
    return {
        "harness_hash": harness_hash(),
        "backend": "inmem",
        "mode": 3,
        "n_base": n_base,
        "n_layers": n_layers,
        "layer_bytes": layer_bytes,
        "rows": rows,
        "joiner_growth": joiner_growth,
        "origin_growth": round(origin_growth, 3),
        # The acceptance bars (docs/membership.md): refills come mostly
        # from peer holders, and origin bytes grow sub-linearly in the
        # joiner count.
        "peers_majority": all(r["peer_fraction"] > 0.5 for r in rows
                              if r["origin_bytes"] + r["peer_bytes"]),
        "origin_sublinear": origin_growth < joiner_growth,
    }


def run_live_swap(warm_s: float = 1.5, after_s: float = 1.5,
                  timeout: float = 300.0) -> dict:
    """Zero-downtime weight swap under live traffic (docs/swap.md, the
    ROADMAP item-4 acceptance row): a tiny-model replica serves
    generation requests continuously while a ``kind="swap"`` job
    disseminates v2 under version-tagged ids; the epoch-fenced commit
    flips the serving params atomically.  Records tokens/s and p99
    request latency BEFORE / DURING / AFTER the swap, the request
    failure count (the bar: zero), per-blob v2 digest verification,
    and RUN_REPORT provenance.  Runs in-process over the inmem
    backend: the row measures the SERVING dip attributable to the
    swap machinery, not loopback-TCP scheduling noise (the dual-
    backend wire path is tier-1-tested in tests/test_swap.py)."""
    import threading

    import jax

    from ..core.types import (
        LayerLocation,
        LayerMeta,
        LayerSrc,
        SourceType,
    )
    from ..models import serde
    from ..models.llama import CONFIGS, init_params
    from ..runtime import (
        FlowRetransmitLeaderNode,
        FlowRetransmitReceiverNode,
        Node,
    )
    from ..runtime.client import GenRequester
    from ..transport import InmemTransport
    from ..utils import integrity, telemetry, trace
    from ..utils.provenance import harness_hash
    from . import report as report_mod

    telemetry.reset_run()
    cfg = CONFIGS["tiny"]
    swap_base = 1000
    v1 = serde.blobs_from_params(cfg, init_params(cfg, jax.random.key(0)))
    v2 = serde.blobs_from_params(cfg, init_params(cfg, jax.random.key(1)))

    def blob_layer(data: bytes) -> LayerSrc:
        return LayerSrc(inmem_data=bytearray(data), data_size=len(data),
                        meta=LayerMeta(location=LayerLocation.INMEM,
                                       source_type=SourceType.MEM))

    ids = [0, 1, 9]
    ts = {i: InmemTransport(str(i)) for i in ids}
    seed = {b: blob_layer(v1[b]) for b in v1}
    seed.update({swap_base + b: blob_layer(v2[b]) for b in v2})
    base = {1: {b: LayerMeta() for b in v1}}
    leader = FlowRetransmitLeaderNode(
        Node(0, 0, ts[0]), seed, base, {i: 10 ** 9 for i in ids},
        expected_nodes={1})
    dest = FlowRetransmitReceiverNode(Node(1, 0, ts[1]), {}, boot_cfg=cfg)
    requester = GenRequester(ts[9], my_id=9)
    prompt, max_new = [3, 5, 7], 8
    lat: dict = {"before": [], "during": [], "after": []}
    failures: list = []
    phase = ["before"]
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            t0 = time.monotonic()
            try:
                requester.request(1, prompt, max_new, timeout=timeout)
                lat[phase[0]].append(time.monotonic() - t0)
            except Exception as e:  # noqa: BLE001 — any failure counts
                failures.append(repr(e))
            time.sleep(0.01)

    def stats(xs):
        if not xs:
            return {"requests": 0}
        xs = sorted(xs)
        p99 = xs[min(len(xs) - 1, int(len(xs) * 0.99))]
        return {"requests": len(xs),
                "tokens_per_s": round(max_new * len(xs) / sum(xs), 2),
                "p50_ms": round(xs[len(xs) // 2] * 1000, 1),
                "p99_ms": round(p99 * 1000, 1)}

    try:
        dest.announce()
        leader.ready().get(timeout=timeout)
        leader.boot_ready().get(timeout=timeout)
        requester.request(1, prompt, max_new, timeout=timeout)  # warm jit
        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        time.sleep(warm_s)
        phase[0] = "during"
        t_swap = time.monotonic()
        leader.submit_job(
            "swap-v2",
            {1: {swap_base + b: LayerMeta() for b in v2}},
            priority=2, kind="swap", version="v2", swap_base=swap_base)
        deadline = time.monotonic() + timeout
        while dest.serving_version != "v2":
            if time.monotonic() > deadline:
                raise TimeoutError("swap never flipped")
            time.sleep(0.02)
        swap_s = time.monotonic() - t_swap
        phase[0] = "after"
        time.sleep(after_s)
        stop.set()
        t.join(timeout=timeout)
        table = leader.swap_table()["v2"]
        digests_ok = (all(swap_base + b in dest._digest_ok for b in v2)
                      if integrity.digests_enabled() else None)
        counters = trace.counter_totals()
        rep = report_mod.build_from_leader(leader)
        before, during, after = (stats(lat[k])
                                 for k in ("before", "during", "after"))
        dip = None
        if before.get("tokens_per_s") and during.get("tokens_per_s"):
            dip = round(1 - during["tokens_per_s"]
                        / before["tokens_per_s"], 4)
        return {
            "harness_hash": harness_hash(),
            "backend": "inmem",
            "mode": 3,
            "model": "tiny",
            "v2_model_bytes": sum(len(b) for b in v2.values()),
            "swap_wall_s": round(swap_s, 4),
            "request_failures": len(failures),
            "zero_failures": not failures,
            "before": before,
            "during": during,
            "after": after,
            "tokens_per_s_dip_frac": dip,
            "v2_digests_verified": digests_ok,
            "flips": counters.get("swap.flips", 0),
            "served_version_after": dest.serving_version,
            "swap_table": table,
            "run_report": rep.get("provenance"),
        }
    finally:
        stop.set()
        requester.close()
        _service_teardown(leader, [dest], ts)


def run_rollout(soak_s: float = 2.5, p99_ms: float = 2000.0,
                bad_delay_ms: float = 1500.0,
                timeout: float = 300.0) -> dict:
    """SLO-guarded rollout pipeline under live traffic (docs/rollout.md,
    the ROADMAP item-3 acceptance row): a continuous request stream
    drives three tiny-model replicas while a ``kind="rollout"`` job
    ships v2 through three canary waves.  Wave 1 is the INJECTED BAD
    WAVE — its replica's answers ride a seeded ``slowserve`` transport
    delay, so its soak p99 breaches the declared SLO: the pipeline must
    auto-PAUSE and roll that wave back to v1 through the revert-abort
    while wave 0 KEEPS serving v2 and wave 2 stays staged-but-held.
    The bars: zero dropped requests fleet-wide, the breach verdict
    recorded with per-replica p99, earlier wave still on v2 after the
    rollback.  In-process inmem (the dual-backend wire path is
    tier-1-tested in tests/test_rollout.py); RUN_REPORT provenance
    recorded."""
    import threading

    import jax

    from ..core.types import (
        LayerLocation,
        LayerMeta,
        LayerSrc,
        SourceType,
    )
    from ..models import serde
    from ..models.llama import CONFIGS, init_params
    from ..runtime import (
        FlowRetransmitLeaderNode,
        FlowRetransmitReceiverNode,
        Node,
    )
    from ..runtime.client import GenRequester
    from ..transport import InmemTransport
    from ..transport.faults import FaultyTransport, rules_from_spec
    from ..utils import telemetry, trace
    from ..utils.provenance import harness_hash
    from . import report as report_mod

    telemetry.reset_run()
    prior_metrics = os.environ.get("DLD_METRICS_INTERVAL_S")
    os.environ["DLD_METRICS_INTERVAL_S"] = "0.25"
    cfg = CONFIGS["tiny"]
    swap_base = 1000
    v1 = serde.blobs_from_params(cfg, init_params(cfg, jax.random.key(0)))
    v2 = serde.blobs_from_params(cfg, init_params(cfg, jax.random.key(1)))

    def blob_layer(data: bytes) -> LayerSrc:
        return LayerSrc(inmem_data=bytearray(data), data_size=len(data),
                        meta=LayerMeta(location=LayerLocation.INMEM,
                                       source_type=SourceType.MEM))

    replicas_ids = [1, 2, 3]
    bad = 2  # wave 1's replica
    ids = [0, *replicas_ids, 9]
    ts = {i: InmemTransport(str(i)) for i in ids}
    fault_spec = f"slowserve={bad_delay_ms:g}"
    seed, rules = rules_from_spec(fault_spec)
    ts[bad] = FaultyTransport(ts[bad], rules, seed=seed)
    seed_layers = {b: blob_layer(v1[b]) for b in v1}
    seed_layers.update({swap_base + b: blob_layer(v2[b]) for b in v2})
    base = {r: {b: LayerMeta() for b in v1} for r in replicas_ids}
    leader = FlowRetransmitLeaderNode(
        Node(0, 0, ts[0]), seed_layers, base,
        {i: 10 ** 9 for i in ids}, expected_nodes=set(replicas_ids))
    replicas = {r: FlowRetransmitReceiverNode(Node(r, 0, ts[r]), {},
                                              boot_cfg=cfg)
                for r in replicas_ids}
    requester = GenRequester(ts[9], my_id=9)
    prompt, max_new = [3, 5, 7], 8
    failures: list = []
    served = {r: 0 for r in replicas_ids}
    stop = threading.Event()

    def hammer(replica):
        while not stop.is_set():
            try:
                requester.request(replica, prompt, max_new,
                                  timeout=timeout)
                served[replica] += 1
            except Exception as e:  # noqa: BLE001 — any failure counts
                failures.append(repr(e))
            time.sleep(0.03)

    threads = [threading.Thread(target=hammer, args=(r,), daemon=True)
               for r in replicas_ids]
    try:
        for r in replicas.values():
            r.announce()
        leader.ready().get(timeout=timeout)
        leader.boot_ready().get(timeout=timeout)
        for r in replicas_ids:  # warm the decode jits pre-rollout
            requester.request(r, prompt, max_new, timeout=timeout)
        for t in threads:
            t.start()
        t_roll = time.monotonic()
        leader.submit_job(
            "roll-v2",
            {r: {swap_base + b: LayerMeta() for b in v2}
             for r in replicas_ids},
            priority=2, kind="rollout", version="v2",
            swap_base=swap_base, waves=[[1], [2], [3]],
            slo={"P99Ms": p99_ms, "MaxFailures": 5, "SoakS": soak_s},
            split=0.5)
        deadline = time.monotonic() + timeout

        def row():
            return leader.rollouts.summary("roll-v2")

        while row().get("State") != "paused":
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"bad wave never breached: {row()}")
            time.sleep(0.05)
        pause_s = time.monotonic() - t_roll
        # The rollback fence is in flight: wait for the replica revert.
        while replicas[bad].serving_version != "":
            if time.monotonic() > deadline:
                raise TimeoutError("bad wave never reverted to v1")
            time.sleep(0.05)
        time.sleep(0.5)  # post-rollback serving window
        stop.set()
        for t in threads:
            t.join(timeout=timeout)
        final = row()
        traffic = final["Traffic"]
        counters = trace.counter_totals()
        rep = report_mod.build_from_leader(leader)
        # Post-rollback serving probes: wave 0 keeps v2, the rolled-
        # back wave answers v1 again, wave 2 never flipped.
        def toks(seed_):
            from ..models.generate import generate
            import jax.numpy as jnp

            out = generate(init_params(cfg, jax.random.key(seed_)),
                           jnp.asarray([prompt], jnp.int32), cfg,
                           max_new=max_new)
            return [int(t) for t in jax.device_get(out)[0]]

        v1_tokens, v2_tokens = toks(0), toks(1)
        probes = {r: requester.request(r, prompt, max_new,
                                       timeout=timeout)
                  for r in replicas_ids}
        return {
            "harness_hash": harness_hash(),
            "backend": "inmem",
            "mode": 3,
            "model": "tiny",
            "waves": final["Waves"],
            "wave_states": final["WaveStates"],
            "slo": final["SLO"],
            "split": final["Split"],
            "fault_spec": fault_spec,
            "state": final["State"],
            "paused_reason": final["PausedReason"],
            "verdicts": final["Verdicts"],
            "wall_to_breach_pause_s": round(pause_s, 3),
            "request_failures": len(failures),
            "zero_failures": not failures,
            "requests_served": dict(served),
            "traffic_after": traffic,
            "wave0_keeps_v2": probes[1] == v2_tokens,
            "bad_wave_back_on_v1": probes[bad] == v1_tokens,
            "wave2_never_flipped": probes[3] == v1_tokens,
            "serving_versions": {r: replicas[r].serving_version
                                 for r in replicas_ids},
            "slo_breaches": counters.get("rollout.slo_breach", 0),
            "reverts": counters.get("swap.reverted", 0),
            "waves_passed": counters.get("rollout.wave_passed", 0),
            "run_report": rep.get("provenance"),
        }
    finally:
        stop.set()
        requester.close()
        if prior_metrics is None:
            os.environ.pop("DLD_METRICS_INTERVAL_S", None)
        else:
            os.environ["DLD_METRICS_INTERVAL_S"] = prior_metrics
        _service_teardown(leader, list(replicas.values()), ts)


def _rollout_md(lines, results) -> None:
    ro = results.get("rollout")
    if not ro:
        return
    bars = {
        "zero dropped requests": ro["zero_failures"],
        "bad wave auto-halted (SLO breach -> pause)":
            ro["state"] == "paused" and ro["slo_breaches"] >= 1,
        "bad wave rolled back to v1": ro["bad_wave_back_on_v1"],
        "earlier wave keeps serving v2": ro["wave0_keeps_v2"],
    }
    lines += [
        "## SLO-guarded rollout pipeline (docs/rollout.md)",
        "",
        f"A continuous request stream drives 3 tiny-model replicas "
        f"({ro['backend']} backend, mode {ro['mode']}) through a "
        f"3-wave `kind=\"rollout\"` pipeline (waves {ro['waves']}, "
        f"SLO p99 <= {ro['slo']['p99_ms']:g}ms over "
        f"{ro['slo']['soak_s']:g}s soaks, split {ro['split']}).  "
        f"Wave 1's replica is the injected bad wave "
        f"(`{ro['fault_spec']}`): its soak breached and the pipeline "
        f"paused after {ro['wall_to_breach_pause_s']}s "
        f"(`{ro['paused_reason']}`).",
        "",
        "| bar | met |",
        "|---|---|",
    ]
    for name, met in bars.items():
        lines.append(f"| {name} | {'MET' if met else 'NOT MET'} |")
    lines += [
        "",
        f"Wave states `{ro['wave_states']}`; verdicts: "
        + "; ".join(
            f"wave {w}: {v['verdict']}"
            + (f" (p99 {next(iter(v['replicas'].values()))['p99_ms']}"
               "ms)" if v.get("replicas") else "")
            for w, v in sorted(ro["verdicts"].items()))
        + f".  {sum(ro['requests_served'].values())} requests served, "
        f"{ro['request_failures']} failed.  Traffic pools after the "
        f"rollback: v2={ro['traffic_after']['v2']} "
        f"v1={ro['traffic_after']['v1']} at split "
        f"{ro['traffic_after']['split']}.  Run report "
        f"`{ro.get('run_report')}`.",
        "",
    ]


def run_autonomy(p99_ms: float = 250.0, hot_delay_ms: float = 600.0,
                 bulk_bytes: int = 24 << 20, bw: int = 25_000_000,
                 slow_rate: int = 2 << 20, timeout: float = 300.0,
                 kill_switch: bool = False) -> dict:
    """The closed-loop fleet-autonomy acceptance row (docs/autonomy.md,
    ROADMAP item 4): a serving fleet takes TWO concurrent injections —
    a ``slowserve`` hot replica breaching the serve SLO and a ``slow=``
    straggler link under a bulk transfer — and the leader's policy
    engine must converge the fleet back inside SLO with ZERO operator
    verbs: the replica set grown onto a spare (join+refill through
    ``submit_job``), the slow link demoted and re-planned around
    through the flow solver, the breaching replica quarantined out of
    the serve rotation, every action audited and span-attributed in
    RUN_REPORT.  ``kill_switch=True`` runs the SAME injections under
    ``DLD_POLICY=0``: sensing stays live (``held_manual`` audit
    records) but nothing fires — the sibling row proving the zero-verb
    convergence was the ENGINE, not a coincidence."""
    import threading

    import jax

    from ..core.types import (
        LayerLocation,
        LayerMeta,
        LayerSrc,
        SourceType,
    )
    from ..models import serde
    from ..models.llama import CONFIGS, init_params
    from ..runtime import (
        FlowRetransmitLeaderNode,
        FlowRetransmitReceiverNode,
        Node,
    )
    from ..runtime import send as send_mod
    from ..runtime.client import GenRequester
    from ..transport import InmemTransport
    from ..transport.faults import FaultyTransport, rules_from_spec
    from ..utils import telemetry, trace
    from ..utils.provenance import harness_hash
    from . import report as report_mod

    telemetry.reset_run()
    prior_metrics = os.environ.get("DLD_METRICS_INTERVAL_S")
    prior_policy = os.environ.get("DLD_POLICY")
    prior_sustain = os.environ.get("DLD_STRAGGLER_N")
    prior_frag = send_mod.FLOW_FRAGMENT_BYTES
    os.environ["DLD_METRICS_INTERVAL_S"] = "0.25"
    os.environ["DLD_POLICY"] = "0" if kill_switch else "1"
    # Two sustained intervals before a straggler flags: a pair planned
    # mid-interval legitimately reads 0 B/s once — judging on a single
    # interval would false-flag the very link the re-plan just chose.
    os.environ["DLD_STRAGGLER_N"] = "2"
    # Small fragments so the throttled link shows per-interval progress
    # to the straggler detector instead of one late burst.
    send_mod.FLOW_FRAGMENT_BYTES = 256 << 10
    cfg = CONFIGS["tiny"]
    v1 = serde.blobs_from_params(cfg, init_params(cfg, jax.random.key(0)))

    def blob_layer(data) -> LayerSrc:
        return LayerSrc(inmem_data=bytearray(data), data_size=len(data),
                        meta=LayerMeta(location=LayerLocation.INMEM,
                                       source_type=SourceType.MEM))

    replicas_ids = [1, 2]
    hot = 2                      # the slowserve-injected breacher
    bulk_dest, spare = 3, 4      # straggler-link dest; growable seat
    bulk_lid = 7000
    bulk = os.urandom(bulk_bytes)
    ids = [0, 1, 2, bulk_dest, spare]
    ts = {i: InmemTransport(str(i)) for i in ids + [9]}
    hot_spec = f"slowserve={hot_delay_ms:g}"
    _, hot_rules = rules_from_spec(hot_spec)
    ts[hot] = FaultyTransport(ts[hot], hot_rules, seed=7)
    slow_spec = f"slow={slow_rate}@{bulk_dest}"
    _, slow_rules = rules_from_spec(slow_spec)
    leader_t = FaultyTransport(ts[0], slow_rules, seed=7)
    seed_layers = {b: blob_layer(v1[b]) for b in v1}
    seed_layers[bulk_lid] = blob_layer(bulk)
    base = {r: {b: LayerMeta() for b in v1} for r in replicas_ids}
    leader = FlowRetransmitLeaderNode(
        Node(0, 0, leader_t), seed_layers, base,
        {i: bw for i in ids},
        expected_nodes={1, 2, bulk_dest, spare})
    rules = [
        {"Rule": "grow_on_serve_pressure", "P99Ms": p99_ms,
         "Sustain": 2, "CooldownS": 60.0},
        {"Rule": "quarantine_breacher", "P99Ms": p99_ms,
         "Breaches": 2, "CooldownS": 60.0},
        {"Rule": "replan_straggler", "FloorFrac": 0.1, "CooldownS": 5.0},
    ]
    leader.policy.arm(rules)
    replicas = {r: FlowRetransmitReceiverNode(Node(r, 0, ts[r]), {},
                                              boot_cfg=cfg)
                for r in replicas_ids}
    # Replica 1 also holds the bulk layer: the re-plan's alternative
    # source once the leader's own link to the dest is demoted.
    others = {
        bulk_dest: FlowRetransmitReceiverNode(Node(bulk_dest, 0,
                                                   ts[bulk_dest]), {}),
        spare: FlowRetransmitReceiverNode(Node(spare, 0, ts[spare]), {}),
    }
    requester = GenRequester(ts[9], my_id=9)
    prompt, max_new = [3, 5, 7], 8
    failures: list = []
    latencies: list = []         # (wall mono t, replica, ms)
    stop = threading.Event()

    def hammer(replica):
        # The request router honors the leader's serve-rotation mask —
        # exactly what the A/B split does in-process (docs/autonomy.md).
        while not stop.is_set():
            if replica in leader.serve_quarantined():
                time.sleep(0.1)
                continue
            t0 = time.monotonic()
            try:
                requester.request(replica, prompt, max_new,
                                  timeout=timeout)
                latencies.append((time.monotonic(), replica,
                                  (time.monotonic() - t0) * 1000.0))
            except Exception as e:  # noqa: BLE001 — any failure counts
                failures.append(repr(e))
            time.sleep(0.03)

    threads = [threading.Thread(target=hammer, args=(r,), daemon=True,
                                name=f"autonomy-hammer-{r}")
               for r in replicas_ids]
    try:
        for r in [*replicas.values(), *others.values()]:
            r.announce()
        leader.ready().get(timeout=timeout)
        leader.boot_ready().get(timeout=timeout)
        # Replica 1 gains the bulk layer out of band (an announce of
        # held state, like any member-held source) so the solver has a
        # second holder to route around the demoted leader link.
        replicas[1].layers[bulk_lid] = blob_layer(bulk)
        replicas[1].announce()
        for r in replicas_ids:  # warm the decode jits
            requester.request(r, prompt, max_new, timeout=timeout)
        for t in threads:
            t.start()
        t0 = time.monotonic()
        leader.submit_job("bulk", {bulk_dest: {bulk_lid: LayerMeta()}},
                          priority=1)
        deadline = time.monotonic() + timeout

        def audits(action, outcome=None):
            return [a for a in leader.policy.table()["Audit"]
                    if a.get("Action") == action
                    and (outcome is None or a.get("Outcome") == outcome)]

        if kill_switch:
            # The engine must SENSE both injections but HOLD: wait for
            # the held_manual audit trail instead of actions.
            while not (audits("quarantine", "held_manual")
                       and audits("replan", "held_manual")):
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"held_manual audits never appeared: "
                        f"{leader.policy.table()['Audit']}")
                time.sleep(0.05)
            time.sleep(0.6)  # more intervals: prove it KEEPS holding
            stop.set()
            for t in threads:
                t.join(timeout=timeout)
            counters = trace.counter_totals()
            tbl = leader.policy.table()
            fired = {a: counters.get(f"policy.action_{a}", 0)
                     for a in ("grow", "replan", "quarantine", "rehome")}
            return {
                "harness_hash": harness_hash(),
                "backend": "inmem",
                "mode": 3,
                "kill_switch": True,
                "env": "DLD_POLICY=0",
                "fault_specs": [hot_spec, slow_spec],
                "sensed_held_manual": {
                    "quarantine": len(audits("quarantine",
                                             "held_manual")),
                    "replan": len(audits("replan", "held_manual")),
                },
                "actions_fired": fired,
                "zero_actions": not any(fired.values()),
                "quarantined": sorted(leader.serve_quarantined()),
                "link_demotions": {f"{s}->{d}": b for (s, d), b
                                   in leader.policy.demotions().items()},
                "policy_jobs": sorted(
                    j for j in leader.jobs.table()
                    if str(j).startswith("policy-")),
                "engine_active": tbl["Active"],
                "request_failures": len(failures),
            }

        # ---- closed loop: wait for each autonomous action to land ----
        def wait_for(pred, what):
            while not pred():
                if time.monotonic() > deadline:
                    raise TimeoutError(f"autonomy never {what}: "
                                       f"{leader.policy.table()}")
                time.sleep(0.05)

        wait_for(lambda: hot in leader.serve_quarantined(),
                 "quarantined the breacher")
        t_quar = time.monotonic()
        wait_for(lambda: audits("replan"), "re-planned the straggler")

        def job_done(jid):
            job = leader.jobs.get(jid)
            return job is not None and job.state == "done"

        wait_for(lambda: job_done("bulk"), "finished the bulk transfer")
        bulk_wall = round(time.monotonic() - t0, 3)

        def grow_done():
            jids = [r.get("Job") for r in audits("grow") if r.get("Job")]
            return any(job_done(j) for j in jids)

        wait_for(grow_done, "grew the replica set")
        time.sleep(1.0)  # post-quarantine serving window for the SLO bar
        stop.set()
        for t in threads:
            t.join(timeout=timeout)
        # One more report round so every node's final span ring lands.
        leader.await_metrics(newer_than=time.monotonic() - 0.01,
                             timeout=5.0)
        counters = trace.counter_totals()
        tbl = leader.policy.table()
        table = leader.cluster_telemetry()
        rep = report_mod.build_from_leader(leader)
        policy_spans = sorted({e.get("span") for e in table["spans"]
                               if str(e.get("span", "")
                                      ).startswith("policy:")})
        grow_jobs = sorted({r.get("Job") for r in audits("grow")
                            if r.get("Job")})
        spare_layers = sorted(leader.status.get(spare) or {})
        straggler = [e for e in leader.health.events()
                     if e.get("kind") == "straggler_link"
                     and e.get("link") == f"0->{bulk_dest}"]
        post = sorted(ms for (t, r, ms) in latencies
                      if t > t_quar + 0.3 and r != hot)
        post_p99 = (round(post[min(len(post) - 1,
                                   int(0.99 * len(post)))], 1)
                    if post else None)
        return {
            "harness_hash": harness_hash(),
            "backend": "inmem",
            "mode": 3,
            "model": "tiny",
            "kill_switch": False,
            "rules": rules,
            "slo_p99_ms": p99_ms,
            "fault_specs": [hot_spec, slow_spec],
            "operator_verbs": 0,   # structural: no ctl message is sent
            "quarantined": sorted(leader.serve_quarantined()),
            "breacher_quarantined": hot in leader.serve_quarantined(),
            "wall_to_quarantine_s": round(t_quar - t0, 3),
            "straggler_flagged_live": bool(straggler),
            "straggler_frac": (straggler[0].get("frac")
                               if straggler else None),
            "link_demotions": {f"{s}->{d}": b for (s, d), b
                               in leader.policy.demotions().items()},
            "bulk_done_s": bulk_wall,
            "grow_jobs": grow_jobs,
            "spare_grown_layers": len(spare_layers),
            "spare_holds_model": all(
                b in spare_layers for b in v1),
            "post_quarantine_p99_ms": post_p99,
            "slo_reconverged": (post_p99 is not None
                                and post_p99 <= p99_ms),
            "request_failures": len(failures),
            "zero_failures": not failures,
            "requests_total": len(latencies),
            "actions_fired": {a: counters.get(f"policy.action_{a}", 0)
                              for a in ("grow", "replan",
                                        "quarantine", "rehome")},
            "audit_tail": tbl["Audit"][-8:],
            "policy_spans": policy_spans,
            "span_attributed": bool(policy_spans),
            "run_report": rep.get("provenance"),
        }
    finally:
        stop.set()
        requester.close()
        send_mod.FLOW_FRAGMENT_BYTES = prior_frag
        if prior_metrics is None:
            os.environ.pop("DLD_METRICS_INTERVAL_S", None)
        else:
            os.environ["DLD_METRICS_INTERVAL_S"] = prior_metrics
        if prior_policy is None:
            os.environ.pop("DLD_POLICY", None)
        else:
            os.environ["DLD_POLICY"] = prior_policy
        if prior_sustain is None:
            os.environ.pop("DLD_STRAGGLER_N", None)
        else:
            os.environ["DLD_STRAGGLER_N"] = prior_sustain
        _service_teardown(
            leader, [*replicas.values(), *others.values()], ts)
        leader_t.close()


def _autonomy_md(lines, results) -> None:
    au = results.get("autonomy")
    if not au or not au.get("closed_loop"):
        return
    cl, ks = au["closed_loop"], au.get("kill_switch") or {}
    bars = {
        "breaching replica quarantined (serve-rotation mask)":
            cl["breacher_quarantined"],
        "straggler link flagged live and re-planned around":
            cl["straggler_flagged_live"] and bool(cl["link_demotions"]),
        "replica set grown onto the spare (join+refill)":
            cl["spare_holds_model"],
        "fleet back inside SLO after quarantine":
            cl["slo_reconverged"],
        "zero operator verbs": cl["operator_verbs"] == 0,
        "zero dropped requests": cl["zero_failures"],
        "every action span-attributed in RUN_REPORT":
            cl["span_attributed"],
    }
    if ks:
        bars["DLD_POLICY=0 sibling: sensed but ZERO actions"] = (
            ks.get("zero_actions") and not ks.get("quarantined")
            and not ks.get("link_demotions")
            and not ks.get("policy_jobs"))
    lines += [
        "## Closed-loop fleet autonomy (docs/autonomy.md)",
        "",
        f"A serving fleet ({cl['backend']} backend, mode {cl['mode']}) "
        f"takes two concurrent injections — `{cl['fault_specs'][0]}` on "
        f"a hot replica and `{cl['fault_specs'][1]}` under a bulk "
        f"transfer — and the leader's policy engine converges it back "
        f"inside the p99 <= {cl['slo_p99_ms']:g}ms SLO with zero "
        f"operator verbs: quarantine after "
        f"{cl['wall_to_quarantine_s']}s, link demoted to "
        f"{cl['link_demotions']}, bulk done in {cl['bulk_done_s']}s, "
        f"post-quarantine p99 {cl['post_quarantine_p99_ms']}ms.",
        "",
        "| bar | met |",
        "|---|---|",
    ]
    for name, met in bars.items():
        lines.append(f"| {name} | {'MET' if met else 'NOT MET'} |")
    lines += [
        "",
        f"Actions fired: {cl['actions_fired']}; policy spans "
        f"{cl['policy_spans']}; {cl['requests_total']} requests served, "
        f"{cl['request_failures']} failed.  "
        + (f"Kill-switch sibling ({ks.get('env')}): held_manual audits "
           f"{ks.get('sensed_held_manual')}, actions fired "
           f"{ks.get('actions_fired')}.  " if ks else "")
        + f"Run report `{cl.get('run_report')}`.",
        "",
    ]


def _swap_md(lines, results) -> None:
    sw = results.get("live_swap")
    if not sw:
        return
    lines += [
        "## Zero-downtime weight swap (docs/swap.md)",
        "",
        f"A tiny-model replica serves generation traffic continuously "
        f"({sw['backend']} backend, mode {sw['mode']}) while a "
        "`kind=\"swap\"` job disseminates v2 under version-tagged ids "
        "and the epoch-fenced `SwapCommitMsg` flips the serving params "
        "atomically between requests — "
        f"**{sw['request_failures']} failed requests** "
        f"(bar: zero → {'MET' if sw['zero_failures'] else 'NOT MET'}), "
        f"v2 digests verified: {sw['v2_digests_verified']}, swap wall "
        f"{sw['swap_wall_s']}s:",
        "",
        "| phase | requests | tokens/s | p50 | p99 |",
        "|---|---|---|---|---|",
    ]
    for k in ("before", "during", "after"):
        ph = sw[k]
        if not ph.get("requests"):
            lines.append(f"| {k} | 0 | — | — | — |")
            continue
        lines.append(
            f"| {k} | {ph['requests']} | {ph['tokens_per_s']} | "
            f"{ph['p50_ms']}ms | {ph['p99_ms']}ms |")
    dip = sw.get("tokens_per_s_dip_frac")
    lines += [
        "",
        (f"tokens/s dip during the swap: {dip:+.1%} vs before "
         if dip is not None else "tokens/s dip: n/a ")
        + f"(served version after: `{sw['served_version_after']}`; "
        f"run report `{sw.get('run_report')}`).",
        "",
    ]


def run_telemetry_overhead(scale: int = 64 << 20, trials: int = 3,
                           scenario: str = "bench_8node_llama8b.json",
                           mode: int = 0,
                           timeout: float = 600.0) -> dict:
    """The always-on telemetry plane's measured cost (docs/
    observability.md acceptance): the same BASELINE scenario run with
    the flight recorder + periodic reports ON (default) and OFF
    (``DLD_TELEMETRY=0``), recorded as a TTD delta.  Medians over
    ``trials``; the target is ≤2% — read with this container's CFS
    drift error bar in mind (the markdown says so)."""
    out: dict = {"scenario": f"{os.path.splitext(scenario)[0]}"
                             f"@{scale >> 20}MiB",
                 "mode": mode, "trials": trials}
    with tempfile.TemporaryDirectory() as td:
        local = os.path.join(td, scenario)
        _localize_config(os.path.join(CONF_DIR, scenario), local,
                         scale_to=scale)
        for label, env_val in (("on", "1"), ("off", "0")):
            env = dict(os.environ)
            env["DLD_TELEMETRY"] = env_val
            ts = [run_once(local, mode, timeout, env=env)
                  for _ in range(trials)]
            out[label] = {"ttd_s": round(statistics.median(ts), 4),
                          "all": [round(t, 4) for t in ts]}
            print(f"telemetry {label}: TTD {out[label]['ttd_s']}s",
                  file=sys.stderr, flush=True)
    out["delta_frac"] = round(
        (out["on"]["ttd_s"] - out["off"]["ttd_s"])
        / max(out["off"]["ttd_s"], 1e-9), 4)
    out["meets_2pct"] = out["delta_frac"] <= 0.02
    return out


def run_attribution(layer_bytes: int = 8 << 20, n_fast: int = 2,
                    bw: int = 25_000_000, slow_rate: int = 2 << 20,
                    timeout: float = 300.0) -> dict:
    """The causal-observability acceptance row (docs/observability.md):
    a mode-3 multi-node run — leader 0 seeding ``n_fast`` fast dests
    plus one dest behind an injected ``slow=`` fault link — whose
    achieved TTD must be EXPLAINED: the critical-path span chain's
    window reconciles with the measured TTD within ±10%, the
    predicted-vs-achieved gap decomposes per phase with no unattributed
    residual above 15%, and the straggler link appears both in the LIVE
    health events (onset stamped mid-run) and in the RUN_REPORT
    critical path's per-link wire split."""
    from ..core.types import LayerMeta
    from ..transport.faults import FaultyTransport, rules_from_spec
    from ..utils import critical_path as cp
    from ..utils import telemetry
    from ..utils.provenance import harness_hash
    from . import report as report_mod
    from ..runtime import (
        FlowRetransmitLeaderNode,
        FlowRetransmitReceiverNode,
        Node,
    )
    from ..runtime import send as send_mod
    from ..transport import TcpTransport

    telemetry.reset_run()
    slow_dest = n_fast + 1
    ids = list(range(n_fast + 2))
    # Small flow fragments so the throttled link trickles per-interval
    # progress (the straggler detector judges interval deltas) instead
    # of landing one late burst.
    prior_frag = send_mod.FLOW_FRAGMENT_BYTES
    prior_interval = os.environ.get("DLD_METRICS_INTERVAL_S")
    send_mod.FLOW_FRAGMENT_BYTES = 256 << 10
    os.environ["DLD_METRICS_INTERVAL_S"] = "0.25"
    block = os.urandom(1 << 20)

    def mem_layer(lid: int):
        from ..core.types import (
            LayerLocation,
            LayerSrc,
            SourceType,
        )

        reps = (layer_bytes + len(block) - 1) // len(block)
        data = bytearray((block * reps)[:layer_bytes])
        data[:8] = lid.to_bytes(8, "big")
        return LayerSrc(inmem_data=data, data_size=layer_bytes,
                        meta=LayerMeta(location=LayerLocation.INMEM,
                                       source_type=SourceType.MEM))

    ts = {i: TcpTransport("127.0.0.1:0") for i in ids}
    reg = {i: t.get_address() for i, t in ts.items()}
    for t in ts.values():
        t.addr_registry.update(reg)
    _, rules = rules_from_spec(f"slow={slow_rate}@{slow_dest}")
    leader_t = FaultyTransport(ts[0], rules, seed=11)
    assignment = {d: {lid: LayerMeta() for lid in range(2)}
                  for d in range(1, n_fast + 1)}
    assignment[slow_dest] = {0: LayerMeta()}
    leader = FlowRetransmitLeaderNode(
        Node(0, 0, leader_t), {lid: mem_layer(lid) for lid in range(2)},
        assignment, {i: bw for i in ids}, expected_nodes=set(ids[1:]))
    dests = [FlowRetransmitReceiverNode(Node(i, 0, ts[i]), {})
             for i in ids[1:]]
    try:
        t0 = time.monotonic()
        for r in dests:
            r.announce()
        leader.ready().get(timeout=timeout)
        ttd = round(time.monotonic() - t0, 4)
        predicted = (leader.predicted_ttd_ms or 0) / 1000.0
        # One more report round so every dest's final span ring lands.
        leader.await_metrics(newer_than=time.monotonic() - 0.01,
                             timeout=5.0)
        table = leader.cluster_telemetry()
        res = cp.analyze(table["spans"], ttd_s=ttd,
                         predicted_s=round(predicted, 4))
        rep = report_mod.build_from_leader(leader, ttd_s=ttd)
        health_events = leader.health.events()
        straggler = [e for e in health_events
                     if e.get("kind") == "straggler_link"
                     and e.get("link") == f"0->{slow_dest}"]
        slow_on_chain = any(c.get("dest") == slow_dest
                            for c in res["chain"])
        slow_in_links = f"0->{slow_dest}" in res["per_link_wire_s"]
        coverage = res.get("coverage_frac") or 0.0
        unattrib = res.get("unattributed_frac")
        return {
            "harness_hash": harness_hash(),
            "backend": "tcp-loopback",
            "mode": 3,
            "layer_bytes": layer_bytes,
            "n_dests": n_fast + 1,
            "modeled_bw_bps": bw,
            "slow_link": {"link": f"0->{slow_dest}",
                          "injected_rate_bps": slow_rate},
            "ttd_s": ttd,
            "predicted_s": round(predicted, 4),
            "critical_path": {
                "window_s": res["window_s"],
                "coverage_frac": coverage,
                "attributed_s": res["attributed_s"],
                "idle_s": res["idle_s"],
                "unattributed_frac": unattrib,
                "phase_totals_s": res["phase_totals_s"],
                "gap_attribution_s": res.get("gap_attribution_s"),
                "per_link_wire_s": res["per_link_wire_s"],
                "chain_spans": [c["span"] for c in res["chain"]],
            },
            "reconciles_10pct": bool(abs(coverage - 1.0) <= 0.10),
            "unattributed_le_15pct": bool(
                unattrib is not None and unattrib <= 0.15),
            "straggler_flagged_live": bool(straggler),
            "straggler_onset_t_ms": (straggler[0]["t_ms"]
                                     if straggler else None),
            "straggler_on_critical_path": bool(slow_on_chain
                                               and slow_in_links),
            "health_events": health_events,
            # Dual-backend span correlation + takeover survival are
            # tier-1-tested; the row names the tests it leans on.
            "span_correlation_tests":
                "tests/test_observability.py::"
                "test_span_chain_full_lifecycle_e2e[inmem|tcp]",
            "takeover_tests":
                "tests/test_observability.py::"
                "test_adopted_leader_still_yields_complete_report + "
                "test_health_events_and_spans_ride_shadow_replication",
            "run_report": rep.get("provenance"),
        }
    finally:
        send_mod.FLOW_FRAGMENT_BYTES = prior_frag
        if prior_interval is None:
            os.environ.pop("DLD_METRICS_INTERVAL_S", None)
        else:
            os.environ["DLD_METRICS_INTERVAL_S"] = prior_interval
        leader.close()
        for r in dests:
            r.close()
        for t in ts.values():
            t.close()
        leader_t.close()


def _attribution_md(lines, results) -> None:
    at = results.get("attribution")
    if not at:
        return
    cp_res = at["critical_path"]
    phases = ", ".join(f"{k}={v}s"
                       for k, v in sorted(cp_res["phase_totals_s"].items()))
    gap = ", ".join(f"{k}={v}s"
                    for k, v in sorted(
                        (cp_res.get("gap_attribution_s") or {}).items()))
    lines += [
        "## Explainable delivery: critical-path TTD attribution "
        "(docs/observability.md)",
        "",
        f"Mode-3 over loopback TCP: leader 0 seeds {at['n_dests']} "
        f"dests ({at['layer_bytes'] >> 20} MiB layers, modeled "
        f"{at['modeled_bw_bps'] / 1e6:.0f} MB/s links); link "
        f"`{at['slow_link']['link']}` is injected "
        f"`slow={at['slow_link']['injected_rate_bps']}` "
        f"({at['slow_link']['injected_rate_bps'] >> 20} MiB/s) — the "
        "run's whole question is whether the observability plane "
        "EXPLAINS the resulting TTD without being told about the "
        "fault.",
        "",
        "| bar | value | met |",
        "|---|---|---|",
        f"| chain window vs achieved TTD (±10%) | "
        f"{cp_res['window_s']}s vs {at['ttd_s']}s "
        f"(coverage {cp_res['coverage_frac']}) | "
        f"{'yes' if at['reconciles_10pct'] else 'NO'} |",
        f"| unattributed residual ≤15% | "
        f"{cp_res['unattributed_frac']} | "
        f"{'yes' if at['unattributed_le_15pct'] else 'NO'} |",
        f"| straggler flagged LIVE (health event, onset mid-run) | "
        f"onset t={at['straggler_onset_t_ms']}ms | "
        f"{'yes' if at['straggler_flagged_live'] else 'NO'} |",
        f"| straggler on the RUN_REPORT critical path | chain spans "
        f"{cp_res['chain_spans']} | "
        f"{'yes' if at['straggler_on_critical_path'] else 'NO'} |",
        "",
        f"Predicted {at['predicted_s']}s vs achieved {at['ttd_s']}s "
        f"— phase totals on the chain: {phases}.  Gap decomposition: "
        f"{gap}.  Per-link wire seconds: "
        + ", ".join(f"{k}: {v}s"
                    for k, v in sorted(
                        cp_res["per_link_wire_s"].items()))
        + " — the injected link carries the excess, as it must.",
        "",
        f"Dual-backend span correlation: {at['span_correlation_tests']} "
        f"(tier-1).  Leader-kill keeping span/health state through "
        f"takeover: {at['takeover_tests']} (tier-1).  RUN_REPORT "
        f"provenance `{at.get('run_report')}` (harness "
        f"`{at.get('harness_hash')}`).",
        "",
    ]


def run_span_overhead(scale: int = 64 << 20, trials: int = 3,
                      scenario: str = "bench_8node_llama8b.json",
                      mode: int = 0,
                      timeout: float = 600.0) -> dict:
    """The span recorder's measured cost (docs/observability.md): the
    same BASELINE scenario with span recording ON (default) vs OFF
    (``DLD_SPANS=0``) — the PR-6 telemetry-overhead A/B, but with the
    arms INTERLEAVED (on, off, on, off, …): this container's CFS state
    drifts 30-50% across minutes (measured: a sequential-arm run read
    +45% that an off/on/off interleave immediately contradicted), so
    sequential arms measure the drift, not the knob; adjacent pairs
    largely cancel it.  Medians per arm + per-pair deltas recorded."""
    import subprocess as _sp

    out: dict = {"scenario": f"{os.path.splitext(scenario)[0]}"
                             f"@{scale >> 20}MiB",
                 "mode": mode, "trials": trials, "retries": 0,
                 "interleaved": True}
    with tempfile.TemporaryDirectory() as td:
        local = os.path.join(td, scenario)
        _localize_config(os.path.join(CONF_DIR, scenario), local,
                         scale_to=scale)

        def one_trial(env) -> float:
            # This container sporadically wedges ONE seat of an 8-node
            # run in its post-run ack-requeue loop (pre-existing;
            # reproduced on the unmodified tree) — a hung HARNESS trial
            # is not a measurement, so it retries bounded and counted,
            # never silently.
            for attempt in range(3):
                try:
                    return run_once(local, mode, timeout, env=env)
                except _sp.TimeoutExpired:
                    out["retries"] += 1
                    print("trial wedged in the known post-run requeue "
                          "loop; retrying", file=sys.stderr, flush=True)
            raise TimeoutError("span-overhead trial wedged 3x")

        arms: dict = {"on": [], "off": []}
        for k in range(trials):
            for label, env_val in (("on", "1"), ("off", "0")):
                env = dict(os.environ)
                env["DLD_SPANS"] = env_val
                t = one_trial(env)
                arms[label].append(t)
                print(f"spans {label} trial {k}: TTD {t:.3f}s",
                      file=sys.stderr, flush=True)
        for label, ts in arms.items():
            out[label] = {"ttd_s": round(statistics.median(ts), 4),
                          "all": [round(t, 4) for t in ts]}
    out["delta_frac"] = round(
        (out["on"]["ttd_s"] - out["off"]["ttd_s"])
        / max(out["off"]["ttd_s"], 1e-9), 4)
    # Per-pair deltas: each pair is two adjacent same-minute runs —
    # the drift-cancelling view the markdown reports next to the
    # arm medians.
    out["pair_deltas"] = [
        round((a - b) / max(b, 1e-9), 4)
        for a, b in zip(arms["on"], arms["off"])]
    return out


def _span_overhead_md(lines, results) -> None:
    ov = results.get("span_overhead")
    if not ov:
        return
    spread_on = ov["on"]["all"]
    spread = round((max(spread_on) - min(spread_on))
                   / max(min(spread_on), 1e-9), 3)
    pairs = ov.get("pair_deltas") or []
    pair_str = (", ".join(f"{p:+.1%}" for p in pairs)
                if pairs else "—")
    lines += [
        "## Span-recording overhead (docs/observability.md)",
        "",
        f"The `{ov['scenario']}` BASELINE scenario (mode {ov['mode']}, "
        f"{ov['trials']} trial pairs, arms INTERLEAVED on/off/on/off — "
        "this container's CFS state drifts 30-50% across minutes, so "
        "sequential arms measure the drift, not the knob) with "
        "pair-lifecycle span recording ON vs OFF (`DLD_SPANS=0`).  The "
        "hot path is one bounded-deque append under the registry lock "
        "per LIFECYCLE EDGE (a handful per delivered layer — not per "
        "frame), so the expected cost is below this host's noise "
        "floor:",
        "",
        "| spans | TTD (median) | trials | arm delta |",
        "|---|---|---|---|",
        f"| on | {ov['on']['ttd_s']}s | {ov['on']['all']} | "
        f"{ov['delta_frac']:+.1%} |",
        f"| off (`DLD_SPANS=0`) | {ov['off']['ttd_s']}s | "
        f"{ov['off']['all']} | — |",
        "",
        f"Per-pair (adjacent-run) deltas: {pair_str}.  "
        f"(on-arm trial spread: {spread:.1%} of the fastest trial"
        + (f"; {ov['retries']} wedged trial(s) retried — the known "
           "pre-existing post-run requeue flake, reproduced on the "
           "unmodified tree" if ov.get("retries") else "")
        + ".  A delta inside the spread — either sign — is "
        "indistinguishable from zero on this 2-core CFS-throttled "
        "container; re-measure on quiet multi-core hardware for a "
        "tight number.)",
        "",
    ]


def _telemetry_overhead_md(lines, results) -> None:
    ov = results.get("telemetry_overhead")
    if not ov:
        return
    spread_on = ov["on"]["all"]
    spread = round((max(spread_on) - min(spread_on))
                   / max(min(spread_on), 1e-9), 3)
    lines += [
        "## Always-on telemetry overhead (docs/observability.md)",
        "",
        f"The `{ov['scenario']}` BASELINE scenario (mode {ov['mode']}, "
        f"median of {ov['trials']}) with the per-link flight recorder + "
        "periodic MetricsReportMsg shipping ON vs OFF "
        "(`DLD_TELEMETRY=0`).  The instrumented hot path is one dict "
        "update under a lock per MiB-scale frame; the ≤2% acceptance "
        "bar is judged on the TTD delta below, read against this "
        "container's run-to-run CFS drift (the ON-arm trial spread is "
        "the error bar):",
        "",
        "| telemetry | TTD | trials | delta | ≤2%? |",
        "|---|---|---|---|---|",
        f"| on | {ov['on']['ttd_s']}s | {ov['on']['all']} | "
        f"{ov['delta_frac']:+.1%} | "
        f"{'yes' if ov['meets_2pct'] else 'NO'} |",
        f"| off (`DLD_TELEMETRY=0`) | {ov['off']['ttd_s']}s | "
        f"{ov['off']['all']} | — | — |",
        "",
        f"(on-arm trial spread: {spread:.1%} of the fastest trial.)",
        "",
    ]
    if ov["delta_frac"] < -0.02:
        lines += [
            "A negative delta this large is NOT telemetry making the "
            "run faster — it is the container's CFS burst-budget drift "
            "dwarfing the effect under measurement (the per-arm trial "
            "spreads above are of the same order).  The honest "
            "conclusion is: the overhead is indistinguishable from "
            "zero at this host's noise floor, which satisfies the ≤2% "
            "bar; re-measure on quiet multi-core hardware for a tight "
            "number.",
            "",
        ]


def _service_md(lines, results) -> None:
    sj = results.get("service_jobs")
    dr = results.get("delta_rollout")
    if not sj and not dr:
        return
    lines.append("## Dissemination service: multi-job scheduling + "
                 "content-addressed delta rollouts")
    lines.append("")
    if sj:
        lines.append(
            "Two overlapping jobs, different priorities, one shared "
            f"source NIC modeled at {sj['modeled_bw_bps'] / 1e6:.0f} "
            "MB/s (docs/service.md): the joint solver gives the high "
            "tier the full link and the low tier the 1/16 preemption-"
            "floor residue; the per-job link rows and completion walls "
            "are the split actually achieved.")
        lines.append("")
        lines.append("| job | priority | intended tier budget | "
                     "measured completion | delivered (per-job link "
                     "rows) | byte-exact |")
        lines.append("|---|---|---|---|---|---|")
        for jid in sorted(sj["jobs"]):
            prio = sj["jobs"][jid]["priority"]
            t_int = sj["intended_tier_ms"].get(jid)
            t_meas = sj["measured_done_s"].get(jid)
            delivered = sum(
                row.get("delivered_bytes", 0)
                for key, row in sj["per_job_links"].items()
                if key.endswith(f"#{jid}"))
            lines.append(
                f"| `{jid}` | {prio} | "
                f"{t_int / 1000.0 if t_int else '?'}s | {t_meas}s | "
                f"{delivered >> 20} MiB | {sj['byte_exact']} |")
        lines.append("")
        lines.append(f"RUN_REPORT provenance `{sj.get('run_report')}` "
                     f"(harness `{sj.get('harness_hash')}`).")
        lines.append("")
    if dr:
        frac = dr["changed_fraction"]
        lines.append(
            f"Delta rollout: v2 re-keys {dr['n_layers']} × "
            f"{dr['layer_bytes'] >> 20} MiB layers under new ids with "
            f"{dr['changed_layers']} small-perturbation sibling(s) "
            f"(~1/{dr.get('perturb_stride', '?')} of positions "
            f"flipped; changed fraction {frac}).  The content store "
            "resolves unchanged layers locally (zero wire bytes), and "
            "the changed layers ship as encoded `delta:<v1-digest>` "
            "streams (docs/codec.md) the dest reconstructs and "
            "verifies against the stamped full-form digest — so the "
            "wire bound tightens from changed-fraction × model bytes "
            "to < 25% of even the CHANGED layers' raw size.")
        lines.append("")
        lines.append("| push | wall | wire bytes | bound | met |")
        lines.append("|---|---|---|---|---|")
        lines.append(f"| v1 full | {dr['v1_full_push_s']}s | "
                     f"{dr['v1_wire_bytes'] >> 20} MiB | — | — |")
        lines.append(
            f"| v2 delta | {dr['v2_delta_push_s']}s | "
            f"{dr['v2_wire_bytes'] / 1048576:.2f} MiB | ≤ "
            f"{dr['v2_bound_bytes'] >> 20} MiB raw / ≤ "
            f"{dr.get('delta_bound_bytes', 0) / 1048576:.1f} MiB delta "
            f"| {dr['bound_met']} / "
            f"{dr.get('delta_bound_met', '—')} |")
        lines.append("")
        lines.append(
            f"{dr['resolved_layers']} layers "
            f"({dr['resolved_bytes'] >> 20} MiB) resolved from the "
            f"dest's content store with zero wire bytes; the leader's "
            f"planner skipped {dr['leader_skipped']} content-equal "
            f"pair(s); {dr.get('delta_pairs_chosen', 0)} pair(s) "
            f"shipped as deltas ({dr.get('delta_wire_bytes', 0)} wire "
            f"bytes reconstructing {dr.get('delta_raw_bytes', 0)} raw "
            f"bytes), XOR+DLE1 encode cost "
            f"{dr.get('encode_ms', 0)} ms thread-time (a ceiling on "
            "this CFS-throttled container, cached once per layer).  "
            f"Digest-exact: {dr.get('digest_exact', False)}.  "
            f"RUN_REPORT provenance `{dr.get('run_report')}` "
            f"(harness `{dr.get('harness_hash')}`).")
        lines.append("")
    dw = results.get("delta_wave")
    if dw:
        grp = dw["group"]
        lines.append(
            f"Sharded delta rollout wave (docs/rollout.md × "
            f"docs/hierarchy.md × docs/codec.md): root 0 seeds "
            f"{dw['n_layers']} × {dw['layer_bytes'] >> 20} MiB v1 "
            f"layers to group {{sub-leader {grp['leader']}, members "
            f"{grp['members']}}} through the group plan, then rolls "
            f"{dw['changed_layers']} perturbed v2 layer(s) "
            f"(version `{dw['version']}`) in "
            f"{len(dw['waves'])} waves — every v2 pair an encoded "
            "delta stream, wave 2 re-encoded and fanned out by the "
            "SUB-LEADER (striped byte ranges of one delta blob through "
            "the group chain), not the root.")
        lines.append("")
        lines.append("| wave | dests | wall | root wire bytes |")
        lines.append("|---|---|---|---|")
        for i, w in enumerate(dw["waves"]):
            lines.append(
                f"| {i + 1} | {w['dests']} | {w['wall_s']}s | "
                f"{w['root_wire_bytes']} |")
        lines.append("")
        lines.append(
            f"v1 group push: {dw['v1_group_push_s']}s, "
            f"{dw['v1_root_wire_bytes'] >> 20} MiB over the root NIC.  "
            f"v2 waves: {dw['wave_wire_bytes']} root wire bytes total "
            f"vs {dw['changed_raw_bytes'] >> 20} MiB changed-raw "
            f"(< 25% bound met: {dw['delta_bound_met']}); "
            f"{dw['delta_pairs_chosen']} delta pair(s) chosen, "
            f"{dw['delta_reconstructed']} reconstruction(s), group-"
            f"internal wire {dw['group_wire_bytes']} bytes.  Byte-"
            f"exact {dw['byte_exact']}, digest-exact "
            f"{dw['digest_exact']}, version tags preserved.")
        lines.append("")


def _failover_md(lines, results) -> None:
    fo = results.get("failover")
    if not fo:
        return
    lines.append("## Failover: time-to-recover (leader killed mid-run)")
    lines.append("")
    lines.append(
        "Control-plane HA (docs/failover.md) at physical-row sizes: a "
        "clean HA-armed mode-3 run vs an identical run whose leader is "
        f"killed at ~{fo['killed'].get('kill_at_s', '?')}s.  TTR = kill "
        "→ delivery resumed to byte-exact completion (includes the "
        f"standby's ~{fo['standby_expiry_s']}s lease-expiry wait — the "
        "detection time IS part of recovery); overhead = killed total "
        "− clean total.")
    lines.append("")
    lines.append("| run | layers | total | kill at | TTR | "
                 "detect+promote | byte-exact |")
    lines.append("|---|---|---|---|---|---|---|")
    size = f"{fo['n_workers']}× {fo['layer_bytes'] >> 20} MiB"
    c, k = fo["clean"], fo["killed"]
    lines.append(f"| clean | {size} | {c['total_s']}s | — | — | — | "
                 f"{c['byte_exact']} |")
    lines.append(
        f"| leader killed | {size} | {k['total_s']}s | "
        f"{k['kill_at_s']}s | {k['ttr_s']}s | {k['takeover_s']}s | "
        f"{k['byte_exact']} |")
    lines.append("")
    if fo["killed"].get("run_report"):
        lines.append(
            "Event counts for both rows come from each run's own "
            "telemetry snapshot; the killed run's RUN_REPORT was built "
            "from the ADOPTED leader (provenance "
            f"`{fo['killed']['run_report']}`, "
            f"{fo['killed'].get('report_links', '?')} link rows — the "
            "replicated cluster picture surviving the takeover is part "
            "of what this row evidences).")
        lines.append("")
    lines.append(
        f"Failover overhead vs clean: **{fo['overhead_s']}s** "
        f"(lease interval {fo['lease_interval_s']}s, standby expiry "
        f"{fo['standby_expiry_s']}s; `harness_hash` "
        f"{fo['harness_hash']}).  `detect+promote` spans kill → "
        "promoted leader live, dominated by the DELIBERATE lease-expiry "
        "wait (the adoption itself — shadow import + epoch bump + "
        "re-plan dispatch — logs as takeover_ms, tens of ms); the rest "
        "of TTR is re-sending what the dead leader had not delivered "
        "(the promoted leader re-drives from the shadow immediately; "
        "worker re-announces then re-ack what already landed, and "
        "duplicate sends are absorbed by interval reassembly).")
    lines.append("")


def _fanout_md(lines, results) -> None:
    fo = results.get("fanout")
    if not fo:
        return
    lines += [
        "## Fleet fan-out: flat vs hierarchical control "
        "(docs/hierarchy.md)",
        "",
        f"The same inmem BASELINE goal — every dest wants "
        f"{fo['n_layers']} × {fo['layer_bytes'] >> 10} KiB layers from "
        "the one seeding root — run flat (mode 3) and under "
        "sqrt-sized sub-leader groups, at each fleet size.  "
        "`root handled` counts control messages the ROOT's message "
        "loop dispatched (`ctrl.handled.<root>`); every run is "
        "byte-exact at every dest.",
        "",
        "| nodes | control | groups | root solve (ms) | root handled "
        "msgs | sub egress/sub | relayed | links exact | TTD |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for r in fo["rows"]:
        if r.get("groups"):
            egress = f"{r.get('egress_bytes_per_subleader', 0) >> 10} KiB"
            relay = f"{r.get('relay_bytes', 0) >> 10} KiB"
        else:
            egress = relay = "—"
        lines.append(
            f"| {r['n_nodes']} | {r['control']} | {r['groups'] or '—'} "
            f"| {r['solve_ms']} | {r['root_handled_msgs']} | "
            f"{egress} | {relay} | "
            f"{'yes' if r.get('link_reconcile_exact') else 'NO'} | "
            f"{r['ttd_s']}s |")
    mg, sg = fo["root_msgs_growth"], fo["solve_growth"]
    lines += [
        "",
        f"Growth {fo['rows'][0]['n_nodes']}→"
        f"{fo['rows'][-1]['n_nodes']} nodes (×{fo['node_growth']:.0f} "
        f"fleet): root-handled messages ×{mg['flat']} flat vs "
        f"×{mg['hierarchical']} hierarchical; solve wall "
        f"×{sg['flat']} flat vs ×{sg['hierarchical']} hierarchical.  "
        f"Sub-linear bars: messages "
        f"**{'MET' if fo['msgs_sublinear'] else 'NOT MET'}**, solve "
        f"**{'MET' if fo['solve_sublinear'] else 'NOT MET'}**.",
        "",
    ]
    ce = fo.get("chain_egress")
    if ce:
        lines += [
            f"Member-to-member chains (docs/hierarchy.md): at "
            f"{fo['rows'][-1]['n_nodes']} nodes each sub-leader "
            f"egressed {ce['egress_bytes_per_subleader'] >> 10} KiB "
            f"(~one model copy) instead of the star's one-copy-per-"
            f"member — {ce['subleader_egress_bytes'] >> 10} KiB total "
            f"vs {ce['star_equivalent_bytes'] >> 10} KiB star-"
            f"equivalent, a {ce['egress_savings_frac']:.0%} egress "
            f"saving; of each R-member group's fan, (R−1)/R rides "
            f"member-to-member relay links "
            f"({ce['relay_bytes'] >> 10} KiB relayed).  Link tables "
            f"reconcile byte-exactly across every hop: "
            f"**{'yes' if fo.get('links_reconcile_exact') else 'NO'}**.",
            "",
        ]
    lines += [
        "Honest framing: TTD at these sizes is dominated by the "
        "2-core container's scheduler, not the wire; the row's bars "
        "are the CONTROL-plane costs (solve wall, root-handled "
        "messages) and the egress/relay BYTE counts, which are "
        "load-independent — every seat shares one CFS quota, so "
        "relaying off the sub's NIC shows up here as bytes moved off "
        "the bottleneck link, not as wall-clock TTD wins.",
        "",
    ]


def _elasticity_md(lines, results) -> None:
    el = results.get("elasticity")
    if not el:
        return
    lines += [
        "## Elastic membership: join mid-run, refill from the swarm "
        "(docs/membership.md)",
        "",
        f"The base goal ({el['n_base']} configured dests × "
        f"{el['n_layers']} × {el['layer_bytes'] >> 10} KiB layers from "
        "ONE origin seeder) disseminates; then N UNCONFIGURED nodes "
        "JOIN the running cluster concurrently.  Each joiner is "
        "admitted as a dest immediately (a `kind=\"join\"` refill job) "
        "and the refill policy avoids the ORIGIN seeder whenever "
        "current peer holders can serve — admission cost must not "
        "scale with origin bandwidth.  Every joiner ends byte-exact "
        "(digest-verified before acking, default integrity plane).",
        "",
        "| joiners | origin refill bytes | peer refill bytes | peer "
        "fraction | coverage | RUN_REPORT |",
        "|---|---|---|---|---|---|",
    ]
    for r in el["rows"]:
        lines.append(
            f"| {r['n_joiners']} | {r['origin_bytes']} | "
            f"{r['peer_bytes']} | {r['peer_fraction']} | "
            f"{r['coverage_s']}s | {str(r.get('run_report'))[:12]} |")
    lines += [
        "",
        f"Joiner growth ×{el['joiner_growth']:.0f} → origin-bytes "
        f"growth ×{el['origin_growth']}.  Bars: peers-majority "
        f"**{'MET' if el['peers_majority'] else 'NOT MET'}**, "
        f"origin-bytes sub-linear "
        f"**{'MET' if el['origin_sublinear'] else 'NOT MET'}**.",
        "",
        "Honest framing: joiners here arrive AFTER the base goal "
        "covered the configured dests (the service-era steady state), "
        "so peers hold every layer and the origin serves zero refill "
        "bytes; a joiner arriving before any peer holds a layer is "
        "served by the origin — the avoid set is advisory and "
        "deliverability always wins (docs/membership.md).",
        "",
    ]


def _sharded_md(lines, results) -> None:
    sd = results.get("sharded_delivery")
    if not sd:
        return
    lb, nl = sd["layer_bytes"], sd["n_layers"]
    full, shard = sd["full"], sd["sharded"]
    lo, hi = sd["shard_bytes_per_dest_bound"]
    lines += [
        "## Sharded delivery: disseminate into the destination sharding "
        "(docs/sharding.md)",
        "",
        f"The same multi-dest goal — {sd['n_dests']} dests × {nl} × "
        f"{lb >> 20} MiB layers from one leader over "
        f"{sd['backend']} (mode {sd['mode']}) — run with FULL-layer "
        f"targets vs `{sd['shard_fraction']}@k` shard targets.  Wire "
        "bytes per dest must land within 10% of fraction × layer bytes "
        f"× layers (bound [{lo >> 20}, {hi >> 20}] MiB); the dests' "
        "shards must gather on-mesh into layers byte-exact against the "
        "stamped full-layer digests.",
        "",
        "| targets | TTD | predicted | wire bytes/dest | gathered "
        "byte-exact |",
        "|---|---|---|---|---|",
    ]

    def _per_dest(rec):
        vals = sorted(d["rx_bytes"]
                      for d in rec["wire_bytes_per_dest"].values())
        return f"{vals[0] >> 20}–{vals[-1] >> 20} MiB"

    lines.append(f"| full layers | {full['ttd_s']}s | "
                 f"{full['predicted_s']}s | {_per_dest(full)} | — |")
    lines.append(
        f"| `{sd['shard_fraction']}` shards | {shard['ttd_s']}s | "
        f"{shard['predicted_s']}s | {_per_dest(shard)} | "
        f"{shard.get('gathered_layers_byte_exact', 0)}/{nl} layers |")
    lines += [
        "",
        f"Wire-bytes-per-dest within 10% of the fraction: "
        f"**{'yes' if sd['wire_within_10pct'] else 'NO'}**; TTD ratio "
        f"sharded/full = {sd['ttd_ratio']} (the proportional-improvement "
        "check — on this 2-core container the CPU, not the modeled "
        "link, can bound small runs; read against the trial spread).  "
        f"RUN_REPORT provenance full `{full.get('run_report')}`, "
        f"sharded `{shard.get('run_report')}` "
        f"(harness `{sd.get('harness_hash')}`).",
        "",
    ]


def _fabric_delivery_md(lines, results) -> None:
    fd = results.get("fabric_delivery")
    if not fd:
        return
    host, fab = fd["host_path"], fd["fabric_assisted"]
    mb = fd["model_bytes"]
    lo, hi = fd["pod_wire_bound"]
    n_trees = fd["replicas"] * fd["n_layers"]
    lines += [
        "## Fabric-assisted pod delivery: 1/N per host over the NIC, "
        "the rest over ICI (docs/fabric.md)",
        "",
        f"The same topology — {fd['replicas']} replica dests × "
        f"{fd['n_layers']} × {fd['layer_bytes'] >> 20} MiB layers from "
        f"one leader over {fd['backend']} (mode {fd['mode']}) — run "
        "HOST-PATH (every replica pulls every full layer: pod NIC "
        "ingress = model_bytes × replicas) vs FABRIC-ASSISTED (the "
        "leader pod-plans one `1/R@k` shard per host; the full tree "
        "materializes over the on-mesh gather, digest-checked against "
        "the leader's stamped full-layer digest).",
        "",
        "| path | TTD | predicted | pod NIC wire bytes | trees "
        "digest-exact |",
        "|---|---|---|---|---|",
        f"| host (full × R) | {host['ttd_s']}s | {host['predicted_s']}s "
        f"| {host['pod_nic_wire_bytes'] >> 20} MiB | "
        f"{host['trees_digest_exact']}/{n_trees} |",
        f"| fabric-assisted | {fab['ttd_s']}s | {fab['predicted_s']}s "
        f"| {fab['pod_nic_wire_bytes'] >> 20} MiB | "
        f"{fab['trees_digest_exact']}/{n_trees} |",
        "",
        f"Pod NIC ingress ≈ model_bytes ({mb >> 20} MiB; bound "
        f"[{lo >> 20}, {hi >> 20}] MiB): "
        f"**{'MET' if fd['pod_wire_within_10pct'] else 'NOT MET'}** — "
        f"wire ratio fabric/host = {fd['wire_ratio_vs_host']} "
        f"(ideal 1/R = {round(1 / fd['replicas'], 4)}), delivered "
        "shard bytes byte-exact against the link-table reconcile: "
        f"**{'yes' if fd['pod_delivered_exact'] else 'NO'}**.  TTD "
        f"ratio fabric/host = {fd['ttd_ratio_vs_host']} (the CFS "
        "caveat of the PR 6 precedent applies: on this 2-core "
        "container the gather's host-side CPU work shares cores with "
        "the TCP stack, so wall-clock gains understate a real pod, "
        "where the modeled NIC — not CPU — is the bottleneck and the "
        "gather rides ICI).  RUN_REPORT provenance host "
        f"`{host.get('run_report')}`, fabric `{fab.get('run_report')}` "
        f"(harness `{fd.get('harness_hash')}`).",
        "",
    ]


def to_markdown(results: dict) -> str:
    lines = [
        "# TTD matrix",
        "",
        "Time-to-deliver (median of "
        f"{results['trials']} runs). TCP scenarios run the real CLI over "
        "loopback, one process per node; the pod_fabric scenario runs "
        "cli.podrun on a virtual 8-device mesh with layer bytes on the "
        "device plane (zero TCP layer bytes); the spmd_fabric scenario "
        "runs the per-node CLI as TWO real OS processes joined into one "
        "jax.distributed runtime, layer bytes as lockstep collectives "
        "(gloo on CPU — the absolute number is dominated by per-plan "
        "compile+collective latency, not bandwidth); the dcn_2slice "
        "scenario keeps Mesh.Slices/DcnBW so mode 3 runs the topology-"
        "aware solve — attribution-first on the native Dinic (round 5), "
        "so the common case never touches scipy and the solve costs "
        "~10 ms cold. North-star secondary "
        "target: mode 1 ≈ mode 0 — note that at loopback-scaled layer "
        "sizes fixed per-transfer overhead (connection setup, protocol "
        "round-trips) dominates both numbers, so ratios within ~1.5x "
        "meet the target; at physical sizes the bandwidth term dominates "
        "and the ratio tightens toward 1.",
        "",
        "| scenario | mode 0 | mode 1 | mode 2 | mode 3 | mode1/mode0 |",
        "|---|---|---|---|---|---|",
    ]
    for name, per_mode in results["scenarios"].items():
        row = [name]
        for m in ("0", "1", "2", "3"):
            if m not in per_mode:
                row.append("—")
                continue
            cell = f"{per_mode[m]['ttd_s']}s"
            if m == "3" and "predicted_s" in per_mode[m]:
                # Plan fidelity: the solver's min-time next to achieved.
                cell += f" (pred {per_mode[m]['predicted_s']}s)"
            row.append(cell)
        row.append(str(per_mode.get("mode1_vs_mode0", "—")))
        lines.append("| " + " | ".join(row) + " |")
    lines.append("")
    ab = results.get("codec_ab")
    if ab:
        lines += [
            "## Transfer codec A/B (measured quantization benefit)",
            "",
            "boot_tiny_4node's topology retargeted at the "
            f"`{ab.get('model', 'tiny2')}` model (~2 MiB layers, so the "
            "256 KiB burst bucket is noise), every source rate-limited "
            f"to {ab['rate_bytes_per_s'] >> 20} MiB/s, mode {ab['mode']}: "
            "TTD is bytes over a fixed rate, so each codec's wire-size "
            "ratio (~0.51x int8, ~0.27x int4) appears as the TTD ratio "
            "(slightly below it: each job's burst head start is "
            "codec-independent).",
            "",
            "| codec | TTD | vs raw |",
            "|---|---|---|",
            f"| raw | {ab['raw']['ttd_s']}s | |",
            f"| int8 | {ab['int8']['ttd_s']}s | {ab['int8_vs_raw']} |",
        ]
        if "int4" in ab:
            lines.append(
                f"| int4 | {ab['int4']['ttd_s']}s | {ab['int4_vs_raw']} |")
        lines.append("")
    cw = results.get("codec_wire")
    if cw:
        dests = (cw.get("int8_wire") or {}).get("dests") or {}
        exact = ("byte-exact" if cw.get("wire_bytes_exact")
                 else "NOT byte-exact")
        lines += [
            "## Negotiated wire codec (docs/codec.md)",
            "",
            "Same rate-limited tiny2 topology, but the seeders hold RAW "
            "canonical blobs and the leader negotiates the wire form "
            "per transfer (`WireCodec: int8`): encode-on-send at the "
            "seeder, decode-at-staging at the dest, codec-qualified "
            "digests/acks, and the flow solver sizing each pair by its "
            "ENCODED bytes (effective capacity = bandwidth x ratio).  "
            f"Wire bytes per dest (RUN_REPORT `dests` table): {exact} "
            f"against `quant.blob_nbytes_codec` "
            f"({cw.get('int8_bytes_per_dest')} B int8 vs "
            f"{cw.get('raw_bytes_per_dest')} B raw, ratio "
            f"{cw.get('ratio')}x).",
            "",
            "| wire | TTD | vs raw | bound (≤ ~1/ratio + burst margin) |",
            "|---|---|---|---|",
            f"| raw | {cw['raw_wire']['ttd_s']}s | | |",
            f"| int8 | {cw['int8_wire']['ttd_s']}s "
            f"| {cw['int8_vs_raw']} "
            f"| {'MET' if cw['bound']['met'] else 'NOT MET'} "
            f"(expected ≲ {cw['bound']['expected_ttd_fraction']}) |",
            "",
        ]
        if dests:
            lines += ["Per-dest wire vs decoded bytes (int8 run):", ""]
            for dest, row in sorted(dests.items()):
                lines.append(
                    f"- dest {dest}: wire {row.get('wire_bytes')} B, "
                    f"decoded {row.get('decoded_bytes')} B "
                    f"({row.get('codec_layers')}/{row.get('layers')} "
                    "layers quantized)")
            lines.append("")
        en = cw.get("entropy")
        if en:
            e_exact = ("byte-exact" if en.get("wire_bytes_exact")
                       else "NOT byte-exact")
            lines += [
                "**Entropy-coded arm (`WireCodec: int8e`):** same "
                "topology with the leader seeded (data-dependent "
                "sizing encodes the leader's own copy); wire bytes "
                f"per dest {e_exact} against the independently "
                "DLE1-encoded seeded blobs "
                f"({en.get('int8e_bytes_per_dest')} B int8e vs "
                f"{en.get('int8_bytes_per_dest')} B int8, "
                f"{en.get('int8e_vs_int8_bytes')}x — seeded-random "
                "weights are near-incompressible, so the entropy pass "
                "is priced at its TRUE size and honestly loses a hair "
                "here; it wins on sparse/low-entropy layers and the "
                "delta rows).  TTD "
                f"{en['int8e_wire']['ttd_s']}s vs raw-seeded "
                f"{en['raw_wire']['ttd_s']}s "
                f"({en.get('int8e_vs_raw')}).",
                "",
            ]
    cb = results.get("codec_bench")
    if cb:
        lines += [
            "## Wire-codec micro-bench (encode/decode GB/s on this host)",
            "",
            "`quant.codec_bench` over one tiny2 layer blob "
            f"({cb.get('raw_bytes', 0)} B raw); rates are RAW bytes "
            "per second (the side the wire saves).  The codec-choice "
            "thresholds (`DLD_CODEC_MIN_RATE`, `DLD_ENTROPY_MIN_RATE`, "
            "`DLD_DELTA_MIN_RATE`) should sit well below the slowest "
            "of these — a link faster than the codec pass gains "
            "nothing from encoded shipping.  The delta row encodes "
            "against a 1%-perturbed sibling (the rollout shape).",
            "",
            "| codec | ratio | encode | host decode | device decode |",
            "|---|---|---|---|---|",
        ]
        for codec in ("int8", "int4", "int8e", "int4e", "delta"):
            row = cb.get(codec) or {}
            if not row:
                continue
            lines.append(
                f"| {codec} | {row.get('ratio')}x "
                f"| {row.get('encode_gbps')} GB/s "
                f"| {row.get('decode_host_gbps')} GB/s "
                f"| {row.get('decode_device_gbps')} GB/s |")
        lines.append("")
    phys = results.get("physical")
    if phys:
        lines += [
            "## Physical-size run (ties the TTD story to the bench)",
            "",
            "Mode 3 with `-hbm`: two seeders co-send the "
            "`llama3-8b-d4v8k` model — four ~416 MiB layers, the exact "
            "per-layer bytes `bench.py` measures (full 8B layer shape; "
            "vocab-trimmed head so it doesn't dwarf the layers) — to one "
            "cold dest that stages into device memory and boots "
            "(TTFT).  Loopback TCP, STRIPED: each flow fragment past "
            "the transport's stripe threshold rides "
            f"{phys.get('stripes', '?')} pooled data connections in "
            "parallel (`transport/tcp.py`).  The achieved rate is the "
            "dest's whole-model ingest, network receive + device "
            "staging end to end; the loopback ceiling columns are this "
            "host's MEASURED raw socket bandwidth (1 stream / the "
            "stripe count), probed next to the run — the fraction makes "
            "the number attributable and regression-guarded the same "
            "way bench.py's `link_fraction` does for the device hop.",
            "",
            "| scenario | backend | TTD | TTFT | achieved ingest | "
            "loopback ceiling (1s / striped) | link fraction |",
            "|---|---|---|---|---|---|---|",
            f"| {phys['scenario']} | {phys['backend']} | "
            f"{phys['ttd_s']}s | "
            + (f"{phys['ttft_s']}s" if "ttft_s" in phys else "—")
            + f" | {phys['achieved_gbps']} GB/s | "
            + (f"{phys.get('loopback_raw_gbps', '—')} / "
               f"{phys.get('loopback_striped_gbps', '—')} GB/s"
               if ("loopback_raw_gbps" in phys
                   or "loopback_striped_gbps" in phys) else "—")
            + " | "
            + (f"{phys['link_fraction']}"
               if "link_fraction" in phys else "—")
            + " |",
            "",
        ]
        prior = phys.get("prior")
        same_backend = (not prior
                        or prior.get("backend", phys.get("backend"))
                        == phys.get("backend"))
        if prior and "stripes" not in prior and same_backend:
            # Only a PRE-striping, SAME-backend prior gets the striping
            # attribution — a later regeneration carries a post-striping
            # prior (it has a "stripes" field), and a backend flip
            # (cpu-fallback vs live accelerator) would otherwise be
            # reported as this PR's speedup.
            lines += [
                "**Before/after (the striped-data-plane PR):** the "
                f"prior recorded row was {prior['ttd_s']}s at "
                f"{prior['achieved_gbps']} GB/s — each (seeder, layer) "
                "transfer was ONE serial socket stream.  With "
                "multi-socket striping, scatter-gather framing, and "
                "receive-to-stage streaming the re-measured row is "
                f"{phys['ttd_s']}s at {phys['achieved_gbps']} GB/s "
                f"({round(phys['achieved_gbps'] / max(prior['achieved_gbps'], 1e-9), 2)}x), "
                "with the remaining gap to the measured loopback "
                "ceiling attributed by the phase table below.",
                "",
            ]
        elif prior:
            lines += [
                f"Previous recorded row: {prior['ttd_s']}s at "
                f"{prior['achieved_gbps']} GB/s (run-to-run drift on "
                "this host is dominated by its bursty CPU budget — "
                "compare link fractions, not absolute rates).",
                "",
            ]
        wire = phys.get("wire_only")
        if wire:
            lines += [
                "Wire-only sibling (same topology, `-boot none`, "
                "measured for attribution): "
                f"TTD {wire['ttd_s']}s = {wire['achieved_gbps']} GB/s.  "
                "The delta to the recorded row is the boot PRECOMPILE "
                "overlap (BootHint fires at distribution start, so XLA "
                "compiles the forward WHILE the bytes are on the wire) "
                "— free concurrency on multi-core hosts, but on this "
                "2-core container the compile threads and the wire "
                "share cores, which is a host property, not a data-"
                "plane regression; the ceiling columns carry the same "
                "caveat (the container's CPU budget is bursty, so the "
                "raw-socket ceiling itself drifts several-fold between "
                "probes).",
                "",
            ]
        cold = phys.get("cold")
        if cold:
            wph = phys.get("phases") or {}
            cph = cold.get("phases") or {}

            def ttft_row(tag, rec, ph):
                boot_ms = ph.get("boot_ms", 0.0)
                pre = ph.get("precompile_ms")
                pre_cell = ("—" if pre is None else
                            f"{pre}ms"
                            + (" (in-wire)" if ph.get("precompile_in_wire")
                               else " (post-startup)"))
                streamed = ph.get("streamed_blobs", 0)
                stream_cell = (
                    f"{ph.get('stream_stage_ms', 0.0)}ms "
                    f"({ph.get('streamed_blobs_in_wire', 0)}/{streamed} "
                    "blobs in-wire)" if streamed else "—")
                ttft = rec.get("ttft_s")
                ttd = rec.get("ttd_s")
                bar = (round(ttft / (ttd + boot_ms / 1000), 2)
                       if ttft and ttd else None)
                return (f"| {tag} | {ttd}s | "
                        + (f"{ttft}s" if ttft else "—")
                        + f" | {boot_ms}ms | {pre_cell} | {stream_cell} | "
                        + (f"{bar}" if bar is not None else "—") + " |")

            lines += [
                "### TTFT: persistent compilation cache + streamed "
                "staging (cold vs warm)",
                "",
                "The same scenario run twice against one "
                "`DLD_COMPILE_CACHE_DIR`: the cold run compiles (and "
                "writes the cache) — its one-time compile overlaps the "
                "wire via the BootHint precompile; the warm run's "
                "compiles are DISK READS, so its boot tail is assembly "
                "+ forward only.  `streamed staging` is the per-layer "
                "receive-to-device boot path "
                "(`runtime/stream_boot.py`): each delivered layer's "
                "decode/upload runs the moment its interval set "
                "completes, concurrent with the remaining transfers.  "
                "`TTFT/(TTD+boot)` is the acceptance ratio — the "
                "leader-observed TTFT against delivery plus the dest's "
                "own boot tail (protocol overhead is the remainder); "
                "the VERDICT item 4 bar is warm TTFT ≤ TTD + decode "
                "+ ~20%.  Seeders run `-boot none` in both rows (only "
                "the dest's boot is the metric; a seeder booting its "
                "own copy would contend for the same 2 cores).",
                "",
                "| cache | TTD | TTFT | boot tail | hint precompile | "
                "streamed stage | TTFT/(TTD+boot) |",
                "|---|---|---|---|---|---|---|",
                ttft_row("cold", cold, cph),
                ttft_row("warm", phys, wph),
                "",
            ]
            prior = phys.get("prior")
            if prior and prior.get("ttft_s"):
                lines += [
                    "**Record vs prior:** the previously recorded row "
                    f"was TTD {prior['ttd_s']}s / TTFT "
                    f"{prior['ttft_s']}s; re-measured here as cold TTD "
                    f"{cold.get('ttd_s')}s / TTFT {cold.get('ttft_s')}s "
                    f"and warm TTD {phys.get('ttd_s')}s / TTFT "
                    f"{phys.get('ttft_s')}s.  These rows run with the "
                    "integrity plane ON (per-fragment wire checksum + "
                    "per-layer digest verify — its measured cost and "
                    "the integrity-OFF sibling are in the integrity "
                    "table below); the rest of the row-to-row movement "
                    "is this host's bursty CPU budget (compare "
                    "within-run siblings, not absolute cross-run "
                    "rates).",
                    "",
                ]
        fab = results.get("physical_fabric")
        if fab:
            frags = fab.get("tcp_layer_fragments",
                            int(fab.get("tcp_layer_bytes", False)))
            lines += [
                "The device-plane sibling: same model, layer bytes over "
                "the pod fabric (virtual 8-device CPU mesh; the single "
                "real chip can't host a [4, 2] mesh, so the collective "
                "runs on the CPU mesh and the real-chip evidence stays "
                "with the `-hbm` row above).  Zero TCP layer bytes "
                "asserted from the run's own logs (exact-match count of "
                "the receiver's per-fragment message):",
                "",
                "| scenario | backend | TTD | achieved | fabric "
                "deliveries | TCP layer fragments |",
                "|---|---|---|---|---|---|",
                f"| {fab['scenario']} | {fab['backend']} | "
                f"{fab['ttd_s']}s | {fab['achieved_gbps']} GB/s | "
                f"{fab['fabric_deliveries']} | "
                f"{f'{frags} (bug)' if frags else 'none'} |",
                "",
            ]
            cache = fab.get("collective_cache")
            phases = fab.get("plan_phases")
            if cache or phases:
                lines += [
                    "Per-plan phase breakdown of the fabric row "
                    "(thread-time sums across the run's plans; phases "
                    "from concurrent plans overlap, so sums can exceed "
                    "the TTD wall clock) and the compiled-collective "
                    "cache's reuse — warm plans skip XLA entirely, so "
                    "`compile` is a one-time cost the batch amortizes:",
                    "",
                    "| compile | upload | collective | splice | cache "
                    "hits | cache misses |",
                    "|---|---|---|---|---|---|",
                ]

                row = []
                for name in ("upload", "collective", "splice"):
                    ms = (phases or {}).get(name, {}).get("ms")
                    row.append(f"{ms}ms" if ms is not None else "—")
                compile_ms = (cache or {}).get("compile_ms")
                lines += [
                    "| " + " | ".join(
                        [f"{compile_ms}ms" if compile_ms is not None
                         else "—"] + row
                        + [str((cache or {}).get("hits", "—")),
                           str((cache or {}).get("misses", "—"))]
                    ) + " |",
                    "",
                ]
            prior = fab.get("prior")
            if prior:
                tcp_ttd = phys.get("ttd_s")
                ratio = (round(fab["ttd_s"] / tcp_ttd, 1)
                         if tcp_ttd else None)
                prior_ratio = prior.get("vs_tcp_same_host")
                lines += [
                    "**Before/after (the warm-path PR):** the prior "
                    f"recorded fabric row was {prior['ttd_s']}s "
                    f"({prior['achieved_gbps']} GB/s) at "
                    f"{prior_ratio}x its same-host TCP sibling "
                    f"({prior['host']}).  With the compiled-executable "
                    "cache + plan batching + full in-flight window, the "
                    f"re-measured row is {fab['ttd_s']}s at "
                    + (f"{ratio}x" if ratio else "—")
                    + " the same-host TCP row — per-plan XLA compile is "
                    "amortized to the one-time `compile` column above "
                    "(warm/batched plans skip it entirely), and the "
                    "remaining gap is the `collective` column: on the "
                    "virtual CPU mesh every \"ICI\" byte is an emulated "
                    "8-way host memcpy, the exact term real ICI hardware "
                    "accelerates.",
                    "",
                ]
        evidence = results.get("collective_cache_evidence")
        if evidence:
            lines += [
                "### Compiled-collective cache: reuse evidence",
                "",
                "Per-run `collective cache stats` (hits / misses / "
                "one-time compile) from the runs' own summaries — "
                "mode 3 batches same-size plans into ONE gather (so its "
                "miss count is the batch count, not the layer count); "
                "unbatched rounds show the warm-path hits directly:",
                "",
                "| run | hits | misses | compile |",
                "|---|---|---|---|",
            ]
            for name, c in evidence.items():
                lines.append(
                    f"| {name} | {c.get('hits', '—')} | "
                    f"{c.get('misses', '—')} | "
                    f"{c.get('compile_ms', '—')}ms |")
            lines.append("")
        ph = phys.get("phases")
        if ph:
            lines += [
                "Phase breakdown from the dest's log (thread-time sums; "
                "concurrent fragment/stripe handlers overlap, so sums "
                "can exceed the TTD wall clock).  Zero copy_ms/"
                "ingest_ms = the zero-copy receive landed socket bytes "
                "directly in the reassembly buffer and staging adopted "
                "that buffer:",
                "",
                "| wire recv | assembly copy | ingest write | stage | "
                "boot |",
                "|---|---|---|---|---|",
                f"| {ph['wire_recv_ms']}ms | {ph['assembly_copy_ms']}ms "
                f"| {ph['ingest_write_ms']}ms | {ph['stage_ms']}ms | "
                f"{ph['boot_ms']}ms |",
                "",
            ]
            if "fragments" in ph:
                span = ph.get("max_layer_recv_span_ms", 0.0)
                tail = ph.get("stage_ms", 0.0)
                lines += [
                    "Receive/stage overlap: fragments (stripes "
                    "included) whose bytes the sink PLACED directly in "
                    "the reassembly buffer stage as offsets complete — "
                    "their device-side accounting runs during the wire "
                    "receive, so only the post-completion `stage tail` "
                    "is serial with the wire:",
                    "",
                    "| fragments | placed (zero-copy) | in-recv ingest "
                    "| max layer recv span | stage tail after recv |",
                    "|---|---|---|---|---|",
                    f"| {ph['fragments']} | {ph['placed_fragments']} | "
                    f"{ph['ingest_write_ms']}ms | {span}ms | "
                    f"{tail}ms |",
                    "",
                ]
        integ = phys.get("integrity")
        if integ:
            lines += [
                "### Integrity plane (docs/integrity.md)",
                "",
                "Every wire frame carries an advisory checksum "
                "(xxh3-64 where the extension is importable, crc32 "
                "otherwise — the hash-rate table below is the measured "
                "why) verified before delivery; every completed layer "
                "verifies its leader-stamped digest (xxh3-128/"
                "blake2b-128, self-describing stamp) before it is acked "
                "or staged.  `verify_ms` is dest-side checksum THREAD "
                "time (concurrent stripe receivers verify in parallel); "
                "`crc_overhead_frac` is that thread time over the TTD "
                "wall clock — verification rides receive threads that "
                "overlap the wire, so the WALL-clock cost (the ≤5% "
                "acceptance metric) is the integrity-OFF row's delta "
                "below.  The faulted "
                "sibling runs the SAME scenario under a seeded schedule "
                "of injected corruption/drops (below the CRC check) and "
                "duplicated sends; delivery must still be byte-exact "
                "(digests verified), with recovery cost visible as TTD "
                "degradation + retransmitted bytes:",
                "",
                "| row | TTD | verify_ms (crc+digest) | "
                "crc_overhead_frac | dropped frames | NACKs | "
                "retransmitted bytes |",
                "|---|---|---|---|---|---|---|",
                f"| clean | {phys['ttd_s']}s | {integ['verify_ms']}ms | "
                f"{integ['crc_overhead_frac']:.2%} | "
                f"{integ['crc_dropped_frames']} | {integ['nacks_sent']} "
                f"| {integ['retransmitted_bytes']} |",
            ]
            nc = phys.get("nocheck")
            if nc:
                delta = round(
                    (phys["ttd_s"] - nc["ttd_s"])
                    / max(nc["ttd_s"], 1e-9), 4)
                lines.append(
                    f"| integrity OFF (`DLD_WIRE_CRC=0 "
                    f"DLD_LAYER_DIGESTS=0`) | {nc['ttd_s']}s "
                    f"(wall-clock delta to clean: {delta:+.1%}) | — | — "
                    "| — | — | — |")
            fl = phys.get("faulted")
            fi = (fl or {}).get("integrity")
            if fl and fi:
                degr = round(fl["ttd_s"] / max(phys["ttd_s"], 1e-9), 2)
                lines.append(
                    f"| faulted (`{fl.get('fault_spec', '?')}`) | "
                    f"{fl['ttd_s']}s ({degr}x clean) | "
                    f"{fi['verify_ms']}ms | "
                    f"{fi['crc_overhead_frac']:.2%} | "
                    f"{fi['crc_dropped_frames']} | {fi['nacks_sent']} | "
                    f"{fi['retransmitted_bytes']} |")
            cold = phys.get("cold") or {}
            if nc and cold.get("ttd_s"):
                spread = abs(phys["ttd_s"] - cold["ttd_s"]) / min(
                    phys["ttd_s"], cold["ttd_s"])
                met = (phys["ttd_s"] - nc["ttd_s"]) / nc["ttd_s"] <= 0.05
                lines += [
                    "",
                    f"The ≤5% overhead bar is "
                    f"{'MET' if met else 'NOT met'} as measured on this "
                    "container — read the delta with its error bar: the "
                    "clean row's same-config cold/warm spread in this "
                    f"very run is {spread:.0%} (CFS burst-budget drift, "
                    "the 0.36-2.7 GB/s raw-loopback band the striping "
                    "PR recorded), the same order as the overhead being "
                    "measured.  The drift-free attribution is the "
                    "thread-time column: verification is DRAM-rate "
                    "hashing sharing 2 CPUs with both seeder processes "
                    "and the dest's boot, so its thread share shrinks "
                    "wherever receive threads have an idle core to ride "
                    "(any real multi-core host); the per-byte verify "
                    "cost itself is bounded by the hash-rate table "
                    "below, not by this box's contention.",
                ]
            lines.append("")
    bench = results.get("integrity_bench")
    if bench:
        lines += [
            "## Integrity hash rates (measured on this host)",
            "",
            f"Why `{bench.get('fragment_algo', 'crc32')}` per FRAGMENT "
            f"and `{bench.get('digest_algo', 'blake2b')}`-128 per LAYER "
            f"(`utils/integrity.hash_bench`, {bench['bytes'] >> 20} MiB "
            "buffer): the fragment check sits on the per-stripe receive "
            "hot path (thread-concurrent, must track wire rate), the "
            "layer digest runs once per layer as the end-to-end "
            "identity.  The threat model is corruption, not adversarial "
            "substitution, so 128 random-collision bits are equivalent "
            "across algorithms and the fastest wins "
            "(`DLD_DIGEST_ALGO=blake2b` buys the cryptographic identity "
            "at the measured cost):",
            "",
            "| crc32 | adler32 | xxh3-64 | xxh3-128 | blake2b-128 | "
            "sha256 |",
            "|---|---|---|---|---|---|",
            f"| {bench['crc32_gbps']} GB/s | {bench['adler32_gbps']} "
            f"GB/s | {bench.get('xxh3_64_gbps', 0.0)} GB/s | "
            f"{bench.get('xxh3_128_gbps', 0.0)} GB/s | "
            f"{bench['blake2b_gbps']} GB/s | "
            f"{bench['sha256_gbps']} GB/s |",
            "",
        ]
    ns = results.get("north_star_model")
    if ns:
        tgt = ns.get("target", {})
        lines += [
            "## north_star_model: the v5e-32 / Llama-70B target, argued "
            "by model",
            "",
            f"The mode-3 solver run on `conf/{ns['config']}` exactly as "
            f"the leader would ({ns['layers']} layers x "
            f"{ns['layer_bytes'] / 2**30:.2f} GiB, 8 hosts x 4 chips, "
            "25 GB/s per-host line rate) — the hardware-independent way "
            "this environment allows the BASELINE north-star row "
            f"(<{tgt.get('time_s', 10):g} s at "
            f">={tgt.get('utilization', 0.7):.0%} of ICI line rate) to "
            "be argued.  `utilization` is dest-side: aggregate planned "
            "ingest over the receiving hosts' summed line rate.  The "
            "three rows isolate the bottleneck: the SHIPPED config is "
            "source-bound (one seeder's 3 GB/s disk class caps the whole "
            "pod — no schedule can beat bytes/rate), and the target is "
            "met exactly when the blobs sit in RAM on replicated "
            "seeders, the paper's multi-seeder co-send shape.",
            "",
            "| sources | predicted completion | aggregate | dest-side "
            "ICI utilization | solve | <10s | >=70% |",
            "|---|---|---|---|---|---|---|",
        ]
        for row in ns.get("rows", []):
            lines.append(
                f"| {row['label']} | {row['predicted_s']}s | "
                f"{row['aggregate_gbps']} GB/s | "
                f"{row['ici_utilization']:.1%} of "
                f"{row['dest_line_gbps']} GB/s | {row['solve_ms']}ms | "
                f"{'yes' if row['meets_time'] else 'NO'} | "
                f"{'yes' if row['meets_utilization'] else 'NO'} |")
        lines.append("")
    baseline = results.get("baseline_scenarios")
    if baseline:
        lines += [
            "## BASELINE.json scenarios (#2-#5)",
            "",
            "Driver-named benchmark topologies (cli.genconf), run over "
            "loopback with faithful node counts and schedules — 8 to 64 "
            "OS processes — at >=64 MiB layers, so the bandwidth term "
            "(not per-transfer overhead) dominates.  The 64-node row "
            "runs ALL FOUR modes, exercising the mode-3 solver at the "
            "scenario's full node count; its predicted_s/solve time are "
            "recorded next to the achieved TTD.",
            "",
            "| scenario | mode | layer bytes | TTD | mode-3 predicted | "
            "solve |",
            "|---|---|---|---|---|---|",
        ]
        for name, rows in baseline.items():
            if isinstance(rows, dict):  # pre-64MiB record (carried over)
                rows = [rows]
            for rec in rows:
                size = rec.get("layer_bytes")
                lines.append(
                    f"| {name} | {rec['mode']} | "
                    + (f"{size >> 20} MiB" if size else "—")
                    + f" | {rec['ttd_s']}s | "
                    + (f"{rec['predicted_s']}s" if "predicted_s" in rec
                       else "—")
                    + " | "
                    + (f"{rec['solve_ms']}ms" if "solve_ms" in rec
                       else "—") + " |")
        lines.append("")
    _telemetry_overhead_md(lines, results)
    _span_overhead_md(lines, results)
    _attribution_md(lines, results)
    _failover_md(lines, results)
    _service_md(lines, results)
    _fanout_md(lines, results)
    _elasticity_md(lines, results)
    _sharded_md(lines, results)
    _fabric_delivery_md(lines, results)
    _swap_md(lines, results)
    _rollout_md(lines, results)
    _autonomy_md(lines, results)
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ttd_matrix", prefix_chars="-")
    p.add_argument("-o", type=str, default="TTD_MATRIX.json")
    p.add_argument("-scale", type=int, default=8 << 20,
                   help="scaled LayerSize bytes for the reference scenario")
    p.add_argument("-trials", type=int, default=3)
    p.add_argument("-baseline", action="store_true",
                   help="also run the BASELINE.json scenarios #2-#5 "
                        "(8-64 processes; minutes of wall time)")
    p.add_argument("-baseline-scale", type=int, default=64 << 20,
                   help="LayerSize bytes for the BASELINE scenarios "
                        "(>=64 MiB so bandwidth dominates)")
    p.add_argument("-physical", action="store_true",
                   help="also run the physical-size scenario (~1.8 GiB "
                        "over loopback + device staging + a boot)")
    p.add_argument("-trace", type=str, default="",
                   help="with -physical: also write a Chrome trace of "
                        "the run (merged per-node logs) to this path")
    p.add_argument("-telemetry-overhead", action="store_true",
                   help="also measure the always-on telemetry plane's "
                        "TTD cost on a BASELINE scenario (ON vs "
                        "DLD_TELEMETRY=0; docs/observability.md)")
    p.add_argument("-failover", action="store_true",
                   help="also measure control-plane failover at "
                        "physical-row sizes: clean HA-armed mode-3 run "
                        "vs leader-killed sibling; records TTR and the "
                        "failover overhead (docs/failover.md)")
    p.add_argument("-service", action="store_true",
                   help="also measure the multi-job service plane "
                        "(docs/service.md): two overlapping jobs with "
                        "the per-link priority split, and a v2 delta "
                        "rollout's shipped bytes vs changed-fraction × "
                        "model bytes against the content store")
    p.add_argument("-swap", action="store_true",
                   help="also measure the zero-downtime weight swap "
                        "row (tokens/s + p99 before/during/after a "
                        "mid-serve v1→v2 swap; docs/swap.md)")
    p.add_argument("-rollout", action="store_true",
                   help="also measure the SLO-guarded rollout pipeline "
                        "(docs/rollout.md): a continuous request "
                        "stream through a 3-wave rollout with an "
                        "injected bad wave — auto-pause on the SLO "
                        "breach, rollback to v1, earlier waves keep "
                        "v2, zero dropped requests")
    p.add_argument("-autonomy", action="store_true",
                   help="also run the closed-loop fleet-autonomy row "
                        "(docs/autonomy.md): a slowserve hot replica + "
                        "a slow= straggler link under live traffic — "
                        "the policy engine must grow the replica set, "
                        "re-plan around the slow link, quarantine the "
                        "breacher and converge back inside SLO with "
                        "zero operator verbs, plus the DLD_POLICY=0 "
                        "kill-switch sibling showing the same "
                        "injections NOT acted on")
    p.add_argument("-sharded", action="store_true",
                   help="also measure sharded delivery "
                        "(docs/sharding.md): the multi-dest 64 MiB "
                        "full-layer vs 1/4-shard comparison — wire "
                        "bytes per dest, TTD, predicted-vs-achieved, "
                        "and the post-gather digest check")
    p.add_argument("-fabric-delivery", action="store_true",
                   dest="fabric_delivery",
                   help="also measure fabric-assisted pod delivery "
                        "(docs/fabric.md): the same replica-pod "
                        "topology run host-path vs pod-sharded — "
                        "per-pod NIC wire bytes must land within 10%% "
                        "of model_bytes (not model_bytes × replicas), "
                        "every replica's gathered tree digest-exact")
    p.add_argument("-fanout", action="store_true",
                   help="also measure the fleet fan-out row "
                        "(docs/hierarchy.md): 64- and 256-node inmem "
                        "BASELINE, flat mode-3 vs hierarchical "
                        "sub-leaders — root solve wall, root-handled "
                        "control message count, TTD")
    p.add_argument("-elasticity", action="store_true",
                   help="also measure elastic membership "
                        "(docs/membership.md): N unconfigured nodes "
                        "JOIN the running cluster concurrently — "
                        "origin-seeder vs peer-holder refill bytes, "
                        "coverage byte-exactness, and the sub-linear "
                        "origin-bytes bar")
    p.add_argument("-attribution", action="store_true",
                   help="also run the explainable-delivery row "
                        "(docs/observability.md): a mode-3 multi-node "
                        "run with an injected slow= straggler link — "
                        "the critical-path span chain must reconcile "
                        "with the achieved TTD (±10%%), decompose the "
                        "predicted-vs-achieved gap per phase, and flag "
                        "the straggler live")
    p.add_argument("-span-overhead", action="store_true",
                   help="also measure span recording's TTD cost on a "
                        "BASELINE scenario (ON vs DLD_SPANS=0; "
                        "docs/observability.md)")
    p.add_argument("-codec-wire", action="store_true",
                   help="also measure the NEGOTIATED wire codec "
                        "(docs/codec.md): raw-canonical seeders, "
                        "leader-chosen int8 wire over a rate-limited "
                        "topology — TTD vs raw, byte-exact wire "
                        "accounting, plus the encode/decode "
                        "micro-bench")
    args = p.parse_args(argv)
    if args.trace and not args.physical:
        p.error("-trace needs -physical (it traces that run)")
    results = run_matrix(args.scale, args.trials)
    results["codec_ab"] = run_codec_ab(args.trials)
    prior_doc = None
    if os.path.exists(args.o):
        try:
            with open(args.o) as f:
                prior_doc = json.load(f)
        except (OSError, ValueError):
            prior_doc = None
    # The solver-by-model north-star record is cheap (a few solves, no
    # processes): regenerate it on every run.
    results["north_star_model"] = run_north_star()
    # Hash-rate micro-bench on THIS host: the measured justification for
    # crc32 on the per-fragment hot path vs blake2b for the per-layer
    # digest (docs/integrity.md).  Cheap; regenerated every run.
    from ..utils.integrity import hash_bench

    results["integrity_bench"] = hash_bench()
    if args.baseline:
        if args.baseline_scale < 64 << 20:
            # Smaller layers are fine for iterating, but the RECORDED
            # matrix wants the bandwidth-dominated regime — say so
            # instead of silently clamping.
            print(f"note: -baseline-scale {args.baseline_scale} is below "
                  "the 64 MiB bandwidth-dominated regime the recorded "
                  "matrix uses", file=sys.stderr)
        results["baseline_scenarios"] = run_baseline_scenarios(
            args.baseline_scale)
    elif prior_doc and prior_doc.get("baseline_scenarios"):
        # A refresh without -baseline must not erase the recorded
        # BASELINE scenario results (minutes of 64-process wall time).
        results["baseline_scenarios"] = prior_doc["baseline_scenarios"]
    if args.physical:
        # Cold-then-warm against ONE persistent compilation cache: the
        # cold run writes it (its compile overlaps the wire via the
        # BootHint precompile), the warm run reads it — the pair is the
        # TTFT cold/warm breakdown the markdown renders.
        import shutil

        cachedir = tempfile.mkdtemp(prefix="dld-compile-cache-")
        try:
            cold = run_physical(trace_out=args.trace, cache_dir=cachedir,
                                label="cold")
            warm = run_physical(cache_dir=cachedir, label="warm")
            # FAULTED sibling (integrity plane): same scenario, warm
            # cache, with a seeded schedule of corruption/drops below
            # the CRC check plus duplicated sends on every node — the
            # recovery (NACK retransmits, digest verify) must deliver
            # byte-exactly; the row records the TTD degradation.
            try:
                nocheck = run_physical(cache_dir=cachedir,
                                       label="nocheck",
                                       integrity_off=True)
                warm["nocheck"] = {
                    k: nocheck[k]
                    for k in ("ttd_s", "ttft_s", "achieved_gbps")
                    if k in nocheck
                }
            except Exception as e:  # noqa: BLE001 — clean rows still record
                print(f"integrity-off physical run failed: {e!r}",
                      file=sys.stderr)
            try:
                faulted = run_physical(cache_dir=cachedir,
                                       label="faulted",
                                       faults=PHYSICAL_FAULT_SPEC)
                warm["faulted"] = {
                    k: faulted[k]
                    for k in ("ttd_s", "ttft_s", "achieved_gbps",
                              "integrity", "fault_spec")
                    if k in faulted
                }
            except Exception as e:  # noqa: BLE001 — clean rows still record
                print(f"faulted physical run failed: {e!r}",
                      file=sys.stderr)
        finally:
            shutil.rmtree(cachedir, ignore_errors=True)
        warm["cold"] = {
            k: cold[k] for k in ("ttd_s", "ttft_s", "achieved_gbps",
                                 "phases", "cache", "predicted_s")
            if k in cold
        }
        results["physical"] = warm
        # Before/after: carry the superseded record's headline numbers so
        # the regenerated markdown states the delta it claims.
        prior_phys = (prior_doc or {}).get("physical")
        if prior_phys and "ttd_s" in prior_phys:
            results["physical"]["prior"] = {
                "ttd_s": prior_phys["ttd_s"],
                "achieved_gbps": prior_phys["achieved_gbps"],
                "backend": prior_phys.get("backend", ""),
            }
            if "ttft_s" in prior_phys:
                results["physical"]["prior"]["ttft_s"] = (
                    prior_phys["ttft_s"])
            if "stripes" in prior_phys:
                # Marks the prior as post-striping: the markdown then
                # reports plain run-to-run drift instead of attributing
                # the delta to the striping PR.
                results["physical"]["prior"]["stripes"] = (
                    prior_phys["stripes"])
        if prior_phys and prior_phys.get("wire_only"):
            # Hand-measured attribution sibling (-boot none): carried
            # forward like baseline_scenarios, not re-measured here.
            results["physical"].setdefault(
                "wire_only", prior_phys["wire_only"])
        results["physical_fabric"] = run_physical_fabric()
        fab_prior = (prior_doc or {}).get("physical_fabric") or {}
        if fab_prior.get("prior"):
            results["physical_fabric"].setdefault(
                "prior", fab_prior["prior"])
    else:
        for key in ("physical", "physical_fabric"):
            if prior_doc and prior_doc.get(key):
                results[key] = prior_doc[key]
    if args.telemetry_overhead:
        results["telemetry_overhead"] = run_telemetry_overhead()
    elif prior_doc and prior_doc.get("telemetry_overhead"):
        results["telemetry_overhead"] = prior_doc["telemetry_overhead"]
    if args.span_overhead:
        results["span_overhead"] = run_span_overhead()
    elif prior_doc and prior_doc.get("span_overhead"):
        results["span_overhead"] = prior_doc["span_overhead"]
    if args.attribution:
        results["attribution"] = run_attribution()
    elif prior_doc and prior_doc.get("attribution"):
        results["attribution"] = prior_doc["attribution"]
    if args.failover:
        results["failover"] = run_failover()
    elif prior_doc and prior_doc.get("failover"):
        results["failover"] = prior_doc["failover"]
    if args.service:
        results["service_jobs"] = run_service_jobs()
        results["delta_rollout"] = run_delta_rollout()
        results["delta_wave"] = run_delta_wave()
    else:
        for key in ("service_jobs", "delta_rollout", "delta_wave"):
            if prior_doc and prior_doc.get(key):
                results[key] = prior_doc[key]
    if args.sharded:
        results["sharded_delivery"] = run_sharded_delivery()
    elif prior_doc and prior_doc.get("sharded_delivery"):
        results["sharded_delivery"] = prior_doc["sharded_delivery"]
    if args.fabric_delivery:
        results["fabric_delivery"] = run_fabric_delivery()
    elif prior_doc and prior_doc.get("fabric_delivery"):
        results["fabric_delivery"] = prior_doc["fabric_delivery"]
    if args.fanout:
        results["fanout"] = run_fanout()
    elif prior_doc and prior_doc.get("fanout"):
        results["fanout"] = prior_doc["fanout"]
    if args.swap:
        results["live_swap"] = run_live_swap()
    elif prior_doc and prior_doc.get("live_swap"):
        results["live_swap"] = prior_doc["live_swap"]
    if args.rollout:
        results["rollout"] = run_rollout()
    elif prior_doc and prior_doc.get("rollout"):
        results["rollout"] = prior_doc["rollout"]
    if args.autonomy:
        results["autonomy"] = {
            "closed_loop": run_autonomy(),
            "kill_switch": run_autonomy(kill_switch=True),
        }
    elif prior_doc and prior_doc.get("autonomy"):
        results["autonomy"] = prior_doc["autonomy"]
    if args.elasticity:
        results["elasticity"] = run_elasticity()
    elif prior_doc and prior_doc.get("elasticity"):
        results["elasticity"] = prior_doc["elasticity"]
    if args.codec_wire:
        results["codec_wire"] = run_codec_wire(args.trials)
        from ..models.quant import codec_bench

        results["codec_bench"] = codec_bench()
    else:
        for key in ("codec_wire", "codec_bench"):
            if prior_doc and prior_doc.get(key):
                results[key] = prior_doc[key]
    # Regenerate the cache-reuse evidence from THIS run's records;
    # fall back to the prior document's (e.g. hand-recorded SPMD rows)
    # when the run produced none.
    evidence = _cache_evidence(results)
    if not evidence and prior_doc:
        evidence = prior_doc.get("collective_cache_evidence") or {}
    if evidence:
        results["collective_cache_evidence"] = evidence
    with open(args.o, "w") as f:
        json.dump(results, f, indent=1)
    md = os.path.splitext(args.o)[0] + ".md"
    with open(md, "w") as f:
        f.write(to_markdown(results))
    print(json.dumps(results["scenarios"], indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
