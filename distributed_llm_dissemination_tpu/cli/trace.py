"""Export merged node logs as a Chrome/Perfetto trace.

The reference's only "trace viewer" is jq post-processing of merged JSON
logs (``/root/reference/conf/collect_logs.sh:14-16``); this tool turns
the same log stream into the Chrome Trace Event Format, so a whole
dissemination run — per-layer receives, per-job sends, solver time,
crashes, resume points — renders as a timeline in ``chrome://tracing``
or https://ui.perfetto.dev.

Mapping:
- one **process row per node** (the ``node`` field);
- log records carrying a duration (layer receives ``duration_ms``, job
  sends ``send_dur_ms``, flow solves ``computation_ms``) become complete
  ("X") slices, laid out on a per-layer track;
- lifecycle markers (timer start/stop, crash declarations, resume
  events) become instant ("i") events;
- reassembly progress (``layer fragment stored``) becomes a per-layer
  counter ("C") track.

Usage:
    python -m distributed_llm_dissemination_tpu.cli.trace logs/ -o run.trace.json
    python -m ....trace merged.jsonl            # from collect_logs output
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Iterable, List

from .collect_logs import iter_records

# message -> (slice name, duration field)
_DURATION_RULES = {
    "(a fraction of) layer received": ("receive layer", "duration_ms"),
    "finished sending layer": ("send layer", "send_dur_ms"),
    "Job assignment completed": ("flow solve", "computation_ms"),
    "decoded tokens after boot": ("decode", "decode_ms"),
}

_INSTANT_MESSAGES = {
    "timer start",
    "timer stop: startup",
    "timer stop: first token",
    "node declared crashed",
    "declared-dead node announced again; reviving",
    "node re-announced; re-planning",
    "resuming partial layer",
    "restored partial layer from checkpoint",
    "steal a job",
    "job assignment",
    "job completed",
    "layer fully received",
    "received startup: ready",
    # Device data plane (fabric) + boot lifecycle:
    "pod fabric up",
    "dispatching device plan",
    "layer landed over device fabric",
    "layer assembled on host after fabric failure",
    "layer staged to HBM",
    "model booted from disseminated layers",
    "pipeline stage booted from disseminated layers",
    "released fabric upload cache",
    # Multi-controller fabric + serving lifecycle:
    "spmd fabric up",
    "spmd fabric plan cancelled",
    "spmd fabric stalled waiting for plan seq",
    "pod serve dispatched",
    "pod serve cancelled: pod no longer servable",
    "pod pipelined forward from staged weights",
    # Round 4: pod generation + topology planning markers.  (All three
    # solver variants are marked so comparing solver modes in a trace
    # never loses the event; the leader-level "Job assignment completed"
    # duration slice still carries the timing for every mode.)
    "pod decoded tokens from staged weights",
    "pod generated token ids",
    "job assignment calculated",
    "job assignment calculated (native)",
    "job assignment calculated (topology)",
    "job assignment calculated (topology LP)",
    "topology solve degraded to flat replan",
    # Fabric-assisted pod delivery (docs/fabric.md): the NIC shard
    # phase, the on-mesh reconstruction, and its degrade edges.
    "pod delivery planned",
    "pod shard published for on-mesh gather",
    "layer materialized from shards (on-mesh gather)",
    "pod delivery materialized full tree",
    "dispatching pod gather plan",
    "pod delivery degraded to host path",
    "pod gather timed out; degrading to host path",
    "pod member gone; degrading its pod to host path",
    # Intra-group chain dissemination (docs/hierarchy.md): the planned
    # member-to-member relay, its per-fragment forwards, and the two
    # repair edges (mid-chain NACK service, dead-hop redrive).
    "group chain planned",
    "chain forward roles installed",
    "relaying layer downstream",
    "NACK served from in-flight partial coverage",
    # Telemetry plane (docs/observability.md):
    "clock offset estimated",
    "cluster telemetry",
    # Causal observability (spans + fleet health + live job progress):
    "fleet health event",
    "fleet health timeline",
    "job progress",
}


def clock_offsets(records) -> dict:
    """Per-node clock offsets (leader clock MINUS node clock, ms) from
    the nodes' announce-time TimeSync estimates ("clock offset
    estimated" records, runtime/receiver.py).  A node that logged
    several (re-announce after a restart or takeover) keeps the LAST —
    its clock may have been corrected, and the most recent probe is the
    freshest estimate."""
    offsets: dict = {}
    for rec in records:
        if rec.get("message") == "clock offset estimated":
            off = rec.get("offset_ms")
            if isinstance(off, (int, float)):
                offsets[rec.get("node", "?")] = float(off)
    return offsets


def _layer_of(rec: dict):
    for key in ("layerID", "layer"):
        if key in rec:
            return rec[key]
    return None


def span_flow_events(records, offsets: dict) -> List[dict]:
    """Perfetto flow arrows from the pair-lifecycle span timeline
    (docs/observability.md): the LAST "cluster telemetry" dump carries
    the merged span events; each span becomes one flow chain — a thin
    anchor slice per phase on its recording node's row (named
    ``span <id> <phase>``) plus s/t/f flow events with the span id —
    so the leader's plan visibly arrows into the sender's dispatch and
    the dest's receive/verify/stage across process rows."""
    from ..utils.critical_path import PHASES

    spans_dump = None
    for rec in records:
        if rec.get("message") == "cluster telemetry" and rec.get("spans"):
            spans_dump = rec["spans"]  # last one wins (failover re-dump)
    if not spans_dump:
        return []
    by_span: dict = {}
    for ev in spans_dump:
        s, ph, t = ev.get("span"), ev.get("phase"), ev.get("t_ms")
        if not s or ph not in PHASES or not isinstance(t, (int, float)):
            continue
        by_span.setdefault(str(s), {})[ph] = ev
    events: List[dict] = []
    for flow_id, (span, phases) in enumerate(sorted(by_span.items()), 1):
        chain = [phases[p] for p in PHASES if p in phases]
        if len(chain) < 2:
            continue
        for k, ev in enumerate(chain):
            pid = str(ev.get("node", "?"))
            ts_us = (float(ev["t_ms"]) + offsets.get(pid, 0.0)) * 1000.0
            layer = ev.get("layer")
            tid = int(layer) if layer is not None else 0
            events.append({
                "ph": "X", "pid": pid, "tid": tid,
                "name": f"span {span} {ev['phase']}",
                "ts": ts_us, "dur": 100.0,  # 0.1 ms anchor slice
                "args": {k2: v for k2, v in ev.items()
                         if k2 not in ("t_ms",)},
            })
            flow_ph = ("s" if k == 0
                       else "f" if k == len(chain) - 1 else "t")
            events.append({
                "ph": flow_ph, "cat": "span", "id": flow_id,
                "pid": pid, "tid": tid, "name": f"span {span}",
                "ts": ts_us + 1.0,
                **({"bp": "e"} if flow_ph == "f" else {}),
            })
    return events


def to_trace_events(records: Iterable[dict],
                    align_clocks: bool = True) -> List[dict]:
    """Chrome trace events from merged log records.

    ``align_clocks`` (default on) applies each node's announce-time
    clock-offset estimate ("clock offset estimated" records) to ALL of
    that node's timestamps, so multi-HOST timelines — where wall clocks
    can disagree by hundreds of ms — line up on the leader's clock
    instead of rendering receives before their sends.  Nodes without an
    estimate (the leader itself, pre-telemetry logs) pass through
    unshifted, which is exactly the old behavior."""
    records = list(records)
    offsets = clock_offsets(records) if align_clocks else {}
    # Flow arrows from the span timeline (docs/observability.md) ride
    # alongside the log-derived slices; same clock alignment.
    events: List[dict] = list(span_flow_events(records, offsets))
    seen_pids = set()
    for rec in records:
        msg = rec.get("message")
        t = rec.get("time")
        if msg is None or not isinstance(t, (int, float)):
            continue
        pid = rec.get("node", "?")
        # offset = leader clock - node clock, so node time + offset is
        # the event on the LEADER's timeline.
        t = t + offsets.get(pid, 0.0)
        ts_us = t * 1000.0  # unix-ms -> µs
        layer = _layer_of(rec)
        tid = int(layer) if layer is not None else 0
        if pid not in seen_pids:
            seen_pids.add(pid)
            events.append({
                "ph": "M", "pid": pid, "name": "process_name",
                "args": {"name": f"node {pid}"},
            })

        # Known duration-carrying messages get curated slice names; any
        # other record with a duration_ms field (e.g. emitted by
        # utils.trace.span) becomes a slice named by its message.
        rule = _DURATION_RULES.get(msg)
        if rule is None and isinstance(rec.get("duration_ms"), (int, float)):
            rule = (msg, "duration_ms")
        if rule is not None:
            name, dur_field = rule
            dur_ms = rec.get(dur_field)
            if isinstance(dur_ms, (int, float)):
                events.append({
                    "ph": "X",
                    "pid": pid,
                    "tid": tid,
                    "name": f"{name} {layer}" if layer is not None else name,
                    "ts": ts_us - dur_ms * 1000.0,  # log records the end
                    "dur": dur_ms * 1000.0,
                    "args": {k: v for k, v in rec.items()
                             if k not in ("message", "time", "level")},
                })
                continue
        if msg == "layer fragment stored":
            events.append({
                "ph": "C",
                "pid": pid,
                "name": f"layer {layer} bytes",
                "ts": ts_us,
                "args": {"received": rec.get("received", 0)},
            })
            continue
        if msg in _INSTANT_MESSAGES:
            events.append({
                "ph": "i",
                "pid": pid,
                "tid": tid,
                "name": msg,
                "ts": ts_us,
                "s": "p",  # process-scoped marker
                "args": {k: v for k, v in rec.items()
                         if k not in ("message", "time", "level")},
            })
    events.sort(key=lambda e: e.get("ts", 0))
    return events


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="trace", description=__doc__)
    p.add_argument("paths", nargs="+", help="log files or directories")
    p.add_argument("-o", "--output", default="-",
                   help="trace JSON output (default: stdout)")
    p.add_argument("--raw-clocks", action="store_true",
                   help="skip clock-offset correction (render each "
                        "node's timestamps as logged)")
    args = p.parse_args(argv)

    events = to_trace_events(iter_records(args.paths),
                             align_clocks=not args.raw_clocks)
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    if args.output == "-":
        json.dump(doc, sys.stdout)
    else:
        with open(args.output, "w") as f:
            json.dump(doc, f)
        print(f"{len(events)} trace events -> {args.output}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
