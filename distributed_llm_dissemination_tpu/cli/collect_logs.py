"""Merge per-node JSONL logs into one leader-relative timeline.

Port of ``/root/reference/conf/collect_logs.sh:14-16`` (the jq pipeline)
into the CLI: gather each node's JSON log stream, merge sorted by the
unix-ms ``time`` field, and rebase every timestamp onto the leader's
``"timer start"`` event so all nodes share one clock origin without any
cross-host clock sync (SURVEY §5.1 — the logs *are* the trace).

Each merged record gains ``rel_ms`` (milliseconds since timer start; events
before it are negative).  This is the offline trace viewer: pipe the output
to jq to plot per-layer receive durations, per-job throughputs, and the
end-to-end time-to-deliver.

Usage:
    python -m distributed_llm_dissemination_tpu.cli.collect_logs logs/*.jsonl
    python -m ....collect_logs --anchor "timer start" -o merged.jsonl logs/
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Iterable, List, Optional


def iter_records(
    paths: Iterable[str], exclude: Optional[str] = None
) -> Iterable[dict]:
    """Yield JSON objects from files (or every ``*.jsonl``/``*.log`` in a
    directory); non-JSON lines are skipped, matching jq's -R fromjson? trick
    used by some log mergers.  ``exclude`` drops one path — the merge's own
    output file, which on a re-run would otherwise be ingested as input and
    duplicate every event."""
    for path in paths:
        if exclude is not None and os.path.abspath(path) == exclude:
            continue
        if os.path.isdir(path):
            inner = sorted(
                os.path.join(path, f)
                for f in os.listdir(path)
                if f.endswith((".jsonl", ".log", ".json"))
            )
            yield from iter_records(inner, exclude)
            continue
        with open(path, "r") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    yield rec


def merge(records: Iterable[dict], anchor: str = "timer start") -> List[dict]:
    """Sort by ``time`` and rebase onto the first ``anchor`` message
    (emitted by the leader at distribution start, runtime/leader.py)."""
    recs = sorted(
        (r for r in records if isinstance(r.get("time"), (int, float))),
        key=lambda r: r["time"],
    )
    t0 = next((r["time"] for r in recs if r.get("message") == anchor), None)
    if t0 is None and recs:
        t0 = recs[0]["time"]
    for r in recs:
        r["rel_ms"] = round(r["time"] - t0, 3) if t0 is not None else 0
    return recs


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="collect_logs", description=__doc__)
    p.add_argument("paths", nargs="+", help="log files or directories")
    p.add_argument("--anchor", default="timer start",
                   help="message whose timestamp becomes rel_ms=0")
    p.add_argument("-o", "--output", default="-",
                   help="output file (default: stdout)")
    args = p.parse_args(argv)

    exclude = None if args.output == "-" else os.path.abspath(args.output)
    merged = merge(iter_records(args.paths, exclude), anchor=args.anchor)
    out = sys.stdout if args.output == "-" else open(args.output, "w")
    try:
        for rec in merged:
            out.write(json.dumps(rec) + "\n")
    finally:
        if out is not sys.stdout:
            out.close()

    ttd = time_to_deliver(merged)
    if ttd is not None:
        print(f"time to deliver: {ttd:.3f} ms", file=sys.stderr)
    return 0


def time_to_deliver(merged: List[dict]) -> float | None:
    """TTD extracted from the merged trace: 'timer start' → 'timer stop:
    startup' (cmd/main.go:173-181 measures the same span in-process).

    Requires the real 'timer start' anchor: without it (leader log missing,
    or rel_ms rebased onto a custom --anchor) the stop event's rel_ms is
    measured from some other origin and would misreport the TTD span."""
    start = next((r for r in merged if r.get("message") == "timer start"), None)
    stop = next(
        (r for r in merged if str(r.get("message", "")).startswith("timer stop")),
        None,
    )
    if start is None or stop is None:
        return None
    return float(stop["time"] - start["time"])


if __name__ == "__main__":
    sys.exit(main())
