"""Transport abstraction: the two-plane communication backend contract.

Mirrors the reference's ``Transport`` interface
(``/root/reference/distributor/transport.go:18-25``): ``send``,
``broadcast``, ``deliver``, ``register_pipe``, ``get_address``, ``close``.
Concrete backends: in-process fake (tests), TCP (host/DCN data plane), and
the device plane in ``parallel/`` which moves layer bytes over ICI as XLA
collectives instead of sockets.
"""

from __future__ import annotations

import abc
import queue
from typing import Dict

from ..core.types import LayerID, NodeID
from .messages import Message

# NodeID -> dialable address (transport.go:57).
AddrRegistry = Dict[NodeID, str]


class Transport(abc.ABC):
    """Abstract send/broadcast/deliver/pipe/close."""

    @abc.abstractmethod
    def send(self, dest_id: NodeID, message: Message) -> None:
        """Deliver ``message`` to ``dest_id``; raises on failure."""

    @abc.abstractmethod
    def broadcast(self, message: Message) -> None:
        """Send to every registered peer (best-effort, errors logged)."""

    @abc.abstractmethod
    def register_pipe(self, layer_id: LayerID, dest_id: NodeID) -> None:
        """Arrange for the next incoming copy of ``layer_id`` to be relayed
        cut-through to ``dest_id`` while being received
        (transport.go:144-196, 427-436)."""

    @abc.abstractmethod
    def deliver(self) -> "queue.Queue[Message]":
        """The incoming-message queue (the Go ``Deliver()`` channel)."""

    @abc.abstractmethod
    def get_address(self) -> str: ...

    @abc.abstractmethod
    def close(self) -> None: ...
