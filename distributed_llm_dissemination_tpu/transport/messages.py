"""Typed control-plane protocol messages.

Re-design of the reference's message layer
(``/root/reference/distributor/message.go``): the same protocol vocabulary —
announce / ack / retransmit / flowRetransmit / layer / clientReq / startup —
as plain dataclasses with symmetric JSON payload codecs.  Layer payloads are
never JSON-encoded: a ``LayerMsg`` travels as a JSON header followed by the
raw byte stream (message.go:286-287, transport.go:308-373).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Union

from ..core.types import (
    LayerID,
    LayerIDs,
    LayerLocation,
    LayerSrc,
    NodeID,
    layer_ids_from_json,
    layer_ids_to_json,
)


class MsgType(enum.IntEnum):
    """Wire message kinds (message.go:16-28)."""

    ANNOUNCE = 0
    ACK = 1
    LAYER = 2
    RETRANSMIT = 3
    FLOW_RETRANSMIT = 4
    CLIENT_REQ = 5
    STARTUP = 6
    SIMPLE = 7
    # Extensions beyond the reference enum (message.go:16-28):
    # HEARTBEAT — liveness beacon for the failure detector, which the
    # reference leaves TODO (crash(n node), node.go:218-220).
    # BOOT_READY — receiver booted its model from the disseminated layers;
    # the reference's startup handler is a stub (node.go:1387-1389), so it
    # has nothing to report back.
    # DEVICE_PLAN — pod-fabric transfer command: the layer bytes move as
    # device traffic (ICI), so the control plane replaces the reference's
    # per-transfer TCP byte stream (transport.go:267-274) with this one
    # small message.
    # SERVE — multi-controller pod serving: after the stage boots, every
    # member process enters one pipelined forward across the stages
    # (runtime/pp_serve.py).
    # BOOT_HINT — leader → assignee at distribution start: the blob ids
    # the dest will end up holding, so its boot programs can COMPILE
    # while the bytes are still on the wire (XLA needs only shapes).
    # GENERATE_REQ / GENERATE_RESP — post-boot inference service: a peer
    # sends prompt token ids, the booted node decodes with its RESIDENT
    # params and answers — the startup hook's engine, actually servable
    # over the same transport that delivered its weights.
    # PLAN_RESEND_REQ — SPMD-fabric self-healing: a process whose
    # executor detects a persistent seq gap (it never received some
    # DevicePlanMsg; later plans queue behind the hole, stalling the
    # pod lockstep) asks the leader for the missing seqs.  The leader
    # re-sends its retained copy — or a cancellation when it has none —
    # so no transfer waits forever on one lost control message.
    # LAYER_NACK — integrity plane (docs/integrity.md): a receiver whose
    # transport dropped a corrupt layer fragment (bad advisory CRC, or a
    # stale abandoned stripe group) asks the fragment's SOURCE for a
    # byte-range retransmit — bounded-retry, so one flipped wire bit
    # costs one fragment re-send instead of a crash-detection timeout.
    # LAYER_DIGESTS — leader → assignee at distribution start: the
    # self-describing digest (xxh3:<hex> / blake2b hex) of each layer
    # the dest will receive (collected from
    # the holders' announces), so completed layers are verified
    # end-to-end BEFORE they are acked or staged to a device.
    # LEADER_LEASE — control-plane HA (docs/failover.md): the leader's
    # liveness beacon, carrying the current EPOCH and the ordered
    # standby succession list.  Standbys and workers feed it to a
    # FailureDetector; on expiry the lowest-ranked live standby assumes
    # leadership at epoch+1 and its first lease at the higher epoch IS
    # the takeover announcement — workers re-point their leader and
    # re-announce (the reconcile channel).
    # CONTROL_DELTA — leader → standbys: one epoch-stamped control-state
    # delta (status row, ack, partial coverage, dropped assignment,
    # digest stamp, plan seq) or a full snapshot, applied to the
    # standby's shadow leader state so takeover starts from replicated
    # knowledge instead of a blank slate.
    # SOURCE_DEAD — leader → dest (mode 3): a mid-transfer SOURCE was
    # declared crashed; the dest must NACK its uncovered byte ranges of
    # the named layer to the surviving ``alt_id`` holder (the PR-4
    # byte-range retransmit plane) instead of waiting for a whole-layer
    # re-send — recovery costs only the dead source's unsent bytes.
    # METRICS_REPORT — telemetry plane (docs/observability.md): a node's
    # periodic run-scoped metric snapshot (counters + per-link flight
    # recorder + gauges), folded by the leader into the cluster table
    # that the -watch hook and the RUN_REPORT render.  Epoch-stamped so
    # a failed-over cluster fences reporters still pointing at a dead
    # leader's run view; omitted-field wire-compatible (every section is
    # optional, an empty report is a liveness-sized envelope).
    # TIME_SYNC — telemetry plane: the request/response clock-offset
    # probe.  A node sends its wall clock (t0) at announce time; the
    # answering leader echoes it with its own wall clock (t1); the node
    # estimates offset = t1 - (t0 + t2)/2 (NTP's midpoint) and LOGS it,
    # so cli/trace.py can line multi-host Perfetto timelines up on the
    # leader's clock without any cross-host time sync daemon.
    # JOB_SUBMIT / JOB_STATUS — the dissemination service plane
    # (docs/service.md): a submitter asks the long-lived leader daemon
    # to admit one dissemination job (a target Assignment + priority +
    # optional per-layer content digests for delta resolution); the
    # leader answers — and answers `-jobs` queries — with the admitted
    # job table (states, remaining pairs, drop counts).  Omitted-field
    # wire-compatible like every extension.
    # SWAP_COMMIT — zero-downtime weight swap (docs/swap.md): the
    # epoch-fenced commit fence of a ``kind="swap"`` job.  Once a
    # replica's full v2 layer set is digest-verified (every versioned
    # ack landed), the leader tells each serving node to atomically
    # flip its serving params to the staged v2 set; the node confirms
    # with ``applied=True``, re-requests a fence it suspects it missed
    # with ``query=True``, and reports an unrecoverable staging failure
    # (digest retries exhausted) via ``error`` — which aborts the swap
    # cluster-wide (``abort=True``: keep serving v1, release staged v2).
    # JOB_REVOKE — preemption revoke (docs/service.md): when a newly
    # admitted higher-priority job demotes a lower tier at the re-plan,
    # the leader revokes that job's not-yet-started queued sends at
    # each sender — the sender drops the pending (job, dest, layer)
    # pairs (counted on ``jobs.revoked_pairs``) instead of burning the
    # reclaimed link budget on superseded commands.
    # GROUP_PLAN / GROUP_STATUS — hierarchical control
    # (docs/hierarchy.md): the root leader partitions its fleet into
    # groups, each owned by a SUB-LEADER.  GROUP_PLAN (root →
    # sub-leader, epoch-fenced) hands the sub-leader its members'
    # delivery targets — the root plans the flow problem over group
    # INGRESS nodes only, and the sub-leader owns intra-group fan-out;
    # with ``dissolve`` it is instead sent root → member when the
    # sub-leader died, telling the member to re-point its control
    # parent at the root (the group degrades to flat).  GROUP_STATUS
    # (sub-leader → root) is the aggregate upward channel: cumulative
    # member coverage (one message per completed layer instead of one
    # ack per member), member announce inventories, member deaths, and
    # batched member telemetry snapshots — the root handles O(groups)
    # control messages where the flat plane handled O(nodes).
    # JOIN / DRAIN — elastic membership (docs/membership.md): the
    # topology stops being a config constant.  JOIN is four roles in one
    # type, disambiguated by its flags like SWAP_COMMIT: a REQUEST
    # (unconfigured node → leader: admit me — my dialable address and,
    # optionally, the layer ids I want; default = the current goal's
    # layer universe), the ADMIT reply (leader → joiner,
    # ``admitted=True``: your control parent — the root, or a sub-leader
    # when a grouped cluster placed you — re-point and announce there),
    # the ROSTER notice (leader → members, ``admitted=True`` +
    # ``node``/``addr``: a peer joined; register its address so a later
    # plan can command sends to it), and the RE-POINT notice (leader →
    # member, ``parent`` set: your control parent changed — a re-formed
    # group's members move back under their re-admitted sub-leader).
    # DRAIN is the planned-departure verbs: a REQUEST (node → leader:
    # drain me; or operator seat → leader with ``node`` naming the
    # drainer) and the DONE notice (leader → drainer + requester,
    # ``done=True``: your unique holdings are re-homed and you are out
    # of every liveness/lease/announce table — exiting now cannot fire
    # the crash path).
    # ROLLOUT_CTL — SLO-guarded fleet rollout pipeline (docs/rollout.md):
    # the operator channel of a ``kind="rollout"`` job.  A QUERY
    # (operator seat → leader) asks for the rollout table (wave states,
    # SLO verdicts, traffic split); PAUSE/RESUME gate the pipeline's
    # wave commits; ``split`` (>= 0) sets the leader-owned traffic-split
    # knob; the leader's reply carries ``table``.  The rollout RECORDS
    # themselves replicate via ControlDeltaMsg kind "rollout" + the
    # snapshot's Rollouts section — this message is only the operator
    # front door.
    # POLICY_CTL — closed-loop fleet autonomy (docs/autonomy.md): the
    # operator channel of the leader-side policy engine.  A QUERY
    # (operator seat → leader) asks for the policy table (armed rules,
    # cooldowns, quarantine mask, audit trail); ENABLE/DISABLE toggle
    # automatic actioning at runtime (token-gated — dropping a fleet to
    # manual is an operator act); the leader's reply carries ``table``.
    # The policy STATE itself replicates via ControlDeltaMsg kind
    # "policy" + the snapshot's Policy section — this message is only
    # the operator front door.
    HEARTBEAT = 8
    BOOT_READY = 9
    DEVICE_PLAN = 10
    SERVE = 11
    BOOT_HINT = 12
    GENERATE_REQ = 13
    GENERATE_RESP = 14
    PLAN_RESEND_REQ = 15
    LAYER_NACK = 16
    LAYER_DIGESTS = 17
    LEADER_LEASE = 18
    CONTROL_DELTA = 19
    SOURCE_DEAD = 20
    METRICS_REPORT = 21
    TIME_SYNC = 22
    JOB_SUBMIT = 23
    JOB_STATUS = 24
    SWAP_COMMIT = 25
    JOB_REVOKE = 26
    GROUP_PLAN = 27
    GROUP_STATUS = 28
    JOIN = 29
    DRAIN = 30
    ROLLOUT_CTL = 31
    POLICY_CTL = 32


def _epoch_to_payload(payload: dict, epoch: int) -> dict:
    """Stamp the leader EPOCH onto an envelope payload, omitted-field
    style: -1 (HA off / legacy peer) adds nothing, so the wire format is
    byte-identical to the pre-failover one unless HA is armed."""
    if epoch >= 0:
        payload["Epoch"] = int(epoch)
    return payload


@dataclasses.dataclass
class AnnounceMsg:
    """Receiver → leader: my initial layers + metadata (message.go:31-58).

    ``partial`` is an extension the reference doesn't have: covered byte
    ranges of checkpointed in-progress layers,
    ``{layer_id: {"Total": n, "Covered": [[s, e), ...]}}`` — the mode-3
    leader schedules only the gaps (checkpoint/resume).

    ``digests`` (integrity plane, docs/integrity.md): self-describing
    hex digest (``xxh3:<hex>``, or bare blake2b hex)
    per announced full layer, ``{layer_id: hex}`` — the leader collects
    them and stamps each assignee's expected digests
    (``LayerDigestsMsg``) so delivered layers verify end-to-end.
    Advisory and omitted when empty (digests disabled, or the bytes are
    client-held and unreadable here).

    ``codecs`` (docs/codec.md): the wire codecs this node can DECODE
    (and encode-serve) — the capability half of the codec negotiation.
    The leader only ever chooses a quantized transfer for a dest that
    advertised the codec; pre-codec peers announce nothing and interop
    as raw.  Omitted when empty.

    ``nic_bw`` (docs/membership.md): this node's own modeled NIC rate
    in bytes/second — an unconfigured JOINER's announce carries its
    locally configured rate so the mode-3 leader can model the link
    honestly instead of pinning the most conservative configured value
    until an operator re-configures.  0 = unknown, omitted on the wire
    (every pre-membership announce)."""

    src_id: NodeID
    layer_ids: LayerIDs
    partial: dict = dataclasses.field(default_factory=dict)
    digests: dict = dataclasses.field(default_factory=dict)
    codecs: list = dataclasses.field(default_factory=list)
    nic_bw: int = 0

    msg_type = MsgType.ANNOUNCE

    def to_payload(self) -> dict:
        payload = {
            "SrcID": self.src_id,
            "LayerIDs": layer_ids_to_json(self.layer_ids),
        }
        if self.partial:
            payload["Partial"] = {
                str(lid): info for lid, info in self.partial.items()
            }
        if self.digests:
            payload["Digests"] = {
                str(lid): str(d) for lid, d in self.digests.items()
            }
        if self.codecs:
            payload["Codecs"] = [str(c) for c in self.codecs]
        if self.nic_bw:
            payload["NicBw"] = int(self.nic_bw)
        return payload

    @classmethod
    def from_payload(cls, d: dict) -> "AnnounceMsg":
        return cls(
            src_id=int(d["SrcID"]),
            layer_ids=layer_ids_from_json(d.get("LayerIDs") or {}),
            partial={
                int(lid): info for lid, info in (d.get("Partial") or {}).items()
            },
            digests={
                int(lid): str(h)
                for lid, h in (d.get("Digests") or {}).items()
            },
            codecs=[str(c) for c in d.get("Codecs") or []],
            nic_bw=int(d.get("NicBw", 0)),
        )


@dataclasses.dataclass
class AckMsg:
    """Receiver → leader: layer landed (message.go:62-91).

    ``shard`` (docs/sharding.md): the delivered shard spec — a dest
    whose target was a byte-range slice acks at SHARD coverage, and the
    leader records the holding as partial (a shard-holder never
    satisfies a full-layer demand).  "" = whole layer, omitted on the
    wire (legacy format unchanged).

    ``version`` (docs/swap.md): the rollout version the delivered
    layer was stamped with (``LayerDigestsMsg.versions``) — the leader
    records the holding version-qualified, so a v2 swap pair is only
    ever completed by bytes verified under v2, and the swap commit
    fence knows exactly when a replica's v2 set is whole.  "" =
    unversioned (every pre-swap ack), omitted on the wire.

    ``codec`` (docs/codec.md): the wire-codec form the delivered bytes
    are in ("" = canonical) — the leader records the holding
    codec-qualified, so a quantized copy can never be mistaken for (or
    satisfy) a raw demand, and can be re-planned as a SOURCE only for
    same-codec transfers.  Omitted on the wire at default."""

    src_id: NodeID
    layer_id: LayerID
    location: LayerLocation = LayerLocation.INMEM
    shard: str = ""
    version: str = ""
    codec: str = ""
    # Advisory pair-lifecycle span id (docs/observability.md): the span
    # this delivery's receiver-side events filed under, echoed so the
    # leader's ``acked`` event correlates without re-derivation.  ""
    # (every pre-span peer) omits the key — the legacy wire format.
    span_id: str = ""

    msg_type = MsgType.ACK

    def to_payload(self) -> dict:
        payload = {
            "SrcID": self.src_id,
            "LayerID": self.layer_id,
            "Location": int(self.location),
        }
        if self.shard:
            payload["Shard"] = str(self.shard)
        if self.version:
            payload["Version"] = str(self.version)
        if self.codec:
            payload["Codec"] = str(self.codec)
        if self.span_id:
            payload["SpanId"] = str(self.span_id)
        return payload

    @classmethod
    def from_payload(cls, d: dict) -> "AckMsg":
        return cls(
            src_id=int(d["SrcID"]),
            layer_id=int(d["LayerID"]),
            location=LayerLocation(d.get("Location", 0)),
            shard=str(d.get("Shard", "")),
            version=str(d.get("Version", "")),
            codec=str(d.get("Codec", "")),
            span_id=str(d.get("SpanId", "")),
        )


def _job_to_payload(payload: dict, job_id: str) -> dict:
    """Stamp the dissemination-job tag, omitted-field style: the base
    single-run goal ("" — every pre-service run) adds nothing, so the
    wire format is byte-identical unless a job plane is active."""
    if job_id:
        payload["Job"] = str(job_id)
    return payload


@dataclasses.dataclass
class RetransmitMsg:
    """Leader → owner: forward your copy of a layer to dest
    (message.go:94-118).  ``epoch``: the issuing leader's fencing epoch
    (docs/failover.md); -1 = HA off.  ``job_id``: the admitted job this
    forward serves (docs/service.md; "" = the base run).  ``shard``
    (docs/sharding.md): forward only this shard's byte range ("" = the
    whole layer; omitted on the wire — a legacy owner ships the full
    layer, which still covers the target).  ``codec`` (docs/codec.md):
    ship the layer in this wire-codec form (the owner encodes its raw
    copy, or serves an already-encoded same-codec holding verbatim);
    "" = canonical bytes, omitted on the wire."""

    src_id: NodeID
    layer_id: LayerID
    dest_id: NodeID
    epoch: int = -1
    job_id: str = ""
    shard: str = ""
    codec: str = ""

    msg_type = MsgType.RETRANSMIT

    def to_payload(self) -> dict:
        payload = _job_to_payload(_epoch_to_payload(
            {"SrcID": self.src_id, "LayerID": self.layer_id,
             "DestID": self.dest_id}, self.epoch), self.job_id)
        if self.shard:
            payload["Shard"] = str(self.shard)
        if self.codec:
            payload["Codec"] = str(self.codec)
        return payload

    @classmethod
    def from_payload(cls, d: dict) -> "RetransmitMsg":
        return cls(int(d["SrcID"]), int(d["LayerID"]), int(d["DestID"]),
                   int(d.get("Epoch", -1)), str(d.get("Job", "")),
                   str(d.get("Shard", "")), str(d.get("Codec", "")))


@dataclasses.dataclass
class FlowRetransmitMsg:
    """Leader → sender: partial-layer send command with a bandwidth budget
    (message.go:121-151).

    ``codec`` (docs/codec.md): the transfer's wire-codec form — the
    commanded byte range ``[offset, offset+data_size)`` then indexes the
    ENCODED blob (the sender encodes its raw copy once and serves
    ranges of the cached form, or serves a same-codec holding
    verbatim).  "" = canonical bytes, omitted on the wire — a legacy
    peer never sees the key.

    ``gen`` (docs/service.md): the leader plan generation that computed
    this command.  A revoke is keyed to the generation it revoked
    (``JobRevokeMsg.gen``); a replacing re-plan's command carries a
    NEWER generation and therefore survives a stale queued revoke — the
    close of the PR 9 "wrong-eat race".  0 = pre-generation leader,
    omitted on the wire (legacy peers keep the old last-writer-wins
    semantics)."""

    src_id: NodeID
    layer_id: LayerID
    dest_id: NodeID
    data_size: int
    offset: int
    rate: int
    epoch: int = -1
    job_id: str = ""  # the admitted job this send serves ("" = base run)
    codec: str = ""
    gen: int = 0

    msg_type = MsgType.FLOW_RETRANSMIT

    def to_payload(self) -> dict:
        payload = _job_to_payload(_epoch_to_payload({
            "SrcID": self.src_id,
            "LayerID": self.layer_id,
            "DestID": self.dest_id,
            "DataSize": self.data_size,
            "Offset": self.offset,
            "Rate": self.rate,
        }, self.epoch), self.job_id)
        if self.codec:
            payload["Codec"] = str(self.codec)
        if self.gen:
            payload["Gen"] = int(self.gen)
        return payload

    @classmethod
    def from_payload(cls, d: dict) -> "FlowRetransmitMsg":
        return cls(
            int(d["SrcID"]),
            int(d["LayerID"]),
            int(d["DestID"]),
            int(d.get("DataSize", 0)),
            int(d.get("Offset", 0)),
            int(d.get("Rate", 0)),
            int(d.get("Epoch", -1)),
            str(d.get("Job", "")),
            str(d.get("Codec", "")),
            int(d.get("Gen", 0)),
        )


@dataclasses.dataclass
class LayerMsg:
    """A layer (or byte-range of one) in flight (message.go:154-190).

    Never JSON-serialized whole: the transport writes a ``LayerHeader``
    then streams the bytes.  ``total_size`` is the full layer size so a
    receiver can account partial transfers (mode 3).

    ``stripe_idx/stripe_n/stripe_off`` are ADVISORY stripe provenance
    (defaults = un-striped): a TCP sender may split one logical payload
    into N stripes riding N pooled data connections in parallel
    (``transport/tcp.py``); a receiving transport stamps the delivered
    fragment with which stripe it was.  Consumers never need them for
    correctness — each stripe is a well-formed byte-range fragment that
    the existing interval reassembly absorbs — they exist for logs,
    tests, and transport-level regrouping.

    ``crc``/``xxh3`` are the ADVISORY payload checksum (integrity
    plane): at most one is stamped — xxh3-64 where the ``xxhash``
    accelerator is importable, crc32 otherwise; both None means
    unstamped (a sender predating the fields, or ``DLD_WIRE_CRC=0``).
    Transports stamp it per frame on send and verify whichever is
    present on receive BEFORE delivery — consumers above the transport
    only ever see verified fragments.
    """

    src_id: NodeID
    layer_id: LayerID
    layer_src: LayerSrc
    total_size: int
    stripe_idx: int = 0
    stripe_n: int = 1
    stripe_off: int = 0
    crc: Optional[int] = None
    xxh3: Optional[int] = None
    # Dissemination-job tag (docs/service.md): which admitted job this
    # fragment serves ("" = the base run).  Advisory, telemetry-only —
    # the flight recorder splits link rows per job so overlapping jobs
    # stop sharing one undifferentiated counter pool.
    job_id: str = ""
    # Advisory shard-target tag (docs/sharding.md): the shard spec this
    # fragment serves ("" = a full-layer target).  Correctness rides the
    # byte ranges alone (offset/size are absolute layer coordinates
    # either way); the tag exists for logs and telemetry.
    shard: str = ""
    # Wire-codec tag (docs/codec.md): the encoded form this fragment's
    # bytes — and its offset/total coordinates — are in ("" = canonical
    # bytes, the pre-codec wire format).  Advisory like the stamp: the
    # dest's authoritative codec comes from the leader's digest-stamp
    # channel; the tag is the fallback identity when no stamp arrived
    # (digests disabled), so encoded bytes are never stored as raw.
    codec: str = ""
    # Advisory pair-lifecycle span correlation (docs/observability.md):
    # the span id this transfer's events file under, and — for a
    # sub-leader fan-out child — the PARENT span (the root-planned
    # group-ingress pair) the child chains beneath.  Both "" at default
    # and telemetry-only: a dropped tag only costs the receiver its
    # recomputation of the deterministic id.
    span_id: str = ""
    span_parent: str = ""

    msg_type = MsgType.LAYER


@dataclasses.dataclass
class LayerHeader:
    """Data-plane preamble (transport.go:47-54, sans the ``Offert`` typo).

    The ``stripe_*`` fields are ADVISORY and wire-compatible: an
    un-striped transfer omits them entirely (the payload is identical to
    the pre-striping wire format), and a peer that predates them sees
    each stripe as an ordinary byte-range fragment at its absolute
    ``offset`` — the existing fragment reassembly path absorbs it.  For
    striped frames, ``stripe_off`` is the stripe's byte offset WITHIN
    the original logical payload (so ``offset - stripe_off`` recovers
    the payload's base offset), ``stripe_span`` the payload's total
    bytes, and ``stripe_tid`` a sender-unique transfer id that groups
    the stripes of one logical send (a retry re-uses the id, so a
    half-landed stripe is simply overwritten).

    ``crc``/``xxh3`` are the ADVISORY checksum of exactly this frame's
    payload bytes (per stripe for striped transfers), omitted-field
    style like the ``stripe_*`` fields: at most one is stamped (xxh3-64
    where the ``xxhash`` accelerator is importable — ~6x the crc32 rate
    on this host — crc32 otherwise), an unstamped frame is
    byte-identical to the pre-CRC wire format, and a peer that predates
    the fields (or can't compute xxh3) ignores the stamp."""

    src_id: NodeID
    layer_id: LayerID
    layer_size: int
    total_size: int
    offset: int
    stripe_idx: int = 0
    stripe_n: int = 1
    stripe_off: int = 0
    stripe_span: int = 0
    stripe_tid: str = ""
    crc: Optional[int] = None
    xxh3: Optional[int] = None
    # Advisory dissemination-job tag (omitted when ""): lets the
    # receiving transport file this frame's bytes on the per-job link
    # row (docs/service.md).  A peer predating the field ignores it.
    job_id: str = ""
    # Advisory shard-target tag (omitted when ""; docs/sharding.md).
    shard: str = ""
    # Wire-codec tag (omitted when ""; docs/codec.md): the encoded form
    # this frame's payload — and byte coordinates — are in.
    codec: str = ""
    # Advisory span correlation tags (omitted when "";
    # docs/observability.md): the pair-lifecycle span this frame's
    # bytes serve, plus the parent span for sub-leader fan-out children.
    # A peer predating the fields ignores them.
    span_id: str = ""
    span_parent: str = ""

    def to_payload(self) -> dict:
        payload = {
            "SrcID": self.src_id,
            "LayerID": self.layer_id,
            "LayerSize": self.layer_size,
            "TotalSize": self.total_size,
            "Offset": self.offset,
        }
        if self.stripe_n > 1:
            payload["StripeIdx"] = self.stripe_idx
            payload["StripeN"] = self.stripe_n
            payload["StripeOff"] = self.stripe_off
            payload["StripeSpan"] = self.stripe_span
            payload["StripeTid"] = self.stripe_tid
        if self.crc is not None:
            payload["Crc"] = int(self.crc)
        if self.xxh3 is not None:
            payload["Xxh3"] = int(self.xxh3)
        if self.job_id:
            payload["Job"] = str(self.job_id)
        if self.shard:
            payload["Shard"] = str(self.shard)
        if self.codec:
            payload["Codec"] = str(self.codec)
        if self.span_id:
            payload["SpanId"] = str(self.span_id)
        if self.span_parent:
            payload["SpanParent"] = str(self.span_parent)
        return payload

    @classmethod
    def from_payload(cls, d: dict) -> "LayerHeader":
        return cls(
            int(d["SrcID"]),
            int(d["LayerID"]),
            int(d["LayerSize"]),
            int(d.get("TotalSize", 0)),
            int(d.get("Offset", 0)),
            int(d.get("StripeIdx", 0)),
            int(d.get("StripeN", 1)),
            int(d.get("StripeOff", 0)),
            int(d.get("StripeSpan", 0)),
            str(d.get("StripeTid", "")),
            int(d["Crc"]) if "Crc" in d else None,
            int(d["Xxh3"]) if "Xxh3" in d else None,
            str(d.get("Job", "")),
            str(d.get("Shard", "")),
            str(d.get("Codec", "")),
            str(d.get("SpanId", "")),
            str(d.get("SpanParent", "")),
        )


@dataclasses.dataclass
class ClientReqMsg:
    """Node → external client: stream me a layer (message.go:193-214)."""

    src_id: NodeID
    layer_id: LayerID
    save_disk: bool = False

    msg_type = MsgType.CLIENT_REQ

    def to_payload(self) -> dict:
        return {
            "SrcID": self.src_id,
            "LayerID": self.layer_id,
            "SaveDisk": self.save_disk,
        }

    @classmethod
    def from_payload(cls, d: dict) -> "ClientReqMsg":
        return cls(int(d["SrcID"]), int(d["LayerID"]), bool(d.get("SaveDisk", False)))


@dataclasses.dataclass
class StartupMsg:
    """Leader → all: assignment satisfied, boot the inference engine
    (message.go:217-241).  ``boot`` carries the LEADER's boot decision so
    one flag governs the whole run — a receiver can't be left booting (or
    skipping) while the leader expects the opposite."""

    src_id: NodeID
    boot: bool = True
    # Multi-controller serving will follow (a ServeMsg after all boots):
    # receivers must stay alive past ready() to enter the collective.
    serve: bool = False
    epoch: int = -1

    msg_type = MsgType.STARTUP

    def to_payload(self) -> dict:
        return _epoch_to_payload(
            {"SrcID": self.src_id, "Boot": self.boot, "Serve": self.serve},
            self.epoch)

    @classmethod
    def from_payload(cls, d: dict) -> "StartupMsg":
        return cls(int(d["SrcID"]), bool(d.get("Boot", True)),
                   bool(d.get("Serve", False)), int(d.get("Epoch", -1)))


@dataclasses.dataclass
class SimpleMsg:
    """Free-form test message (message.go:244-270)."""

    src_addr: str
    payload_str: str

    msg_type = MsgType.SIMPLE

    def to_payload(self) -> dict:
        return {"SrcAddr": self.src_addr, "PayloadStr": self.payload_str}

    @classmethod
    def from_payload(cls, d: dict) -> "SimpleMsg":
        return cls(d.get("SrcAddr", ""), d.get("PayloadStr", ""))


@dataclasses.dataclass
class HeartbeatMsg:
    """Receiver → leader: I'm alive.  Extension beyond the reference
    (its failure handling is explicitly TODO, node.go:218-220)."""

    src_id: NodeID

    msg_type = MsgType.HEARTBEAT

    def to_payload(self) -> dict:
        return {"SrcID": self.src_id}

    @classmethod
    def from_payload(cls, d: dict) -> "HeartbeatMsg":
        return cls(int(d["SrcID"]))


@dataclasses.dataclass
class BootReadyMsg:
    """Receiver → leader: model (or pipeline stage) booted from the
    delivered layers.  ``seconds`` is the receiver's blob-assembly +
    compile + first-forward wall time; ``kind`` is "full" or "stage"."""

    src_id: NodeID
    seconds: float = 0.0
    kind: str = ""

    msg_type = MsgType.BOOT_READY

    def to_payload(self) -> dict:
        return {"SrcID": self.src_id, "Seconds": self.seconds, "Kind": self.kind}

    @classmethod
    def from_payload(cls, d: dict) -> "BootReadyMsg":
        return cls(int(d["SrcID"]), float(d.get("Seconds", 0.0)),
                   str(d.get("Kind", "")))


@dataclasses.dataclass
class BootHintMsg:
    """Leader → assignee, sent when distribution starts: the blob ids
    this dest's Assignment will deliver.  Purely advisory — the receiver
    uses it to lower + compile its boot programs (decode jits, the
    forward) on a background thread while the layer bytes are still in
    flight, so the post-startup boot hits warm caches and TTFT shrinks
    by the compile time.  Shapes are all XLA needs; the weights aren't.
    Losing or ignoring the hint costs nothing but the overlap."""

    src_id: NodeID
    blob_ids: list  # the dest's assigned blob ids
    epoch: int = -1

    msg_type = MsgType.BOOT_HINT

    def to_payload(self) -> dict:
        return _epoch_to_payload(
            {"SrcID": self.src_id,
             "BlobIDs": [int(b) for b in self.blob_ids]}, self.epoch)

    @classmethod
    def from_payload(cls, d: dict) -> "BootHintMsg":
        return cls(int(d["SrcID"]),
                   [int(b) for b in d.get("BlobIDs") or []],
                   int(d.get("Epoch", -1)))


@dataclasses.dataclass
class GenerateReqMsg:
    """Requester → booted node: decode ``max_new`` tokens after
    ``prompt`` (token ids) with the node's resident params and answer
    with a ``GenerateRespMsg`` echoing ``req_id``.  ``temperature`` 0 is
    greedy (deterministic); > 0 samples with ``seed`` (the same seed
    reproduces the same tokens).  ``src_id`` must be addressable by the
    serving node's transport (a topology node id, or the client role's
    id)."""

    src_id: NodeID
    req_id: int
    prompt: list  # token ids
    max_new: int
    temperature: float = 0.0
    seed: int = 0

    msg_type = MsgType.GENERATE_REQ

    def to_payload(self) -> dict:
        return {"SrcID": self.src_id, "ReqID": self.req_id,
                "Prompt": [int(t) for t in self.prompt],
                "MaxNew": self.max_new,
                "Temperature": self.temperature, "Seed": self.seed}

    @classmethod
    def from_payload(cls, d: dict) -> "GenerateReqMsg":
        return cls(int(d["SrcID"]), int(d["ReqID"]),
                   [int(t) for t in d.get("Prompt") or []],
                   int(d.get("MaxNew", 0)),
                   float(d.get("Temperature", 0.0)),
                   int(d.get("Seed", 0)))


@dataclasses.dataclass
class GenerateRespMsg:
    """Booted node → requester: the decoded token ids (or why not)."""

    src_id: NodeID
    req_id: int
    tokens: list = dataclasses.field(default_factory=list)
    error: str = ""

    msg_type = MsgType.GENERATE_RESP

    def to_payload(self) -> dict:
        return {"SrcID": self.src_id, "ReqID": self.req_id,
                "Tokens": [int(t) for t in self.tokens],
                "Error": self.error}

    @classmethod
    def from_payload(cls, d: dict) -> "GenerateRespMsg":
        return cls(int(d["SrcID"]), int(d["ReqID"]),
                   [int(t) for t in d.get("Tokens") or []],
                   str(d.get("Error", "")))


@dataclasses.dataclass
class ServeMsg:
    """Leader → all (multi-controller SPMD): the stage boots partition
    the model — every ``members`` process must now enter the SAME
    serving collective (``runtime/pp_serve.py``) with its resident stage
    weights: one pipelined forward, or (``gen`` > 0) a KV-cached greedy
    decode of ``gen`` tokens.  ``counts`` carries each member's stage
    depth (aligned with ``members``) so uneven partitions assemble
    identically on every process.  Non-members ignore it."""

    src_id: NodeID
    members: list  # stage-ordered node ids
    batch: int = 1
    seq_len: int = 16
    counts: list = dataclasses.field(default_factory=list)
    gen: int = 0  # >0: decode this many tokens instead of one forward
    epoch: int = -1

    msg_type = MsgType.SERVE

    def to_payload(self) -> dict:
        return _epoch_to_payload(
            {"SrcID": self.src_id,
             "Members": [int(m) for m in self.members],
             "Batch": self.batch, "SeqLen": self.seq_len,
             "Counts": [int(c) for c in self.counts],
             "Gen": self.gen}, self.epoch)

    @classmethod
    def from_payload(cls, d: dict) -> "ServeMsg":
        return cls(int(d["SrcID"]),
                   [int(m) for m in d.get("Members") or []],
                   int(d.get("Batch", 1)), int(d.get("SeqLen", 16)),
                   [int(c) for c in d.get("Counts") or []],
                   int(d.get("Gen", 0)), int(d.get("Epoch", -1)))


@dataclasses.dataclass
class DevicePlanMsg:
    """Leader → fabric participants: execute one layer transfer on the
    device data plane (``parallel/fabric.py``).

    ``layout`` is the plan's per-sender byte-range split,
    ``[(sender_id, offset, size), ...]`` — the same shape as a mode-3
    flow schedule's jobs (flow.go:193-211); modes 0-2 send a one-element
    layout (a single full-layer source).  Each listed sender uploads its
    range onto its own stage devices and publishes it under ``plan_id``;
    ``dest_id`` ingests every contribution over the fabric and acks.  The
    layer bytes themselves never touch the transport."""

    src_id: NodeID
    plan_id: str
    layer_id: LayerID
    dest_id: NodeID
    total_size: int
    layout: list  # [(sender_id, offset, size), ...]
    # Global plan order for the multi-controller SPMD fabric
    # (parallel/spmd_fabric.py): every process must enter the same
    # collective programs in the same order, so plans execute strictly by
    # seq.  An EMPTY layout with a seq is a cancellation — the leader
    # aborted dispatch mid-way and every process must advance past the
    # seq without entering a collective.  -1 = unordered (the in-process
    # FabricPlane ignores it).
    seq: int = -1
    # Plan batching (advisory): the leader groups same-dest, same-size
    # plans and stamps each member with one batch id + the member count;
    # the dest then finishes the whole group as ONE batched gather
    # (parallel.ingest.finalize_many) instead of N serial collectives.
    # Empty/1 = unbatched; receivers that predate the hint ignore it.
    batch_id: str = ""
    batch_n: int = 1
    # Pod-delivery gather (advisory, docs/fabric.md): the plan is the
    # on-mesh RECONSTRUCTION of a pod's NIC-delivered shards — every
    # node listed here keeps the gathered layer (not just ``dest_id``,
    # which is the lowest-id member, kept for legacy addressing).
    # Empty = a plain single-dest plan, omitted on the wire.
    pod: list = dataclasses.field(default_factory=list)
    epoch: int = -1

    msg_type = MsgType.DEVICE_PLAN

    def to_payload(self) -> dict:
        payload = {
            "SrcID": self.src_id,
            "PlanID": self.plan_id,
            "LayerID": self.layer_id,
            "DestID": self.dest_id,
            "TotalSize": self.total_size,
            "Layout": [[int(s), int(o), int(z)] for s, o, z in self.layout],
            "Seq": self.seq,
        }
        if self.batch_id:
            payload["BatchID"] = self.batch_id
            payload["BatchN"] = self.batch_n
        if self.pod:
            payload["Pod"] = [int(n) for n in self.pod]
        return _epoch_to_payload(payload, self.epoch)

    @classmethod
    def from_payload(cls, d: dict) -> "DevicePlanMsg":
        return cls(
            int(d["SrcID"]),
            str(d["PlanID"]),
            int(d["LayerID"]),
            int(d["DestID"]),
            int(d.get("TotalSize", 0)),
            [(int(s), int(o), int(z)) for s, o, z in d.get("Layout") or []],
            int(d.get("Seq", -1)),
            str(d.get("BatchID", "")),
            int(d.get("BatchN", 1)),
            [int(n) for n in d.get("Pod") or []],
            int(d.get("Epoch", -1)),
        )


@dataclasses.dataclass
class PlanResendReqMsg:
    """Fabric process → leader: my SPMD executor is stalled on a seq gap
    — re-send (or cancel) these plan seqs.  See MsgType.PLAN_RESEND_REQ."""

    src_id: NodeID
    seqs: list  # missing plan sequence numbers, ascending

    msg_type = MsgType.PLAN_RESEND_REQ

    def to_payload(self) -> dict:
        return {"SrcID": self.src_id, "Seqs": [int(s) for s in self.seqs]}

    @classmethod
    def from_payload(cls, d: dict) -> "PlanResendReqMsg":
        return cls(int(d["SrcID"]), [int(s) for s in d.get("Seqs") or []])


@dataclasses.dataclass
class LayerNackMsg:
    """Receiver → fragment source: the byte range ``[offset,
    offset+size)`` of ``layer_id`` arrived CORRUPT (advisory CRC
    mismatch) — or was abandoned mid-transfer (a TTL-pruned stripe
    group) — and was dropped before any accounting; please retransmit
    it.  ``src_id`` is the NACKing receiver (the retransmit's dest).
    Handled by every node that serves layers (leaders, retransmit
    receivers) with a bounded per-(dest, layer, range) retry budget —
    a persistently corrupt path must fail loudly, not livelock.

    ``codec`` (docs/codec.md): the wire-codec form of the transfer the
    NACK belongs to — offset/size/total then index the ENCODED blob,
    and the serving holder retransmits ranges of its cached encoded
    form.  "" = canonical bytes, omitted on the wire."""

    src_id: NodeID
    layer_id: LayerID
    offset: int
    size: int
    total_size: int = 0
    reason: str = "crc"  # "crc" | "drop" | "stale" | "digest"
    codec: str = ""

    msg_type = MsgType.LAYER_NACK

    def to_payload(self) -> dict:
        payload = {"SrcID": self.src_id, "LayerID": self.layer_id,
                   "Offset": self.offset, "Size": self.size,
                   "TotalSize": self.total_size, "Reason": self.reason}
        if self.codec:
            payload["Codec"] = str(self.codec)
        return payload

    @classmethod
    def from_payload(cls, d: dict) -> "LayerNackMsg":
        return cls(int(d["SrcID"]), int(d["LayerID"]),
                   int(d.get("Offset", 0)), int(d.get("Size", 0)),
                   int(d.get("TotalSize", 0)),
                   str(d.get("Reason", "crc")),
                   str(d.get("Codec", "")))


@dataclasses.dataclass
class LayerDigestsMsg:
    """Leader → assignee: the expected self-describing digest of each
    layer this
    dest will receive, ``{layer_id: hex}`` (collected from the holders'
    announces + the leader's own layers).  Advisory: a receiver verifies
    a completed layer against the digest BEFORE acking/staging it, and a
    mismatch re-opens the covered intervals (the layer is re-fetched)
    instead of acking corrupt bytes.  Layers without a digest (unstamped
    holder, digests disabled) verify by per-fragment CRC alone.

    Sharded targets (docs/sharding.md) ride this stamp too — it is the
    one leader→dest channel that precedes the bytes:

    - ``shards``: ``{layer_id: shard_spec}`` — the dest's target is
      THIS byte-range slice; its interval set is complete (and it acks)
      at shard coverage, not layer coverage.
    - ``range_digests``: ``{layer_id: hex}`` — the digest of exactly
      the dest's shard range, so a shard verifies end-to-end WITHOUT
      holding the full layer.  Stamped only when the leader can read
      the layer's bytes; absent, the shard verifies by per-fragment
      CRC alone (honest limit, docs/sharding.md).

    Versioned rollout targets (docs/swap.md) ride the stamp the same
    way: ``versions`` — ``{layer_id: version}`` — tells the dest which
    rollout version each assigned layer belongs to, so its ack (and
    its stored holding) carries the tag and the leader's swap fence
    can tell a v2 delivery from a stale copy under the same id.

    Wire-codec transfers (docs/codec.md) ride it too — the codec
    choice must precede the bytes: ``codecs`` — ``{layer_id: codec}``
    — tells the dest which encoded form each assigned layer will
    arrive in (interval accounting, journal, and NACK ranges then live
    in ENCODED byte space), and for those layers the ``digests`` entry
    is the CODEC-QUALIFIED digest — the hash of exactly the encoded
    bytes — so a quantized copy verifies (and acks) under its own byte
    identity and can never silently pass as a raw one.

    Fabric-assisted pod delivery (docs/fabric.md) rides the stamp the
    same way: ``pods`` — ``{layer_id: n}`` — tells the dest its shard
    target for the layer is one slice of an ``n``-way POD split (its
    rank is the ``@K`` of its shard spec); after per-range verification
    it feeds the shard into the on-mesh reconstruction and acks the
    FULL layer once the gathered tree verifies against the stamped
    full-layer (wire-form) digest, instead of acking at shard coverage.

    Content-delta transfers (docs/codec.md) stamp their base INSIDE
    the codec string — ``codecs[lid] = "delta:<base_digest_hex>"`` — so
    the choice, the byte space, and the base can never skew apart; the
    ``digests`` entry is then the digest of the encoded DELTA stream,
    and ``full_digests`` — ``{layer_id: hex}`` — carries the digest of
    the full RECONSTRUCTED form, which the dest verifies after applying
    the delta to its held base (and which its raw holding then vouches
    under).  Omitted for every non-delta layer.

    All omitted-at-default: an unsharded, unversioned, un-codec'd,
    un-pod run's stamp is byte-identical to the legacy format."""

    src_id: NodeID
    digests: dict  # {layer_id: hex digest}
    epoch: int = -1
    shards: dict = dataclasses.field(default_factory=dict)
    range_digests: dict = dataclasses.field(default_factory=dict)
    versions: dict = dataclasses.field(default_factory=dict)
    codecs: dict = dataclasses.field(default_factory=dict)
    pods: dict = dataclasses.field(default_factory=dict)
    full_digests: dict = dataclasses.field(default_factory=dict)

    msg_type = MsgType.LAYER_DIGESTS

    def to_payload(self) -> dict:
        payload = {"SrcID": self.src_id,
                   "Digests": {str(lid): str(h)
                               for lid, h in self.digests.items()}}
        if self.shards:
            payload["Shards"] = {str(lid): str(s)
                                 for lid, s in self.shards.items()}
        if self.range_digests:
            payload["RangeDigests"] = {
                str(lid): str(h)
                for lid, h in self.range_digests.items()}
        if self.versions:
            payload["Versions"] = {str(lid): str(v)
                                   for lid, v in self.versions.items()}
        if self.codecs:
            payload["WireCodecs"] = {str(lid): str(c)
                                     for lid, c in self.codecs.items()}
        if self.pods:
            payload["Pods"] = {str(lid): int(n)
                               for lid, n in self.pods.items()}
        if self.full_digests:
            payload["FullDigests"] = {
                str(lid): str(h)
                for lid, h in self.full_digests.items()}
        return _epoch_to_payload(payload, self.epoch)

    @classmethod
    def from_payload(cls, d: dict) -> "LayerDigestsMsg":
        return cls(int(d["SrcID"]),
                   {int(lid): str(h)
                    for lid, h in (d.get("Digests") or {}).items()},
                   int(d.get("Epoch", -1)),
                   {int(lid): str(s)
                    for lid, s in (d.get("Shards") or {}).items()},
                   {int(lid): str(h)
                    for lid, h in (d.get("RangeDigests") or {}).items()},
                   {int(lid): str(v)
                    for lid, v in (d.get("Versions") or {}).items()},
                   {int(lid): str(c)
                    for lid, c in (d.get("WireCodecs") or {}).items()},
                   {int(lid): int(n)
                    for lid, n in (d.get("Pods") or {}).items()},
                   {int(lid): str(h)
                    for lid, h in (d.get("FullDigests") or {}).items()})


@dataclasses.dataclass
class LeaderLeaseMsg:
    """Leader → all: liveness lease + the fencing EPOCH + the ordered
    standby succession (docs/failover.md).  Standbys and workers feed it
    to a ``FailureDetector``; a lease at a HIGHER epoch from a different
    node is a completed takeover (workers re-point their leader and
    re-announce), and any control message below the highest epoch seen
    is fenced — a zombie ex-leader's plans are rejected, not raced.
    ``interval`` is the sender's advisory beacon period (receivers size
    their expiry off it when they have no config of their own)."""

    src_id: NodeID
    epoch: int
    standbys: list = dataclasses.field(default_factory=list)
    interval: float = 0.0

    msg_type = MsgType.LEADER_LEASE

    def to_payload(self) -> dict:
        return {"SrcID": self.src_id, "Epoch": int(self.epoch),
                "Standbys": [int(s) for s in self.standbys],
                "Interval": float(self.interval)}

    @classmethod
    def from_payload(cls, d: dict) -> "LeaderLeaseMsg":
        return cls(int(d["SrcID"]), int(d.get("Epoch", 0)),
                   [int(s) for s in d.get("Standbys") or []],
                   float(d.get("Interval", 0.0)))


@dataclasses.dataclass
class ControlDeltaMsg:
    """Leader → standby: one epoch-stamped control-state delta (or a
    full ``snapshot``), applied to the standby's shadow leader state
    (``runtime/failover.ShadowLeaderState``).  ``kind`` names the
    mutation ("snapshot" | "status" | "ack" | "partial" | "crash" |
    "assignment" | "digests" | "startup" | "plan_seq" | "revive" |
    "metrics" | "base_assignment" | "job" | "job_done" — the last two
    carry the dissemination service's admitted-job records,
    docs/service.md — | "swap" | "rollout", the live-swap and
    rollout-pipeline records, docs/swap.md + docs/rollout.md — |
    "policy", the autonomy engine's full state REPLACE — armed rules,
    cooldowns, quarantine mask, in-flight actions — docs/autonomy.md);
    ``data`` is the
    kind-specific JSON payload; ``seq`` is a per-leader monotonic
    counter (diagnostics — the shadow is reconciliation-corrected at
    takeover, so ordering races only cost re-sent bytes, never
    correctness)."""

    src_id: NodeID
    epoch: int
    seq: int
    kind: str
    data: dict = dataclasses.field(default_factory=dict)

    msg_type = MsgType.CONTROL_DELTA

    def to_payload(self) -> dict:
        return {"SrcID": self.src_id, "Epoch": int(self.epoch),
                "Seq": int(self.seq), "Kind": self.kind,
                "Data": self.data}

    @classmethod
    def from_payload(cls, d: dict) -> "ControlDeltaMsg":
        return cls(int(d["SrcID"]), int(d.get("Epoch", 0)),
                   int(d.get("Seq", 0)), str(d.get("Kind", "")),
                   dict(d.get("Data") or {}))


@dataclasses.dataclass
class SourceDeadMsg:
    """Leader → dest (mode 3): the source ``dead_id`` of an in-flight
    transfer of ``layer_id`` was declared crashed.  The dest must NACK
    its UNCOVERED byte ranges of the layer to the surviving holder
    ``alt_id`` (the PR-4 ``LayerNackMsg`` byte-range retransmit plane) —
    recovery then costs exactly the dead source's unsent bytes instead
    of a whole-layer re-send (docs/failover.md)."""

    src_id: NodeID
    layer_id: LayerID
    dead_id: NodeID
    alt_id: NodeID
    epoch: int = -1

    msg_type = MsgType.SOURCE_DEAD

    def to_payload(self) -> dict:
        return _epoch_to_payload(
            {"SrcID": self.src_id, "LayerID": self.layer_id,
             "DeadID": self.dead_id, "AltID": self.alt_id}, self.epoch)

    @classmethod
    def from_payload(cls, d: dict) -> "SourceDeadMsg":
        return cls(int(d["SrcID"]), int(d["LayerID"]), int(d["DeadID"]),
                   int(d["AltID"]), int(d.get("Epoch", -1)))


@dataclasses.dataclass
class MetricsReportMsg:
    """Node → leader: one run-scoped telemetry snapshot (docs/
    observability.md).  ``counters``/``gauges`` are flat name→number
    maps; ``links`` is ``{"src->dest": {field: number}}`` — the node's
    view of each link it touched (``utils/telemetry.py`` owns the field
    vocabulary and the rx/tx ownership split the leader folds by).
    Snapshots are CUMULATIVE for the run (the registry is run-scoped),
    so the leader's fold is replace-per-node — a lost report costs
    staleness, never skew, and a freshly promoted leader reconstructs
    the whole table from one report round.  ``epoch``: the leader epoch
    this reporter believes in (-1 = HA off); a failed-over leader fences
    reports from nodes still pointing at its dead predecessor."""

    src_id: NodeID
    counters: dict = dataclasses.field(default_factory=dict)
    gauges: dict = dataclasses.field(default_factory=dict)
    links: dict = dataclasses.field(default_factory=dict)
    t_wall_ms: float = 0.0
    epoch: int = -1
    # The reporter's process token (telemetry.PROC_TOKEN): co-resident
    # nodes share one registry, so the cluster counter fold counts one
    # snapshot per distinct token, not per node.  Omitted-field
    # compatible ("" = legacy reporter, counted per node).
    proc: str = ""
    # Fixed-bucket histograms (utils/telemetry.HIST_BUCKETS_MS):
    # ``{name: {"buckets": [...], "sum_ms": float, "n": int}}``.  Added
    # for the rollout pipeline's SLO guard (docs/rollout.md) — the
    # leader computes per-replica p99 serve latency from the shipped
    # buckets.  Omitted when empty (every pre-rollout reporter).
    hists: dict = dataclasses.field(default_factory=dict)
    # Pair-lifecycle span events (docs/observability.md): the node's
    # bounded span ring, cumulative like every other section — the
    # leader's fold is replace-per-node.  Omitted when empty (spans
    # disabled, or a pre-span reporter).
    spans: list = dataclasses.field(default_factory=list)
    # Advisory locally-detected health events (docs/observability.md):
    # a reporter MAY surface anomaly events for the leader's fleet
    # health timeline to ingest verbatim.  Nothing in this repo
    # populates it from plain receivers today — the timeline is
    # leader-derived — but the section rides the wire so aggregating
    # seats can.  Omitted when empty.
    health: list = dataclasses.field(default_factory=list)

    msg_type = MsgType.METRICS_REPORT

    def to_payload(self) -> dict:
        payload: dict = {"SrcID": self.src_id}
        if self.proc:
            payload["Proc"] = str(self.proc)
        if self.counters:
            payload["Counters"] = {str(k): int(v)
                                   for k, v in self.counters.items()}
        if self.gauges:
            payload["Gauges"] = {str(k): float(v)
                                 for k, v in self.gauges.items()}
        if self.links:
            payload["Links"] = {
                str(k): {str(f): v for f, v in row.items()}
                for k, row in self.links.items()
            }
        if self.hists:
            payload["Hists"] = {str(k): dict(h)
                                for k, h in self.hists.items()}
        if self.spans:
            payload["Spans"] = [dict(ev) for ev in self.spans]
        if self.health:
            payload["Health"] = [dict(ev) for ev in self.health]
        if self.t_wall_ms:
            payload["T"] = float(self.t_wall_ms)
        return _epoch_to_payload(payload, self.epoch)

    @classmethod
    def from_payload(cls, d: dict) -> "MetricsReportMsg":
        return cls(
            int(d["SrcID"]),
            {str(k): int(v)
             for k, v in (d.get("Counters") or {}).items()},
            {str(k): float(v)
             for k, v in (d.get("Gauges") or {}).items()},
            {str(k): dict(row)
             for k, row in (d.get("Links") or {}).items()},
            float(d.get("T", 0.0)),
            int(d.get("Epoch", -1)),
            str(d.get("Proc", "")),
            {str(k): dict(h) for k, h in (d.get("Hists") or {}).items()},
            [dict(ev) for ev in d.get("Spans") or []],
            [dict(ev) for ev in d.get("Health") or []],
        )


@dataclasses.dataclass
class TimeSyncMsg:
    """Clock-offset probe (docs/observability.md).  Request: a node
    sends its wall clock as ``t0_ms``.  Response (``reply=True``): the
    leader echoes ``t0_ms`` and stamps its own wall clock as ``t1_ms``;
    the requester, reading its clock again as t2, estimates
    ``offset = t1 - (t0 + t2) / 2`` — the leader-minus-me clock offset,
    assuming a symmetric path (the error bound is rtt/2, logged next to
    the estimate).  Purely advisory: nothing in the protocol consumes
    the offset; it exists so the LOGS carry enough to align multi-host
    trace timelines offline (cli/trace.py)."""

    src_id: NodeID
    t0_ms: float
    t1_ms: float = 0.0
    reply: bool = False

    msg_type = MsgType.TIME_SYNC

    def to_payload(self) -> dict:
        payload = {"SrcID": self.src_id, "T0": float(self.t0_ms)}
        if self.reply:
            payload["T1"] = float(self.t1_ms)
            payload["Reply"] = True
        return payload

    @classmethod
    def from_payload(cls, d: dict) -> "TimeSyncMsg":
        return cls(int(d["SrcID"]), float(d.get("T0", 0.0)),
                   float(d.get("T1", 0.0)), bool(d.get("Reply", False)))


@dataclasses.dataclass
class JobSubmitMsg:
    """Submitter → leader daemon: admit one dissemination job
    (docs/service.md).  ``assignment`` is the job's goal state (the
    single-run ``Assignment`` vocabulary — dest → layers it must end up
    holding); ``priority`` (higher preempts) and ``kind`` ("push" |
    "repair" | "ab" | ...) drive scheduling and reporting; ``digests``
    optionally names each layer's content stamp (``xxh3:<hex>``) so the
    content-addressed store ships only layers whose digest changed.
    Idempotent per ``job_id``: a retried submit returns the existing
    job's status.  The leader answers with a ``JobStatusMsg``.

    ``version``/``swap_base`` (docs/swap.md): a ``kind="swap"`` job
    names the rollout version it delivers and the blob-id base of the
    v2 set — v2 blob ``swap_base + slot`` carries model slot ``slot``,
    so the commit-time flip can map staged ids back to model blobs.

    ``auth`` (docs/service.md, admission control): the shared-secret
    job token.  A leader started with ``DLD_JOB_TOKEN`` set rejects
    (and counts) any submit whose token does not constant-time-compare
    equal; omitted on the wire when empty, so open clusters keep the
    legacy format.

    ``waves``/``slo``/``split`` (docs/rollout.md): a ``kind="rollout"``
    submission declares its staged wave plan — ``waves`` is an ordered
    list of replica-id subsets (canary first), ``slo`` the guard
    (``{"P99Ms": float, "MaxFailures": int, "SoakS": float}``), and
    ``split`` the initial traffic-split knob value.  All omitted at
    default: every pre-rollout submit keeps the legacy format."""

    src_id: NodeID
    job_id: str
    assignment: dict  # Assignment: {dest: {layer_id: LayerMeta}}
    priority: int = 0
    kind: str = "push"
    digests: dict = dataclasses.field(default_factory=dict)
    avoid: list = dataclasses.field(default_factory=list)
    epoch: int = -1
    version: str = ""
    swap_base: int = -1
    auth: str = ""
    waves: list = dataclasses.field(default_factory=list)
    slo: dict = dataclasses.field(default_factory=dict)
    # -1 = unset (the driver applies its default); an EXPLICIT 0.0 is
    # a real operator choice (no eligible v2 traffic during soak) and
    # must ride the wire, so the sentinel mirrors RolloutCtlMsg.split.
    split: float = -1.0

    msg_type = MsgType.JOB_SUBMIT

    def to_payload(self) -> dict:
        payload = {
            "SrcID": self.src_id,
            "JobID": str(self.job_id),
            "Assignment": {str(n): layer_ids_to_json(r)
                           for n, r in self.assignment.items()},
        }
        if self.priority:
            payload["Priority"] = int(self.priority)
        if self.kind and self.kind != "push":
            payload["Kind"] = str(self.kind)
        if self.digests:
            payload["Digests"] = {str(l): str(d)
                                  for l, d in self.digests.items()}
        if self.avoid:
            payload["Avoid"] = [int(n) for n in self.avoid]
        if self.version:
            payload["Version"] = str(self.version)
        if self.swap_base >= 0:
            payload["SwapBase"] = int(self.swap_base)
        if self.auth:
            payload["Auth"] = str(self.auth)
        if self.waves:
            payload["Waves"] = [[int(n) for n in w] for w in self.waves]
        if self.slo:
            payload["SLO"] = dict(self.slo)
        if self.split >= 0:
            payload["Split"] = float(self.split)
        return _epoch_to_payload(payload, self.epoch)

    @classmethod
    def from_payload(cls, d: dict) -> "JobSubmitMsg":
        return cls(
            int(d["SrcID"]),
            str(d["JobID"]),
            {int(n): layer_ids_from_json(r or {})
             for n, r in (d.get("Assignment") or {}).items()},
            int(d.get("Priority", 0)),
            str(d.get("Kind", "push")),
            {int(l): str(h) for l, h in (d.get("Digests") or {}).items()},
            [int(n) for n in d.get("Avoid") or []],
            int(d.get("Epoch", -1)),
            str(d.get("Version", "")),
            int(d.get("SwapBase", -1)),
            str(d.get("Auth", "")),
            [[int(n) for n in w] for w in d.get("Waves") or []],
            dict(d.get("SLO") or {}),
            float(d.get("Split", -1.0)),
        )


@dataclasses.dataclass
class JobStatusMsg:
    """Job-table query/response (docs/service.md).  ``query=True`` asks
    the leader for the full admitted-job table; the response carries
    ``jobs`` — ``{job_id: summary}`` rows (``sched.jobs.Job.summary``:
    state, priority, remaining/total pairs, drop counts).  Also the
    leader's acknowledgement of a ``JobSubmitMsg`` (one row)."""

    src_id: NodeID
    jobs: dict = dataclasses.field(default_factory=dict)
    query: bool = False
    error: str = ""
    epoch: int = -1

    msg_type = MsgType.JOB_STATUS

    def to_payload(self) -> dict:
        payload: dict = {"SrcID": self.src_id}
        if self.query:
            payload["Query"] = True
        if self.jobs:
            payload["Jobs"] = {str(j): dict(row)
                               for j, row in self.jobs.items()}
        if self.error:
            payload["Error"] = str(self.error)
        return _epoch_to_payload(payload, self.epoch)

    @classmethod
    def from_payload(cls, d: dict) -> "JobStatusMsg":
        return cls(
            int(d["SrcID"]),
            {str(j): dict(row)
             for j, row in (d.get("Jobs") or {}).items()},
            bool(d.get("Query", False)),
            str(d.get("Error", "")),
            int(d.get("Epoch", -1)),
        )


@dataclasses.dataclass
class SwapCommitMsg:
    """The zero-downtime weight-swap fence (docs/swap.md) — one message
    type, four protocol roles, disambiguated by its flags:

    - **commit** (leader → serving node; no flags): every v2 layer of
      ``version`` verified on every replica — atomically flip the
      serving params to the staged v2 set (mapped ``blob = id -
      swap_base``) between decode steps, then release v1.  The leader
      re-sends an unconfirmed commit on a bounded watchdog, so a lost
      fence is re-delivered instead of leaving one node serving v1.
    - **prepare** (leader → serving node; ``prepare=True``, sent at
      swap-job admission): the version + blob mapping announcement —
      the node stages each v2 layer the moment it verifies, so the
      decode/device work overlaps the rollout's remaining transfers
      and the later flip is (headroom permitting) a pure pointer swap.
      Advisory: a lost prepare only costs the overlap — the commit
      carries the same mapping.
    - **abort** (leader → serving node; ``abort=True``): the rollout
      failed (digest mismatch, dest crash) — do NOT flip; release the
      staged v2 set and keep serving v1 uninterrupted.
    - **confirm** (node → leader; ``applied=True``): the flip (or the
      abort release) completed on this node.
    - **query** (node → leader; ``query=True``): this node staged its
      full v2 set but never saw the fence (it suspects a lost commit)
      — the leader answers with the operative commit/abort, so a node
      that missed the fence re-requests it instead of serving a stale
      version indefinitely.

    ``error`` (node → leader): an unrecoverable v2 staging failure
    (digest retry budget exhausted) — the leader aborts the swap.
    ``epoch``: leader fencing epoch (docs/failover.md); a promoted
    standby re-drives an adopted swap at its bumped epoch.

    Rollout-pipeline extensions (docs/rollout.md), omitted at default:

    - ``revert`` (with ``abort=True``): the abort targets a COMMITTED
      wave — the replica must roll its serving params BACK to the
      retained pre-flip tree (the SLO-breach rollback), where a plain
      abort of a committed version is refused.
    - ``finalize`` (leader → replica): the wave's soak verdict PASSED
      — release the retained pre-flip params (the rollback window is
      over).  Advisory: a lost finalize only costs retained memory
      until the next rollout."""

    src_id: NodeID
    version: str
    swap_base: int = -1
    abort: bool = False
    query: bool = False
    applied: bool = False
    prepare: bool = False
    error: str = ""
    epoch: int = -1
    revert: bool = False
    finalize: bool = False

    msg_type = MsgType.SWAP_COMMIT

    def to_payload(self) -> dict:
        payload: dict = {"SrcID": self.src_id,
                         "Version": str(self.version)}
        if self.swap_base >= 0:
            payload["SwapBase"] = int(self.swap_base)
        if self.abort:
            payload["Abort"] = True
        if self.query:
            payload["Query"] = True
        if self.applied:
            payload["Applied"] = True
        if self.prepare:
            payload["Prepare"] = True
        if self.error:
            payload["Error"] = str(self.error)
        if self.revert:
            payload["Revert"] = True
        if self.finalize:
            payload["Finalize"] = True
        return _epoch_to_payload(payload, self.epoch)

    @classmethod
    def from_payload(cls, d: dict) -> "SwapCommitMsg":
        return cls(
            int(d["SrcID"]),
            str(d["Version"]),
            int(d.get("SwapBase", -1)),
            bool(d.get("Abort", False)),
            bool(d.get("Query", False)),
            bool(d.get("Applied", False)),
            bool(d.get("Prepare", False)),
            str(d.get("Error", "")),
            int(d.get("Epoch", -1)),
            bool(d.get("Revert", False)),
            bool(d.get("Finalize", False)),
        )


@dataclasses.dataclass
class JobRevokeMsg:
    """Leader → sender: a re-plan demoted a lower priority tier — drop
    the named job's queued-but-not-yet-started sends to these (dest,
    layer) pairs (docs/service.md).  Best-effort and advisory: a send
    already completed simply ignores the revocation (the registry entry
    is consumed on first match and TTL-bounded), and a send wrongly
    dropped is re-planned by the very re-plan that triggered the
    revoke.  Dropped pairs count on ``jobs.revoked_pairs``.

    ``gen``: the plan generation this revoke fences (docs/service.md) —
    the registry entry only eats commands stamped with ``gen`` <= this
    value, so the replacing re-plan's own (newer-generation) command
    can never be consumed by its stale revoke.  0 = pre-generation
    leader, omitted on the wire (legacy eat-anything semantics)."""

    src_id: NodeID
    job_id: str
    pairs: list = dataclasses.field(default_factory=list)  # [[dest, layer]]
    epoch: int = -1
    gen: int = 0

    msg_type = MsgType.JOB_REVOKE

    def to_payload(self) -> dict:
        payload: dict = {"SrcID": self.src_id, "JobID": str(self.job_id)}
        if self.pairs:
            payload["Pairs"] = [[int(d), int(l)] for d, l in self.pairs]
        if self.gen:
            payload["Gen"] = int(self.gen)
        return _epoch_to_payload(payload, self.epoch)

    @classmethod
    def from_payload(cls, d: dict) -> "JobRevokeMsg":
        return cls(
            int(d["SrcID"]),
            str(d["JobID"]),
            [[int(p[0]), int(p[1])] for p in d.get("Pairs") or []],
            int(d.get("Epoch", -1)),
            int(d.get("Gen", 0)),
        )


@dataclasses.dataclass
class GroupPlanMsg:
    """Root leader → sub-leader (docs/hierarchy.md): the group's member
    delivery targets.  Re-sent on every root re-plan — idempotent at
    the sub-leader (targets REPLACE; receipt also answers with a full
    cumulative ``GroupStatusMsg``, the takeover/reconcile poke).

    ``targets``: ``{member: {layer: LayerMeta json}}`` — what each
    member must end up holding.  The sub-leader fans a layer out to
    every member wanting it the moment its own copy completes.

    ``dissolve`` (root → MEMBER): the member's sub-leader was declared
    dead — re-point the control parent at ``src_id`` (the root) and
    re-announce there; the group degrades to flat delivery.  All other
    fields are omitted on a dissolve notice.

    ``forward`` (sub-leader → MEMBER, advisory): chain relay roles —
    ``{layer: [[lo, hi, next_member], ...]}`` byte ranges (in the
    transfer's wire byte space, i.e. the encoded blob for codec pairs)
    the receiving member forwards downstream the moment they land
    (docs/hierarchy.md).  Re-sent roles REPLACE per layer; an
    empty-list row clears that layer's roles.  A legacy member ignores
    the key and the sub-leader's redrive converges it by direct send.

    Epoch-fenced like every leader-originated control message: a
    zombie root's group plans are rejected, not raced."""

    src_id: NodeID
    group_id: int = 0
    targets: dict = dataclasses.field(default_factory=dict)
    dissolve: bool = False
    epoch: int = -1
    forward: dict = dataclasses.field(default_factory=dict)

    msg_type = MsgType.GROUP_PLAN

    def to_payload(self) -> dict:
        payload: dict = {"SrcID": self.src_id, "Group": int(self.group_id)}
        if self.targets:
            payload["Targets"] = {
                str(m): layer_ids_to_json(row)
                for m, row in self.targets.items()}
        if self.dissolve:
            payload["Dissolve"] = True
        if self.forward:
            payload["Forward"] = {
                str(lid): [[int(h[0]), int(h[1]), int(h[2])] for h in hops]
                for lid, hops in self.forward.items()}
        return _epoch_to_payload(payload, self.epoch)

    @classmethod
    def from_payload(cls, d: dict) -> "GroupPlanMsg":
        return cls(
            src_id=int(d["SrcID"]),
            group_id=int(d.get("Group", 0)),
            targets={int(m): layer_ids_from_json(row or {})
                     for m, row in (d.get("Targets") or {}).items()},
            dissolve=bool(d.get("Dissolve", False)),
            epoch=int(d.get("Epoch", -1)),
            forward={int(lid): [[int(h[0]), int(h[1]), int(h[2])]
                                for h in hops or []]
                     for lid, hops in (d.get("Forward") or {}).items()},
        )


@dataclasses.dataclass
class GroupStatusMsg:
    """Sub-leader → root (docs/hierarchy.md): the aggregate upward
    channel — the root handles ONE message per group event where the
    flat plane handled one per member.

    ``covered``: cumulative ``{layer: [members]}`` — members whose copy
    of the layer completed (verified + acked to the sub-leader).
    CUMULATIVE on purpose: the root applies it as a set-union, so a
    report lost in a failover window is repaired by the next one (and
    by the reply every ``GroupPlanMsg`` receipt sends).

    ``announced``: ``{member: {layer: LayerMeta json}}`` — member
    announce inventories folded upward (pre-held layers reduce the
    group's ingress demand).

    ``dead``: members the sub-leader's own failure detector declared
    crashed; the root drops their pairs exactly like a direct crash.

    ``metrics``: batched member telemetry snapshots (``{member:
    {"Counters", "Gauges", "Links", "T", "Proc"}}``), folded into the
    root's cluster table like direct ``MetricsReportMsg`` reports.

    ``digests``: ``{member: {layer: digest}}`` — the members' announced
    digest inventories, folded with the same debounce as ``announced``.
    Advisory, but it is what lets the root digest-verify a GROUPED
    joiner and promote it to a source (docs/membership.md) — without
    it the aggregate fold left grouped joiners quarantined forever.

    ``codecs``: ``{member: [codec, ...]}`` — the members' announced
    wire-codec decode capabilities (docs/codec.md), folded with the
    same debounce.  An explicit empty list is a REVOCATION (a restarted
    member may have lost the capability with its config), mirroring the
    flat announce path; without this fold the root could never choose a
    quantized transfer for a grouped member, so codec-qualified pairs
    were forced to plan flat around the hierarchy.

    Every section is optional and omitted at default — a legacy peer
    decodes the required keys alone."""

    src_id: NodeID
    group_id: int = 0
    covered: dict = dataclasses.field(default_factory=dict)
    announced: dict = dataclasses.field(default_factory=dict)
    dead: list = dataclasses.field(default_factory=list)
    metrics: dict = dataclasses.field(default_factory=dict)
    digests: dict = dataclasses.field(default_factory=dict)
    codecs: dict = dataclasses.field(default_factory=dict)
    # Advisory span correlation for the aggregated coverage
    # (docs/observability.md): ``{layer: {member: span_id}}`` — the
    # sub-leader's fan-out child span per covered (member, layer), so
    # the root's ``acked`` events chain the members under the planned
    # group-ingress spans.  Omitted when empty (every pre-span peer).
    spans: dict = dataclasses.field(default_factory=dict)

    msg_type = MsgType.GROUP_STATUS

    def to_payload(self) -> dict:
        payload: dict = {"SrcID": self.src_id, "Group": int(self.group_id)}
        if self.covered:
            payload["Covered"] = {
                str(lid): [int(m) for m in members]
                for lid, members in self.covered.items()}
        if self.announced:
            payload["Announced"] = {
                str(m): layer_ids_to_json(row)
                for m, row in self.announced.items()}
        if self.dead:
            payload["Dead"] = [int(m) for m in self.dead]
        if self.metrics:
            payload["Metrics"] = {str(m): dict(snap)
                                  for m, snap in self.metrics.items()}
        if self.spans:
            payload["Spans"] = {
                str(lid): {str(m): str(s) for m, s in per.items()}
                for lid, per in self.spans.items()}
        if self.digests:
            payload["Digests"] = {
                str(m): {str(lid): str(dg) for lid, dg in row.items()}
                for m, row in self.digests.items()}
        if self.codecs:
            payload["Codecs"] = {str(m): [str(c) for c in caps]
                                 for m, caps in self.codecs.items()}
        return payload

    @classmethod
    def from_payload(cls, d: dict) -> "GroupStatusMsg":
        return cls(
            src_id=int(d["SrcID"]),
            group_id=int(d.get("Group", 0)),
            covered={int(lid): [int(m) for m in members]
                     for lid, members in (d.get("Covered") or {}).items()},
            announced={int(m): layer_ids_from_json(row or {})
                       for m, row in (d.get("Announced") or {}).items()},
            dead=[int(m) for m in d.get("Dead") or []],
            metrics={int(m): dict(snap)
                     for m, snap in (d.get("Metrics") or {}).items()},
            spans={int(lid): {int(m): str(s) for m, s in per.items()}
                   for lid, per in (d.get("Spans") or {}).items()},
            digests={int(m): {int(lid): str(dg) for lid, dg in row.items()}
                     for m, row in (d.get("Digests") or {}).items()},
            codecs={int(m): [str(c) for c in caps or []]
                    for m, caps in (d.get("Codecs") or {}).items()},
        )


@dataclasses.dataclass
class JoinMsg:
    """Elastic membership: the JOIN verb (docs/membership.md) — four
    protocol roles in one type (see MsgType.JOIN).

    - **request** (node → leader; no flags): admit ``src_id`` into the
      running cluster.  ``addr`` is the joiner's dialable transport
      address (the leader — and, via roster notices, every sender —
      installs it in its registry; an unconfigured seat is in nobody's
      config).  ``want`` optionally names the layer ids the joiner
      wants; empty = the current goal's full layer universe.
    - **admit** (leader → joiner; ``admitted=True``): admission
      confirmed at ``epoch``.  ``parent`` (>= 0) names the joiner's
      control parent — the root, or the sub-leader a grouped cluster
      placed it under (``parent_addr`` its address) — the joiner
      re-points its leader there and announces.
    - **roster** (leader → member; ``admitted=True`` + ``node``/
      ``addr``): peer ``node`` joined at ``addr`` — install the
      address so later plans can command sends to it.
    - **re-point** (leader → member; ``parent`` >= 0, ``node`` names
      the parent): your control parent changed (a dissolved group
      re-formed under its re-admitted sub-leader) — re-point and
      re-announce there.

    Epoch-fenced like every leader-originated notice: a zombie
    ex-leader's admits and re-points are rejected, not raced.  All
    extension fields are omitted at default — the request a legacy
    tool could mint is the minimal {SrcID} payload."""

    src_id: NodeID
    addr: str = ""
    want: list = dataclasses.field(default_factory=list)  # layer ids
    node: NodeID = -1  # subject of an admit/roster notice (-1 = src_id)
    admitted: bool = False
    parent: NodeID = -1  # control parent to re-point at (-1 = keep)
    parent_addr: str = ""
    error: str = ""
    epoch: int = -1

    msg_type = MsgType.JOIN

    def to_payload(self) -> dict:
        payload: dict = {"SrcID": self.src_id}
        if self.addr:
            payload["Addr"] = str(self.addr)
        if self.want:
            payload["Want"] = [int(l) for l in self.want]
        if self.node >= 0:
            payload["Node"] = int(self.node)
        if self.admitted:
            payload["Admitted"] = True
        if self.parent >= 0:
            payload["Parent"] = int(self.parent)
        if self.parent_addr:
            payload["ParentAddr"] = str(self.parent_addr)
        if self.error:
            payload["Error"] = str(self.error)
        return _epoch_to_payload(payload, self.epoch)

    @classmethod
    def from_payload(cls, d: dict) -> "JoinMsg":
        return cls(
            int(d["SrcID"]),
            str(d.get("Addr", "")),
            [int(l) for l in d.get("Want") or []],
            int(d.get("Node", -1)),
            bool(d.get("Admitted", False)),
            int(d.get("Parent", -1)),
            str(d.get("ParentAddr", "")),
            str(d.get("Error", "")),
            int(d.get("Epoch", -1)),
        )


@dataclasses.dataclass
class DrainMsg:
    """Elastic membership: the DRAIN verb (docs/membership.md) — a
    planned departure, never a crash.

    - **request** (node → leader; no flags): drain ``src_id`` —
      re-home my unique holdings onto survivors, then release me.  An
      OPERATOR seat drains another node by naming it in ``node``
      (the ``cli.main -drain NODE`` one-shot).
    - **done** (leader → drainer + requester; ``done=True``): ``node``'s
      unique holdings are re-homed and it is pruned from the failure
      detector, lease recipients, and announce gating — exiting now
      cannot fire the crash path.  ``error`` reports a refused drain
      (unknown node, the leader itself) instead of silence."""

    src_id: NodeID
    node: NodeID = -1  # the node to drain (-1 = src_id)
    done: bool = False
    error: str = ""
    epoch: int = -1

    msg_type = MsgType.DRAIN

    def to_payload(self) -> dict:
        payload: dict = {"SrcID": self.src_id}
        if self.node >= 0:
            payload["Node"] = int(self.node)
        if self.done:
            payload["Done"] = True
        if self.error:
            payload["Error"] = str(self.error)
        return _epoch_to_payload(payload, self.epoch)

    @classmethod
    def from_payload(cls, d: dict) -> "DrainMsg":
        return cls(
            int(d["SrcID"]),
            int(d.get("Node", -1)),
            bool(d.get("Done", False)),
            str(d.get("Error", "")),
            int(d.get("Epoch", -1)),
        )


@dataclasses.dataclass
class RolloutCtlMsg:
    """Operator ↔ leader channel of the SLO-guarded rollout pipeline
    (docs/rollout.md).  Request roles (operator seat → leader),
    disambiguated by flags like SWAP_COMMIT/JOIN:

    - **query** (``query=True``): answer with the rollout table —
      per-rollout wave states, SLO verdicts, the traffic-split knob,
      and the derived v1/v2 serving pools.
    - **pause** (``pause=True`` + ``rollout_id``): stop committing
      further waves (in-flight dissemination and soaks finish; nothing
      new flips).
    - **resume** (``resume=True`` + ``rollout_id``): re-arm a paused
      pipeline; a wave that was rolled back is re-disseminated as a
      retry wave job.
    - **set split** (``split`` >= 0 + ``rollout_id``): move the
      leader-owned traffic-split knob (the fraction of eligible
      traffic routed at v2 replicas during soak).

    The reply (leader → requester) carries ``table`` (and ``error``
    for refusals) — always ANSWERED, the serving invariant.

    ``auth``: the shared-secret job token (docs/service.md).  The
    MUTATING verbs — pause / resume / set-split — change what the
    fleet serves (resume re-submits a rolled-back wave's swap job), so
    a DLD_JOB_TOKEN-armed leader refuses them unauthenticated exactly
    like job submission; query stays open like ``-jobs``.  Omitted on
    the wire when empty."""

    src_id: NodeID
    rollout_id: str = ""
    query: bool = False
    pause: bool = False
    resume: bool = False
    split: float = -1.0
    table: dict = dataclasses.field(default_factory=dict)
    error: str = ""
    epoch: int = -1
    auth: str = ""

    msg_type = MsgType.ROLLOUT_CTL

    def to_payload(self) -> dict:
        payload: dict = {"SrcID": self.src_id}
        if self.rollout_id:
            payload["RolloutID"] = str(self.rollout_id)
        if self.query:
            payload["Query"] = True
        if self.pause:
            payload["Pause"] = True
        if self.resume:
            payload["Resume"] = True
        if self.split >= 0:
            payload["Split"] = float(self.split)
        if self.table:
            payload["Table"] = {str(k): dict(v)
                                for k, v in self.table.items()}
        if self.error:
            payload["Error"] = str(self.error)
        if self.auth:
            payload["Auth"] = str(self.auth)
        return _epoch_to_payload(payload, self.epoch)

    @classmethod
    def from_payload(cls, d: dict) -> "RolloutCtlMsg":
        return cls(
            int(d["SrcID"]),
            str(d.get("RolloutID", "")),
            bool(d.get("Query", False)),
            bool(d.get("Pause", False)),
            bool(d.get("Resume", False)),
            float(d.get("Split", -1.0)),
            {str(k): dict(v) for k, v in (d.get("Table") or {}).items()},
            str(d.get("Error", "")),
            int(d.get("Epoch", -1)),
            str(d.get("Auth", "")),
        )


@dataclasses.dataclass
class PolicyCtlMsg:
    """Operator seat ↔ leader: the autonomy engine's control channel
    (docs/autonomy.md).

    Verbs (operator seat → leader):

    - **query** (``query=True``): return the policy table — armed
      rules, enabled flag, cooldown deadlines, quarantine mask, and
      the recent audit trail of fired actions.
    - **enable** (``enable=True``) / **disable** (``disable=True``):
      toggle automatic actioning at runtime.  Disable is the soft
      kill-switch: rules keep evaluating (streaks/cooldowns stay
      warm) but no action fires until re-enabled.  The hard
      kill-switch is ``DLD_POLICY=0`` (env, overrides everything).

    The reply (leader → requester) carries ``table`` (and ``error``
    for refusals) — always ANSWERED, the serving invariant.

    ``auth``: the shared-secret job token (docs/service.md).  The
    MUTATING verbs — enable / disable — change whether the fleet acts
    on itself, so a DLD_JOB_TOKEN-armed leader refuses them
    unauthenticated exactly like job submission; query stays open like
    ``-jobs``.  Omitted on the wire when empty."""

    src_id: NodeID
    query: bool = False
    enable: bool = False
    disable: bool = False
    table: dict = dataclasses.field(default_factory=dict)
    error: str = ""
    epoch: int = -1
    auth: str = ""

    msg_type = MsgType.POLICY_CTL

    def to_payload(self) -> dict:
        payload: dict = {"SrcID": self.src_id}
        if self.query:
            payload["Query"] = True
        if self.enable:
            payload["Enable"] = True
        if self.disable:
            payload["Disable"] = True
        if self.table:
            payload["Table"] = dict(self.table)
        if self.error:
            payload["Error"] = str(self.error)
        if self.auth:
            payload["Auth"] = str(self.auth)
        return _epoch_to_payload(payload, self.epoch)

    @classmethod
    def from_payload(cls, d: dict) -> "PolicyCtlMsg":
        return cls(
            int(d["SrcID"]),
            bool(d.get("Query", False)),
            bool(d.get("Enable", False)),
            bool(d.get("Disable", False)),
            dict(d.get("Table") or {}),
            str(d.get("Error", "")),
            int(d.get("Epoch", -1)),
            str(d.get("Auth", "")),
        )


Message = Union[
    AnnounceMsg,
    AckMsg,
    RetransmitMsg,
    FlowRetransmitMsg,
    LayerMsg,
    ClientReqMsg,
    StartupMsg,
    SimpleMsg,
    HeartbeatMsg,
    BootReadyMsg,
    DevicePlanMsg,
    ServeMsg,
    PlanResendReqMsg,
    LayerNackMsg,
    LayerDigestsMsg,
    LeaderLeaseMsg,
    ControlDeltaMsg,
    SourceDeadMsg,
    MetricsReportMsg,
    TimeSyncMsg,
    JobSubmitMsg,
    JobStatusMsg,
    SwapCommitMsg,
    JobRevokeMsg,
    GroupPlanMsg,
    GroupStatusMsg,
    JoinMsg,
    DrainMsg,
    RolloutCtlMsg,
    PolicyCtlMsg,
]

_DECODERS = {
    MsgType.ANNOUNCE: AnnounceMsg,
    MsgType.ACK: AckMsg,
    MsgType.RETRANSMIT: RetransmitMsg,
    MsgType.FLOW_RETRANSMIT: FlowRetransmitMsg,
    MsgType.CLIENT_REQ: ClientReqMsg,
    MsgType.STARTUP: StartupMsg,
    MsgType.SIMPLE: SimpleMsg,
    MsgType.HEARTBEAT: HeartbeatMsg,
    MsgType.BOOT_READY: BootReadyMsg,
    MsgType.DEVICE_PLAN: DevicePlanMsg,
    MsgType.SERVE: ServeMsg,
    MsgType.BOOT_HINT: BootHintMsg,
    MsgType.GENERATE_REQ: GenerateReqMsg,
    MsgType.GENERATE_RESP: GenerateRespMsg,
    MsgType.PLAN_RESEND_REQ: PlanResendReqMsg,
    MsgType.LAYER_NACK: LayerNackMsg,
    MsgType.LAYER_DIGESTS: LayerDigestsMsg,
    MsgType.LEADER_LEASE: LeaderLeaseMsg,
    MsgType.CONTROL_DELTA: ControlDeltaMsg,
    MsgType.SOURCE_DEAD: SourceDeadMsg,
    MsgType.METRICS_REPORT: MetricsReportMsg,
    MsgType.TIME_SYNC: TimeSyncMsg,
    MsgType.JOB_SUBMIT: JobSubmitMsg,
    MsgType.JOB_STATUS: JobStatusMsg,
    MsgType.SWAP_COMMIT: SwapCommitMsg,
    MsgType.JOB_REVOKE: JobRevokeMsg,
    MsgType.GROUP_PLAN: GroupPlanMsg,
    MsgType.GROUP_STATUS: GroupStatusMsg,
    MsgType.JOIN: JoinMsg,
    MsgType.DRAIN: DrainMsg,
    MsgType.ROLLOUT_CTL: RolloutCtlMsg,
    MsgType.POLICY_CTL: PolicyCtlMsg,
}


def decode_msg(msg_type: MsgType, payload: dict) -> Message:
    """Envelope payload → typed message (message.go:280-301).  LAYER is
    intentionally absent: it is reconstructed by the transport from the
    binary stream, never JSON-decoded."""
    try:
        cls = _DECODERS[MsgType(msg_type)]
    except (KeyError, ValueError):
        raise ValueError(f"unknown MsgType: {msg_type}")
    return cls.from_payload(payload)


def src_of(msg: Message) -> Optional[NodeID]:
    """Originating node id, if the message carries one."""
    return getattr(msg, "src_id", None)
