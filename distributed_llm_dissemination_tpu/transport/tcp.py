"""TCP transport: the real two-plane network backend.

Re-design of the reference's ``TcpTransport``
(``/root/reference/distributor/transport.go:28-491``) with cleaner framing:

- **Control plane**: length-prefixed JSON envelopes (4-byte big-endian size
  + ``{"type", "src", "payload"}``) on persistent per-peer connections with
  a per-connection write lock (the reference instead streams back-to-back
  JSON objects, transport.go:100-124).
- **Data plane**: a ``LayerMsg`` travels as an envelope whose payload is the
  ``LayerHeader``, followed by exactly ``layer_size`` raw bytes — on a
  per-destination POOLED data connection: sequential transfers (a flow
  job's 16 MiB fragments) share one connection instead of paying a
  handshake + slow-start per fragment, while concurrent transfers still
  fan out over as many connections as are in flight.  (The reference dials
  fresh per transfer, transport.go:267-274 — fine for whole-layer sends,
  ~640 dials for a fragmented 10 GiB flow job.)
- In-memory layers are paced by a token bucket (transport.go:407-424); disk
  layers go out via ``socket.sendfile`` — the zero-copy path matching the
  reference's ``io.Copy(SectionReader)`` sendfile (transport.go:357-367).
- A registered ``(layer_id → dest_id)`` pipe relays an incoming layer to a
  downstream node *while* it is being received, chunk by chunk — cut-through
  relay, the reference's TeeReader trick (transport.go:144-196).
- Self-sends short-circuit into the local delivery queue
  (transport.go:282-285).
"""

from __future__ import annotations

import itertools
import json
import os
import queue
import selectors
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..core.types import LayerID, LayerLocation, LayerMeta, LayerSrc, NodeID
from ..ops.reassembly import stripe_offsets
from ..utils import integrity, telemetry, threads, trace
from ..utils.backoff import Backoff
from ..utils.buffers import alloc_recv_buffer
from ..utils.logging import log
from ..utils.rate import PacedWriter
from .base import AddrRegistry, Transport
from .messages import (
    LayerHeader,
    LayerMsg,
    Message,
    MsgType,
    decode_msg,
)

_LEN = struct.Struct("!I")
_CHUNK = 1 << 20  # 1 MiB receive/relay chunk
# Dial retry window: the reference has no retries at all (errors are only
# logged, node.go:345-348), so peers racing the leader's listener die.
_DIAL_TIMEOUT = 10.0
_DIAL_RETRY_DELAY = 0.1
# Pooled send retries (utils/backoff.py): how many FRESH dials a failed
# layer/control send gets — with jittered exponential delays between
# them — before the OSError surfaces to the protocol layer.  Matters
# during a failover window: every worker loses the leader at once, and
# un-jittered immediate retries would stampede the successor in
# lockstep.
_SEND_RETRIES = max(1, int(os.environ.get("DLD_TCP_SEND_RETRIES", "3")))

# --- layer striping -------------------------------------------------------
# One (source, layer) transfer used to ride ONE pooled data connection: a
# physical-size layer was a single serial byte stream, so end-to-end ingest
# was capped by per-socket throughput while the link (and the device side)
# could absorb multiples of it.  Payloads >= STRIPE_THRESHOLD split into up
# to STRIPE_COUNT stripes sent CONCURRENTLY over that many pooled data
# connections; each stripe is a well-formed byte-range fragment at its
# absolute offset (wire-compatible — see LayerHeader.stripe_*), so a
# receiver reassembles striped and un-striped frames through one path.
# STRIPE_MIN keeps every stripe big enough that TCP slow-start and framing
# overhead stay noise.  Rate-limited sends never stripe (N paced streams
# would multiply the commanded rate).
STRIPE_THRESHOLD = int(os.environ.get("DLD_TCP_STRIPE_THRESHOLD",
                                      str(8 << 20)))
STRIPE_COUNT = max(1, int(os.environ.get("DLD_TCP_STRIPES", "4")))
STRIPE_MIN = 2 << 20
# Rate-limited sends stripe only when the commanded rate is at least this
# (1 GB/s): past it the rate is a capacity BUDGET (an ICI/NIC line rate
# the flow solver allotted — the physical-size rows), which stripes split
# proportionally so the aggregate still honors it.  Below it the rate is
# a scarcity model (a slow source being simulated) whose burst semantics
# tests and the codec A/B rows depend on — those never stripe.
STRIPE_PACED_MIN_RATE = int(os.environ.get("DLD_TCP_STRIPE_MIN_RATE",
                                           str(10 ** 9)))
# Reassembly groups for striped transfers to a receiver WITHOUT a
# zero-copy layer sink are pruned after this long without completing
# (their sender died mid-transfer and gave up on the retry).
_STRIPE_GROUP_TTL = 300.0


def _dial(addr: Tuple[str, int], closed: threading.Event) -> socket.socket:
    """create_connection with jittered exponential retry until
    _DIAL_TIMEOUT elapses (utils/backoff.py): a dead peer costs a
    bounded, decaying probe sequence — not a tight 5 Hz loop — and
    concurrent dialers racing a restarting listener don't stampede it
    in lockstep."""
    deadline = time.monotonic() + _DIAL_TIMEOUT
    delays = Backoff(base=_DIAL_RETRY_DELAY, factor=1.7, max_delay=1.0,
                     retries=64, seed=hash(addr) & 0xFFFF).delays()
    while True:
        try:
            sock = socket.create_connection(addr, timeout=_DIAL_TIMEOUT)
            sock.settimeout(None)
            return sock
        except OSError:
            if closed.is_set() or time.monotonic() >= deadline:
                raise
            delay = next(delays, _DIAL_RETRY_DELAY)
            time.sleep(min(delay, max(0.0, deadline - time.monotonic())))


def _normalize(addr: str) -> str:
    """':8080' listens on all interfaces; dial via localhost."""
    return addr if not addr.startswith(":") else "127.0.0.1" + addr


def _parse_addr(addr: str) -> Tuple[str, int]:
    host, _, port = _normalize(addr).rpartition(":")
    return host or "127.0.0.1", int(port)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("connection closed mid-read")
        got += r
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Optional[dict]:
    """Read one length-prefixed JSON envelope; None on clean EOF."""
    try:
        hdr = _recv_exact(sock, _LEN.size)
    except ConnectionError:
        return None
    (size,) = _LEN.unpack(hdr)
    return json.loads(_recv_exact(sock, size))


def _sendmsg_all(sock: socket.socket, bufs) -> None:
    """``sendall`` over a scatter-gather list: every buffer goes out, in
    order, without ever concatenating them into a staging buffer —
    ``socket.sendmsg`` hands the kernel an iovec, so a layer frame's
    length prefix + JSON header + payload leave in one syscall with zero
    host-side joins (the old framing paid a ``bytes`` concat per frame,
    a full extra copy pass at physical layer sizes)."""
    views: List[memoryview] = [
        v for v in (memoryview(b).cast("B") for b in bufs) if len(v)
    ]
    while views:
        sent = sock.sendmsg(views)
        if sent == 0:
            raise ConnectionError("connection closed mid-write")
        while sent:
            if sent >= len(views[0]):
                sent -= len(views[0])
                views.pop(0)
            else:
                views[0] = views[0][sent:]
                sent = 0


def _send_frame(sock: socket.socket, envelope: dict) -> None:
    body = json.dumps(envelope).encode()
    _sendmsg_all(sock, (_LEN.pack(len(body)), body))


class _PConn:
    """A persistent control connection + its write lock
    (transport.go:42-45).  ``sock`` is None until the first dial completes;
    dialing happens under this connection's own lock so one unreachable
    peer never stalls sends to the others."""

    def __init__(self, sock: Optional[socket.socket] = None):
        self.sock = sock
        self.lock = threading.Lock()


class _ReadinessLoop:
    """The shared receive event loop: ONE selector thread drives every
    TcpTransport in the process, so connection count no longer implies
    thread count (docs/transport.md).

    Three fd kinds ride the selector:

    - **listener** — accepts inline; accepted connections register as
      conns (no per-connection thread, ever).
    - **conn** (accepted) — the loop parses the length-prefixed JSON
      envelope INCREMENTALLY with non-blocking reads (a stalled or
      malicious peer can never wedge the loop mid-frame).  A complete
      non-LAYER envelope is decoded and delivered inline — control
      traffic costs zero threads and can never be starved by slow layer
      bodies.  A LAYER envelope unregisters the connection and hands it
      to the bounded ``utils.threads.rx_pool()``: the worker
      blocking-reads the body through the unchanged zero-copy /
      stripe-regroup / cut-through paths (the sender is actively
      streaming it, and only layer bodies ever occupy a worker slot),
      then re-registers the connection at the next frame boundary.
    - **drain** — dialed control connections are write-only by protocol;
      the loop watches them for FIN/RST and evicts, replacing the old
      per-peer drain threads.

    Registration mutates the selector, which is not thread-safe against
    a concurrent ``select``: all mutations post to a command queue and
    wake the loop via a self-pipe."""

    def __init__(self):
        self._sel = selectors.DefaultSelector()
        self._cmds: "queue.Queue" = queue.Queue()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ,
                           {"kind": "wake"})
        threading.Thread(target=self._run, daemon=True,
                         name="tcp-evloop").start()

    # ------------------------------------------------------ registration

    def _post(self, fn) -> None:
        self._cmds.put(fn)
        try:
            self._wake_w.send(b"x")
        except (BlockingIOError, OSError):
            pass  # wake pipe full = the loop is already awake

    def _register(self, sock: socket.socket, rec: dict,
                  nonblocking: bool = True) -> None:
        try:
            if nonblocking:
                sock.setblocking(False)
        except OSError:
            if rec.get("kind") != "drain":
                rec["transport"]._discard_accepted(sock)
            return
        try:
            self._sel.register(sock, selectors.EVENT_READ, rec)
        except KeyError:
            # The kernel reuses fd NUMBERS: a socket closed before its
            # unwatch command ran leaves a stale selector entry that a
            # NEW socket with the same fd trips over.  Purge the stale
            # entry (it can never fire — epoll dropped the closed fd)
            # and register the live socket.
            try:
                stale = self._sel.get_key(sock)
                self._sel.unregister(stale.fileobj)
                self._sel.register(sock, selectors.EVENT_READ, rec)
            except (KeyError, ValueError, OSError):
                if rec.get("kind") != "drain":
                    rec["transport"]._discard_accepted(sock)
        except (ValueError, OSError):
            if rec.get("kind") != "drain":
                rec["transport"]._discard_accepted(sock)

    def watch_listener(self, transport: "TcpTransport",
                       sock: socket.socket) -> None:
        self._post(lambda: self._register(
            sock, {"kind": "listener", "transport": transport}))

    def watch_conn(self, transport: "TcpTransport",
                   sock: socket.socket) -> None:
        """(Re-)arm envelope parsing on an accepted connection.  Called
        at accept time and by a pool worker returning a connection at a
        frame boundary; a transport that closed meanwhile gets the
        socket closed instead of leaked into the selector."""
        if transport._closed.is_set():
            transport._discard_accepted(sock)
            return
        rec = {"kind": "conn", "transport": transport, "sock": sock,
               "buf": bytearray(), "need": _LEN.size, "phase": "len"}
        self._post(lambda: self._register(sock, rec))

    def watch_drain(self, transport: "TcpTransport", sock: socket.socket,
                    dest_addr: str, pconn: _PConn) -> None:
        # The dialed conn stays BLOCKING: senders write frames on it
        # concurrently (_send_frame under pconn.lock), and flipping it
        # non-blocking would make a full send buffer raise mid-frame.
        # The loop's drain read uses MSG_DONTWAIT instead.
        self._post(lambda: self._register(
            sock, {"kind": "drain", "transport": transport,
                   "addr": dest_addr, "pconn": pconn}, nonblocking=False))

    def unwatch_all(self, transport: "TcpTransport") -> None:
        """Drop every registration belonging to a closing transport."""

        def run():
            for key in [k for k in list(self._sel.get_map().values())
                        if k.data.get("transport") is transport]:
                try:
                    self._sel.unregister(key.fileobj)
                except (KeyError, ValueError, OSError):
                    pass

        self._post(run)

    # -------------------------------------------------------------- loop

    def _run(self) -> None:
        while True:
            try:
                events = self._sel.select()
            except OSError:
                time.sleep(0.01)  # a closed fd raced the select; retry
                continue
            # Wake bytes are consumed BEFORE the command drain — never
            # the other way around, or a command posted while we were
            # dispatching has its wake byte swallowed and sleeps until
            # the next unrelated event (a lost wakeup).
            try:
                while self._wake_r.recv(4096):
                    pass
            except (BlockingIOError, OSError):
                pass
            while True:
                try:
                    self._cmds.get_nowait()()
                except queue.Empty:
                    break
            for key, _ in events:
                rec = key.data
                kind = rec.get("kind")
                try:
                    if kind == "wake":
                        pass  # drained above
                    elif kind == "listener":
                        self._on_accept(key.fileobj, rec)
                    elif kind == "conn":
                        self._on_conn(key.fileobj, rec)
                    elif kind == "drain":
                        self._on_drain(key.fileobj, rec)
                except Exception as e:  # noqa: BLE001 — loop must survive
                    log.error("readiness loop dispatch failed",
                              kind=kind, err=repr(e))
                    self._drop(key.fileobj, rec)

    def _drop(self, sock, rec: dict) -> None:
        try:
            self._sel.unregister(sock)
        except (KeyError, ValueError, OSError):
            pass
        tr = rec.get("transport")
        if tr is not None:
            tr._discard_accepted(sock)
        else:
            try:
                sock.close()
            except OSError:
                pass

    def _on_accept(self, listener, rec: dict) -> None:
        tr: "TcpTransport" = rec["transport"]
        while True:
            try:
                conn, _ = listener.accept()
            except (BlockingIOError, OSError):
                return
            if tr._closed.is_set():
                conn.close()
                return
            with tr._lock:
                tr._accepted.add(conn)
            self.watch_conn(tr, conn)

    def _on_conn(self, sock, rec: dict) -> None:
        """Advance one connection's envelope parse as far as the kernel
        buffer allows; never blocks."""
        tr: "TcpTransport" = rec["transport"]
        while True:
            try:
                chunk = sock.recv(rec["need"] - len(rec["buf"]))
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._drop(sock, rec)
                return
            if not chunk:
                self._drop(sock, rec)  # clean EOF (or RST)
                return
            rec["buf"] += chunk
            if len(rec["buf"]) < rec["need"]:
                continue
            if rec["phase"] == "len":
                (rec["need"],) = _LEN.unpack(bytes(rec["buf"]))
                rec["buf"] = bytearray()
                rec["phase"] = "env"
                continue
            # One complete envelope.
            try:
                envelope = json.loads(bytes(rec["buf"]))
                mtype = MsgType(envelope["type"])
            except (ValueError, KeyError) as e:
                if not tr._closed.is_set():
                    log.error("receive loop failed", err=e)
                self._drop(sock, rec)
                return
            rec["buf"] = bytearray()
            rec["need"] = _LEN.size
            rec["phase"] = "len"
            if mtype != MsgType.LAYER:
                overflow = tr._deliver_control(mtype, envelope)
                if overflow is None:
                    continue
                # Delivery queue FULL: the consumer is wedged or
                # absent.  Take the CONNECTION off the loop and let a
                # pool worker do the blocking put, then re-register —
                # per-connection ordering is preserved (nobody else
                # reads the socket meanwhile) and the loop itself
                # never blocks.
                try:
                    self._sel.unregister(sock)
                except (KeyError, ValueError, OSError):
                    return
                threads.rx_pool().submit(tr._deliver_control_blocking,
                                         sock, overflow)
                return
            # Layer body follows: hand the connection to the bounded
            # worker pool for the (blocking) body read; the worker
            # re-registers at the next frame boundary.
            try:
                self._sel.unregister(sock)
            except (KeyError, ValueError, OSError):
                return
            threads.rx_pool().submit(tr._serve_layer_body, sock, envelope)
            return

    def _on_drain(self, sock, rec: dict) -> None:
        """Dialed control conns: peers never write here, so readable
        means FIN/RST (or stray bytes to discard) — evict so the next
        send re-dials."""
        tr: "TcpTransport" = rec["transport"]
        while True:
            try:
                data = sock.recv(4096, socket.MSG_DONTWAIT)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                data = b""
            if data:
                continue  # discard unexpected bytes until EOF
            try:
                self._sel.unregister(sock)
            except (KeyError, ValueError, OSError):
                pass
            if not tr._closed.is_set():
                tr._evict(rec["addr"], rec["pconn"])
            return


_loop: Optional[_ReadinessLoop] = None
_loop_lock = threading.Lock()


def _readiness_loop() -> _ReadinessLoop:
    global _loop
    with _loop_lock:
        if _loop is None:
            _loop = _ReadinessLoop()
        return _loop


class TcpTransport(Transport):
    def __init__(
        self,
        addr: str,
        buf_size: int = 1024,
        addr_registry: Optional[AddrRegistry] = None,
        is_client: bool = False,
    ):
        self.addr = addr
        self.addr_registry: AddrRegistry = dict(addr_registry or {})
        self.is_client = is_client
        self._queue: "queue.Queue[Message]" = queue.Queue(maxsize=buf_size)
        self._conns: Dict[str, _PConn] = {}
        # dest addr -> idle data connections (LIFO: the hottest conn has
        # the widest cwnd).  Checked out per layer transfer, returned
        # after a clean send; never shared concurrently.
        self._data_pool: Dict[str, list] = {}
        self._accepted: "set[socket.socket]" = set()
        self._pipes: Dict[LayerID, NodeID] = {}
        # Striped receive state: (src_id, layer_id, tid) -> in-progress
        # reassembly group (no-sink receivers regroup stripes into the
        # original logical payload before delivery), completed-transfer
        # tombstones (a late duplicate stripe — a sender retry whose
        # first copy actually landed — must be drained, not resurrected
        # as a phantom group pinning a payload-sized buffer), and the
        # per-transfer relay countdowns for striped frames hitting a
        # registered pipe.
        self._stripe_groups: Dict[tuple, dict] = {}
        self._stripe_done: Dict[tuple, float] = {}
        self._stripe_relays: Dict[tuple, dict] = {}
        # Lazy background sweeper for the striped-receive TTLs: arrival-
        # time pruning alone would let the LAST abandoned transfer pin
        # its payload-sized buffer forever (nothing striped arrives
        # after it to trigger the sweep).  Started on first striped
        # state, exits with the transport.
        self._stripe_sweeper_started = False
        self._stripe_tid = itertools.count(
            int.from_bytes(os.urandom(4), "big") << 20
        )
        self._lock = threading.Lock()
        self._closed = threading.Event()
        # Zero-copy receive hook (set by a reassembling receiver):
        # sink(layer_id, total_size, offset, size) -> None, or
        # (view, token, abort_fn) — a writable memoryview straight into
        # the destination reassembly buffer, the coverage claim token
        # the handler will commit, and the rollback for a failed recv.
        # When it engages, fragment bytes go socket→assembly in ONE
        # copy (no bounce buffer, no handler memcpy) — the hot path at
        # physical layer sizes on memory-bandwidth-bound hosts.
        self.layer_sink = None
        # Integrity hooks (docs/integrity.md).  ``recv_tamper(info,
        # view) -> bool`` is the TEST-ONLY fault-injection hook
        # (transport/faults.py), run on a frame's landed bytes BEFORE
        # CRC verification — it may flip bytes in place (simulating wire
        # corruption below the checksum) or return False to inject a
        # drop.  ``on_corrupt(src_id, layer_id, offset, size,
        # total_size, reason)`` fires whenever a frame is dropped for a
        # failed check (or a stripe group is TTL-pruned): the receiver
        # runtime NACKs the source from it so the range is retransmitted
        # instead of waiting out crash detection.
        self.recv_tamper = None
        self.on_corrupt = None
        # Telemetry identity (utils/telemetry.py): the node id whose
        # (src, dest) links this transport's frame accounting files
        # under.  Bound by runtime.node.Node; None = record nothing.
        self.node_id = None

        host, port = _parse_addr(addr)
        self._listener = socket.create_server((host, port), reuse_port=False)
        # Record the kernel-chosen port when addr asked for :0 (tests).
        if port == 0:
            actual = self._listener.getsockname()[1]
            self.addr = f"{host}:{actual}" if not addr.startswith(":") else f":{actual}"
        log.info("start listening", addr=self.addr)
        # The shared readiness loop owns the listener AND every accepted
        # connection (docs/transport.md): accepts and control frames are
        # handled inline in the loop thread; layer bodies ride the
        # bounded rx pool — K connections never mean K threads.
        _readiness_loop().watch_listener(self, self._listener)

    # ------------------------------------------------------------------ rx

    def _discard_accepted(self, conn: socket.socket) -> None:
        with self._lock:
            self._accepted.discard(conn)
        try:
            conn.close()
        except OSError:
            pass

    def _deliver_control(self, mtype: MsgType, envelope: dict):
        """Deliver one inline-parsed control envelope; returns None on
        success (undecodable frames are dropped, logged, and count as
        delivered) or the DECODED message when the delivery queue is
        FULL — the caller (the readiness loop) then hands the whole
        connection plus the message to ``_deliver_control_blocking``,
        so the loop itself never blocks, per-connection frame order is
        preserved (nothing reads the socket until the blocked put
        lands), and the decode is paid exactly once."""
        try:
            msg = decode_msg(mtype, envelope["payload"])
        except (ValueError, KeyError) as e:
            if not self._closed.is_set():
                log.error("control frame decode failed", err=repr(e))
            return None
        try:
            self._queue.put_nowait(msg)
            return None
        except queue.Full:
            return msg

    def _deliver_control_blocking(self, conn: socket.socket, msg) -> None:
        """Pool worker: block until the full delivery queue accepts the
        message (the consumer's backpressure, like the old
        per-connection reader), then return the connection to the
        readiness loop."""
        while not self._closed.is_set():
            try:
                self._queue.put(msg, timeout=0.5)
                break
            except queue.Full:
                continue
        _readiness_loop().watch_conn(self, conn)

    def _serve_layer_body(self, conn: socket.socket, envelope: dict) -> None:
        """Pool worker: blocking-read one layer frame's body through the
        unchanged receive paths (zero-copy sink placement, stripe
        regroup, cut-through relay), then return the connection to the
        readiness loop at the frame boundary."""
        try:
            conn.setblocking(True)
            self._receive_layer(conn, envelope)
        except (ConnectionError, OSError, ValueError, KeyError) as e:
            if not self._closed.is_set():
                log.error("receive loop failed", err=e)
            self._discard_accepted(conn)
            return
        except BaseException:
            self._discard_accepted(conn)
            raise
        _readiness_loop().watch_conn(self, conn)

    def _frame_ok(self, header: LayerHeader, view,
                  notify: bool = True) -> Tuple[bool, float]:
        """Run the test-only tamper hook, then verify the frame's
        advisory CRC; ``(ok, crc_ms)``.  On False the frame must be
        DROPPED — the caller rolls back any sink claim; corruption is
        reported via ``on_corrupt`` unless ``notify`` is False (the
        regroup path reports the whole span instead, so the retransmit
        regroups as the one logical message plain receivers expect)."""
        reason = None
        tamper = self.recv_tamper
        if tamper is not None:
            info = {"src": header.src_id, "layer": header.layer_id,
                    "offset": header.offset, "size": header.layer_size,
                    "total": header.total_size,
                    "stripe_idx": header.stripe_idx,
                    "stripe_n": header.stripe_n}
            try:
                if tamper(info, view) is False:
                    reason = "drop"
            except Exception as e:  # noqa: BLE001 — test hook must not wedge rx
                log.error("recv_tamper hook failed", err=repr(e))
        crc_ms = 0.0
        if reason is None and integrity.wire_crc_enabled():
            # Verify whichever stamp is present (xxh3 preferred); CPU
            # seconds, not wall — on a contended host a wall span around
            # a GIL-released hash mostly measures the scheduler.
            t0 = time.thread_time()
            ok = integrity.verify_stamp(view, crc=header.crc,
                                        xxh3=header.xxh3)
            if ok is not None:
                crc_ms = (time.thread_time() - t0) * 1000
                trace.add_phase("integrity_crc_recv", crc_ms / 1000)
                if not ok:
                    reason = "crc"
        if reason is None:
            return True, crc_ms
        self._notify_corrupt(
            header.src_id, header.layer_id, header.offset,
            header.layer_size, header.total_size, reason,
            stripe=(f"{header.stripe_idx + 1}/{header.stripe_n}"
                    if header.stripe_n > 1 else ""),
            silent=not notify)
        return False, crc_ms

    def _notify_corrupt(self, src_id, layer_id, offset: int, size: int,
                        total: int, reason: str, stripe: str = "",
                        silent: bool = False) -> None:
        """Count + log + report one dropped byte range (the shared
        reporter — one wording/counter scheme across transports); the
        receiver runtime's ``on_corrupt`` hook turns the report into a
        ``LayerNackMsg`` so the source retransmits the range."""
        integrity.report_corrupt_frame(
            self.on_corrupt, src_id, layer_id, offset, size, total,
            reason, stripe=stripe, silent=silent, dest_id=self.node_id)

    def _telemetry_rx(self, header: LayerHeader, dur_ms: float,
                      crc_ms: float, placed: bool) -> None:
        """File one VERIFIED received frame on the (src, me) link of the
        flight recorder: wire bytes/frames, stripe occupancy, zero-copy
        placement, and the wire-wait vs verify stall split.  Dropped
        frames are filed by ``_notify_corrupt`` instead."""
        telemetry.link_add(
            header.src_id, self.node_id, job=header.job_id,
            rx_bytes=header.layer_size, rx_frames=1,
            rx_stripe_frames=1 if header.stripe_n > 1 else 0,
            rx_placed_frames=1 if placed else 0,
            wire_s=dur_ms / 1000.0, verify_s=crc_ms / 1000.0)
        telemetry.observe_ms("tcp.rx_frame_ms", dur_ms)

    def _receive_layer(self, conn: socket.socket, envelope: dict) -> None:
        header = LayerHeader.from_payload(envelope["payload"])
        if header.stripe_n > 1:
            self._receive_stripe(conn, envelope, header)
            return
        log.info(
            "start receiving layer",
            layerID=header.layer_id,
            layer_size=header.layer_size,
            total_size=header.total_size,
        )
        t0 = time.monotonic()

        pipe_sock = self._get_and_unregister_pipe(header.layer_id)
        placed = None
        if pipe_sock is None and self.layer_sink is not None:
            placed = self.layer_sink(header.layer_id, header.total_size,
                                     header.offset, header.layer_size)
        if placed is not None:
            view, token, abort = placed
            try:
                self._recv_body(conn, view, header.layer_size)
            except BaseException:
                abort()  # roll the claim back or the layer wedges forever
                raise
            ok, crc_ms = self._frame_ok(header, view)
            if not ok:
                # The bytes in the reassembly buffer are garbage, but the
                # claim rollback un-covers the range — the NACKed
                # retransmit overwrites it and only committed bytes are
                # ever read.
                abort()
                return
            dur_ms = (time.monotonic() - t0) * 1000
            self._telemetry_rx(header, dur_ms, crc_ms, placed=True)
            log.info(
                "(a fraction of) layer received",
                layerID=header.layer_id,
                offset=header.offset,
                layer_size=header.layer_size,
                total_size=header.total_size,
                duration_ms=round(dur_ms, 3),
                crc_ms=round(crc_ms, 3),
                placed=True,
            )
            src = LayerSrc(
                inmem_data=None, data_size=header.layer_size,
                offset=header.offset,
                meta=LayerMeta(location=LayerLocation.INMEM),
            )
            src.placed_token = token
            self._queue.put(LayerMsg(header.src_id, header.layer_id, src,
                                     header.total_size,
                                     job_id=header.job_id,
                                     shard=header.shard,
                                     codec=header.codec,
                                     span_id=header.span_id,
                                     span_parent=header.span_parent))
            return
        buf = alloc_recv_buffer(header.layer_size)
        view = memoryview(buf)
        if pipe_sock is not None:
            # Cut-through relay: stream chunks to the downstream node while
            # receiving (transport.go:144-196) — over a FRESH data
            # connection, like every other layer transfer, so a multi-GiB
            # relay never head-of-line blocks control messages to that peer
            # (the reference relays through the shared-mutex control
            # connection, transport.go:144-196 + :42-45).  The forwarded
            # header keeps the original src, matching the reference (TODO
            # at :152-164).
            try:
                _send_frame(pipe_sock, envelope)
                self._recv_body(conn, view, header.layer_size, pipe_sock)
            finally:
                pipe_sock.close()
        else:
            self._recv_body(conn, view, header.layer_size)

        # The pipe already teed the bytes downstream chunk-by-chunk — a
        # corrupt relay can't be recalled, but the downstream transport
        # verifies the SAME forwarded CRC and drops/NACKs it itself.
        ok, crc_ms = self._frame_ok(header, view)
        if not ok:
            return
        dur_ms = (time.monotonic() - t0) * 1000
        self._telemetry_rx(header, dur_ms, crc_ms, placed=False)
        log.info(
            "(a fraction of) layer received",
            layerID=header.layer_id,
            offset=header.offset,
            layer_size=header.layer_size,
            total_size=header.total_size,
            duration_ms=round(dur_ms, 3),
            crc_ms=round(crc_ms, 3),
        )
        layer_src = LayerSrc(
            inmem_data=buf,
            data_size=header.layer_size,
            offset=header.offset,
            meta=LayerMeta(location=LayerLocation.INMEM),
        )
        self._queue.put(
            LayerMsg(header.src_id, header.layer_id, layer_src,
                     header.total_size, job_id=header.job_id,
                     shard=header.shard, codec=header.codec,
                     span_id=header.span_id,
                     span_parent=header.span_parent)
        )

    # --------------------------------------------------------- striped rx

    def _recv_body(self, conn: socket.socket, view: memoryview,
                   n: int, pipe_sock=None) -> None:
        """Land a frame body's bytes in ``view`` (socket → destination
        buffer in ONE copy), optionally teeing each chunk to a
        cut-through pipe downstream.  The one receive loop shared by the
        striped and un-striped paths."""
        got = 0
        while got < n:
            if pipe_sock is None:
                r = conn.recv_into(view[got:], n - got)
            else:
                r = conn.recv_into(view[got:], min(_CHUNK, n - got))
            if r == 0:
                raise ConnectionError("connection closed mid-body")
            if pipe_sock is not None:
                pipe_sock.sendall(view[got : got + r])
            got += r

    def _stripe_pipe_sock(self, header: LayerHeader, envelope: dict):
        """Cut-through relay for a STRIPED frame: every stripe of the
        transfer relays over its own fresh downstream connection (they
        arrive concurrently on different sockets), and the one-shot pipe
        unregisters only once all ``stripe_n`` stripes relayed.  Returns
        the dialed downstream socket with the stripe envelope already
        forwarded, or None (no pipe / downstream unreachable)."""
        key = (header.src_id, header.layer_id, header.stripe_tid)
        with self._lock:
            rec = self._stripe_relays.get(key)
            if rec is None:
                if header.layer_id not in self._pipes:
                    return None
                # Claim the one-shot pipe for this whole striped transfer.
                dest_id = self._pipes.pop(header.layer_id)
                rec = self._stripe_relays[key] = {
                    "dest_id": dest_id, "done": set(),
                    "n": header.stripe_n, "t": time.monotonic()}
            else:
                rec["t"] = time.monotonic()
        # Failures below do NOT retire the stripe's relay slot: only a
        # fully-relayed stripe counts (``_stripe_relay_done``), so a
        # sender retry of a failed stripe gets relayed on its own fresh
        # downstream connection instead of the transfer silently losing
        # that byte range.  A record whose stripes never all land is
        # TTL-pruned with the rest of the striped-receive state.
        dest = self.addr_registry.get(rec["dest_id"])
        if dest is None:
            log.error("addr does not exist", dest=rec["dest_id"])
            return None
        try:
            sock = _dial(_parse_addr(dest), self._closed)
        except OSError as e:
            log.error("failed to connect pipe dest", dest=rec["dest_id"],
                      err=e)
            return None
        try:
            _send_frame(sock, envelope)
        except OSError as e:
            log.error("failed to forward stripe header", err=e)
            sock.close()
            return None
        return sock

    def _stripe_relay_done(self, key, stripe_idx: int) -> None:
        """Mark one DISTINCT stripe fully relayed; the claimed pipe's
        record retires once every stripe index has been (duplicate
        relays of a retried stripe collapse into the set)."""
        with self._lock:
            rec = self._stripe_relays.get(key)
            if rec is not None:
                rec["done"].add(stripe_idx)
                if len(rec["done"]) >= rec["n"]:
                    del self._stripe_relays[key]

    def _receive_stripe(self, conn: socket.socket, envelope: dict,
                        header: LayerHeader) -> None:
        """One stripe of a striped layer transfer.

        Three landings, in priority order (mirroring ``_receive_layer``):
        a registered pipe relays the stripe downstream while receiving
        (and still lands it locally); a zero-copy ``layer_sink`` places
        the stripe DIRECTLY at its absolute offset in the receiver's
        reassembly buffer and delivers it as its own fragment — so
        device staging begins per-stripe, overlapping the tail of the
        wire; otherwise stripes regroup transport-side into the original
        logical payload (plain receivers expect whole messages), landing
        each stripe at ``stripe_off`` in one shared buffer."""
        t0 = time.monotonic()
        with self._lock:
            # First striped arrival arms the background sweeper — the
            # TTL owner for ALL striped-receive state (groups,
            # tombstones, relay records), including the last abandoned
            # transfer that no later arrival would ever sweep.
            if not self._stripe_sweeper_started:
                self._stripe_sweeper_started = True
                threading.Thread(target=self._stripe_sweep_loop,
                                 name="tcp-stripe-sweep",
                                 daemon=True).start()
        pipe_sock = self._stripe_pipe_sock(header, envelope)
        key = (header.src_id, header.layer_id, header.stripe_tid)
        landed = False
        try:
            placed = None
            if self.layer_sink is not None:
                placed = self.layer_sink(header.layer_id, header.total_size,
                                         header.offset, header.layer_size)
            if placed is not None:
                view, token, abort = placed
                try:
                    self._recv_body(conn, view, header.layer_size,
                                           pipe_sock)
                except BaseException:
                    abort()
                    raise
                ok, crc_ms = self._frame_ok(header, view)
                if not ok:
                    # Claim rolled back; ``landed`` stays False so the
                    # relay slot isn't retired (the downstream copy is
                    # corrupt too and the retransmit must re-relay).
                    abort()
                    return
                landed = True
                src = LayerSrc(
                    inmem_data=None, data_size=header.layer_size,
                    offset=header.offset,
                    meta=LayerMeta(location=LayerLocation.INMEM),
                )
                src.placed_token = token
                self._log_stripe(header, t0, placed=True, crc_ms=crc_ms)
                self._queue.put(LayerMsg(
                    header.src_id, header.layer_id, src, header.total_size,
                    stripe_idx=header.stripe_idx, stripe_n=header.stripe_n,
                    stripe_off=header.stripe_off, job_id=header.job_id,
                    shard=header.shard, codec=header.codec,
                    span_id=header.span_id,
                    span_parent=header.span_parent))
                return
            if self.layer_sink is not None:
                # Sink present but declined (duplicate/overlap/finished):
                # bounce THIS stripe as its own fragment — the receiver's
                # interval reassembly (or its re-ack path) absorbs it.
                buf = alloc_recv_buffer(header.layer_size)
                self._recv_body(conn, memoryview(buf),
                                header.layer_size, pipe_sock)
                ok, crc_ms = self._frame_ok(header, memoryview(buf))
                if not ok:
                    return
                landed = True
                self._log_stripe(header, t0, placed=False, crc_ms=crc_ms)
                self._queue.put(LayerMsg(
                    header.src_id, header.layer_id,
                    LayerSrc(inmem_data=buf, data_size=header.layer_size,
                             offset=header.offset,
                             meta=LayerMeta(location=LayerLocation.INMEM)),
                    header.total_size,
                    stripe_idx=header.stripe_idx, stripe_n=header.stripe_n,
                    stripe_off=header.stripe_off, job_id=header.job_id,
                    shard=header.shard, codec=header.codec,
                    span_id=header.span_id,
                    span_parent=header.span_parent))
                return
            # No sink: regroup stripes into the original logical payload
            # so un-striped consumers (mode-0/1/2 receivers, raw
            # transport users) see exactly the message the sender passed
            # to send().  The group buffer is the final LayerSrc buffer —
            # stripes still land socket→payload in one copy.
            base = header.offset - header.stripe_off
            span = header.stripe_span
            done = None
            with self._lock:
                if key in self._stripe_done:
                    # Late duplicate of an already-delivered transfer (a
                    # sender retry whose first copy landed): drain the
                    # body, never resurrect a group for it.
                    rec = None
                else:
                    rec = self._stripe_groups.get(key)
                    if rec is None:
                        rec = self._stripe_groups[key] = {
                            "buf": alloc_recv_buffer(span), "span": span,
                            "base": base, "got": set(),
                            "t": time.monotonic(), "inflight": 0,
                            "total": header.total_size,
                        }
                    # The in-flight count keeps the prune off a group one
                    # of whose stripes is still mid-receive (a slow link
                    # can legitimately stream past the idle TTL).
                    rec["inflight"] += 1
                    rec["t"] = time.monotonic()
            if rec is None:
                self._drain_stripe_body(conn, header.layer_size, pipe_sock)
                landed = True
                return
            view = memoryview(rec["buf"])[
                header.stripe_off : header.stripe_off + header.layer_size]
            try:
                self._recv_body(conn, view, header.layer_size, pipe_sock)
            except BaseException:
                with self._lock:
                    rec["inflight"] -= 1
                raise
            ok, crc_ms = self._frame_ok(header, view, notify=False)
            if not ok:
                # A corrupt stripe poisons the whole regroup (plain
                # receivers expect ONE whole message, so a range
                # retransmit can't patch the group): tombstone it (late
                # sibling stripes drain; the retransmit's fresh tid
                # forms a new group) and NACK the WHOLE logical span.
                with self._lock:
                    rec["inflight"] -= 1
                    self._stripe_groups.pop(key, None)
                    self._stripe_done[key] = time.monotonic()
                integrity.fire_on_corrupt(
                    self.on_corrupt, header.src_id, header.layer_id,
                    base, span, header.total_size, "crc")
                return
            landed = True
            self._log_stripe(header, t0, placed=False, crc_ms=crc_ms)
            with self._lock:
                rec["inflight"] -= 1
                rec["got"].add(header.stripe_idx)
                rec["t"] = time.monotonic()
                if len(rec["got"]) >= header.stripe_n:
                    done = self._stripe_groups.pop(key, None)
                    if done is not None:
                        self._stripe_done[key] = time.monotonic()
            if done is not None:
                self._queue.put(LayerMsg(
                    header.src_id, header.layer_id,
                    LayerSrc(inmem_data=done["buf"], data_size=done["span"],
                             offset=done["base"],
                             meta=LayerMeta(location=LayerLocation.INMEM)),
                    done["total"],
                    stripe_idx=0, stripe_n=1, stripe_off=0,
                    job_id=header.job_id,
                    shard=header.shard, codec=header.codec,
                    span_id=header.span_id,
                    span_parent=header.span_parent))
        finally:
            if pipe_sock is not None:
                pipe_sock.close()
                if landed:
                    # Only a fully-relayed stripe retires its relay slot:
                    # a failed receive means the downstream copy is
                    # partial too, and the sender's retry must be relayed
                    # again (per-idx, so a duplicate can't over-retire).
                    self._stripe_relay_done(key, header.stripe_idx)

    def _drain_stripe_body(self, conn: socket.socket, n: int,
                           pipe_sock) -> None:
        """Consume (and discard) a stripe body that has no local landing
        — the connection framing must stay intact for whatever message
        follows on it.  A teed pipe still gets the bytes."""
        buf = memoryview(bytearray(min(n, _CHUNK)))
        got = 0
        while got < n:
            r = conn.recv_into(buf[: min(len(buf), n - got)])
            if r == 0:
                raise ConnectionError("connection closed mid-stripe")
            if pipe_sock is not None:
                pipe_sock.sendall(buf[:r])
            got += r

    def _stripe_sweep_loop(self) -> None:
        """Periodic TTL sweep of the striped-receive state (half-TTL
        cadence); exits when the transport closes.  NACKs for pruned
        groups fire OUTSIDE the lock — the receiver's ``on_corrupt``
        hook sends over this same transport, whose send path briefly
        takes ``self._lock``."""
        while not self._closed.wait(_STRIPE_GROUP_TTL / 2):
            with self._lock:
                notices = self._prune_stripe_groups_locked()
            for src_id, layer_id, base, span, total in notices:
                self._notify_corrupt(src_id, layer_id, base, span, total,
                                     "stale")

    def _prune_stripe_groups_locked(self) -> list:
        """Drop striped-receive state whose sender went silent (it died
        after exhausting its per-stripe retry) so abandoned transfers
        can't pin layer-sized buffers — or leak completion tombstones
        and relay countdowns — forever.  Groups with a stripe mid-recv
        (``inflight`` > 0) are never pruned.  Caller holds
        ``self._lock``.  Returns NACK notices ``(src, layer, base, span,
        total)`` for each abandoned group: the dead sender's half-layer
        is RE-REQUESTED from its source (best-effort — the source may be
        the dead sender itself, in which case crash detection remains
        the recovery) instead of silently discarded."""
        now = time.monotonic()
        notices = []
        for key in [k for k, r in self._stripe_groups.items()
                    if r["inflight"] <= 0
                    and now - r["t"] > _STRIPE_GROUP_TTL]:
            rec = self._stripe_groups.pop(key)
            log.warn("dropping stale stripe reassembly group", key=key)
            # Tombstone: straggler stripes of the pruned transfer drain
            # instead of resurrecting a fresh group for a dead tid.
            self._stripe_done[key] = now
            notices.append((key[0], key[1], rec["base"], rec["span"],
                            rec["total"]))
        for key in [k for k, t in self._stripe_done.items()
                    if now - t > _STRIPE_GROUP_TTL]:
            del self._stripe_done[key]
        for key in [k for k, r in self._stripe_relays.items()
                    if now - r["t"] > _STRIPE_GROUP_TTL]:
            log.warn("dropping stale stripe relay record", key=key)
            del self._stripe_relays[key]
        return notices

    def _log_stripe(self, header: LayerHeader, t0: float, placed: bool,
                    crc_ms: float = 0.0) -> None:
        self._telemetry_rx(header, (time.monotonic() - t0) * 1000,
                           crc_ms, placed=placed)
        log.info(
            "(a fraction of) layer received",
            layerID=header.layer_id,
            offset=header.offset,
            layer_size=header.layer_size,
            total_size=header.total_size,
            duration_ms=round((time.monotonic() - t0) * 1000, 3),
            crc_ms=round(crc_ms, 3),
            placed=placed,
            stripe=f"{header.stripe_idx + 1}/{header.stripe_n}",
        )

    # ------------------------------------------------------------------ tx

    def _get_or_connect(self, dest_addr: str) -> Optional[_PConn]:
        """Persistent control connection, dialed on demand
        (transport.go:228-256); None means 'myself'.  The registry lock is
        held only to look up/create the entry — the (possibly slow,
        retrying) dial runs under the per-connection lock."""
        if dest_addr == self.addr:
            return None
        with self._lock:
            pconn = self._conns.get(dest_addr)
            if pconn is None:
                pconn = _PConn()
                self._conns[dest_addr] = pconn
        with pconn.lock:
            if pconn.sock is None:
                try:
                    pconn.sock = _dial(_parse_addr(dest_addr), self._closed)
                except OSError:
                    self._evict(dest_addr, pconn)
                    raise
                # Watch the dialed conn for FIN/RST on the shared
                # readiness loop (the old per-peer drain thread).
                # Dialed control conns are write-only by protocol
                # (replies arrive on the PEER'S dial to OUR listener),
                # so readable means the peer closed — without the
                # watch, a peer restart leaves a half-closed socket in
                # the pool and the NEXT send to it succeeds silently
                # (TCP buffers the bytes, the RST arrives later): one
                # message vanishes without tripping the send path's
                # evict-and-redial retry.
                _readiness_loop().watch_drain(self, pconn.sock,
                                              dest_addr, pconn)
        return pconn

    def _evict(self, dest_addr: str, pconn: _PConn) -> None:
        """Drop a broken control connection so the next send re-dials."""
        with self._lock:
            if self._conns.get(dest_addr) is pconn:
                del self._conns[dest_addr]
        if pconn.sock is not None:
            try:
                pconn.sock.close()
            except OSError:
                pass

    def send(self, dest_id: NodeID, message: Message) -> None:
        dest = self.addr_registry.get(dest_id)
        if dest is None:
            raise KeyError(f"addr of {dest_id} does not exist")

        if isinstance(message, LayerMsg):
            streams = self._send_layer_pooled(dest, message)
            # Sent without raising: file the frame(s) on the (src, dest)
            # link — ``tx_stripe_frames / tx_frames`` is the run's
            # average stripe occupancy for the link.
            telemetry.link_add(
                message.src_id, dest_id, job=message.job_id,
                tx_bytes=message.layer_src.data_size, tx_frames=1,
                tx_stripe_frames=streams if streams > 1 else 0)
            return

        envelope = {
            "type": int(message.msg_type),
            "src": str(getattr(message, "src_id", self.addr)),
            "payload": message.to_payload(),
        }
        # A cached connection may have died (peer restart): evict and
        # re-dial with bounded jittered backoff (utils/backoff.py) —
        # the reference poisons the conn forever; the pre-backoff code
        # here retried exactly once, immediately, which a failover
        # window (leader seat rebinding) routinely outlasted.
        delays = Backoff(base=0.05, factor=2.0, max_delay=0.8,
                         retries=_SEND_RETRIES,
                         seed=hash(dest) & 0xFFFF).delays()
        for attempt in range(_SEND_RETRIES + 1):
            pconn = self._get_or_connect(dest)
            if pconn is None:
                self._queue.put(message)  # self-send short-circuit
                return
            try:
                with pconn.lock:
                    _send_frame(pconn.sock, envelope)
                return
            except OSError:
                self._evict(dest, pconn)
                if attempt >= _SEND_RETRIES:
                    raise
                time.sleep(next(delays, 0.05))

    def _send_layer_pooled(self, dest: str, message: LayerMsg) -> int:
        """One layer transfer over pooled data connection(s); returns
        the number of concurrent streams the payload rode (1 =
        un-striped) for the sender-side stripe-occupancy accounting.

        Payloads past ``STRIPE_THRESHOLD`` split into stripes riding
        several pooled connections CONCURRENTLY (``_send_layer_striped``)
        so one logical transfer can saturate the link instead of one
        socket; smaller (or rate-limited) payloads take the single-stream
        path below.

        A pooled connection may be stale (peer restarted while it idled):
        the first attempt may fail mid-stream, in which case the transfer
        retries once on a FRESH dial.  A half-sent fragment on the dead
        connection is harmless — the receiver drops partial bodies on
        connection error, and interval reassembly tolerates the re-send.
        """
        src = message.layer_src
        if (STRIPE_COUNT > 1
                and src.data_size >= max(STRIPE_THRESHOLD, 2 * STRIPE_MIN)
                and (src.meta.limit_rate == 0
                     or src.meta.limit_rate >= STRIPE_PACED_MIN_RATE)
                and src.meta.location in (LayerLocation.INMEM,
                                          LayerLocation.HBM,
                                          LayerLocation.DISK)):
            spans = stripe_offsets(src.data_size, STRIPE_COUNT, STRIPE_MIN)
            if len(spans) > 1 and self._send_layer_striped(
                    dest, message, spans):
                return len(spans)
        self._send_one_stream(dest, message)
        return 1

    def _send_one_stream(self, dest: str, message: LayerMsg,
                         stripe: Optional[dict] = None) -> None:
        """One byte stream (a whole payload, or one stripe of one) over a
        pooled data connection, with the stale-connection retry: attempt
        0 uses a pooled conn (free to fail — the peer may have restarted
        while it idled), later attempts dial FRESH with jittered
        exponential backoff (utils/backoff.py) before the OSError
        surfaces.  A half-sent fragment on a dead connection is harmless
        — the receiver drops partial bodies on connection error, and
        interval reassembly tolerates the re-send."""
        delays = Backoff(base=0.05, factor=2.0, max_delay=0.8,
                         retries=_SEND_RETRIES,
                         seed=(hash(dest) ^ message.layer_id) & 0xFFFF
                         ).delays()
        for attempt in range(_SEND_RETRIES + 1):
            fresh = attempt > 0
            last = attempt >= _SEND_RETRIES
            sock = None
            try:
                sock = (self._dial_data(dest) if fresh
                        else self._acquire_data_conn(dest))
                self._send_layer(sock, message, stripe=stripe)
            except OSError:
                if sock is not None:
                    sock.close()  # state unknown: never pool a broken conn
                if last:
                    raise
                time.sleep(next(delays, 0.05))
                continue
            except Exception:
                # Non-socket failure (e.g. an unserveable LayerSrc) can
                # strike after the header frame is on the wire: the conn
                # is mid-message — close it, never pool it, don't retry.
                if sock is not None:
                    sock.close()
                raise
            self._release_data_conn(dest, sock)
            return

    def _send_layer_striped(self, dest: str, message: LayerMsg,
                            spans) -> bool:
        """Send one logical payload as ``len(spans)`` stripes over that
        many pooled data connections in parallel.  Each stripe is an
        independent single-stream send (own pooled checkout, own stale
        retry); the first stripe runs on the calling thread.  Returns
        False without touching the wire when the source can't serve
        concurrent range reads (the caller then streams it whole)."""
        src = message.layer_src
        if src.meta.location == LayerLocation.HBM and src.inmem_data is None:
            # One device→host fetch up front; stripes then slice host RAM.
            if not src.ensure_host_bytes():
                return False
        if (src.meta.location in (LayerLocation.INMEM, LayerLocation.HBM)
                and src.inmem_data is None):
            return False
        tid = f"{next(self._stripe_tid):x}"
        n = len(spans)
        errors: List[BaseException] = []

        def send_stripe(idx: int, rel_off: int, size: int) -> None:
            meta = src.meta
            if meta.limit_rate > 0:
                # Split the commanded budget proportionally: N paced
                # stripes together still flow at (almost exactly) the
                # allotted rate.
                meta = LayerMeta(
                    location=meta.location,
                    limit_rate=max(1, meta.limit_rate * size
                                   // src.data_size),
                    source_type=meta.source_type,
                )
            sub = LayerSrc(
                inmem_data=src.inmem_data, fp=src.fp, data_size=size,
                offset=src.offset + rel_off, meta=meta,
            )
            stripe = {"idx": idx, "n": n, "off": rel_off,
                      "span": src.data_size, "tid": tid}
            try:
                self._send_one_stream(
                    dest,
                    LayerMsg(message.src_id, message.layer_id, sub,
                             message.total_size, job_id=message.job_id,
                             shard=message.shard, codec=message.codec,
                             span_id=message.span_id,
                             span_parent=message.span_parent),
                    stripe=stripe)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors.append(e)

        # Concurrent stripes ride the bounded tx pool (utils/threads.py)
        # — stripe 0 runs on the calling thread (run_all's guaranteed-
        # progress slot), so a saturated pool serializes extra stripes
        # instead of spawning a thread per stripe.
        threads.tx_pool().run_all(
            [(send_stripe, i, off, size)
             for i, (off, size) in enumerate(spans)])
        if errors:
            raise errors[0]
        return True

    def _dial_data(self, dest: str) -> socket.socket:
        return _dial(_parse_addr(dest), self._closed)

    def _acquire_data_conn(self, dest: str) -> socket.socket:
        with self._lock:
            pool = self._data_pool.get(dest)
            if pool:
                return pool.pop()
        return self._dial_data(dest)

    def _release_data_conn(self, dest: str, sock: socket.socket) -> None:
        with self._lock:
            if not self._closed.is_set():
                self._data_pool.setdefault(dest, []).append(sock)
                return
        sock.close()

    def _send_layer(self, sock: socket.socket, message: LayerMsg,
                    stripe: Optional[dict] = None) -> None:
        """Header then raw body (transport.go:308-373).  In-memory bodies
        ride the header's scatter-gather ``sendmsg`` (no concat, one
        syscall batch); disk bodies keep the kernel ``sendfile`` path —
        including disk-backed STRIPES, which sendfile serves by
        (offset, count) with no host read at all.  Every frame is
        stamped with the advisory checksum (xxh3-64 where available,
        crc32 otherwise — ``integrity.fragment_checksum``) of exactly
        its payload bytes (per stripe), computed BEFORE anything touches
        the wire."""
        src = message.layer_src
        header = LayerHeader(
            src_id=message.src_id,
            layer_id=message.layer_id,
            layer_size=src.data_size,
            total_size=message.total_size,
            offset=src.offset,
            job_id=message.job_id,
            shard=message.shard,
            codec=message.codec,
            span_id=message.span_id,
            span_parent=message.span_parent,
        )
        if stripe is not None:
            header.stripe_idx = stripe["idx"]
            header.stripe_n = stripe["n"]
            header.stripe_off = stripe["off"]
            header.stripe_span = stripe["span"]
            header.stripe_tid = stripe["tid"]

        # HBM-staged layers keep their host buffer and serve like INMEM;
        # fabric-delivered layers never had one — materialize it from the
        # device array (one cached device→host fetch) so an HBM owner can
        # re-serve over the host path too.
        if (src.meta.location == LayerLocation.HBM
                and src.inmem_data is None):
            src.ensure_host_bytes()
        data = None
        if (src.meta.location in (LayerLocation.INMEM, LayerLocation.HBM)
                and src.inmem_data is not None):
            data = memoryview(src.inmem_data)[src.offset : src.offset + src.data_size]
        if message.crc is not None or message.xxh3 is not None:
            header.crc = message.crc  # caller-stamped (tests)
            header.xxh3 = message.xxh3
        elif integrity.wire_crc_enabled():
            t_crc = time.thread_time()
            stamp = None
            if data is not None:
                stamp = integrity.fragment_checksum(data)
            elif src.meta.location == LayerLocation.DISK and src.fp:
                # One warm page-cache checksum sweep; the body itself
                # still leaves via kernel sendfile below.
                stamp = integrity.file_checksum(src.fp, src.offset,
                                                src.data_size)
            if stamp is not None:
                algo, value = stamp
                if algo == "xxh3":
                    header.xxh3 = value
                else:
                    header.crc = value
                trace.add_phase("integrity_crc_send",
                                time.thread_time() - t_crc)
        envelope = {
            "type": int(MsgType.LAYER),
            "src": str(message.src_id),
            "payload": header.to_payload(),
        }
        if data is not None:
            if src.meta.limit_rate > 0:
                _send_frame(sock, envelope)
                log.debug(
                    "sending with limit",
                    layerID=message.layer_id,
                    mibps=src.meta.limit_rate >> 20,
                )
                PacedWriter(sock.sendall, src.meta.limit_rate).write(data)
            else:
                body = json.dumps(envelope).encode()
                _sendmsg_all(sock, (_LEN.pack(len(body)), body, data))
        elif src.meta.location == LayerLocation.DISK:
            if not src.fp:
                raise ValueError("no data source specified")
            _send_frame(sock, envelope)
            # Zero-copy kernel sendfile, the io.Copy(SectionReader) path.
            with open(src.fp, "rb") as f:
                sock.sendfile(f, offset=src.offset, count=src.data_size)
        else:
            raise ValueError(f"cannot send layer {message.layer_id} from {src.meta}")

    def broadcast(self, message: Message) -> None:
        with self._lock:
            ids = list(self.addr_registry)
        for dest_id in ids:
            try:
                self.send(dest_id, message)
            except (OSError, KeyError) as e:
                log.error("failed to broadcast", dest=dest_id, err=e)

    # ------------------------------------------------------------------ pipes

    def register_pipe(self, layer_id: LayerID, dest_id: NodeID) -> None:
        with self._lock:
            if layer_id in self._pipes:
                raise ValueError("pipe already registered")
            self._pipes[layer_id] = dest_id

    def _get_and_unregister_pipe(self, layer_id: LayerID) -> Optional[socket.socket]:
        """Fresh data connection to the pipe's downstream node (closed by
        the relay when the layer completes)."""
        with self._lock:
            dest_id = self._pipes.pop(layer_id, None)
        if dest_id is None:
            return None
        dest = self.addr_registry.get(dest_id)
        if dest is None:
            log.error("addr does not exist", dest=dest_id)
            return None
        try:
            return _dial(_parse_addr(dest), self._closed)
        except OSError as e:
            log.error("failed to connect pipe dest", dest=dest_id, err=e)
            return None

    # ------------------------------------------------------------------ misc

    def deliver(self) -> "queue.Queue[Message]":
        return self._queue

    def get_address(self) -> str:
        return self.addr

    def close(self) -> None:
        self._closed.set()
        # Unhook from the shared readiness loop first, so the selector
        # stops dispatching on sockets the shutdown below is closing.
        _readiness_loop().unwatch_all(self)
        try:
            # shutdown() wakes the thread blocked in accept(); close()
            # alone leaves the kernel listener alive (the syscall holds a
            # reference) and the port stays bound.
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
            pooled = [s for pool in self._data_pool.values() for s in pool]
            self._data_pool.clear()
            accepted = list(self._accepted)
            self._accepted.clear()
        # shutdown() before close(), for the same reason as the listener
        # above: a thread blocked in recv() on the socket holds the
        # kernel file reference, so close() alone sends NO FIN until
        # that syscall returns — peers would never learn we went away
        # (their drain threads keep the stale conn pooled, and their
        # next send to this seat's address silently vanishes).
        for sock in pooled + [p.sock for p in conns if p.sock] + accepted:
            for op in (lambda: sock.shutdown(socket.SHUT_RDWR), sock.close):
                try:
                    op()
                except OSError:
                    pass
