"""TCP transport: the real two-plane network backend.

Re-design of the reference's ``TcpTransport``
(``/root/reference/distributor/transport.go:28-491``) with cleaner framing:

- **Control plane**: length-prefixed JSON envelopes (4-byte big-endian size
  + ``{"type", "src", "payload"}``) on persistent per-peer connections with
  a per-connection write lock (the reference instead streams back-to-back
  JSON objects, transport.go:100-124).
- **Data plane**: a ``LayerMsg`` travels as an envelope whose payload is the
  ``LayerHeader``, followed by exactly ``layer_size`` raw bytes — on a
  per-destination POOLED data connection: sequential transfers (a flow
  job's 16 MiB fragments) share one connection instead of paying a
  handshake + slow-start per fragment, while concurrent transfers still
  fan out over as many connections as are in flight.  (The reference dials
  fresh per transfer, transport.go:267-274 — fine for whole-layer sends,
  ~640 dials for a fragmented 10 GiB flow job.)
- In-memory layers are paced by a token bucket (transport.go:407-424); disk
  layers go out via ``socket.sendfile`` — the zero-copy path matching the
  reference's ``io.Copy(SectionReader)`` sendfile (transport.go:357-367).
- A registered ``(layer_id → dest_id)`` pipe relays an incoming layer to a
  downstream node *while* it is being received, chunk by chunk — cut-through
  relay, the reference's TeeReader trick (transport.go:144-196).
- Self-sends short-circuit into the local delivery queue
  (transport.go:282-285).
"""

from __future__ import annotations

import json
import queue
import socket
import struct
import threading
import time
from typing import Dict, Optional, Tuple

from ..core.types import LayerID, LayerLocation, LayerMeta, LayerSrc, NodeID
from ..utils.buffers import alloc_recv_buffer
from ..utils.logging import log
from ..utils.rate import PacedWriter
from .base import AddrRegistry, Transport
from .messages import (
    LayerHeader,
    LayerMsg,
    Message,
    MsgType,
    decode_msg,
)

_LEN = struct.Struct("!I")
_CHUNK = 1 << 20  # 1 MiB receive/relay chunk
# Dial retry window: the reference has no retries at all (errors are only
# logged, node.go:345-348), so peers racing the leader's listener die.
_DIAL_TIMEOUT = 10.0
_DIAL_RETRY_DELAY = 0.2


def _dial(addr: Tuple[str, int], closed: threading.Event) -> socket.socket:
    """create_connection with retry/backoff until _DIAL_TIMEOUT elapses."""
    deadline = time.monotonic() + _DIAL_TIMEOUT
    while True:
        try:
            sock = socket.create_connection(addr, timeout=_DIAL_TIMEOUT)
            sock.settimeout(None)
            return sock
        except OSError:
            if closed.is_set() or time.monotonic() >= deadline:
                raise
            time.sleep(_DIAL_RETRY_DELAY)


def _normalize(addr: str) -> str:
    """':8080' listens on all interfaces; dial via localhost."""
    return addr if not addr.startswith(":") else "127.0.0.1" + addr


def _parse_addr(addr: str) -> Tuple[str, int]:
    host, _, port = _normalize(addr).rpartition(":")
    return host or "127.0.0.1", int(port)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("connection closed mid-read")
        got += r
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Optional[dict]:
    """Read one length-prefixed JSON envelope; None on clean EOF."""
    try:
        hdr = _recv_exact(sock, _LEN.size)
    except ConnectionError:
        return None
    (size,) = _LEN.unpack(hdr)
    return json.loads(_recv_exact(sock, size))


def _send_frame(sock: socket.socket, envelope: dict) -> None:
    body = json.dumps(envelope).encode()
    sock.sendall(_LEN.pack(len(body)) + body)


class _PConn:
    """A persistent control connection + its write lock
    (transport.go:42-45).  ``sock`` is None until the first dial completes;
    dialing happens under this connection's own lock so one unreachable
    peer never stalls sends to the others."""

    def __init__(self, sock: Optional[socket.socket] = None):
        self.sock = sock
        self.lock = threading.Lock()


class TcpTransport(Transport):
    def __init__(
        self,
        addr: str,
        buf_size: int = 1024,
        addr_registry: Optional[AddrRegistry] = None,
        is_client: bool = False,
    ):
        self.addr = addr
        self.addr_registry: AddrRegistry = dict(addr_registry or {})
        self.is_client = is_client
        self._queue: "queue.Queue[Message]" = queue.Queue(maxsize=buf_size)
        self._conns: Dict[str, _PConn] = {}
        # dest addr -> idle data connections (LIFO: the hottest conn has
        # the widest cwnd).  Checked out per layer transfer, returned
        # after a clean send; never shared concurrently.
        self._data_pool: Dict[str, list] = {}
        self._accepted: "set[socket.socket]" = set()
        self._pipes: Dict[LayerID, NodeID] = {}
        self._lock = threading.Lock()
        self._closed = threading.Event()
        # Zero-copy receive hook (set by a reassembling receiver):
        # sink(layer_id, total_size, offset, size) -> None, or
        # (view, token, abort_fn) — a writable memoryview straight into
        # the destination reassembly buffer, the coverage claim token
        # the handler will commit, and the rollback for a failed recv.
        # When it engages, fragment bytes go socket→assembly in ONE
        # copy (no bounce buffer, no handler memcpy) — the hot path at
        # physical layer sizes on memory-bandwidth-bound hosts.
        self.layer_sink = None

        host, port = _parse_addr(addr)
        self._listener = socket.create_server((host, port), reuse_port=False)
        # Record the kernel-chosen port when addr asked for :0 (tests).
        if port == 0:
            actual = self._listener.getsockname()[1]
            self.addr = f"{host}:{actual}" if not addr.startswith(":") else f":{actual}"
        log.info("start listening", addr=self.addr)
        threading.Thread(target=self._accept_loop, daemon=True).start()

    # ------------------------------------------------------------------ rx

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            with self._lock:
                self._accepted.add(conn)
            threading.Thread(
                target=self._recv_loop, args=(conn,), daemon=True
            ).start()

    def _recv_loop(self, conn: socket.socket) -> None:
        """Per-connection reader (transport.go:97-225)."""
        try:
            while True:
                envelope = _recv_frame(conn)
                if envelope is None:
                    return
                mtype = MsgType(envelope["type"])
                if mtype != MsgType.LAYER:
                    self._queue.put(decode_msg(mtype, envelope["payload"]))
                    continue
                self._receive_layer(conn, envelope)
        except (ConnectionError, OSError, ValueError, KeyError) as e:
            if not self._closed.is_set():
                log.error("receive loop failed", err=e)
        finally:
            with self._lock:
                self._accepted.discard(conn)
            conn.close()

    def _receive_layer(self, conn: socket.socket, envelope: dict) -> None:
        header = LayerHeader.from_payload(envelope["payload"])
        log.info(
            "start receiving layer",
            layerID=header.layer_id,
            layer_size=header.layer_size,
            total_size=header.total_size,
        )
        t0 = time.monotonic()

        pipe_sock = self._get_and_unregister_pipe(header.layer_id)
        placed = None
        if pipe_sock is None and self.layer_sink is not None:
            placed = self.layer_sink(header.layer_id, header.total_size,
                                     header.offset, header.layer_size)
        if placed is not None:
            view, token, abort = placed
            try:
                got = 0
                while got < header.layer_size:
                    r = conn.recv_into(view[got:],
                                       header.layer_size - got)
                    if r == 0:
                        raise ConnectionError("connection closed mid-layer")
                    got += r
            except BaseException:
                abort()  # roll the claim back or the layer wedges forever
                raise
            dur_ms = (time.monotonic() - t0) * 1000
            log.info(
                "(a fraction of) layer received",
                layerID=header.layer_id,
                layer_size=header.layer_size,
                total_size=header.total_size,
                duration_ms=round(dur_ms, 3),
                placed=True,
            )
            src = LayerSrc(
                inmem_data=None, data_size=header.layer_size,
                offset=header.offset,
                meta=LayerMeta(location=LayerLocation.INMEM),
            )
            src.placed_token = token
            self._queue.put(LayerMsg(header.src_id, header.layer_id, src,
                                     header.total_size))
            return
        buf = alloc_recv_buffer(header.layer_size)
        view = memoryview(buf)
        if pipe_sock is not None:
            # Cut-through relay: stream chunks to the downstream node while
            # receiving (transport.go:144-196) — over a FRESH data
            # connection, like every other layer transfer, so a multi-GiB
            # relay never head-of-line blocks control messages to that peer
            # (the reference relays through the shared-mutex control
            # connection, transport.go:144-196 + :42-45).  The forwarded
            # header keeps the original src, matching the reference (TODO
            # at :152-164).
            try:
                _send_frame(pipe_sock, envelope)
                got = 0
                while got < header.layer_size:
                    r = conn.recv_into(view[got:], min(_CHUNK, header.layer_size - got))
                    if r == 0:
                        raise ConnectionError("connection closed mid-layer")
                    pipe_sock.sendall(view[got : got + r])
                    got += r
            finally:
                pipe_sock.close()
        else:
            got = 0
            while got < header.layer_size:
                r = conn.recv_into(view[got:], header.layer_size - got)
                if r == 0:
                    raise ConnectionError("connection closed mid-layer")
                got += r

        dur_ms = (time.monotonic() - t0) * 1000
        log.info(
            "(a fraction of) layer received",
            layerID=header.layer_id,
            layer_size=header.layer_size,
            total_size=header.total_size,
            duration_ms=round(dur_ms, 3),
        )
        layer_src = LayerSrc(
            inmem_data=buf,
            data_size=header.layer_size,
            offset=header.offset,
            meta=LayerMeta(location=LayerLocation.INMEM),
        )
        self._queue.put(
            LayerMsg(header.src_id, header.layer_id, layer_src, header.total_size)
        )

    # ------------------------------------------------------------------ tx

    def _get_or_connect(self, dest_addr: str) -> Optional[_PConn]:
        """Persistent control connection, dialed on demand
        (transport.go:228-256); None means 'myself'.  The registry lock is
        held only to look up/create the entry — the (possibly slow,
        retrying) dial runs under the per-connection lock."""
        if dest_addr == self.addr:
            return None
        with self._lock:
            pconn = self._conns.get(dest_addr)
            if pconn is None:
                pconn = _PConn()
                self._conns[dest_addr] = pconn
        with pconn.lock:
            if pconn.sock is None:
                try:
                    pconn.sock = _dial(_parse_addr(dest_addr), self._closed)
                except OSError:
                    self._evict(dest_addr, pconn)
                    raise
                threading.Thread(
                    target=self._drain_control, args=(dest_addr, pconn),
                    daemon=True,
                ).start()
        return pconn

    def _drain_control(self, dest_addr: str, pconn: _PConn) -> None:
        """Evict a dialed control connection the moment the peer closes.

        Dialed control conns are write-only by protocol (replies arrive
        on the PEER'S dial to OUR listener), so a recv() here only ever
        returns on FIN/RST.  Without this, a peer restart leaves a
        half-closed socket in the pool and the NEXT send to it succeeds
        silently (TCP buffers the bytes, the RST arrives later) — one
        message vanishes without tripping the send path's evict-and-
        redial retry.  A rebound seat (a genreq requester reusing an
        idle seat's address, a restarted node) would lose exactly its
        first reply that way."""
        sock = pconn.sock
        try:
            while sock.recv(4096):
                pass  # peers never write here; discard until EOF
        except OSError:
            pass
        if not self._closed.is_set():
            self._evict(dest_addr, pconn)

    def _evict(self, dest_addr: str, pconn: _PConn) -> None:
        """Drop a broken control connection so the next send re-dials."""
        with self._lock:
            if self._conns.get(dest_addr) is pconn:
                del self._conns[dest_addr]
        if pconn.sock is not None:
            try:
                pconn.sock.close()
            except OSError:
                pass

    def send(self, dest_id: NodeID, message: Message) -> None:
        dest = self.addr_registry.get(dest_id)
        if dest is None:
            raise KeyError(f"addr of {dest_id} does not exist")

        if isinstance(message, LayerMsg):
            self._send_layer_pooled(dest, message)
            return

        envelope = {
            "type": int(message.msg_type),
            "src": str(getattr(message, "src_id", self.addr)),
            "payload": message.to_payload(),
        }
        # A cached connection may have died (peer restart): evict and
        # re-dial once.  The reference poisons the conn forever.
        for attempt in (0, 1):
            pconn = self._get_or_connect(dest)
            if pconn is None:
                self._queue.put(message)  # self-send short-circuit
                return
            try:
                with pconn.lock:
                    _send_frame(pconn.sock, envelope)
                return
            except OSError:
                self._evict(dest, pconn)
                if attempt == 1:
                    raise

    def _send_layer_pooled(self, dest: str, message: LayerMsg) -> None:
        """One layer transfer over a pooled data connection.

        A pooled connection may be stale (peer restarted while it idled):
        the first attempt may fail mid-stream, in which case the transfer
        retries once on a FRESH dial.  A half-sent fragment on the dead
        connection is harmless — the receiver drops partial bodies on
        connection error, and interval reassembly tolerates the re-send.
        """
        for attempt in (0, 1):
            fresh = attempt == 1
            sock = None
            try:
                sock = (self._dial_data(dest) if fresh
                        else self._acquire_data_conn(dest))
                self._send_layer(sock, message)
            except OSError:
                if sock is not None:
                    sock.close()  # state unknown: never pool a broken conn
                if fresh:
                    raise
                continue
            except Exception:
                # Non-socket failure (e.g. an unserveable LayerSrc) can
                # strike after the header frame is on the wire: the conn
                # is mid-message — close it, never pool it, don't retry.
                if sock is not None:
                    sock.close()
                raise
            self._release_data_conn(dest, sock)
            return

    def _dial_data(self, dest: str) -> socket.socket:
        return _dial(_parse_addr(dest), self._closed)

    def _acquire_data_conn(self, dest: str) -> socket.socket:
        with self._lock:
            pool = self._data_pool.get(dest)
            if pool:
                return pool.pop()
        return self._dial_data(dest)

    def _release_data_conn(self, dest: str, sock: socket.socket) -> None:
        with self._lock:
            if not self._closed.is_set():
                self._data_pool.setdefault(dest, []).append(sock)
                return
        sock.close()

    def _send_layer(self, sock: socket.socket, message: LayerMsg) -> None:
        """Header then raw body (transport.go:308-373)."""
        src = message.layer_src
        header = LayerHeader(
            src_id=message.src_id,
            layer_id=message.layer_id,
            layer_size=src.data_size,
            total_size=message.total_size,
            offset=src.offset,
        )
        _send_frame(
            sock,
            {
                "type": int(MsgType.LAYER),
                "src": str(message.src_id),
                "payload": header.to_payload(),
            },
        )

        # HBM-staged layers keep their host buffer and serve like INMEM;
        # fabric-delivered layers never had one — materialize it from the
        # device array (one cached device→host fetch) so an HBM owner can
        # re-serve over the host path too.
        if (src.meta.location == LayerLocation.HBM
                and src.inmem_data is None):
            src.ensure_host_bytes()
        if (src.meta.location in (LayerLocation.INMEM, LayerLocation.HBM)
                and src.inmem_data is not None):
            data = memoryview(src.inmem_data)[src.offset : src.offset + src.data_size]
            if src.meta.limit_rate > 0:
                log.debug(
                    "sending with limit",
                    layerID=message.layer_id,
                    mibps=src.meta.limit_rate >> 20,
                )
                PacedWriter(sock.sendall, src.meta.limit_rate).write(data)
            else:
                sock.sendall(data)
        elif src.meta.location == LayerLocation.DISK:
            if not src.fp:
                raise ValueError("no data source specified")
            # Zero-copy kernel sendfile, the io.Copy(SectionReader) path.
            with open(src.fp, "rb") as f:
                sock.sendfile(f, offset=src.offset, count=src.data_size)
        else:
            raise ValueError(f"cannot send layer {message.layer_id} from {src.meta}")

    def broadcast(self, message: Message) -> None:
        with self._lock:
            ids = list(self.addr_registry)
        for dest_id in ids:
            try:
                self.send(dest_id, message)
            except (OSError, KeyError) as e:
                log.error("failed to broadcast", dest=dest_id, err=e)

    # ------------------------------------------------------------------ pipes

    def register_pipe(self, layer_id: LayerID, dest_id: NodeID) -> None:
        with self._lock:
            if layer_id in self._pipes:
                raise ValueError("pipe already registered")
            self._pipes[layer_id] = dest_id

    def _get_and_unregister_pipe(self, layer_id: LayerID) -> Optional[socket.socket]:
        """Fresh data connection to the pipe's downstream node (closed by
        the relay when the layer completes)."""
        with self._lock:
            dest_id = self._pipes.pop(layer_id, None)
        if dest_id is None:
            return None
        dest = self.addr_registry.get(dest_id)
        if dest is None:
            log.error("addr does not exist", dest=dest_id)
            return None
        try:
            return _dial(_parse_addr(dest), self._closed)
        except OSError as e:
            log.error("failed to connect pipe dest", dest=dest_id, err=e)
            return None

    # ------------------------------------------------------------------ misc

    def deliver(self) -> "queue.Queue[Message]":
        return self._queue

    def get_address(self) -> str:
        return self.addr

    def close(self) -> None:
        self._closed.set()
        try:
            # shutdown() wakes the thread blocked in accept(); close()
            # alone leaves the kernel listener alive (the syscall holds a
            # reference) and the port stays bound.
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
            pooled = [s for pool in self._data_pool.values() for s in pool]
            self._data_pool.clear()
            accepted = list(self._accepted)
            self._accepted.clear()
        # shutdown() before close(), for the same reason as the listener
        # above: a thread blocked in recv() on the socket holds the
        # kernel file reference, so close() alone sends NO FIN until
        # that syscall returns — peers would never learn we went away
        # (their drain threads keep the stale conn pooled, and their
        # next send to this seat's address silently vanishes).
        for sock in pooled + [p.sock for p in conns if p.sock] + accepted:
            for op in (lambda: sock.shutdown(socket.SHUT_RDWR), sock.close):
                try:
                    op()
                except OSError:
                    pass
