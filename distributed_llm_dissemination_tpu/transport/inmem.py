"""Process-local fake transport for protocol tests.

Re-design of the reference's ``InmemoryTransport``
(``/root/reference/distributor/transport.go:494-631``): messages land
straight in peers' delivery queues via a global addr→transport registry, so
multi-node protocol logic runs in one process with no sockets.  Unlike the
reference's fake, this one also honors layer semantics: a ``LayerMsg`` is
materialized to in-RAM bytes on delivery (what the TCP receive path does)
and registered pipes relay the layer onward — so the client/relay paths are
testable in-process too.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, Optional

from ..core.types import LayerID, LayerLocation, LayerMeta, LayerSrc, NodeID
from ..utils import integrity, telemetry, trace
from ..utils.logging import log
from .base import AddrRegistry, Transport
from .messages import LayerMsg, Message

# Global registry: addr -> transport instance (transport.go:507-511).
_registry: Dict[str, "InmemTransport"] = {}
_registry_lock = threading.Lock()


def reset_registry() -> None:
    """Test helper: forget all registered transports."""
    with _registry_lock:
        _registry.clear()


class InmemTransport(Transport):
    def __init__(
        self,
        addr: str,
        buf_size: int = 1024,
        addr_registry: Optional[AddrRegistry] = None,
        is_client: bool = False,
    ):
        self.addr = addr
        self.addr_registry: AddrRegistry = dict(addr_registry or {})
        self.is_client = is_client
        self._queue: "queue.Queue[Message]" = queue.Queue(maxsize=buf_size)
        self._pipes: Dict[LayerID, NodeID] = {}
        self._lock = threading.Lock()
        self._closed = False
        # Integrity hooks, mirroring TcpTransport (docs/integrity.md):
        # ``recv_tamper(info, view) -> bool`` is the TEST-ONLY fault hook
        # (transport/faults.py) run on landed bytes BEFORE verification
        # (False = inject a drop); ``on_corrupt(src_id, layer_id, offset,
        # size, total_size, reason)`` fires when a frame is dropped for a
        # failed check — the receiver runtime NACKs the source from it.
        self.recv_tamper = None
        self.on_corrupt = None
        # Telemetry identity (utils/telemetry.py): bound by
        # runtime.node.Node; None = record nothing.
        self.node_id = None
        with _registry_lock:
            _registry[addr] = self

    # -- internal -----------------------------------------------------------

    def _resolve(self, dest_id: NodeID) -> "InmemTransport":
        addr = self.addr_registry.get(dest_id, str(dest_id))
        with _registry_lock:
            peer = _registry.get(addr)
        if peer is None:
            raise ConnectionError(f"peer {addr} not found")
        return peer

    def _deliver_local(self, message: Message) -> None:
        if isinstance(message, LayerMsg):
            self._receive_layer(message)
        else:
            self._queue.put(message)

    def _receive_layer(self, message: LayerMsg) -> None:
        """Mimic the TCP receive path: materialize the byte range to RAM,
        verify the payload's advisory CRC (dropping + reporting corrupt
        frames exactly like the wire transport), relay through a
        registered pipe if one exists, then deliver."""
        src = message.layer_src
        # Materialize exactly the [offset, offset+data_size) range, like the
        # TCP wire does; the landed fragment keeps the offset so a mode-3
        # receiver can reassemble it into place.
        data = bytearray(src.read_range())
        # The "wire" checksum: sender-stamped when present, else the
        # bytes as sent (computed BEFORE the fault hook below —
        # in-process there is no real wire, so this IS the sender-side
        # stamp).  xxh3-64 where available, crc32 otherwise, exactly
        # like the TCP sender (``integrity.fragment_checksum``).  With
        # no tamper hook installed nothing can touch the bytearray
        # between stamp and verify, so the self-stamp would be two
        # tautological hash passes per frame — skip it; an inbound
        # sender stamp is still verified either way.
        crc, xxh3 = message.crc, message.xxh3
        if (crc is None and xxh3 is None and self.recv_tamper is not None
                and integrity.wire_crc_enabled()):
            algo, value = integrity.fragment_checksum(data)
            if algo == "xxh3":
                xxh3 = value
            else:
                crc = value
        if not self._frame_ok(message, data, crc, xxh3):
            return
        # The verified frame lands on the (src, me) link of the flight
        # recorder — in-process there is no wire to wait on, so only
        # bytes/frames are filed (verify time is filed by _frame_ok).
        telemetry.link_add(message.src_id, self.node_id,
                           job=message.job_id,
                           rx_bytes=len(data), rx_frames=1)
        landed = LayerSrc(
            inmem_data=data,
            data_size=len(data),
            offset=src.offset,
            meta=LayerMeta(location=LayerLocation.INMEM),
        )
        relayed = LayerMsg(
            src_id=message.src_id,
            layer_id=message.layer_id,
            layer_src=landed,
            total_size=message.total_size,
            crc=crc,
            xxh3=xxh3,
            job_id=message.job_id,
            shard=message.shard,
            codec=message.codec,
            span_id=message.span_id,
            span_parent=message.span_parent,
        )
        with self._lock:
            pipe_dest = self._pipes.pop(message.layer_id, None)
        if pipe_dest is not None:
            # Cut-through relay (transport.go:144-196): forward while
            # "receiving".  In-process this is just a second delivery.
            try:
                self._resolve(pipe_dest)._deliver_local(relayed)
            except ConnectionError as e:
                log.error("failed to relay layer", layer=message.layer_id, err=e)
        self._queue.put(relayed)

    def _frame_ok(self, message: LayerMsg, data: bytearray,
                  crc, xxh3) -> bool:
        """Fault hook + checksum verification for one landed frame;
        False means the frame was dropped (and reported via
        ``on_corrupt``, through the reporter shared with the TCP
        transport)."""
        import time as _time

        src = message.layer_src
        reason = None
        tamper = self.recv_tamper
        if tamper is not None:
            info = {"src": message.src_id, "layer": message.layer_id,
                    "offset": src.offset, "size": len(data),
                    "total": message.total_size}
            try:
                if tamper(info, memoryview(data)) is False:
                    reason = "drop"
            except Exception as e:  # noqa: BLE001 — test hook must not wedge rx
                log.error("recv_tamper hook failed", err=repr(e))
        if reason is None and integrity.wire_crc_enabled():
            t0 = _time.thread_time()
            ok = integrity.verify_stamp(data, crc=crc, xxh3=xxh3)
            if ok is not None:
                dt = _time.thread_time() - t0
                trace.add_phase("integrity_crc_recv", dt)
                telemetry.link_add(message.src_id, self.node_id,
                                   verify_s=dt)
                if not ok:
                    reason = "crc"
        if reason is None:
            return True
        integrity.report_corrupt_frame(
            self.on_corrupt, message.src_id, message.layer_id,
            src.offset, len(data), message.total_size, reason,
            dest_id=self.node_id)
        return False

    # -- Transport API ------------------------------------------------------

    def send(self, dest_id: NodeID, message: Message) -> None:
        self._resolve(dest_id)._deliver_local(message)
        if isinstance(message, LayerMsg):
            telemetry.link_add(message.src_id, dest_id,
                               job=message.job_id,
                               tx_bytes=message.layer_src.data_size,
                               tx_frames=1)

    def broadcast(self, message: Message) -> None:
        with _registry_lock:
            peers = [t for a, t in _registry.items() if a != self.addr]
        for peer in peers:
            peer._deliver_local(message)

    def register_pipe(self, layer_id: LayerID, dest_id: NodeID) -> None:
        with self._lock:
            if layer_id in self._pipes:
                raise ValueError("pipe already registered")
            self._pipes[layer_id] = dest_id

    def deliver(self) -> "queue.Queue[Message]":
        return self._queue

    def get_address(self) -> str:
        return self.addr

    def close(self) -> None:
        with _registry_lock:
            _registry.pop(self.addr, None)
        self._closed = True
