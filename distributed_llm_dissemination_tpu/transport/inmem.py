"""Process-local fake transport for protocol tests.

Re-design of the reference's ``InmemoryTransport``
(``/root/reference/distributor/transport.go:494-631``): messages land
straight in peers' delivery queues via a global addr→transport registry, so
multi-node protocol logic runs in one process with no sockets.  Unlike the
reference's fake, this one also honors layer semantics: a ``LayerMsg`` is
materialized to in-RAM bytes on delivery (what the TCP receive path does)
and registered pipes relay the layer onward — so the client/relay paths are
testable in-process too.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, Optional

from ..core.types import LayerID, LayerLocation, LayerMeta, LayerSrc, NodeID
from ..utils.logging import log
from .base import AddrRegistry, Transport
from .messages import LayerMsg, Message

# Global registry: addr -> transport instance (transport.go:507-511).
_registry: Dict[str, "InmemTransport"] = {}
_registry_lock = threading.Lock()


def reset_registry() -> None:
    """Test helper: forget all registered transports."""
    with _registry_lock:
        _registry.clear()


class InmemTransport(Transport):
    def __init__(
        self,
        addr: str,
        buf_size: int = 1024,
        addr_registry: Optional[AddrRegistry] = None,
        is_client: bool = False,
    ):
        self.addr = addr
        self.addr_registry: AddrRegistry = dict(addr_registry or {})
        self.is_client = is_client
        self._queue: "queue.Queue[Message]" = queue.Queue(maxsize=buf_size)
        self._pipes: Dict[LayerID, NodeID] = {}
        self._lock = threading.Lock()
        self._closed = False
        with _registry_lock:
            _registry[addr] = self

    # -- internal -----------------------------------------------------------

    def _resolve(self, dest_id: NodeID) -> "InmemTransport":
        addr = self.addr_registry.get(dest_id, str(dest_id))
        with _registry_lock:
            peer = _registry.get(addr)
        if peer is None:
            raise ConnectionError(f"peer {addr} not found")
        return peer

    def _deliver_local(self, message: Message) -> None:
        if isinstance(message, LayerMsg):
            self._receive_layer(message)
        else:
            self._queue.put(message)

    def _receive_layer(self, message: LayerMsg) -> None:
        """Mimic the TCP receive path: materialize the byte range to RAM,
        relay through a registered pipe if one exists, then deliver."""
        src = message.layer_src
        # Materialize exactly the [offset, offset+data_size) range, like the
        # TCP wire does; the landed fragment keeps the offset so a mode-3
        # receiver can reassemble it into place.
        data = bytearray(src.read_range())
        landed = LayerSrc(
            inmem_data=data,
            data_size=len(data),
            offset=src.offset,
            meta=LayerMeta(location=LayerLocation.INMEM),
        )
        relayed = LayerMsg(
            src_id=message.src_id,
            layer_id=message.layer_id,
            layer_src=landed,
            total_size=message.total_size,
        )
        with self._lock:
            pipe_dest = self._pipes.pop(message.layer_id, None)
        if pipe_dest is not None:
            # Cut-through relay (transport.go:144-196): forward while
            # "receiving".  In-process this is just a second delivery.
            try:
                self._resolve(pipe_dest)._deliver_local(relayed)
            except ConnectionError as e:
                log.error("failed to relay layer", layer=message.layer_id, err=e)
        self._queue.put(relayed)

    # -- Transport API ------------------------------------------------------

    def send(self, dest_id: NodeID, message: Message) -> None:
        self._resolve(dest_id)._deliver_local(message)

    def broadcast(self, message: Message) -> None:
        with _registry_lock:
            peers = [t for a, t in _registry.items() if a != self.addr]
        for peer in peers:
            peer._deliver_local(message)

    def register_pipe(self, layer_id: LayerID, dest_id: NodeID) -> None:
        with self._lock:
            if layer_id in self._pipes:
                raise ValueError("pipe already registered")
            self._pipes[layer_id] = dest_id

    def deliver(self) -> "queue.Queue[Message]":
        return self._queue

    def get_address(self) -> str:
        return self.addr

    def close(self) -> None:
        with _registry_lock:
            _registry.pop(self.addr, None)
        self._closed = True
