"""Deterministic fault-injection transport (docs/integrity.md).

``FaultyTransport`` wraps any concrete ``Transport`` and injects a
SEEDED, fully deterministic schedule of faults — the machinery that
*proves* the integrity plane instead of trusting it:

- **corrupt** (inbound layer frames): flips a payload byte below the
  CRC check, via the wrapped transport's ``recv_tamper`` hook — exactly
  where real wire/DMA corruption lands.  The transport must detect it
  (advisory CRC), drop the frame, and NACK the source.
- **drop** (inbound layer frames): discards the landed frame through the
  same hook (the transport treats it like a CRC failure: claim rolled
  back, NACK sent) — modeling a frame that arrived damaged beyond
  reading.  Inbound drops of CONTROL messages (e.g. SPMD ``DevicePlanMsg``
  by seq — the ported ``-test-drop-plan-seqs`` path) really vanish: their
  loss-recovery is the gap-report/watchdog machinery, not a NACK.
- **dup** (outbound): sends the message twice — reassembly and re-ack
  paths must absorb it.
- **delay** (outbound): sleeps before sending — reordering pressure.
- **reset** (outbound): raises ``ConnectionError`` to the caller —
  the path under test must survive a peer reset at send time.

Determinism: every rule matches message events in arrival order and
fires on every ``every``-th match with a phase derived from ``seed`` —
no randomness, so a failing chaos run replays bit-for-bit from its seed.

Construction-gated like the old ``-test-drop-plan-seqs`` (ADVICE r5): a
production process never wraps its transport, so no environment variable
can inject faults into a real run.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import List, Optional, Tuple

from ..core.types import LayerID, NodeID
from ..utils.logging import log
from .base import Transport
from .messages import DevicePlanMsg, LayerMsg, Message, MsgType


@dataclasses.dataclass
class FaultRule:
    """One deterministic fault: WHAT to do, WHERE (out = this node's
    sends, in = this node's receive path), WHICH messages match, and
    WHEN to fire (every Nth match, at most ``times`` times).

    Two TIME-SCHEDULED kinds ride the same record (docs/failover.md —
    leader-kill and split-brain tests must be seeded, not sleep-based):
    ``partition`` (bidirectional drop between this node and ``dest``
    during [t_start, t_end), both directions, all message types) and
    ``kill`` (hard-stop this node's whole transport at ``t_start``:
    sends raise, inbound vanishes).  Both are evaluated against the
    clock started at FaultyTransport construction, so a spec replays
    the same failure timeline every run."""

    kind: str  # "corrupt" | "drop" | "dup" | "delay" | "reset"
    #          | "partition" | "kill" | "join" | "leave"
    direction: str = "out"  # "out" (send-side) | "in" (receive-side)
    # Matchers; None = wildcard.
    msg_type: Optional[MsgType] = None
    layer: Optional[LayerID] = None
    src: Optional[NodeID] = None  # message src_id ("in" rules)
    dest: Optional[NodeID] = None  # send destination ("out" rules)
    offset_lo: int = 0  # fragment-range matchers ("in" layer rules):
    offset_hi: int = 1 << 62  # match frames overlapping [lo, hi)
    seq: Optional[int] = None  # DevicePlanMsg seq matcher
    # Firing schedule.
    every: int = 1  # fire on every Nth match...
    times: int = 0  # ...at most this many times (0 = unlimited)
    # Action parameters.
    delay_s: float = 0.0  # "delay"
    rate: int = 0  # "slow": bytes/second for the injected link limit
    flip_at: int = 0  # "corrupt": byte index within the fragment
    flip_mask: int = 0xFF  # "corrupt": XOR mask (non-zero)
    # Time schedule ("partition"/"kill"): seconds since transport
    # construction.  t_end None = forever.
    t_start: float = 0.0
    t_end: Optional[float] = None
    # Mutable counters (per-rule; FaultyTransport guards with its lock).
    matches: int = dataclasses.field(default=0, repr=False)
    fired: int = dataclasses.field(default=0, repr=False)

    def _matches_common(self, mtype, layer, seq) -> bool:
        if self.msg_type is not None and mtype != self.msg_type:
            return False
        if self.layer is not None and layer != self.layer:
            return False
        if self.seq is not None and seq != self.seq:
            return False
        return True

    def should_fire(self, phase: int) -> bool:
        """Advance the match counter; True when this match is a firing
        one.  Caller has already checked the matchers."""
        self.matches += 1
        if self.times and self.fired >= self.times:
            return False
        if (self.matches - 1) % max(1, self.every) != phase % max(
                1, self.every):
            return False
        self.fired += 1
        return True


def rules_from_spec(spec: str) -> Tuple[int, List[FaultRule]]:
    """Parse the CLI's compact fault spec into rules.  Grammar:
    comma-separated ``key=value`` pairs —

    - ``seed=N``: deterministic phase for every periodic rule
    - ``corrupt=N`` / ``dropin=N``: corrupt/drop every Nth INBOUND layer
      frame (0 = off)
    - ``drop=N`` / ``dup=N`` / ``reset=N``: every Nth OUTBOUND layer send
    - ``delay=N:MS``: delay every Nth outbound layer send by MS ms
    - ``times=K``: cap each generated rule at K firings (0 = unlimited)
    - ``drop-plan-seqs=a;b;c``: drop the FIRST inbound delivery of the
      named SPMD plan seqs (the ported ``-test-drop-plan-seqs``)
    - ``resetany=N``: like ``reset`` but matching EVERY outbound message
      type (control included) — the leader-routed requeue path's test
      hook
    - ``partition=P[@T1[-T2]]``: bidirectional drop between this node
      and node P during [T1, T2) seconds after construction (defaults:
      T1=0, T2=forever) — seeded split-brain, not sleep-based
    - ``kill_after=T``: hard-stop this node's transport T seconds after
      construction (sends raise ``ConnectionError``, inbound vanishes)
      — the deterministic leader-kill switch
    - ``join=T``: elastic-membership churn schedule (docs/membership.md)
      — this node is DARK (sends raise, inbound vanishes: it does not
      exist yet) until T seconds after construction, then comes alive;
      the harness reads ``FaultyTransport.join_at`` and fires the
      seat's ``join()`` at that moment — a seeded late-join, not a
      sleep in test code
    - ``leave=T``: the departure half of the churn schedule — purely an
      exposed timestamp (``FaultyTransport.leave_at``): the harness
      initiates the node's graceful DRAIN at T.  The transport itself
      stays healthy (a drain is planned, not a fault); pair with
      ``kill_after`` to model a crash-leave instead
    - ``slow=RATE[@P]``: rate-limit this node's outbound LAYER sends to
      peer P (all peers when omitted) to RATE bytes/second via a token
      bucket — the deterministic straggler-link injection the live-swap
      chaos case needs (a replica whose v2 staging lags the fleet while
      v1 keeps serving, docs/swap.md)
    - ``slowserve=MS[:N]``: delay every Nth (default: every) outbound
      GENERATE_RESP by MS ms — the deterministic BAD-WAVE injection of
      the rollout pipeline (docs/rollout.md): a wave's replicas answer
      slowly enough to breach the declared p99 SLO, without dropping a
      single request
    - ``flap=P@T1-T2[:N]``: a FLAPPING link to peer P — the [T1, T2)
      window splits into N (default 3) equal up/down cycles, each
      cycle's first half DOWN (a partition to P) and second half up.
      Purely sugar over ``partition``: the parser emits N partition
      rules with deterministic windows, so a flap replays bit-for-bit
      like every time-scheduled fault.  The autonomy chaos case
      (docs/autonomy.md) uses it to prove a flapping link is
      quarantined/demoted ONCE, not toggled every interval

    e.g. ``seed=7,corrupt=9,dropin=13,dup=11,times=8``.  Returns
    ``(seed, rules)`` — hand both to ``FaultyTransport``."""
    seed = 0
    times = 0
    pending = []  # (factory taking (seed, times))
    for part in [p.strip() for p in spec.split(",") if p.strip()]:
        key, _, val = part.partition("=")
        key = key.strip().lower()
        val = val.strip()
        if key == "seed":
            seed = int(val)
            continue
        if key == "times":
            times = int(val)
            continue
        if key == "partition":
            peer, _, window = val.partition("@")
            t1s, _, t2s = window.partition("-")
            t1 = float(t1s) if t1s else 0.0
            t2 = float(t2s) if t2s else None
            pending.append(lambda sd, tm, p=int(peer), a=t1, b=t2:
                           FaultRule("partition", "out", dest=p,
                                     t_start=a, t_end=b))
            continue
        if key == "flap":
            peer, _, rest = val.partition("@")
            window, _, n_s = rest.partition(":")
            t1s, _, t2s = window.partition("-")
            if not t2s:
                raise ValueError(
                    "flap needs a bounded window: flap=P@T1-T2[:N]")
            t1, t2 = float(t1s or 0.0), float(t2s)
            cycles = int(n_s or 3)
            if cycles < 1 or t2 <= t1:
                raise ValueError(f"bad flap window/cycles: {val!r}")
            # N down/up cycles of equal width: cycle i is DOWN for
            # [t1 + 2iW, t1 + (2i+1)W), up for the next W.
            w = (t2 - t1) / (2 * cycles)
            for i in range(cycles):
                a = t1 + 2 * i * w
                pending.append(lambda sd, tm, p=int(peer), a=a, b=a + w:
                               FaultRule("partition", "out", dest=p,
                                         t_start=a, t_end=b))
            continue
        if key == "kill_after":
            pending.append(lambda sd, tm, t=float(val):
                           FaultRule("kill", "out", t_start=t))
            continue
        if key == "join":
            pending.append(lambda sd, tm, t=float(val):
                           FaultRule("join", "out", t_start=t))
            continue
        if key == "leave":
            pending.append(lambda sd, tm, t=float(val):
                           FaultRule("leave", "out", t_start=t))
            continue
        if key == "slow":
            rate_s, _, peer = val.partition("@")
            pending.append(lambda sd, tm, r=int(rate_s),
                           p=(int(peer) if peer else None):
                           FaultRule("slow", "out",
                                     msg_type=MsgType.LAYER,
                                     dest=p, rate=r))
            continue
        if key == "slowserve":
            ms_s, _, n_s = val.partition(":")
            pending.append(lambda sd, tm, ms=float(ms_s),
                           n=int(n_s or 1): FaultRule(
                "delay", "out", msg_type=MsgType.GENERATE_RESP,
                every=n, times=tm, delay_s=ms / 1000.0))
            continue
        if key == "resetany":
            n = int(val)
            if n > 0:
                pending.append(lambda sd, tm, n=n: FaultRule(
                    "reset", "out", every=n, times=tm))
            continue
        if key == "drop-plan-seqs":
            for s in [x for x in val.split(";") if x.strip()]:
                pending.append(lambda sd, tm, s=int(s): FaultRule(
                    "drop", "in", msg_type=MsgType.DEVICE_PLAN,
                    seq=s, times=1))
            continue
        if key == "delay":
            n, _, ms = val.partition(":")
            if int(n) > 0:
                pending.append(lambda sd, tm, n=int(n),
                               ms=float(ms or 1.0): FaultRule(
                    "delay", "out", msg_type=MsgType.LAYER, every=n,
                    times=tm, delay_s=ms / 1000.0))
            continue
        if key in ("corrupt", "dropin", "drop", "dup", "reset"):
            n = int(val)
            if n <= 0:
                continue
            kind = {"dropin": "drop"}.get(key, key)
            direction = "in" if key in ("corrupt", "dropin") else "out"
            pending.append(lambda sd, tm, k=kind, d=direction, n=n:
                           FaultRule(k, d, msg_type=MsgType.LAYER,
                                     every=n, times=tm))
            continue
        raise ValueError(f"unknown fault spec key: {key!r}")
    return seed, [f(seed, times) for f in pending]


class FaultyTransport(Transport):
    """A seeded fault-injecting wrapper over any concrete transport.

    Send-side ("out") rules intercept ``send``/``broadcast``; inbound
    LAYER rules install a ``recv_tamper`` hook on the wrapped transport
    (so corruption lands BELOW the CRC check, exactly like the wire);
    inbound CONTROL rules run on a pump thread between the inner
    delivery queue and this transport's own — a dropped control message
    really vanishes.  Everything else (pipes, sinks, corruption
    reporting, addressing) delegates to the wrapped transport, so
    receivers wire their hooks through this wrapper unchanged."""

    def __init__(self, inner: Transport, rules=(), seed: int = 0):
        from ..utils.rate import TokenBucket

        self.inner = inner
        self.rules: List[FaultRule] = [
            r for r in rules
            if r.kind not in ("partition", "kill", "slow", "join",
                              "leave")]
        self.seed = seed
        self._lock = threading.Lock()
        self.stats = {"corrupt": 0, "drop": 0, "dup": 0, "delay": 0,
                      "reset": 0, "partition": 0, "kill": 0, "slow": 0,
                      "join": 0}
        # slow=RATE@P: a persistent per-link rate limit (token bucket),
        # not an every-Nth rule — the injected straggler link.
        self._slow = [(r.dest, TokenBucket(r.rate)) for r in rules
                      if r.kind == "slow" and r.rate > 0]
        self._q: "queue.Queue[Message]" = queue.Queue()
        self._stop = threading.Event()
        # Time-scheduled faults (docs/failover.md): the clock starts NOW,
        # so a spec's partition windows and kill time replay identically
        # run to run.
        self._t0 = time.monotonic()
        self._partitions = [(r.dest, r.t_start, r.t_end) for r in rules
                            if r.kind == "partition"]
        kills = [r.t_start for r in rules if r.kind == "kill"]
        self._kill_at = min(kills) if kills else None
        # Churn schedule (docs/membership.md): the node is DARK before
        # join_at (it does not exist yet); leave_at is purely an
        # exposed timestamp the harness drains the node at.  Both are
        # seconds since construction, like every time-scheduled fault.
        joins = [r.t_start for r in rules if r.kind == "join"]
        self.join_at = min(joins) if joins else None
        leaves = [r.t_start for r in rules if r.kind == "leave"]
        self.leave_at = min(leaves) if leaves else None
        need_tamper = (
            any(r.direction == "in" and r.msg_type in (None, MsgType.LAYER)
                for r in self.rules)
            or self._partitions or self._kill_at is not None
            or self.join_at is not None)
        if need_tamper:
            if hasattr(inner, "recv_tamper"):
                inner.recv_tamper = self._tamper
            else:
                log.warn("inner transport has no recv_tamper hook; "
                         "inbound layer faults will not fire")
        self._pump = threading.Thread(target=self._pump_loop, daemon=True,
                                      name="fault-pump")
        self._pump.start()

    # ------------------------------------------------- time-scheduled faults

    def _killed(self) -> bool:
        return (self._kill_at is not None
                and time.monotonic() - self._t0 >= self._kill_at)

    def _dark(self) -> bool:
        """True before the join schedule says this node exists
        (docs/membership.md): sends raise, inbound vanishes — a seeded
        late joiner, invisible until its moment."""
        return (self.join_at is not None
                and time.monotonic() - self._t0 < self.join_at)

    def seconds_until_join(self):
        """Remaining dark time (None = no join schedule): the harness
        sleeps this long, then fires the seat's ``join()``."""
        if self.join_at is None:
            return None
        return max(0.0, self._t0 + self.join_at - time.monotonic())

    def seconds_until_leave(self):
        """Remaining time to the scheduled graceful drain (None = no
        leave schedule)."""
        if self.leave_at is None:
            return None
        return max(0.0, self._t0 + self.leave_at - time.monotonic())

    def _partitioned(self, peer) -> bool:
        """Whether traffic between this node and ``peer`` is currently
        inside an active partition window."""
        if peer is None or not self._partitions:
            return False
        now = time.monotonic() - self._t0
        for p, t1, t2 in self._partitions:
            if p == peer and now >= t1 and (t2 is None or now < t2):
                return True
        return False

    # ------------------------------------------------------------ matching

    def _fire(self, kind: str, direction: str, mtype, layer=None,
              seq=None, dest=None, src=None, offset=None,
              size=None) -> Optional[FaultRule]:
        """The first rule of ``kind``/``direction`` matching this event
        that elects to fire (counters advance under the lock)."""
        with self._lock:
            for r in self.rules:
                if r.kind != kind or r.direction != direction:
                    continue
                if not r._matches_common(mtype, layer, seq):
                    continue
                if direction == "out" and r.dest is not None and dest != r.dest:
                    continue
                if direction == "in" and r.src is not None and src != r.src:
                    continue
                if offset is not None and size is not None:
                    if offset + size <= r.offset_lo or offset >= r.offset_hi:
                        continue
                if r.should_fire(self.seed):
                    self.stats[kind] = self.stats.get(kind, 0) + 1
                    return r
        return None

    # ------------------------------------------------------------- inbound

    def _tamper(self, info: dict, view) -> bool:
        """The wrapped transport's receive-path hook: corrupt or drop a
        landed layer frame BEFORE its CRC verification.  Returning False
        injects a drop (the transport treats it exactly like a CRC
        failure: rollback + NACK)."""
        layer = info.get("layer")
        src = info.get("src")
        off = info.get("offset", 0)
        size = info.get("size", len(view))
        if self._killed():
            with self._lock:
                self.stats["kill"] += 1
            return False  # hard-stopped transport: nothing lands
        if self._dark():
            with self._lock:
                self.stats["join"] += 1
            return False  # not joined yet: nothing lands
        if self._partitioned(src):
            with self._lock:
                self.stats["partition"] += 1
            log.warn("FAULT: partition dropping inbound layer frame",
                     layerID=layer, src=src)
            return False
        if self._fire("drop", "in", MsgType.LAYER, layer=layer, src=src,
                      offset=off, size=size) is not None:
            log.warn("FAULT: dropping inbound layer frame", layerID=layer,
                     offset=off, size=size)
            return False
        rule = self._fire("corrupt", "in", MsgType.LAYER, layer=layer,
                          src=src, offset=off, size=size)
        if rule is not None and len(view) > 0:
            at = rule.flip_at % len(view)
            view[at] = view[at] ^ (rule.flip_mask or 0xFF)
            log.warn("FAULT: corrupted inbound layer frame", layerID=layer,
                     offset=off, size=size, at=at)
        return True

    def _pump_loop(self) -> None:
        inner_q = self.inner.deliver()
        while not self._stop.is_set():
            try:
                msg = inner_q.get(timeout=0.1)
            except queue.Empty:
                continue
            if self._killed():
                with self._lock:
                    self.stats["kill"] += 1
                continue  # hard-stopped: inbound vanishes
            if self._dark():
                with self._lock:
                    self.stats["join"] += 1
                continue  # not joined yet: inbound vanishes
            if not isinstance(msg, LayerMsg):
                src = getattr(msg, "src_id", None)
                if self._partitioned(src):
                    with self._lock:
                        self.stats["partition"] += 1
                    log.warn("FAULT: partition dropping inbound control "
                             "message", kind=type(msg).__name__, src=src)
                    continue
                mtype = getattr(msg, "msg_type", None)
                seq = (msg.seq if isinstance(msg, DevicePlanMsg) else None)
                if self._fire("drop", "in", mtype, seq=seq,
                              src=src) is not None:
                    log.warn("FAULT: dropping inbound control message",
                             kind=type(msg).__name__, seq=seq)
                    continue
            self._q.put(msg)

    # ----------------------------------------------------------- transport

    def send(self, dest_id: NodeID, message: Message) -> None:
        mtype = getattr(message, "msg_type", None)
        layer = getattr(message, "layer_id", None)
        seq = (message.seq if isinstance(message, DevicePlanMsg) else None)
        if self._killed():
            with self._lock:
                self.stats["kill"] += 1
            raise ConnectionError("injected fault: transport killed")
        if self._dark():
            with self._lock:
                self.stats["join"] += 1
            raise ConnectionError("injected fault: node not joined yet")
        if self._partitioned(dest_id):
            with self._lock:
                self.stats["partition"] += 1
            log.warn("FAULT: partition dropping outbound message",
                     kind=type(message).__name__, dest=dest_id)
            return
        if self._fire("drop", "out", mtype, layer=layer, seq=seq,
                      dest=dest_id) is not None:
            log.warn("FAULT: dropping outbound message",
                     kind=type(message).__name__, dest=dest_id)
            return
        if self._fire("reset", "out", mtype, layer=layer, seq=seq,
                      dest=dest_id) is not None:
            log.warn("FAULT: injecting connection reset on send",
                     kind=type(message).__name__, dest=dest_id)
            raise ConnectionError("injected fault: peer reset")
        rule = self._fire("delay", "out", mtype, layer=layer, seq=seq,
                          dest=dest_id)
        if rule is not None:
            time.sleep(rule.delay_s)
        if self._slow and isinstance(message, LayerMsg):
            size = getattr(message.layer_src, "data_size", 0)
            for peer, bucket in self._slow:
                if peer is None or peer == dest_id:
                    with self._lock:
                        self.stats["slow"] += 1
                    bucket.wait_n(size)
        self.inner.send(dest_id, message)
        if self._fire("dup", "out", mtype, layer=layer, seq=seq,
                      dest=dest_id) is not None:
            log.warn("FAULT: duplicating outbound message",
                     kind=type(message).__name__, dest=dest_id)
            self.inner.send(dest_id, message)

    def broadcast(self, message: Message) -> None:
        # Broadcasts bypass per-dest out rules on purpose: they carry
        # run-wide control (startup, serve) whose loss has no protocol
        # recovery; targeted faults go through send().
        self.inner.broadcast(message)

    def register_pipe(self, layer_id: LayerID, dest_id: NodeID) -> None:
        self.inner.register_pipe(layer_id, dest_id)

    def deliver(self) -> "queue.Queue[Message]":
        return self._q

    def get_address(self) -> str:
        return self.inner.get_address()

    def close(self) -> None:
        self._stop.set()
        self.inner.close()

    # Hook pass-throughs: receivers set these on "the transport" without
    # caring whether it is wrapped.
    @property
    def layer_sink(self):
        return getattr(self.inner, "layer_sink", None)

    @layer_sink.setter
    def layer_sink(self, fn) -> None:
        self.inner.layer_sink = fn

    @property
    def on_corrupt(self):
        return getattr(self.inner, "on_corrupt", None)

    @on_corrupt.setter
    def on_corrupt(self, fn) -> None:
        self.inner.on_corrupt = fn

    @property
    def addr_registry(self):
        return self.inner.addr_registry

    @property
    def node_id(self):
        return getattr(self.inner, "node_id", None)

    @node_id.setter
    def node_id(self, value) -> None:
        # The INNER transport does the per-frame telemetry accounting
        # (utils/telemetry.py), so the node identity must land there.
        self.inner.node_id = value
