from .base import AddrRegistry, Transport  # noqa: F401
from .faults import FaultRule, FaultyTransport, rules_from_spec  # noqa: F401
from .inmem import InmemTransport, reset_registry  # noqa: F401
from .messages import (  # noqa: F401
    AckMsg,
    AnnounceMsg,
    ClientReqMsg,
    FlowRetransmitMsg,
    LayerDigestsMsg,
    LayerHeader,
    LayerMsg,
    LayerNackMsg,
    Message,
    MsgType,
    RetransmitMsg,
    SimpleMsg,
    StartupMsg,
    decode_msg,
    src_of,
)
from .tcp import TcpTransport  # noqa: F401
