"""Sharded training-state checkpointing (orbax).

The dissemination layer journals LAYER BYTES (``runtime/checkpoint.py``
— fsync'd fragment intervals, resume plans only the gaps).  This module
is the TRAINING side of durability: (params, AdamW state) saved and
restored WITH their shardings, so a restarted pod resumes exactly —
each process writes/reads only its own shards (orbax handles the
per-host fan-out on a real multi-host mesh).

The reference has no training loop at all; this exists because a
TPU-native framework whose dissemination feeds a training mesh needs
the other half of the crash story: weights land (dissemination resume)
AND optimization continues (state restore), without either path caring
about the other.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding

from .llama import ModelConfig
from .sharded import adamw_state_specs, param_specs


def _state_shardings(cfg: ModelConfig, mesh: Mesh):
    """NamedShardings for the (params, opt) tree — derived from the same
    specs the train step runs with, so a restored state is placed
    EXACTLY where the donated-buffer step expects it."""
    to_sharding = lambda spec: NamedSharding(mesh, spec)  # noqa: E731
    return {
        "params": jax.tree.map(to_sharding, param_specs(cfg)),
        "opt": jax.tree.map(to_sharding, adamw_state_specs(cfg)),
    }


def save_train_state(path: str, params, opt_state) -> None:
    """Write {params, opt} atomically (orbax tmp+rename).  Every leaf
    keeps its dtype; on multi-host meshes each process persists only
    its addressable shards."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, {"params": params, "opt": opt_state}, force=True)
        ckptr.wait_until_finished()


def restore_train_state(path: str, cfg: ModelConfig, mesh: Mesh):
    """(params, opt_state) restored onto ``mesh`` with the train step's
    shardings — ready to feed ``build_adamw_train_step`` directly.

    The target tree (structure + shapes + dtypes + shardings) is built
    from the config, NOT trusted from disk: restoring under a different
    topology places shards for THIS mesh, and a checkpoint whose
    structure disagrees fails loudly instead of materializing
    mis-sharded state."""
    import numpy as np
    import orbax.checkpoint as ocp

    from .llama import init_params
    from .sharded import init_adamw_state

    shardings = _state_shardings(cfg, mesh)
    # Abstract targets: shape/dtype from a throwaway host init (cheap at
    # config scale), sharding from the train-step specs.
    host_params = jax.eval_shape(
        lambda: init_params(cfg, jax.random.key(0)))
    host_opt = jax.eval_shape(
        lambda: init_adamw_state(
            init_params(cfg, jax.random.key(0))))
    target = {
        "params": jax.tree.map(
            lambda a, sh: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                               sharding=sh),
            host_params, shardings["params"]),
        "opt": jax.tree.map(
            lambda a, sh: jax.ShapeDtypeStruct(
                np.shape(a), a.dtype, sharding=sh),
            host_opt, shardings["opt"]),
    }
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(path, target)
    return restored["params"], restored["opt"]
