"""Autoregressive decoding with a KV cache: the booted engine serves.

The reference's startup hook gestures at "launching an inference engine"
(``/root/reference/distributor/message.go:216-241``); ``runtime/boot.py``
makes the hook assemble the model and produce logits.  This module is
the serving half: a jitted, TPU-shaped decode loop —

- **prefill**: one full-attention pass over the prompt that also writes
  every layer's K/V into a preallocated cache (``lax.dynamic_update_
  slice`` at static offsets);
- **decode**: ``lax.scan`` over steps, each step attending the single
  new query against the cache under a position mask (static shapes —
  the cache is sized to ``prompt + max_new`` up front, so XLA compiles
  ONE step program and reuses it every token).

Greedy decoding is exact: ``tests/test_hf.py`` pins the generated token
ids to the ``transformers`` implementation's ``generate`` on the same
checkpoint.  Sampling takes a temperature + PRNG key.

MoE configs serve too: the cache layer dispatches to the same
``moe_ffn`` as the full forward (each token routes through its top-k
experts), so the dense and MoE paths share one attention/cache
implementation.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .llama import (
    ModelConfig,
    dense_ffn,
    gqa_attention,
    moe_ffn,
    qkv_proj,
    rms_norm,
)

KVCache = Dict[str, jax.Array]  # {"k","v"}: [n_layers, b, max_len, kvh, hd]


class MixedVersionError(ValueError):
    """A serving tree was about to assemble from blobs of more than one
    rollout version — a forward across mixed layer versions would emit
    garbage that LOOKS like a healthy decode (docs/swap.md)."""


def ensure_uniform_version(versions: Dict[int, str],
                           expected: str = "") -> str:
    """The live-swap version guard: every blob entering a serving
    params tree must carry the SAME rollout version tag (and, when
    ``expected`` is non-empty, exactly that one).  Raises
    :class:`MixedVersionError` otherwise; returns the uniform version.
    Runs where params are ASSEMBLED — the one chokepoint every flip
    goes through — so no decode step can ever span two versions."""
    tags = set(versions.values())
    if len(tags) > 1:
        raise MixedVersionError(
            f"refusing to assemble serving params across mixed layer "
            f"versions {sorted(tags)!r}: {dict(sorted(versions.items()))}")
    got = next(iter(tags)) if tags else ""
    if expected and got != expected:
        raise MixedVersionError(
            f"serving params version {got!r} does not match the "
            f"committed version {expected!r}")
    return got


def init_cache(cfg: ModelConfig, batch: int, max_len: int) -> KVCache:
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def _layer_with_cache(
    p: Dict[str, jax.Array], x, positions, k_cache, v_cache, cfg: ModelConfig,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One layer over ``x`` [b, s, d]: writes this block's K/V into the
    cache at ``positions`` and attends against the WHOLE (masked) cache
    — the same ``gqa_attention``/``dense_ffn`` kernels as the cache-less
    forward, with the causal mask generalized to cache-row validity.
    Returns (x_out, k_cache, v_cache)."""
    b, s, d = x.shape
    h, hd = cfg.n_heads, cfg.head_dim
    xn = rms_norm(x, p["ln1"], cfg.norm_eps)
    q, k, v = qkv_proj(p, xn, positions, cfg)
    # Contiguous block write at the first position (prefill writes the
    # prompt at 0; a decode step writes one row at pos).
    start = positions[0]
    k_cache = jax.lax.dynamic_update_slice(k_cache, k, (0, start, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(v_cache, v, (0, start, 0, 0))

    max_len = k_cache.shape[1]
    # Valid: the cache row holds a key at position <= this query's.
    k_valid = jnp.arange(max_len)[None, :] <= positions[:, None]  # [s, max]
    mask = jnp.where(k_valid, 0.0, -jnp.inf).astype(jnp.float32)
    out = gqa_attention(q, k_cache, v_cache, mask)
    x = x + jnp.einsum("bsq,qd->bsd", out.reshape(b, s, h * hd), p["wo"])
    ffn = moe_ffn if cfg.n_experts else dense_ffn
    return ffn(p, x, cfg), k_cache, v_cache


def _forward_with_cache(params, tokens, positions, cache, cfg: ModelConfig):
    """Stacked-layer forward that threads the KV cache; returns
    (logits for the LAST position, updated cache)."""
    x = params["embed"][tokens]

    def body(x, scanned):
        layer_p, k_cache, v_cache = scanned
        x, k_cache, v_cache = _layer_with_cache(
            layer_p, x, positions, k_cache, v_cache, cfg
        )
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rms_norm(x, params["ln_f"], cfg.norm_eps)
    logits = jnp.einsum(
        "bd,dv->bv", x[:, -1, :], params["lm_head"],
        preferred_element_type=jnp.float32,
    )
    return logits, {"k": k_new, "v": v_new}


def _pick(logits, step_key, temperature: float):
    if temperature <= 0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        step_key, logits / temperature, axis=-1
    ).astype(jnp.int32)


@functools.lru_cache(maxsize=32)
def _prefill_fn(cfg: ModelConfig, p: int):
    @jax.jit
    def prefill(params, prompt, cache):
        return _forward_with_cache(params, prompt, jnp.arange(p), cache, cfg)

    return prefill


@functools.lru_cache(maxsize=32)
def _decode_fn(cfg: ModelConfig, p: int, max_new: int, temperature: float):
    @jax.jit
    def decode(params, cache, first, keys):
        def step(carry, scanned):
            cache, token, pos = carry
            step_key, = scanned
            logits, cache = _forward_with_cache(
                params, token[:, None], pos[None], cache, cfg
            )
            nxt = _pick(logits, step_key, temperature)
            return (cache, nxt, pos + 1), token

        (_, last, _), toks = jax.lax.scan(
            step, (cache, first, jnp.asarray(p, jnp.int32)),
            (keys,), length=max_new - 1,
        )
        # toks holds tokens emitted BEFORE each step: [first, ...]; the
        # final pick is `last`.
        return jnp.concatenate([toks.T, last[:, None]], axis=1)

    return decode


@functools.lru_cache(maxsize=32)
def _decode_step_fn(cfg: ModelConfig, temperature: float):
    """ONE jitted decode step (vs ``_decode_fn``'s whole-generation
    scan): forward the carried token at ``pos``, pick the next.  The
    position is a traced scalar, so every step of a generation reuses
    the same compiled program — the per-token flip path costs one
    dispatch per token, not one compile."""

    @jax.jit
    def step(params, cache, token, pos, step_key):
        logits, cache = _forward_with_cache(
            params, token[:, None], pos[None], cache, cfg
        )
        return _pick(logits, step_key, temperature), cache

    return step


def generate_stepwise(
    params_fn,
    prompt: jax.Array,
    cfg: ModelConfig,
    max_new: int,
    temperature: float = 0.0,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Token-at-a-time decoding that RE-READS the serving params before
    every step — the per-token flip granularity of docs/rollout.md: an
    in-flight generation finishes its current token on the params it
    holds and picks up a freshly committed version on the NEXT decode
    step, instead of pinning the flip behind the whole request.

    ``params_fn() -> (params, version)`` is called once for the prefill
    and once per decode step; the caller owns the per-step version
    guard (the receiver's provider runs ``ensure_uniform_version`` on
    the serving tree before returning it, so a step can never execute
    on a mixed-version tree).  With a CONSTANT provider the emitted
    tokens are exactly ``generate``'s — same kernels, same order, the
    scan merely unrolled into per-step dispatches.  Note the KV cache
    rows written before a mid-generation flip were computed under the
    PREVIOUS version — the documented semantics of per-token pickup
    (docs/rollout.md), not a bug: the alternative is serving the stale
    version for the whole request."""
    if max_new <= 0:
        raise ValueError(f"max_new must be positive, got {max_new}")
    if temperature > 0 and key is None:
        raise ValueError("sampling needs a PRNG key")
    b, p = prompt.shape
    cache = init_cache(cfg, b, p + max_new)
    params, _ = params_fn()
    logits, cache = _prefill_fn(cfg, p)(params, prompt, cache)
    keys = (jax.random.split(key, max_new) if key is not None
            else jnp.zeros((max_new, 2), jnp.uint32))
    token = _pick(logits, keys[0], temperature)
    out = [token]
    step = _decode_step_fn(cfg, float(temperature))
    for i in range(1, max_new):
        params, _ = params_fn()
        token, cache = step(params, cache, token,
                            jnp.asarray(p + i - 1, jnp.int32), keys[i])
        out.append(token)
    return jnp.stack(out, axis=1)


def generate(
    params: Dict[str, Any],
    prompt: jax.Array,
    cfg: ModelConfig,
    max_new: int,
    temperature: float = 0.0,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Decode ``max_new`` tokens after ``prompt`` [b, p] (int32).

    temperature 0 = greedy (exact — parity-tested against transformers);
    otherwise softmax sampling with ``key``.  Returns [b, max_new].

    The prefill and decode programs are built per (cfg, shapes,
    temperature) and cached — repeated serving calls on a booted model
    reuse the compiled step, they don't re-trace."""
    if max_new <= 0:
        raise ValueError(f"max_new must be positive, got {max_new}")
    if temperature > 0 and key is None:
        raise ValueError("sampling needs a PRNG key")
    b, p = prompt.shape
    cache = init_cache(cfg, b, p + max_new)

    logits, cache = _prefill_fn(cfg, p)(params, prompt, cache)
    keys = (jax.random.split(key, max_new) if key is not None
            else jnp.zeros((max_new, 2), jnp.uint32))
    first = _pick(logits, keys[0], temperature)
    if max_new == 1:
        return first[:, None]
    return _decode_fn(cfg, p, max_new, temperature)(
        params, cache, first, keys[1:]
    )
