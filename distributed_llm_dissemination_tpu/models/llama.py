"""Llama-style transformer: the model whose layers get disseminated.

The reference treats layers as opaque byte blobs sized like Llama-70B
shards (``/root/reference/conf/config.json``: 8 × 10.18 GiB) and its
``startupMsg`` is "the hook that would launch an inference engine"
(``distributor/message.go:216-241``).  This module supplies that engine:
a pure-JAX (pytree params + functional apply) Llama-3-family model — GQA
attention with RoPE, RMSNorm, SwiGLU FFN, optional MoE — so disseminated
weights boot a real jitted forward pass, and the preset configs give the
benchmark scenarios their true layer sizes.

All matmuls are einsums in bfloat16 with fp32 accumulation — large, batched,
MXU-friendly; no data-dependent Python control flow anywhere.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    name: str = "tiny"
    vocab: int = 256
    d_model: int = 128
    n_layers: int = 4
    n_heads: int = 4
    n_kv_heads: int = 2
    d_ff: int = 256
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # MoE (expert-parallel) variant: 0 experts = dense SwiGLU.
    n_experts: int = 0
    top_k: int = 2

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def layer_nbytes(self) -> int:
        """Bytes of one transformer layer's params in this dtype — the
        'LayerSize' the dissemination configs should use."""
        itemsize = np.dtype(self.dtype).itemsize
        d, f, h, kv = self.d_model, self.d_ff, self.n_heads, self.n_kv_heads
        hd = self.head_dim
        attn = d * h * hd + 2 * d * kv * hd + h * hd * d
        if self.n_experts:
            ffn = self.n_experts * 3 * d * f + d * self.n_experts
        else:
            ffn = 3 * d * f
        norms = 2 * d
        return (attn + ffn + norms) * itemsize


# Real Llama-3 family shapes (public architecture constants) + test sizes.
CONFIGS: Dict[str, ModelConfig] = {
    "tiny": ModelConfig(),
    "tiny-moe": ModelConfig(name="tiny-moe", n_experts=4, top_k=2),
    # ~2 MiB/layer: big enough that the transport's 256 KiB burst bucket
    # is noise — the shape rate-limited wire benchmarks need.
    "tiny2": ModelConfig(
        name="tiny2", vocab=512, d_model=256, n_layers=4,
        n_heads=4, n_kv_heads=2, d_ff=1024,
    ),
    "llama3-8b": ModelConfig(
        name="llama3-8b", vocab=128256, d_model=4096, n_layers=32,
        n_heads=32, n_kv_heads=8, d_ff=14336,
    ),
    # Flagship at reduced depth: full 8B layer SHAPE (so each layer blob
    # is the physical ~416 MiB the bench measures) but 4 layers, fitting
    # one chip next to activations.  The driver's entry() compile check
    # and the TTD matrix's physical-size scenario share it; "v8k" trims
    # the vocab so the head blob doesn't dwarf the layers it escorts.
    "llama3-8b-d4": ModelConfig(
        name="llama3-8b-d4", vocab=128256, d_model=4096, n_layers=4,
        n_heads=32, n_kv_heads=8, d_ff=14336,
    ),
    "llama3-8b-d4v8k": ModelConfig(
        name="llama3-8b-d4v8k", vocab=8192, d_model=4096, n_layers=4,
        n_heads=32, n_kv_heads=8, d_ff=14336,
    ),
    "llama3-70b": ModelConfig(
        name="llama3-70b", vocab=128256, d_model=8192, n_layers=80,
        n_heads=64, n_kv_heads=8, d_ff=28672,
    ),
    "llama3-405b": ModelConfig(
        name="llama3-405b", vocab=128256, d_model=16384, n_layers=126,
        n_heads=128, n_kv_heads=8, d_ff=53248,
    ),
}


# ---------------------------------------------------------------------- init

def init_layer_params(cfg: ModelConfig, key: jax.Array) -> Dict[str, jax.Array]:
    """One transformer layer's weights as a flat dict pytree."""
    d, f = cfg.d_model, cfg.d_ff
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    k = iter(jax.random.split(key, 8))
    scale = d ** -0.5
    p = {
        "wq": jax.random.normal(next(k), (d, h * hd), cfg.dtype) * scale,
        "wk": jax.random.normal(next(k), (d, kv * hd), cfg.dtype) * scale,
        "wv": jax.random.normal(next(k), (d, kv * hd), cfg.dtype) * scale,
        "wo": jax.random.normal(next(k), (h * hd, d), cfg.dtype) * scale,
        "ln1": jnp.ones((d,), cfg.dtype),
        "ln2": jnp.ones((d,), cfg.dtype),
    }
    if cfg.n_experts:
        e = cfg.n_experts
        p["router"] = jax.random.normal(next(k), (d, e), cfg.dtype) * scale
        p["w1"] = jax.random.normal(next(k), (e, d, f), cfg.dtype) * scale
        p["w3"] = jax.random.normal(next(k), (e, d, f), cfg.dtype) * scale
        p["w2"] = jax.random.normal(next(k), (e, f, d), cfg.dtype) * (f ** -0.5)
    else:
        p["w1"] = jax.random.normal(next(k), (d, f), cfg.dtype) * scale
        p["w3"] = jax.random.normal(next(k), (d, f), cfg.dtype) * scale
        p["w2"] = jax.random.normal(next(k), (f, d), cfg.dtype) * (f ** -0.5)
    return p


def init_head_params(
    cfg: ModelConfig, k_emb: jax.Array, k_out: jax.Array
) -> Dict[str, jax.Array]:
    """The non-layer weights (embed / final norm / lm head)."""
    return {
        "embed": jax.random.normal(k_emb, (cfg.vocab, cfg.d_model), cfg.dtype)
        * (cfg.d_model ** -0.5),
        "ln_f": jnp.ones((cfg.d_model,), cfg.dtype),
        "lm_head": jax.random.normal(
            k_out, (cfg.d_model, cfg.vocab), cfg.dtype
        ) * (cfg.d_model ** -0.5),
    }


def model_keys(cfg: ModelConfig, key: jax.Array):
    """Deterministic per-component key split — exposed so one layer's
    weights can be regenerated in isolation (seeded dissemination blobs)
    bit-identically to ``init_params``."""
    k_emb, k_layers, k_out = jax.random.split(key, 3)
    return k_emb, jax.random.split(k_layers, cfg.n_layers), k_out


def init_params(cfg: ModelConfig, key: jax.Array) -> Dict[str, Any]:
    """Full model params.  Layer weights are STACKED along a leading
    n_layers axis — one pytree leaf per weight kind — so a layer is a
    slice (disseminable blob) and scan/pipeline stages index it."""
    k_emb, layer_keys, k_out = model_keys(cfg, key)
    per_layer = [init_layer_params(cfg, lk) for lk in layer_keys]
    stacked = {
        name: jnp.stack([lp[name] for lp in per_layer])
        for name in per_layer[0]
    }
    head = init_head_params(cfg, k_emb, k_out)
    return {
        "embed": head["embed"],
        "layers": stacked,
        "ln_f": head["ln_f"],
        "lm_head": head["lm_head"],
    }


# ------------------------------------------------------------------- blocks

def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * rms).astype(x.dtype) * w


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embeddings; x: [..., seq, heads, head_dim]."""
    hd = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, hd // 2, dtype=jnp.float32) / (hd // 2))
    angles = positions[..., :, None].astype(jnp.float32) * freqs  # [..., seq, hd/2]
    cos = jnp.cos(angles)[..., :, None, :]
    sin = jnp.sin(angles)[..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def gqa_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal_mask: jax.Array
) -> jax.Array:
    """Grouped-query attention core.  q: [b, s, h, hd]; k/v: [b, s, kv, hd];
    mask: [sq, sk] additive."""
    b, sq, h, hd = q.shape
    kvh = k.shape[2]
    group = h // kvh
    qg = q.reshape(b, sq, kvh, group, hd)
    logits = jnp.einsum(
        "bskgh,btkh->bkgst", qg, k, preferred_element_type=jnp.float32
    ) / np.sqrt(hd)
    logits = logits + causal_mask  # broadcast over [b, kv, g, sq, sk]
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgst,btkh->bskgh", probs, v)
    return out.reshape(b, sq, h, hd)


def qkv_proj(
    p: Dict[str, jax.Array], xn: jax.Array, positions: jax.Array,
    cfg: ModelConfig,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Project the normed hidden state to rotary-encoded q/k/v — shared
    by the training/forward path and the KV-cached serving path
    (models/generate.py), so the two can't drift."""
    b, s, _ = xn.shape
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    q = jnp.einsum("bsd,dq->bsq", xn, p["wq"]).reshape(b, s, h, hd)
    k = jnp.einsum("bsd,dq->bsq", xn, p["wk"]).reshape(b, s, kv, hd)
    v = jnp.einsum("bsd,dq->bsq", xn, p["wv"]).reshape(b, s, kv, hd)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    return q, k, v


def attention_block(
    p: Dict[str, jax.Array], x: jax.Array, positions: jax.Array, cfg: ModelConfig
) -> jax.Array:
    b, s, d = x.shape
    h, hd = cfg.n_heads, cfg.head_dim
    xn = rms_norm(x, p["ln1"], cfg.norm_eps)
    q, k, v = qkv_proj(p, xn, positions, cfg)
    mask = jnp.where(
        positions[:, None] >= positions[None, :], 0.0, -jnp.inf
    ).astype(jnp.float32)
    out = gqa_attention(q, k, v, mask)
    return x + jnp.einsum("bsq,qd->bsd", out.reshape(b, s, h * hd), p["wo"])


def dense_ffn(p: Dict[str, jax.Array], x: jax.Array, cfg: ModelConfig) -> jax.Array:
    xn = rms_norm(x, p["ln2"], cfg.norm_eps)
    gate = jax.nn.silu(jnp.einsum("bsd,df->bsf", xn, p["w1"]))
    up = jnp.einsum("bsd,df->bsf", xn, p["w3"])
    return x + jnp.einsum("bsf,fd->bsd", gate * up, p["w2"])


def route_topk(weights: jax.Array, cfg: ModelConfig) -> jax.Array:
    """Keep the top-k routing weights per token (tie-inclusive) and
    renormalize.  Shared by the dense-dispatch and the ep-sharded MoE paths
    so routing semantics cannot diverge."""
    if cfg.top_k >= cfg.n_experts:
        return weights
    top = jax.lax.top_k(weights, cfg.top_k)[0][..., -1:]
    weights = jnp.where(weights >= top, weights, 0.0)
    return weights / (weights.sum(-1, keepdims=True) + 1e-9)


def moe_ffn(p: Dict[str, jax.Array], x: jax.Array, cfg: ModelConfig) -> jax.Array:
    """Top-k routed mixture of SwiGLU experts (dense dispatch: every expert
    computes, gates zero out unrouted pairs — compile-friendly, and the
    expert dimension shards cleanly over the ep axis)."""
    xn = rms_norm(x, p["ln2"], cfg.norm_eps)
    logits = jnp.einsum("bsd,de->bse", xn, p["router"]).astype(jnp.float32)
    weights = route_topk(jax.nn.softmax(logits, axis=-1), cfg)
    gate = jax.nn.silu(jnp.einsum("bsd,edf->besf", xn, p["w1"]))
    up = jnp.einsum("bsd,edf->besf", xn, p["w3"])
    expert_out = jnp.einsum("besf,efd->besd", gate * up, p["w2"])
    mixed = jnp.einsum("besd,bse->bsd", expert_out, weights.astype(x.dtype))
    return x + mixed


def layer_apply(
    p: Dict[str, jax.Array], x: jax.Array, positions: jax.Array, cfg: ModelConfig
) -> jax.Array:
    x = attention_block(p, x, positions, cfg)
    if cfg.n_experts:
        return moe_ffn(p, x, cfg)
    return dense_ffn(p, x, cfg)


# ------------------------------------------------------------------ forward

def forward(params: Dict[str, Any], tokens: jax.Array, cfg: ModelConfig) -> jax.Array:
    """Logits for [batch, seq] int tokens.  Layers run under lax.scan over
    the stacked layer axis — one traced layer body regardless of depth."""
    b, s = tokens.shape
    positions = jnp.arange(s)
    x = params["embed"][tokens]

    def body(x, layer_p):
        return layer_apply(layer_p, x, positions, cfg), None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["ln_f"], cfg.norm_eps)
    # f32 accumulation, matching the KV-cached decode head
    # (generate.py:_step_fn) — on bf16 checkpoints a lower-precision
    # accumulation here could make greedy argmax diverge between the
    # full forward and the decode loop.
    return jnp.einsum("bsd,dv->bsv", x, params["lm_head"],
                      preferred_element_type=jnp.float32)


def loss_fn(params: Dict[str, Any], tokens: jax.Array, cfg: ModelConfig) -> jax.Array:
    """Next-token cross-entropy (fp32 logits)."""
    logits = forward(params, tokens[:, :-1], cfg).astype(jnp.float32)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


@functools.partial(jax.jit, static_argnums=(2,))
def forward_jit(params, tokens, cfg: ModelConfig):
    return forward(params, tokens, cfg)
