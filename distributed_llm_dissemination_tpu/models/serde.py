"""Model params ↔ disseminable layer blobs.

The reference disseminates opaque byte blobs and its ``startupMsg`` is "the
hook that would launch an inference engine"
(``/root/reference/distributor/message.go:216-241``).  This module defines
the byte format that closes that loop for real: each transformer layer of a
``models.llama`` model serializes to one blob (the dissemination unit), and
a receiver reassembles delivered blobs back into the stacked-layer params
pytree the jitted forward consumes.

Format (deterministic, self-describing via the ModelConfig):
- Blob ``i`` for ``0 <= i < n_layers`` is layer ``i``'s weights — each leaf
  in the fixed ``layer_param_specs`` order, as raw C-order bytes of
  ``cfg.dtype``.
- Blob ``head_blob_id(cfg) == n_layers`` holds the non-layer params:
  ``embed``, ``ln_f``, ``lm_head`` (same encoding).

Two decode paths, bit-identical by construction (and by test):
- **host**: numpy views over the blob bytes (zero-copy) — used when layers
  were delivered to host RAM.
- **device**: delivered blobs that already live in HBM as uint8 arrays
  (the ``-hbm`` ingest path) are reinterpreted *on device* with
  ``lax.bitcast_convert_type`` under one jit — no host round-trip; the
  bytes never leave the accelerator they were disseminated into.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .llama import ModelConfig

Spec = Tuple[str, Tuple[int, ...]]


def layer_param_specs(cfg: ModelConfig) -> List[Spec]:
    """(name, shape) of one layer's leaves, in canonical blob order."""
    d, f = cfg.d_model, cfg.d_ff
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    specs: List[Spec] = [
        ("wq", (d, h * hd)),
        ("wk", (d, kv * hd)),
        ("wv", (d, kv * hd)),
        ("wo", (h * hd, d)),
        ("ln1", (d,)),
        ("ln2", (d,)),
    ]
    if cfg.n_experts:
        e = cfg.n_experts
        specs += [
            ("router", (d, e)),
            ("w1", (e, d, f)),
            ("w3", (e, d, f)),
            ("w2", (e, f, d)),
        ]
    else:
        specs += [("w1", (d, f)), ("w3", (d, f)), ("w2", (f, d))]
    return specs


def head_param_specs(cfg: ModelConfig) -> List[Spec]:
    """(name, shape) of the non-layer leaves, in canonical blob order."""
    return [
        ("embed", (cfg.vocab, cfg.d_model)),
        ("ln_f", (cfg.d_model,)),
        ("lm_head", (cfg.d_model, cfg.vocab)),
    ]


def head_blob_id(cfg: ModelConfig) -> int:
    """The blob id carrying embed/ln_f/lm_head: one past the layers."""
    return cfg.n_layers


def blob_nbytes(cfg: ModelConfig, blob_id: int) -> int:
    """Exact byte size of a blob (== cfg.layer_nbytes() for layer blobs)."""
    itemsize = np.dtype(cfg.dtype).itemsize
    specs = (head_param_specs(cfg) if blob_id == head_blob_id(cfg)
             else layer_param_specs(cfg))
    return sum(int(np.prod(s)) for _, s in specs) * itemsize


def _encode(leaves: Sequence[np.ndarray]) -> bytes:
    return b"".join(np.ascontiguousarray(a).tobytes() for a in leaves)


def blobs_from_params(cfg: ModelConfig, params: Dict[str, Any]) -> Dict[int, bytes]:
    """Serialize a full params pytree into its dissemination blobs."""
    layers = jax.device_get(params["layers"])
    blobs: Dict[int, bytes] = {}
    specs = layer_param_specs(cfg)
    for i in range(cfg.n_layers):
        blobs[i] = _encode([np.asarray(layers[name][i]) for name, _ in specs])
    head = {name: np.asarray(jax.device_get(params[name]))
            for name, _ in head_param_specs(cfg)}
    blobs[head_blob_id(cfg)] = _encode(
        [head[name] for name, _ in head_param_specs(cfg)]
    )
    return blobs


def _split_blob(
    cfg: ModelConfig, data, specs: List[Spec]
) -> Dict[str, np.ndarray]:
    """Host path: zero-copy numpy views of one blob's leaves."""
    dt = np.dtype(cfg.dtype)
    buf = np.frombuffer(memoryview(data), dtype=np.uint8)
    out: Dict[str, np.ndarray] = {}
    off = 0
    for name, shape in specs:
        n = int(np.prod(shape)) * dt.itemsize
        out[name] = buf[off : off + n].view(dt).reshape(shape)
        off += n
    if off != len(buf):
        raise ValueError(f"blob size {len(buf)} != expected {off}")
    return out


def params_from_blobs(
    cfg: ModelConfig, blobs: Dict[int, Any]
) -> Dict[str, Any]:
    """Host path: reassemble the full params pytree from all blobs.

    ``blobs`` maps blob id → bytes-like.  Requires every layer blob plus
    the head blob.  Leaves are numpy (host) arrays; callers place them on
    device under whatever sharding the stage placement prescribes."""
    missing = [i for i in range(cfg.n_layers + 1) if i not in blobs]
    if missing:
        raise ValueError(f"missing blobs for full model: {missing}")
    specs = layer_param_specs(cfg)
    per_layer = [_split_blob(cfg, blobs[i], specs) for i in range(cfg.n_layers)]
    stacked = {
        name: np.stack([lp[name] for lp in per_layer]) for name, _ in specs
    }
    head = _split_blob(cfg, blobs[head_blob_id(cfg)], head_param_specs(cfg))
    return {
        "embed": head["embed"],
        "layers": stacked,
        "ln_f": head["ln_f"],
        "lm_head": head["lm_head"],
    }


def head_from_blob(cfg: ModelConfig, data) -> Dict[str, np.ndarray]:
    """Host path: embed/ln_f/lm_head views over the head blob's bytes."""
    return _split_blob(cfg, data, head_param_specs(cfg))


def stacked_from_blobs(
    cfg: ModelConfig, blobs: Dict[int, Any], layer_ids: Sequence[int]
) -> Dict[str, np.ndarray]:
    """Host path: stacked params for a *contiguous subset* of layers — a
    pipeline stage's slice of the model."""
    specs = layer_param_specs(cfg)
    per_layer = [_split_blob(cfg, blobs[i], specs) for i in layer_ids]
    return {name: np.stack([lp[name] for lp in per_layer]) for name, _ in specs}


def seeded_blob(cfg: ModelConfig, blob_id: int, seed: int = 0) -> bytes:
    """Regenerate ONE blob of the model ``init_params(cfg, key(seed))``
    would produce, bit-identically, without materializing the rest — how
    seeder nodes fabricate real (non-dummy) initial layers from just a
    config + seed, so every process agrees on the weights and a booted
    model can be checked against an independently initialized source."""
    import jax

    from .llama import init_head_params, init_layer_params, model_keys

    k_emb, layer_keys, k_out = model_keys(cfg, jax.random.key(seed))
    if blob_id == head_blob_id(cfg):
        head = init_head_params(cfg, k_emb, k_out)
        leaves = [np.asarray(jax.device_get(head[name]))
                  for name, _ in head_param_specs(cfg)]
        return _encode(leaves)
    if not 0 <= blob_id < cfg.n_layers:
        raise ValueError(f"blob {blob_id} out of range for {cfg.name}")
    p = init_layer_params(cfg, layer_keys[blob_id])
    return _encode([np.asarray(jax.device_get(p[name]))
                    for name, _ in layer_param_specs(cfg)])


# ------------------------------------------------------------- device path

def _bytes_to_wide(flat_u8: jax.Array, dtype) -> jax.Array:
    """1-D uint8[n*k] → 1-D dtype[n] on device (k = itemsize).

    Widening via k strided byte slices + integer shifts, then a
    SAME-WIDTH bitcast.  The direct route — reshape to (..., k) and a
    widening ``bitcast_convert_type`` — materializes the k-minor
    intermediate in a tiled TPU layout that pads k to the 128 lane tile
    (64x the logical bytes for bf16: a 27.9 GiB allocation per physical
    416 MiB blob — the boot OOM).  Strided 1-D slices and the same-width
    bitcast never change rank or minor-dim size, so no such layout
    exists to choose."""
    dt = np.dtype(dtype)
    k = dt.itemsize
    if k == 1:
        return jax.lax.bitcast_convert_type(flat_u8, dtype)
    if k not in (2, 4):
        # 8-byte widths would need jax_enable_x64 (without it uint64
        # silently truncates to 32 bits); no model config uses them.
        raise ValueError(f"unsupported decode itemsize {k} for {dt}")
    wide = {2: jnp.uint16, 4: jnp.uint32}[k]
    n = flat_u8.shape[0] // k
    word = None
    for i in range(k):
        b = jax.lax.slice(flat_u8, (i,), (i + (n - 1) * k + 1,), (k,))
        piece = b.astype(wide) << (8 * i)  # little-endian byte order
        word = piece if word is None else word | piece
    return jax.lax.bitcast_convert_type(word, dtype)


def _decode_blobs_impl(blobs_u8: Tuple[jax.Array, ...], specs: Tuple[Spec, ...],
                       dtype_name: str):
    """n separate 1-D uint8 blobs → {name: (n, *shape) dtype} on device.

    Each blob's leaves are sliced 1-D, widened 1-D
    (``_bytes_to_wide``), reshaped to the leaf's shape, and only then
    stacked per leaf.  An earlier form stacked the blobs into one
    (n, blob_len) array and sliced along axis 1; at physical layer
    sizes the TPU compiler laid the widening bitcast's intermediate out
    with a tiny minor dim padded to the 128 tile — 32-64x the logical
    bytes, a ~30 GiB allocation for four 416 MiB layers (the
    physical-size boot OOM).  With every intermediate strictly 1-D or
    leaf-shaped (minor dims the leaf's own, large ones), no degenerate
    layout choice exists."""
    dt = jnp.dtype(dtype_name)
    out = {}
    off = 0
    for name, shape in specs:
        n = int(np.prod(shape)) * dt.itemsize
        leaves = []
        for blob in blobs_u8:
            leaf = jax.lax.slice(blob, (off,), (off + n,))
            leaves.append(_bytes_to_wide(leaf, dt).reshape(shape))
        out[name] = jnp.stack(leaves)
        off += n
    return out


# The traced name (compile logs, cache keys, the tests' compile-log
# oracle) comes from the wrapped function; keep the historical name.
_decode_blobs_impl.__name__ = "_decode_blobs"
_decode_blobs = functools.partial(
    jax.jit, static_argnums=(1, 2))(_decode_blobs_impl)
# Donated twin: the wire blobs are CONSUMED by the decode.  XLA honors
# donation as input→output aliasing, so it reuses a blob's HBM only
# where an output matches its layout; the boot pairs the donated call
# with dropping the store's blob references (``runtime/boot.py``), which
# is what actually collapses the blobs+params peak at 8B scale — and the
# streaming stager gets the same effect per blob, mid-wire.  A separate
# jitted callable on purpose: donation is part of the executable, so the
# two variants cache — in-memory and persistently — as distinct
# programs.
_decode_blobs_donated = jax.jit(
    _decode_blobs_impl, static_argnums=(1, 2), donate_argnums=(0,))

# Device-path consumers go through the codec-dispatch facade
# (``quant.stacked_from_device`` / ``quant.head_from_device`` /
# ``quant.device_decode_jit``) so the codec AND donation dispatch live
# in exactly one place.
