"""Hugging Face Llama checkpoint import: real weights as dissemination blobs.

The reference fabricates dummy byte blobs (``cmd/config.go:94-171``); this
framework's seeded blobs already upgrade those to real-but-synthetic
weights.  This module closes the remaining gap to a production workflow:
point the topology at an on-disk Hugging Face Llama checkpoint —

    "Model": "hf:/path/to/checkpoint"

— and seeders fabricate their blobs FROM THE CHECKPOINT (per-layer slices
of the safetensors state dict, through the same ``serde`` wire format),
the schedulers ship them like any other blobs (transfer codecs compose),
and the booted engine runs the actual model.

The weight mapping is transposition-only because the compute conventions
match HF's Llama exactly: rotate-half rotary (``llama.rope`` expands to
HF's ``x*cos + rotate_half(x)*sin``), f32 RMSNorm with the same
cast-then-scale order, 1/sqrt(head_dim) attention scaling, SwiGLU.  A
parity test (``tests/test_hf.py``) checks our jitted forward against the
``transformers`` implementation on the same checkpoint.

Loading is lazy safetensors reads: fabricating one layer's blob touches
only that layer's nine tensors, so a seeder of one 70B layer pays one
layer's RAM, not the checkpoint's.  (``.bin`` torch checkpoints are not
supported — convert to safetensors first.)
"""

from __future__ import annotations

import functools
import json
import os
from typing import Any, Dict

import numpy as np

from .llama import ModelConfig

PREFIX = "hf:"

_DTYPES = {
    "float32": np.float32,
    "float16": np.float16,
    "bfloat16": "bfloat16",  # resolved via ml_dtypes below
}


def is_hf(name: str) -> bool:
    return name.startswith(PREFIX)


def _np_dtype(torch_dtype: str):
    name = torch_dtype or "float32"
    if name not in _DTYPES:
        # FP8/int-quantized checkpoints etc.: silently coercing to f32
        # would surface later as wrong-sized blobs — reject at config
        # time like every other unsupported checkpoint feature.
        raise ValueError(
            f"unsupported torch_dtype {name!r}; known: {sorted(_DTYPES)}"
        )
    dt = _DTYPES[name]
    if dt == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(dt)


@functools.lru_cache(maxsize=4)
def config_from_dir(path: str) -> ModelConfig:
    """Our ModelConfig from an HF checkpoint's config.json.

    Raises for checkpoint features our forward does NOT implement —
    booting one of those would produce silently wrong logits, the worst
    possible failure mode for a weights pipeline."""
    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    arch = (hf.get("architectures") or ["?"])[0]
    if "Llama" not in arch:
        raise ValueError(f"unsupported HF architecture {arch!r} (Llama only)")
    if hf.get("rope_scaling"):
        raise ValueError(
            f"checkpoint uses rope_scaling={hf['rope_scaling']!r} "
            "(Llama-3.1+ long-context scaling); this forward implements "
            "plain RoPE only — logits would silently diverge"
        )
    if hf.get("attention_bias") or hf.get("mlp_bias"):
        raise ValueError(
            "checkpoint uses attention/mlp biases; this forward is "
            "bias-free — logits would silently diverge"
        )
    d = int(hf["hidden_size"])
    heads = int(hf["num_attention_heads"])
    head_dim = int(hf.get("head_dim") or d // heads)
    if head_dim != d // heads:
        raise ValueError(
            f"explicit head_dim {head_dim} != hidden/heads {d // heads}: "
            "unsupported layout"
        )
    return ModelConfig(
        name=PREFIX + path,
        vocab=int(hf["vocab_size"]),
        d_model=d,
        n_layers=int(hf["num_hidden_layers"]),
        n_heads=heads,
        n_kv_heads=int(hf.get("num_key_value_heads") or heads),
        d_ff=int(hf["intermediate_size"]),
        rope_theta=float(hf.get("rope_theta") or 10000.0),
        norm_eps=float(hf.get("rms_norm_eps") or 1e-5),
        dtype=_np_dtype(hf.get("torch_dtype")),
    )


def config_from_name(name: str) -> ModelConfig:
    if not is_hf(name):
        raise ValueError(f"not an hf: model name: {name!r}")
    return config_from_dir(name[len(PREFIX):])


# Our leaf name -> (HF per-layer key suffix, transpose?).  Order is
# irrelevant here; blob encoding follows serde.layer_param_specs.
_LAYER_KEYS = {
    "wq": ("self_attn.q_proj.weight", True),
    "wk": ("self_attn.k_proj.weight", True),
    "wv": ("self_attn.v_proj.weight", True),
    "wo": ("self_attn.o_proj.weight", True),
    "ln1": ("input_layernorm.weight", False),
    "ln2": ("post_attention_layernorm.weight", False),
    "w1": ("mlp.gate_proj.weight", True),
    "w3": ("mlp.up_proj.weight", True),
    "w2": ("mlp.down_proj.weight", True),
}


@functools.lru_cache(maxsize=4)
def _weight_files(path: str) -> Dict[str, str]:
    """tensor name -> safetensors file, without decoding any tensor —
    a seeder fabricating ONE layer's blob must not pull the whole
    checkpoint into RAM."""
    from safetensors import safe_open

    st_files = sorted(
        f for f in os.listdir(path) if f.endswith(".safetensors")
    )
    index: Dict[str, str] = {}
    for fname in st_files:
        with safe_open(os.path.join(path, fname), framework="np") as f:
            for key in f.keys():
                index[key] = fname
    if not index:
        raise FileNotFoundError(f"no .safetensors weights in {path}")
    return index


def _read_tensor(path: str, name: str) -> np.ndarray:
    from safetensors import safe_open

    fname = _weight_files(path).get(name)
    if fname is None:
        raise KeyError(f"tensor {name!r} not in checkpoint {path}")
    with safe_open(os.path.join(path, fname), framework="np") as f:
        return f.get_tensor(name)


def _has_tensor(path: str, name: str) -> bool:
    return name in _weight_files(path)


def _leaf(path: str, name: str, transpose: bool, dtype) -> np.ndarray:
    t = _read_tensor(path, name)
    if transpose:
        t = t.T
    return np.ascontiguousarray(t).astype(dtype, copy=False)


def _layer_leaves(path: str, cfg: ModelConfig, i: int) -> Dict[str, np.ndarray]:
    dt = np.dtype(cfg.dtype)
    prefix = f"model.layers.{i}."
    return {
        ours: _leaf(path, prefix + key, tr, dt)
        for ours, (key, tr) in _LAYER_KEYS.items()
    }


def _head_leaves(path: str, cfg: ModelConfig) -> Dict[str, np.ndarray]:
    dt = np.dtype(cfg.dtype)
    embed = _leaf(path, "model.embed_tokens.weight", False, dt)
    if _has_tensor(path, "lm_head.weight"):
        lm_head = _leaf(path, "lm_head.weight", True, dt)
    else:  # tied embeddings
        lm_head = np.ascontiguousarray(embed.T)
    return {
        "embed": embed,
        "ln_f": _leaf(path, "model.norm.weight", False, dt),
        "lm_head": lm_head,
    }


def params_from_dir(path: str) -> Dict[str, Any]:
    """The full params pytree (our stacked-layer layout) from an HF
    checkpoint directory — every projection transposed from HF's
    [out, in] to our [in, out]."""
    cfg = config_from_dir(path)
    per_layer = [_layer_leaves(path, cfg, i) for i in range(cfg.n_layers)]
    head = _head_leaves(path, cfg)
    return {
        "embed": head["embed"],
        "layers": {
            k: np.stack([lp[k] for lp in per_layer]) for k in _LAYER_KEYS
        },
        "ln_f": head["ln_f"],
        "lm_head": head["lm_head"],
    }


def blob_from_name(name: str, blob_id: int) -> bytes:
    """One dissemination blob of an ``hf:<dir>`` model — what a seeder
    node fabricates from the checkpoint (``core.config.create_layers``).
    Loads ONLY that blob's tensors (lazy safetensors reads), so a seeder
    of one 70B layer pays one layer's RAM, not the checkpoint's."""
    from . import serde

    path = name[len(PREFIX):]
    cfg = config_from_dir(path)
    if blob_id == serde.head_blob_id(cfg):
        leaves = _head_leaves(path, cfg)
        return serde._encode(
            [leaves[n] for n, _ in serde.head_param_specs(cfg)]
        )
    if not 0 <= blob_id < cfg.n_layers:
        raise ValueError(f"blob {blob_id} out of range for {cfg.name}")
    leaves = _layer_leaves(path, cfg, blob_id)
    return serde._encode(
        [leaves[n] for n, _ in serde.layer_param_specs(cfg)]
    )
