"""Quantized transfer codecs: shrink the bytes a layer costs on the wire.

Dissemination is bandwidth-bound — TTD is bytes over line rate
(SURVEY §6; the reference models it exactly that way in its flow solver,
``/root/reference/distributor/flow.go:221-270``).  A transfer codec
attacks the numerator: seeders encode each layer blob into a quantized
form (scales + narrow values), the wire and every scheduler see only the
smaller opaque blob, and the receiver dequantizes AFTER the bytes land —
on the accelerator, when the ``-hbm`` ingest staged them, so the host
never touches decoded weights.  The reference has no equivalent; it
ships raw bytes only.

Two quantized formats (leaves in ``serde``'s canonical order):

- **int8** (~0.50x bf16): per leaf, ``rows`` f32 scales then
  ``rows x cols`` int8 values, where a leaf of shape ``(..., cols)`` is
  flattened to ``(rows, cols)`` — per-output-row symmetric absmax
  scaling, ``x_hat = q * scale``.
- **int4** (~0.27x bf16): per leaf, ``rows x groups`` f32 scales
  (group = 128 columns when the leaf allows, else one group per row)
  then ``rows x cols/2`` packed bytes.  Packing pairs COLUMN HALVES,
  not neighbors: byte ``j`` of a row holds column ``j``'s nibble (low)
  and column ``j + cols/2``'s (high), so the device decode rebuilds the
  leaf with one large ``concatenate([lo, hi], axis=1)`` — a
  neighbor-interleave would need a ``(rows, cols/2, 2)`` intermediate
  whose tiny minor dim provokes the TPU tiled-layout padding blowup
  (the documented physical-size OOM class, see ``serde``).  Leaves that
  can't pack (1-D norm gains, odd columns) ride raw inside the blob —
  a negligible fraction of layer bytes.

Both are deterministic round-to-nearest (every seeder fabricating the
same seeded blob must agree byte-for-byte).

Decode paths mirror ``serde``'s two:
- host: numpy over the blob bytes;
- device: HBM-resident uint8 blobs are sliced, bitcast, and dequantized
  under one jit — XLA fuses the multiply into the bitcast reads, so the
  decode is one pass over HBM.

Codec choice is carried by the topology config (``ModelCodec``) next to
``Model``/``ModelSeed``: every node — seeder, scheduler, booting
receiver — derives identical blob sizes from (model, codec) alone.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import serde
from .llama import ModelConfig
from .serde import (
    Spec,
    blob_nbytes,
    head_blob_id,
    head_param_specs,
    layer_param_specs,
)

CODECS = ("raw", "int8", "int4", "int8e", "int4e")
# Entropy wire forms (models/entropy.py): the quantized base form run
# through the DLE1 block coder.  Sizes are DATA-DEPENDENT — the codec
# plane prices them by actually encoding (``WireCodecPlane.ensure_sized``)
# instead of from (model, codec) alone — and decode is host-first (the
# byte-domain coder has no device program; the unpacked base then rides
# the base codec's normal paths).
ENTROPY_CODECS = {"int8e": "int8", "int4e": "int4"}
_SCALE_DT = np.float32
_QMAX = 127.0
_QMAX4 = 7.0
_GROUP4 = 128  # int4 scale-group width (one TPU lane tile of columns)


def _blob_specs(cfg: ModelConfig, blob_id: int) -> List[Spec]:
    return (head_param_specs(cfg) if blob_id == head_blob_id(cfg)
            else layer_param_specs(cfg))


def _rows_cols(shape: Tuple[int, ...]) -> Tuple[int, int]:
    if len(shape) == 1:
        return 1, shape[0]
    return int(np.prod(shape[:-1])), shape[-1]


def _q4_layout(shape: Tuple[int, ...], itemsize: int):
    """One leaf's int4 wire layout: ``("raw", nbytes)`` for leaves that
    can't pack (1-D norm gains, odd columns), else
    ``("q4", rows, cols, groups)`` with groups of ``cols // groups``
    columns sharing one f32 scale."""
    rows, cols = _rows_cols(shape)
    if len(shape) == 1 or cols % 2:
        return ("raw", rows * cols * itemsize)
    # Scale groups and nibble packing are independent (packing pairs
    # column j with j + cols/2; dequant multiplies AFTER unpacking), so
    # grouping only needs the group width to divide cols.
    g = _GROUP4 if cols % _GROUP4 == 0 else cols
    return ("q4", rows, cols, cols // g)


def _q4_leaf_nbytes(layout) -> int:
    if layout[0] == "raw":
        return layout[1]
    _, rows, cols, groups = layout
    return rows * groups * _SCALE_DT().itemsize + rows * (cols // 2)


def blob_nbytes_codec(cfg: ModelConfig, blob_id: int, codec: str) -> int:
    """Exact wire size of a blob under ``codec``.  Entropy forms raise:
    their size depends on the bytes, not just (model, codec) — callers
    price them through the codec plane's true-size cache."""
    if codec == "raw":
        return blob_nbytes(cfg, blob_id)
    if codec in ENTROPY_CODECS:
        raise ValueError(
            f"codec {codec!r} is data-dependent; size it by encoding "
            "(WireCodecPlane.ensure_sized), not from the model config")
    if codec == "int4":
        itemsize = np.dtype(cfg.dtype).itemsize
        return sum(
            _q4_leaf_nbytes(_q4_layout(shape, itemsize))
            for _, shape in _blob_specs(cfg, blob_id)
        )
    if codec != "int8":
        raise ValueError(f"unknown codec {codec!r}; known: {CODECS}")
    total = 0
    for _, shape in _blob_specs(cfg, blob_id):
        rows, cols = _rows_cols(shape)
        total += rows * _SCALE_DT().itemsize + rows * cols
    return total


def encode_blob(cfg: ModelConfig, blob_id: int, raw: bytes, codec: str) -> bytes:
    """Encode a raw (cfg.dtype) blob into its wire form under ``codec``."""
    if codec == "raw":
        return raw
    if codec in ENTROPY_CODECS:
        from . import entropy

        return entropy.encode(
            encode_blob(cfg, blob_id, raw, ENTROPY_CODECS[codec]))
    if codec == "int4":
        return _encode_blob_q4(cfg, blob_id, raw)
    if codec != "int8":
        raise ValueError(f"unknown codec {codec!r}; known: {CODECS}")
    dt = np.dtype(cfg.dtype)
    buf = np.frombuffer(memoryview(raw), dtype=np.uint8)
    parts: List[bytes] = []
    off = 0
    for _, shape in _blob_specs(cfg, blob_id):
        rows, cols = _rows_cols(shape)
        n = rows * cols * dt.itemsize
        x = buf[off : off + n].view(dt).reshape(rows, cols).astype(np.float32)
        off += n
        scale = np.abs(x).max(axis=1) / _QMAX
        scale = np.where(scale > 0, scale, 1.0).astype(_SCALE_DT)
        q = np.clip(np.rint(x / scale[:, None]), -_QMAX, _QMAX).astype(np.int8)
        parts.append(scale.tobytes())
        parts.append(q.tobytes())
    if off != len(buf):
        raise ValueError(f"raw blob size {len(buf)} != expected {off}")
    return b"".join(parts)


def decode_blob_host(
    cfg: ModelConfig, blob_id: int, data, codec: str
) -> Dict[str, np.ndarray]:
    """Host path: decode one wire blob into {name: cfg.dtype array}."""
    specs = _blob_specs(cfg, blob_id)
    if codec == "raw":
        return serde._split_blob(cfg, data, specs)
    if codec in ENTROPY_CODECS:
        from . import entropy

        return decode_blob_host(cfg, blob_id, entropy.decode(data),
                                ENTROPY_CODECS[codec])
    if codec == "int4":
        return _decode_blob_q4_host(cfg, blob_id, data)
    if codec != "int8":
        raise ValueError(f"unknown codec {codec!r}; known: {CODECS}")
    dt = np.dtype(cfg.dtype)
    buf = np.frombuffer(memoryview(data), dtype=np.uint8)
    out: Dict[str, np.ndarray] = {}
    off = 0
    for name, shape in specs:
        rows, cols = _rows_cols(shape)
        sb = rows * _SCALE_DT().itemsize
        scale = buf[off : off + sb].view(_SCALE_DT).reshape(rows, 1)
        off += sb
        q = buf[off : off + rows * cols].view(np.int8).reshape(rows, cols)
        off += rows * cols
        out[name] = (q.astype(np.float32) * scale).astype(dt).reshape(shape)
    if off != len(buf):
        raise ValueError(f"wire blob size {len(buf)} != expected {off}")
    return out


# ---------------------------------------------------------- int4 host path


def _encode_blob_q4(cfg: ModelConfig, blob_id: int, raw: bytes) -> bytes:
    """Host encode under the int4 format (see module docstring)."""
    dt = np.dtype(cfg.dtype)
    buf = np.frombuffer(memoryview(raw), dtype=np.uint8)
    parts: List[bytes] = []
    off = 0
    for _, shape in _blob_specs(cfg, blob_id):
        layout = _q4_layout(shape, dt.itemsize)
        rows, cols = _rows_cols(shape)
        n = rows * cols * dt.itemsize
        if layout[0] == "raw":
            parts.append(buf[off : off + n].tobytes())
            off += n
            continue
        _, rows, cols, groups = layout
        g = cols // groups
        x = (buf[off : off + n].view(dt).reshape(rows, cols)
             .astype(np.float32))
        off += n
        scale = np.abs(x).reshape(rows, groups, g).max(axis=2) / _QMAX4
        scale = np.where(scale > 0, scale, 1.0).astype(_SCALE_DT)
        q = np.clip(
            np.rint(x.reshape(rows, groups, g) / scale[:, :, None]),
            -_QMAX4, _QMAX4,
        ).astype(np.int8).reshape(rows, cols)
        c2 = cols // 2
        packed = (((q[:, :c2] + 8) & 0xF)
                  | (((q[:, c2:] + 8) & 0xF) << 4)).astype(np.uint8)
        parts.append(scale.tobytes())
        parts.append(packed.tobytes())
    if off != len(buf):
        raise ValueError(f"raw blob size {len(buf)} != expected {off}")
    return b"".join(parts)


def _decode_blob_q4_host(
    cfg: ModelConfig, blob_id: int, data
) -> Dict[str, np.ndarray]:
    """Host decode of one int4 wire blob into {name: cfg.dtype array}."""
    dt = np.dtype(cfg.dtype)
    buf = np.frombuffer(memoryview(data), dtype=np.uint8)
    out: Dict[str, np.ndarray] = {}
    off = 0
    for name, shape in _blob_specs(cfg, blob_id):
        layout = _q4_layout(shape, dt.itemsize)
        if layout[0] == "raw":
            n = layout[1]
            out[name] = buf[off : off + n].view(dt).reshape(shape)
            off += n
            continue
        _, rows, cols, groups = layout
        g = cols // groups
        sb = rows * groups * _SCALE_DT().itemsize
        scale = buf[off : off + sb].view(_SCALE_DT).reshape(rows, groups)
        off += sb
        c2 = cols // 2
        packed = buf[off : off + rows * c2].view(np.uint8).reshape(rows, c2)
        off += rows * c2
        q = np.concatenate(
            [(packed & 0xF).astype(np.int8) - 8,
             (packed >> 4).astype(np.int8) - 8], axis=1)
        x = (q.astype(np.float32).reshape(rows, groups, g)
             * scale[:, :, None])
        out[name] = x.reshape(rows, cols).astype(dt).reshape(shape)
    if off != len(buf):
        raise ValueError(f"wire blob size {len(buf)} != expected {off}")
    return out


# ------------------------------------------------------------- device path


def _decode_qblobs_impl(blobs_u8, specs: Tuple[Spec, ...], dtype_name: str):
    """n separate 1-D uint8 qblobs → {name: (n, *shape) dtype} on device.

    Per-blob 1-D slices, leaf-shaped bitcasts, dequant multiply, then a
    per-leaf stack — same layout discipline as ``serde._decode_blobs``
    (a stacked (n, blob_len) intermediate provoked a dim0-minor tiled
    layout on TPU that padded n to the 128 tile: the physical-size boot
    OOM)."""
    dt = jnp.dtype(dtype_name)
    sdt = jnp.dtype(_SCALE_DT)
    out = {}
    off = 0
    for name, shape in specs:
        rows, cols = _rows_cols(shape)
        sb = rows * _SCALE_DT().itemsize  # one wire format: host's widths
        leaves = []
        for blob in blobs_u8:
            sraw = jax.lax.slice(blob, (off,), (off + sb,))
            scale = serde._bytes_to_wide(sraw, sdt)  # (rows,)
            qraw = jax.lax.slice(blob, (off + sb,),
                                 (off + sb + rows * cols,))
            q = serde._bytes_to_wide(qraw, jnp.int8).reshape(rows, cols)
            x = (q.astype(jnp.float32) * scale[:, None]).astype(dt)
            leaves.append(x.reshape(shape))
        out[name] = jnp.stack(leaves)
        off += sb + rows * cols
    return out


def _decode_q4blobs_impl(blobs_u8, specs: Tuple[Spec, ...], dtype_name: str):
    """n separate 1-D uint8 int4-codec blobs → {name: (n, *shape) dtype}
    on device.  Same layout discipline as ``_decode_qblobs``; the packed
    column-halves format means deinterleave is one big
    ``concatenate([lo, hi], axis=1)`` — no tiny-minor-dim intermediates
    (the TPU tiled-layout padding class, see module docstring)."""
    dt = jnp.dtype(dtype_name)
    sdt = jnp.dtype(_SCALE_DT)
    itemsize = dt.itemsize
    out = {}
    off = 0
    for name, shape in specs:
        layout = _q4_layout(shape, itemsize)
        leaves = []
        if layout[0] == "raw":
            n = layout[1]
            for blob in blobs_u8:
                raw = jax.lax.slice(blob, (off,), (off + n,))
                leaves.append(serde._bytes_to_wide(raw, dt).reshape(shape))
            out[name] = jnp.stack(leaves)
            off += n
            continue
        _, rows, cols, groups = layout
        g = cols // groups
        c2 = cols // 2
        sb = rows * groups * _SCALE_DT().itemsize
        for blob in blobs_u8:
            sraw = jax.lax.slice(blob, (off,), (off + sb,))
            scale = serde._bytes_to_wide(sraw, sdt).reshape(rows, groups)
            praw = jax.lax.slice(blob, (off + sb,),
                                 (off + sb + rows * c2,))
            packed = praw.reshape(rows, c2)
            q = jnp.concatenate(
                [(packed & 0xF).astype(jnp.int8) - 8,
                 (packed >> 4).astype(jnp.int8) - 8], axis=1)
            x = (q.astype(jnp.float32).reshape(rows, groups, g)
                 * scale[:, :, None]).astype(dt)
            leaves.append(x.reshape(shape))
        out[name] = jnp.stack(leaves)
        off += sb + rows * c2
    return out


# Traced names (compile logs / the tests' oracle) keep the historical
# jit names for both the plain and donated variants.
_decode_qblobs_impl.__name__ = "_decode_qblobs"
_decode_q4blobs_impl.__name__ = "_decode_q4blobs"
_decode_qblobs = functools.partial(
    jax.jit, static_argnums=(1, 2))(_decode_qblobs_impl)
_decode_q4blobs = functools.partial(
    jax.jit, static_argnums=(1, 2))(_decode_q4blobs_impl)
# Donated twins (see serde._decode_blobs_donated): the HBM wire blobs
# are consumed by the dequant; the callers' reference-drop does the
# actual freeing where XLA finds no aliasable output.
_decode_qblobs_donated = jax.jit(
    _decode_qblobs_impl, static_argnums=(1, 2), donate_argnums=(0,))
_decode_q4blobs_donated = jax.jit(
    _decode_q4blobs_impl, static_argnums=(1, 2), donate_argnums=(0,))


def decode_to_raw(cfg: ModelConfig, blob_id: int, data, codec: str) -> bytes:
    """Re-materialize the CANONICAL raw blob bytes from a wire-codec
    blob: host decode, then the leaves concatenated back in ``serde``'s
    spec order (a raw blob IS exactly that concatenation).  The wire
    receiver's normalization path (docs/codec.md): a holding delivered
    as int8/int4 becomes servable to any raw consumer — at the
    quantization error the operator opted into, not byte-identity with
    the original."""
    if codec == "raw":
        return bytes(data)
    decoded = decode_blob_host(cfg, blob_id, data, codec)
    return b"".join(
        np.ascontiguousarray(decoded[name]).tobytes()
        for name, _ in _blob_specs(cfg, blob_id)
    )


def codec_bench(cfg: Optional[ModelConfig] = None, blob_id: int = 0,
                device: bool = True) -> dict:
    """Micro-bench the wire codecs on THIS host — the measured basis of
    the codec-choice threshold (``DLD_CODEC_MIN_RATE``): a codec only
    pays when the link is slower than the encode/decode path, and that
    crossover is a property of the running container, not a guess.
    Returns {codec: {encode_gbps, decode_host_gbps, decode_device_gbps,
    ratio}} over one layer blob of ``cfg`` (default: the "tiny2" test
    model); rates are raw-bytes-per-second (the side the wire saves).
    ``device=False`` skips the jit decode (hosts without a warm XLA)."""
    import time

    if cfg is None:
        from .llama import CONFIGS

        cfg = CONFIGS["tiny2"]
    from .serde import seeded_blob

    raw = seeded_blob(cfg, blob_id, 0)

    def rate(fn, nbytes: int) -> float:
        fn()  # warm (jit compile / numpy allocator)
        t0 = time.monotonic()
        n = 0
        while time.monotonic() - t0 < 0.2:
            fn()
            n += 1
        dt = time.monotonic() - t0
        return round(nbytes * n / max(dt, 1e-9) / 1e9, 3)

    out: dict = {"raw_bytes": len(raw)}
    for codec in ("int8", "int4", "int8e", "int4e"):
        enc = encode_blob(cfg, blob_id, raw, codec)
        row = {
            "encoded_bytes": len(enc),
            "ratio": round(len(raw) / len(enc), 3),
            "encode_gbps": rate(
                lambda c=codec: encode_blob(cfg, blob_id, raw, c),
                len(raw)),
            "decode_host_gbps": rate(
                lambda c=codec, e=enc: decode_blob_host(cfg, blob_id, e, c),
                len(raw)),
            "decode_device_gbps": 0.0,
        }
        if device:
            specs = tuple(layer_param_specs(cfg))
            dt_name = np.dtype(cfg.dtype).name
            base = ENTROPY_CODECS.get(codec, codec)
            fn = device_decode_jit(base)
            if codec in ENTROPY_CODECS:
                # The honest device row for an entropy form is the boot
                # path it actually takes: host unwrap THEN the base jit.
                def dev_decode(e=enc, s=specs, c=codec, f=fn):
                    _, bb = host_unwrap(c, e)
                    leaves = f(
                        (jnp.asarray(np.frombuffer(bb, np.uint8)),),
                        s, dt_name)
                    jax.block_until_ready(leaves)
            else:
                arr = jnp.asarray(np.frombuffer(enc, np.uint8))

                def dev_decode(a=arr, s=specs, c=codec, f=fn):
                    leaves = f((a,), s, dt_name)
                    jax.block_until_ready(leaves)

            row["decode_device_gbps"] = rate(dev_decode, len(raw))
        out[codec] = row

    # Content-delta form (models/entropy.py): encode/decode rates over a
    # small-perturbation v2 of the same blob — the rollout-wave shape the
    # delta codec exists for.  ~1% of the bytes touched deterministically
    # (seeded), so the ratio row shows the regime where delta wins; a
    # high-churn v2 degrades toward 1.0x (docs/codec.md frames when delta
    # loses).  No device row: deltas reconstruct to RAW on the host
    # before ack — the device never sees the wire form.
    from . import entropy

    rng = np.random.default_rng(1)
    v2 = np.frombuffer(raw, np.uint8).copy()
    touched = rng.choice(len(v2), size=max(1, len(v2) // 100),
                         replace=False)
    v2[touched] ^= rng.integers(1, 256, size=len(touched)).astype(np.uint8)
    v2b = v2.tobytes()
    denc = entropy.delta_encode(v2b, raw)
    out["delta"] = {
        "encoded_bytes": len(denc),
        "ratio": round(len(raw) / len(denc), 3),
        "encode_gbps": rate(
            lambda: entropy.delta_encode(v2b, raw), len(raw)),
        "decode_host_gbps": rate(
            lambda: entropy.delta_decode(denc, raw), len(raw)),
        "decode_device_gbps": 0.0,
    }
    return out


def device_decode_jit(codec: str, donate: bool = False):
    """THE jitted device-decode program for ``codec``: callable as
    ``f(blobs_u8_tuple, specs_tuple, dtype_name)``.  One lookup shared by
    the boot (``runtime/boot.py``), the streaming stager
    (``runtime/stream_boot.py``) and the hint-time precompile — the three
    must agree on the exact callable (donated and plain variants are
    distinct executables) or a warmup warms the wrong program."""
    if codec == "raw":
        return serde._decode_blobs_donated if donate else serde._decode_blobs
    if codec in ENTROPY_CODECS:
        raise ValueError(
            f"codec {codec!r} has no device decode program — entropy "
            "forms unwrap on the host first (host_unwrap), then the "
            "base codec's jit applies")
    if codec == "int4":
        return _decode_q4blobs_donated if donate else _decode_q4blobs
    if codec != "int8":
        raise ValueError(f"unknown codec {codec!r}; known: {CODECS}")
    return _decode_qblobs_donated if donate else _decode_qblobs


def host_unwrap(codec: str, data) -> Tuple[str, Any]:
    """Peel an entropy wire form back to its quantized BASE on the host
    (the byte-domain coder has no device program).  Returns
    ``(base_codec, base_bytes)`` — identity for every other codec — so
    device-path callers can prestage once and keep their jit dispatch
    unchanged (runtime/boot.py, parallel/collectives.py)."""
    base = ENTROPY_CODECS.get(codec)
    if base is None:
        return codec, data
    from . import entropy

    return base, entropy.decode(data)


# -------------------------------------------------- codec-dispatch facade
#
# boot_from_layers talks to the codec layer through these four calls, so
# adding a codec touches this module only.


def stacked_from_blobs_host(
    cfg: ModelConfig, blobs: Dict[int, Any], layer_ids: Sequence[int],
    codec: str,
) -> Dict[str, np.ndarray]:
    """Host path: stacked layer params from wire blobs under ``codec``."""
    if codec == "raw":
        return serde.stacked_from_blobs(cfg, blobs, layer_ids)
    per_layer = [
        decode_blob_host(cfg, lid, blobs[lid], codec) for lid in layer_ids
    ]
    return {
        name: np.stack([lp[name] for lp in per_layer])
        for name, _ in layer_param_specs(cfg)
    }


def head_from_blob_host(cfg: ModelConfig, data, codec: str):
    """Host path: head leaves from the wire head blob under ``codec``."""
    return decode_blob_host(cfg, head_blob_id(cfg), data, codec)


def stacked_from_device(
    cfg: ModelConfig, blob_arrays: Sequence[Any], codec: str,
    donate: bool = False,
) -> Dict[str, Any]:
    """Device path: stacked layer params from HBM wire blobs.
    ``donate``: consume the wire blobs in place (the caller must drop its
    own references — they are deleted after this call)."""
    return device_decode_jit(codec, donate)(
        tuple(blob_arrays), tuple(layer_param_specs(cfg)),
        np.dtype(cfg.dtype).name,
    )


def head_from_device(cfg: ModelConfig, blob_u8, codec: str,
                     donate: bool = False) -> Dict[str, Any]:
    """Device path: head leaves from the HBM wire head blob."""
    decoded = device_decode_jit(codec, donate)(
        (blob_u8,), tuple(head_param_specs(cfg)),
        np.dtype(cfg.dtype).name,
    )
    return {name: arr[0] for name, arr in decoded.items()}
