"""Quantized transfer codecs: halve the bytes a layer costs on the wire.

Dissemination is bandwidth-bound — TTD is bytes over line rate
(SURVEY §6; the reference models it exactly that way in its flow solver,
``/root/reference/distributor/flow.go:221-270``).  A transfer codec
attacks the numerator: seeders encode each layer blob into a symmetric
per-row int8 form (scales + values, ~2x smaller than bf16), the wire and
every scheduler see only the smaller opaque blob, and the receiver
dequantizes AFTER the bytes land — on the accelerator, when the ``-hbm``
ingest staged them, so the host never touches decoded weights.  The
reference has no equivalent; it ships raw bytes only.

Format of an encoded blob (leaves in the same canonical order as
``serde``): per leaf, ``rows`` f32 scales followed by ``rows x cols``
int8 values, where a leaf of shape ``(..., cols)`` is flattened to
``(rows, cols)`` — per-output-row symmetric absmax scaling,
``x_hat = q * scale``, deterministic round-to-nearest (every seeder
fabricating the same seeded blob must agree byte-for-byte).

Decode paths mirror ``serde``'s two:
- host: numpy over the blob bytes;
- device: HBM-resident uint8 blobs are sliced, bitcast, and dequantized
  under one jit — XLA fuses the multiply into the bitcast reads, so the
  decode is one pass over HBM.

Codec choice is carried by the topology config (``ModelCodec``) next to
``Model``/``ModelSeed``: every node — seeder, scheduler, booting
receiver — derives identical blob sizes from (model, codec) alone.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import serde
from .llama import ModelConfig
from .serde import (
    Spec,
    blob_nbytes,
    head_blob_id,
    head_param_specs,
    layer_param_specs,
)

CODECS = ("raw", "int8")
_SCALE_DT = np.float32
_QMAX = 127.0


def _blob_specs(cfg: ModelConfig, blob_id: int) -> List[Spec]:
    return (head_param_specs(cfg) if blob_id == head_blob_id(cfg)
            else layer_param_specs(cfg))


def _rows_cols(shape: Tuple[int, ...]) -> Tuple[int, int]:
    if len(shape) == 1:
        return 1, shape[0]
    return int(np.prod(shape[:-1])), shape[-1]


def blob_nbytes_codec(cfg: ModelConfig, blob_id: int, codec: str) -> int:
    """Exact wire size of a blob under ``codec``."""
    if codec == "raw":
        return blob_nbytes(cfg, blob_id)
    if codec != "int8":
        raise ValueError(f"unknown codec {codec!r}; known: {CODECS}")
    total = 0
    for _, shape in _blob_specs(cfg, blob_id):
        rows, cols = _rows_cols(shape)
        total += rows * _SCALE_DT().itemsize + rows * cols
    return total


def encode_blob(cfg: ModelConfig, blob_id: int, raw: bytes, codec: str) -> bytes:
    """Encode a raw (cfg.dtype) blob into its wire form under ``codec``."""
    if codec == "raw":
        return raw
    if codec != "int8":
        raise ValueError(f"unknown codec {codec!r}; known: {CODECS}")
    dt = np.dtype(cfg.dtype)
    buf = np.frombuffer(memoryview(raw), dtype=np.uint8)
    parts: List[bytes] = []
    off = 0
    for _, shape in _blob_specs(cfg, blob_id):
        rows, cols = _rows_cols(shape)
        n = rows * cols * dt.itemsize
        x = buf[off : off + n].view(dt).reshape(rows, cols).astype(np.float32)
        off += n
        scale = np.abs(x).max(axis=1) / _QMAX
        scale = np.where(scale > 0, scale, 1.0).astype(_SCALE_DT)
        q = np.clip(np.rint(x / scale[:, None]), -_QMAX, _QMAX).astype(np.int8)
        parts.append(scale.tobytes())
        parts.append(q.tobytes())
    if off != len(buf):
        raise ValueError(f"raw blob size {len(buf)} != expected {off}")
    return b"".join(parts)


def decode_blob_host(
    cfg: ModelConfig, blob_id: int, data, codec: str
) -> Dict[str, np.ndarray]:
    """Host path: decode one wire blob into {name: cfg.dtype array}."""
    specs = _blob_specs(cfg, blob_id)
    if codec == "raw":
        return serde._split_blob(cfg, data, specs)
    if codec != "int8":
        raise ValueError(f"unknown codec {codec!r}; known: {CODECS}")
    dt = np.dtype(cfg.dtype)
    buf = np.frombuffer(memoryview(data), dtype=np.uint8)
    out: Dict[str, np.ndarray] = {}
    off = 0
    for name, shape in specs:
        rows, cols = _rows_cols(shape)
        sb = rows * _SCALE_DT().itemsize
        scale = buf[off : off + sb].view(_SCALE_DT).reshape(rows, 1)
        off += sb
        q = buf[off : off + rows * cols].view(np.int8).reshape(rows, cols)
        off += rows * cols
        out[name] = (q.astype(np.float32) * scale).astype(dt).reshape(shape)
    if off != len(buf):
        raise ValueError(f"wire blob size {len(buf)} != expected {off}")
    return out


# ------------------------------------------------------------- device path


@functools.partial(jax.jit, static_argnums=(1, 2))
def _decode_qblobs(blobs_u8, specs: Tuple[Spec, ...], dtype_name: str):
    """n separate 1-D uint8 qblobs → {name: (n, *shape) dtype} on device.

    Per-blob 1-D slices, leaf-shaped bitcasts, dequant multiply, then a
    per-leaf stack — same layout discipline as ``serde._decode_blobs``
    (a stacked (n, blob_len) intermediate provoked a dim0-minor tiled
    layout on TPU that padded n to the 128 tile: the physical-size boot
    OOM)."""
    dt = jnp.dtype(dtype_name)
    sdt = jnp.dtype(_SCALE_DT)
    out = {}
    off = 0
    for name, shape in specs:
        rows, cols = _rows_cols(shape)
        sb = rows * _SCALE_DT().itemsize  # one wire format: host's widths
        leaves = []
        for blob in blobs_u8:
            sraw = jax.lax.slice(blob, (off,), (off + sb,))
            scale = serde._bytes_to_wide(sraw, sdt)  # (rows,)
            qraw = jax.lax.slice(blob, (off + sb,),
                                 (off + sb + rows * cols,))
            q = serde._bytes_to_wide(qraw, jnp.int8).reshape(rows, cols)
            x = (q.astype(jnp.float32) * scale[:, None]).astype(dt)
            leaves.append(x.reshape(shape))
        out[name] = jnp.stack(leaves)
        off += sb + rows * cols
    return out


def stacked_from_device_qblobs(
    cfg: ModelConfig, blob_arrays: Sequence[Any]
) -> Dict[str, Any]:
    """Device path: stacked layer params from HBM-resident int8-codec
    blobs — slices, bitcasts and the dequant multiply fused in one jit;
    the disseminated bytes never leave the accelerator."""
    return _decode_qblobs(
        tuple(blob_arrays), tuple(layer_param_specs(cfg)),
        np.dtype(cfg.dtype).name,
    )


def head_from_device_qblob(cfg: ModelConfig, blob_u8) -> Dict[str, Any]:
    """Device path: embed/ln_f/lm_head from the HBM-resident head blob."""
    decoded = _decode_qblobs(
        (blob_u8,), tuple(head_param_specs(cfg)),
        np.dtype(cfg.dtype).name,
    )
    return {name: arr[0] for name, arr in decoded.items()}


# -------------------------------------------------- codec-dispatch facade
#
# boot_from_layers talks to the codec layer through these four calls, so
# adding a codec touches this module only.


def stacked_from_blobs_host(
    cfg: ModelConfig, blobs: Dict[int, Any], layer_ids: Sequence[int],
    codec: str,
) -> Dict[str, np.ndarray]:
    """Host path: stacked layer params from wire blobs under ``codec``."""
    if codec == "raw":
        return serde.stacked_from_blobs(cfg, blobs, layer_ids)
    per_layer = [
        decode_blob_host(cfg, lid, blobs[lid], codec) for lid in layer_ids
    ]
    return {
        name: np.stack([lp[name] for lp in per_layer])
        for name, _ in layer_param_specs(cfg)
    }


def head_from_blob_host(cfg: ModelConfig, data, codec: str):
    """Host path: head leaves from the wire head blob under ``codec``."""
    return decode_blob_host(cfg, head_blob_id(cfg), data, codec)


def stacked_from_device(
    cfg: ModelConfig, blob_arrays: Sequence[Any], codec: str
) -> Dict[str, Any]:
    """Device path: stacked layer params from HBM wire blobs."""
    if codec == "raw":
        return serde.stacked_from_device_blobs(cfg, blob_arrays)
    return stacked_from_device_qblobs(cfg, blob_arrays)


def head_from_device(cfg: ModelConfig, blob_u8, codec: str) -> Dict[str, Any]:
    """Device path: head leaves from the HBM wire head blob."""
    if codec == "raw":
        return serde.head_from_device_blob(cfg, blob_u8)
    return head_from_device_qblob(cfg, blob_u8)
