from .llama import (  # noqa: F401
    CONFIGS,
    ModelConfig,
    forward,
    forward_jit,
    init_params,
    loss_fn,
)
from .sharded import (  # noqa: F401
    AXES,
    build_train_step,
    example_batch,
    factor_mesh_axes,
    make_train_mesh,
    param_specs,
    shard_params,
)
