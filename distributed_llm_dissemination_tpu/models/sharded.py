"""Fully-sharded training step: dp / sp / pp / ep / tp on one mesh.

The five parallelism strategies, each implemented with explicit collectives
inside a single fully-manual ``jax.shard_map`` program:

- **dp** — batch dim sharded; gradients all-reduced (psum) over ``dp``.
- **sp** — sequence dim sharded; ring attention rotates K/V blocks around
  the ``sp`` axis (``parallel/ring_attention.py``).
- **pp** — the stacked layer axis sharded over ``pp``: each stage owns
  n_layers/pp layers (exactly the reference's Assignment as stage
  placement); activations hand off stage→stage by ``ppermute``, and the
  sequential fill means logits are valid on stage 0 after the wrap-around.
  AD masks the in-fill garbage paths to zero cotangents automatically.
- **ep** — MoE expert dim sharded over ``ep``; each device computes its
  local experts densely and contributions combine by psum over ``ep``.
- **tp** — Megatron-style: attention heads and FFN hidden dim sharded over
  ``tp``; the row-parallel matmuls (wo, w2) psum their partial sums.  The
  lm head is vocab-sharded, with the softmax cross-entropy computed via
  pmax/psum over ``tp`` so no device materializes the full vocab.

Mesh axes are factored from the device count in priority order
tp → pp → sp → ep → dp, so an 8-chip slice runs (tp2, pp2, sp2) and larger
pods enable ep and dp too.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.compat import axis_size, shard_map
from ..parallel.mesh import make_mesh
from ..parallel.ring_attention import ring_attention
from .llama import ModelConfig, rms_norm, rope, route_topk

AXES = ("dp", "sp", "pp", "ep", "tp")


def factor_mesh_axes(n_devices: int, cfg: ModelConfig) -> Dict[str, int]:
    """Split n_devices over (dp, sp, pp, ep, tp) round-robin in priority
    order tp → pp → sp → ep → dp, one prime factor per axis per round.

    tp must divide n_kv_heads, pp must divide n_layers, ep must divide
    n_experts (dense models keep ep=1); sp and dp are unconstrained."""
    sizes = {a: 1 for a in AXES}

    def accepts(axis: str, f: int) -> bool:
        if axis == "tp":
            return cfg.n_kv_heads % (sizes["tp"] * f) == 0
        if axis == "pp":
            return cfg.n_layers % (sizes["pp"] * f) == 0
        if axis == "ep":
            return cfg.n_experts > 0 and cfg.n_experts % (sizes["ep"] * f) == 0
        return True  # sp, dp unconstrained

    remaining = n_devices
    while remaining > 1:
        # dp accepts anything, so each pass always consumes a factor.
        for axis in ("tp", "pp", "sp", "ep", "dp"):
            if remaining == 1:
                break
            f = next(p for p in range(2, remaining + 1) if remaining % p == 0)
            if accepts(axis, f):
                sizes[axis] *= f
                remaining //= f
    return sizes


def make_train_mesh(n_devices: int, cfg: ModelConfig) -> Mesh:
    sizes = factor_mesh_axes(n_devices, cfg)
    return make_mesh([sizes[a] for a in AXES], AXES)


def param_specs(cfg: ModelConfig) -> Dict[str, Any]:
    """PartitionSpec per parameter leaf (layer leaves lead with the
    pp-sharded stacked-layer axis)."""
    layers = {
        "wq": P("pp", None, "tp"),
        "wk": P("pp", None, "tp"),
        "wv": P("pp", None, "tp"),
        "wo": P("pp", "tp", None),
        "ln1": P("pp", None),
        "ln2": P("pp", None),
    }
    if cfg.n_experts:
        layers.update(
            router=P("pp", None, None),
            w1=P("pp", "ep", None, "tp"),
            w3=P("pp", "ep", None, "tp"),
            w2=P("pp", "ep", "tp", None),
        )
    else:
        layers.update(
            w1=P("pp", None, "tp"),
            w3=P("pp", None, "tp"),
            w2=P("pp", "tp", None),
        )
    return {
        "embed": P(),
        "layers": layers,
        "ln_f": P(),
        "lm_head": P(None, "tp"),
    }


def shard_params(params, mesh: Mesh, cfg: ModelConfig):
    """device_put every leaf under its spec (leaf orders align: the spec
    tree mirrors the param tree's dict structure)."""
    specs = param_specs(cfg)
    flat_p, treedef = jax.tree.flatten(params)
    flat_s = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    placed = [
        jax.device_put(x, NamedSharding(mesh, s)) for x, s in zip(flat_p, flat_s)
    ]
    return jax.tree.unflatten(treedef, placed)


def _grad_reduce_axes(spec: P) -> Tuple[str, ...]:
    """Axes a parameter is replicated over — its gradient psum axes."""
    used = {a for part in spec if part for a in (part if isinstance(part, tuple) else (part,))}
    return tuple(a for a in AXES if a not in used)


# ---------------------------------------------------------------- per-device


def _local_layer(cfg: ModelConfig, p, x, q_pos):
    """One transformer layer on this device's shard (manual collectives)."""
    b, s_loc, d = x.shape
    hd = cfg.head_dim
    h_loc = p["wq"].shape[-1] // hd
    kv_loc = p["wk"].shape[-1] // hd

    xn = rms_norm(x, p["ln1"], cfg.norm_eps)
    q = jnp.einsum("bsd,dq->bsq", xn, p["wq"]).reshape(b, s_loc, h_loc, hd)
    k = jnp.einsum("bsd,dq->bsq", xn, p["wk"]).reshape(b, s_loc, kv_loc, hd)
    v = jnp.einsum("bsd,dq->bsq", xn, p["wv"]).reshape(b, s_loc, kv_loc, hd)
    q = rope(q, q_pos, cfg.rope_theta)
    k = rope(k, q_pos, cfg.rope_theta)
    attn = ring_attention(q, k, v, "sp", s_loc)  # sp collective inside
    o_part = jnp.einsum("bsq,qd->bsd", attn.reshape(b, s_loc, h_loc * hd), p["wo"])
    x = x + lax.psum(o_part, "tp")  # tp row-parallel reduce

    xn = rms_norm(x, p["ln2"], cfg.norm_eps)
    if cfg.n_experts:
        e_loc = p["w1"].shape[0]
        ep_idx = lax.axis_index("ep")
        logits = jnp.einsum("bsd,de->bse", xn, p["router"]).astype(jnp.float32)
        weights = route_topk(jax.nn.softmax(logits, axis=-1), cfg)
        w_loc = lax.dynamic_slice_in_dim(weights, ep_idx * e_loc, e_loc, axis=-1)
        gate = jax.nn.silu(jnp.einsum("bsd,edf->besf", xn, p["w1"]))
        up = jnp.einsum("bsd,edf->besf", xn, p["w3"])
        out_part = jnp.einsum("besf,efd->besd", gate * up, p["w2"])
        mixed = jnp.einsum("besd,bse->bsd", out_part, w_loc.astype(x.dtype))
        x = x + lax.psum(mixed, ("ep", "tp"))
    else:
        gate = jax.nn.silu(jnp.einsum("bsd,df->bsf", xn, p["w1"]))
        up = jnp.einsum("bsd,df->bsf", xn, p["w3"])
        down_part = jnp.einsum("bsf,fd->bsd", gate * up, p["w2"])
        x = x + lax.psum(down_part, "tp")
    return x


def _local_loss(cfg: ModelConfig, pp_size: int, params, inputs, targets,
                remat: bool = False):
    """Per-device loss: embedding → pipeline loop → vocab-sharded CE.
    ``inputs``/``targets`` arrive pre-shifted on host so sequence sharding
    over sp never straddles the shift boundary.  ``remat``: checkpoint
    each scanned layer so the backward recomputes its activations
    instead of keeping every layer's live (O(1) vs O(n_layers) layer
    activations; bit-identical results)."""
    b, s_loc = inputs.shape
    sp_idx = lax.axis_index("sp")
    q_pos = sp_idx * s_loc + jnp.arange(s_loc)

    x = params["embed"][inputs]

    layer_fn = functools.partial(_local_layer, cfg)
    if remat:
        layer_fn = jax.checkpoint(layer_fn)

    def run_stage(x):
        def body(h, layer_p):
            return layer_fn(layer_p, h, q_pos), None

        return lax.scan(body, x, params["layers"])[0]

    # Sequential pipeline fill: stage s applies its layers at hop s; after
    # pp hops the fully-processed activations have wrapped back to stage 0.
    fwd = [(i, (i + 1) % pp_size) for i in range(pp_size)]
    for _ in range(pp_size):
        x = run_stage(x)
        if pp_size > 1:
            x = lax.ppermute(x, "pp", fwd)

    xn = rms_norm(x, params["ln_f"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", xn, params["lm_head"],
                        preferred_element_type=jnp.float32)

    # Cross-entropy over the tp-sharded vocab: global logsumexp via
    # pmax+psum; the target logit is owned by exactly one tp member.
    v_loc = logits.shape[-1]
    tp_idx = lax.axis_index("tp")
    # Global max for stabilization only (gradient-neutral); pmax has no
    # diff rule, so gather the per-shard maxes instead.
    m_local = lax.stop_gradient(logits.max(axis=-1))
    m = lax.all_gather(m_local, "tp").max(axis=0)
    sumexp = lax.psum(jnp.exp(logits - m[..., None]).sum(axis=-1), "tp")
    lse = jnp.log(sumexp) + m
    tgt_local = targets - tp_idx * v_loc
    own = (tgt_local >= 0) & (tgt_local < v_loc)
    safe = jnp.clip(tgt_local, 0, v_loc - 1)
    picked = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    tgt_logit = lax.psum(jnp.where(own, picked, 0.0), "tp")
    nll = (lse - tgt_logit).mean()

    # Only stage 0 holds valid logits (wrap-around); other stages' paths
    # get zero cotangents through this mask.  The return value is this
    # device's SHARE of the global mean loss: the nll is computed
    # redundantly on every (tp, ep) member and split across (dp, sp) data
    # shards, so dividing by dp*sp*tp*ep makes the all-axis psum of shares
    # equal the global mean — and makes per-leaf gradient psums over each
    # leaf's replication group exact (validated against jax.grad of the
    # unsharded loss on 11 mesh shapes to ~1e-6).
    pp_idx = lax.axis_index("pp")
    denom = (
        axis_size("dp")
        * axis_size("sp")
        * axis_size("tp")
        * axis_size("ep")
    )
    return jnp.where(pp_idx == 0, nll, 0.0) / denom


def build_train_step(cfg: ModelConfig, mesh: Mesh, lr: float = 1e-3,
                     remat: bool = True):
    """jitted (params, tokens) -> (params, loss) over the 5-axis mesh.

    ``remat``: rematerialize each layer's activations in the backward
    pass (``jax.checkpoint`` on the scanned layer body) — the standard
    TPU memory/FLOPs trade: per-layer activations are not kept live
    across the whole backward, at the cost of one extra forward.
    Numerics are identical (tested)."""
    pp_size = mesh.shape["pp"]
    specs = param_specs(cfg)
    flat_specs = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    loss_fn = functools.partial(_local_loss, cfg, pp_size, remat=remat)

    def per_device(params, inputs, targets):
        loss_share, grads = jax.value_and_grad(loss_fn)(
            params, inputs, targets)
        loss = lax.psum(loss_share, AXES)  # shares sum to the global mean
        flat_grads, treedef = jax.tree.flatten(grads)
        flat_grads = [
            lax.psum(g, axes) if (axes := _grad_reduce_axes(s)) else g
            for g, s in zip(flat_grads, flat_specs)
        ]
        grads = jax.tree.unflatten(treedef, flat_grads)
        new_params = jax.tree.map(
            lambda p, g: (
                p.astype(jnp.float32) - lr * g.astype(jnp.float32)
            ).astype(p.dtype),
            params,
            grads,
        )
        return new_params, loss

    step = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(specs, P("dp", "sp"), P("dp", "sp")),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return jax.jit(step, donate_argnums=(0,))


def init_adamw_state(params):
    """AdamW moments, one (m, v) pair per leaf — f32 regardless of the
    param dtype (bf16 moments lose the small-update tail), sharded
    EXACTLY like their leaves (the state specs mirror param_specs)."""
    zeros = lambda p: jnp.zeros(p.shape, jnp.float32)  # noqa: E731
    return {
        "m": jax.tree.map(zeros, params),
        "v": jax.tree.map(zeros, params),
        "step": jnp.zeros((), jnp.int32),
    }


def adamw_state_specs(cfg: ModelConfig):
    """PartitionSpecs for ``init_adamw_state``'s tree: moments shard
    like params; the step counter is replicated."""
    specs = param_specs(cfg)
    return {"m": specs, "v": specs, "step": P()}


def build_adamw_train_step(cfg: ModelConfig, mesh: Mesh, lr: float = 1e-3,
                           betas=(0.9, 0.999), eps: float = 1e-8,
                           weight_decay: float = 0.01, remat: bool = True):
    """jitted (params, opt_state, inputs, targets) -> (params, opt_state,
    loss): AdamW with bias correction and decoupled weight decay, the
    moments sharded exactly like the params (each leaf's m/v live on the
    same devices as the leaf — no extra collectives beyond the gradient
    psums the SGD step already pays).  Params and state are donated."""
    pp_size = mesh.shape["pp"]
    specs = param_specs(cfg)
    state_specs = adamw_state_specs(cfg)
    flat_specs = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    b1, b2 = betas
    loss_fn = functools.partial(_local_loss, cfg, pp_size, remat=remat)

    def per_device(params, opt_state, inputs, targets):
        loss_share, grads = jax.value_and_grad(loss_fn)(
            params, inputs, targets)
        loss = lax.psum(loss_share, AXES)
        flat_grads, treedef = jax.tree.flatten(grads)
        flat_grads = [
            lax.psum(g, axes) if (axes := _grad_reduce_axes(s)) else g
            for g, s in zip(flat_grads, flat_specs)
        ]
        grads = jax.tree.unflatten(treedef, flat_grads)
        t = opt_state["step"] + 1
        c1 = 1.0 - b1 ** t.astype(jnp.float32)
        c2 = 1.0 - b2 ** t.astype(jnp.float32)

        def upd(p, g, m, v):
            g = g.astype(jnp.float32)
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * g * g
            step_dir = (m / c1) / (jnp.sqrt(v / c2) + eps)
            new_p = (p.astype(jnp.float32)
                     - lr * (step_dir + weight_decay * p.astype(jnp.float32))
                     ).astype(p.dtype)
            return new_p, m, v

        out = jax.tree.map(upd, params, grads,
                           opt_state["m"], opt_state["v"])
        # tree of (p, m, v) tuples -> three trees
        new_params = jax.tree.map(lambda o: o[0], out,
                                  is_leaf=lambda o: isinstance(o, tuple))
        new_m = jax.tree.map(lambda o: o[1], out,
                             is_leaf=lambda o: isinstance(o, tuple))
        new_v = jax.tree.map(lambda o: o[2], out,
                             is_leaf=lambda o: isinstance(o, tuple))
        return new_params, {"m": new_m, "v": new_v, "step": t}, loss

    step = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(specs, state_specs, P("dp", "sp"), P("dp", "sp")),
        out_specs=(specs, state_specs, P()),
        check_vma=False,
    )
    return jax.jit(step, donate_argnums=(0, 1))


def example_batch(cfg: ModelConfig, mesh: Mesh, batch: int = 0, seq: int = 0):
    """(inputs, targets) shaped to divide evenly over (dp, sp)."""
    dp, sp = mesh.shape["dp"], mesh.shape["sp"]
    batch = batch or 2 * dp
    seq = seq or 8 * sp
    assert batch % dp == 0 and seq % sp == 0
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab, size=(batch, seq + 1), dtype=np.int32)
    sharding = NamedSharding(mesh, P("dp", "sp"))
    inputs = jax.device_put(jnp.asarray(tokens[:, :-1]), sharding)
    targets = jax.device_put(jnp.asarray(tokens[:, 1:]), sharding)
    return inputs, targets


# ------------------------------------------------------------ pp inference


def build_pp_forward(cfg: ModelConfig, mesh: Mesh, pp_axis: str):
    """jitted (layers, counts, head, tokens) -> logits over a
    pipeline-sharded mesh: each stage holds its stacked slice resident
    (the Assignment's placement — what dissemination landed), head leaves
    are replicated, and activations hand off stage→stage by ``ppermute``
    exactly like the train step's pipeline fill.  Logits are valid on
    stage 0 after the wrap-around and broadcast by psum.

    UNEVEN contiguous partitions serve too: slices arrive PADDED to the
    deepest stage and ``counts`` [pp] (sharded along ``pp_axis``) gives
    each stage's real depth — the padded tail passes the hidden state
    through unchanged.

    Any extra mesh axes (e.g. tp) replicate the computation — this is the
    serving form of the staged placement, not the full 5-axis program."""
    from .llama import layer_apply

    pp = mesh.shape[pp_axis]
    fwd = [(i, (i + 1) % pp) for i in range(pp)]

    def per_device(layers_local, counts_local, head, tokens):
        count = counts_local[0]
        l_max = jax.tree.leaves(layers_local)[0].shape[0]
        positions = jnp.arange(tokens.shape[1])
        x = head["embed"][tokens]

        def body(h, scanned):
            layer_p, li = scanned
            h_new = layer_apply(layer_p, h, positions, cfg)
            return jnp.where(li < count, h_new, h), None

        for _ in range(pp):
            x = lax.scan(body, x, (layers_local, jnp.arange(l_max)))[0]
            if pp > 1:
                x = lax.ppermute(x, pp_axis, fwd)

        if pp > 1:
            # Broadcast the valid (stage-0) HIDDEN STATE, not the logits:
            # [b, s, d_model] over ICI instead of [b, s, vocab] — ~vocab/d
            # times less collective traffic for the same result.
            idx = lax.axis_index(pp_axis)
            x = lax.psum(jnp.where(idx == 0, x, 0.0), pp_axis)
        xn = rms_norm(x, head["ln_f"], cfg.norm_eps)
        return jnp.einsum(
            "bsd,dv->bsv", xn, head["lm_head"],
            preferred_element_type=jnp.float32,
        )

    f = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(pp_axis), P(pp_axis), P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(f)


def build_pp_decode(cfg: ModelConfig, mesh: Mesh, pp_axis: str,
                    max_new: int):
    """jitted (layers, counts, head, prompt) -> greedy token ids
    [b, max_new]: the KV-cached decode loop (``models/generate.py``) run
    as a lockstep pipeline collective over the staged placement — the
    multi-controller serving analogue of the reference's startup
    inference hook (message.go:216-241).

    Mechanics: in pipeline-rotation round r only stage r's application
    is REAL (the rotated copies other stages chew are in-fill garbage,
    same as ``build_pp_forward``), so each stage masks its per-layer KV
    cache writes to ``(round == my_stage) & (layer < count)`` — the
    cache stays exact while every process executes the identical
    program.  The final hidden state wraps to stage 0, is psum-broadcast
    as [b, d_model], and argmax picks the next token identically on
    every device, so the replicated decode loop can never diverge.
    Uneven padded slices work exactly as in ``build_pp_forward``."""
    from .generate import _layer_with_cache

    pp = mesh.shape[pp_axis]
    fwd = [(i, (i + 1) % pp) for i in range(pp)]

    def per_device(layers_local, counts_local, head, prompt):
        count = counts_local[0]
        idx = lax.axis_index(pp_axis)
        b, p = prompt.shape
        l_max = jax.tree.leaves(layers_local)[0].shape[0]
        max_len = p + max_new
        kc = jnp.zeros((l_max, b, max_len, cfg.n_kv_heads, cfg.head_dim),
                       cfg.dtype)
        vc = jnp.zeros_like(kc)

        def pipeline(x, positions, kc, vc):
            """One full pipelined pass; returns (last-pos logits, caches)."""
            for r in range(pp):
                real = idx == r

                def body(h, scanned):
                    layer_p, k_l, v_l, li = scanned
                    h_new, k_new, v_new = _layer_with_cache(
                        layer_p, h, positions, k_l, v_l, cfg)
                    valid = real & (li < count)
                    return (
                        jnp.where(valid, h_new, h),
                        (jnp.where(valid, k_new, k_l),
                         jnp.where(valid, v_new, v_l)),
                    )

                x, (kc, vc) = lax.scan(
                    body, x, (layers_local, kc, vc, jnp.arange(l_max)))
                if pp > 1:
                    x = lax.ppermute(x, pp_axis, fwd)
            if pp > 1:
                x = lax.psum(jnp.where(idx == 0, x, 0.0), pp_axis)
            xn = rms_norm(x[:, -1, :], head["ln_f"], cfg.norm_eps)
            logits = jnp.einsum("bd,dv->bv", xn, head["lm_head"],
                                preferred_element_type=jnp.float32)
            return logits, kc, vc

        logits, kc, vc = pipeline(
            head["embed"][prompt], jnp.arange(p), kc, vc)
        first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if max_new == 1:
            return first[:, None]

        def step(carry, _):
            kc, vc, token, pos = carry
            logits, kc, vc = pipeline(
                head["embed"][token[:, None]], pos[None], kc, vc)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (kc, vc, nxt, pos + 1), token

        (_, _, last, _), toks = lax.scan(
            step, (kc, vc, first, jnp.asarray(p, jnp.int32)),
            None, length=max_new - 1,
        )
        return jnp.concatenate([toks.T, last[:, None]], axis=1)

    f = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(pp_axis), P(pp_axis), P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(f)
