"""Cheap deterministic byte-domain entropy coder — the shared engine of
the ``int8e``/``int4e`` wire forms and the content-delta codec
(docs/codec.md).

Design point (EQuARX, arXiv:2506.17615): block-scaled quantization
leaves the value bytes with LOW per-byte entropy — int8 rows cluster
near zero after absmax scaling, and a content delta (v2 XOR v1) of a
lightly-perturbed checkpoint is MOSTLY zeros.  A heavyweight
context-model coder would eat the byte win in CPU time, so this module
codes fixed 64 KiB blocks under four trivial modes and picks, per
block, whichever is smallest:

- mode 0 — **literal**: the block verbatim (the incompressible floor;
  an encoded stream is never more than ~1 byte/block larger than raw).
- mode 1 — **sparse**: ``uint32 n`` + ``n`` uint16 positions + ``n``
  values.  Wins when well under 1/3 of the bytes are nonzero (cold
  deltas).
- mode 2 — **zigzag bitpack**: one bitwidth byte ``b`` then
  ``ceil(len*b/8)`` packed bytes of zigzagged int8 values (``b = 0``
  encodes an all-zero block in 2 bytes).  Wins on quantized value
  planes whose magnitudes fit ``b < 8`` bits.
- mode 3 — **bitmap**: ``ceil(len/8)`` presence bitmap + the nonzero
  bytes.  Wins between sparse and literal (~1/3..7/8 nonzero density).

Every mode is numpy-vectorized both ways; there is no entropy-coded
state across blocks, so ranges of the ENCODED stream shard/salvage
exactly like any other wire blob (the flow plane's byte-identity
invariant).  Encoding is a pure function of the input bytes — ties
break to the lowest mode id — so independent seeders produce
byte-identical streams (multi-sender ranges, NACK salvage, and
codec-qualified digests all depend on this).

Stream layout: ``b"DLE1"`` magic, uint64-le raw length, then blocks in
order.  The coder is model-agnostic: it sees bytes, not leaves, which
is what lets the delta form ride arbitrary (even non-model) layer
buffers.
"""

from __future__ import annotations

from typing import List

import numpy as np

MAGIC = b"DLE1"
BLOCK = 64 * 1024
_HEADER = len(MAGIC) + 8

MODE_LITERAL = 0
MODE_SPARSE = 1
MODE_BITPACK = 2
MODE_BITMAP = 3


def _zigzag(block: np.ndarray) -> np.ndarray:
    """int8-domain zigzag: 0,-1,1,-2,... -> 0,1,2,3,... (uint8)."""
    v = block.view(np.int8).astype(np.int16)
    return (((v << 1) ^ (v >> 8)) & 0xFF).astype(np.uint8)


def _unzigzag(z: np.ndarray) -> np.ndarray:
    zz = z.astype(np.int16)
    return (((zz >> 1) ^ -(zz & 1)) & 0xFF).astype(np.uint8)


def _bitwidth(maxval: int) -> int:
    return int(maxval).bit_length()


def _pack_bits(z: np.ndarray, b: int) -> bytes:
    """Pack each uint8 of ``z`` into ``b`` bits (big-endian within the
    value, values in order)."""
    bits = np.unpackbits(z[:, None], axis=1)[:, 8 - b:]
    return np.packbits(bits.reshape(-1)).tobytes()


def _unpack_bits(data: np.ndarray, n: int, b: int) -> np.ndarray:
    bits = np.unpackbits(data)[: n * b].reshape(n, b)
    full = np.zeros((n, 8), dtype=np.uint8)
    full[:, 8 - b:] = bits
    return np.packbits(full, axis=1).reshape(-1)


def encode(raw) -> bytes:
    """Encode ``raw`` bytes into one deterministic DLE1 stream."""
    buf = np.frombuffer(memoryview(raw), dtype=np.uint8)
    out: List[bytes] = [MAGIC, np.uint64(len(buf)).tobytes()]
    for off in range(0, len(buf), BLOCK):
        block = buf[off : off + BLOCK]
        L = len(block)
        nz = np.flatnonzero(block)
        n = len(nz)
        z = _zigzag(block)
        b = _bitwidth(int(z.max())) if L else 0
        # Candidate payload sizes (excluding the mode byte), computed
        # without materializing any payload; ties -> lowest mode id.
        sizes = (
            L,                                   # 0: literal
            4 + 3 * n,                           # 1: sparse
            1 + (L * b + 7) // 8,                # 2: zigzag bitpack
            (L + 7) // 8 + n,                    # 3: bitmap
        )
        mode = int(np.argmin(sizes))
        out.append(bytes([mode]))
        if mode == MODE_LITERAL:
            out.append(block.tobytes())
        elif mode == MODE_SPARSE:
            out.append(np.uint32(n).tobytes())
            out.append(nz.astype(np.uint16).tobytes())
            out.append(block[nz].tobytes())
        elif mode == MODE_BITPACK:
            out.append(bytes([b]))
            if b:
                out.append(_pack_bits(z, b))
        else:
            bitmap = np.zeros(L, dtype=np.uint8)
            bitmap[nz] = 1
            out.append(np.packbits(bitmap).tobytes())
            out.append(block[nz].tobytes())
    return b"".join(out)


def decode(data) -> bytes:
    """Decode one DLE1 stream back to the exact raw bytes."""
    buf = np.frombuffer(memoryview(data), dtype=np.uint8)
    if len(buf) < _HEADER or buf[:4].tobytes() != MAGIC:
        raise ValueError("not a DLE1 entropy stream (bad magic)")
    raw_len = int(buf[4:_HEADER].view(np.uint64)[0])
    out = np.empty(raw_len, dtype=np.uint8)
    off, pos = _HEADER, 0
    while pos < raw_len:
        L = min(BLOCK, raw_len - pos)
        mode = int(buf[off])
        off += 1
        if mode == MODE_LITERAL:
            out[pos : pos + L] = buf[off : off + L]
            off += L
        elif mode == MODE_SPARSE:
            n = int(buf[off : off + 4].view(np.uint32)[0])
            off += 4
            idx = buf[off : off + 2 * n].view(np.uint16)
            off += 2 * n
            block = np.zeros(L, dtype=np.uint8)
            block[idx.astype(np.int64)] = buf[off : off + n]
            off += n
            out[pos : pos + L] = block
        elif mode == MODE_BITPACK:
            b = int(buf[off])
            off += 1
            if b == 0:
                out[pos : pos + L] = 0
            else:
                nb = (L * b + 7) // 8
                out[pos : pos + L] = _unzigzag(
                    _unpack_bits(buf[off : off + nb], L, b))
                off += nb
        elif mode == MODE_BITMAP:
            mb = (L + 7) // 8
            bitmap = np.unpackbits(buf[off : off + mb])[:L]
            off += mb
            idx = np.flatnonzero(bitmap)
            block = np.zeros(L, dtype=np.uint8)
            block[idx] = buf[off : off + len(idx)]
            off += len(idx)
            out[pos : pos + L] = block
        else:
            raise ValueError(f"corrupt DLE1 stream: unknown block mode "
                             f"{mode} at offset {off - 1}")
        pos += L
    if off != len(buf):
        raise ValueError(
            f"corrupt DLE1 stream: {len(buf) - off} trailing bytes")
    return out.tobytes()


def xor_bytes(a, b) -> bytes:
    """Byte-wise XOR of two equal-length buffers (the delta residual)."""
    va = np.frombuffer(memoryview(a), dtype=np.uint8)
    vb = np.frombuffer(memoryview(b), dtype=np.uint8)
    if len(va) != len(vb):
        raise ValueError(
            f"xor_bytes: length mismatch {len(va)} != {len(vb)}")
    return np.bitwise_xor(va, vb).tobytes()


def delta_encode(new, base) -> bytes:
    """The content-delta wire form: DLE1-coded (new XOR base).  Requires
    same-length buffers — a base of another size can't be a delta base
    (the leader's base selection enforces this upstream)."""
    return encode(xor_bytes(new, base))


def delta_decode(data, base) -> bytes:
    """Reconstruct the full new bytes from a delta stream + the base."""
    return xor_bytes(decode(data), base)
