"""Core identifier and layer-store types.

TPU-native re-design of the reference's core types
(``/root/reference/distributor/node.go:128-211``): a *layer* is an opaque
byte blob that may live in host RAM, on disk, at an external client process,
or — new in this framework — in TPU HBM as a ``jax.Array`` sharded over a
``jax.sharding.Mesh``. The *Assignment* (node → layers it must end up
holding, ``distributor/node.go:174``) doubles as the pipeline-parallel stage
placement for the model that boots after dissemination.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
from typing import Dict, List, Optional, Set, Tuple

# Reference: distributor/node.go:128-129 — uint identifiers.
NodeID = int
LayerID = int

# ---------------------------------------------------------------------------
# Shard specs (docs/sharding.md)
#
# A delivery target is (layer, shard spec): the spec names a DETERMINISTIC
# byte-range slice of the layer, so every plane — planner, wire, digest
# stamp, ack — can derive the same [offset, offset+size) from the spec and
# the layer's total size alone.  Grammar: ``"1/N@K"`` = slice K (0-based)
# of the layer split into N floor-bounded equal ranges (boundary i sits at
# ``i * total // N`` — the same split rule as the transport's stripe
# offsets, so shard edges are stable under any total).  ``""`` = the whole
# layer (the pre-sharding vocabulary; every legacy peer speaks it).
# ---------------------------------------------------------------------------

ShardSpec = str  # "" (full layer) or "1/N@K"


def parse_shard_spec(spec: ShardSpec) -> Optional[Tuple[int, int]]:
    """``"1/N@K"`` → ``(N, K)``; ``""`` → None (full layer).  Raises
    ``ValueError`` on malformed or out-of-range specs — a typo'd spec
    must fail at the plane that first reads it, not deliver the wrong
    byte range."""
    if not spec:
        return None
    try:
        frac, idx = spec.split("@", 1)
        num, den = frac.split("/", 1)
        n, k, one = int(den), int(idx), int(num)
    except (ValueError, AttributeError):
        raise ValueError(f"malformed shard spec {spec!r} (want '1/N@K')")
    if one != 1 or n < 1 or not 0 <= k < n:
        raise ValueError(f"shard spec {spec!r} out of range (want 1/N@K "
                         f"with 0 <= K < N)")
    return n, k


def shard_range(spec: ShardSpec, total: int) -> Tuple[int, int]:
    """The spec's byte range ``(offset, size)`` of a ``total``-byte
    layer.  Floor-bounded equal split: slice K covers
    ``[K*total//N, (K+1)*total//N)``."""
    parsed = parse_shard_spec(spec)
    if parsed is None:
        return 0, total
    n, k = parsed
    start = k * total // n
    end = (k + 1) * total // n
    return start, end - start


def shard_fraction(spec: ShardSpec) -> float:
    """The spec's share of the layer (1.0 = full)."""
    parsed = parse_shard_spec(spec)
    return 1.0 if parsed is None else 1.0 / parsed[0]


def shard_covers(held: ShardSpec, want: ShardSpec) -> bool:
    """Whether a holder of shard ``held`` provably holds every byte of
    shard ``want``, for ANY layer total.  ``""`` (full layer) covers
    everything.  Cross-multiplied rational bounds: range(N, K) =
    [K*T/N, (K+1)*T/N), and floor() preserves the ordering of the
    rational endpoints, so K1/N1 <= K2/N2 and (K1+1)/N1 >= (K2+1)/N2
    imply byte-range containment at every T."""
    h = parse_shard_spec(held)
    if h is None:
        return True
    w = parse_shard_spec(want)
    if w is None:
        return False  # a shard never covers the full layer
    n1, k1 = h
    n2, k2 = w
    return k1 * n2 <= k2 * n1 and (k1 + 1) * n2 >= (k2 + 1) * n1


def shard_specs_for(n: int) -> List[ShardSpec]:
    """The N specs of an N-way split — what a planner targeting a dest
    mesh of N shards (one per PartitionSpec slot along the sharded axis)
    hands out, one per participant."""
    if n <= 1:
        return [""] if n == 1 else []
    return [f"1/{n}@{k}" for k in range(n)]


# ---------------------------------------------------------------------------
# Wire codecs (docs/codec.md)
#
# A transfer may ship a layer in a quantized wire form (``models/quant.py``:
# int8 ~0.50x, int4 ~0.27x of the canonical bytes).  The codec is an
# IDENTITY property of the bytes, not a transport detail: a holding tagged
# ``codec="int8"`` holds the int8-encoded form — a different byte string
# with a different digest — and can only ever satisfy (or re-seed) a target
# planned at that same codec.  ``""`` = the canonical (raw) form.
# ---------------------------------------------------------------------------

WireCodec = str  # "" (canonical bytes) or "int8" / "int4"


def codec_accepts(held: WireCodec, want: WireCodec) -> bool:
    """Whether a holding in wire-codec form ``held`` satisfies a target
    planned at codec ``want``.  Canonical bytes (``""``) satisfy every
    target — raw is the lossless superset any codec can be derived from
    — while a quantized holding satisfies ONLY a target planned at
    exactly that codec: int8 bytes can never complete a raw (or int4)
    demand, which is the "a quantized copy cannot ack as a raw one"
    invariant (docs/codec.md)."""
    return not held or held == want


def codec_capability(codec: WireCodec) -> WireCodec:
    """The CAPABILITY a codec string demands of an encoder.  Most codec
    ids are their own capability; parameterized forms carry their
    parameter after a colon — ``"delta:<base_digest_hex>"`` needs a
    sender with the generic ``"delta"`` capability (announced in
    ``AnnounceMsg.Codecs``) — so every "can this node encode it?" check
    compares the prefix, never the full string (docs/codec.md)."""
    return codec.split(":", 1)[0] if codec else codec


def delta_base_digest(codec: WireCodec) -> str:
    """The base digest a ``"delta:<hex>"`` codec string names, or ``""``
    for every non-delta codec.  The base rides INSIDE the codec string —
    one vocabulary through stamps, caches, sizes, and NACK coordinates —
    so there is no separate base field to skew against the choice."""
    if codec.startswith("delta:"):
        return codec.split(":", 1)[1]
    return ""

# Reference: distributor/node.go:132 — a set of node IDs.
NodeIDs = Set[NodeID]

# Reference: distributor/client.go:10 — clients use the max uint as their ID.
# Python ints are unbounded; pick the Go MaxUint64 for wire compatibility.
CLIENT_ID: NodeID = (1 << 64) - 1


class LayerLocation(enum.IntEnum):
    """Where a layer currently lives (distributor/node.go:182-189).

    ``HBM`` is new: the layer has been materialized as a device array on the
    TPU — the terminal state for this framework's data plane, whereas the
    reference's terminal state is host RAM (``InmemLayer``).
    """

    INMEM = 0
    DISK = 1
    CLIENT = 2
    HBM = 3


class SourceType(enum.IntEnum):
    """Class of a layer's origin, keying per-source rate limits
    (distributor/node.go:192-198)."""

    CLIENT = 0
    DISK = 1
    MEM = 2


@dataclasses.dataclass
class LayerMeta:
    """Per-layer metadata (distributor/node.go:134-138).

    ``data_size`` is an extension over the reference: announce messages
    carry each layer's size so a mode-3 leader can schedule layers it does
    not itself hold (the reference's announce drops sizes, so its flow
    solver zero-sizes peer-only layers).

    ``shard`` (docs/sharding.md): the shard spec this entry refers to.
    In an *assignment*, the target — the dest must end up holding that
    byte range; in a *status/announce* row, the holding — the node holds
    ONLY that range (``data_size`` stays the FULL layer size; the spec
    qualifies which bytes of it are real).  ``""`` = the whole layer.
    Omitted-at-default on the wire (legacy peers never see the key).

    ``version`` (docs/swap.md): the model-rollout version this entry
    belongs to.  In an *assignment*, the target version — only a
    holding tagged with the SAME version satisfies it (a stale
    unversioned copy of a reused layer id can never complete a v2
    rollout pair); in a *status/announce* row, the version the holder
    verified the bytes under.  ``""`` = the pre-swap vocabulary (every
    legacy peer); omitted-at-default on the wire.

    ``codec`` (docs/codec.md): the wire-codec form of the bytes.  In an
    *assignment*, the codec the leader CHOSE for this transfer (the
    dest will receive — and is satisfied by — the encoded form); in a
    *status/announce* row, the form the holder actually holds
    (``data_size`` is then the ENCODED byte count — the bytes that
    exist and can be range-served).  ``""`` = canonical bytes (every
    pre-codec peer); omitted-at-default on the wire."""

    location: LayerLocation = LayerLocation.INMEM
    limit_rate: int = 0  # bytes/sec; 0 = unlimited
    source_type: SourceType = SourceType.MEM
    data_size: int = 0  # bytes; 0 = unknown
    shard: ShardSpec = ""  # "" = full layer
    version: str = ""  # "" = unversioned (pre-swap)
    codec: WireCodec = ""  # "" = canonical bytes (pre-codec)

    def to_json(self) -> dict:
        out = {
            "Location": int(self.location),
            "LimitRate": self.limit_rate,
            "SourceType": int(self.source_type),
            "DataSize": self.data_size,
        }
        if self.shard:
            out["Shard"] = str(self.shard)
        if self.version:
            out["Version"] = str(self.version)
        if self.codec:
            out["Codec"] = str(self.codec)
        return out

    @classmethod
    def from_json(cls, d: dict) -> "LayerMeta":
        return cls(
            location=LayerLocation(d.get("Location", 0)),
            limit_rate=int(d.get("LimitRate", 0)),
            source_type=SourceType(d.get("SourceType", 0)),
            data_size=int(d.get("DataSize", 0)),
            shard=str(d.get("Shard", "")),
            version=str(d.get("Version", "")),
            codec=str(d.get("Codec", "")),
        )


# Reference: distributor/node.go:141 — map[LayerID]LayerMeta, a set with
# metadata.  JSON keys are strings, mirroring Go's map encoding.
LayerIDs = Dict[LayerID, LayerMeta]


def layer_ids_to_json(layers: LayerIDs) -> dict:
    return {str(lid): meta.to_json() for lid, meta in layers.items()}


def layer_ids_from_json(d: dict) -> LayerIDs:
    return {int(lid): LayerMeta.from_json(meta) for lid, meta in d.items()}


@dataclasses.dataclass
class LayerSrc:
    """A layer's storage record (distributor/node.go:200-211).

    Exactly one of ``inmem_data`` / ``fp`` / client-location describes where
    the bytes are; ``device_array`` is the TPU-native extension — once a
    layer has been staged into HBM it is a jax.Array and ``meta.location``
    is ``LayerLocation.HBM``.
    """

    inmem_data: Optional[bytearray] = None
    fp: str = ""  # file path when on disk
    data_size: int = 0
    offset: int = 0
    meta: LayerMeta = dataclasses.field(default_factory=LayerMeta)
    # TPU-native: the layer materialized on device (jax.Array), if staged.
    device_array: object = None
    # Guards the one-time device→host materialization of ensure_host_bytes.
    _host_lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False
    )
    # Set by the fabric upload cache when a whole-layer device_put failed
    # for this record — later plans then stick to range uploads instead of
    # re-reading a multi-GiB layer just to fail the same allocation again.
    upload_failed: bool = dataclasses.field(
        default=False, repr=False, compare=False
    )
    # Zero-copy receive: the transport landed this fragment's bytes
    # DIRECTLY in the destination's reassembly buffer (TcpTransport
    # ``layer_sink``).  ``inmem_data`` is then None and this carries the
    # already-held coverage claim token the fragment handler must commit
    # — the bytes were never materialized anywhere else.
    placed_token: object = dataclasses.field(
        default=None, repr=False, compare=False
    )

    def _host_resident(self) -> bool:
        """Host bytes available?  True for INMEM, and for HBM-staged layers
        whose host buffer was retained (staging keeps ``inmem_data``, so an
        HBM layer can still be *served* to peers over the host transport)."""
        return (
            self.meta.location in (LayerLocation.INMEM, LayerLocation.HBM)
            and self.inmem_data is not None
        )

    def read_bytes(self) -> bytes:
        """This record's own bytes (a received fragment's buffer, or a full
        in-RAM layer).  For slicing a *source* store by offset/data_size use
        ``read_range`` — the two differ only for INMEM records, where this
        returns the whole buffer."""
        if self._host_resident():
            return bytes(self.inmem_data)
        return self.read_range()

    def read_range(self) -> bytes:
        """The byte range ``[offset, offset+data_size)`` of this source
        store — what a transport actually puts on the wire.  ``offset``
        indexes into the full layer (RAM buffer or file)."""
        return self.read_span(0, self.data_size)

    def read_span(self, off: int, size: int) -> bytes:
        """The byte range ``[offset+off, offset+off+size)`` of this
        source store — the one place that knows every backing kind's
        range semantics (RAM slice, file seek+read, HBM fetch).  Only the
        requested span touches host RAM for disk-backed stores; HBM-only
        stores materialize once via ``ensure_host_bytes``."""
        base = self.offset + off
        if self._host_resident():
            return bytes(memoryview(self.inmem_data)[base : base + size])
        if self.meta.location == LayerLocation.DISK and self.fp:
            with open(self.fp, "rb") as f:
                f.seek(base)
                return f.read(size)
        if self.ensure_host_bytes():
            return bytes(memoryview(self.inmem_data)[base : base + size])
        raise ValueError(
            f"layer has no host-readable bytes (location={self.meta.location!r})"
        )

    def ensure_host_bytes(self) -> bool:
        """Materialize a host copy of an HBM-only layer (e.g. delivered
        over the pod fabric, where no host copy ever existed) from its
        device array — one device→host fetch, cached in ``inmem_data`` so
        re-serving the layer to peers or assembling it at boot doesn't
        re-fetch.  Returns whether host bytes are now available.  The
        fetch is once-guarded: concurrent callers (e.g. two flow jobs for
        the same layer on the handler pool) must not each pull a
        multi-GiB transfer and spike host RAM."""
        if self.inmem_data is not None:
            return True
        if self.device_array is None:
            return False
        with self._host_lock:
            if self.inmem_data is None:
                import jax
                import numpy as np

                self.inmem_data = bytearray(
                    np.asarray(jax.device_get(self.device_array)).tobytes()
                )
        return True


# Reference: distributor/node.go:166 — node's layer store.
LayersSrc = Dict[LayerID, LayerSrc]

# Reference: distributor/node.go:174-176 — the goal state (node → layers it
# must hold) and the leader's live view of who holds what.
Assignment = Dict[NodeID, LayerIDs]
Status = Dict[NodeID, LayerIDs]


def assignment_to_json(a: Assignment) -> dict:
    return {str(nid): layer_ids_to_json(layers) for nid, layers in a.items()}


def assignment_from_json(d: dict) -> Assignment:
    return {int(nid): layer_ids_from_json(layers) for nid, layers in d.items()}


@dataclasses.dataclass
class RoutingInfo:
    """Next-hop entry (distributor/node.go:168-171)."""

    next_hop: NodeID
    remaining_hops: int = 1


def delivered(meta: LayerMeta) -> bool:
    """Whether a layer counts as delivered for assignment satisfaction.

    The reference requires ``InmemLayer`` (distributor/node.go:435-446);
    the TPU build additionally accepts HBM, which is strictly "more
    delivered" — the bytes are already on the accelerator.

    NOTE: location only.  A sharded target's satisfaction additionally
    requires the held shard to COVER the assigned one — use
    :func:`satisfies` wherever an assignment meta is being checked
    against a status meta.
    """
    return meta.location in (LayerLocation.INMEM, LayerLocation.HBM)


def satisfies(held: Optional[LayerMeta], want: LayerMeta) -> bool:
    """Whether a status entry ``held`` satisfies the assignment target
    ``want``: delivered-grade location AND the held shard covers the
    wanted one (a shard-holder never satisfies a full-layer target;
    docs/sharding.md) AND the version matches (docs/swap.md).

    Version semantics mirror shard coverage: a VERSIONED target is met
    only by a holding verified under exactly that version, while an
    UNVERSIONED target ("" — every pre-swap job) accepts any verified
    holding of the id, versioned or not — a later push/repair job over
    already-swapped layer ids must not wedge on the tag (the digest
    plane, not the tag, governs content).

    Codec semantics (docs/codec.md) are STRICT the other way: the
    target's codec is the leader's chosen wire form for the pair, and a
    quantized holding satisfies only that exact codec (canonical bytes
    satisfy everything) — int8 bytes must never complete a raw demand."""
    return (held is not None and delivered(held)
            and shard_covers(held.shard, want.shard)
            and (not want.version or held.version == want.version)
            and codec_accepts(held.codec, want.codec))
