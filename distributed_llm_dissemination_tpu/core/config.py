"""JSON topology config, schema-compatible with the reference.

Mirrors ``/root/reference/cmd/config.go:14-45``: one JSON file holds the
node list (addr, leader bit, NIC bandwidth, per-source rate limits, initial
layer placement with per-layer sizes), external clients, the target
``Assignment``, and a default ``LayerSize``.  TPU extension: an optional
``Mesh`` section describing the device mesh the Assignment maps onto
(axis names/sizes, which axis is the pipeline axis) so dissemination can
land layers directly in HBM with pipeline-stage placement.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional

from .types import (
    Assignment,
    LayerID,
    LayerMeta,
    LayerLocation,
    LayerSrc,
    LayersSrc,
    NodeID,
    SourceType,
    assignment_from_json,
)


def _jget(d: dict, key: str, default=None):
    """Go-style JSON field lookup: exact key first, then case-insensitive
    (encoding/json unmarshal semantics — the reference's own config.json
    uses ``Id`` against a struct field ``ID``)."""
    if key in d:
        return d[key]
    lk = key.lower()
    for k, v in d.items():
        if k.lower() == lk:
            return v
    return default


@dataclasses.dataclass
class MeshConf:
    """TPU extension: device-mesh description for the HBM data plane."""

    axis_names: List[str] = dataclasses.field(default_factory=lambda: ["nodes"])
    axis_sizes: List[int] = dataclasses.field(default_factory=lambda: [1])
    pipeline_axis: str = "nodes"
    # Pod fabric: all nodes are stages of ONE device mesh, and scheduled
    # layer transfers move as device traffic (ICI) instead of TCP streams
    # (parallel/fabric.py); TCP carries only the control plane.  Run with
    # cli.podrun (single controller addresses the whole mesh).
    fabric: bool = False
    # Per-stage ICI ingress/egress capacity, bytes/s.  When set on a
    # fabric config, the mode-3 flow solver plans against it instead of
    # the nodes' NIC NetworkBW — the plan governs the device plane, where
    # the NIC is not in the path (SURVEY §7: "rate limiting on ICI").
    # Per-source LimitRates still cap seeders (host→HBM or disk reads
    # remain the source-side bottleneck).  0 = plan with NetworkBW.
    ici_bw: int = 0
    # Multi-slice pods: node id -> slice index ("Slices": {"0": 0, ...}).
    # Nodes on the same slice exchange bytes over ICI; nodes on different
    # slices share the DCN.  The mode-3 solver then adds one DcnBW-capped
    # edge per ordered slice pair (sched/flow.PodTopology) — the reference
    # models only flat per-node NICs (flow.go:221-270).  Empty = one slice.
    slices: Dict[int, int] = dataclasses.field(default_factory=dict)
    dcn_bw: int = 0  # bytes/s per ordered slice pair; 0 = no DCN modeling
    # Per-slice torus interior (SURVEY §7 hard part): each slice's
    # members (sorted by id, row-major) sit on a torus of this shape,
    # and every directed torus link carries IciLinkBW bytes/s.  The
    # mode-3 solver then budgets each intra-slice transfer's
    # dimension-ordered route per LINK — multi-sender plans spread
    # across links, not just nodes.  Empty shape / 0 = unmodeled.
    slice_shape: List[int] = dataclasses.field(default_factory=list)
    ici_link_bw: int = 0

    @classmethod
    def from_json(cls, d: dict) -> "MeshConf":
        return cls(
            axis_names=list(_jget(d, "AxisNames", ["nodes"])),
            axis_sizes=[int(s) for s in _jget(d, "AxisSizes", [1])],
            pipeline_axis=_jget(d, "PipelineAxis", "nodes"),
            fabric=bool(_jget(d, "Fabric", False)),
            ici_bw=int(_jget(d, "IciBW", 0)),
            slices={int(k): int(v)
                    for k, v in (_jget(d, "Slices", {}) or {}).items()},
            dcn_bw=int(_jget(d, "DcnBW", 0)),
            slice_shape=[int(s) for s in _jget(d, "SliceShape", []) or []],
            ici_link_bw=int(_jget(d, "IciLinkBW", 0)),
        )

    def topology(self):
        """The solver-facing ``PodTopology`` (None when nothing beyond
        flat per-node rates is modeled: no DCN pairs AND no torus)."""
        if not self.slices:
            return None
        torus = bool(self.slice_shape) and self.ici_link_bw > 0
        if self.dcn_bw <= 0 and not torus:
            return None
        from ..sched.flow import PodTopology

        return PodTopology.make(self.slices, self.dcn_bw,
                                slice_shape=self.slice_shape,
                                ici_link_bw=self.ici_link_bw)


@dataclasses.dataclass
class DistributedConf:
    """TPU extension: multi-host mesh formation (parallel/multihost.py).

    Present (even empty ``{}``) = every node-process joins one pod-wide
    JAX runtime via ``jax.distributed.initialize`` before any device use;
    absent = single-host, no initialization.  ``coordinator`` defaults to
    the leader node's host on JAX's default port; ``cpu_collectives``
    ("gloo") enables cross-process collectives on CPU backends (the
    2-process smoke deployment) and is ignored on TPU."""

    coordinator: str = ""
    cpu_collectives: str = ""

    @classmethod
    def from_json(cls, d: dict) -> "DistributedConf":
        return cls(
            coordinator=_jget(d, "Coordinator", "") or "",
            cpu_collectives=_jget(d, "CpuCollectives", "") or "",
        )


@dataclasses.dataclass
class NodeConf:
    """Per-node config (cmd/config.go:21-28)."""

    id: NodeID
    addr: str
    network_bw: int = 0  # NIC bandwidth, bytes/sec
    is_leader: bool = False
    # SourceType -> rate limit (bytes/sec)  (cmd/config.go:26)
    sources: Dict[SourceType, int] = dataclasses.field(default_factory=dict)
    # SourceType -> {LayerID -> layer size}  (cmd/config.go:30-36)
    initial_layers: Dict[SourceType, Dict[LayerID, int]] = dataclasses.field(
        default_factory=dict
    )

    @classmethod
    def from_json(cls, d: dict) -> "NodeConf":
        sources = {
            SourceType(int(k)): int(v)
            for k, v in (_jget(d, "Sources") or {}).items()
        }
        initial: Dict[SourceType, Dict[LayerID, int]] = {}
        for st, by_layer in (_jget(d, "InitialLayers") or {}).items():
            initial[SourceType(int(st))] = {
                int(lid): int(_jget(lc or {}, "LayerSize", 0))
                for lid, lc in by_layer.items()
            }
        return cls(
            id=int(_jget(d, "ID", 0) or 0),
            addr=_jget(d, "Addr", ""),
            network_bw=int(_jget(d, "NetworkBW", 0)),
            is_leader=bool(_jget(d, "IsLeader", False)),
            sources=sources,
            initial_layers=initial,
        )


@dataclasses.dataclass
class ClientConf:
    """External weight-source config (cmd/config.go:41-45).

    ``layers_rate_limit`` maps LayerID -> bytes/sec serving rate (the JSON
    key is ``Layers``, as in the reference).
    """

    id: NodeID
    addr: str
    layers_rate_limit: Dict[LayerID, int] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_json(cls, d: dict) -> "ClientConf":
        return cls(
            id=int(_jget(d, "ID", 0) or 0),
            addr=_jget(d, "Addr", ""),
            layers_rate_limit={
                int(k): int(v) for k, v in (_jget(d, "Layers") or {}).items()
            },
        )


@dataclasses.dataclass
class Config:
    """Top-level config (cmd/config.go:14-19) + TPU mesh extension."""

    nodes: List[NodeConf] = dataclasses.field(default_factory=list)
    clients: List[ClientConf] = dataclasses.field(default_factory=list)
    assignment: Assignment = dataclasses.field(default_factory=dict)
    layer_size: int = 0
    mesh: Optional[MeshConf] = None
    distributed: Optional[DistributedConf] = None
    # TPU extension: when set (a models.llama.CONFIGS name), seeders
    # fabricate REAL model weight blobs (deterministic from ModelSeed)
    # instead of dummy zero bytes, so the disseminated layers can boot an
    # inference engine after delivery (-boot).
    model: str = ""
    model_seed: int = 0
    # Transfer codec for the fabricated blobs ("raw" | "int8" | "int4"):
    # int8 halves the bytes every schedule ships, int4 quarters them
    # (models/quant.py); receivers dequantize after landing, on-device
    # when ingest staged to HBM.
    model_codec: str = "raw"
    # NEGOTIATED per-transfer wire codec (docs/codec.md): when set, the
    # leader may ship individual (dest, layer) transfers in this
    # quantized form over SLOW links (bottleneck rate below
    # DLD_CODEC_MIN_RATE) while fast links keep shipping canonical
    # bytes — the flow solver sizes each pair by its encoded bytes, so
    # a quantized copy's effective link capacity is
    # bandwidth x (raw/encoded).  Requires ModelCodec == "raw" (the
    # canonical form must be the raw dtype blob; double quantization is
    # refused at parse time) and a Model (codec sizes derive from it).
    wire_codec: str = "raw"
    # Control-plane HA (docs/failover.md): ordered leader-succession
    # list.  Non-empty arms state replication + lease fencing — the
    # leader streams control deltas to these nodes and beacons its
    # lease; on leader death the lowest-ranked live standby takes over
    # at a bumped epoch.  Standby ids must name receiver seats.
    standbys: List[NodeID] = dataclasses.field(default_factory=list)
    # Hierarchical control (docs/hierarchy.md), mode 3 only: either an
    # auto-partition request ``{"Size": K}`` (0 = ~sqrt(N) groups) over
    # every non-root seat, or an explicit list of ``{"Leader": id,
    # "Members": [...]}`` declarations.  Grouped members point their
    # control plane at their sub-leader; the root plans over group
    # ingress nodes.  None = flat control (the legacy plane).
    groups: Optional[object] = None
    # Fabric-assisted pod delivery (docs/fabric.md), mode 3 only: a
    # list of member-id lists — each inner list one POD of dests
    # sharing an ICI domain.  A layer every member of a pod wants ships
    # as ONE 1/R shard per host over the NIC (possibly quantized under
    # WireCodec) and the full tree materializes over the on-mesh
    # gather, so pod NIC ingress is O(model_bytes), not
    # O(model_bytes x replicas).  None = no pod delivery.
    pods: Optional[List[List[NodeID]]] = None
    # Closed-loop autonomy (docs/autonomy.md): declarative policy rules
    # the leader-side engine evaluates against the folded cluster
    # signals every metrics interval — ``[{"Rule": <kind>, ...params},
    # ...]``, validated LOUDLY at parse time (runtime/policy.py owns
    # the grammar).  None/[] = manual fleet (no engine armed).  The
    # ``DLD_POLICY`` env kill-switch drops an armed fleet back to
    # manual without a config change.
    policies: Optional[List[dict]] = None

    @classmethod
    def from_json(cls, d: dict) -> "Config":
        conf = cls(
            nodes=[NodeConf.from_json(n) for n in _jget(d, "Nodes") or []],
            clients=[ClientConf.from_json(c) for c in _jget(d, "Clients") or []],
            assignment=assignment_from_json(_jget(d, "Assignment") or {}),
            layer_size=int(_jget(d, "LayerSize", 0)),
            mesh=MeshConf.from_json(_jget(d, "Mesh")) if _jget(d, "Mesh") else None,
            distributed=(DistributedConf.from_json(_jget(d, "Distributed"))
                         if _jget(d, "Distributed") is not None else None),
            model=_jget(d, "Model", "") or "",
            model_seed=int(_jget(d, "ModelSeed", 0)),
            model_codec=_validated_codec(_jget(d, "ModelCodec", "raw") or "raw"),
            wire_codec=_validated_codec(_jget(d, "WireCodec", "raw") or "raw"),
            standbys=[int(s) for s in _jget(d, "Standbys") or []],
            groups=_jget(d, "Groups"),
            pods=([[int(m) for m in pod] for pod in _jget(d, "Pods")]
                  if _jget(d, "Pods") is not None else None),
            policies=(list(_jget(d, "Policies"))
                      if _jget(d, "Policies") is not None else None),
        )
        if conf.policies is not None:
            # A bad rule must be refused at ADMISSION (config parse),
            # never at fire time — the engine owns the grammar
            # (lazy import: policy pulls runtime modules pure-config
            # users never need).
            from ..runtime.policy import validate_policies

            conf.policies = validate_policies(conf.policies)
        if conf.groups is not None and not isinstance(conf.groups,
                                                      (dict, list)):
            raise ValueError(
                "Groups must be {'Size': K} or a list of "
                "{'Leader': id, 'Members': [...]} declarations")
        if conf.pods is not None:
            known = {nc.id for nc in conf.nodes}
            seen: set = set()
            for pod in conf.pods:
                if len(pod) < 2:
                    raise ValueError("each Pods entry needs >= 2 members")
                for m in pod:
                    if m not in known:
                        raise ValueError(f"Pods names unknown node {m}")
                    if m in seen:
                        raise ValueError(
                            f"node {m} appears in more than one pod")
                    seen.add(m)
        if conf.model_codec != "raw":
            # Entropy forms are WIRE-only: the canonical held form must
            # boot through the codec jits, and the byte-domain DLE1
            # coder has no device program (models/entropy.py) — refuse
            # at parse time, not mid-boot.
            from ..models.quant import ENTROPY_CODECS

            if conf.model_codec in ENTROPY_CODECS:
                raise ValueError(
                    f"ModelCodec {conf.model_codec!r} is a wire-only "
                    "entropy form; use it as WireCodec over raw "
                    "canonicals instead")
        if conf.wire_codec != "raw":
            # Fail at PARSE time like an unknown codec: a wire codec
            # re-encodes the CANONICAL blob, so the canonical form must
            # be the raw dtype (double quantization silently degrades
            # weights twice) and a model must name the blob layouts.
            if conf.model_codec != "raw":
                raise ValueError(
                    f"WireCodec {conf.wire_codec!r} requires ModelCodec "
                    f"'raw' (got {conf.model_codec!r}): wire codecs "
                    "re-encode the canonical raw blobs per transfer")
            if not conf.model:
                raise ValueError(
                    f"WireCodec {conf.wire_codec!r} requires a Model "
                    "(encoded sizes derive from the blob layouts)")
        return conf


def _validated_codec(codec: str) -> str:
    """Reject unknown codecs AT PARSE TIME: a destination node holds no
    layers, so a typo'd codec would otherwise only surface after
    dissemination as a swallowed boot failure — a distributed hang on the
    leader's boot wait instead of an immediate config error."""
    if codec == "raw":  # default: don't pull jax into pure-TCP nodes
        return codec
    from ..models.quant import CODECS  # lazy for the same reason

    if codec not in CODECS:
        raise ValueError(f"unknown ModelCodec {codec!r}; known: {CODECS}")
    return codec


def read_json(path: str) -> Config:
    """Load a topology config file (cmd/config.go:48-62)."""
    with open(path, "r") as f:
        return Config.from_json(json.load(f))


def get_leader_conf(conf: Config) -> NodeConf:
    """First node with IsLeader set (cmd/config.go:64-72)."""
    for nc in conf.nodes:
        if nc.is_leader:
            return nc
    raise ValueError("no leader found")


def get_node_conf(conf: Config, node: NodeID) -> NodeConf:
    for nc in conf.nodes:
        if nc.id == node:
            return nc
    raise ValueError(f"no node found: {node}")


def get_client_conf(conf: Config, node: NodeID) -> ClientConf:
    for cc in conf.clients:
        if cc.id == node:
            return cc
    raise ValueError(f"no client found: {node}")


# ---------------------------------------------------------------------------
# Dummy-layer fabrication (cmd/config.go:94-198)
# ---------------------------------------------------------------------------


def create_layers(
    my_conf: NodeConf,
    save_disk: bool,
    storage_path: str = ".",
    model: str = "",
    model_seed: int = 0,
    model_codec: str = "raw",
) -> LayersSrc:
    """Fabricate this node's initial layers (cmd/config.go:94-117).

    ``SourceType`` is a *rate class* keying the per-source limit, not a
    storage location: layers are fabricated in RAM unless ``save_disk``
    (the reference's ``-s`` flag) forces disk-backed files.

    ``model``: a ``models.llama.CONFIGS`` name — layers are then REAL
    weight blobs (``serde.seeded_blob``, deterministic from ``model_seed``)
    the delivered model boots from, instead of the reference's dummy zero
    bytes; the blob's true size overrides the configured LayerSize."""
    blob_fn = None
    if model:
        from ..models import hf
        from ..models.quant import encode_blob

        if hf.is_hf(model):
            # Real weights: blobs come from the Hugging Face checkpoint
            # the config names (models/hf.py), not a seeded init.
            mcfg = hf.config_from_name(model)
            raw_fn = lambda lid: hf.blob_from_name(model, lid)  # noqa: E731
        else:
            from ..models.llama import CONFIGS
            from ..models.serde import seeded_blob

            mcfg = CONFIGS[model]
            raw_fn = lambda lid: seeded_blob(mcfg, lid, model_seed)  # noqa: E731

        def blob_fn(lid):
            return encode_blob(mcfg, lid, raw_fn(lid), model_codec)
    layers: LayersSrc = {}
    for source_type, by_layer in my_conf.initial_layers.items():
        for layer_id, size in by_layer.items():
            size = max(0, size)
            blob = blob_fn(layer_id) if blob_fn is not None else None
            if blob is not None:
                size = len(blob)
            if save_disk:
                src = create_disk_layer(my_conf.id, layer_id, size,
                                        storage_path, content=blob)
            else:
                src = create_inmem_layer(layer_id, size, content=blob)
            src.data_size = size
            src.meta.limit_rate = my_conf.sources.get(source_type, 0)
            src.meta.source_type = source_type
            layers[layer_id] = src
    return layers


def add_client_layers(
    client_conf: ClientConf, layer_size: int, layers: LayersSrc
) -> LayersSrc:
    """Record layers served by this node's external client
    (cmd/config.go:119-131); layers already in RAM/disk win."""
    for layer_id, limit_rate in client_conf.layers_rate_limit.items():
        if layer_id in layers:
            continue
        layers[layer_id] = create_client_layer_info(layer_id, layer_size, limit_rate)
    return layers


def create_disk_layer(
    my_id: NodeID, layer_id: LayerID, layer_size: int, storage_path: str,
    content: Optional[bytes] = None,
) -> LayerSrc:
    """Write a layer file ``layers/<nodeID>/<layerID>.layer``
    (cmd/config.go:133-157); dummy zeros unless real ``content`` given."""
    d = os.path.join(storage_path, "layers", str(my_id))
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{layer_id}.layer")
    if not os.path.exists(path) or os.path.getsize(path) != layer_size:
        # A size mismatch is ALWAYS refabricated, dummy bytes included: a
        # stale file from an earlier topology under the same storage path
        # would otherwise be served as this layer — the sender then
        # streams fewer bytes than it announced and the dest waits
        # forever on coverage that can't complete.
        with open(path, "wb") as f:
            f.write(content if content is not None else b"\x00" * layer_size)
    return LayerSrc(
        inmem_data=None,
        fp=path,
        data_size=layer_size,
        offset=0,
        meta=LayerMeta(location=LayerLocation.DISK, source_type=SourceType.DISK),
    )


def create_inmem_layer(
    layer_id: LayerID, layer_size: int, content: Optional[bytes] = None
) -> LayerSrc:
    """In-RAM layer (cmd/config.go:159-171): dummy zeros, or real bytes."""
    return LayerSrc(
        inmem_data=bytearray(content) if content is not None
        else bytearray(layer_size),
        fp="",
        data_size=layer_size,
        offset=0,
        meta=LayerMeta(location=LayerLocation.INMEM, source_type=SourceType.MEM),
    )


def create_client_layer(layer_id: LayerID, layer_size: int, limit_rate: int) -> LayerSrc:
    """A layer held *at the client process itself* (cmd/config.go:174-184)."""
    src = create_inmem_layer(layer_id, layer_size)
    src.meta = LayerMeta(
        location=LayerLocation.INMEM,
        limit_rate=limit_rate,
        source_type=SourceType.CLIENT,
    )
    return src


def create_client_layer_info(
    layer_id: LayerID, layer_size: int, limit_rate: int
) -> LayerSrc:
    """The *node's record* of a layer that lives at its external client
    (cmd/config.go:187-198)."""
    return LayerSrc(
        inmem_data=None,
        fp="",
        data_size=layer_size,
        offset=0,
        meta=LayerMeta(
            location=LayerLocation.CLIENT,
            limit_rate=limit_rate,
            source_type=SourceType.CLIENT,
        ),
    )
