from .flow import FlowGraph, FlowJob, FlowJobsMap  # noqa: F401
