from .flow import FlowGraph, FlowJob, FlowJobsMap  # noqa: F401
from .native import NativeFlowGraph, make_flow_graph  # noqa: F401
