from .flow import FlowGraph, FlowJob, FlowJobsMap, solve_joint  # noqa: F401
from .jobs import Job, JobManager, merge_assignments  # noqa: F401
from .native import NativeFlowGraph, make_flow_graph  # noqa: F401
