"""Native-accelerated mode-3 scheduler: same flow model, C++ core.

Builds the identical six-level graph as :class:`~..sched.flow.FlowGraph`
(source → sender → source-class → layer → receiver → sink, reference
flow.go:55-144) but expresses it as an edge list whose capacities are
affine in the candidate completion time ``t`` and hands the whole
exponential+binary time search to the Dinic solver in
``native/flow_solver.cc``.  One C call replaces ~2·log2(t) Python
Edmonds–Karp runs — the leader-side scheduling hot path at pod scale.

``make_flow_graph`` picks the native path when the library is available
and falls back to the pure-Python :class:`FlowGraph` otherwise; both
return the same minimum time and a valid byte-range decomposition (the
exact per-sender split may differ — any max flow is an optimal plan).
"""

from __future__ import annotations

import ctypes
import time
from typing import Dict, List, Tuple

from ..core.types import Assignment, LayerID, NodeID, Status
from ..native import load_flow_solver
from ..utils.logging import log
from .flow import TIME_SCALE, FlowGraph, FlowJob, FlowJobsMap, _INF, _V


class NativeFlowGraph(FlowGraph):
    """FlowGraph whose search + max-flow run in the native library.

    Vertex indexing is inherited (deterministic, sorted); only the solver
    differs.  Falls back to the parent's pure-Python path if the library
    can't be loaded at call time.
    """

    def _edge_list(self) -> Tuple[List[int], List[int], List[int], List[int],
                                  Dict[Tuple[NodeID, LayerID, NodeID], int]]:
        """Edges as (u, v, cap_const, cap_per_t) arrays, plus the map from
        (sender, layer, dest) to its class→layer edge index — the edges
        whose flow is read back as that sender's byte contribution toward
        that (layer, dest) pair (flow.go:193-211)."""
        eu: List[int] = []
        ev: List[int] = []
        const: List[int] = []
        per_t: List[int] = []
        contrib: Dict[Tuple[NodeID, LayerID, NodeID], int] = {}
        class_edge: Dict[Tuple[int, int], int] = {}
        seen: set = set()  # dedup for the topology's shared INF edges

        src = self.idx[_V("source")]
        sink = self.idx[_V("sink")]

        for node_id in sorted(self.status):
            sender = self.idx[_V("sender", node_id=node_id)]
            eu.append(src)
            ev.append(sender)
            const.append(0)
            per_t.append(self.node_network_bw.get(node_id, 0))
            for layer_id in sorted(self.status[node_id]):
                dests = self.dests_of.get(layer_id, ())
                if not dests:
                    continue
                meta = self.status[node_id][layer_id]
                cls = self.idx[
                    _V("class", node_id=node_id, source_type=int(meta.source_type))
                ]
                # Class-edge rate: max across the class's layers, matching
                # FlowGraph._build (rates belong to the source class).
                # _class_capacity at t=TIME_SCALE (one full second of ms)
                # is exactly the per-second rate.
                rate = self._class_capacity(node_id, meta.limit_rate, TIME_SCALE)
                if (sender, cls) not in class_edge:
                    class_edge[(sender, cls)] = len(eu)
                    eu.append(sender)
                    ev.append(cls)
                    const.append(0)
                    per_t.append(rate)
                else:
                    i = class_edge[(sender, cls)]
                    per_t[i] = max(per_t[i], rate)
                for dest in dests:
                    if not self._arc_ok(node_id, meta, layer_id, dest):
                        continue  # codec-inadmissible sender (docs/codec.md)
                    layer = self.idx[
                        _V("layer", layer_id=layer_id, node_id=dest)
                    ]
                    if self._cross(node_id, dest):
                        # Topology: cross-slice arcs route through the
                        # pair's shared xin→xout DCN edge, mirroring
                        # FlowGraph._build — the relaxation (labels
                        # dropped at the pair vertex) is identical, so
                        # the native min time IS the Python bound.  No
                        # contrib entry: cross flow is attributed by the
                        # caller (LP or transportation re-split), never
                        # read off these edges.
                        a = self._slice[node_id]
                        b = self._slice[dest]
                        xin = self.idx[_V("xin", node_id=a, layer_id=b)]
                        xout = self.idx[_V("xout", node_id=a, layer_id=b)]
                        for u, v in ((cls, xin), (xout, layer)):
                            if (u, v) not in seen:
                                seen.add((u, v))
                                eu.append(u)
                                ev.append(v)
                                const.append(_INF)
                                per_t.append(0)
                    else:
                        contrib[(node_id, layer_id, dest)] = len(eu)
                        eu.append(cls)
                        ev.append(layer)
                        # A health-demoted straggler link is priced at
                        # its measured rate instead of _INF, mirroring
                        # FlowGraph._build (docs/autonomy.md).
                        demoted = self.link_demotions.get(
                            (node_id, dest))
                        if demoted:
                            const.append(0)
                            per_t.append(demoted)
                        else:
                            const.append(_INF)
                            per_t.append(0)
        for a, b in self.x_pairs:
            eu.append(self.idx[_V("xin", node_id=a, layer_id=b)])
            ev.append(self.idx[_V("xout", node_id=a, layer_id=b)])
            const.append(0)
            per_t.append(self.topology.dcn_bw)

        for node_id in sorted(self.assignment):
            receiver = self.idx[_V("receiver", node_id=node_id)]
            for layer_id in sorted(self.assignment[node_id]):
                layer = self.idx[_V("layer", layer_id=layer_id, node_id=node_id)]
                eu.append(layer)
                ev.append(receiver)
                const.append(self._pair_size(layer_id, node_id))
                per_t.append(0)
            eu.append(receiver)
            ev.append(sink)
            const.append(0)
            per_t.append(self.node_network_bw.get(node_id, 0))

        return eu, ev, const, per_t, contrib

    def _relaxed_bound(self, required: int) -> Tuple[int, bool]:
        """The C++ Dinic search over the (topology-aware) relaxed graph:
        one C call instead of ~2·log2(t) Python Edmonds–Karp runs.  Does
        NOT populate ``self.cap`` residuals — callers that decompose
        flows re-run ``max_flow`` at the returned t (one Python solve at
        a known time, not a search)."""
        lib = load_flow_solver()
        if lib is None:
            return super()._relaxed_bound(required)
        eu, ev, const, per_t, _ = self._edge_list()
        m = len(eu)
        achieved = ctypes.c_int64(0)
        t = lib.flow_min_time_schedule(
            self.n, m, (ctypes.c_int32 * m)(*eu), (ctypes.c_int32 * m)(*ev),
            (ctypes.c_int64 * m)(*const), (ctypes.c_int64 * m)(*per_t),
            self.idx[_V("source")], self.idx[_V("sink")],
            required, TIME_SCALE, (ctypes.c_int64 * m)(),
            ctypes.byref(achieved),
        )
        return t, achieved.value >= required

    def get_job_assignment(self) -> Tuple[int, FlowJobsMap]:
        lib = load_flow_solver()
        if lib is None or self.topology is not None:
            # Topology planning stays in the parent (LP for exactness,
            # transportation re-attribution otherwise) — but its relaxed
            # time searches ride the native solver via _relaxed_bound.
            return super().get_job_assignment()

        required = sum(self._pair_size(lid, dest) for lid, dest in self.pairs)
        eu, ev, const, per_t, contrib = self._edge_list()
        m = len(eu)
        a_eu = (ctypes.c_int32 * m)(*eu)
        a_ev = (ctypes.c_int32 * m)(*ev)
        a_const = (ctypes.c_int64 * m)(*const)
        a_per_t = (ctypes.c_int64 * m)(*per_t)
        flows = (ctypes.c_int64 * m)()
        achieved = ctypes.c_int64(0)

        t0 = time.monotonic()
        t = lib.flow_min_time_schedule(
            self.n, m, a_eu, a_ev, a_const, a_per_t,
            self.idx[_V("source")], self.idx[_V("sink")],
            required, TIME_SCALE, flows, ctypes.byref(achieved),
        )
        if achieved.value < required:
            log.error("flow schedule infeasible",
                      required=required, achieved=achieved.value)

        jobs: FlowJobsMap = {}
        # Sharded targets decompose from their shard's base offset
        # (resume-override pairs stay in remaining-space; the leader
        # remaps those) — same seeding as the Python decompositions.
        pair_offset: Dict[Tuple[LayerID, NodeID], int] = (
            self.seed_pair_offsets())
        for sender_id in sorted(self.status):
            for layer_id in sorted(self.status[sender_id]):
                for dest in self.dests_of.get(layer_id, ()):
                    edge = contrib.get((sender_id, layer_id, dest))
                    if edge is None:
                        continue
                    flow = flows[edge]
                    if flow > 0:
                        offset = pair_offset.get((layer_id, dest), 0)
                        jobs.setdefault(sender_id, []).append(
                            FlowJob(sender_id, layer_id, flow, offset, dest)
                        )
                        pair_offset[(layer_id, dest)] = offset + flow

        log.info(
            "job assignment calculated (native)",
            min_time_ms=t,
            solver_ms=round((time.monotonic() - t0) * 1000, 3),
        )
        return t, jobs


def make_flow_graph(
    assignment: Assignment,
    status: Status,
    layer_sizes: Dict[LayerID, int],
    node_network_bw: Dict[NodeID, int],
    remaining=None,
    topology=None,
    codec_sizes=None,
    node_codecs=None,
    base_holders=None,
    link_demotions=None,
) -> FlowGraph:
    """The fastest available mode-3 scheduler for this environment.

    With a ``PodTopology``, planning itself stays in the Python solver
    (the LP carries the holdings structure the relaxed graph drops) but
    every relaxed time search — the LP's seed bound and the no-scipy
    fallback's search — runs in the C++ Dinic, which now carries the
    per-pair DCN ``xin``/``xout`` edges.  Wire-codec pairs
    (``codec_sizes``/``node_codecs``, docs/codec.md) size and
    arc-filter identically on both paths — the predicates live on the
    shared base class."""
    cls = FlowGraph if load_flow_solver() is None else NativeFlowGraph
    return cls(assignment, status, layer_sizes, node_network_bw,
               remaining=remaining, topology=topology,
               codec_sizes=codec_sizes, node_codecs=node_codecs,
               base_holders=base_holders, link_demotions=link_demotions)
